"""Continuous-batching engine: one jit-compiled steady-state decode step
over a fixed-capacity SLOT batch, with host-side join/evict.

Design (the fungible-row-slot property models/decode.paged_kv_geometry
was built for):

- The device state is ONE paged KV pool per layer plus a [slots]-shaped
  decode batch: [slots, max_blocks] block tables, per-slot positions, an
  active mask, per-slot PRNG key chains, per-slot row indices, and the
  last logits. Every shape is static, so joining or evicting a request
  only rewrites HOST tables — the step executable never recompiles.
- Inactive slots ride through the step as dead weight: the paged
  attention op steers their KV writes to the pool's reserved scratch
  page (ops/decode_attention active mask) so a freed slot can never
  corrupt pages the allocator has already handed to a new request, and
  their sampled tokens/logits are garbage the host ignores.
- Joins prefill through SEPARATE per-shape-bucket programs
  (models/decode.slot_prefill): the join batch runs the ragged paged
  prefill against a throwaway local geometry, and the resulting page
  arrays scatter into the long-lived pool at allocator-assigned ids.
  Bucketed (join_width, prompt_len, page_count) shapes bound the number
  of compiles.
- Streams are BIT-IDENTICAL to the row-keyed oracle
  (generate_kv_batched(..., row_keyed=True, page_block=...)) no matter
  when a request joins: each slot carries its own PRNG key chain, reset
  to the engine's base key at join, advanced by one split per decode
  step — after j emitted tokens the slot's sub-key equals the oracle's
  step-j sub-key — and sampling folds in the request's GLOBAL row index
  (models/decode._sample vector row_key_offset). Numerics are row-local
  (a row's logits depend only on its own tokens — the ragged/paged
  equivalence tests pin this), so neither the join batch's composition
  nor the physical page ids perturb a stream.
- dp/tp meshes (parallel/serve.engine_specs): slots shard over dp with
  SHARD-LOCAL pools and shard-local PagePool allocators (page ids in the
  tables are shard-local; no page crosses the mesh); tp shards heads.
  The decode-only collective contract is serve.lint_contract(...,
  decode_only=True): dp = 0 psums, tp = 2L.
- PREFIX CACHE (ISSUE 9, default on): each dp shard holds a
  serving/prefix_cache.PrefixCache over its PagePool. Admission looks up
  the longest cached page-aligned prefix, ACQUIRES those immutable pages
  (refcount bump — N tables, one physical page), allocates private pages
  only for the divergent tail, and prefills ONLY the uncached suffix
  (models/decode.prefill_suffix — bit-equal to the full prefill, see its
  docstring); completed prefills PUBLISH their full prompt blocks back.
  Copy-on-write is enforced every dispatch: validate_block_tables checks
  no active row's write block is a shared page (the write block is
  always >= plen // block, i.e. private by construction). All of this is
  host-side allocator work — the step program and its collective
  contract are byte-identical with the cache on or off.

- FLIGHT RECORDER (ISSUE 12, default on): every request lifecycle
  transition (submit/shed/admit/running/first-token/finish/cancel/
  poison) and every dispatched step's six host-phase spans
  (schedule_admit, prefix_lookup, prefill_dispatch, table_rewrite,
  step_dispatch, readback_sample — consecutive ``_t(now)`` reads tile
  the step wall exactly) append to ``self.flight``
  (serving/flight.py); analysis/servetrace.py folds the log into the
  canonical servetrace/v1 artifact (latency decomposition,
  engine-steps/s, counter windows). Pure host-side appends on the
  existing clock abstraction — zero device dispatches, the jit step
  program is byte-identical recorder on or off, and the engine makes
  the SAME clock reads either way so stateful test clocks tick
  identically.

- CHUNKED PREFILL (ISSUE 15, opt-in via ``prefill_chunk``): a joining
  request's prompt no longer runs as one monolithic prefill that stalls
  every active decode slot for the full prompt. ``_admit`` allocates the
  request's pages up front (identical feasibility/blocking behavior)
  but enqueues a ``_PrefillState`` cursor instead of dispatching;
  each ``step()`` then drains at most ``prefill_budget`` tokens of
  page-aligned chunk work (models/decode.prefill_chunk — a chunk IS a
  suffix prefill whose prefix is everything already landed, so chunk
  dispatches share ``_prefill_suffix_fn``'s compiled shape buckets:
  zero extra steady-state compiles) before the decode dispatch. The
  request becomes ``running`` only when its final chunk's boundary
  logits land — exactly the state ``slot_prefill`` would have produced,
  so streams stay BIT-IDENTICAL to the unchunked engine and the
  row-keyed oracle (chunking changes WHEN prefill compute runs, never
  its result). With the prefix cache on, only uncached-suffix tokens
  are chunked and the completed prompt publishes exactly as before.
  All host-side scheduling: the jit decode step program is
  byte-identical chunking on/off (decode-only lint contract verbatim,
  zero new collectives; lint family serve_engine_chunked pins it).

- ROBUSTNESS (ISSUE 10): every failure is a typed ``serving.errors``
  exception with a ``retriable`` verdict; admission is policy-pluggable
  (``scheduler.DeadlinePolicy`` sheds SLO-unreachable requests with a
  retriable ``DeadlineExceeded``); ``cancel(rid)`` evicts a queued or
  mid-stream request through the same host-table rewrite path as EOS;
  a slot whose carried logits go non-finite is contained pre-dispatch
  (``SlotPoisoned``) instead of streaming garbage; and ``self_check()``
  is the consolidated invariant sweep the servesan chaos harness
  (serving/chaos.py) proves detects every injected fault class. All of
  it is host-side control plane — the jit step program stays
  byte-identical (the serve_engine/serve_engine_prefix lint contracts
  hold verbatim, zero new collectives).

TPU perf notes (CPU-correct here; open items for the chip, queued in
results/decode_v5e.txt): per-slot host state is re-uploaded every step
(~KBs; should become device-resident carries), and the step program
unstacks the stacked block params per dispatch — the known ~131 us/token
re-slice cost (unstack_blocks docstring) — acceptable until the engine
grows a persistent on-device param cache, since unstacking on the host
would double param HBM.
"""

from __future__ import annotations

import math
import time as _time

import jax
import jax.numpy as jnp
import numpy as np
from jax import shard_map
from jax.sharding import NamedSharding

from cs336_systems_tpu.models.decode import (
    PAGE_BLOCK,
    _sample,
    decode_step,
    prefill_chunk,
    prefill_suffix,
    slot_prefill,
    unstack_blocks,
    validate_block_tables,
)
from cs336_systems_tpu.models.transformer import TransformerConfig
from cs336_systems_tpu.parallel.serve import engine_specs
from cs336_systems_tpu.parallel.serve import lint_contract as _serve_lint
from cs336_systems_tpu.serving.errors import (
    AdmissionImpossible,
    InvariantViolation,
    ServingError,
    SlotPoisoned,
)
from cs336_systems_tpu.serving.flight import FlightRecorder
from cs336_systems_tpu.serving.pool import PagePool
from cs336_systems_tpu.serving.prefix_cache import PrefixCache, params_fingerprint
from cs336_systems_tpu.serving.scheduler import AdmissionPolicy, Request, Scheduler


def engine_lint_contract(cfg: TransformerConfig, dp_axis=None, tp_axis=None,
                         ep_axis=None) -> dict:
    """Collective contract of ``make_engine_step`` — the decode-only
    serve contract (no prefill sites in the step program)."""
    return _serve_lint(cfg, dp_axis, tp_axis, ep_axis, decode_only=True)


def make_engine_step(cfg: TransformerConfig, page_block: int,
                     mesh=None, dp_axis: str | None = None,
                     tp_axis: str | None = None,
                     temperature: float = 1.0, top_k: int | None = None,
                     top_p: float | None = None, attn_impl: str = "auto",
                     approx_top_k: bool = False, donate: bool = True):
    """Build the steady-state engine step:

    ``(params, pool, logits, keys, pos, active, row_off, tables) ->
    (pool, logits, tokens, keys, pos)``

    pool: per-layer tuple of [P, H, block, 2*Dh] page pools (donated —
    the only multi-MB state); logits [slots, V] fp32 (each slot's last
    logits); keys [slots, 2] uint32 per-slot PRNG chains; pos/active/
    row_off [slots] int32; tables [slots, max_blocks] int32.

    One step = sample each slot's next token from its carried logits
    (per-slot key split + global-row fold_in — the oracle's exact key
    schedule), then one paged decode step with the active mask. Inactive
    slots produce garbage tokens/logits and write only to the scratch
    page. ``donate=False`` for analysis tracing (tracekit re-runs the
    same bundle)."""
    temperature = float(temperature)

    def local(params, pool, logits, keys, pos, active, row_off, tables):
        params = unstack_blocks(params)
        ks = jax.vmap(jax.random.split)(keys)  # [S, 2, 2]
        keys2, subs = ks[:, 0], ks[:, 1]
        nxt = _sample(logits, subs, temperature, top_k, top_p,
                      approx_top_k, row_key_offset=row_off).astype(jnp.int32)
        new_logits, cache = decode_step(
            params, {"kv": pool}, pos, nxt, cfg, None, attn_impl,
            tp_axis, tables, page_block, active)
        pos2 = jnp.where(active != 0, pos + 1, pos)
        return cache["kv"], new_logits, nxt, keys2, pos2

    donate_args = (1,) if donate else ()
    if mesh is None:
        return jax.jit(local, donate_argnums=donate_args)
    pspecs, pool_spec, batch_spec = engine_specs(cfg, dp_axis, tp_axis)
    fn = shard_map(
        local, mesh=mesh,
        in_specs=(pspecs, pool_spec, batch_spec, batch_spec, batch_spec,
                  batch_spec, batch_spec, batch_spec),
        out_specs=(pool_spec, batch_spec, batch_spec, batch_spec,
                   batch_spec),
        check_vma=False,  # same argument as make_sharded_generate: the
        # slot state is tp-replicated by construction (psum'd activations
        # + per-slot keys); the strict checker cannot prove it
    )
    return jax.jit(fn, donate_argnums=donate_args)


def _pow2(n: int) -> int:
    p = 1
    while p < n:
        p *= 2
    return p


# Measurement seam (scripts/check_chunked_prefill_gate.py): called with
# the token count of every prefill dispatch BETWEEN the span's two clock
# reads, so a deterministic work-proportional virtual clock can charge
# prefill time per token — the flight-recorder stall decomposition then
# compares chunked vs monolithic prefill on structure alone, no wall
# jitter. Same idiom as checkpoint._FAULT_HOOK / train_cli._STEP_FAULT_
# HOOK; None (a no-op) in production.
_PREFILL_CLOCK_HOOK = None


class _PrefillState:
    """Host-side cursor of one mid-prefill (chunked) request: the slot
    it will occupy, the acquired prefix-hit pages, the private pages
    for its uncached tail, and ``done`` — the absolute prompt-token
    count already landed in the pool. ``done`` starts at hit·block,
    advances one chunk per drained step, and is ALWAYS a multiple of
    the page block while the cursor lives (only a prompt's final chunk
    may be ragged, and landing it retires the cursor). The slot stays
    INACTIVE (scratch-steered in the decode step) until the final
    chunk's boundary logits move the request to ``running``."""

    __slots__ = ("slot", "req", "priv", "hit", "hit_pages", "done",
                 "chunks")

    def __init__(self, slot, req, priv, hit, hit_pages, done):
        self.slot, self.req = slot, req
        self.priv, self.hit = list(priv), hit
        self.hit_pages = list(hit_pages)
        self.done = done   # absolute tokens landed (hit·block at admit)
        self.chunks = 0    # chunks dispatched so far


class ServingEngine:
    """Continuous-batching serving: submit ``Request``s, step the slot
    batch, stream tokens back per request.

    ``slots``: fixed decode-batch capacity (divisible by the dp degree);
    ``n_pages``: PER-SHARD page-pool capacity; ``max_blocks``: table
    width — the per-request page-count ceiling. ``key``: base PRNG key;
    a request's stream equals ``generate_kv_batched(..., key=key,
    row_keyed=True, row_key_offset=row, page_block=...)`` on its row.
    ``eos_token_id``: a slot sampling EOS finishes WITHOUT emitting it
    (the oracle's truncation excludes the EOS token) and its pages free
    immediately. ``clock``: callable for arrival/latency timestamps
    (benchmarks pass time.monotonic; tests drive virtual time through
    ``step(now)``/``run(time_fn)``). ``prefix_cache``: shard-local
    shared-prefix KV page reuse (default on; False builds the unshared
    twin — same streams bit-for-bit, no page sharing)."""

    def __init__(self, params, cfg: TransformerConfig, *, key,
                 slots: int, n_pages: int, max_blocks: int,
                 page_block: int = PAGE_BLOCK,
                 temperature: float = 1.0, top_k: int | None = None,
                 top_p: float | None = None,
                 eos_token_id: int | None = None,
                 attn_impl: str = "auto", approx_top_k: bool = False,
                 mesh=None, dp_axis: str | None = None,
                 tp_axis: str | None = None,
                 clock=None, on_token=None, prefix_cache: bool = True,
                 policy: AdmissionPolicy | None = None,
                 flight: bool = True,
                 prefill_chunk: int | None = None,
                 prefill_budget: int | None = None):
        if page_block <= 0 or page_block % 8:
            raise ValueError(
                f"page block must be a positive multiple of 8, "
                f"got {page_block}")
        # chunked prefill (ISSUE 15): chunk = per-request tokens per
        # drained step (page-aligned so every non-final chunk boundary
        # lands on a block edge); budget = the per-STEP token bound
        # across all mid-prefill requests (defaults to one chunk).
        # None = the unchunked engine, byte-identical to pre-ISSUE-15.
        if prefill_chunk is not None:
            if prefill_chunk <= 0 or prefill_chunk % page_block:
                raise ValueError(
                    f"prefill_chunk must be a positive multiple of "
                    f"page_block={page_block}, got {prefill_chunk}")
            if prefill_budget is None:
                prefill_budget = prefill_chunk
            elif prefill_budget < prefill_chunk:
                raise ValueError(
                    f"prefill_budget ({prefill_budget}) must be >= "
                    f"prefill_chunk ({prefill_chunk})")
        elif prefill_budget is not None:
            raise ValueError("prefill_budget requires prefill_chunk")
        if slots < 1:
            raise ValueError(f"slots must be >= 1, got {slots}")
        if n_pages < 1 or max_blocks < 1:
            raise ValueError(
                f"n_pages ({n_pages}) and max_blocks ({max_blocks}) "
                f"must be >= 1")
        dp = 1
        if mesh is not None:
            for name, ax in (("dp_axis", dp_axis), ("tp_axis", tp_axis)):
                if ax is not None and ax not in mesh.shape:
                    raise ValueError(f"{name}={ax!r} not in mesh "
                                     f"{dict(mesh.shape)}")
            if dp_axis is not None:
                dp = mesh.shape[dp_axis]
            if tp_axis is not None and cfg.num_heads % mesh.shape[tp_axis]:
                raise ValueError(
                    f"num_heads={cfg.num_heads} must divide by "
                    f"{tp_axis}={mesh.shape[tp_axis]}")
        if slots % dp:
            raise ValueError(f"slots={slots} not divisible by dp={dp}")
        self.cfg = cfg
        self.params = params
        self.page_block = page_block
        self.slots, self.n_pages, self.max_blocks = slots, n_pages, max_blocks
        self.mesh, self.dp_axis, self.tp_axis = mesh, dp_axis, tp_axis
        self.dp, self.slots_per = dp, slots // dp
        self.eos_token_id = eos_token_id
        self.clock, self.on_token = clock, on_token
        self.base_key = np.asarray(jax.device_get(key), np.uint32).reshape(2)

        # shard-local allocators — page ids in the tables are shard-local
        self.pools = [PagePool(n_pages) for _ in range(dp)]
        # shard-local prefix caches (prefix_cache=False: the unshared
        # twin for A/B tests and the memkit margin check)
        self.prefix_caches = None
        if prefix_cache:
            fp = params_fingerprint(params)
            self.prefix_caches = [
                PrefixCache(self.pools[k], page_block, fp)
                for k in range(dp)]
        # one physical page's HBM across all layers (full heads — the
        # host books model bytes, not per-tp-shard bytes)
        self._page_bytes = (cfg.num_heads * page_block * 2 * cfg.d_head
                            * jnp.dtype(cfg.cdtype).itemsize
                            * cfg.num_layers)
        # prefix telemetry (benchmarks/serving.py columns)
        self.prefix_hit_tokens = 0     # prompt tokens served from cache
        self.prefix_prompt_tokens = 0  # prompt tokens admitted
        self.prefill_tokens = 0        # tokens actually run through prefill
        self.shared_kv_bytes_peak = 0  # high-water of shared-page HBM
        self.scheduler = Scheduler(policy)
        self.flight = FlightRecorder(enabled=flight)
        self.running: dict[int, Request] = {}
        self.results: dict[int, np.ndarray] = {}
        # terminal non-success outcomes (ISSUE 10): rid -> retriable
        # typed error for shed/poisoned requests; rid -> partial stream
        # for cancelled ones. results/failed/cancelled are disjoint and
        # together cover every submitted rid once the engine drains.
        self.failed: dict[int, ServingError] = {}
        self.cancelled: dict[int, np.ndarray] = {}
        self.steps = 0
        # chunked prefill: mid-prefill cursors by slot — dict insertion
        # order IS the FIFO drain order — plus the two benchmark
        # telemetry counters (benchmarks/serving.py columns)
        self.prefill_chunk = prefill_chunk
        self.prefill_budget = prefill_budget
        self.prefilling: dict[int, _PrefillState] = {}
        self.prefill_chunks = 0           # chunk dispatches, total
        self.max_step_prefill_tokens = 0  # max tokens drained per step

        # host-side slot state, re-uploaded per step (see module note)
        self.tables = np.zeros((slots, max_blocks), np.int32)
        self.pos = np.zeros((slots,), np.int32)
        self.active = np.zeros((slots,), np.int32)
        self.keys = np.zeros((slots, 2), np.uint32)
        self.row_off = np.zeros((slots,), np.int32)
        self.logits = np.zeros((slots, cfg.vocab_size), np.float32)

        # device pool: dp shard-pools stacked on the page axis, each with
        # its own scratch page at local index n_pages
        shape = (dp * (n_pages + 1), cfg.num_heads, page_block,
                 2 * cfg.d_head)
        pool = tuple(jnp.zeros(shape, cfg.cdtype)
                     for _ in range(cfg.num_layers))
        if mesh is not None:
            _, pool_spec, _ = engine_specs(cfg, dp_axis, tp_axis)
            sh = NamedSharding(mesh, pool_spec)
            pool = tuple(jax.device_put(x, sh) for x in pool)
        self._pool = pool

        self._step_fn = make_engine_step(
            cfg, page_block, mesh=mesh, dp_axis=dp_axis, tp_axis=tp_axis,
            temperature=temperature, top_k=top_k, top_p=top_p,
            attn_impl=attn_impl, approx_top_k=approx_top_k)
        self._pf_cache = {}

    def _t(self, now: float) -> float:
        """Recorder timestamp: the wall clock when one is set, else the
        step's virtual ``now`` — called UNCONDITIONALLY of the
        recorder's enabled flag so a stateful test clock ticks
        identically recorder on/off."""
        return self.clock() if self.clock is not None else now

    # -- admission ---------------------------------------------------

    def _pages_needed(self, req: Request) -> int:
        return -(-(req.prompt.size + req.max_new_tokens) // self.page_block)

    def submit(self, req: Request) -> None:
        """Queue a request, or raise the non-retriable
        ``AdmissionImpossible`` when NO sequence of evictions could ever
        admit it — checked exhaustively at submit time (context window,
        whole-shard page pool, block-table width, live rid) so an
        impossible request never occupies queue space it cannot convert
        into a slot, and a page-starved scheduler head can only ever be
        waiting on pages that CAN free up."""
        if req.prompt.size + req.max_new_tokens > self.cfg.context_length:
            raise AdmissionImpossible(
                f"request {req.rid}: prompt ({req.prompt.size}) + "
                f"max_new_tokens ({req.max_new_tokens}) exceeds "
                f"context_length={self.cfg.context_length}")
        npg = self._pages_needed(req)
        if npg > self.n_pages:
            raise AdmissionImpossible(
                f"request {req.rid} needs {npg} pages; the shard pool has "
                f"{self.n_pages} — it could never be admitted")
        if npg > self.max_blocks:
            raise AdmissionImpossible(
                f"request {req.rid} needs {npg} blocks; tables are "
                f"{self.max_blocks} wide")
        if (req.rid in self.scheduler
                or any(r.rid == req.rid for r in self.running.values())
                or any(st.req.rid == req.rid
                       for st in self.prefilling.values())):
            raise AdmissionImpossible(
                f"request {req.rid} is already queued or running "
                f"(duplicate rid)")
        self.scheduler.submit(req)
        # t = the request's LOGICAL submission time (its arrival), not a
        # clock read: submit may run before the trace clock starts
        self.flight.event("submit", req.rid, float(req.arrival),
                          prompt_tokens=int(req.prompt.size),
                          max_new_tokens=int(req.max_new_tokens))

    def _admit(self, now: float) -> int:
        """Strict-FIFO join: the head request takes a free slot whose
        shard allocator can hold its pages; if none can, it BLOCKS
        (nothing behind it bypasses) until an eviction frees capacity.

        With the prefix cache on, per head request: look up the longest
        cached page-aligned prefix on each shard with a free slot, pick
        the deepest hit (ties -> lowest slot, the FIFO order), ACQUIRE
        the hit pages, LRU-spill unreferenced cached pages if the free
        list is short (acquire-first so the spill can never reclaim the
        request's own hit), and allocate private pages only for the
        tail. Feasibility counts spillable pages, so cached-but-idle
        prefixes can never deadlock admission. A full-prompt hit whose
        final trie node cached boundary logits joins with ZERO device
        work. When the head request's missing blocks are about to be
        published by joins already collected in THIS batch, the batch is
        FLUSHED first (prefill + publish) and admission continues — an
        arrival burst sharing a cold prefix prefills it once, not N
        times.

        CHUNKED mode (``prefill_chunk`` set): slot/page selection,
        feasibility and blocking are IDENTICAL, but instead of a join
        the request gets a ``_PrefillState`` cursor — ``_drain_prefill``
        runs its chunks across subsequent steps and only the final
        chunk makes it ``running``. A cold shared prefix may prefill
        more than once (no pending-flush — the cursor batch spans
        steps), which publish-skips-cached-blocks makes harmless:
        streams are row-local either way."""
        # policy shedding first: an expired request must never reach a
        # slot (FIFO's policy sheds nothing — identical behavior)
        for req, err in self.scheduler.shed_expired(now):
            req.finish_time = now
            self.failed[req.rid] = err
            self.flight.event("shed", req.rid, now,
                              error=type(err).__name__)
        admitted = 0
        joins = []
        # chain hashes the current join batch will publish, per shard
        pending = [set() for _ in range(self.dp)]
        while True:
            req = self.scheduler.head(now)
            if req is None:
                break
            npg = self._pages_needed(req)
            # lowest free slot per shard
            free_slot = {}
            for s in range(self.slots):
                k = s // self.slots_per
                if (s not in self.running and s not in self.prefilling
                        and k not in free_slot):
                    free_slot[k] = s
            if self.prefix_caches is None:
                slot = None
                for k in sorted(free_slot):
                    if self.pools[k].available >= npg:
                        slot = free_slot[k]
                        break
                if slot is None:
                    break
                self.scheduler.pop(req.rid)
                pages = self.pools[slot // self.slots_per].alloc(
                    npg, req.rid)
                self.prefill_tokens += req.prompt.size
                admitted += 1
                self.flight.event(
                    "admit", req.rid, self._t(now), slot=slot,
                    shard=slot // self.slots_per, hit_tokens=0,
                    suffix_tokens=int(req.prompt.size))
                if self.prefill_chunk is not None:
                    # chunked: enqueue a cursor instead of a join — the
                    # request runs only when its last chunk lands
                    self.prefilling[slot] = _PrefillState(
                        slot, req, pages, 0, [], 0)
                else:
                    self.running[slot] = req
                    joins.append((slot, req, pages, 0, []))
                continue

            t_lk = self._t(now)
            hashes = (self.prefix_caches[0].chain_hashes(req.prompt)
                      if free_slot else [])
            self.flight.span("prefix_lookup", t_lk, self._t(now))
            # flush-on-pending-conflict: the blocks this request misses
            # are being published by the batch we're holding — land them
            # first so this request (and the rest of the burst) can hit
            if joins and any(h in pending[k] for k in free_slot
                             for h in hashes):
                self._prefill_joins(joins, now)
                joins = []
                pending = [set() for _ in range(self.dp)]
                continue
            t_lk = self._t(now)
            best = None  # (-hit, slot, shard, pages, logits)
            for k in sorted(free_slot):
                pool, cache = self.pools[k], self.prefix_caches[k]
                hit, pages, logits = cache.lookup(req.prompt)
                # the hit's own refcount-0 pages stop being spillable
                # the moment we acquire them — discount them
                hit_ref0 = sum(1 for p in pages if pool.refcount(p) == 0)
                if (pool.available + cache.spillable_pages() - hit_ref0
                        < npg - hit):
                    continue
                cand = (-hit, free_slot[k], k, pages, logits)
                if best is None or cand < best:
                    best = cand
            self.flight.span("prefix_lookup", t_lk, self._t(now))
            if best is None:
                break
            neg_hit, slot, shard, hit_pages, cached_logits = best
            hit = -neg_hit
            self.scheduler.pop(req.rid)
            pool, cache = self.pools[shard], self.prefix_caches[shard]
            if hit:
                pool.acquire(hit_pages, req.rid)
            need = npg - hit  # >= 1: growth pages outlive the prompt
            if need > pool.available:
                cache.spill(need - pool.available)
            priv = pool.alloc(need, req.rid)
            req.prefix_hit_tokens = hit * self.page_block
            self.prefix_hit_tokens += hit * self.page_block
            self.prefix_prompt_tokens += req.prompt.size
            admitted += 1
            self.flight.event(
                "admit", req.rid, self._t(now), slot=slot, shard=shard,
                hit_tokens=hit * self.page_block,
                suffix_tokens=max(int(req.prompt.size)
                                  - hit * self.page_block, 0))
            if cached_logits is not None:
                # zero-prefill join: the whole prompt is cached and the
                # publisher's boundary logits replay the join state
                # (chunked mode too — there is nothing to chunk)
                self.running[slot] = req
                t_rw = self._t(now)
                self.logits[slot] = cached_logits
                self.pos[slot] = req.prompt.size
                self.active[slot] = 1
                self.keys[slot] = self.base_key
                self.row_off[slot] = req.row
                tab = hit_pages + priv
                self.tables[slot] = tab + [tab[-1]] * (
                    self.max_blocks - len(tab))
                self._update_shared_peak()
                t_rw1 = self._t(now)
                self.flight.span("table_rewrite", t_rw, t_rw1)
                # decode-ready with zero device work: running == admit
                self.flight.event("running", req.rid, t_rw1,
                                  step=self.steps)
                continue
            self.prefill_tokens += req.prompt.size - hit * self.page_block
            if self.prefill_chunk is not None:
                self.prefilling[slot] = _PrefillState(
                    slot, req, priv, hit, hit_pages,
                    hit * self.page_block)
                continue
            self.running[slot] = req
            pending[shard].update(hashes[hit:])
            joins.append((slot, req, priv, hit, hit_pages))
        if joins:
            self._prefill_joins(joins, now)
        return admitted

    def _update_shared_peak(self) -> None:
        if self.prefix_caches is None:
            return
        cur = sum(len(c) for c in self.prefix_caches) * self._page_bytes
        self.shared_kv_bytes_peak = max(self.shared_kv_bytes_peak, cur)

    # -- prefill-into-pool -------------------------------------------

    def _prefill_fn(self, jw: int, plen: int, npg: int):
        cache_key = (jw, plen, npg)
        fn = self._pf_cache.get(cache_key)
        if fn is not None:
            return fn
        cfg, blk, tp = self.cfg, self.page_block, self.tp_axis

        def local(params, pool, ids, lens, prows, pblks, dest):
            logits, pages, _ = slot_prefill(
                params, ids, cfg, lens, blk, (None, prows, pblks),
                reduce_axis=tp)
            pool = tuple(x.at[dest].set(pg) for x, pg in zip(pool, pages))
            return logits, pool

        if self.mesh is None:
            fn = jax.jit(local, donate_argnums=(1,))
        else:
            pspecs, pool_spec, batch_spec = engine_specs(
                cfg, self.dp_axis, tp)
            fn = jax.jit(shard_map(
                local, mesh=self.mesh,
                in_specs=(pspecs, pool_spec, batch_spec, batch_spec,
                          batch_spec, batch_spec, batch_spec),
                out_specs=(batch_spec, pool_spec),
                check_vma=False), donate_argnums=(1,))
        self._pf_cache[cache_key] = fn
        return fn

    def _prefill_suffix_fn(self, jw: int, sw: int, npg: int, pnb: int):
        """Compiled suffix-prefill bucket: like ``_prefill_fn`` but the
        rows attend their cached prefix pages out of the (donated) pool
        via models/decode.prefill_suffix and scatter only SUFFIX pages."""
        cache_key = ("sfx", jw, sw, npg, pnb)
        fn = self._pf_cache.get(cache_key)
        if fn is not None:
            return fn
        cfg, blk, tp = self.cfg, self.page_block, self.tp_axis

        def local(params, pool, ids, slens, plens, ptab, prows, pblks,
                  dest):
            logits, pages, _ = prefill_suffix(
                params, ids, cfg, slens, plens, ptab, pool, blk,
                (None, prows, pblks), reduce_axis=tp)
            pool = tuple(x.at[dest].set(pg) for x, pg in zip(pool, pages))
            return logits, pool

        if self.mesh is None:
            fn = jax.jit(local, donate_argnums=(1,))
        else:
            pspecs, pool_spec, batch_spec = engine_specs(
                cfg, self.dp_axis, tp)
            fn = jax.jit(shard_map(
                local, mesh=self.mesh,
                in_specs=(pspecs, pool_spec, batch_spec, batch_spec,
                          batch_spec, batch_spec, batch_spec, batch_spec,
                          batch_spec),
                out_specs=(batch_spec, pool_spec),
                check_vma=False), donate_argnums=(1,))
        self._pf_cache[cache_key] = fn
        return fn

    def _prefill_chunk_fn(self, jw: int, sw: int, npg: int, pnb: int):
        """Compiled chunk-prefill bucket. ``decode.prefill_chunk`` IS
        ``prefill_suffix`` (a documented delegation), so the bucket is
        cached under the SAME key as ``_prefill_suffix_fn`` — chunk
        dispatches and suffix joins of one shape share one compiled
        program, and chunking adds zero steady-state compiles beyond
        the suffix path's existing buckets."""
        cache_key = ("sfx", jw, sw, npg, pnb)
        fn = self._pf_cache.get(cache_key)
        if fn is not None:
            return fn
        cfg, blk, tp = self.cfg, self.page_block, self.tp_axis

        def local(params, pool, ids, slens, dlens, ptab, prows, pblks,
                  dest):
            logits, pages, _ = prefill_chunk(
                params, ids, cfg, slens, dlens, ptab, pool, blk,
                (None, prows, pblks), reduce_axis=tp)
            pool = tuple(x.at[dest].set(pg) for x, pg in zip(pool, pages))
            return logits, pool

        if self.mesh is None:
            fn = jax.jit(local, donate_argnums=(1,))
        else:
            pspecs, pool_spec, batch_spec = engine_specs(
                cfg, self.dp_axis, tp)
            fn = jax.jit(shard_map(
                local, mesh=self.mesh,
                in_specs=(pspecs, pool_spec, batch_spec, batch_spec,
                          batch_spec, batch_spec, batch_spec, batch_spec,
                          batch_spec),
                out_specs=(batch_spec, pool_spec),
                check_vma=False), donate_argnums=(1,))
        self._pf_cache[cache_key] = fn
        return fn

    def _prefill_joins(self, joins, now: float = math.inf) -> None:
        """Prefill the join batch and scatter its pages into the pool.

        Shapes are bucketed — join width to a power of two, prompt width
        to a multiple of 8, page count to a power of two — so repeated
        joins reuse a handful of compiled programs. Padding rows carry a
        1-token dummy prompt and padding geometry entries scatter to the
        shard's LOCAL scratch page (id n_pages — never in a table), so
        junk K/V never lands on allocated pages. Row-local numerics make
        each request's prefill bit-equal to the oracle's regardless of
        the join batch around it.

        Joins are (slot, req, private_pages, hit_blocks, hit_pages).
        All-miss batches take the full-prompt path; any prefix hit
        switches the batch to the SUFFIX path (prefill_suffix) where
        each row runs only its uncached tail against its acquired
        prefix pages. Either way, completed rows PUBLISH their full
        prompt blocks into the shard's prefix cache."""
        t_pf0 = self._t(now)
        blk, dp, npages = self.page_block, self.dp, self.n_pages
        per_shard = [[] for _ in range(dp)]
        for j in joins:
            per_shard[j[0] // self.slots_per].append(j)
        jw = _pow2(max(len(v) for v in per_shard))
        max_hit = max(j[3] for j in joins)

        if max_hit == 0:
            plen = -(-max(j[1].prompt.size for j in joins) // 8) * 8
            npg = _pow2(max(
                max((sum(-(-req.prompt.size // blk) for _, req, *_ in v)
                     for v in per_shard if v), default=1), 1))
            ids = np.zeros((dp * jw, plen), np.int32)
            lens = np.ones((dp * jw,), np.int32)  # dummy rows: 1 pad token
            prows = np.zeros((dp * npg,), np.int32)
            pblks = np.zeros((dp * npg,), np.int32)
            dest = np.full((dp * npg,), npages, np.int32)  # default: scratch
            for k, v in enumerate(per_shard):
                o = 0
                for r, (slot, req, pages, _hit, _hp) in enumerate(v):
                    ln = req.prompt.size
                    ids[k * jw + r, :ln] = req.prompt
                    lens[k * jw + r] = ln
                    nbp = -(-ln // blk)  # prompt blocks only; growth pages
                    # start with stale/zero data decode overwrites
                    prows[k * npg + o:k * npg + o + nbp] = r
                    pblks[k * npg + o:k * npg + o + nbp] = np.arange(nbp)
                    dest[k * npg + o:k * npg + o + nbp] = pages[:nbp]
                    o += nbp
            fn = self._prefill_fn(jw, plen, npg)
            logits, self._pool = fn(
                self.params, self._pool, jnp.asarray(ids),
                jnp.asarray(lens), jnp.asarray(prows),
                jnp.asarray(pblks), jnp.asarray(dest))
        else:
            sfx = lambda req, hit: req.prompt.size - hit * blk
            sw = -(-max(sfx(req, hit)
                        for _, req, _, hit, _hp in joins) // 8) * 8
            npg = _pow2(max(
                max((sum(-(-sfx(req, hit) // blk)
                         for _, req, _, hit, _hp in v)
                     for v in per_shard if v), default=1), 1))
            pnb = _pow2(max(max_hit, 1))
            ids = np.zeros((dp * jw, sw), np.int32)
            slens = np.ones((dp * jw,), np.int32)
            plens = np.zeros((dp * jw,), np.int32)
            # pad table entries read the scratch page; the validity mask
            # retires them before they reach a softmax
            ptab = np.full((dp * jw, pnb), npages, np.int32)
            prows = np.zeros((dp * npg,), np.int32)
            pblks = np.zeros((dp * npg,), np.int32)
            dest = np.full((dp * npg,), npages, np.int32)
            for k, v in enumerate(per_shard):
                o = 0
                for r, (slot, req, priv, hit, hit_pages) in enumerate(v):
                    ln = sfx(req, hit)
                    ids[k * jw + r, :ln] = req.prompt[hit * blk:]
                    slens[k * jw + r] = ln
                    plens[k * jw + r] = hit * blk
                    ptab[k * jw + r, :hit] = hit_pages
                    nbp = -(-ln // blk)  # suffix prompt blocks
                    prows[k * npg + o:k * npg + o + nbp] = r
                    pblks[k * npg + o:k * npg + o + nbp] = np.arange(nbp)
                    dest[k * npg + o:k * npg + o + nbp] = priv[:nbp]
                    o += nbp
            fn = self._prefill_suffix_fn(jw, sw, npg, pnb)
            logits, self._pool = fn(
                self.params, self._pool, jnp.asarray(ids),
                jnp.asarray(slens), jnp.asarray(plens),
                jnp.asarray(ptab), jnp.asarray(prows),
                jnp.asarray(pblks), jnp.asarray(dest))

        lg = np.asarray(jax.device_get(logits))
        # the prefill span: operand build + bucket dispatch + logits
        # readback — the window during which every OTHER running slot's
        # decode is blocked (servetrace's prefill_stall component)
        tokens = int(sum(j[1].prompt.size - j[3] * blk for j in joins))
        if _PREFILL_CLOCK_HOOK is not None:
            _PREFILL_CLOCK_HOOK(tokens)
        t_pf1 = self._t(now)
        self.flight.prefill(
            t_pf0, t_pf1, [j[1].rid for j in joins], tokens=tokens)
        for k, v in enumerate(per_shard):
            for r, (slot, req, priv, hit, hit_pages) in enumerate(v):
                self.logits[slot] = lg[k * jw + r]
                self.pos[slot] = req.prompt.size
                self.active[slot] = 1
                self.keys[slot] = self.base_key  # fresh per-slot chain
                self.row_off[slot] = req.row
                tab = list(hit_pages) + list(priv)
                self.tables[slot] = tab + [tab[-1]] * (
                    self.max_blocks - len(tab))
        if self.prefix_caches is not None:
            for slot, req, priv, hit, hit_pages in joins:
                cache = self.prefix_caches[slot // self.slots_per]
                nbp = -(-(req.prompt.size - hit * blk) // blk)
                cache.publish(
                    req.prompt, req.rid,
                    {hit + j: priv[j] for j in range(nbp)},
                    logits=self.logits[slot])
            self._update_shared_peak()
        # scratch-never-in-a-table + copy-on-write, checked on every join
        self._validate_tables()
        self.flight.span("table_rewrite", t_pf1, self._t(now))
        for slot, req, priv, hit, hit_pages in joins:
            self.flight.event("running", req.rid, t_pf1, step=self.steps)

    def _drain_prefill(self, now: float) -> None:
        """Run at most ``prefill_budget`` tokens of chunk work — the
        bounded per-step prefill bill chunking exists to enforce
        (ISSUE 15; the flight-recorder prefill records are what the CI
        gate asserts the bound from).

        Drain policy: mid-prefill cursors in FIFO admission order, at
        most ONE chunk of ``min(prefill_chunk, remaining)`` tokens
        each, stopping at the first cursor whose chunk would push the
        step total over the budget (strict FIFO — nothing behind it
        bypasses; the first cursor always fits since chunk <= budget,
        so every non-empty drain makes progress). The batch dispatches
        exactly like a suffix join batch — each row's "prefix" is its
        landed blocks (hit pages + earlier chunks' private pages) —
        and a row whose cursor reaches the prompt end takes its
        boundary logits as the join state ``slot_prefill`` would have
        produced, publishes, and moves to ``running``."""
        if not self.prefilling:
            return
        batch = []  # (cursor, chunk tokens) in FIFO admission order
        total = 0
        for st in self.prefilling.values():
            ct = min(self.prefill_chunk, st.req.prompt.size - st.done)
            if total + ct > self.prefill_budget:
                break
            batch.append((st, ct))
            total += ct
        if not batch:
            return
        t_pf0 = self._t(now)
        blk, dp, npages = self.page_block, self.dp, self.n_pages
        per_shard = [[] for _ in range(dp)]
        for st, ct in batch:
            per_shard[st.slot // self.slots_per].append((st, ct))
        jw = _pow2(max(len(v) for v in per_shard))
        sw = -(-max(ct for _, ct in batch) // 8) * 8
        npg = _pow2(max(
            max((sum(-(-ct // blk) for _, ct in v)
                 for v in per_shard if v), default=1), 1))
        pnb = _pow2(max(max(st.done // blk for st, _ in batch), 1))
        ids = np.zeros((dp * jw, sw), np.int32)
        slens = np.ones((dp * jw,), np.int32)  # dummy rows: 1 pad token
        dlens = np.zeros((dp * jw,), np.int32)
        # pad table entries read the scratch page; the validity mask
        # retires them before they reach a softmax
        ptab = np.full((dp * jw, pnb), npages, np.int32)
        prows = np.zeros((dp * npg,), np.int32)
        pblks = np.zeros((dp * npg,), np.int32)
        dest = np.full((dp * npg,), npages, np.int32)  # default: scratch
        for k, v in enumerate(per_shard):
            o = 0
            for r, (st, ct) in enumerate(v):
                ids[k * jw + r, :ct] = st.req.prompt[st.done:st.done + ct]
                slens[k * jw + r] = ct
                dlens[k * jw + r] = st.done
                nb_done = st.done // blk  # blocks already landed
                landed = (st.hit_pages + st.priv)[:nb_done]
                ptab[k * jw + r, :nb_done] = landed
                nbc = -(-ct // blk)  # this chunk's blocks
                prows[k * npg + o:k * npg + o + nbc] = r
                pblks[k * npg + o:k * npg + o + nbc] = np.arange(nbc)
                first = nb_done - st.hit  # first private-block index
                dest[k * npg + o:k * npg + o + nbc] = \
                    st.priv[first:first + nbc]
                o += nbc
        fn = self._prefill_chunk_fn(jw, sw, npg, pnb)
        logits, self._pool = fn(
            self.params, self._pool, jnp.asarray(ids),
            jnp.asarray(slens), jnp.asarray(dlens), jnp.asarray(ptab),
            jnp.asarray(prows), jnp.asarray(pblks), jnp.asarray(dest))
        lg = np.asarray(jax.device_get(logits))
        if _PREFILL_CLOCK_HOOK is not None:
            _PREFILL_CLOCK_HOOK(int(total))
        t_pf1 = self._t(now)
        self.flight.prefill(
            t_pf0, t_pf1, [st.req.rid for st, _ in batch],
            tokens=int(total),
            chunks=[{"rid": st.req.rid, "chunk": st.chunks,
                     "tokens": int(ct)} for st, ct in batch])
        self.prefill_chunks += len(batch)
        self.max_step_prefill_tokens = max(
            self.max_step_prefill_tokens, total)
        finished = []
        for k, v in enumerate(per_shard):
            for r, (st, ct) in enumerate(v):
                st.done += ct
                st.chunks += 1
                if st.done == st.req.prompt.size:
                    finished.append((st, lg[k * jw + r]))
        for st, boundary in finished:
            slot, req = st.slot, st.req
            self.logits[slot] = boundary
            self.pos[slot] = req.prompt.size
            self.active[slot] = 1
            self.keys[slot] = self.base_key  # fresh per-slot chain
            self.row_off[slot] = req.row
            tab = st.hit_pages + st.priv
            self.tables[slot] = tab + [tab[-1]] * (
                self.max_blocks - len(tab))
            del self.prefilling[slot]
            self.running[slot] = req
        if self.prefix_caches is not None and finished:
            for st, _ in finished:
                cache = self.prefix_caches[st.slot // self.slots_per]
                nbp = -(-(st.req.prompt.size - st.hit * blk) // blk)
                cache.publish(
                    st.req.prompt, st.req.rid,
                    {st.hit + j: st.priv[j] for j in range(nbp)},
                    logits=self.logits[st.slot])
            self._update_shared_peak()
        self._validate_tables()
        self.flight.span("table_rewrite", t_pf1, self._t(now))
        for st, _ in finished:
            self.flight.event("running", st.req.rid, t_pf1,
                              step=self.steps)

    def _validate_tables(self) -> None:
        """The block-table contracts, per shard: no scratch id in any
        table, and (prefix cache on) no ACTIVE row's write block on a
        shared page — models/decode.validate_block_tables."""
        if self.prefix_caches is None:
            validate_block_tables(self.tables, self.n_pages)
            return
        for k in range(self.dp):
            sl = slice(k * self.slots_per, (k + 1) * self.slots_per)
            validate_block_tables(
                self.tables[sl], self.n_pages,
                read_only=self.pools[k].shared_page_ids(),
                write_pos=self.pos[sl], block=self.page_block,
                active=self.active[sl])

    # -- the steady-state step ---------------------------------------

    def _release_slot(self, slot: int, req: Request, when: float) -> None:
        """The one eviction path (EOS, max_new, cancel, poison): free
        the request's private pages, release its shared prefix refs
        (pages stay cached at refcount-1 less), deactivate the slot —
        the step program then scratch-steers its writes — and drop it
        from running. Host-table rewrites only; zero recompiles."""
        pool = self.pools[slot // self.slots_per]
        if pool.owns(req.rid):
            pool.free(req.rid)
        if pool.acquired_by(req.rid):
            pool.release(req.rid)  # shared pages stay cached, refcount-1
        self.active[slot] = 0
        del self.running[slot]
        req.finish_time = when

    def _release_prefill(self, slot: int, st: _PrefillState,
                         when: float) -> None:
        """Mid-prefill eviction (cancel): free the cursor's private
        pages, release its acquired prefix refs, drop the cursor. The
        slot was never activated, so no device state needs touching —
        the partially-landed KV is dead weight the pages' next owner
        overwrites, and the pool conservation gate sees zero leaks."""
        pool = self.pools[slot // self.slots_per]
        if pool.owns(st.req.rid):
            pool.free(st.req.rid)
        if pool.acquired_by(st.req.rid):
            pool.release(st.req.rid)
        del self.prefilling[slot]
        st.req.finish_time = when

    def _finish(self, slot: int, req: Request, when: float) -> None:
        self._release_slot(slot, req, when)
        self.results[req.rid] = np.asarray(req.tokens, np.int32)

    def _fail_slot(self, slot: int, req: Request, err: ServingError,
                   when: float) -> None:
        """Evict a slot with a typed error instead of a result; the
        tokens streamed before the failure stay on ``req.tokens``."""
        self._release_slot(slot, req, when)
        self.failed[req.rid] = err

    def cancel(self, rid: int, now: float | None = None) -> bool:
        """Cancel a request mid-stream or while queued; returns whether
        anything was cancelled (False: unknown/already finished — cancel
        is idempotent). A running request's eviction is the same
        host-table rewrite as EOS (pages freed, prefix refs released,
        slot scratch-steered; zero recompiles); its partial stream lands
        in ``cancelled[rid]``. Remaining streams are untouched — tokens
        are row-local, so they stay bit-identical to an oracle that
        never saw the cancelled request."""
        when = now
        if when is None:
            when = self.clock() if self.clock is not None else math.inf
        req = self.scheduler.remove(rid)
        if req is not None:
            req.finish_time = when
            self.cancelled[rid] = np.asarray(req.tokens, np.int32)
            self.flight.event("cancel", rid, when, running=False,
                              tokens=0)
            return True
        for slot, run in list(self.running.items()):
            if run.rid == rid:
                self._release_slot(slot, run, when)
                self.cancelled[rid] = np.asarray(run.tokens, np.int32)
                self.flight.event("cancel", rid, when, running=True,
                                  tokens=len(run.tokens))
                return True
        for slot, st in list(self.prefilling.items()):
            if st.req.rid == rid:
                # mid-prefill: no tokens streamed yet — the cursor's
                # pages release cleanly, same as a queued cancel
                self._release_prefill(slot, st, when)
                self.cancelled[rid] = np.asarray(st.req.tokens, np.int32)
                self.flight.event("cancel", rid, when, running=False,
                                  tokens=0)
                return True
        return False

    def _contain_poisoned(self, when: float) -> list:
        """Poisoned-slot containment: a slot whose CARRIED logits went
        non-finite would sample garbage on the next dispatch — evict it
        with the retriable ``SlotPoisoned`` first (tokens already
        streamed came from finite logits and stay valid). Runs before
        every dispatch, so prefill-poisoned joins are contained before
        their first decode step too. Returns [(rid, err)]."""
        out = []
        for slot in sorted(self.running):
            if np.isfinite(self.logits[slot]).all():
                continue
            req = self.running[slot]
            err = SlotPoisoned(
                f"slot {slot} (rid {req.rid}): non-finite carried "
                f"logits after {len(req.tokens)} tokens",
                shard=slot // self.slots_per)
            self._fail_slot(slot, req, err, when)
            self.flight.event("poison", req.rid, when,
                              tokens=len(req.tokens))
            out.append((req.rid, err))
        return out

    def step(self, now: float | None = None) -> list:
        """Admit what has arrived by ``now``, run ONE decode step over
        the slot batch, emit/evict. Returns [(rid, token-or-None)]
        events (None = finished at EOS without emitting)."""
        if now is None:
            now = self.clock() if self.clock is not None else math.inf
        step_i = self.steps
        t_enter = self._t(now)
        self.flight.begin_step(step_i, t_enter)
        self._admit(now)
        # chunked prefill: at most prefill_budget tokens of chunk work
        # before the decode dispatch (no-op when prefill_chunk is None)
        self._drain_prefill(now)
        # containment BEFORE dispatch: a poisoned carry never reaches
        # the sampler (joins above may have admitted poisoned prefills)
        self._contain_poisoned(now)
        t_admit = self._t(now)
        # schedule_admit = the admit segment minus the lookup/prefill/
        # rewrite sub-spans recorded inside it
        self.flight.admit_residual(t_enter, t_admit)
        if not self.running:
            self.flight.drop_step()  # idle invocation, not a step
            return []
        # copy-on-write, re-checked per dispatch: the step is about to
        # write every active row's block pos // block
        self._validate_tables()
        t_val = self._t(now)
        self.flight.span("table_rewrite", t_admit, t_val)
        out = self._step_fn(
            self.params, self._pool, jnp.asarray(self.logits),
            jnp.asarray(self.keys), jnp.asarray(self.pos),
            jnp.asarray(self.active), jnp.asarray(self.row_off),
            jnp.asarray(self.tables))
        self._pool = out[0]
        # dispatch is async: this span is the HOST cost of launching the
        # step; the device wait lands in readback_sample's device_get
        t_disp = self._t(now)
        self.flight.span("step_dispatch", t_val, t_disp)
        logits, toks, keys, pos = jax.device_get(out[1:])
        # device_get hands back read-only arrays; joins mutate these
        self.logits, self.keys, self.pos = (
            np.array(logits), np.array(keys), np.array(pos))
        self.steps += 1

        emit_t = self.clock() if self.clock is not None else now
        events = []
        emitted, evicted = [], []
        for slot in sorted(self.running):
            req = self.running[slot]
            t = int(toks[slot])
            if self.eos_token_id is not None and t == self.eos_token_id:
                # the oracle's truncation EXCLUDES the EOS token
                self._finish(slot, req, emit_t)
                evicted.append(req.rid)
                self.flight.event("finish", req.rid, emit_t, step=step_i,
                                  tokens=len(req.tokens), eos=True)
                events.append((req.rid, None))
                continue
            first = not req.tokens
            req.tokens.append(t)
            req.emit_times.append(emit_t)
            emitted.append(req.rid)
            if first:
                self.flight.event("first_token", req.rid, emit_t,
                                  step=step_i)
            if self.on_token is not None:
                self.on_token(req.rid, t)
            events.append((req.rid, t))
            if len(req.tokens) >= req.max_new_tokens:
                self._finish(slot, req, emit_t)
                evicted.append(req.rid)
                self.flight.event("finish", req.rid, emit_t, step=step_i,
                                  tokens=len(req.tokens), eos=False)
        t_exit = self._t(now)
        self.flight.span("readback_sample", t_disp, t_exit)
        self.flight.end_step(
            t_exit, emitted, evicted,
            self._counters(now) if self.flight.enabled else {})
        return events

    def _counters(self, now: float) -> dict:
        """Scheduler/pool/prefix-cache snapshot for the step record —
        the per-window occupancy/free-pages/hit-rate counters of the
        servetrace artifact."""
        return {
            "running": len(self.running),
            "prefilling": len(self.prefilling),
            "queued": len(self.scheduler),
            "arrived": self.scheduler.depth(now),
            "free_pages": sum(p.available for p in self.pools),
            "shared_pages": (sum(len(c) for c in self.prefix_caches)
                             if self.prefix_caches is not None else 0),
            "prefix_hit_tokens": self.prefix_hit_tokens,
            "prefill_tokens": self.prefill_tokens,
        }

    def run(self, time_fn=None) -> dict[int, np.ndarray]:
        """Drive steps until every submitted request completes; returns
        {rid: tokens}. ``time_fn``: virtual clock for tests (the engine
        fast-forwards an idle batch to the next arrival); without it the
        engine's ``clock`` (wall time) or "everything already arrived"
        (math.inf) applies."""
        while len(self.scheduler) or self.running or self.prefilling:
            if time_fn is not None:
                now = time_fn()
            elif self.clock is not None:
                now = self.clock()
            else:
                now = math.inf
            if (not self.running and not self.prefilling
                    and self.scheduler.head(now) is None):
                nxt = self.scheduler.next_arrival()
                if self.clock is not None and time_fn is None:
                    _time.sleep(min(max(nxt - now, 0.0), 0.05))
                    continue
                now = nxt  # virtual clock: jump to the next arrival
            self.step(now)
        return self.results

    # -- invariants ---------------------------------------------------

    def check_conserved(self) -> None:
        """Shard-by-shard pool partition + refcount check against the
        LIVE block tables (serving/pool.check_conserved) — runnable at
        any point, drained or not. Re-raises the pool's typed error
        with the shard attached."""
        for k in range(self.dp):
            tabs = [self.tables[s] for s in sorted(self.running)
                    if s // self.slots_per == k]
            # mid-prefill cursors hold pages with no live table yet —
            # their page lists stand in as pseudo-tables so acquired
            # hit pages' refcounts reconcile
            tabs += [np.asarray(st.hit_pages + st.priv, np.int32)
                     for s, st in sorted(self.prefilling.items())
                     if s // self.slots_per == k]
            try:
                self.pools[k].check_conserved(tabs)
            except ServingError as e:
                raise type(e)(e.detail, shard=k) from None

    def check_idle(self) -> None:
        """Drained-engine invariant (the CI smoke's leak gate): no
        running requests and every shard pool fully free — the prefix
        caches spill their (necessarily unreferenced) pages first."""
        if self.running:
            raise InvariantViolation(
                f"requests still running: "
                f"{sorted(r.rid for r in self.running.values())}")
        if self.prefilling:
            raise InvariantViolation(
                f"requests still mid-prefill: "
                f"{sorted(st.req.rid for st in self.prefilling.values())}")
        for k, p in enumerate(self.pools):
            if self.prefix_caches is not None:
                self.prefix_caches[k].drop_unreferenced()
            try:
                p.check_all_free()
            except ServingError as e:
                raise type(e)(e.detail, shard=k) from None

    def self_check(self) -> None:
        """Consolidated invariant sweep (ISSUE 10) — the detector the
        servesan chaos harness (serving/chaos.py) proves catches every
        injected fault class. Sweep order, most-specific error first:

        1. block-table contracts (scratch-page + copy-on-write) →
           ``CorruptBlockTable``
        2. pool conservation partition → ``InvariantViolation``;
           refcount vs acquire records / live tables →
           ``RefcountViolation``
        3. prefix-trie ↔ pool consistency → ``InvariantViolation``
        4. slot ↔ allocator coherence: active mask == running set,
           every running slot's table pages allocated TO that rid,
           every private owner a running or mid-prefill rid →
           ``InvariantViolation``
        5. chunk-cursor coherence (chunked prefill, ISSUE 15): a
           mid-prefill slot is inactive and not running, its cursor is
           block-aligned inside [hit·block, prompt), and its pages are
           allocated to it → ``InvariantViolation`` (torn chunk cursor)
        6. finite carried sampling state → ``SlotPoisoned``

        Raises the first violation; a clean engine returns None. Pure
        host-side reads — never dispatches, safe at any point."""
        self._validate_tables()
        self.check_conserved()
        if self.prefix_caches is not None:
            for k, cache in enumerate(self.prefix_caches):
                cache.self_check(shard=k)
        all_rids = [req.rid for req in self.running.values()]
        all_rids += [st.req.rid for st in self.prefilling.values()]
        running_rids = set(all_rids)
        if len(all_rids) != len(running_rids):
            dupes = sorted(r for r in running_rids
                           if all_rids.count(r) > 1)
            raise InvariantViolation(
                f"duplicate rid(s) {dupes} in the running set — two "
                f"slots are streaming the same request")
        for slot in range(self.slots):
            is_running = slot in self.running
            if bool(self.active[slot]) != is_running:
                raise InvariantViolation(
                    f"slot {slot}: active={int(self.active[slot])} but "
                    f"{'in' if is_running else 'not in'} the running set",
                    shard=slot // self.slots_per)
        for slot in sorted(self.running):
            req = self.running[slot]
            k = slot // self.slots_per
            pool = self.pools[k]
            allowed = set(pool.owned_by(req.rid) if pool.owns(req.rid)
                          else []) | set(pool.acquired_by(req.rid))
            table_pages = set(int(p) for p in self.tables[slot])
            stray = table_pages - allowed
            if stray:
                raise InvariantViolation(
                    f"slot {slot} (rid {req.rid}): table pages "
                    f"{sorted(stray)} are not allocated to it", shard=k)
        for slot, st in sorted(self.prefilling.items()):
            req, k = st.req, slot // self.slots_per
            pool = self.pools[k]
            if slot in self.running:
                raise InvariantViolation(
                    f"slot {slot}: both running and mid-prefill "
                    f"(rid {req.rid})", shard=k)
            if self.active[slot]:
                raise InvariantViolation(
                    f"slot {slot} (rid {req.rid}): active while "
                    f"mid-prefill — a chunked join may only activate "
                    f"on its final chunk", shard=k)
            lo = st.hit * self.page_block
            if (st.done < lo or st.done >= req.prompt.size
                    or st.done % self.page_block):
                raise InvariantViolation(
                    f"slot {slot} (rid {req.rid}): torn chunk cursor — "
                    f"done={st.done} outside [{lo}, {req.prompt.size}) "
                    f"or not a multiple of page_block="
                    f"{self.page_block}", shard=k)
            owned = set(pool.owned_by(req.rid) if pool.owns(req.rid)
                        else [])
            if not set(int(p) for p in st.priv) <= owned:
                raise InvariantViolation(
                    f"slot {slot} (rid {req.rid}): chunk cursor's "
                    f"private pages are not allocated to it", shard=k)
            if not (set(int(p) for p in st.hit_pages)
                    <= set(pool.acquired_by(req.rid))):
                raise InvariantViolation(
                    f"slot {slot} (rid {req.rid}): chunk cursor's hit "
                    f"pages are not acquired by it", shard=k)
        for k, pool in enumerate(self.pools):
            orphans = pool.owners() - running_rids
            if orphans:
                raise InvariantViolation(
                    f"private pages owned by non-running rids "
                    f"{sorted(orphans, key=repr)}", shard=k)
        for slot in sorted(self.running):
            if not np.isfinite(self.logits[slot]).all():
                req = self.running[slot]
                raise SlotPoisoned(
                    f"slot {slot} (rid {req.rid}): non-finite carried "
                    f"logits", shard=slot // self.slots_per)
