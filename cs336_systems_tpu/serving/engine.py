"""Continuous-batching engine: one jit-compiled steady-state decode step
over a fixed-capacity SLOT batch, with host-side join/evict.

Design (the fungible-row-slot property models/decode.paged_kv_geometry
was built for):

- The device state is ONE paged KV pool per layer plus a [slots]-shaped
  decode batch: [slots, max_blocks] block tables, per-slot positions, an
  active mask, per-slot PRNG key chains, per-slot row indices, and the
  last logits. Every shape is static, so joining or evicting a request
  only rewrites HOST tables — the step executable never recompiles.
- Inactive slots ride through the step as dead weight: the paged
  attention op steers their KV writes to the pool's reserved scratch
  page (ops/decode_attention active mask) so a freed slot can never
  corrupt pages the allocator has already handed to a new request, and
  their sampled tokens/logits are garbage the host ignores.
- Joins prefill through SEPARATE per-shape-bucket programs
  (models/decode.slot_prefill): the join batch runs the ragged paged
  prefill against a throwaway local geometry, and the resulting page
  arrays scatter into the long-lived pool at allocator-assigned ids.
  Bucketed (join_width, prompt_len, page_count) shapes bound the number
  of compiles.
- Streams are BIT-IDENTICAL to the row-keyed oracle
  (generate_kv_batched(..., row_keyed=True, page_block=...)) no matter
  when a request joins: each slot carries its own PRNG key chain, reset
  to the engine's base key at join, advanced by one split per decode
  step — after j emitted tokens the slot's sub-key equals the oracle's
  step-j sub-key — and sampling folds in the request's GLOBAL row index
  (models/decode._sample vector row_key_offset). Numerics are row-local
  (a row's logits depend only on its own tokens — the ragged/paged
  equivalence tests pin this), so neither the join batch's composition
  nor the physical page ids perturb a stream.
- dp/tp meshes (parallel/serve.engine_specs): slots shard over dp with
  SHARD-LOCAL pools and shard-local PagePool allocators (page ids in the
  tables are shard-local; no page crosses the mesh); tp shards heads.
  The decode-only collective contract is serve.lint_contract(...,
  decode_only=True): dp = 0 psums, tp = 2L.

TPU perf notes (CPU-correct here; open items for the chip, queued in
results/decode_v5e.txt): per-slot host state is re-uploaded every step
(~KBs; should become device-resident carries), and the step program
unstacks the stacked block params per dispatch — the known ~131 us/token
re-slice cost (unstack_blocks docstring) — acceptable until the engine
grows a persistent on-device param cache, since unstacking on the host
would double param HBM.
"""

from __future__ import annotations

import math
import time as _time

import jax
import jax.numpy as jnp
import numpy as np
from jax import shard_map
from jax.sharding import NamedSharding

from cs336_systems_tpu.models.decode import (
    PAGE_BLOCK,
    _sample,
    decode_step,
    slot_prefill,
    unstack_blocks,
    validate_block_tables,
)
from cs336_systems_tpu.models.transformer import TransformerConfig
from cs336_systems_tpu.parallel.serve import engine_specs
from cs336_systems_tpu.parallel.serve import lint_contract as _serve_lint
from cs336_systems_tpu.serving.pool import PagePool
from cs336_systems_tpu.serving.scheduler import Request, Scheduler


def engine_lint_contract(cfg: TransformerConfig, dp_axis=None, tp_axis=None,
                         ep_axis=None) -> dict:
    """Collective contract of ``make_engine_step`` — the decode-only
    serve contract (no prefill sites in the step program)."""
    return _serve_lint(cfg, dp_axis, tp_axis, ep_axis, decode_only=True)


def make_engine_step(cfg: TransformerConfig, page_block: int,
                     mesh=None, dp_axis: str | None = None,
                     tp_axis: str | None = None,
                     temperature: float = 1.0, top_k: int | None = None,
                     top_p: float | None = None, attn_impl: str = "auto",
                     approx_top_k: bool = False, donate: bool = True):
    """Build the steady-state engine step:

    ``(params, pool, logits, keys, pos, active, row_off, tables) ->
    (pool, logits, tokens, keys, pos)``

    pool: per-layer tuple of [P, H, block, 2*Dh] page pools (donated —
    the only multi-MB state); logits [slots, V] fp32 (each slot's last
    logits); keys [slots, 2] uint32 per-slot PRNG chains; pos/active/
    row_off [slots] int32; tables [slots, max_blocks] int32.

    One step = sample each slot's next token from its carried logits
    (per-slot key split + global-row fold_in — the oracle's exact key
    schedule), then one paged decode step with the active mask. Inactive
    slots produce garbage tokens/logits and write only to the scratch
    page. ``donate=False`` for analysis tracing (tracekit re-runs the
    same bundle)."""
    temperature = float(temperature)

    def local(params, pool, logits, keys, pos, active, row_off, tables):
        params = unstack_blocks(params)
        ks = jax.vmap(jax.random.split)(keys)  # [S, 2, 2]
        keys2, subs = ks[:, 0], ks[:, 1]
        nxt = _sample(logits, subs, temperature, top_k, top_p,
                      approx_top_k, row_key_offset=row_off).astype(jnp.int32)
        new_logits, cache = decode_step(
            params, {"kv": pool}, pos, nxt, cfg, None, attn_impl,
            tp_axis, tables, page_block, active)
        pos2 = jnp.where(active != 0, pos + 1, pos)
        return cache["kv"], new_logits, nxt, keys2, pos2

    donate_args = (1,) if donate else ()
    if mesh is None:
        return jax.jit(local, donate_argnums=donate_args)
    pspecs, pool_spec, batch_spec = engine_specs(cfg, dp_axis, tp_axis)
    fn = shard_map(
        local, mesh=mesh,
        in_specs=(pspecs, pool_spec, batch_spec, batch_spec, batch_spec,
                  batch_spec, batch_spec, batch_spec),
        out_specs=(pool_spec, batch_spec, batch_spec, batch_spec,
                   batch_spec),
        check_vma=False,  # same argument as make_sharded_generate: the
        # slot state is tp-replicated by construction (psum'd activations
        # + per-slot keys); the strict checker cannot prove it
    )
    return jax.jit(fn, donate_argnums=donate_args)


def _pow2(n: int) -> int:
    p = 1
    while p < n:
        p *= 2
    return p


class ServingEngine:
    """Continuous-batching serving: submit ``Request``s, step the slot
    batch, stream tokens back per request.

    ``slots``: fixed decode-batch capacity (divisible by the dp degree);
    ``n_pages``: PER-SHARD page-pool capacity; ``max_blocks``: table
    width — the per-request page-count ceiling. ``key``: base PRNG key;
    a request's stream equals ``generate_kv_batched(..., key=key,
    row_keyed=True, row_key_offset=row, page_block=...)`` on its row.
    ``eos_token_id``: a slot sampling EOS finishes WITHOUT emitting it
    (the oracle's truncation excludes the EOS token) and its pages free
    immediately. ``clock``: callable for arrival/latency timestamps
    (benchmarks pass time.monotonic; tests drive virtual time through
    ``step(now)``/``run(time_fn)``)."""

    def __init__(self, params, cfg: TransformerConfig, *, key,
                 slots: int, n_pages: int, max_blocks: int,
                 page_block: int = PAGE_BLOCK,
                 temperature: float = 1.0, top_k: int | None = None,
                 top_p: float | None = None,
                 eos_token_id: int | None = None,
                 attn_impl: str = "auto", approx_top_k: bool = False,
                 mesh=None, dp_axis: str | None = None,
                 tp_axis: str | None = None,
                 clock=None, on_token=None):
        if page_block <= 0 or page_block % 8:
            raise ValueError(
                f"page block must be a positive multiple of 8, "
                f"got {page_block}")
        dp = 1
        if mesh is not None:
            for name, ax in (("dp_axis", dp_axis), ("tp_axis", tp_axis)):
                if ax is not None and ax not in mesh.shape:
                    raise ValueError(f"{name}={ax!r} not in mesh "
                                     f"{dict(mesh.shape)}")
            if dp_axis is not None:
                dp = mesh.shape[dp_axis]
            if tp_axis is not None and cfg.num_heads % mesh.shape[tp_axis]:
                raise ValueError(
                    f"num_heads={cfg.num_heads} must divide by "
                    f"{tp_axis}={mesh.shape[tp_axis]}")
        if slots % dp:
            raise ValueError(f"slots={slots} not divisible by dp={dp}")
        self.cfg = cfg
        self.params = params
        self.page_block = page_block
        self.slots, self.n_pages, self.max_blocks = slots, n_pages, max_blocks
        self.mesh, self.dp_axis, self.tp_axis = mesh, dp_axis, tp_axis
        self.dp, self.slots_per = dp, slots // dp
        self.eos_token_id = eos_token_id
        self.clock, self.on_token = clock, on_token
        self.base_key = np.asarray(jax.device_get(key), np.uint32).reshape(2)

        # shard-local allocators — page ids in the tables are shard-local
        self.pools = [PagePool(n_pages) for _ in range(dp)]
        self.scheduler = Scheduler()
        self.running: dict[int, Request] = {}
        self.results: dict[int, np.ndarray] = {}
        self.steps = 0

        # host-side slot state, re-uploaded per step (see module note)
        self.tables = np.zeros((slots, max_blocks), np.int32)
        self.pos = np.zeros((slots,), np.int32)
        self.active = np.zeros((slots,), np.int32)
        self.keys = np.zeros((slots, 2), np.uint32)
        self.row_off = np.zeros((slots,), np.int32)
        self.logits = np.zeros((slots, cfg.vocab_size), np.float32)

        # device pool: dp shard-pools stacked on the page axis, each with
        # its own scratch page at local index n_pages
        shape = (dp * (n_pages + 1), cfg.num_heads, page_block,
                 2 * cfg.d_head)
        pool = tuple(jnp.zeros(shape, cfg.cdtype)
                     for _ in range(cfg.num_layers))
        if mesh is not None:
            _, pool_spec, _ = engine_specs(cfg, dp_axis, tp_axis)
            sh = NamedSharding(mesh, pool_spec)
            pool = tuple(jax.device_put(x, sh) for x in pool)
        self._pool = pool

        self._step_fn = make_engine_step(
            cfg, page_block, mesh=mesh, dp_axis=dp_axis, tp_axis=tp_axis,
            temperature=temperature, top_k=top_k, top_p=top_p,
            attn_impl=attn_impl, approx_top_k=approx_top_k)
        self._pf_cache = {}

    # -- admission ---------------------------------------------------

    def _pages_needed(self, req: Request) -> int:
        return -(-(req.prompt.size + req.max_new_tokens) // self.page_block)

    def submit(self, req: Request) -> None:
        if req.prompt.size + req.max_new_tokens > self.cfg.context_length:
            raise ValueError(
                f"request {req.rid}: prompt ({req.prompt.size}) + "
                f"max_new_tokens ({req.max_new_tokens}) exceeds "
                f"context_length={self.cfg.context_length}")
        npg = self._pages_needed(req)
        if npg > self.n_pages:
            raise ValueError(
                f"request {req.rid} needs {npg} pages; the shard pool has "
                f"{self.n_pages} — it could never be admitted")
        if npg > self.max_blocks:
            raise ValueError(
                f"request {req.rid} needs {npg} blocks; tables are "
                f"{self.max_blocks} wide")
        self.scheduler.submit(req)

    def _admit(self, now: float) -> int:
        """Strict-FIFO join: the head request takes the lowest free slot
        whose shard allocator can hold its pages; if none can, it BLOCKS
        (nothing behind it bypasses) until an eviction frees capacity."""
        joins = []
        while True:
            req = self.scheduler.head(now)
            if req is None:
                break
            npg = self._pages_needed(req)
            slot = None
            for s in range(self.slots):
                if s in self.running:
                    continue
                if self.pools[s // self.slots_per].available >= npg:
                    slot = s
                    break
            if slot is None:
                break
            self.scheduler.pop()
            pages = self.pools[slot // self.slots_per].alloc(npg, req.rid)
            self.running[slot] = req
            joins.append((slot, req, pages))
        if joins:
            self._prefill_joins(joins)
        return len(joins)

    # -- prefill-into-pool -------------------------------------------

    def _prefill_fn(self, jw: int, plen: int, npg: int):
        cache_key = (jw, plen, npg)
        fn = self._pf_cache.get(cache_key)
        if fn is not None:
            return fn
        cfg, blk, tp = self.cfg, self.page_block, self.tp_axis

        def local(params, pool, ids, lens, prows, pblks, dest):
            logits, pages, _ = slot_prefill(
                params, ids, cfg, lens, blk, (None, prows, pblks),
                reduce_axis=tp)
            pool = tuple(x.at[dest].set(pg) for x, pg in zip(pool, pages))
            return logits, pool

        if self.mesh is None:
            fn = jax.jit(local, donate_argnums=(1,))
        else:
            pspecs, pool_spec, batch_spec = engine_specs(
                cfg, self.dp_axis, tp)
            fn = jax.jit(shard_map(
                local, mesh=self.mesh,
                in_specs=(pspecs, pool_spec, batch_spec, batch_spec,
                          batch_spec, batch_spec, batch_spec),
                out_specs=(batch_spec, pool_spec),
                check_vma=False), donate_argnums=(1,))
        self._pf_cache[cache_key] = fn
        return fn

    def _prefill_joins(self, joins) -> None:
        """Prefill the join batch and scatter its pages into the pool.

        Shapes are bucketed — join width to a power of two, prompt width
        to a multiple of 8, page count to a power of two — so repeated
        joins reuse a handful of compiled programs. Padding rows carry a
        1-token dummy prompt and padding geometry entries scatter to the
        shard's LOCAL scratch page (id n_pages — never in a table), so
        junk K/V never lands on allocated pages. Row-local numerics make
        each request's prefill bit-equal to the oracle's regardless of
        the join batch around it."""
        blk, dp, npages = self.page_block, self.dp, self.n_pages
        per_shard = [[] for _ in range(dp)]
        for slot, req, pages in joins:
            per_shard[slot // self.slots_per].append((slot, req, pages))
        jw = _pow2(max(len(v) for v in per_shard))
        plen = -(-max(req.prompt.size for _, req, _ in joins) // 8) * 8
        npg = _pow2(max(
            max((sum(-(-req.prompt.size // blk) for _, req, _ in v)
                 for v in per_shard if v), default=1), 1))

        ids = np.zeros((dp * jw, plen), np.int32)
        lens = np.ones((dp * jw,), np.int32)  # dummy rows: 1 pad token
        prows = np.zeros((dp * npg,), np.int32)
        pblks = np.zeros((dp * npg,), np.int32)
        dest = np.full((dp * npg,), npages, np.int32)  # default: scratch
        for k, v in enumerate(per_shard):
            o = 0
            for r, (slot, req, pages) in enumerate(v):
                ln = req.prompt.size
                ids[k * jw + r, :ln] = req.prompt
                lens[k * jw + r] = ln
                nbp = -(-ln // blk)  # prompt blocks only; growth pages
                # start with stale/zero data decode overwrites pre-attend
                prows[k * npg + o:k * npg + o + nbp] = r
                pblks[k * npg + o:k * npg + o + nbp] = np.arange(nbp)
                dest[k * npg + o:k * npg + o + nbp] = pages[:nbp]
                o += nbp

        fn = self._prefill_fn(jw, plen, npg)
        logits, self._pool = fn(self.params, self._pool, jnp.asarray(ids),
                                jnp.asarray(lens), jnp.asarray(prows),
                                jnp.asarray(pblks), jnp.asarray(dest))
        lg = np.asarray(jax.device_get(logits))
        for k, v in enumerate(per_shard):
            for r, (slot, req, pages) in enumerate(v):
                self.logits[slot] = lg[k * jw + r]
                self.pos[slot] = req.prompt.size
                self.active[slot] = 1
                self.keys[slot] = self.base_key  # fresh per-slot chain
                self.row_off[slot] = req.row
                self.tables[slot] = (pages
                                     + [pages[-1]]
                                     * (self.max_blocks - len(pages)))
        # the scratch-never-in-a-table contract, checked on every join
        validate_block_tables(self.tables, self.n_pages)

    # -- the steady-state step ---------------------------------------

    def _finish(self, slot: int, req: Request, when: float) -> None:
        self.pools[slot // self.slots_per].free(req.rid)
        self.active[slot] = 0
        del self.running[slot]
        req.finish_time = when
        self.results[req.rid] = np.asarray(req.tokens, np.int32)

    def step(self, now: float | None = None) -> list:
        """Admit what has arrived by ``now``, run ONE decode step over
        the slot batch, emit/evict. Returns [(rid, token-or-None)]
        events (None = finished at EOS without emitting)."""
        if now is None:
            now = self.clock() if self.clock is not None else math.inf
        self._admit(now)
        if not self.running:
            return []
        out = self._step_fn(
            self.params, self._pool, jnp.asarray(self.logits),
            jnp.asarray(self.keys), jnp.asarray(self.pos),
            jnp.asarray(self.active), jnp.asarray(self.row_off),
            jnp.asarray(self.tables))
        self._pool = out[0]
        logits, toks, keys, pos = jax.device_get(out[1:])
        # device_get hands back read-only arrays; joins mutate these
        self.logits, self.keys, self.pos = (
            np.array(logits), np.array(keys), np.array(pos))
        self.steps += 1

        emit_t = self.clock() if self.clock is not None else now
        events = []
        for slot in sorted(self.running):
            req = self.running[slot]
            t = int(toks[slot])
            if self.eos_token_id is not None and t == self.eos_token_id:
                # the oracle's truncation EXCLUDES the EOS token
                self._finish(slot, req, emit_t)
                events.append((req.rid, None))
                continue
            req.tokens.append(t)
            req.emit_times.append(emit_t)
            if self.on_token is not None:
                self.on_token(req.rid, t)
            events.append((req.rid, t))
            if len(req.tokens) >= req.max_new_tokens:
                self._finish(slot, req, emit_t)
        return events

    def run(self, time_fn=None) -> dict[int, np.ndarray]:
        """Drive steps until every submitted request completes; returns
        {rid: tokens}. ``time_fn``: virtual clock for tests (the engine
        fast-forwards an idle batch to the next arrival); without it the
        engine's ``clock`` (wall time) or "everything already arrived"
        (math.inf) applies."""
        while len(self.scheduler) or self.running:
            if time_fn is not None:
                now = time_fn()
            elif self.clock is not None:
                now = self.clock()
            else:
                now = math.inf
            if not self.running and self.scheduler.head(now) is None:
                nxt = self.scheduler.next_arrival()
                if self.clock is not None and time_fn is None:
                    _time.sleep(min(max(nxt - now, 0.0), 0.05))
                    continue
                now = nxt  # virtual clock: jump to the next arrival
            self.step(now)
        return self.results

    # -- invariants ---------------------------------------------------

    def check_idle(self) -> None:
        """Drained-engine invariant (the CI smoke's leak gate): no
        running requests and every shard pool fully free."""
        if self.running:
            raise AssertionError(f"requests still running: "
                                 f"{sorted(r.rid for r in self.running.values())}")
        for k, p in enumerate(self.pools):
            try:
                p.check_all_free()
            except AssertionError as e:
                raise AssertionError(f"shard {k}: {e}") from None
