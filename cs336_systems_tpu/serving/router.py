"""Fleet router: multi-replica serving with prefix-affinity dispatch and
mid-stream failover (ISSUE 14).

The continuous-batching engine (engine.py) is chaos-hardened but
SINGULAR: one slot batch, one page pool, one failure domain. This module
is the first cross-replica scheduler decision in the repo — a
``FleetRouter`` over N independent ``ServingEngine`` replicas (the
replica-pool topology of the Gemma-on-TPU serving comparison point,
PAPERS.md), built on three contracts the single engine already pins:

- PREFIX-AFFINITY DISPATCH. The prefix cache (ISSUE 9) is shard-local by
  design, so the fleet-level hit rate is a ROUTING property: requests
  sharing a page-aligned prefix must land on the replica that already
  holds its KV. The affinity key is the PrefixCache chain hash of the
  request's FIRST full block (the same ``chain_hashes`` the trie is
  keyed by — params fingerprint included, so two fleets never alias);
  first sight of a key pins it to the least-loaded eligible replica,
  later requests follow it. Cold prefixes (or ``policy="least-loaded"`` /
  ``"random"``) fall back to load balance. An affinity entry pointing at
  a replica that has since been quarantined is a TRANSIENT: dispatch
  logs a retriable ``ReplicaUnavailable`` and re-pins — never an
  invariant violation (that is reserved for entries naming an index
  outside the fleet).

- HEALTH STATE MACHINE, driven by the typed ServingError surface
  (ISSUE 10): healthy → degraded → quarantined. Every error a replica's
  ``step()``/``self_check()``/containment surfaces is absorbed as a
  STRIKE (logged in ``faults``); a degraded replica takes no NEW
  dispatches but keeps streaming; ``quarantine_after`` strikes — or a
  crash (non-ServingError escaping ``step``), or the dispatch WATCHDOG
  (a replica with running slots that produces zero events for
  ``watchdog_steps`` consecutive steps; a healthy engine emits or
  finishes every running slot every step, so silence IS the hang
  signal) — quarantines it: the replica is drained (best-effort cancel
  frees its pages) and never stepped again. ``heal_after`` consecutive
  clean steps walk a degraded replica back to healthy.

- MID-STREAM FAILOVER, bit-exact. A request's stream is a pure function
  of (params, base key, row, prompt) — the per-slot key chain resets to
  the engine's base key at join and folds in the request's global row
  (engine.py), so EVERY replica of a fleet built with the same base key
  produces the identical stream for a given request. On quarantine the
  router re-dispatches each in-flight request to a survivor as a fresh
  clone (same rid/row/prompt/arrival) that replays from the prompt; the
  AT-MOST-ONCE EMIT CURSOR (``_on_token``) verifies the replayed tokens
  against the already-delivered prefix token by token — a divergence is
  a torn stream, ``FleetInvariantViolation`` — and forwards only the
  extension, so a client callback never sees a duplicated or torn
  stream. Zero survivors is the shed-storm: every pending request fails
  with a retriable ``ReplicaUnavailable`` and ``run()`` terminates —
  proportional degradation through the existing AdmissionPolicy
  machinery, never a cliff or a hang.

Everything here is host-side control plane: the router never builds a
jit program, never adds a collective, and never touches the replicas'
step executables — the serve_engine/serve_engine_prefix lint contracts
hold verbatim, and a 1-replica router with affinity off drives the
engine through the exact same submit/step sequence as calling it
directly (tests/test_fleet_router.py pins byte-identity). The proof of
the failure semantics is fleetsan (fleet_chaos.py — ``python -m
cs336_systems_tpu.serving.fleet_chaos``), the gradsan/servesan-shaped
chaos harness that injects each fleet-level fault class and requires
the expected typed error AND surviving streams bit-exact to the
single-replica oracle.
"""

from __future__ import annotations

import hashlib
import math
import time as _time

import numpy as np

from cs336_systems_tpu.serving.engine import ServingEngine
from cs336_systems_tpu.serving.errors import (
    AdmissionImpossible,
    DeadlineExceeded,
    FleetInvariantViolation,
    ReplicaUnavailable,
    ServingError,
)
from cs336_systems_tpu.serving.flight import FlightRecorder
from cs336_systems_tpu.serving.scheduler import Request

POLICIES = ("affinity", "least-loaded", "random")


class _Replica:
    """Per-replica health record. ``state``: healthy (dispatchable) →
    degraded (streams, no new dispatches) → quarantined (drained, never
    stepped again). ``idle``: consecutive steps with running slots but
    zero events — the watchdog counter."""

    __slots__ = ("engine", "idx", "state", "strikes", "idle", "clean")

    def __init__(self, engine: ServingEngine, idx: int):
        self.engine = engine
        self.idx = idx
        self.state = "healthy"
        self.strikes = 0
        self.idle = 0
        self.clean = 0


class FleetRouter:
    """Route requests over N independent ``ServingEngine`` replicas.

    ``engines``: the replicas — same config, same ``page_block``, and
    (checked) the SAME base PRNG key, which is what makes a failed-over
    stream bit-identical to the original replica's. ``policy``: one of
    ``POLICIES`` (affinity = chain-hash pinning with least-loaded
    fallback). ``on_token(rid, tok)``: client callback, called exactly
    once per delivered token fleet-wide (the at-most-once cursor);
    the router OWNS every replica's ``on_token`` hook. ``on_step``:
    optional hook called at each ``step()`` entry with the router (the
    benchmark's kill-mid-trace seam). Mirrors the engine surface the
    benchmark driver consumes: ``submit``/``step``/``run``/``cancel``/
    ``results``/``failed``/``cancelled``/``check_idle``/``self_check``
    plus the summed prefix-cache telemetry."""

    def __init__(self, engines: list[ServingEngine], *,
                 policy: str = "affinity", watchdog_steps: int = 4,
                 quarantine_after: int = 3, max_redispatch: int = 3,
                 heal_after: int = 16, seed: int = 0,
                 on_token=None, on_step=None, flight: bool = True):
        if not engines:
            raise ValueError("FleetRouter needs at least one replica")
        if policy not in POLICIES:
            raise ValueError(f"unknown policy {policy!r}; one of {POLICIES}")
        base = engines[0]
        for k, eng in enumerate(engines):
            if not np.array_equal(eng.base_key, base.base_key):
                raise ValueError(
                    f"replica {k} has a different base key — failover "
                    f"streams would not be bit-identical")
            if eng.page_block != base.page_block:
                raise ValueError(
                    f"replica {k}: page_block {eng.page_block} != "
                    f"{base.page_block} — affinity keys would not agree")
        self.replicas = [_Replica(eng, k) for k, eng in enumerate(engines)]
        self.policy = policy
        self.watchdog_steps = int(watchdog_steps)
        self.quarantine_after = int(quarantine_after)
        self.max_redispatch = int(max_redispatch)
        self.heal_after = int(heal_after)
        self.on_token = on_token
        self.on_step = on_step
        self.clock = base.clock
        self._rng = np.random.default_rng(seed)
        self.flight = FlightRecorder(enabled=flight)
        for k, eng in enumerate(engines):
            eng.flight.replica = k
            eng.on_token = (lambda rid, tok, _k=k:
                            self._on_token(_k, rid, tok))

        # fleet-level request state
        self._requests: dict[int, Request] = {}   # rid -> ORIGINAL request
        self._cur_req: dict[int, Request] = {}    # rid -> live (orig/clone)
        self._where: dict[int, int] = {}          # rid -> assigned replica
        self._open: set[int] = set()              # submitted, not terminal
        self._tries: dict[int, int] = {}          # rid -> dispatch count
        # the at-most-once emit cursor: delivered tokens + per-(rid,
        # replica) stream positions; a replayed token must EQUAL the
        # delivered one at its position, only the extension forwards
        self._delivered: dict[int, list[int]] = {}
        self._emit_t: dict[int, list[float]] = {}
        self._seen: dict[tuple[int, int], int] = {}
        self._affinity: dict[bytes, int] = {}

        self.results: dict[int, np.ndarray] = {}
        self.failed: dict[int, ServingError] = {}
        self.cancelled: dict[int, np.ndarray] = {}
        self.faults: list[ServingError] = []  # every absorbed strike
        self.failovers = 0
        self.quarantines = 0
        self.rounds = 0        # router step() invocations
        self._now = 0.0

    # -- aggregate telemetry (benchmarks/serving.run_cell columns) -----

    @property
    def engines(self) -> list[ServingEngine]:
        return [rep.engine for rep in self.replicas]

    @property
    def steps(self) -> int:
        return sum(rep.engine.steps for rep in self.replicas)

    @property
    def slots(self) -> int:
        return sum(rep.engine.slots for rep in self.replicas)

    @property
    def dp(self) -> int:
        return self.replicas[0].engine.dp

    @property
    def running(self) -> dict:
        """Union of replica running maps, keyed (replica, slot) — only
        servetrace's live-token conservation reads it."""
        out = {}
        for rep in self.replicas:
            for slot, req in rep.engine.running.items():
                out[(rep.idx, slot)] = req
        return out

    @property
    def prefix_hit_tokens(self) -> int:
        return sum(r.engine.prefix_hit_tokens for r in self.replicas)

    @property
    def prefix_prompt_tokens(self) -> int:
        return sum(r.engine.prefix_prompt_tokens for r in self.replicas)

    @property
    def prefill_tokens(self) -> int:
        return sum(r.engine.prefill_tokens for r in self.replicas)

    @property
    def shared_kv_bytes_peak(self) -> int:
        return sum(r.engine.shared_kv_bytes_peak for r in self.replicas)

    def states(self) -> list[str]:
        return [rep.state for rep in self.replicas]

    # -- dispatch ------------------------------------------------------

    def _affinity_key(self, prompt: np.ndarray) -> bytes | None:
        """Chain hash of the first FULL page-aligned block — the exact
        key the replica tries are keyed by (params fingerprint folded
        in), so affinity agrees with what lookup() will actually hit.
        Prompts shorter than one block have no cacheable prefix: None →
        least-loaded fallback."""
        eng0 = self.replicas[0].engine
        if prompt.size < eng0.page_block:
            return None
        if eng0.prefix_caches is not None:
            hashes = eng0.prefix_caches[0].chain_hashes(prompt)
            if hashes:
                return hashes[0]
        return hashlib.blake2b(
            np.asarray(prompt[:eng0.page_block], np.int32).tobytes(),
            digest_size=16).digest()

    def _load(self, k: int) -> int:
        eng = self.replicas[k].engine
        return len(eng.scheduler) + len(eng.running)

    def _eligible(self, exclude: int | None = None) -> list[int]:
        """Dispatch targets: healthy replicas first; if none, degraded
        (still streaming) beats shedding; quarantined never."""
        for states in (("healthy",), ("healthy", "degraded")):
            ok = [rep.idx for rep in self.replicas
                  if rep.state in states and rep.idx != exclude]
            if ok:
                return ok
        return []

    def _pick(self, key: bytes | None, exclude: int | None = None) -> int | None:
        """Choose a replica for (re-)dispatch; None = no survivor."""
        eligible = self._eligible(exclude)
        if not eligible:
            return None
        if self.policy == "random":
            return int(eligible[self._rng.integers(len(eligible))])
        least = min(eligible, key=lambda k: (self._load(k), k))
        if self.policy != "affinity" or key is None:
            return least
        pinned = self._affinity.get(key)
        if pinned is not None and pinned in eligible:
            return pinned
        if pinned is not None and 0 <= pinned < len(self.replicas):
            # stale affinity: the pinned replica was quarantined (or is
            # the excluded faulty one) after the key was pinned — a
            # transient, re-routed with a logged retriable error; the
            # out-of-range case is FleetInvariantViolation in self_check
            self._log_fault(ReplicaUnavailable(
                f"stale affinity entry {key.hex()[:8]}: pinned replica "
                f"is {self.replicas[pinned].state} — re-routing to "
                f"replica {least}", replica=pinned))
        self._affinity[key] = least
        return least

    def _log_fault(self, err: ServingError) -> None:
        self.faults.append(err)

    def submit(self, req: Request) -> None:
        """Route and queue a request on one replica. Raises the
        replica's ``AdmissionImpossible`` verbatim (nothing was
        registered), or a retriable ``ReplicaUnavailable`` when the
        whole fleet is quarantined."""
        if req.rid in self._open:
            raise AdmissionImpossible(
                f"request {req.rid} is already live in the fleet "
                f"(duplicate rid)")
        key = (self._affinity_key(req.prompt)
               if self.policy == "affinity" else None)
        k = self._pick(key)
        if k is None:
            raise ReplicaUnavailable(
                f"request {req.rid}: no healthy replica in the fleet "
                f"({len(self.replicas)} quarantined) — resubmit when a "
                f"replica recovers")
        self.replicas[k].engine.submit(req)
        self._requests[req.rid] = req
        self._cur_req[req.rid] = req
        self._where[req.rid] = k
        self._open.add(req.rid)
        self._tries[req.rid] = 1
        self._seen[(req.rid, k)] = 0
        self._delivered.setdefault(req.rid, [])
        self._emit_t.setdefault(req.rid, [])
        self.flight.event("dispatch", req.rid, float(req.arrival),
                          replica=k)

    # -- the at-most-once emit cursor ---------------------------------

    def _on_token(self, k: int, rid: int, tok: int) -> None:
        """Every replica token lands here. Position ``pos`` of replica
        k's stream for ``rid``: below the delivered cursor it is a
        REPLAY and must match bit-for-bit (else the stream tore); at the
        cursor it extends and forwards to the client exactly once."""
        pos = self._seen.get((rid, k), 0)
        self._seen[(rid, k)] = pos + 1
        delivered = self._delivered.setdefault(rid, [])
        if pos < len(delivered):
            if tok != delivered[pos]:
                raise FleetInvariantViolation(
                    f"rid {rid}: replayed token at position {pos} on "
                    f"replica {k} is {tok}, already delivered "
                    f"{delivered[pos]} — torn stream")
            return  # replay of an already-delivered token: suppressed
        delivered.append(int(tok))
        req = self._cur_req.get(rid)
        self._emit_t.setdefault(rid, []).append(
            req.emit_times[-1] if req is not None and req.emit_times
            else self._now)
        if self.on_token is not None:
            self.on_token(rid, tok)

    # -- health machine / failover ------------------------------------

    def _strike(self, k: int, err: ServingError) -> None:
        rep = self.replicas[k]
        self._log_fault(err)
        rep.strikes += 1
        rep.clean = 0
        if rep.state == "healthy":
            rep.state = "degraded"
        if rep.strikes >= self.quarantine_after:
            self._quarantine(k, ReplicaUnavailable(
                f"quarantined after {rep.strikes} strikes "
                f"(last: {type(err).__name__}: {err})", replica=k))

    def _quarantine(self, k: int, err: ReplicaUnavailable) -> None:
        """Quarantine + drain: mark the replica dead, best-effort cancel
        its live requests (frees pages on a host-side-intact engine) and
        fail them over to survivors."""
        rep = self.replicas[k]
        if rep.state == "quarantined":
            return
        rep.state = "quarantined"
        self.quarantines += 1
        self._log_fault(err)
        self.flight.event("quarantine", None, self._now, replica=k,
                          error=err.detail)
        live = [r.rid for r in rep.engine.running.values()]
        live += [r.rid for _, _, r in rep.engine.scheduler._queue]
        for rid in live:
            try:
                rep.engine.cancel(rid, self._now)
            except Exception:  # noqa: BLE001 — the replica is dead; its
                pass           # allocator may be beyond a clean eviction
            if rid in self._open and self._where.get(rid) == k:
                self._redispatch(rid, exclude=k,
                                 why=f"replica {k} quarantined")

    def _redispatch(self, rid: int, exclude: int, why: str) -> None:
        """Fail a live request over to a survivor: a fresh clone (same
        rid/row/prompt/arrival — the key-chain identity) replays from
        the prompt; the emit cursor suppresses the replayed prefix."""
        if rid not in self._open:
            return
        orig, cur = self._requests[rid], self._cur_req[rid]
        delivered = self._delivered.get(rid, [])
        if self._tries.get(rid, 0) > self.max_redispatch:
            self._finalize_failure(rid, ReplicaUnavailable(
                f"request {rid}: gave up after "
                f"{self._tries[rid]} dispatches ({why})"))
            return
        key = (self._affinity_key(orig.prompt)
               if self.policy == "affinity" else None)
        target = self._pick(key, exclude=exclude)
        if target is None:
            self._finalize_failure(rid, ReplicaUnavailable(
                f"request {rid}: no surviving replica to fail over to "
                f"({why}) — shed"))
            return
        if key is not None:
            self._affinity[key] = target
        clone = Request(rid=rid, prompt=np.array(orig.prompt),
                        max_new_tokens=orig.max_new_tokens,
                        arrival=orig.arrival, row=orig.row,
                        deadline=orig.deadline, priority=orig.priority)
        # progress made so far folds into the original's record before
        # the clone takes over (the clone's replay re-verifies it)
        if cur is not orig:
            orig.tokens = list(delivered)
            orig.emit_times = list(self._emit_t.get(rid, []))
        self.replicas[target].engine.submit(clone)
        self._cur_req[rid] = clone
        self._where[rid] = target
        self._tries[rid] = self._tries.get(rid, 0) + 1
        self._seen[(rid, target)] = 0
        self.failovers += 1
        self.flight.event("failover", rid, self._now, replica=target,
                          source=exclude, delivered=len(delivered),
                          why=why)

    def _close(self, rid: int) -> None:
        self._open.discard(rid)

    def _finalize_success(self, rid: int, k: int) -> None:
        req, orig = self._cur_req[rid], self._requests[rid]
        if req is not orig:
            # graft the clone's stream back onto the caller's Request:
            # delivered tokens with their ORIGINAL first-delivery stamps
            orig.tokens = list(self._delivered.get(rid, []))
            orig.emit_times = list(self._emit_t.get(rid, []))
            orig.finish_time = req.finish_time
        self.results[rid] = self.replicas[k].engine.results[rid]
        self._close(rid)

    def _finalize_failure(self, rid: int, err: ServingError) -> None:
        req, orig = self._cur_req.get(rid), self._requests.get(rid)
        if req is not None and orig is not None and req is not orig:
            orig.tokens = list(self._delivered.get(rid, []))
            orig.emit_times = list(self._emit_t.get(rid, []))
            orig.finish_time = req.finish_time
        self.failed[rid] = err
        self.flight.event("shed", rid, self._now,
                          error=type(err).__name__)
        self._close(rid)

    # -- the fleet step ------------------------------------------------

    def step(self, now: float | None = None) -> list:
        """Step every non-quarantined replica once and merge their
        events (replica order — a 1-replica fleet returns the engine's
        event list verbatim). Absorbs replica failures into the health
        machine; only ``FleetInvariantViolation`` (a torn stream /
        corrupt router state) propagates."""
        if now is None:
            now = self.clock() if self.clock is not None else math.inf
        self._now = now
        self.rounds += 1
        if self.on_step is not None:
            self.on_step(self)
        events = []
        for rep in self.replicas:
            if rep.state == "quarantined":
                continue
            k, eng = rep.idx, rep.engine
            try:
                ev = eng.step(now)
            except FleetInvariantViolation:
                raise  # router-level corruption: never absorbed
            except ServingError as e:
                self._strike(k, e)
                continue
            except Exception as e:  # noqa: BLE001 — replica crash
                self._quarantine(k, ReplicaUnavailable(
                    f"crashed mid-step: {type(e).__name__}: {e}",
                    replica=k))
                continue
            # dispatch watchdog: a healthy engine emits or finishes
            # every running slot every step — running slots with zero
            # events IS the hang signal
            if eng.running and not ev:
                rep.idle += 1
                if rep.idle >= self.watchdog_steps:
                    self._quarantine(k, ReplicaUnavailable(
                        f"hung: {len(eng.running)} running slot(s) "
                        f"produced no events for {rep.idle} consecutive "
                        f"steps — dispatch watchdog tripped", replica=k))
                    continue
            else:
                rep.idle = 0
            events.extend(ev)
            self._collect(rep)
            if rep.state == "quarantined":
                continue
            try:
                eng.self_check()
            except FleetInvariantViolation:
                raise
            except ServingError as e:
                self._strike(k, e)
                continue
            rep.clean += 1
            if rep.state == "degraded" and rep.clean >= self.heal_after:
                rep.state, rep.strikes = "healthy", 0
        return events

    def _collect(self, rep: _Replica) -> None:
        """Harvest the replica's terminal outcomes: completions close
        out; retriable containment failures (``SlotPoisoned``) strike
        the replica and fail the request over; policy sheds
        (``DeadlineExceeded``) and non-retriable request errors are
        FINAL — the shed is the admission control working, not a
        replica fault."""
        k, eng = rep.idx, rep.engine
        for rid, err in list(eng.failed.items()):
            if rid not in self._open or self._where.get(rid) != k:
                continue
            if isinstance(err, DeadlineExceeded) or not err.retriable:
                self._finalize_failure(rid, err)
                continue
            eng.failed.pop(rid)  # absorbed: the router owns the retry
            self._strike(k, err)
            self._redispatch(rid, exclude=k,
                             why=f"{type(err).__name__} on replica {k}")
        for rid in list(eng.results):
            if rid in self._open and self._where.get(rid) == k:
                self._finalize_success(rid, k)

    def cancel(self, rid: int, now: float | None = None) -> bool:
        """Client cancel: delegate to the assigned replica; the partial
        stream (delivered tokens only) lands in ``cancelled[rid]``."""
        if rid not in self._open:
            return False
        k = self._where[rid]
        try:
            self.replicas[k].engine.cancel(rid, now)
        except Exception:  # noqa: BLE001 — cancel on a sick replica
            pass
        self.cancelled[rid] = np.asarray(
            self._delivered.get(rid, []), np.int32)
        self._close(rid)
        return True

    # -- drive / invariants -------------------------------------------

    def _shed_all(self) -> None:
        for rid in sorted(self._open):
            self._finalize_failure(rid, ReplicaUnavailable(
                f"request {rid}: no healthy replica in the fleet — shed"))

    def run(self, time_fn=None) -> dict[int, np.ndarray]:
        """Drive steps until every submitted request reaches a terminal
        state; returns ``results``. Same clock semantics as
        ``ServingEngine.run``. Terminates under TOTAL fleet loss (every
        replica quarantined): remaining requests shed with the retriable
        ``ReplicaUnavailable`` — capacity loss degrades to rejections,
        never a hang."""
        while self._open:
            alive = [rep for rep in self.replicas
                     if rep.state != "quarantined"]
            if not alive:
                self._shed_all()
                break
            if time_fn is not None:
                now = time_fn()
            elif self.clock is not None:
                now = self.clock()
            else:
                now = math.inf
            if not any(rep.engine.running for rep in alive):
                heads = [rep.engine.scheduler.head(now) for rep in alive]
                if not any(h is not None for h in heads):
                    nxt = [rep.engine.scheduler.next_arrival()
                           for rep in alive]
                    nxt = [x for x in nxt if x is not None]
                    if not nxt:
                        # open rids but no queued or running work on any
                        # live replica: unreachable state — shed rather
                        # than spin forever
                        self._shed_all()
                        break
                    if self.clock is not None and time_fn is None:
                        _time.sleep(min(max(min(nxt) - now, 0.0), 0.05))
                        continue
                    now = min(nxt)
            self.step(now)
        return self.results

    def kill(self, k: int, why: str = "operator kill") -> None:
        """Forcibly quarantine replica ``k`` (the benchmark's
        replica-kill-mid-trace seam): drains and fails its requests over
        exactly as a detected crash would."""
        self._quarantine(k, ReplicaUnavailable(why, replica=k))

    def check_idle(self) -> None:
        """Drained-fleet leak gate: every NON-quarantined replica's pool
        fully free (quarantined replicas were best-effort drained; their
        engines are outside the trust boundary by definition)."""
        for rep in self.replicas:
            if rep.state != "quarantined":
                rep.engine.check_idle()

    def self_check(self) -> None:
        """Fleet-level invariant sweep (the fleetsan detector surface —
        replica-LOCAL invariants are swept by each replica's own
        ``self_check`` inside ``step``):

        1. at-most-once dispatch: no rid live (queued or running) on two
           non-quarantined replicas → ``FleetInvariantViolation``
        2. routing-table integrity: every affinity entry names a replica
           index inside the fleet → ``FleetInvariantViolation``
        3. assignment coherence: every open rid's assigned replica is in
           range → ``FleetInvariantViolation``
        """
        seen: dict[int, int] = {}
        for rep in self.replicas:
            if rep.state == "quarantined":
                continue
            live = [r.rid for r in rep.engine.running.values()]
            live += [r.rid for _, _, r in rep.engine.scheduler._queue]
            for rid in live:
                if rid in seen and seen[rid] != rep.idx:
                    raise FleetInvariantViolation(
                        f"rid {rid} is live on two replicas "
                        f"({seen[rid]} and {rep.idx}) — duplicate "
                        f"dispatch; the at-most-once emit contract is "
                        f"about to tear")
                seen[rid] = rep.idx
        n = len(self.replicas)
        for key, target in self._affinity.items():
            if not (isinstance(target, (int, np.integer))
                    and 0 <= target < n):
                raise FleetInvariantViolation(
                    f"affinity entry {key.hex()[:8]} names replica "
                    f"{target!r}, outside the {n}-replica fleet — "
                    f"routing table corrupt")
        for rid in self._open:
            k = self._where.get(rid)
            if k is None or not 0 <= k < n:
                raise FleetInvariantViolation(
                    f"open rid {rid} assigned to replica {k!r}, outside "
                    f"the {n}-replica fleet")
