"""Prefix cache: a hash-chain trie over completed-prefill KV pages.

The millions-of-users scenario is N concurrent requests sharing a system
prompt: without this module the engine prefills and stores that prefix N
times (N·P prefill FLOPs, N·P tokens of kv-cache HBM). The paged pool
already separates logical rows from physical pages, so sharing is pure
allocator/admission work: publish the FULL, page-aligned KV pages of a
completed prefill into a trie keyed by the token-block hash chain, and
let later block tables reference the same physical pages.

Keying — ``(params fingerprint, page_block, token-block hash chain)``:
node i's key is ``H(h_{i-1} ‖ tokens[i·blk : (i+1)·blk])`` with
``h_{-1} = H(fingerprint ‖ blk)``, so a chain hash names the ENTIRE
token prefix up to its block boundary (two prompts share node i iff
their first (i+1)·blk tokens are identical), and caches built against
different weights or page sizes can never collide.

Copy-on-write contract (enforced downstream by
models/decode.validate_block_tables's read-only set): only FULLY-filled
page-aligned prompt blocks are published — the paged decode kernel
writes block ``pos // blk``, which for any request is at or past block
``plen // blk``, i.e. always a PRIVATE page. A request whose prompt
diverges mid-block shares the full blocks before the divergence and
owns the divergent partial block privately.

Boundary logits: a publisher whose prompt ends exactly at a block
boundary also stores its last-token logits on the final node — a later
request with the identical full prompt then joins with ZERO prefill
(pages acquired, logits replayed, position set). Without cached logits
a full-chain match is capped one block short so the suffix prefill
always has >= 1 token to produce the join logits from.

Spill — the no-deadlock rule: unreferenced nodes (pool refcount 0) are
evictable in LRU order, deepest-first within a chain, so admission can
always reclaim cached-but-idle pages; referenced pages are never touched
(a live block table points at them). Touch order makes a child's
``last_used`` <= its parent's, so the (last_used, -depth) sort can never
evict a parent before its children and the trie stays well-formed.

Shard-locality: page ids are shard-local (parallel/serve.engine_specs),
so the engine holds ONE PrefixCache per dp shard over that shard's
PagePool; no page, hash or refcount ever crosses the mesh and prefix
reuse adds ZERO collectives to any step program.
"""

from __future__ import annotations

import dataclasses
import hashlib

import numpy as np

from cs336_systems_tpu.serving.errors import InvariantViolation


def params_fingerprint(params) -> bytes:
    """Cheap content-sensitive digest of a param pytree: tree structure,
    every leaf's shape/dtype, and the raw bytes of the (tiny) final-norm
    leaves when present. KV pages are only valid against the weights
    that produced them; the fingerprint domain-separates hash chains so
    an engine restarted with different weights (or a future multi-model
    pool) can never alias another model's pages. Not a cryptographic
    identity of the full weights — the cache is engine-local and the
    engine's params are fixed for its lifetime."""
    import jax

    h = hashlib.blake2b(digest_size=16)
    leaves, treedef = jax.tree_util.tree_flatten(params)
    h.update(repr(treedef).encode())
    for leaf in leaves:
        h.update(str(jax.numpy.shape(leaf)).encode())
        h.update(str(getattr(leaf, "dtype", type(leaf))).encode())
    ln = params.get("ln_final") if hasattr(params, "get") else None
    if ln is not None:
        for leaf in jax.tree_util.tree_leaves(ln):
            h.update(np.asarray(jax.device_get(leaf)).tobytes())
    return h.digest()


@dataclasses.dataclass
class _Node:
    """One cached token block: the chain hash that names the token
    prefix ending at this block, the physical page holding its KV, trie
    links, and the LRU clock. ``logits``: the publisher's last-token
    logits when its prompt ended exactly at this node's boundary (the
    zero-prefill full-hit join), else None."""

    h: bytes
    parent: bytes | None
    depth: int
    page: int
    last_used: int
    logits: np.ndarray | None = None


class PrefixCache:
    """Trie of shared KV pages over one shard-local PagePool."""

    def __init__(self, pool, page_block: int, fingerprint: bytes):
        self.pool = pool
        self.block = int(page_block)
        self._root = hashlib.blake2b(
            fingerprint + self.block.to_bytes(4, "little"),
            digest_size=16).digest()
        self._nodes: dict[bytes, _Node] = {}
        self._clock = 0
        # block-level telemetry for the benchmark columns
        self.hit_blocks_total = 0
        self.lookup_blocks_total = 0
        self.spilled_pages_total = 0

    def __len__(self) -> int:
        return len(self._nodes)

    def chain_hashes(self, prompt) -> list[bytes]:
        """Chain hashes of the prompt's FULL blocks (``len // block`` of
        them) — the publishable/hittable spine of the prompt."""
        toks = np.ascontiguousarray(np.asarray(prompt, np.int32))
        n = toks.size // self.block
        out, h = [], self._root
        for i in range(n):
            blk = toks[i * self.block:(i + 1) * self.block]
            h = hashlib.blake2b(h + blk.tobytes(), digest_size=16).digest()
            out.append(h)
        return out

    def lookup(self, prompt):
        """Longest cached page-aligned prefix of ``prompt``.

        Returns ``(hit_blocks, pages, logits)``: the number of matched
        full blocks, their page ids in block order, and — ONLY when the
        match covers the entire prompt exactly at a block boundary AND
        the final node cached boundary logits — that logits row (the
        zero-prefill join). Otherwise the hit is capped so at least one
        prompt token remains for the suffix prefill. Touches the hit
        path's LRU clocks. Does NOT acquire: the caller bumps refcounts
        through the pool once it commits to the admission."""
        hashes = self.chain_hashes(prompt)
        plen = int(np.asarray(prompt).size)
        self.lookup_blocks_total += len(hashes)
        m, path = 0, []
        for h in hashes:
            node = self._nodes.get(h)
            if node is None:
                break
            path.append(node)
            m += 1
        self._clock += 1
        for node in path:
            node.last_used = self._clock
        logits = None
        if m and m * self.block == plen:
            if path[-1].logits is not None:
                logits = path[-1].logits
            else:
                m -= 1  # keep >= 1 suffix token for the join logits
                path.pop()
        self.hit_blocks_total += m
        return m, [n.page for n in path], logits

    def publish(self, prompt, owner, pages_by_block: dict,
                logits=None) -> int:
        """Publish a completed prefill's full prompt blocks: for each
        uncached chain node, PROMOTE the owner's private page for that
        block into a shared page (refcount 1 — the publisher's own block
        table keeps its reference). ``pages_by_block`` maps block index
        -> the owner's private page id; blocks already cached (hit at
        admission, or raced by an earlier publish) are skipped — the
        owner's duplicate page, if any, simply stays private. ``logits``:
        the request's last-token logits, stored on the final node when
        the prompt ends exactly at a block boundary. Returns the number
        of newly published pages."""
        hashes = self.chain_hashes(prompt)
        plen = int(np.asarray(prompt).size)
        new = 0
        self._clock += 1
        parent = None
        for i, h in enumerate(hashes):
            node = self._nodes.get(h)
            if node is None:
                if i not in pages_by_block:
                    break  # owner holds no private page for this block
                page = pages_by_block[i]
                self.pool.promote(owner, [page], h)
                node = _Node(h, parent, i, page, self._clock)
                self._nodes[h] = node
                new += 1
            else:
                node.last_used = self._clock
            parent = h
        if (hashes and logits is not None
                and len(hashes) * self.block == plen):
            tail = self._nodes.get(hashes[-1])
            if tail is not None and tail.logits is None:
                tail.logits = np.array(logits, np.float32)
        return new

    def spillable_pages(self) -> int:
        """Pages reclaimable right now (refcount-0 nodes) — what
        admission adds to ``pool.available`` when deciding whether a
        request CAN fit (the no-deadlock bound)."""
        return sum(1 for n in self._nodes.values()
                   if self.pool.refcount(n.page) == 0)

    def spill(self, n_pages: int) -> int:
        """Evict unreferenced nodes until ``n_pages`` pages returned to
        the free list (or no candidates remain); returns the count.
        Order: least-recently-used first, deepest-first within equal
        clocks — a parent is never evicted before its children (see
        module docstring), so the trie stays well-formed."""
        if n_pages <= 0:
            return 0
        cand = [n for n in self._nodes.values()
                if self.pool.refcount(n.page) == 0]
        cand.sort(key=lambda n: (n.last_used, -n.depth))
        freed = 0
        for node in cand:
            if freed >= n_pages:
                break
            self.pool.drop_shared(node.h)
            del self._nodes[node.h]
            freed += 1
        self.spilled_pages_total += freed
        return freed

    def drop_unreferenced(self) -> int:
        """Spill EVERY refcount-0 node (the drained-engine path before
        ``PagePool.check_all_free``); returns pages freed."""
        return self.spill(len(self._nodes))

    def shared_pages(self) -> int:
        """Number of pages currently held by the cache."""
        return len(self._nodes)

    def self_check(self, shard: int | None = None) -> None:
        """Trie ↔ pool consistency sweep (ISSUE 10, part of the engine's
        consolidated ``self_check``): every trie node must name a live
        shared allocation holding exactly its page, every pool shared
        tag must be a trie node (no orphan shared allocations), and the
        parent links must form a well-rooted chain (parent present,
        depth exactly one less). Raises ``InvariantViolation`` — a break
        here means spill/publish state diverged from the allocator and
        neither side can be trusted."""
        for h, node in self._nodes.items():
            pages = self.pool.shared_alloc(h)
            if pages is None:
                raise InvariantViolation(
                    f"trie node at depth {node.depth} has no shared "
                    f"allocation in the pool", shard=shard)
            if pages != [node.page]:
                raise InvariantViolation(
                    f"trie node at depth {node.depth} maps to page "
                    f"{node.page} but the pool holds {pages} under its "
                    f"tag", shard=shard)
            if node.parent is None:
                if node.depth != 0:
                    raise InvariantViolation(
                        f"root-linked trie node has depth {node.depth}",
                        shard=shard)
            else:
                parent = self._nodes.get(node.parent)
                if parent is None:
                    raise InvariantViolation(
                        f"trie node at depth {node.depth} has a dangling "
                        f"parent hash", shard=shard)
                if parent.depth != node.depth - 1:
                    raise InvariantViolation(
                        f"trie parent depth {parent.depth} != "
                        f"{node.depth} - 1", shard=shard)
        orphans = self.pool.shared_tags() - set(self._nodes)
        if orphans:
            raise InvariantViolation(
                f"{len(orphans)} shared allocation(s) in the pool are "
                f"not trie nodes (orphaned shared pages)", shard=shard)
