"""fleetsan — the fleet-router chaos harness (ISSUE 14).

    python -m cs336_systems_tpu.serving.fleet_chaos --list
    python -m cs336_systems_tpu.serving.fleet_chaos              # all + clean
    python -m cs336_systems_tpu.serving.fleet_chaos --fault replica-crash --json
    python -m cs336_systems_tpu.serving.fleet_chaos --mesh dp2 --seed 3

The gradsan/servesan pattern one level up: servesan proves a SINGLE
engine's invariant sweep catches allocator/table corruption; fleetsan
proves the ROUTER's failure semantics — health machine, watchdog,
failover, emit cursor, routing-table sweep — against seeded fleet-level
faults. Each fault perturbs a REAL 3-replica fleet mid-trace: 10
requests in two shared-prefix sessions (affinity pins each session to
one replica, so the refcounted shared-page regime is live on two
replicas at once) join, stream and evict over a virtual clock; after
``PRE_STEPS`` clean steps the named seam is corrupted and the harness
keeps stepping, running ``FleetRouter.self_check`` after every step.

The verdict is STRICTER than servesan's: the expected typed error must
surface (raised for router-state corruption, ABSORBED into
``router.faults``/``router.failed`` for replica failures — absorption IS
the contract: a replica dying must not throw at the client), every
surviving or failed-over stream must be BIT-EXACT to the single-replica
row-keyed oracle (the per-request key chain makes a replayed stream a
pure function of (params, base key, row, prompt)), no request may be
lost, duplicated or torn, and each fault's structural postcondition
must hold (the crashed replica quarantined, the shed storm ending with
every request retriably failed — degradation, never a hang). The clean
run must drain with zero findings, zero failovers and a fully-free pool
on every replica — the false-positive gate.

Everything is seeded and host-side: the jit step programs are never
touched (step-program invariance is pinned by the serve_engine lint
families), so verdicts are identical on single-device and dp2-per-
replica meshes.

Exit status: 0 every requested fault detected with the expected typed
error and bit-exact survivors (and the clean run clean), 1 a fault was
MISSED / misclassified / tore a stream, 2 the trace failed to build.
Same gate semantics as gradsan — scripts/run_tests_and_package.sh wires
it into CI as-is.
"""

from __future__ import annotations

import os

# Force the hermetic CPU backend BEFORE jax initializes (the site TPU
# plugin must not grab the tunneled chip for a host-side control-plane
# check) — same pattern as chaos.py; CS336_TPU_CHAOS=1 opts out.
if not os.environ.get("CS336_TPU_CHAOS"):
    os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()

import argparse
import json
import re
import sys
import traceback

import numpy as np

from cs336_systems_tpu.serving.errors import (
    FleetInvariantViolation,
    ReplicaUnavailable,
    ServingError,
    SlotPoisoned,
)

N_REPLICAS = 3   # the standard fleet
SLOTS = 4        # per replica (divisible by the dp2 mesh)
N_PAGES = 16     # per replica per shard — ample for 4 slots x 3 blocks
MAX_BLOCKS = 3   # 12-token prompt + up to 7 new tokens at blk=8
PRE_STEPS = 3    # clean fleet steps before the injection
MAX_STEPS = 96   # post-injection bound (failover replays from the prompt)
LATE_RID = 100   # the stale-affinity fault's late same-session request


class ChaosBuildError(RuntimeError):
    """The fleet trace could not be built/driven far enough to inject —
    exit 2 territory, distinct from a missed detection."""


# -- the standard trace -------------------------------------------------


def _blk() -> int:
    from cs336_systems_tpu.analysis.registry import serve_chaos_geometry

    return serve_chaos_geometry()[3]


def _params(seed: int):
    import jax

    from cs336_systems_tpu.analysis.registry import _tiny_cfg
    from cs336_systems_tpu.models.transformer import init_transformer_lm

    cfg = _tiny_cfg()
    return init_transformer_lm(jax.random.PRNGKey(seed), cfg), cfg


def _build_fleet(mesh_name: str = "none", seed: int = 0):
    """The standard chaos fleet: 3 replicas, SAME base key (the failover
    bit-exactness precondition), prefix caches on, affinity policy,
    virtual clock (the harness passes explicit ``now``)."""
    import jax

    from cs336_systems_tpu.parallel.mesh import make_mesh
    from cs336_systems_tpu.serving.engine import ServingEngine
    from cs336_systems_tpu.serving.router import FleetRouter

    params, cfg = _params(seed)
    mesh = dp = None
    if mesh_name == "dp2":
        mesh, dp = make_mesh({"dp": 2}), "dp"
    elif mesh_name != "none":
        raise ChaosBuildError(f"unknown mesh {mesh_name!r} (none | dp2)")
    engines = [
        ServingEngine(params, cfg, key=jax.random.PRNGKey(seed + 1),
                      slots=SLOTS, n_pages=N_PAGES, max_blocks=MAX_BLOCKS,
                      page_block=_blk(), mesh=mesh, dp_axis=dp)
        for _ in range(N_REPLICAS)]
    return FleetRouter(engines, policy="affinity", seed=seed)


def _prefixes(seed: int):
    """Two full-block session prefixes — affinity pins each session to
    one replica, so a fault on the busiest replica always has a warm
    survivor session to interleave with."""
    rng = np.random.default_rng(seed)
    blk, vocab = _blk(), 64  # registry _tiny_cfg vocab
    return rng.integers(0, vocab, size=blk), rng.integers(0, vocab, size=blk)


def _build_requests(seed: int):
    """10 requests: session A (even rids) and session B (odd rids), each
    a shared full prefix block + a distinct 4-token tail, ``max_new =
    4 + (i % 4)`` so evictions are staggered — early finishers free
    slots mid-trace while the longest-lived requests still stream."""
    from cs336_systems_tpu.serving.scheduler import Request

    pref_a, pref_b = _prefixes(seed)
    rng = np.random.default_rng(seed + 1)
    reqs = []
    for i in range(10):
        tail = rng.integers(0, 64, size=4)
        prefix = pref_a if i % 2 == 0 else pref_b
        prompt = np.concatenate([prefix, tail]).astype(np.int32)
        reqs.append(Request(i, prompt, max_new_tokens=4 + (i % 4),
                            arrival=0.0))
    return reqs


def _late_request(seed: int):
    """The stale-affinity fault's late arrival: session A's prefix with
    a fresh tail, submitted AFTER the pinned replica was quarantined."""
    from cs336_systems_tpu.serving.scheduler import Request

    pref_a, _ = _prefixes(seed)
    tail = np.random.default_rng(seed + 2).integers(0, 64, size=4)
    prompt = np.concatenate([pref_a, tail]).astype(np.int32)
    return Request(LATE_RID, prompt, max_new_tokens=4, arrival=0.0)


_ORACLE_CACHE: dict = {}


def _oracle_results(seed: int, include_late: bool) -> dict:
    """The single-replica row-keyed oracle: ONE clean engine with ample
    capacity over clones of the same requests. A stream is a pure
    function of (params, base key, row, prompt), so every fleet stream —
    original, failed-over, or late — must match this bitwise."""
    key = (seed, include_late)
    if key not in _ORACLE_CACHE:
        import jax

        from cs336_systems_tpu.serving.engine import ServingEngine

        params, cfg = _params(seed)
        eng = ServingEngine(params, cfg, key=jax.random.PRNGKey(seed + 1),
                            slots=8, n_pages=64, max_blocks=MAX_BLOCKS,
                            page_block=_blk())
        reqs = _build_requests(seed)
        if include_late:
            reqs.append(_late_request(seed))
        for r in reqs:
            eng.submit(r)
        tick = iter(np.arange(0.0, 1e4, 1.0))
        eng.run(time_fn=lambda: float(next(tick)))
        if set(eng.results) != {r.rid for r in reqs}:
            raise ChaosBuildError("oracle did not complete every request")
        _ORACLE_CACHE[key] = {
            rid: np.asarray(arr) for rid, arr in eng.results.items()}
    return _ORACLE_CACHE[key]


def _busiest(router):
    """The non-quarantined replica with the most live work — the fault
    victim (ties: lowest index, deterministic)."""
    cand = [rep for rep in router.replicas if rep.state != "quarantined"
            and (rep.engine.running or len(rep.engine.scheduler))]
    if not cand:
        raise ChaosBuildError("no busy replica to injure")
    return max(cand, key=lambda rep: (
        len(rep.engine.running) + len(rep.engine.scheduler), -rep.idx))


# -- the fault injectors (each takes (router, seed)) --------------------


def _inject_replica_crash(router, seed):
    """The busiest replica's step raises a non-ServingError mid-stream —
    a segfault/device-loss stand-in. The router must quarantine it,
    drain, and fail its in-flight streams over to survivors."""
    rep = _busiest(router)

    def _boom(now=None):
        raise RuntimeError("injected segfault: replica device lost")

    rep.engine.step = _boom


def _inject_replica_hang(router, seed):
    """The busiest replica keeps 'running' slots but produces zero
    events — a wedged dispatch. Silence past ``watchdog_steps`` must
    trip the dispatch watchdog and quarantine it."""
    rep = _busiest(router)
    if not rep.engine.running:
        raise ChaosBuildError("hang victim has no running slots")
    rep.engine.step = lambda now=None: []


def _inject_poisoned_replica(router, seed):
    """Every step, the busiest replica's carried sampling state goes
    non-finite (a sick host/HBM stand-in). The engine's own containment
    evicts with retriable SlotPoisoned each time; REPEATED poison must
    accumulate strikes until the health machine quarantines the replica,
    and every contained request must complete elsewhere bit-exact."""
    rep = _busiest(router)
    eng, orig = rep.engine, rep.engine.step

    def _sick(now=None):
        ev = orig(now)
        for slot in list(eng.running):
            eng.logits[slot, : min(8, eng.logits.shape[1])] = np.nan
        return ev

    eng.step = _sick


def _inject_routing_corruption(router, seed):
    """An affinity entry is overwritten to name a replica outside the
    fleet — the routing-table sweep must raise, not dispatch into the
    void."""
    if not router._affinity:
        raise ChaosBuildError("no affinity entries pinned yet")
    router._affinity[sorted(router._affinity)[0]] = 99


def _inject_duplicate_dispatch(router, seed):
    """A live rid is submitted straight into a SECOND replica's engine,
    bypassing the router (a buggy front-end retry). Token-level checks
    cannot see it — the duplicate's key chain replays the identical
    stream — so the at-most-once liveness sweep must catch it
    structurally."""
    from cs336_systems_tpu.serving.scheduler import Request

    rep = _busiest(router)
    if not rep.engine.running:
        raise ChaosBuildError("no running request to duplicate")
    req = min(rep.engine.running.values(), key=lambda r: r.rid)
    other = next(r for r in router.replicas
                 if r.idx != rep.idx and r.state != "quarantined")
    other.engine.submit(Request(req.rid, np.array(req.prompt), 2,
                                arrival=0.0))


def _inject_stale_affinity(router, seed):
    """Session A's pinned replica is killed, and the affinity entry is
    restored to point at the corpse — the completed-session case: drain
    only re-points entries of LIVE requests, so an entry learned before
    the quarantine can legitimately outlive its target. A late
    same-session arrival must be detected as stale at dispatch, logged
    retriable, and re-routed to a survivor — never an invariant trip."""
    late = _late_request(seed)
    akey = router._affinity_key(late.prompt)
    k0 = router._affinity.get(akey)
    if k0 is None:
        raise ChaosBuildError("late-session prefix not pinned yet")
    router.kill(int(k0), why="injected spill")
    router._affinity[akey] = int(k0)
    router.submit(late)


def _inject_shed_storm(router, seed):
    """Every replica crashes at once — zero survivors. The fleet must
    DEGRADE: every unfinished request fails with the retriable
    ReplicaUnavailable, run() terminates — never a cliff-hang."""

    def _boom(now=None):
        raise RuntimeError("injected fleet-wide outage")

    for rep in router.replicas:
        rep.engine.step = _boom


# -- per-fault structural postconditions --------------------------------


def _post_failover_complete(router, rids):
    """>=1 quarantine, and EVERY request still completed (on survivors)."""
    return (router.quarantines >= 1 and router.failovers >= 1
            and set(router.results) == set(rids))


def _post_shed_storm(router, rids):
    return (all(rep.state == "quarantined" for rep in router.replicas)
            and set(router.results) | set(router.failed) == set(rids)
            and all(e.retriable for e in router.failed.values()))


def _post_late_completed(router, rids):
    return (router.quarantines == 1
            and set(router.results) == set(rids) | {LATE_RID})


# fault -> (injector, expected error classes, message pattern,
#           needs-late-oracle, structural postcondition)
FAULTS = {
    "replica-crash": (
        _inject_replica_crash, (ReplicaUnavailable,), r"crashed mid-step",
        False, _post_failover_complete),
    "replica-hang": (
        _inject_replica_hang, (ReplicaUnavailable,),
        r"watchdog tripped", False, _post_failover_complete),
    "poisoned-replica": (
        _inject_poisoned_replica, (SlotPoisoned, ReplicaUnavailable),
        r"non-finite|strikes", False, _post_failover_complete),
    "routing-corruption": (
        _inject_routing_corruption, (FleetInvariantViolation,),
        r"routing table corrupt", False, None),
    "duplicate-dispatch": (
        _inject_duplicate_dispatch, (FleetInvariantViolation,),
        r"live on two replicas", False, None),
    "stale-affinity": (
        _inject_stale_affinity, (ReplicaUnavailable,),
        r"stale affinity", True, _post_late_completed),
    "shed-storm": (
        _inject_shed_storm, (ReplicaUnavailable,),
        r"no surviving replica|no healthy replica", False,
        _post_shed_storm),
}


def fault_names():
    return list(FAULTS)


# -- the drive loop -----------------------------------------------------


def _drive(router, inject=None, seed: int = 0):
    """Drive the standard fleet trace: PRE_STEPS clean (router
    self_check MUST stay silent — a raise here is a build error), inject,
    then step + self_check until a ServingError propagates or every
    request reaches a terminal state. Returns (raised-or-None, steps)."""
    t = 0.0
    for _ in range(PRE_STEPS):
        router.step(t)
        t += 1.0
        router.self_check()  # pre-injection: any raise = build error
    if inject is not None:
        inject(router, seed)
    steps = 0
    try:
        router.self_check()
        for _ in range(MAX_STEPS):
            if not router._open:
                break
            router.step(t)
            t += 1.0
            steps += 1
            router.self_check()
        else:
            raise ChaosBuildError(
                f"fleet did not reach terminal state within {MAX_STEPS} "
                f"steps — a hang is exactly what the router must prevent")
        router.check_idle()
    except ServingError as e:
        return e, steps
    return None, steps


def _bit_exact(router, oracle) -> bool:
    """Every completed stream — engine record AND the client-facing
    delivered cursor — must equal the oracle's tokens bitwise."""
    for rid, arr in router.results.items():
        if rid not in oracle:
            return False
        if not np.array_equal(np.asarray(arr), oracle[rid]):
            return False
        if list(np.asarray(arr)) != router._delivered.get(rid, []):
            return False
    return True


def _err_dict(err):
    return None if err is None else {
        "type": type(err).__name__,
        "retriable": err.retriable,
        "shard": err.shard,
        "message": str(err),
    }


def run_fault(name: str, mesh_name: str = "none", seed: int = 0) -> dict:
    """Inject fault ``name`` into a fresh standard fleet trace and
    report the verdict. ``detected`` = the expected typed error surfaced
    (raised, absorbed into ``router.faults``, or a terminal entry in
    ``router.failed``); ``ok`` additionally requires bit-exact surviving
    streams, full request accounting, and the fault's structural
    postcondition."""
    if name not in FAULTS:
        raise ChaosBuildError(f"unknown fault {name!r} (see --list)")
    inject, expected, pattern, late, post = FAULTS[name]
    router = _build_fleet(mesh_name, seed)
    reqs = _build_requests(seed)
    for r in reqs:
        router.submit(r)
    oracle = _oracle_results(seed, include_late=late)
    raised, steps = _drive(router, inject, seed)
    # router-state corruption (FleetInvariantViolation) must PROPAGATE —
    # the fleet is condemned, drain/rebuild is the caller's move, so the
    # raise IS the verdict and no terminal accounting is possible;
    # replica failures must be ABSORBED (faults/failed) and fully drain
    aborts = any(issubclass(c, FleetInvariantViolation) for c in expected)
    candidates = ([raised] if raised is not None else [])
    if not aborts:
        candidates += router.faults + list(router.failed.values())
    matches = [e for e in candidates
               if isinstance(e, expected) and re.search(pattern, str(e))]
    detected = bool(matches)
    rids = [r.rid for r in reqs]
    accounted = aborts or (
        not router._open
        and set(router.results) | set(router.failed)
        | set(router.cancelled)
        >= set(rids))
    exact = _bit_exact(router, oracle)
    structural = post is None or post(router, rids)
    ok = detected and exact and accounted and structural
    return {
        "fault": name,
        "mesh": mesh_name,
        "seed": seed,
        "expected": [c.__name__ for c in expected],
        "pattern": pattern,
        "detected": detected,
        "bit_exact": exact,
        "accounted": accounted,
        "structural": structural,
        "ok": bool(ok),
        "steps_after_injection": steps,
        "failovers": router.failovers,
        "quarantines": router.quarantines,
        "states": router.states(),
        "completed": len(router.results),
        "failed": len(router.failed),
        "error": _err_dict(matches[0] if matches
                           else (raised if raised is not None
                                 else (router.faults[0] if router.faults
                                       else None))),
    }


def run_clean(mesh_name: str = "none", seed: int = 0) -> dict:
    """The false-positive gate: the un-injected fleet must drain with
    zero findings, zero failovers/quarantines, every request completed
    bit-exact, and every replica's pool fully free."""
    router = _build_fleet(mesh_name, seed)
    reqs = _build_requests(seed)
    for r in reqs:
        router.submit(r)
    oracle = _oracle_results(seed, include_late=False)
    raised, steps = _drive(router, None, seed)
    complete = set(router.results) == {r.rid for r in reqs}
    exact = _bit_exact(router, oracle)
    quiet = (raised is None and not router.faults
             and router.failovers == 0 and router.quarantines == 0)
    return {
        "fault": "clean",
        "mesh": mesh_name,
        "seed": seed,
        "detected": not quiet,
        "bit_exact": exact,
        "accounted": complete,
        "structural": True,
        "ok": bool(quiet and complete and exact),
        "steps_after_injection": steps,
        "failovers": router.failovers,
        "quarantines": router.quarantines,
        "states": router.states(),
        "completed": len(router.results),
        "failed": len(router.failed),
        "error": _err_dict(raised if raised is not None
                           else (router.faults[0] if router.faults
                                 else None)),
    }


# -- CLI ----------------------------------------------------------------


def _fmt_report(rows: list[dict]) -> str:
    lines = [
        f"fleetsan: chaos harness over the standard {N_REPLICAS}-replica "
        f"two-session trace (mesh={rows[0]['mesh']}, "
        f"seed={rows[0]['seed']})",
        f"  {'fault':<20} {'expected':<36} {'caught':<24} verdict",
    ]
    for r in rows:
        caught = "-" if r["error"] is None else r["error"]["type"]
        if r["fault"] == "clean":
            verdict = ("clean" if r["ok"]
                       else "FALSE POSITIVE" if r["detected"]
                       else "NOT BIT-EXACT" if not r["bit_exact"]
                       else "INCOMPLETE DRAIN")
            lines.append(f"  {'clean':<20} {'(zero findings)':<36} "
                         f"{caught:<24} {verdict}")
            continue
        verdict = ("detected" if r["ok"]
                   else "MISSED" if not r["detected"]
                   else "NOT BIT-EXACT" if not r["bit_exact"]
                   else "LOST REQUESTS" if not r["accounted"]
                   else "BAD POSTCONDITION")
        lines.append(f"  {r['fault']:<20} {'|'.join(r['expected']):<36} "
                     f"{caught:<24} {verdict}")
    n_bad = sum(1 for r in rows if not r["ok"])
    lines.append("  all detected, survivors bit-exact, clean run clean"
                 if n_bad == 0 else f"  {n_bad} verdict(s) FAILED")
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="fleetsan",
        description="fleet-router chaos harness: inject fleet-level "
                    "faults and prove the router surfaces the expected "
                    "typed error with bit-exact surviving streams")
    ap.add_argument("--fault", help="single fault to inject (see --list); "
                                    "default: every fault + the clean run")
    ap.add_argument("--mesh", default="none", choices=("none", "dp2"),
                    help="per-replica mesh (default none = single device "
                         "per replica)")
    ap.add_argument("--seed", type=int, default=0,
                    help="trace seed (params, prompts, PRNG chains)")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable report on stdout")
    ap.add_argument("--list", action="store_true",
                    help="list fault classes, then exit")
    args = ap.parse_args(argv)

    if args.list:
        if args.json:
            print(json.dumps({"faults": fault_names()}))
        else:
            print("fault classes (--fault):")
            for name in fault_names():
                print(f"  {name}")
        return 0

    try:
        if args.fault:
            rows = [run_fault(args.fault, args.mesh, args.seed)]
        else:
            rows = [run_fault(name, args.mesh, args.seed)
                    for name in fault_names()]
            rows.append(run_clean(args.mesh, args.seed))
    except Exception as e:  # noqa: BLE001 — exit 2 is the build-error gate
        if args.json:
            print(json.dumps({"schema": "fleetsan/v1",
                              "error": f"{type(e).__name__}: {e}"}))
        else:
            traceback.print_exc()
            print(f"fleetsan: BUILD/RUN ERROR: {type(e).__name__}: {e}")
        return 2

    print(json.dumps({"schema": "fleetsan/v1", "rows": rows})
          if args.json else _fmt_report(rows))
    return 0 if all(r["ok"] for r in rows) else 1


if __name__ == "__main__":
    sys.exit(main())
