"""Page-pool allocator: a host-side free list over the physical page ids
of one paged KV pool (models/decode.init_paged_kv_cache).

The pool array has ``n_pages + 1`` pages; index ``n_pages`` is the
kernel's reserved write scratch and is PERMANENTLY excluded here — it is
not in the free list at construction, ``alloc`` can never hand it out,
and ``free`` rejects it — so a block table built from this allocator's
ids satisfies models/decode.validate_block_tables by construction.

Two ownership regimes (ISSUE 9 added the second):

- PRIVATE pages: ``alloc(n, owner)`` binds n pages to one owner,
  ``free(owner)`` returns ALL of them at once (a finished request's
  pages come back in one move — the eviction contract).
- SHARED pages: immutable prefix-cache pages referenced by any number of
  block tables. ``alloc_shared``/``promote`` create a shared allocation
  under a cache-entry ``tag`` with an explicit REFCOUNT per page;
  ``acquire(pages, owner)`` bumps the refcounts when a block table takes
  a reference, ``release(owner)`` drops them all at eviction, and
  ``drop_shared(tag)`` returns the pages to the free list — legal ONLY
  at refcount 0 (the prefix cache's LRU spill path). A shared page is
  never written (copy-on-write is enforced one level up by
  models/decode.validate_block_tables's read-only set), so sharing is
  pure aliasing: N tables, one physical page.

Failure surface (ISSUE 10, serving/errors.py): capacity misses raise
the retriable ``PoolExhausted`` (all-or-nothing — nothing was taken);
ownership/refcount misuse (double alloc/free/acquire, early release,
spilling a referenced page) raises the non-retriable
``RefcountViolation``; the invariant sweeps raise ``InvariantViolation``
for partition breaks and ``RefcountViolation`` for refcount drift, so a
caller can tell "retry later" from "allocator state is corrupt".

``check_conserved()`` asserts the free list + private owners + shared
allocations exactly partition the page range — each shared page counted
ONCE — and that every shared page's refcount equals the number of
acquire records (and, when the caller passes the engine's live block
tables, the number of tables that actually contain it). This is the
leak/double-count check the CI smoke and every benchmark trace run after
draining (ISSUE 8 + ISSUE 9 acceptance criteria).
"""

from __future__ import annotations

from cs336_systems_tpu.serving.errors import (
    InvariantViolation,
    PoolExhausted,
    RefcountViolation,
)


class PagePool:
    """Free-list allocator over page ids [0, n_pages) of one pool array.

    LIFO free list: freshly freed pages are reused first, which keeps the
    touched working set small and makes allocation order deterministic —
    the engine's bit-exactness across join orders does NOT depend on
    which physical ids a request gets (row-local numerics), but
    determinism keeps failures reproducible.
    """

    def __init__(self, n_pages: int):
        if n_pages < 1:
            raise ValueError(f"pool needs at least one real page, got {n_pages}")
        self.n_pages = n_pages
        self.scratch_page = n_pages  # array index of the reserved page
        self._free: list[int] = list(range(n_pages - 1, -1, -1))
        self._owned: dict[object, list[int]] = {}
        # shared (prefix-cache) state: tag -> pages, page -> refcount,
        # owner -> acquired shared pages (the block-table references)
        self._shared: dict[object, list[int]] = {}
        self._ref: dict[int, int] = {}
        self._acquired: dict[object, list[int]] = {}

    @property
    def available(self) -> int:
        return len(self._free)

    def owned_by(self, owner) -> list[int]:
        """The owner's PRIVATE pages, in block order (a copy)."""
        return list(self._owned[owner])

    def owns(self, owner) -> bool:
        """True when ``owner`` holds a private allocation."""
        return owner in self._owned

    def owners(self) -> set:
        """All owners currently holding PRIVATE allocations — the
        engine's self_check cross-references these against the running
        set (an owner that is not a live request is an orphaned
        allocation)."""
        return set(self._owned)

    def acquired_by(self, owner) -> list[int]:
        """The owner's acquired SHARED pages, in acquire order (a copy);
        empty list for an owner with no acquire record."""
        return list(self._acquired.get(owner, ()))

    def shared_page_ids(self) -> set[int]:
        """All pages currently in shared allocations — the read-only set
        models/decode.validate_block_tables enforces copy-on-write with."""
        return set(self._ref)

    def shared_alloc(self, tag) -> list[int] | None:
        """The pages of shared allocation ``tag`` (a copy), or None —
        the prefix trie's self_check cross-references its nodes here."""
        pages = self._shared.get(tag)
        return None if pages is None else list(pages)

    def shared_tags(self) -> set:
        """All live shared-allocation tags (the trie's node keys)."""
        return set(self._shared)

    def refcount(self, page: int) -> int:
        """Block-table references on a SHARED page (KeyError: not shared)."""
        return self._ref[page]

    def alloc(self, n: int, owner) -> list[int]:
        """Take ``n`` PRIVATE pages for ``owner``; returns them in block
        order. All-or-nothing: raises ``PoolExhausted`` without touching
        the free list when the pool cannot satisfy the request (the
        scheduler then leaves the request queued until an eviction frees
        enough pages)."""
        if n < 1:
            raise ValueError(f"alloc needs n >= 1, got {n}")
        if owner in self._owned:
            raise RefcountViolation(
                f"owner {owner!r} already holds pages "
                f"{self._owned[owner]} (double alloc)")
        if n > len(self._free):
            raise PoolExhausted(
                f"pool exhausted: {n} pages requested, "
                f"{len(self._free)} free of {self.n_pages}")
        pages = [self._free.pop() for _ in range(n)]
        assert self.scratch_page not in pages  # excluded at construction
        self._owned[owner] = pages
        return list(pages)

    def free(self, owner) -> int:
        """Return ALL of ``owner``'s private pages to the free list;
        returns the count. ``RefcountViolation`` on an unknown owner
        (double free)."""
        if owner not in self._owned:
            raise RefcountViolation(
                f"owner {owner!r} holds no pages (double free?)")
        pages = self._owned.pop(owner)
        self._free.extend(pages)
        return len(pages)

    # -- shared (prefix-cache) pages ----------------------------------

    def alloc_shared(self, n: int, tag) -> list[int]:
        """Take ``n`` pages from the free list as a SHARED allocation
        under ``tag``, refcount 0 (cached but unreferenced — spillable
        until the first ``acquire``)."""
        if n < 1:
            raise ValueError(f"alloc_shared needs n >= 1, got {n}")
        if tag in self._shared:
            raise RefcountViolation(
                f"shared tag {tag!r} already holds pages "
                f"{self._shared[tag]} (double alloc_shared)")
        if n > len(self._free):
            raise PoolExhausted(
                f"pool exhausted: {n} shared pages requested, "
                f"{len(self._free)} free of {self.n_pages}")
        pages = [self._free.pop() for _ in range(n)]
        self._shared[tag] = pages
        for p in pages:
            self._ref[p] = 0
        return list(pages)

    def promote(self, owner, pages: list[int], tag) -> None:
        """Convert ``pages`` of ``owner``'s PRIVATE allocation into a
        SHARED allocation under ``tag`` with refcount 1 — the publish
        path: a completed prefill's full prefix pages become immutable
        cache pages, and the publisher's block table keeps its reference
        (recorded as an acquire, released at its eviction)."""
        if tag in self._shared:
            raise RefcountViolation(f"shared tag {tag!r} already exists")
        if owner not in self._owned:
            raise RefcountViolation(
                f"owner {owner!r} holds no private pages")
        held = self._owned[owner]
        for p in pages:
            if p not in held:
                raise RefcountViolation(
                    f"page {p} is not in owner {owner!r}'s private "
                    f"allocation {held} — cannot promote")
        remaining = [p for p in held if p not in pages]
        if remaining:
            self._owned[owner] = remaining
        else:
            del self._owned[owner]
        self._shared[tag] = list(pages)
        for p in pages:
            self._ref[p] = 1
        self._acquired.setdefault(owner, []).extend(pages)

    def acquire(self, pages: list[int], owner) -> None:
        """Bump the refcount of each SHARED page for a block table that
        now references it. ``RefcountViolation`` on a page that is not
        shared (acquiring a free/private page would alias mutable state)
        and on the same owner acquiring the same page twice (its table
        would have to contain the page twice)."""
        mine = self._acquired.get(owner, [])
        for p in pages:
            if p not in self._ref:
                raise RefcountViolation(
                    f"page {p} is not a shared page (acquire of "
                    f"free/private page)")
            if p in mine:
                raise RefcountViolation(
                    f"owner {owner!r} already acquired shared page {p} "
                    f"(double acquire)")
        for p in pages:
            self._ref[p] += 1
        self._acquired.setdefault(owner, []).extend(pages)

    def release(self, owner) -> int:
        """Drop ALL of ``owner``'s shared-page references (eviction);
        returns the count. Pages stay cached at refcount 0 until the
        prefix cache spills them. ``RefcountViolation`` on an owner with
        no acquire record (early/double release)."""
        if owner not in self._acquired:
            raise RefcountViolation(
                f"owner {owner!r} holds no shared references "
                f"(double release?)")
        pages = self._acquired.pop(owner)
        for p in pages:
            if self._ref[p] <= 0:
                raise RefcountViolation(
                    f"refcount underflow on page {p}")
            self._ref[p] -= 1
        return len(pages)

    def drop_shared(self, tag) -> int:
        """Return a shared allocation's pages to the free list (the LRU
        spill). Legal ONLY when every page's refcount is 0 — spilling a
        referenced page would free memory a live block table points at."""
        if tag not in self._shared:
            raise RefcountViolation(f"unknown shared tag {tag!r}")
        pages = self._shared[tag]
        for p in pages:
            if self._ref[p]:
                raise RefcountViolation(
                    f"shared page {p} (tag {tag!r}) still has "
                    f"refcount {self._ref[p]} — cannot spill")
        del self._shared[tag]
        for p in pages:
            del self._ref[p]
        self._free.extend(pages)
        return len(pages)

    # -- invariants ---------------------------------------------------

    def check_conserved(self, block_tables=None) -> None:
        """Assert the free list, the private owners and the shared
        allocations exactly partition [0, n_pages) — each shared page
        counted ONCE — no leak, no duplication, no scratch intrusion
        (``InvariantViolation``); and that each shared page's refcount
        equals its acquire-record count (``RefcountViolation`` — the
        drifted-refcount signature). ``block_tables``: optional iterable
        of the ACTIVE requests' page-id lists — when given, each shared
        page's refcount must also equal the number of tables containing
        it (the refcount == owning-block-tables contract)."""
        seen = list(self._free)
        for pages in self._owned.values():
            seen.extend(pages)
        for pages in self._shared.values():
            seen.extend(pages)
        if len(seen) != len(set(seen)):
            raise InvariantViolation("page id duplicated across free/owned/"
                                     "shared sets")
        if set(seen) != set(range(self.n_pages)):
            missing = set(range(self.n_pages)) - set(seen)
            extra = set(seen) - set(range(self.n_pages))
            raise InvariantViolation(
                f"pool not conserved: leaked={sorted(missing)} "
                f"foreign={sorted(extra)}")
        counts: dict[int, int] = {}
        for pages in self._acquired.values():
            for p in pages:
                counts[p] = counts.get(p, 0) + 1
        if counts != {p: r for p, r in self._ref.items() if r}:
            raise RefcountViolation(
                f"shared refcounts {self._ref} disagree with acquire "
                f"records {counts}")
        if block_tables is not None:
            table_counts: dict[int, int] = {}
            for table in block_tables:
                for p in set(int(x) for x in table):
                    if p in self._ref:
                        table_counts[p] = table_counts.get(p, 0) + 1
            for p, r in self._ref.items():
                if table_counts.get(p, 0) != r:
                    raise RefcountViolation(
                        f"shared page {p}: refcount {r} but "
                        f"{table_counts.get(p, 0)} block tables contain it")

    def check_all_free(self) -> None:
        """Assert every page is back in the free list (a drained engine
        whose prefix cache has been dropped): the CI smoke's no-leak
        gate."""
        self.check_conserved()
        if self._owned:
            raise InvariantViolation(
                f"pages still owned after drain: "
                f"{ {k: v for k, v in self._owned.items()} }")
        if self._shared:
            raise InvariantViolation(
                f"shared pages still cached after drain: "
                f"{ {k: v for k, v in self._shared.items()} } — spill the "
                "prefix cache before the all-free check")
