"""Page-pool allocator: a host-side free list over the physical page ids
of one paged KV pool (models/decode.init_paged_kv_cache).

The pool array has ``n_pages + 1`` pages; index ``n_pages`` is the
kernel's reserved write scratch and is PERMANENTLY excluded here — it is
not in the free list at construction, ``alloc`` can never hand it out,
and ``free`` rejects it — so a block table built from this allocator's
ids satisfies models/decode.validate_block_tables by construction.

Owner tracking is per request id: ``alloc(n, owner)`` binds n pages to
the owner, ``free(owner)`` returns ALL of them at once (a finished
request's pages come back in one move — the eviction contract), and
``check_conserved()`` asserts the free list + owned sets partition the
full page range, which is the leak check the CI smoke and every
benchmark trace run after draining (ISSUE 8 acceptance criterion).
"""

from __future__ import annotations


class PagePool:
    """Free-list allocator over page ids [0, n_pages) of one pool array.

    LIFO free list: freshly freed pages are reused first, which keeps the
    touched working set small and makes allocation order deterministic —
    the engine's bit-exactness across join orders does NOT depend on
    which physical ids a request gets (row-local numerics), but
    determinism keeps failures reproducible.
    """

    def __init__(self, n_pages: int):
        if n_pages < 1:
            raise ValueError(f"pool needs at least one real page, got {n_pages}")
        self.n_pages = n_pages
        self.scratch_page = n_pages  # array index of the reserved page
        self._free: list[int] = list(range(n_pages - 1, -1, -1))
        self._owned: dict[object, list[int]] = {}

    @property
    def available(self) -> int:
        return len(self._free)

    def owned_by(self, owner) -> list[int]:
        """The owner's pages, in block order (a copy)."""
        return list(self._owned[owner])

    def alloc(self, n: int, owner) -> list[int]:
        """Take ``n`` pages for ``owner``; returns them in block order.
        All-or-nothing: raises without touching the free list when the
        pool cannot satisfy the request (the scheduler then leaves the
        request queued until an eviction frees enough pages)."""
        if n < 1:
            raise ValueError(f"alloc needs n >= 1, got {n}")
        if owner in self._owned:
            raise ValueError(f"owner {owner!r} already holds pages "
                             f"{self._owned[owner]} (double alloc)")
        if n > len(self._free):
            raise MemoryError(
                f"pool exhausted: {n} pages requested, "
                f"{len(self._free)} free of {self.n_pages}")
        pages = [self._free.pop() for _ in range(n)]
        assert self.scratch_page not in pages  # excluded at construction
        self._owned[owner] = pages
        return list(pages)

    def free(self, owner) -> int:
        """Return ALL of ``owner``'s pages to the free list; returns the
        count. Raises on unknown owner (double free)."""
        if owner not in self._owned:
            raise KeyError(f"owner {owner!r} holds no pages (double free?)")
        pages = self._owned.pop(owner)
        self._free.extend(pages)
        return len(pages)

    def check_conserved(self) -> None:
        """Assert the free list and the owned sets exactly partition
        [0, n_pages) — no leak, no duplication, no scratch intrusion."""
        seen = list(self._free)
        for pages in self._owned.values():
            seen.extend(pages)
        if len(seen) != len(set(seen)):
            raise AssertionError("page id duplicated across free/owned sets")
        if set(seen) != set(range(self.n_pages)):
            missing = set(range(self.n_pages)) - set(seen)
            extra = set(seen) - set(range(self.n_pages))
            raise AssertionError(
                f"pool not conserved: leaked={sorted(missing)} "
                f"foreign={sorted(extra)}")

    def check_all_free(self) -> None:
        """Assert every page is back in the free list (a drained engine):
        the CI smoke's no-leak gate."""
        self.check_conserved()
        if self._owned:
            raise AssertionError(
                f"pages still owned after drain: "
                f"{ {k: v for k, v in self._owned.items()} }")
