"""Flight recorder: always-on host-side lifecycle + host-phase log for
the continuous-batching engine (ISSUE 12).

The engine records only ``emit_times``/``finish_time``
(serving/scheduler.py:76), so the benchmark can report a p99 but not
ATTRIBUTE it — queue wait vs prefill stall vs decode vs host overhead —
and nothing measures engine-steps/s or the host/device split of the step
loop. This module is the raw log those numbers fold out of
(analysis/servetrace.py): an append-only, host-side event list inside
``ServingEngine``. ZERO device dispatches and zero effect on the jit
step program — the recorder reads the engine's existing clock
abstraction and Python state, nothing else, so streams are bit-identical
recorder on or off (tests/test_servetrace.py pins it on dp8 and
dp2×tp4, the same contract the prefix cache and blow-up recovery set).

Three append-only streams:

- ``events``: per-request lifecycle dicts — ``submit`` (t = arrival),
  ``shed``, ``admit`` (slot/shard + prefix-hit and suffix token counts),
  ``running`` (decode-ready: own prefill landed, or the zero-prefill
  join), ``first_token``, ``finish`` (EOS/max_new evict, with the stream
  length), ``cancel``, ``poison``. Per-step emits live on the step
  records, not here — one event per token would dominate the log.
- ``steps``: one record per DISPATCHED engine step (idle invocations
  that admit nothing and run nothing are dropped): enter/exit
  timestamps, the six host-phase durations (schedule_admit,
  prefix_lookup, prefill_dispatch, table_rewrite, step_dispatch,
  readback_sample — consecutive clock reads tile [t0, t1] exactly, so
  the phases sum to the step wall time by construction), the rids that
  emitted / evicted this step, and a scheduler/pool/prefix-cache
  counter snapshot.
- ``prefills``: every prefill-batch span (t0, t1, rids, tokens) — the
  join cost that stalls every OTHER running slot's decode, which is the
  disaggregated-prefill motivation number servetrace's
  ``prefill_stall`` component measures. Chunk-drain spans (chunked
  prefill, ISSUE 15) additionally carry per-row ``chunks`` records
  (rid, chunk index, tokens) so the stall attribution and the per-rid
  token conservation stay EXACT under interleaving.

Clock discipline: timestamps come from the engine's ``_t(now)`` —
``clock()`` when set (wall time in benchmarks), else the step's virtual
``now``. The engine makes the SAME clock reads whether the recorder is
enabled or not, so a stateful test clock ticks identically on/off.
With no clock at all (``now = math.inf``) every duration is inf−inf =
NaN; ``span`` drops non-finite deltas and counts them in
``nonfinite_spans``, and the folds skip non-finite samples — the
non-finite guard ISSUE 12 requires (engine.cancel's math.inf fallback
must never poison a percentile).
"""

from __future__ import annotations

import math

PHASES = ("schedule_admit", "prefix_lookup", "prefill_dispatch",
          "table_rewrite", "step_dispatch", "readback_sample")


class FlightRecorder:
    """Append-only host-side log; ``enabled=False`` keeps every hook a
    no-op (the A/B twin for the bit-identity test) without changing the
    engine's clock-read pattern."""

    def __init__(self, enabled: bool = True, replica: int | None = None):
        self.enabled = enabled
        # fleet tag (ISSUE 14): when the engine is one replica of a
        # FleetRouter the router stamps its index here, and every event
        # and step record carries a "replica" field; ``None`` (the
        # single-engine default) keeps the records byte-identical to
        # pre-fleet logs, so committed servetrace artifacts fold and
        # --diff unchanged. Survives ``reset()`` — the identity of the
        # replica does not change when its log is cleared.
        self.replica = replica
        self.reset()

    def reset(self) -> None:
        """Drop everything recorded so far (benchmarks reset after the
        warmup request so compile time doesn't pollute the trace)."""
        self.events: list[dict] = []
        self.steps: list[dict] = []
        self.prefills: list[dict] = []
        self.nonfinite_spans = 0
        self._cur: dict | None = None

    # -- request lifecycle -------------------------------------------

    def event(self, kind: str, rid, t: float, **fields) -> None:
        if self.enabled:
            rec = {"kind": kind, "rid": rid, "t": t, **fields}
            if self.replica is not None:
                rec["replica"] = self.replica
            self.events.append(rec)

    # -- per-step phase spans ----------------------------------------

    def begin_step(self, i: int, t0: float) -> None:
        """Open step record ``i`` (the engine's pre-dispatch counter).
        Spans and prefills recorded until ``end_step`` attach to it."""
        if self.enabled:
            self._cur = {"i": i, "t0": t0,
                         "phases": dict.fromkeys(PHASES, 0.0),
                         "emits": [], "evicts": []}
            if self.replica is not None:
                self._cur["replica"] = self.replica

    def span(self, phase: str, t0: float, t1: float) -> None:
        """Accumulate ``t1 - t0`` into the open step's phase. Non-finite
        deltas (the no-clock math.inf timeline) are dropped and counted,
        never accumulated — an inf here would poison every fold."""
        if self._cur is None:
            return
        d = t1 - t0
        if math.isfinite(d):
            self._cur["phases"][phase] += d
        else:
            self.nonfinite_spans += 1

    def admit_residual(self, t0: float, t1: float) -> None:
        """schedule_admit = the admit segment [t0, t1] MINUS the
        lookup/prefill/rewrite sub-spans already accumulated inside it —
        the pure scheduler+allocator bookkeeping. Clamped at 0 (the
        sub-spans are measured with the same clock, but two reads can
        tie on a coarse clock)."""
        if self._cur is None:
            return
        seg = t1 - t0
        if not math.isfinite(seg):
            self.nonfinite_spans += 1
            return
        ph = self._cur["phases"]
        inner = (ph["prefix_lookup"] + ph["prefill_dispatch"]
                 + ph["table_rewrite"])
        ph["schedule_admit"] += max(seg - inner, 0.0)

    def prefill(self, t0: float, t1: float, rids: list,
                tokens: int, chunks: list | None = None) -> None:
        """One prefill-batch span: dispatch + logits readback for the
        join batch ``rids`` (``tokens`` prompt tokens actually run).
        Lands in the global ``prefills`` stream AND the open step's
        prefill_dispatch phase.

        ``chunks`` (chunked prefill, ISSUE 15): per-row
        ``{"rid", "chunk", "tokens"}`` dicts when the span is a chunk
        drain — the per-chunk records servetrace's fold-time
        conservation check (sum of chunk tokens == admitted suffix
        tokens per rid) and the CI budget-bound gate read. Absent on
        monolithic join spans, so unchunked logs are byte-identical to
        pre-ISSUE-15 records."""
        if not self.enabled:
            return
        rec = {"t0": t0, "t1": t1, "rids": list(rids), "tokens": tokens}
        if chunks is not None:
            rec["chunks"] = [dict(c) for c in chunks]
        self.prefills.append(rec)
        self.span("prefill_dispatch", t0, t1)

    def end_step(self, t1: float, emits: list, evicts: list,
                 counters: dict) -> None:
        """Commit the open record: exit timestamp, the rids that emitted
        a token this step, the rids evicted, and the counter snapshot."""
        if self._cur is None:
            return
        self._cur["t1"] = t1
        self._cur["emits"] = list(emits)
        self._cur["evicts"] = list(evicts)
        self._cur["counters"] = counters
        self.steps.append(self._cur)
        self._cur = None

    def drop_step(self) -> None:
        """Discard the open record — the idle early-return path (nothing
        running after admission). Any prefill spans it recorded stay in
        the global stream: the work happened, only the step didn't."""
        self._cur = None
