"""ctypes binding for the native (C++) data loader.

The native component of the data path (see native/dataloader.cpp for the
design): mmap'd token corpus, xoshiro random-crop sampling, threaded
prefetch ring. This module compiles the shared library on first use (plain
``g++ -O3 -shared -fPIC`` — no pybind11/bazel dependency), binds it with
ctypes, and exposes:

- ``NativeTokenLoader(path, dtype)`` — ``sample(batch, ctx, seed, step)``
  (pure in its arguments) and ``batches(batch, ctx, seed)`` (prefetching
  iterator yielding the same sequence).
- ``native_available()`` — whether the library could be built/loaded;
  callers fall back to the NumPy sampler in ``data.loader`` otherwise.

Determinism contract (tested): the prefetch iterator yields exactly
``sample(step=0), sample(step=1), ...``.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from pathlib import Path

import numpy as np

_DTYPES = {"uint16": 0, "int32": 1, "uint32": 2, "int64": 3}

_SRC = Path(__file__).resolve().parent.parent / "native" / "dataloader.cpp"
_LIB = _SRC.with_suffix(".so")

_lock = threading.Lock()
_lib = None
_load_error: str | None = None


def _build_and_load():
    global _lib, _load_error
    with _lock:
        if _lib is not None or _load_error is not None:
            return _lib
        try:
            if (not _LIB.exists()
                    or _LIB.stat().st_mtime < _SRC.stat().st_mtime):
                # Concurrency-safe build: an exclusive file lock serialises
                # concurrent builders (pytest-xdist, multi-process hosts),
                # and the compile goes to a temp path that is atomically
                # renamed — a reader can never CDLL a half-written .so.
                import fcntl

                lock_path = _LIB.with_suffix(".lock")
                with open(lock_path, "w") as lock:
                    fcntl.flock(lock, fcntl.LOCK_EX)
                    try:
                        if (not _LIB.exists()
                                or _LIB.stat().st_mtime < _SRC.stat().st_mtime):
                            tmp = _LIB.with_suffix(f".tmp{os.getpid()}.so")
                            cmd = [
                                os.environ.get("CXX", "g++"), "-O3",
                                "-shared", "-fPIC", "-std=c++17", "-pthread",
                                str(_SRC), "-o", str(tmp),
                            ]
                            subprocess.run(
                                cmd, check=True, capture_output=True, text=True
                            )
                            os.rename(tmp, _LIB)
                    finally:
                        fcntl.flock(lock, fcntl.LOCK_UN)
            lib = ctypes.CDLL(str(_LIB))
        except (OSError, subprocess.CalledProcessError) as e:
            detail = getattr(e, "stderr", "") or str(e)
            _load_error = f"native loader unavailable: {detail}"
            return None

        lib.dl_open.restype = ctypes.c_void_p
        lib.dl_open.argtypes = [ctypes.c_char_p, ctypes.c_int32,
                                ctypes.POINTER(ctypes.c_int64)]
        lib.dl_close.argtypes = [ctypes.c_void_p]
        lib.dl_len.restype = ctypes.c_int64
        lib.dl_len.argtypes = [ctypes.c_void_p]
        lib.dl_token.restype = ctypes.c_int64
        lib.dl_token.argtypes = [ctypes.c_void_p, ctypes.c_int64]
        i32p = np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS")
        lib.dl_sample.restype = ctypes.c_int32
        lib.dl_sample.argtypes = [ctypes.c_void_p, ctypes.c_int64,
                                  ctypes.c_int64, ctypes.c_uint64,
                                  ctypes.c_int64, i32p, i32p]
        lib.dl_prefetch_start.restype = ctypes.c_int32
        lib.dl_prefetch_start.argtypes = [ctypes.c_void_p, ctypes.c_int64,
                                          ctypes.c_int64, ctypes.c_uint64,
                                          ctypes.c_int32]
        lib.dl_next.restype = ctypes.c_int32
        lib.dl_next.argtypes = [ctypes.c_void_p, i32p, i32p]
        lib.dl_prefetch_stop.argtypes = [ctypes.c_void_p]
        _lib = lib
        return _lib


def native_available() -> bool:
    return _build_and_load() is not None


def native_load_error() -> str | None:
    _build_and_load()
    return _load_error


class NativeTokenLoader:
    """Random-crop LM batch sampler over a memmapped token file."""

    def __init__(self, path: str | os.PathLike, dtype: str = "uint16"):
        if dtype not in _DTYPES:
            raise ValueError(f"dtype {dtype!r} not in {sorted(_DTYPES)}")
        lib = _build_and_load()
        if lib is None:
            raise RuntimeError(_load_error)
        self._lib = lib
        n = ctypes.c_int64()
        self._h = lib.dl_open(str(path).encode(), _DTYPES[dtype],
                              ctypes.byref(n))
        if not self._h:
            raise OSError(f"dl_open failed for {path!r} (dtype {dtype})")
        self.num_tokens = int(n.value)
        self._prefetching = False

    def __len__(self) -> int:
        return self.num_tokens

    def token(self, i: int) -> int:
        return int(self._lib.dl_token(self._h, i))

    def sample(self, batch: int, ctx: int, seed: int, step: int):
        """-> (x, y) int32 [batch, ctx]; pure in (batch, ctx, seed, step)."""
        x = np.empty((batch, ctx), np.int32)
        y = np.empty((batch, ctx), np.int32)
        rc = self._lib.dl_sample(self._h, batch, ctx, seed, step, x, y)
        if rc != 0:
            raise ValueError(
                f"dl_sample failed (batch={batch}, ctx={ctx}, "
                f"corpus={self.num_tokens} tokens)"
            )
        return x, y

    def batches(self, batch: int, ctx: int, seed: int, slots: int = 4):
        """Prefetching iterator: yields the ``sample(step=0,1,2,...)``
        sequence with sampling overlapped against the consumer."""
        rc = self._lib.dl_prefetch_start(self._h, batch, ctx, seed, slots)
        if rc != 0:
            raise RuntimeError("prefetch already running or bad args")
        self._prefetching = True
        try:
            while True:
                x = np.empty((batch, ctx), np.int32)
                y = np.empty((batch, ctx), np.int32)
                if self._lib.dl_next(self._h, x, y) != 0:
                    return
                yield x, y
        finally:
            self._lib.dl_prefetch_stop(self._h)
            self._prefetching = False

    def close(self) -> None:
        if self._h:
            self._lib.dl_close(self._h)
            self._h = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass
