"""Token-array batch sampling.

Parity with the reference ``get_batch`` (cs336-basics/cs336_basics/data.py:
10-30): random crops of a 1-D token array → (x, y = x shifted by one).

TPU-first: the crop gather is vectorised (one fancy-index instead of a
Python loop of per-sample copies) and the result is shipped to device with
a single ``jax.device_put`` — the analogue of the reference's pinned-memory
async H2D. The native C++ sampler (``data.native_loader`` over
``native/dataloader.cpp``: mmap corpus, xoshiro crops, threaded prefetch
ring) does the same gather off the GIL and overlapped with device compute;
``stream_batches`` prefers it and falls back to the NumPy path.
"""

from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp


def sample_batch_np(
    dataset: np.ndarray,
    batch_size: int,
    context_length: int,
    rng: np.random.Generator,
) -> tuple[np.ndarray, np.ndarray]:
    """Host-side crop sampling; returns int32 numpy (x, y) [B, ctx]."""
    starts = rng.integers(0, len(dataset) - context_length, size=batch_size)
    idx = starts[:, None] + np.arange(context_length + 1)[None, :]
    window = dataset[idx].astype(np.int32)  # [B, ctx+1]
    return np.ascontiguousarray(window[:, :-1]), np.ascontiguousarray(window[:, 1:])


def get_batch(
    dataset: np.ndarray,
    batch_size: int,
    context_length: int,
    rng: np.random.Generator | int | None = None,
    device=None,
    sharding=None,
) -> tuple[jax.Array, jax.Array]:
    """Sample a (x, y) LM batch and place it on device.

    ``sharding`` (a ``jax.sharding.Sharding``) places the batch directly in
    its distributed layout — the multi-chip replacement for per-rank
    slicing. ``device`` pins to a single device.
    """
    if not isinstance(rng, np.random.Generator):
        rng = np.random.default_rng(rng)
    x, y = sample_batch_np(np.asarray(dataset), batch_size, context_length, rng)
    return _put(x, y, device, sharding)


def _put(x, y, device, sharding):
    target = sharding if sharding is not None else device
    if target is not None:
        return jax.device_put(x, target), jax.device_put(y, target)
    return jnp.asarray(x), jnp.asarray(y)


def stream_batches(
    corpus_path,
    batch_size: int,
    context_length: int,
    seed: int = 0,
    dtype: str = "uint16",
    device=None,
    sharding=None,
    use_native: bool | None = None,
):
    """Infinite iterator of device-placed (x, y) batches from a token FILE.

    Prefers the native C++ prefetching loader (sampling overlaps with the
    training step); ``use_native=None`` auto-falls back to a NumPy memmap
    when the toolchain is unavailable. The two paths draw from different
    RNGs, so fix ``use_native`` when bitwise batch reproducibility across
    machines matters.
    """
    from cs336_systems_tpu.data.native_loader import (
        NativeTokenLoader,
        native_available,
    )

    native = native_available() if use_native is None else use_native
    if native:
        dl = NativeTokenLoader(corpus_path, dtype)
        try:
            for x, y in dl.batches(batch_size, context_length, seed):
                yield _put(x, y, device, sharding)
        finally:
            dl.close()
    else:
        data = np.memmap(corpus_path, dtype=np.dtype(dtype), mode="r")
        rng = np.random.default_rng(seed)
        while True:
            x, y = sample_batch_np(data, batch_size, context_length, rng)
            yield _put(x, y, device, sharding)
