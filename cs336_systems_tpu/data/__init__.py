from cs336_systems_tpu.data.loader import get_batch

__all__ = ["get_batch"]
