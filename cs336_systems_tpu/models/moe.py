"""Mixture-of-Experts SwiGLU feed-forward with top-k routing.

A second model family beyond the reference's dense Transformer (the
reference has no MoE anywhere — this is part of the complete framework
surface, and the substrate for expert parallelism in ``parallel/ep.py``).

Dispatch schemes, same routing semantics (GShard priority fill: top-1
claims take capacity before top-2, token order within a priority) —
"dense" and "sorted" below, plus "gmm" (dropless Pallas grouped matmul
with the fused gate/up+silu·mul kernel, ops/grouped_matmul.py), the
expert-parallel all-to-all form (``_moe_ffn_ep_a2a``, parallel/ep.py's
default step), and the expert-sharded serving form
(``moe_ffn_ep_local``, parallel/serve.py):

- ``"dense"`` — GShard/Mesh-TensorFlow one-hot dispatch/combine tensors
  [T, E, C] (T tokens, E experts, C capacity slots); the layer is three
  einsums + a vmapped expert SwiGLU. Everything lands on the MXU with
  static shapes, but the dispatch einsums cost O(T·E·C·D) — fine for few
  experts, quadratic-ish waste at many.
- ``"sorted"`` — index-based dispatch: the router emits (expert, slot)
  integer coordinates per claim and tokens move by ONE scatter into the
  [E, C_buf, D] expert batch and ONE gather back, O(T·k·D) data movement
  regardless of E. Over-capacity claims scatter out of bounds and XLA
  drops them (mode="drop") — no masked arithmetic. This is the
  Megablocks-style dropless *mechanism* under a static capacity bound;
  with ``capacity_factor`` covering the worst skew nothing drops.

The sorted router also supports DATA-PARALLEL-consistent routing
(``dp_axis``): claim positions are computed in the GLOBAL (j, shard,
token) fill order via a per-expert count all-gather, so which tokens drop
matches the full-batch single-device model exactly — the per-shard
capacity artifact the plain per-shard router has (parallel/dp.py) goes
away. Expert compute is per-token, so token-level outputs then equal the
full-batch model's bit-for-bit.

Shared numerics: routing runs in fp32 (softmax over expert logits)
regardless of compute dtype; expert weights match the dense SwiGLU init
so a 1-expert MoE is numerically the dense layer; the load-balancing aux
loss is the GShard formulation ``E · Σ_e mean(gate_e) · mean(top1_e)``,
differentiable through the gate term.
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from cs336_systems_tpu.models.layers import init_linear, init_swiglu, linear, swiglu
from cs336_systems_tpu.ops.grouped_matmul import float0_like as _float0_like
from cs336_systems_tpu.utils.profiling import annotate


def _prefix_count(onehot: jax.Array) -> jax.Array:
    """Inclusive prefix sum along axis 0 of a [T, E] count matrix, as two
    tril matmuls on the MXU.

    ``lax.cumsum``'s TPU lowering was the sorted path's single largest
    overhead at the E8k2 peak: 2.1 ms per [16384, 8] call, 27.5 ms/step
    across the routing (round-4 trace, scripts/trace_moe_step.py) — the
    reduce-window form is O(T·window) on the VPU. Blocked form: within-
    block prefix via a [b, b] tril dot, block offsets via an exclusive
    tril dot over the [T/b] block sums — ~16 M MACs at T=16384, MXU work
    measured at noise level. Exact: counts < 2^24 held in fp32.
    """
    t, e = onehot.shape
    b = 128
    pad = (-t) % b
    x = onehot.astype(jnp.float32)
    if pad:
        x = jnp.concatenate([x, jnp.zeros((pad, e), jnp.float32)])
    tb = x.shape[0] // b
    x = x.reshape(tb, b, e)
    tril = jnp.tril(jnp.ones((b, b), jnp.float32))
    within = jnp.einsum("ij,bje->bie", tril, x)  # inclusive, within block
    offs = jnp.einsum(
        "ij,je->ie", jnp.tril(jnp.ones((tb, tb), jnp.float32), -1),
        within[:, -1, :],
    )  # exclusive cumsum of block totals
    out = (within + offs[:, None, :]).reshape(-1, e)[:t]
    return out.astype(onehot.dtype)


# ---------------------------------------------------------------------------
# Gather-both-ways claim movement (the round-4 sorted dispatch)
#
# The round-3 sorted path moved rows with an XLA scatter into [E, C, D] and
# a 2-D-index gather back; its backward then scattered again. Row scatters
# never run well here, and the fp32 combine staged two 50 MB temporaries per
# layer — enough HBM pressure that XLA rematerialized ~20 ms/step of
# converts (round-4 trace). With BOTH index maps materialized (claim→slot
# ``dest`` and slot→claim ``src``), every direction — forward dispatch,
# forward combine, and both backwards — is a row GATHER; the only scatter
# left in the layer is one [E·C] int32 scalar scatter building ``src``.


@jax.custom_vjp
def _dispatch_rows(xt, tok_of_slot, valid, dest_c, keep):
    """xe_flat[s] = valid[s] ? xt[token(src[s])] : 0 — [E·C, D] from [T, D].

    ``dest_c``/``keep`` ([T·k], clamped slot of each claim / kept mask) are
    unused in the forward; they make the TRANSPOSE a gather: dxt[t] =
    Σ_kept-claims-of-t dxe[dest]. Slots are unique per claim, so this is
    the exact adjoint of the forward's (valid, src) gather.
    """
    del dest_c, keep
    return jnp.where(valid[:, None], jnp.take(xt, tok_of_slot, axis=0), 0)


def _dispatch_rows_fwd(xt, tok_of_slot, valid, dest_c, keep):
    out = _dispatch_rows(xt, tok_of_slot, valid, dest_c, keep)
    res = (dest_c, keep, xt.shape[0], tok_of_slot, valid)
    return out, res


def _dispatch_rows_bwd(res, g):
    dest_c, keep, t, tok_of_slot, valid = res
    k = dest_c.size // t
    picked = jnp.take(g, dest_c, axis=0)  # [T·k, D]
    picked = jnp.where(keep[:, None], picked, 0)
    dxt = jnp.sum(picked.reshape(t, k, -1), axis=1)
    return (dxt, _float0_like(tok_of_slot), _float0_like(valid),
            _float0_like(dest_c), _float0_like(keep))


_dispatch_rows.defvjp(_dispatch_rows_fwd, _dispatch_rows_bwd)


def _invert_map(dest: jax.Array, keep: jax.Array | None, n_rows: int):
    """Invert a claim→row map into its row→claim form with ONE int32
    scalar scatter — the only kind of scatter the MoE layers ever issue
    (row movement is always a gather; see the gather-both-ways note
    above). Dropped/invalid claims (``keep`` False) are redirected to
    unique out-of-bounds destinations so ``unique_indices`` holds for
    the drop-mode scatter. Returns ``(src_clamped [n_rows] int32,
    valid [n_rows] bool)`` where ``src_clamped[r]`` is the claim filling
    row ``r`` (0 where no claim does — mask with ``valid``)."""
    rank = jnp.arange(dest.shape[0], dtype=jnp.int32)
    dest_sc = dest if keep is None else jnp.where(keep, dest, n_rows + rank)
    src = (
        jnp.full((n_rows,), -1, jnp.int32)
        .at[dest_sc]
        .set(rank, mode="drop", unique_indices=True)
    )
    valid = src >= 0
    return jnp.where(valid, src, 0), valid


@jax.custom_vjp
def _combine_rows(ye_flat, wk, dest_c, src_c, valid, tok_of_slot):
    """Combined token outputs: [T, D] fp32, out[t] = Σ_j wk[t,j] ·
    ye_flat[dest_c[t,j]]. The k-sum lives INSIDE so the gather, the
    weight multiply, and the reduction fuse into one pass — per-claim
    [T·k, D] fp32 rows never hit HBM (they were ~30 ms/step of combine
    glue at the E8k2 b32 cell when materialized).

    ``wk``/``dest_c`` are [T, k]. CONTRACT: ``wk`` MUST be the
    kept-masked weight (weight · keep) when claims can drop — a dropped
    claim's ``dest_c`` is clamped to 0, so its raw d_wk here is the
    nonzero <g[t], ye_flat[0]>; the keep-product's own chain rule is
    what zeroes the router-gate gradient. Passing unmasked weights with
    drops would contaminate router gradients silently. The backward
    gathers in both directions: d_ye via the slot→claim map
    (src_c/valid/tok_of_slot), d_wk via the claim→slot map (dest_c).
    """
    del src_c, valid, tok_of_slot
    t, k = wk.shape
    d = ye_flat.shape[-1]
    rows = jnp.take(ye_flat, dest_c.reshape(-1), axis=0).astype(jnp.float32)
    return jnp.sum(rows.reshape(t, k, d) * wk[..., None], axis=1)


def _combine_rows_fwd(ye_flat, wk, dest_c, src_c, valid, tok_of_slot):
    out = _combine_rows(ye_flat, wk, dest_c, src_c, valid, tok_of_slot)
    return out, (ye_flat, wk, dest_c, src_c, valid, tok_of_slot)


def _combine_rows_bwd(res, g):
    ye_flat, wk, dest_c, src_c, valid, tok_of_slot = res
    t, k = wk.shape
    # g: [T, D] fp32. d_ye[s] = valid[s] · wk[claim(s)] · g[token(s)] —
    # slot s is filled by claim src_c[s] alone, so the adjoint of the dest
    # gather is this src/token gather.
    ws = jnp.take(wk.reshape(-1), src_c)
    gs = jnp.take(g, tok_of_slot, axis=0)
    d_ye = jnp.where(valid[:, None], ws[:, None] * gs, 0).astype(ye_flat.dtype)
    # d_wk[t,j] = <g[t], ye_flat[dest_c[t,j]]> — both sides gathers.
    rows = jnp.take(ye_flat, dest_c.reshape(-1), axis=0).astype(jnp.float32)
    d_wk = jnp.sum(rows.reshape(t, k, -1) * g[:, None, :], axis=-1)
    return (d_ye, d_wk, _float0_like(dest_c), _float0_like(src_c),
            _float0_like(valid), _float0_like(tok_of_slot))


_combine_rows.defvjp(_combine_rows_fwd, _combine_rows_bwd)


def init_moe(key, d_model: int, d_ff: int, num_experts: int, dtype=jnp.float32):
    """Router + E stacked expert SwiGLUs (leaves [E, ...])."""
    k_router, k_experts = jax.random.split(key)
    expert_keys = jax.random.split(k_experts, num_experts)
    experts = jax.vmap(lambda k: init_swiglu(k, d_model, d_ff, dtype))(expert_keys)
    return {
        "router": init_linear(k_router, d_model, num_experts, dtype),
        "experts": experts,
    }


def moe_capacity(num_tokens: int, num_experts: int, top_k: int,
                 capacity_factor: float) -> int:
    """Per-expert capacity C = ceil(k·T/E · factor), floored at top_k."""
    return max(top_k, math.ceil(top_k * num_tokens / num_experts * capacity_factor))


def route_topk(gates: jax.Array, top_k: int, capacity: int):
    """Build dispatch/combine tensors from gate probabilities.

    ``gates``: [T, E] fp32 probabilities. Returns
    ``(dispatch [T,E,C] bool-ish fp32, combine [T,E,C] fp32, aux scalar)``.

    Slot j=0 (the top-1 choice) claims capacity before j=1, etc., so lower-
    priority assignments are the ones dropped under pressure — the GShard
    ordering. Positions within an expert's queue follow token order.
    """
    t, e = gates.shape
    vals, idx = jax.lax.top_k(gates, top_k)  # [T, k]
    vals = vals / jnp.sum(vals, axis=-1, keepdims=True)

    dispatch = jnp.zeros((t, e, capacity), jnp.float32)
    combine = jnp.zeros((t, e, capacity), jnp.float32)
    fill = jnp.zeros((e,), jnp.int32)  # running per-expert occupancy
    for j in range(top_k):  # top_k is small and static
        onehot_e = jax.nn.one_hot(idx[:, j], e, dtype=jnp.float32)  # [T, E]
        # position this token would take in each expert's queue
        pos_if = _prefix_count(onehot_e) - 1.0 + fill[None, :].astype(jnp.float32)
        pos = jnp.sum(pos_if * onehot_e, axis=-1)  # [T]
        keep = (pos < capacity) & (pos >= 0)
        slot = jax.nn.one_hot(pos.astype(jnp.int32), capacity, dtype=jnp.float32)
        assigned = onehot_e[:, :, None] * slot[:, None, :] * keep[:, None, None]
        dispatch = dispatch + assigned
        combine = combine + assigned * vals[:, j][:, None, None]
        fill = fill + jnp.sum(onehot_e, axis=0).astype(jnp.int32)

    # GShard load-balancing aux: E * sum_e mean(gate_e) * mean(top1_e)
    top1 = jax.nn.one_hot(idx[:, 0], e, dtype=jnp.float32)
    aux = e * jnp.sum(jnp.mean(gates, axis=0) * jnp.mean(top1, axis=0))
    return dispatch, combine, aux


def _shard_index(axes) -> jax.Array:
    """Raveled shard index over one or several mesh axes (row-major in the
    given order) — the order ``P((a1, a2))`` shards a batch dim in, so a
    token shard's raveled index IS its contiguous range's rank."""
    if isinstance(axes, str):
        return jax.lax.axis_index(axes)
    idx = jnp.int32(0)
    for a in axes:
        idx = idx * jax.lax.axis_size(a) + jax.lax.axis_index(a)
    return idx


def _gather_counts(local_count: jax.Array, axes) -> jax.Array:
    """All-gather per-expert counts over the token-sharding axes into
    ``[W, E]`` rows ordered by ``_shard_index``: each axis is gathered
    EXPLICITLY, innermost (last-named) axis first, so after the row-major
    reshape row ``i1·s2 + i2`` is the shard whose raveled index is
    ``i1·s2 + i2`` *by construction*. A single tuple-axis ``all_gather``
    would leave that interleaving to a JAX stacking convention — a
    convention change would silently reorder the global drop decisions;
    here it instead fails the shape assertion loudly."""
    if isinstance(axes, str):
        return jax.lax.all_gather(local_count, axes)  # [W, E]
    counts = local_count
    for a in reversed(tuple(axes)):
        counts = jax.lax.all_gather(counts, a)
    sizes = tuple(jax.lax.axis_size(a) for a in axes)
    expect = sizes + local_count.shape
    if counts.shape != expect:
        raise AssertionError(
            f"gathered counts layout {counts.shape} != axis-ordered "
            f"{expect} — the global fill order would be scrambled"
        )
    return counts.reshape(-1, local_count.shape[-1])


def route_topk_indexed(gates: jax.Array, top_k: int, capacity: int,
                       dp_axis=None):
    """Index-form routing: the same GShard priority fill as ``route_topk``
    but emitting integer coordinates instead of one-hot tensors.

    Returns ``(expert [T,k] int32, pos [T,k] int32, weight [T,k] fp32,
    aux scalar)`` where ``pos`` is the claim's position in its expert's
    fill order — claims with ``pos >= capacity`` are the dropped ones
    (callers scatter with mode="drop", so they simply never land).

    ``dp_axis``: mesh axis name — or a TUPLE of names, for batches sharded
    over several axes at once (the ep all-to-all step shards tokens over
    (dp, ep)) — to compute positions in the GLOBAL fill order across the
    token sharding (shards hold contiguous token ranges, so the global
    (priority, shard, token) order IS the full-batch (priority, token)
    order). Costs one [W, E] all-gather of per-expert counts per priority
    — a few KB — and makes drop decisions match the full-batch model
    exactly; ``capacity`` must then be the GLOBAL capacity.
    """
    t, e = gates.shape
    vals, idx = jax.lax.top_k(gates, top_k)  # [T, k]
    vals = vals / jnp.sum(vals, axis=-1, keepdims=True)

    fill = jnp.zeros((e,), jnp.int32)  # occupancy entering this priority
    pos_cols = []
    for j in range(top_k):  # top_k is small and static
        onehot = jax.nn.one_hot(idx[:, j], e, dtype=jnp.int32)  # [T, E]
        local_count = jnp.sum(onehot, axis=0)  # [E]
        if dp_axis is not None:
            counts = _gather_counts(local_count, dp_axis)  # [W, E]
            w = _shard_index(dp_axis)
            prev_shards = jnp.sum(
                jnp.where(jnp.arange(counts.shape[0])[:, None] < w, counts, 0),
                axis=0,
            )
            offset = fill + prev_shards
            fill = fill + jnp.sum(counts, axis=0)
        else:
            offset = fill
            fill = fill + local_count
        pos_if = _prefix_count(onehot) - 1 + offset[None, :]
        pos_cols.append(jnp.sum(pos_if * onehot, axis=-1))  # [T]
    pos = jnp.stack(pos_cols, axis=1)  # [T, k]

    top1 = jax.nn.one_hot(idx[:, 0], e, dtype=jnp.float32)
    if dp_axis is None:
        aux = e * jnp.sum(jnp.mean(gates, axis=0) * jnp.mean(top1, axis=0))
    else:
        # Global aux: means over ALL tokens via pmean (equal shard sizes →
        # true global means). A per-shard aux would be a mean of per-shard
        # PRODUCTS — a different function than the full-batch model's.
        # Gradients need no correction: shard_map transposes psum as psum,
        # so each shard's backward already carries the full global aux
        # gradient for its local gates, and the DP layer's gradient pmean
        # leaves the (identical-across-shards) result unchanged.
        m_g = jax.lax.pmean(jnp.mean(gates, axis=0), dp_axis)
        m_t = jax.lax.pmean(jnp.mean(top1, axis=0), dp_axis)
        aux = e * jnp.sum(m_g * m_t)
    return idx.astype(jnp.int32), pos, vals, aux


def _moe_ffn_sorted(params, xt, top_k, capacity, compute_dtype,
                    dp_axis: str | None, scatter_rows: bool = False,
                    ffn_remat: bool = False):
    """Index dispatch (see module docstring). xt: [T, D].

    Default is the round-4 gather-both-ways movement (``_dispatch_rows`` /
    ``_combine_rows``); ``scatter_rows=True`` is the round-3 row-scatter
    form, kept for the A/B in results/moe_v5e.txt.
    """
    t, d = xt.shape
    e = params["router"]["weight"].shape[0]
    in_dtype = xt.dtype if compute_dtype is None else jnp.dtype(compute_dtype)

    with annotate("routing"):
        router_logits = linear(
            params["router"], xt.astype(jnp.float32), jnp.float32
        )
        gates = jax.nn.softmax(router_logits, axis=-1)
        expert, pos, weight, aux = route_topk_indexed(
            gates, top_k, capacity, dp_axis
        )

    # Local buffer: a shard can land at most min(capacity, T·k) of its own
    # claims; under dp the GLOBAL pos can exceed the local buffer, so
    # re-index kept claims by their LOCAL kept-rank per expert (expert
    # compute is per-token — slot identity does not affect values).
    c_buf = min(capacity, t * top_k)
    keep = pos < capacity  # [T, k] bool, global-consistent under dp
    flat_e = expert.reshape(-1)
    flat_keep = keep.reshape(-1)
    kept_onehot = jax.nn.one_hot(flat_e, e, dtype=jnp.int32) * flat_keep[:, None]
    local_rank = jnp.sum(
        (_prefix_count(kept_onehot) - kept_onehot) * kept_onehot, axis=-1
    )
    token = jnp.repeat(jnp.arange(t), top_k)  # claim -> source token

    expert_fn = jax.vmap(lambda p, h: swiglu(p, h, compute_dtype))
    if ffn_remat:
        # Recompute the expert hidden activations in the backward instead
        # of stashing them: the [E, C, d_ff] gate/up pair is the layer's
        # largest stash (≈150 MB/layer at the E8k2 b24 cell) and costs two
        # of three expert matmuls to rebuild — the knob that fits larger
        # batches without full-block remat.
        expert_fn = jax.checkpoint(expert_fn)

    if scatter_rows:
        # dropped claims -> slot c_buf (out of bounds): scatter mode="drop"
        # discards them, gather mode="fill" reads them as zero
        slot = jnp.where(flat_keep, local_rank, c_buf)
        xe = (
            jnp.zeros((e, c_buf, d), in_dtype)
            .at[flat_e, slot]
            .set(xt.astype(in_dtype)[token], mode="drop")
        )
        ye = expert_fn(params["experts"], xe)
        back = ye.astype(jnp.float32).at[flat_e, slot].get(
            mode="fill", fill_value=0.0
        )  # [T·k, D]
        out = jnp.sum(
            back.reshape(t, top_k, d)
            * (weight * keep.astype(jnp.float32))[..., None],
            axis=1,
        )
        return out.astype(in_dtype), aux

    # Gather-both-ways: materialize claim→slot (dest) AND slot→claim (src,
    # via the one scalar scatter in _invert_map — never a row scatter).
    dest = flat_e * c_buf + local_rank
    dest_c = jnp.where(flat_keep, dest, 0)
    src_c, valid = _invert_map(dest, flat_keep, e * c_buf)
    tok_of_slot = jnp.take(token, src_c)

    xe_flat = _dispatch_rows(
        xt.astype(in_dtype), tok_of_slot, valid, dest_c, flat_keep
    )
    dest_c = dest_c.reshape(t, top_k)
    ye = expert_fn(params["experts"], xe_flat.reshape(e, c_buf, d))
    wk = weight * keep.astype(jnp.float32)  # [T, k]
    out = _combine_rows(
        ye.reshape(e * c_buf, d), wk, dest_c, src_c, valid, tok_of_slot
    )
    return out.astype(in_dtype), aux


def _moe_ffn_ep_a2a(params, xt, top_k, capacity, compute_dtype,
                    ep_axis: str, token_axes, ffn_remat: bool):
    """EXPERT-PARALLEL indexed dispatch: tokens move to their experts'
    owner devices with explicit ``lax.all_to_all`` over ``ep_axis``
    (Switch/GShard style), expert compute runs LOCALLY on each shard's
    E/W experts, and a second all-to-all brings the rows home — replacing
    the GSPMD-dense einsum path whose O(T·E·C·D) dispatch loses to the
    indexed form in every measured regime (results/moe_v5e.txt).

    Runs inside a shard_map whose expert leaves are ep-sharded
    ([E/W, ...] locally) and whose tokens shard over ``token_axes``
    (e.g. (dp, ep)). Routing uses the GLOBAL fill order over
    ``token_axes`` (route_topk_indexed), so drop decisions — and
    therefore every token's output — equal the full-batch single-device
    "sorted" model exactly; the oracle tests pin it.

    Movement is GATHER-BOTH-WAYS end to end (the round-4 discipline —
    no row scatter anywhere): claims pack into a [W, S, D] send buffer
    (S = T_local·k, the worst case of every local claim targeting one
    shard) via ``_dispatch_rows``; the received rows land in the local
    [E/W·C, D] expert buffer via a second ``_dispatch_rows`` keyed by the
    slot ids that ride along as an int32 [W, S] all-to-all; the computed
    rows retrace both hops (``_dispatch_rows`` + the transposing
    all-to-all) and ``_combine_rows`` applies the kept-masked weights.
    The only scatters build int32 slot->row maps (scalar, unique). All
    four backward directions are gathers plus the all-to-alls' own
    transposes (an all-to-all transposes to an all-to-all).
    """
    t, d = xt.shape
    e = params["router"]["weight"].shape[0]
    e_local = params["experts"]["w1"]["weight"].shape[0]
    if e % e_local:
        raise ValueError(f"global experts {e} not a multiple of local {e_local}")
    w = e // e_local
    in_dtype = xt.dtype if compute_dtype is None else jnp.dtype(compute_dtype)

    with annotate("routing"):
        router_logits = linear(
            params["router"], xt.astype(jnp.float32), jnp.float32
        )
        gates = jax.nn.softmax(router_logits, axis=-1)
        expert, pos, weight, aux = route_topk_indexed(
            gates, top_k, capacity, token_axes
        )
    keep = pos < capacity  # [T, k], global-fill-order consistent

    s = t * top_k  # per-destination send bound (static worst case)
    flat_e = expert.reshape(-1)
    flat_pos = pos.reshape(-1)
    flat_keep = keep.reshape(-1)
    dstw = flat_e // e_local  # owner shard of each claim
    slot_local = (flat_e % e_local) * capacity + flat_pos  # owner-local slot

    # pack claims per destination in token order (kept only)
    dst_onehot = jax.nn.one_hot(dstw, w, dtype=jnp.int32) * flat_keep[:, None]
    rank = jnp.sum((_prefix_count(dst_onehot) - dst_onehot) * dst_onehot,
                   axis=-1)
    dest_send = dstw * s + rank  # claim -> [W·S] send-buffer row
    dest_send_c = jnp.where(flat_keep, dest_send, 0)
    src_send_c, valid_send = _invert_map(dest_send, flat_keep, w * s)
    token = jnp.repeat(jnp.arange(t), top_k)
    tok_of_send = jnp.take(token, src_send_c)

    send_x = _dispatch_rows(
        xt.astype(in_dtype), tok_of_send, valid_send, dest_send_c, flat_keep
    )  # [W·S, D]
    send_slot = jnp.where(valid_send, jnp.take(slot_local, src_send_c), -1)

    recv_x = jax.lax.all_to_all(
        send_x.reshape(w, s, d), ep_axis, 0, 0
    ).reshape(w * s, d)
    recv_slot = jax.lax.all_to_all(
        send_slot.reshape(w, s), ep_axis, 0, 0
    ).reshape(w * s)

    # received rows -> the local [E/W·C, D] expert buffer (gather both ways;
    # slots are globally unique: one claim per (expert, global fill pos))
    valid_recv = recv_slot >= 0
    slot_c = jnp.where(valid_recv, recv_slot, 0)
    nrows = e_local * capacity
    src_buf_c, valid_buf = _invert_map(recv_slot, valid_recv, nrows)
    xe = _dispatch_rows(recv_x, src_buf_c, valid_buf, slot_c, valid_recv)

    expert_fn = jax.vmap(lambda p, h: swiglu(p, h, compute_dtype))
    if ffn_remat:
        expert_fn = jax.checkpoint(expert_fn)  # see _moe_ffn_sorted
    ye = expert_fn(params["experts"], xe.reshape(e_local, capacity, d))

    back = _dispatch_rows(
        ye.reshape(nrows, d), slot_c, valid_recv, src_buf_c, valid_buf
    )  # [W·S, D] in the senders' layout
    back = jax.lax.all_to_all(
        back.reshape(w, s, d), ep_axis, 0, 0
    ).reshape(w * s, d)

    wk = weight * keep.astype(jnp.float32)  # kept-mask contract: _combine_rows
    out = _combine_rows(
        back, wk, dest_send_c.reshape(t, top_k), src_send_c, valid_send,
        tok_of_send,
    )
    return out.astype(in_dtype), aux


def moe_ffn_ep_local(params, x, top_k: int, compute_dtype=None,
                     ep_axis: str = "ep"):
    """EXPERT-SHARDED serving FFN: tokens REPLICATED over ``ep_axis``,
    expert weights sharded over it, one psum per layer.

    The serving-side counterpart of the training a2a path
    (``_moe_ffn_ep_a2a``) for the regime that motivates expert-sharded
    decode: large-E MoE whose expert weights exceed one chip's HBM while
    the per-step token count (B rows at decode) is small. Replicating
    the tokens costs each shard the dense compute once, but moves ZERO
    activation rows over the interconnect until the single fp32 psum of
    the combined outputs — at decode token counts that psum is the
    entire communication.

    Mechanics: routing runs replicated over the full E experts (router
    weight replicated); each shard keeps only the claims owned by its
    E/W local experts, packs them with the gather-both-ways machinery at
    the DROPLESS capacity (c = T: a token's top-k experts are distinct,
    so no expert can receive more than T claims — the serving contract,
    models/decode._ffn), computes its local experts, combines with the
    locality-masked weights, and psums. Every (token, claim) term is
    computed on exactly ONE shard, so the result equals the
    single-device dropless path: BIT-EXACT for top_k ≤ 2 (the combine
    is then at most one fp32 addition, and IEEE addition is
    commutative); for k > 2 the shard-order summation can differ in low
    bits from slot order (documented tolerance). Memory: the packed
    buffer is [E/W · T, D] per shard — the same O(E·T·D)-class bound as
    sorted-at-C=T divided by the ep degree, which is the point.
    """
    lead = x.shape[:-1]
    d = x.shape[-1]
    xt = x.reshape(-1, d)
    t = xt.shape[0]
    e = params["router"]["weight"].shape[0]
    e_local = params["experts"]["w1"]["weight"].shape[0]
    if e % e_local:
        raise ValueError(f"global experts {e} not a multiple of local {e_local}")
    in_dtype = xt.dtype if compute_dtype is None else jnp.dtype(compute_dtype)

    with annotate("routing"):
        router_logits = linear(
            params["router"], xt.astype(jnp.float32), jnp.float32
        )
        gates = jax.nn.softmax(router_logits, axis=-1)
        vals, idx = jax.lax.top_k(gates, top_k)  # [T, k]
        vals = vals / jnp.sum(vals, axis=-1, keepdims=True)

    local_lo = jax.lax.axis_index(ep_axis) * e_local
    is_local = (idx >= local_lo) & (idx < local_lo + e_local)  # [T, k]
    eloc = jnp.clip(idx - local_lo, 0, e_local - 1)
    flat_e = eloc.reshape(-1)
    flat_keep = is_local.reshape(-1)

    onehot = jax.nn.one_hot(flat_e, e_local, dtype=jnp.int32) * flat_keep[:, None]
    local_rank = jnp.sum((_prefix_count(onehot) - onehot) * onehot, axis=-1)
    c_buf = t  # dropless
    dest = flat_e * c_buf + local_rank
    dest_c = jnp.where(flat_keep, dest, 0)
    src_c, valid = _invert_map(dest, flat_keep, e_local * c_buf)
    token = jnp.repeat(jnp.arange(t), top_k)
    tok_of_slot = jnp.take(token, src_c)

    xe = _dispatch_rows(xt.astype(in_dtype), tok_of_slot, valid, dest_c,
                        flat_keep)
    # (no remat knob: this is a forward-only serving path — nothing is
    # stashed for a backward, so jax.checkpoint would be a no-op trap)
    expert_fn = jax.vmap(lambda p, h: swiglu(p, h, compute_dtype))
    ye = expert_fn(params["experts"], xe.reshape(e_local, c_buf, d))

    wk = vals * is_local.astype(jnp.float32)
    out = _combine_rows(
        ye.reshape(e_local * c_buf, d), wk, dest_c.reshape(t, top_k),
        src_c, valid, tok_of_slot,
    )
    out = jax.lax.psum(out, ep_axis)
    return out.astype(in_dtype).reshape(*lead, d)


def _moe_ffn_gmm(params, xt, top_k, compute_dtype, dp_axis: str | None,
                 ffn_remat: bool, bm: int = 256):
    """DROPLESS dispatch over the Pallas grouped matmul
    (ops/grouped_matmul.py): tokens packed tightly by expert (per-group
    pad only to the ``bm`` row tile, ~3% at the E8k2 peak vs the capacity
    form's cf−1 = 25%), every claim computed — capacity never drops.
    Routing probabilities/aux are identical to the capacity paths; under
    ``dp_axis`` the only cross-shard work is the aux loss's pmean (nothing
    drops, so per-shard compute already equals the full-batch model —
    routing runs locally, no fill-position all-gathers).
    """
    from cs336_systems_tpu.ops.grouped_matmul import (
        grouped_matmul, grouped_matmul_w13, tile_maps)

    t, d = xt.shape
    e = params["router"]["weight"].shape[0]
    in_dtype = xt.dtype if compute_dtype is None else jnp.dtype(compute_dtype)

    with annotate("routing"):
        router_logits = linear(
            params["router"], xt.astype(jnp.float32), jnp.float32
        )
        gates = jax.nn.softmax(router_logits, axis=-1)
        # Route LOCALLY even under dp (dropless compute needs no cross-shard
        # fill positions — route_topk_indexed's [W, E] all-gathers would buy
        # nothing); only the aux loss takes the global-mean form below.
        expert, pos, weight, aux = route_topk_indexed(
            gates, top_k, t * top_k, None
        )
    if dp_axis is not None:
        top1 = jax.nn.one_hot(expert[:, 0], e, dtype=jnp.float32)
        m_g = jax.lax.pmean(jnp.mean(gates, axis=0), dp_axis)
        m_t = jax.lax.pmean(jnp.mean(top1, axis=0), dp_axis)
        aux = e * jnp.sum(m_g * m_t)  # same global form as route_topk_indexed

    flat_e = expert.reshape(-1)
    counts = jnp.sum(jax.nn.one_hot(flat_e, e, dtype=jnp.int32), axis=0)
    # Dropless routing makes ``pos`` a bijective 0..count-1 fill rank
    # within each expert already — no prefix recompute needed (the
    # capacity paths re-rank because drops puncture the sequence).
    local_rank = pos.reshape(-1)
    # static row budget covering Σ round_up(counts, bm) in whole tiles
    m_pad = (-(-(t * top_k) // bm) + e) * bm
    te, first, visited, starts = tile_maps(counts, bm, m_pad // bm)

    token = jnp.repeat(jnp.arange(t), top_k)
    dest = jnp.take(starts, flat_e) + local_rank  # tight packed row
    src_c, valid = _invert_map(dest, None, m_pad)
    tok_of_slot = jnp.take(token, src_c)
    all_keep = jnp.ones_like(flat_e, dtype=bool)

    xs = _dispatch_rows(
        xt.astype(in_dtype), tok_of_slot, valid, dest, all_keep
    )

    def expert_ffn(wp, xs):
        # grouped_matmul consumes the native [E, out, in] layers.linear
        # layout directly (its kernels pick contracting dims) — only the
        # bf16 cast materializes, same as the capacity paths. The gate/up
        # pair + silu·mul run as ONE fused kernel (grouped_matmul_w13):
        # h and g never leave VMEM, x is read once, and the separate
        # elementwise silu pass — the attributed reason gmm lost
        # end-to-end despite winning in isolation — is gone.
        cast = lambda a: a.astype(in_dtype)
        p = grouped_matmul_w13(
            xs, cast(wp["w1"]["weight"]), cast(wp["w3"]["weight"]),
            te, first, visited, bm,
        )
        return grouped_matmul(p, cast(wp["w2"]["weight"]), te, first, visited, bm)

    if ffn_remat:
        expert_ffn = jax.checkpoint(expert_ffn)
    ys = expert_ffn(params["experts"], xs)

    out = _combine_rows(
        ys, weight, dest.reshape(t, top_k), src_c, valid, tok_of_slot
    )
    return out.astype(in_dtype), aux


def _axes_size(axes) -> int:
    if isinstance(axes, str):
        return jax.lax.axis_size(axes)
    n = 1
    for a in axes:
        n *= jax.lax.axis_size(a)
    return n


def moe_ffn(params, x: jax.Array, top_k: int, capacity_factor: float,
            compute_dtype=None, dispatch: str = "dense",
            dp_axis=None, global_tokens: int | None = None,
            ffn_remat: bool = False, capacity: int | None = None,
            ep_axis: str | None = None):
    """MoE SwiGLU: [..., S, D] -> ([..., S, D], aux loss scalar).

    ``dispatch``: "dense" (one-hot einsums), "sorted" (index dispatch,
    gather-both-ways row movement), "sorted_scatter" (the round-3
    row-scatter form of "sorted", kept for A/B), or "gmm" (DROPLESS —
    tokens packed tightly by expert and computed by the Pallas grouped
    matmul, ops/grouped_matmul.py; ``capacity_factor`` is ignored, no
    claim ever drops). The capacity schemes share routing decisions;
    "gmm" shares routing probabilities but never drops. ``dp_axis``
    (sorted/gmm): full-batch-consistent routing under data parallelism —
    a mesh axis name or a tuple of names when the batch shards over
    several axes (for "gmm" only the aux loss needs the global form —
    dropless per-shard compute already matches the full batch);
    ``global_tokens`` overrides the token count used for capacity
    (defaults to T · axis size). ``capacity``: explicit per-expert slot
    count overriding the ``moe_capacity`` formula — e.g. ``capacity=T``
    makes a call provably dropless (top-k experts are distinct per token,
    so no expert can receive more than T claims), which is the serving
    contract (models/decode._ffn).

    ``ep_axis``: EXPERT-PARALLEL all-to-all dispatch (requires
    dispatch="sorted" and a shard_map whose expert leaves are sharded
    over this axis): tokens travel to their experts' owner shards and
    back with explicit all-to-alls, expert compute is local — see
    ``_moe_ffn_ep_a2a``. ``dp_axis`` must then name ALL the token-
    sharding axes (including ``ep_axis`` if tokens shard over it).
    """
    lead = x.shape[:-1]
    d = x.shape[-1]
    xt = x.reshape(-1, d)  # [T, D]
    t = xt.shape[0]
    e = params["router"]["weight"].shape[0]

    if ep_axis is not None:
        if dispatch != "sorted":
            raise ValueError(
                f"ep_axis (all-to-all expert parallelism) requires "
                f"dispatch='sorted', got {dispatch!r}"
            )
        if dp_axis is None:
            raise ValueError(
                "ep_axis requires dp_axis naming the token-sharding axes "
                "(the global fill order is what the oracle contract pins)"
            )
        t_cap = global_tokens or t * _axes_size(dp_axis)
        c = capacity or moe_capacity(t_cap, e, top_k, capacity_factor)
        out, aux = _moe_ffn_ep_a2a(
            params, xt, top_k, c, compute_dtype, ep_axis, dp_axis, ffn_remat
        )
        return out.reshape(*lead, d), aux

    if dispatch == "gmm":
        out, aux = _moe_ffn_gmm(
            params, xt, top_k, compute_dtype, dp_axis, ffn_remat
        )
        return out.reshape(*lead, d), aux
    if dispatch in ("sorted", "sorted_scatter"):
        if dp_axis is not None:
            t_cap = global_tokens or t * _axes_size(dp_axis)
        else:
            t_cap = t
        c = capacity or moe_capacity(t_cap, e, top_k, capacity_factor)
        out, aux = _moe_ffn_sorted(
            params, xt, top_k, c, compute_dtype, dp_axis,
            scatter_rows=dispatch == "sorted_scatter",
            ffn_remat=ffn_remat,
        )
        return out.reshape(*lead, d), aux
    if dp_axis is not None:
        raise ValueError(
            "dp_axis-consistent routing requires dispatch='sorted' (the "
            "dense one-hot dispatch has no global-position form)"
        )
    if dispatch != "dense":
        raise ValueError(f"unknown moe dispatch {dispatch!r}")
    c = capacity or moe_capacity(t, e, top_k, capacity_factor)

    with annotate("routing"):
        router_logits = linear(
            params["router"], xt.astype(jnp.float32), jnp.float32
        )
        gates = jax.nn.softmax(router_logits, axis=-1)  # [T, E] fp32
        dispatch_t, combine, aux = route_topk(gates, top_k, c)

    in_dtype = xt.dtype if compute_dtype is None else jnp.dtype(compute_dtype)
    xe = jnp.einsum(
        "tec,td->ecd", dispatch_t.astype(in_dtype), xt.astype(in_dtype),
        preferred_element_type=jnp.float32,
    ).astype(in_dtype)  # [E, C, D]

    expert_fn = jax.vmap(lambda p, h: swiglu(p, h, compute_dtype))
    if ffn_remat:
        expert_fn = jax.checkpoint(expert_fn)  # see _moe_ffn_sorted
    ye = expert_fn(params["experts"], xe)

    out = jnp.einsum(
        "tec,ecd->td", combine.astype(jnp.float32), ye.astype(jnp.float32),
        preferred_element_type=jnp.float32,
    ).astype(in_dtype)
    return out.reshape(*lead, d), aux
