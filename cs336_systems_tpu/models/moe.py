"""Mixture-of-Experts SwiGLU feed-forward with top-k routing.

A second model family beyond the reference's dense Transformer (the
reference has no MoE anywhere — this is part of the complete framework
surface, and the substrate for expert parallelism in ``parallel/ep.py``).

Two dispatch schemes, same routing semantics (GShard priority fill:
top-1 claims take capacity before top-2, token order within a priority):

- ``"dense"`` — GShard/Mesh-TensorFlow one-hot dispatch/combine tensors
  [T, E, C] (T tokens, E experts, C capacity slots); the layer is three
  einsums + a vmapped expert SwiGLU. Everything lands on the MXU with
  static shapes, but the dispatch einsums cost O(T·E·C·D) — fine for few
  experts, quadratic-ish waste at many.
- ``"sorted"`` — index-based dispatch: the router emits (expert, slot)
  integer coordinates per claim and tokens move by ONE scatter into the
  [E, C_buf, D] expert batch and ONE gather back, O(T·k·D) data movement
  regardless of E. Over-capacity claims scatter out of bounds and XLA
  drops them (mode="drop") — no masked arithmetic. This is the
  Megablocks-style dropless *mechanism* under a static capacity bound;
  with ``capacity_factor`` covering the worst skew nothing drops.

The sorted router also supports DATA-PARALLEL-consistent routing
(``dp_axis``): claim positions are computed in the GLOBAL (j, shard,
token) fill order via a per-expert count all-gather, so which tokens drop
matches the full-batch single-device model exactly — the per-shard
capacity artifact the plain per-shard router has (parallel/dp.py) goes
away. Expert compute is per-token, so token-level outputs then equal the
full-batch model's bit-for-bit.

Shared numerics: routing runs in fp32 (softmax over expert logits)
regardless of compute dtype; expert weights match the dense SwiGLU init
so a 1-expert MoE is numerically the dense layer; the load-balancing aux
loss is the GShard formulation ``E · Σ_e mean(gate_e) · mean(top1_e)``,
differentiable through the gate term.
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from cs336_systems_tpu.models.layers import init_linear, init_swiglu, linear, swiglu


def init_moe(key, d_model: int, d_ff: int, num_experts: int, dtype=jnp.float32):
    """Router + E stacked expert SwiGLUs (leaves [E, ...])."""
    k_router, k_experts = jax.random.split(key)
    expert_keys = jax.random.split(k_experts, num_experts)
    experts = jax.vmap(lambda k: init_swiglu(k, d_model, d_ff, dtype))(expert_keys)
    return {
        "router": init_linear(k_router, d_model, num_experts, dtype),
        "experts": experts,
    }


def moe_capacity(num_tokens: int, num_experts: int, top_k: int,
                 capacity_factor: float) -> int:
    """Per-expert capacity C = ceil(k·T/E · factor), floored at top_k."""
    return max(top_k, math.ceil(top_k * num_tokens / num_experts * capacity_factor))


def route_topk(gates: jax.Array, top_k: int, capacity: int):
    """Build dispatch/combine tensors from gate probabilities.

    ``gates``: [T, E] fp32 probabilities. Returns
    ``(dispatch [T,E,C] bool-ish fp32, combine [T,E,C] fp32, aux scalar)``.

    Slot j=0 (the top-1 choice) claims capacity before j=1, etc., so lower-
    priority assignments are the ones dropped under pressure — the GShard
    ordering. Positions within an expert's queue follow token order.
    """
    t, e = gates.shape
    vals, idx = jax.lax.top_k(gates, top_k)  # [T, k]
    vals = vals / jnp.sum(vals, axis=-1, keepdims=True)

    dispatch = jnp.zeros((t, e, capacity), jnp.float32)
    combine = jnp.zeros((t, e, capacity), jnp.float32)
    fill = jnp.zeros((e,), jnp.int32)  # running per-expert occupancy
    for j in range(top_k):  # top_k is small and static
        onehot_e = jax.nn.one_hot(idx[:, j], e, dtype=jnp.float32)  # [T, E]
        # position this token would take in each expert's queue
        pos_if = jnp.cumsum(onehot_e, axis=0) - 1.0 + fill[None, :].astype(jnp.float32)
        pos = jnp.sum(pos_if * onehot_e, axis=-1)  # [T]
        keep = (pos < capacity) & (pos >= 0)
        slot = jax.nn.one_hot(pos.astype(jnp.int32), capacity, dtype=jnp.float32)
        assigned = onehot_e[:, :, None] * slot[:, None, :] * keep[:, None, None]
        dispatch = dispatch + assigned
        combine = combine + assigned * vals[:, j][:, None, None]
        fill = fill + jnp.sum(onehot_e, axis=0).astype(jnp.int32)

    # GShard load-balancing aux: E * sum_e mean(gate_e) * mean(top1_e)
    top1 = jax.nn.one_hot(idx[:, 0], e, dtype=jnp.float32)
    aux = e * jnp.sum(jnp.mean(gates, axis=0) * jnp.mean(top1, axis=0))
    return dispatch, combine, aux


def route_topk_indexed(gates: jax.Array, top_k: int, capacity: int,
                       dp_axis: str | None = None):
    """Index-form routing: the same GShard priority fill as ``route_topk``
    but emitting integer coordinates instead of one-hot tensors.

    Returns ``(expert [T,k] int32, pos [T,k] int32, weight [T,k] fp32,
    aux scalar)`` where ``pos`` is the claim's position in its expert's
    fill order — claims with ``pos >= capacity`` are the dropped ones
    (callers scatter with mode="drop", so they simply never land).

    ``dp_axis``: compute positions in the GLOBAL fill order across the
    data-parallel axis (shards hold contiguous token ranges, so the global
    (priority, shard, token) order IS the full-batch (priority, token)
    order). Costs one [W, E] all-gather of per-expert counts per priority
    — a few KB — and makes drop decisions match the full-batch model
    exactly; ``capacity`` must then be the GLOBAL capacity.
    """
    t, e = gates.shape
    vals, idx = jax.lax.top_k(gates, top_k)  # [T, k]
    vals = vals / jnp.sum(vals, axis=-1, keepdims=True)

    fill = jnp.zeros((e,), jnp.int32)  # occupancy entering this priority
    pos_cols = []
    for j in range(top_k):  # top_k is small and static
        onehot = jax.nn.one_hot(idx[:, j], e, dtype=jnp.int32)  # [T, E]
        local_count = jnp.sum(onehot, axis=0)  # [E]
        if dp_axis is not None:
            counts = jax.lax.all_gather(local_count, dp_axis)  # [W, E]
            w = jax.lax.axis_index(dp_axis)
            prev_shards = jnp.sum(
                jnp.where(jnp.arange(counts.shape[0])[:, None] < w, counts, 0),
                axis=0,
            )
            offset = fill + prev_shards
            fill = fill + jnp.sum(counts, axis=0)
        else:
            offset = fill
            fill = fill + local_count
        pos_if = jnp.cumsum(onehot, axis=0) - 1 + offset[None, :]
        pos_cols.append(jnp.sum(pos_if * onehot, axis=-1))  # [T]
    pos = jnp.stack(pos_cols, axis=1)  # [T, k]

    top1 = jax.nn.one_hot(idx[:, 0], e, dtype=jnp.float32)
    if dp_axis is None:
        aux = e * jnp.sum(jnp.mean(gates, axis=0) * jnp.mean(top1, axis=0))
    else:
        # Global aux: means over ALL tokens via pmean (equal shard sizes →
        # true global means). A per-shard aux would be a mean of per-shard
        # PRODUCTS — a different function than the full-batch model's.
        # Gradients need no correction: shard_map transposes psum as psum,
        # so each shard's backward already carries the full global aux
        # gradient for its local gates, and the DP layer's gradient pmean
        # leaves the (identical-across-shards) result unchanged.
        m_g = jax.lax.pmean(jnp.mean(gates, axis=0), dp_axis)
        m_t = jax.lax.pmean(jnp.mean(top1, axis=0), dp_axis)
        aux = e * jnp.sum(m_g * m_t)
    return idx.astype(jnp.int32), pos, vals, aux


def _moe_ffn_sorted(params, xt, top_k, capacity, compute_dtype,
                    dp_axis: str | None):
    """Scatter/gather dispatch (see module docstring). xt: [T, D]."""
    t, d = xt.shape
    e = params["router"]["weight"].shape[0]
    in_dtype = xt.dtype if compute_dtype is None else jnp.dtype(compute_dtype)

    router_logits = linear(params["router"], xt.astype(jnp.float32), jnp.float32)
    gates = jax.nn.softmax(router_logits, axis=-1)
    expert, pos, weight, aux = route_topk_indexed(
        gates, top_k, capacity, dp_axis
    )

    # Local buffer: a shard can land at most min(capacity, T·k) of its own
    # claims; under dp the GLOBAL pos can exceed the local buffer, so
    # re-index kept claims by their LOCAL kept-rank per expert (expert
    # compute is per-token — slot identity does not affect values).
    c_buf = min(capacity, t * top_k)
    keep = pos < capacity  # [T, k] bool, global-consistent under dp
    flat_e = expert.reshape(-1)
    flat_keep = keep.reshape(-1)
    kept_onehot = jax.nn.one_hot(flat_e, e, dtype=jnp.int32) * flat_keep[:, None]
    local_rank = jnp.sum(
        (jnp.cumsum(kept_onehot, axis=0) - kept_onehot) * kept_onehot, axis=-1
    )
    # dropped claims -> slot c_buf (out of bounds): scatter mode="drop"
    # discards them, gather mode="fill" reads them as zero
    slot = jnp.where(flat_keep, local_rank, c_buf)

    token = jnp.repeat(jnp.arange(t), top_k)  # claim -> source token
    xe = (
        jnp.zeros((e, c_buf, d), in_dtype)
        .at[flat_e, slot]
        .set(xt.astype(in_dtype)[token], mode="drop")
    )
    ye = jax.vmap(lambda p, h: swiglu(p, h, compute_dtype))(params["experts"], xe)
    back = ye.astype(jnp.float32).at[flat_e, slot].get(
        mode="fill", fill_value=0.0
    )  # [T·k, D]
    out = jnp.sum(
        back.reshape(t, top_k, d)
        * (weight * keep.astype(jnp.float32))[..., None],
        axis=1,
    )
    return out.astype(in_dtype), aux


def moe_ffn(params, x: jax.Array, top_k: int, capacity_factor: float,
            compute_dtype=None, dispatch: str = "dense",
            dp_axis: str | None = None, global_tokens: int | None = None):
    """MoE SwiGLU: [..., S, D] -> ([..., S, D], aux loss scalar).

    ``dispatch``: "dense" (one-hot einsums) or "sorted" (index scatter /
    gather) — same routing decisions, different data movement (module
    docstring). ``dp_axis`` (sorted only): full-batch-consistent routing
    under data parallelism; ``global_tokens`` overrides the token count
    used for capacity (defaults to T · axis size).
    """
    lead = x.shape[:-1]
    d = x.shape[-1]
    xt = x.reshape(-1, d)  # [T, D]
    t = xt.shape[0]
    e = params["router"]["weight"].shape[0]

    if dispatch == "sorted":
        if dp_axis is not None:
            t_cap = global_tokens or t * jax.lax.axis_size(dp_axis)
        else:
            t_cap = t
        c = moe_capacity(t_cap, e, top_k, capacity_factor)
        out, aux = _moe_ffn_sorted(params, xt, top_k, c, compute_dtype, dp_axis)
        return out.reshape(*lead, d), aux
    if dp_axis is not None:
        raise ValueError(
            "dp_axis-consistent routing requires dispatch='sorted' (the "
            "dense one-hot dispatch has no global-position form)"
        )
    if dispatch != "dense":
        raise ValueError(f"unknown moe dispatch {dispatch!r}")
    c = moe_capacity(t, e, top_k, capacity_factor)

    router_logits = linear(params["router"], xt.astype(jnp.float32), jnp.float32)
    gates = jax.nn.softmax(router_logits, axis=-1)  # [T, E] fp32
    dispatch_t, combine, aux = route_topk(gates, top_k, c)

    in_dtype = xt.dtype if compute_dtype is None else jnp.dtype(compute_dtype)
    xe = jnp.einsum(
        "tec,td->ecd", dispatch_t.astype(in_dtype), xt.astype(in_dtype),
        preferred_element_type=jnp.float32,
    ).astype(in_dtype)  # [E, C, D]

    ye = jax.vmap(lambda p, h: swiglu(p, h, compute_dtype))(params["experts"], xe)

    out = jnp.einsum(
        "tec,ecd->td", combine.astype(jnp.float32), ye.astype(jnp.float32),
        preferred_element_type=jnp.float32,
    ).astype(in_dtype)
    return out.reshape(*lead, d), aux
