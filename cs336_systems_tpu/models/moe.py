"""Mixture-of-Experts SwiGLU feed-forward with top-k routing.

A second model family beyond the reference's dense Transformer (the
reference has no MoE anywhere — this is part of the complete framework
surface, and the substrate for expert parallelism in ``parallel/ep.py``).

TPU-first design — GShard/Mesh-TensorFlow style DENSE dispatch:

- No scatters, no ragged shapes, no host-side routing: the router builds
  one-hot dispatch/combine tensors [T, E, C] (T tokens, E experts, C
  capacity slots) and the whole layer is three einsums + a vmapped expert
  SwiGLU — everything lands on the MXU with static shapes, which is exactly
  what XLA needs. Tokens over capacity are dropped (their combine weight is
  zero and the residual stream carries them through), the standard
  capacity-factor trade; a sort-based dropless dispatch is the documented
  upgrade for very large T·E·C.
- Routing runs in fp32 (softmax over expert logits) regardless of the
  compute dtype; expert weights match the dense SwiGLU init so a 1-expert
  MoE is numerically the dense layer.
- The load-balancing auxiliary loss is the GShard formulation:
  ``E · Σ_e mean_tokens(gate_e) · mean_tokens(is_top1_e)`` — differentiable
  through the gate term.
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from cs336_systems_tpu.models.layers import init_linear, init_swiglu, linear, swiglu


def init_moe(key, d_model: int, d_ff: int, num_experts: int, dtype=jnp.float32):
    """Router + E stacked expert SwiGLUs (leaves [E, ...])."""
    k_router, k_experts = jax.random.split(key)
    expert_keys = jax.random.split(k_experts, num_experts)
    experts = jax.vmap(lambda k: init_swiglu(k, d_model, d_ff, dtype))(expert_keys)
    return {
        "router": init_linear(k_router, d_model, num_experts, dtype),
        "experts": experts,
    }


def moe_capacity(num_tokens: int, num_experts: int, top_k: int,
                 capacity_factor: float) -> int:
    """Per-expert capacity C = ceil(k·T/E · factor), floored at top_k."""
    return max(top_k, math.ceil(top_k * num_tokens / num_experts * capacity_factor))


def route_topk(gates: jax.Array, top_k: int, capacity: int):
    """Build dispatch/combine tensors from gate probabilities.

    ``gates``: [T, E] fp32 probabilities. Returns
    ``(dispatch [T,E,C] bool-ish fp32, combine [T,E,C] fp32, aux scalar)``.

    Slot j=0 (the top-1 choice) claims capacity before j=1, etc., so lower-
    priority assignments are the ones dropped under pressure — the GShard
    ordering. Positions within an expert's queue follow token order.
    """
    t, e = gates.shape
    vals, idx = jax.lax.top_k(gates, top_k)  # [T, k]
    vals = vals / jnp.sum(vals, axis=-1, keepdims=True)

    dispatch = jnp.zeros((t, e, capacity), jnp.float32)
    combine = jnp.zeros((t, e, capacity), jnp.float32)
    fill = jnp.zeros((e,), jnp.int32)  # running per-expert occupancy
    for j in range(top_k):  # top_k is small and static
        onehot_e = jax.nn.one_hot(idx[:, j], e, dtype=jnp.float32)  # [T, E]
        # position this token would take in each expert's queue
        pos_if = jnp.cumsum(onehot_e, axis=0) - 1.0 + fill[None, :].astype(jnp.float32)
        pos = jnp.sum(pos_if * onehot_e, axis=-1)  # [T]
        keep = (pos < capacity) & (pos >= 0)
        slot = jax.nn.one_hot(pos.astype(jnp.int32), capacity, dtype=jnp.float32)
        assigned = onehot_e[:, :, None] * slot[:, None, :] * keep[:, None, None]
        dispatch = dispatch + assigned
        combine = combine + assigned * vals[:, j][:, None, None]
        fill = fill + jnp.sum(onehot_e, axis=0).astype(jnp.int32)

    # GShard load-balancing aux: E * sum_e mean(gate_e) * mean(top1_e)
    top1 = jax.nn.one_hot(idx[:, 0], e, dtype=jnp.float32)
    aux = e * jnp.sum(jnp.mean(gates, axis=0) * jnp.mean(top1, axis=0))
    return dispatch, combine, aux


def moe_ffn(params, x: jax.Array, top_k: int, capacity_factor: float,
            compute_dtype=None):
    """MoE SwiGLU: [..., S, D] -> ([..., S, D], aux loss scalar).

    Three einsums around a vmapped expert SwiGLU:
    dispatch ([T,E,C] × [T,D] → [E,C,D]) → experts → combine back.
    """
    lead = x.shape[:-1]
    d = x.shape[-1]
    xt = x.reshape(-1, d)  # [T, D]
    t = xt.shape[0]
    e = params["router"]["weight"].shape[0]
    c = moe_capacity(t, e, top_k, capacity_factor)

    router_logits = linear(params["router"], xt.astype(jnp.float32), jnp.float32)
    gates = jax.nn.softmax(router_logits, axis=-1)  # [T, E] fp32
    dispatch, combine, aux = route_topk(gates, top_k, c)

    in_dtype = xt.dtype if compute_dtype is None else jnp.dtype(compute_dtype)
    xe = jnp.einsum(
        "tec,td->ecd", dispatch.astype(in_dtype), xt.astype(in_dtype),
        preferred_element_type=jnp.float32,
    ).astype(in_dtype)  # [E, C, D]

    ye = jax.vmap(lambda p, h: swiglu(p, h, compute_dtype))(params["experts"], xe)

    out = jnp.einsum(
        "tec,ecd->td", combine.astype(jnp.float32), ye.astype(jnp.float32),
        preferred_element_type=jnp.float32,
    ).astype(in_dtype)
    return out.reshape(*lead, d), aux
