from cs336_systems_tpu.models.transformer import (
    TransformerConfig,
    MODEL_SIZES,
    config_for_size,
    init_transformer_lm,
    transformer_lm,
    count_params,
    generate,
)

__all__ = [
    "TransformerConfig",
    "MODEL_SIZES",
    "config_for_size",
    "init_transformer_lm",
    "transformer_lm",
    "count_params",
    "generate",
]
