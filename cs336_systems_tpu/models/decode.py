"""KV-cache incremental decoding — the fast inference path.

Capability beyond the reference: ``BasicsTransformerLM.generate``
(model.py:255-310) runs a FULL forward per emitted token (O(S²·L) per
token); here a prefill pass populates per-layer K/V caches and each new
token costs one cached attention row (O(S·L)). The reference's sampling
semantics (temperature scale → optional top-k threshold → categorical
draw, EOS stop, context-window bound) are preserved exactly.

TPU-first design:

- The cache is a pytree of per-layer PACKED [B, H, S_max, 2·Dh] K‖V
  leaves (one XLA buffer per layer — see ``init_kv_cache`` for the
  packing rationale and why per-layer leaves beat a stacked [L, ...]
  array by ~10× per token) and the whole decode LOOP runs inside
  a single jit (``lax.scan`` over steps, PRNG key threaded through the
  carry) — one dispatch per generation, not per token, which matters when
  host→device dispatch costs milliseconds.
- Static shapes throughout: the cache is allocated at ``S_max`` once and
  masked by the current length (``iota <= pos``) — no dynamic shapes, no
  recompilation per step.
- EOS: a scan cannot early-exit, so generation runs to ``max_new_tokens``
  steps — but with ``eos_token_id`` set the scan carries a per-row
  finished mask IN the jit: finished rows stop advancing their cache
  position (the paged kernel's pos//block early-out then stops paying
  their KV stream) and the first-EOS step comes back with the tokens, so
  the host truncation is a slice, not a rescan.
"""

from __future__ import annotations

import dataclasses
import functools

import jax
import jax.numpy as jnp

from cs336_systems_tpu.models.layers import apply_rope, embedding, linear, rmsnorm, rope_cache, swiglu
from cs336_systems_tpu.models.transformer import (
    TransformerConfig,
    top_p_filter,
    transformer_lm,
)
from cs336_systems_tpu.utils.profiling import annotate


def init_kv_cache(cfg: TransformerConfig, batch: int, max_len: int | None = None,
                  num_heads: int | None = None):
    """Zeroed cache pytree: {"kv"} — a per-layer TUPLE of PACKED
    [B, H, S_max, 2*Dh] arrays (compute dtype; K in lanes [0, Dh), V in
    [Dh, 2*Dh) — ops/decode_attention.pack_kv).

    Packed K‖V on the lane axis because the decode kernel reads both
    anyway and at Dh=64 the packed width is one full 128-lane tile: the
    slab DMA runs at full rate where separate 64-wide K/V slabs measured
    ~60% efficiency, and the per-token column write is ONE in-kernel tile
    update instead of two XLA dynamic-update-slices (7.3 us each, traced).

    Per-layer leaves rather than one stacked [L, ...] array on purpose:
    each leaf is its own XLA buffer, so the one-tile in-place update
    aliases through the decode scan's carry. A stacked cache forces the
    layer loop to dynamic-slice and re-stack every layer's whole slab per
    token — traced on v5e that was ~13 ms/token of pure cache copies at
    B=32, ~10× the actual attention+matmul work.
    """
    s = max_len or cfg.context_length
    h = num_heads if num_heads is not None else cfg.num_heads
    shape = (batch, h, s, 2 * cfg.d_head)
    return {
        "kv": tuple(jnp.zeros(shape, cfg.cdtype) for _ in range(cfg.num_layers)),
    }


# Default page size for the paged KV cache: 128 rows keeps the paged
# kernel's per-page DMA a full [128, W] tile (the unpaged kernel's slab
# granularity) while making skewed batches pay per-row page counts.
PAGE_BLOCK = 128


@dataclasses.dataclass(frozen=True)
class PagedKVGeometry:
    """Host-side page-pool layout for one ragged generation: row i owns
    ``ceil((len_i + new) / block)`` consecutive pages, so the pool holds
    ``sum`` of those — the HBM win over the unpaged cache's B·max rows.

    ``tables`` [B, max_blocks] int32: row i's page id for block j, with
    entries past the row's last page CLAMPED to its last page — they are
    never attended (the kernel early-outs at pos // block) but a prefetch
    may touch them, so they must stay valid ids of the SAME row and never
    the pool's reserved write-scratch page. ``page_rows``/``page_blks``
    [n_pages] invert the tables: the owning batch row and block index of
    each pool page (what the prefill gather consumes)."""

    block: int
    n_pages: int       # real pages — the pool allocates n_pages + 1
    max_blocks: int
    tables: object     # np [B, max_blocks] int32
    page_rows: object  # np [n_pages] int32
    page_blks: object  # np [n_pages] int32


def paged_kv_geometry(prompt_lens, max_new_tokens: int,
                      block: int = PAGE_BLOCK) -> PagedKVGeometry:
    """Build the page-pool geometry for per-row prompt lengths (host
    numpy in, host numpy out — shapes feed static jit specialization)."""
    import numpy as np

    if block <= 0 or block % 8:
        raise ValueError(
            f"page block must be a positive multiple of 8 (Mosaic HBM "
            f"write tiles are 8-row-aligned), got {block}")
    lens = np.asarray(prompt_lens, np.int64)
    if lens.ndim != 1 or lens.size == 0:
        raise ValueError(f"prompt_lens must be a non-empty [B] vector, "
                         f"got shape {lens.shape}")
    pages = -(-(lens + max_new_tokens) // block)
    offs = np.concatenate([[0], np.cumsum(pages)])
    nb = int(pages.max())
    b = lens.shape[0]
    tables = (offs[:b, None]
              + np.minimum(np.arange(nb)[None, :], pages[:, None] - 1))
    page_rows = np.repeat(np.arange(b), pages)
    page_blks = np.concatenate([np.arange(p) for p in pages])
    return PagedKVGeometry(
        block, int(pages.sum()), nb, tables.astype(np.int32),
        page_rows.astype(np.int32), page_blks.astype(np.int32))


def validate_block_tables(tables, n_pages: int, read_only=None,
                          write_pos=None, block: int | None = None,
                          active=None) -> None:
    """Host-side hard check of the reserved-scratch-page contract: every
    block-table entry must be a REAL page id in [0, n_pages) — page id
    ``n_pages`` (array index n_pages of the [n_pages + 1]-page pool) is
    the kernel's write scratch and steering it into a table would let one
    row's non-final grid flushes overwrite another row's live KV. Called
    by every table producer (paged_kv_geometry consumers, the serving
    page-pool allocator) before tables reach a device op; the in-kernel
    clamp in ops/decode_attention is defensive only and silently corrupts
    reads, which is exactly why the violation must be caught here.

    ``read_only``: optional set of SHARED page ids (the prefix cache's
    immutable pages, PagePool.shared_page_ids) — the copy-on-write
    contract. With ``write_pos`` ([B] per-row positions) and ``block``
    also given, each row's WRITE TARGET ``tables[i, pos_i // block]``
    must not be a shared page: the paged kernel writes exactly that
    block, so a shared id there would let one request's decode stamp
    every other reference-holder's prefix. ``active``: optional [B]
    mask — inactive rows write the scratch page, not their table, so
    they are exempt. Rows whose write position is past the table width
    (a finished row at its last block boundary) are skipped: the engine
    evicts them before the next step dispatch.

    Violations raise ``serving.errors.CorruptBlockTable`` (a ValueError
    subclass, imported lazily so this module stays import-light and
    cycle-free) — non-retriable: one dispatch with such a table corrupts
    other rows' live KV."""
    import numpy as np

    from cs336_systems_tpu.serving.errors import CorruptBlockTable

    t = np.asarray(tables)
    if t.size == 0:
        raise CorruptBlockTable("block tables must be non-empty")
    if t.min() < 0:
        raise CorruptBlockTable(
            f"block table contains negative page id {int(t.min())}")
    if t.max() >= n_pages:
        where = np.argwhere(t == t.max())[0]
        if t.max() == n_pages:
            raise CorruptBlockTable(
                f"block table entry {tuple(int(i) for i in where)} is the "
                f"reserved scratch page id {n_pages} — the scratch page "
                "must never enter a block table (see init_paged_kv_cache)")
        raise CorruptBlockTable(
            f"block table entry {tuple(int(i) for i in where)} = "
            f"{int(t.max())} out of range for a {n_pages}-page pool")
    if read_only is None or write_pos is None or block is None:
        return
    ro = set(int(p) for p in read_only)
    if not ro:
        return
    pos = np.asarray(write_pos, np.int64)
    act = (np.ones(t.shape[0], bool) if active is None
           else np.asarray(active).astype(bool))
    for i in range(t.shape[0]):
        if not act[i]:
            continue
        wb = int(pos[i]) // block
        if wb >= t.shape[1]:
            continue  # finished row at its final boundary; evicted next
        page = int(t[i, wb])
        if page in ro:
            raise CorruptBlockTable(
                f"row {i} would WRITE shared (read-only) page {page} at "
                f"block {wb} (pos {int(pos[i])}) — copy-on-write requires "
                "the first partially-filled block to be private "
                "(serving/prefix_cache.py module docstring)")


def init_paged_kv_cache(cfg: TransformerConfig, n_pages: int, block: int,
                        num_heads: int | None = None):
    """Zeroed paged cache pytree: {"kv"} — a per-layer tuple of packed
    [n_pages + 1, H, block, 2*Dh] page pools (same lane packing and
    per-layer-leaf rationale as ``init_kv_cache``). The +1 page is the
    kernel's reserved write scratch: non-final grid steps steer their
    output flush there (ops/decode_attention._paged_decode_kernel), so
    it must never appear in a block table."""
    h = num_heads if num_heads is not None else cfg.num_heads
    shape = (n_pages + 1, h, block, 2 * cfg.d_head)
    return {
        "kv": tuple(jnp.zeros(shape, cfg.cdtype) for _ in range(cfg.num_layers)),
    }


def _resolve_impl(impl: str, attend: int, d: int, itemsize: int) -> str:
    """Serving-kernel choice: "auto" = the fused Pallas update+attend
    kernel on TPU (falls back to "xla" beyond its VMEM slab plan),
    "pallas"/"xla" force. NOT TransformerConfig.attn_impl (that steers the
    training/prefill attention op)."""
    if impl not in ("auto", "pallas", "xla"):
        raise ValueError(
            f"unknown decode attention impl: {impl!r} (want 'auto', "
            "'pallas' or 'xla' — this is the serving-kernel choice, not "
            "TransformerConfig.attn_impl)"
        )
    if impl == "auto":
        from cs336_systems_tpu.ops import decode_attention as da

        # the kernel also needs an 8-row-aligned attended prefix (its
        # write-back tile) — non-multiple-of-8 lengths take the xla path
        fits = attend % 8 == 0 and da.supported(attend, d, itemsize)
        impl = "pallas" if fits and jax.default_backend() == "tpu" else "xla"
    return impl


def _attend_update_xla(q, kv_cache, k_new, v_new, pos,
                       window: int | None = None,
                       attend_len: int | None = None):
    """Portable update+attend on the packed cache: write the packed new
    column with a dynamic-update-slice, then the shared masked-softmax op
    (ops/attention.py — the oracle the Pallas kernel is tested against)
    over the filled prefix. Mask rows j <= pos; with ``window`` set the
    mask additionally requires ``pos - j < window``, matching
    ``ops.attention.banded_causal_mask`` row ``pos`` so cached decoding
    agrees with the uncached ``generate`` numerics.

    ``attend_len``: STATIC bound on the filled length (pos < attend_len);
    only that prefix is read. Decode is HBM-bound (the cache is the
    dominant per-token traffic at serving batch), so not touching the
    unfilled tail is a bandwidth saving proportional to 1 − fill/S_max.
    The lane-unpack slices here COPY k/v — fine for CPU tests and the
    long-prefix fallback; the TPU serving path is the fused kernel.

    ``pos`` may be [B] per-batch-row positions (ragged serving): each row
    then writes its own column (a masked where — the dynamic-update-slice
    form needs one shared offset) and masks its own prefix."""
    from cs336_systems_tpu.ops.attention import attention_with_lse
    from cs336_systems_tpu.ops.decode_attention import pack_kv

    d = q.shape[-1]
    packed = pack_kv(k_new, v_new)  # [B, H, 1, 2*Dh]
    if pos.ndim == 1:
        # per-row start indices: vmap the one-column DUS over batch rows —
        # a masked full-cache where() would turn the O(column) write into
        # O(S) on exactly the long-prefix fallback path where S is largest
        kv_cache = jax.vmap(
            lambda c, p_, col: jax.lax.dynamic_update_slice(c, col, (0, p_, 0))
        )(kv_cache, pos, packed)
    else:
        kv_cache = jax.lax.dynamic_update_slice(
            kv_cache, packed, (0, 0, pos, 0)
        )
    kv_read = kv_cache
    if attend_len is not None and attend_len < kv_read.shape[-2]:
        kv_read = kv_read[:, :, :attend_len]
    s = kv_read.shape[-2]
    idx = jnp.arange(s)
    if pos.ndim == 1:
        mask = idx[None, :] <= pos[:, None]  # [B, S]
        if window is not None:
            mask &= pos[:, None] - idx[None, :] < window
        mask = mask[:, None, None, :]
    else:
        mask = idx <= pos
        if window is not None:
            mask &= pos - idx < window
        mask = mask[None, :]
    o = attention_with_lse(
        q, kv_read[..., :d], kv_read[..., d:], mask
    )[0]
    return o, kv_cache


def _resolve_impl_paged(impl: str, block: int, d: int, itemsize: int) -> str:
    """Paged counterpart of ``_resolve_impl``: "auto" picks the paged
    Pallas kernel on TPU when the page geometry fits its VMEM plan."""
    if impl not in ("auto", "pallas", "xla"):
        raise ValueError(
            f"unknown decode attention impl: {impl!r} (want 'auto', "
            "'pallas' or 'xla' — this is the serving-kernel choice, not "
            "TransformerConfig.attn_impl)"
        )
    if impl == "auto":
        from cs336_systems_tpu.ops import decode_attention as da

        fits = da.paged_supported(block, d, itemsize)
        impl = "pallas" if fits and jax.default_backend() == "tpu" else "xla"
    return impl


def _attend_update_xla_paged(q, kv_pool, k_new, v_new, pos, tables,
                             block: int, window: int | None = None,
                             active=None):
    """Portable update+attend on the PAGED pool — the oracle the paged
    Pallas kernel is tested against, and the CPU/fallback serving path.
    Scatters each row's packed new column into its current page, gathers
    the row's pages back into a contiguous [B, H, nb*block, W] view, and
    runs the shared masked-softmax op with mask ``j <= pos_i`` — the same
    write-then-attend order as ``_attend_update_xla``, so paged and
    unpaged XLA decoding are BIT-IDENTICAL: every attended column holds
    the same value in both layouts and the clamped/duplicate page columns
    are masked to exact softmax zeros. The gather materializes the
    contiguous view (fine for CPU tests); the TPU path is the kernel,
    which never does.

    ``active``: optional [B] mask (serving-engine slot batches) — an
    inactive row's column write is steered to the pool's reserved scratch
    page (the LAST pool page, never in any table) so its real pages stay
    untouched; its attention output is garbage the engine discards. Same
    semantics as the Pallas kernel's steered write-back tile."""
    from cs336_systems_tpu.ops.attention import attention_with_lse
    from cs336_systems_tpu.ops.decode_attention import pack_kv

    b, h, _, d = q.shape
    nb = tables.shape[1]
    packed = pack_kv(k_new, v_new)[:, :, 0]  # [B, H, W]
    page = jnp.take_along_axis(tables, (pos // block)[:, None], axis=1)[:, 0]
    if active is not None:
        page = jnp.where(jnp.asarray(active, bool), page,
                         kv_pool.shape[0] - 1)
    row = pos % block
    kv_pool = kv_pool.at[page, :, row, :].set(packed)
    gathered = kv_pool[tables]  # [B, nb, H, block, W]
    kv = gathered.transpose(0, 2, 1, 3, 4).reshape(b, h, nb * block, 2 * d)
    idx = jnp.arange(nb * block)
    mask = idx[None, :] <= pos[:, None]
    if window is not None:
        mask &= pos[:, None] - idx[None, :] < window
    o = attention_with_lse(
        q, kv[..., :d], kv[..., d:], mask[:, None, None, :]
    )[0]
    return o, kv_pool


def _local_heads(attn_params, cfg: TransformerConfig) -> int:
    """Head count from the q-projection weight's output dim — equals
    cfg.num_heads single-device, and the PER-SHARD head count when the
    block runs inside a tensor-parallel shard_map (parallel/serve.py)
    where the projection weights arrive head-sharded."""
    w = attn_params["q_proj"]["weight"]
    return w.shape[-2] // cfg.d_head


def _decode_block(bp, x, kv, cos, sin, pos, cfg: TransformerConfig,
                  attend_len: int | None = None, attn_impl: str = "auto",
                  reduce_axis: str | None = None, tables=None,
                  page_block: int | None = None, active=None):
    """One block on a single-token hidden state; returns (x, kv').

    ``kv``: this layer's packed [B, H, S, 2*Dh] cache (init_kv_cache).
    The new token's K/V column is written at ``pos`` and attention runs
    over rows <= pos — in ONE fused Pallas kernel on TPU (in-place tile
    write, ops/decode_attention.decode_attention_update), or a
    dynamic-update-slice + the shared masked-softmax op elsewhere.

    ``reduce_axis``: mesh axis to psum the row-parallel matmul outputs
    over — the Megatron f/g pair for head-sharded serving (the attention
    out-projection and the SwiGLU w2 each produce partial sums when their
    input dim is sharded). None single-device.

    ``pos`` scalar (one shared write position) or [B] (ragged serving:
    per-row position → per-row rope angle and attend mask).

    ``page_block``/``tables``: PAGED cache mode — ``kv`` is then the
    layer's [n_pages + 1, H, page_block, 2*Dh] pool (init_paged_kv_cache)
    and ``tables`` its [B, n_blocks] block table; ``pos`` must be [B].
    The fused paged kernel (or its XLA oracle) streams only each row's
    own pages, so a skewed batch pays sum(ceil(len_i/block)) page reads
    instead of B·max — ``attend_len`` does not apply (the table IS the
    per-row bound).

    ``active``: [B] slot mask (serving engine), paged mode only —
    inactive rows' KV writes are steered to the pool's scratch page so
    eviction/join can recycle their pages under the SAME compiled step."""
    b = x.shape[0]
    dh = cfg.d_head
    h = _local_heads(bp["attn"], cfg)
    hsplit = lambda t: t.reshape(b, 1, h, dh).transpose(0, 2, 1, 3)

    with annotate("attn"):
        hx = rmsnorm(bp["ln1"], x)
        q = hsplit(linear(bp["attn"]["q_proj"], hx, cfg.cdtype))
        k = hsplit(linear(bp["attn"]["k_proj"], hx, cfg.cdtype))
        v = hsplit(linear(bp["attn"]["v_proj"], hx, cfg.cdtype))
        # [1] broadcasts over rows; [B,1,1] gives each row its own angle row
        positions = pos[:, None, None] if pos.ndim == 1 else pos[None]
        q = apply_rope(q, cos, sin, positions)
        k = apply_rope(k, cos, sin, positions)

        # "kv_update" nests inside "attn": tracekit's phase precedence
        # checks the inner scope first, so the fused update+attend kernel
        # (and the XLA DUS+softmax fallback) land in kv_update, the
        # projections/rope around it in attn.
        if active is not None and page_block is None:
            raise ValueError(
                "active masks apply to the paged cache only (the steered "
                "scratch write needs the page pool)")
        if page_block is not None:
            impl = _resolve_impl_paged(attn_impl, page_block, dh,
                                       kv.dtype.itemsize)
            if impl == "pallas":
                from cs336_systems_tpu.ops.decode_attention import (
                    paged_decode_attention_update,
                )

                with annotate("kv_update"):
                    attn, kv = paged_decode_attention_update(
                        q, k, v, kv, tables, pos, window=cfg.attn_window,
                        active=active,
                    )
            else:
                with annotate("kv_update"):
                    attn, kv = _attend_update_xla_paged(
                        q, kv, k, v, pos, tables, page_block,
                        cfg.attn_window, active=active,
                    )
        elif _resolve_impl(attn_impl,
                           attend_len if attend_len is not None
                           else kv.shape[-2],
                           dh, kv.dtype.itemsize) == "pallas":
            from cs336_systems_tpu.ops.decode_attention import (
                decode_attention_update,
            )

            with annotate("kv_update"):
                attn, kv = decode_attention_update(
                    q, k, v, kv, pos, window=cfg.attn_window,
                    attend_len=attend_len,
                )
        else:
            with annotate("kv_update"):
                attn, kv = _attend_update_xla(
                    q, kv, k, v, pos, cfg.attn_window, attend_len
                )
        attn = attn.transpose(0, 2, 1, 3).reshape(b, 1, h * dh)
        attn_out = linear(bp["attn"]["output_proj"], attn, cfg.cdtype)
        if reduce_axis is not None:
            attn_out = jax.lax.psum(attn_out, reduce_axis)
    x = x + attn_out
    with annotate("ffn"):
        ffn_out = _ffn(bp["ffn"], rmsnorm(bp["ln2"], x), cfg)
    # The tp reduce applies to the DENSE SwiGLU's row-parallel w2
    # partial sums only: under MoE serving the expert weights are never
    # tp-sharded (replicated, or ep-sharded with _ffn psumming over ep
    # internally), so the ffn output is already tp-replicated and a tp
    # psum here would multiply it by the tp degree.
    if reduce_axis is not None and cfg.num_experts == 0:
        ffn_out = jax.lax.psum(ffn_out, reduce_axis)
    x = x + ffn_out
    return x, kv


def _ffn(ffn_params, x, cfg: TransformerConfig):
    """Dense SwiGLU or MoE, matching the training block's dispatch
    (transformer._block). At inference the MoE aux loss is discarded.

    MoE serving contract (ENFORCED, round 4): decode routing is DROPLESS —
    the per-expert capacity is pinned to the call's token count T, and
    since a token's top-k experts are distinct, no expert can ever receive
    more than T claims, so nothing drops for ANY routing skew. The
    per-call ``moe_capacity`` formula would make a decode step (T = B
    tokens) and the full forward (T = B·S) drop DIFFERENT tokens under
    overflow; serving a learned model should not drop activations at all.
    The training forward may still drop (its capacity_factor semantics),
    so decode == full-forward exactly when the full forward is also
    dropless — tests/test_decode.py pins both the equality and the
    enforced no-drop behavior at a router skewed enough that the old
    per-call capacity WOULD have dropped."""
    if cfg.num_experts > 0:
        from cs336_systems_tpu.models.moe import moe_ffn

        if cfg.moe_ep_axis is not None:
            # EXPERT-SHARDED serving: tokens replicated over the ep axis,
            # expert weights sharded over it, one psum — dropless by the
            # same capacity argument as below (moe_ffn_ep_local docstring;
            # parallel/serve.py builds this config).
            from cs336_systems_tpu.models.moe import moe_ffn_ep_local

            return moe_ffn_ep_local(
                ffn_params, x, cfg.moe_top_k, cfg.cdtype,
                ep_axis=cfg.moe_ep_axis,
            )

        t = x.reshape(-1, x.shape[-1]).shape[0]
        # Serving always routes via an INDEX dispatch: the dense one-hot
        # form builds [T, E, C] dispatch tensors, and at the dropless
        # capacity C = T that is O(T²·E) — a compile-killing blow-up at
        # prefill (T = B·P). The sorted gather path avoids the quadratic
        # one-hot but at C = T still materializes [E·T, D] dispatch rows
        # and [E, T, d_ff] expert hiddens — O(E·T·D) activation memory,
        # E/k× more than the routed work needs (binds MoE *prefill* well
        # before compute at large B·P). "gmm" packs rows tightly
        # (O(T·k·D), dropless by construction) and is the right dispatch
        # when prefill activation memory binds; chip-validated at
        # serving prefill shapes (results/moe_v5e.txt round-5 note:
        # B·P=8192 logits agree with sorted to bf16 dot-order). It stays
        # opt-in via cfg.moe_dispatch pending a trained-model token A/B.
        dispatch = "gmm" if cfg.moe_dispatch == "gmm" else "sorted"
        out, _aux = moe_ffn(
            ffn_params, x, cfg.moe_top_k, cfg.moe_capacity_factor, cfg.cdtype,
            dispatch=dispatch,  # dp_axis never applies at decode
            capacity=t,  # dropless: see docstring
        )
        return out
    return swiglu(ffn_params, x, cfg.cdtype)


def decode_step(params, cache, pos, token_ids, cfg: TransformerConfig,
                attend_len: int | None = None, attn_impl: str = "auto",
                reduce_axis: str | None = None, tables=None,
                page_block: int | None = None, active=None):
    """One incremental step: token_ids [B] at position ``pos`` (scalar
    int32, or [B] per-row positions for ragged serving)
    → (logits [B, vocab] fp32, updated cache).

    ``page_block``/``tables``: paged-cache mode — ``cache`` holds page
    pools and each row attends only its own pages (see _decode_block).
    ``active``: [B] slot mask for the serving engine's fixed-capacity
    slot batch (paged mode only): inactive rows run through the step as
    dead weight — their KV writes land on the pool's scratch page and
    their logits are garbage — so join/evict never changes the compiled
    executable, only host-side tables.

    ``attend_len``: static bound on the filled cache length (pos <
    attend_len); attention reads only that prefix — see
    ``_decode_block``. ``params["blocks"]`` may be the stacked
    [L, ...]-leaf pytree (the training layout) or a tuple of per-layer
    pytrees (``unstack_blocks``) — inside the generation scan the caller
    unstacks ONCE so the per-layer slices are loop-invariant; left stacked,
    XLA re-materializes every block's weight slices each token (~141
    slice DMAs/token traced at b32, scripts/trace_decode_step.py)."""
    pos = jnp.asarray(pos, jnp.int32)
    cos, sin = rope_cache(cfg.context_length, cfg.d_head, cfg.rope_theta)
    x = embedding(params["token_embeddings"], token_ids[:, None], cfg.cdtype)

    # Unrolled layer loop over per-layer cache leaves (see init_kv_cache):
    # each layer's one-tile cache update aliases in place.
    blocks = params["blocks"]
    stacked = not isinstance(blocks, (tuple, list))
    kvs = []
    for l in range(cfg.num_layers):
        bp = (
            jax.tree_util.tree_map(lambda a: a[l], blocks) if stacked
            else blocks[l]
        )
        x, kv = _decode_block(
            bp, x, cache["kv"][l], cos, sin, pos, cfg,
            attend_len, attn_impl, reduce_axis, tables, page_block,
            active,
        )
        kvs.append(kv)
    x = rmsnorm(params["ln_final"], x)
    logits = linear(params["lm_head"], x, cfg.cdtype)[:, 0]
    return logits.astype(jnp.float32), {"kv": tuple(kvs)}


def prefill(params, prompt_ids, cfg: TransformerConfig, max_len: int | None = None,
            reduce_axis: str | None = None, prompt_lens=None,
            page_block: int | None = None, page_geom=None):
    """Fill the cache with ONE batched forward over the whole prompt (full
    MXU tiles, causal attention), capturing each layer's post-RoPE K/V into
    the cache — identical values to stepwise decoding, since projections
    are position-independent.

    prompt_ids: [B, P] (P <= context window). Returns (last-token logits
    [B, vocab] fp32, cache, next position P). ``reduce_axis``: psum axis
    for head-sharded serving (see _decode_block) — the cache then holds
    this shard's heads only.

    ``prompt_lens``: [B] int32 per-row prompt lengths (ragged serving).
    Rows are LEFT-ALIGNED: row i's tokens sit at positions [0, len_i) and
    the tail is padding (any token id). Positions are absolute, so the
    shared arange rope and the plain causal mask are already per-row
    correct — a real token p < len_i only ever attends real tokens
    j <= p. Pad positions run through the forward and deposit junk K/V in
    rows [len_i, P), but decoding overwrites them one per step and masks
    j <= pos_i until it does, so they are never attended. The returned
    logits come from each row's LAST REAL token (len_i − 1) and the next
    position is the [B] vector ``prompt_lens``.

    ``page_block``/``page_geom``: PAGED cache — the prompt K/V is laid
    out into a per-layer page pool instead of the contiguous cache.
    ``page_geom`` is the (tables, page_rows, page_blks) triple from
    ``paged_kv_geometry``; the pool is built by reshaping the packed
    prompt into page-shaped slabs and ONE gather over the page axis — no
    [B, max_len] intermediate, so prefill peak stays at the pool size."""
    b, plen = prompt_ids.shape
    dh = cfg.d_head
    blocks = params["blocks"]  # stacked [L, ...] leaves (scan below)
    h = _local_heads(blocks["attn"], cfg)
    cache = None if page_block is not None else init_kv_cache(
        cfg, b, max_len, num_heads=h)
    cos, sin = rope_cache(cfg.context_length, cfg.d_head, cfg.rope_theta)
    positions = jnp.arange(plen)

    from cs336_systems_tpu.ops.attention import (
        attention_with_lse,
        banded_causal_mask,
        causal_mask,
    )

    x = embedding(params["token_embeddings"], prompt_ids, cfg.cdtype)
    if cfg.attn_window is not None:
        mask = banded_causal_mask(plen, plen, cfg.attn_window)
    else:
        mask = causal_mask(plen, plen)

    def body(carry, bp):
        x = carry
        with annotate("attn"):
            hsplit = lambda t: t.reshape(b, plen, h, dh).transpose(0, 2, 1, 3)
            hx = rmsnorm(bp["ln1"], x)
            q = hsplit(linear(bp["attn"]["q_proj"], hx, cfg.cdtype))
            k = hsplit(linear(bp["attn"]["k_proj"], hx, cfg.cdtype))
            v = hsplit(linear(bp["attn"]["v_proj"], hx, cfg.cdtype))
            q = apply_rope(q, cos, sin, positions)
            k = apply_rope(k, cos, sin, positions)
            attn = attention_with_lse(q, k, v, mask)[0]
            attn = attn.transpose(0, 2, 1, 3).reshape(b, plen, h * dh)
            attn_out = linear(bp["attn"]["output_proj"], attn, cfg.cdtype)
            if reduce_axis is not None:
                attn_out = jax.lax.psum(attn_out, reduce_axis)
        x = x + attn_out
        with annotate("ffn"):
            ffn_out = _ffn(bp["ffn"], rmsnorm(bp["ln2"], x), cfg)
        # same tp/ep reduce split as _decode_block: MoE ffn output is
        # never tp-sharded (ep-psum'd internally or replicated)
        if reduce_axis is not None and cfg.num_experts == 0:
            ffn_out = jax.lax.psum(ffn_out, reduce_axis)
        x = x + ffn_out
        return x, (k, v)

    x, (ks, vs) = jax.lax.scan(body, x, blocks)
    x = rmsnorm(params["ln_final"], x)
    if prompt_lens is None:
        logits = linear(params["lm_head"], x[:, -1:], cfg.cdtype)[:, 0]
        nxt = plen
    else:
        # gather each row's last REAL hidden state BEFORE the lm_head so
        # the vocab matmul is [B, 1, d], not [B, P, V] (take_along_axis on
        # the dot output would block XLA's slice-into-dot simplification)
        lens = jnp.asarray(prompt_lens, jnp.int32)
        x_last = jnp.take_along_axis(x, (lens - 1)[:, None, None], axis=1)
        logits = linear(params["lm_head"], x_last, cfg.cdtype)[:, 0]
        nxt = lens
    logits = logits.astype(jnp.float32)

    # write each layer's packed [B, H, P, 2*Dh] prompt K/V into its cache
    # prefix (one-time cost at prefill; per-layer leaves — init_kv_cache)
    from cs336_systems_tpu.ops.decode_attention import pack_kv

    if page_block is not None:
        _tables, page_rows, page_blks = page_geom
        blk = page_block
        nbp = -(-plen // blk)  # prompt blocks per row
        pad = nbp * blk - plen
        # Source page s of the pool is (row page_rows[s], block
        # page_blks[s]); blocks past the padded prompt (decode-growth
        # pages) clamp to the row's last prompt block — junk data beyond
        # every len_i, never attended, overwritten as decode fills them.
        src = page_rows * nbp + jnp.minimum(page_blks, nbp - 1)
        with annotate("kv_update"):
            kv = []
            for l in range(cfg.num_layers):
                packed = pack_kv(ks[l], vs[l])  # [B, H, P, W]
                if pad:
                    packed = jnp.pad(
                        packed, ((0, 0), (0, 0), (0, pad), (0, 0)))
                src_pages = packed.reshape(
                    b, h, nbp, blk, 2 * dh).transpose(0, 2, 1, 3, 4)
                src_pages = src_pages.reshape(b * nbp, h, blk, 2 * dh)
                pool = jnp.concatenate(
                    [src_pages[src],
                     jnp.zeros((1, h, blk, 2 * dh), cfg.cdtype)], axis=0)
                kv.append(pool)
            cache = {"kv": tuple(kv)}
        if prompt_lens is None:
            nxt = jnp.full((b,), plen, jnp.int32)  # paged pos is per-row
    else:
        with annotate("kv_update"):
            cache = {
                "kv": tuple(
                    jax.lax.dynamic_update_slice(
                        c, pack_kv(ks[l], vs[l]), (0, 0, 0, 0)
                    )
                    for l, c in enumerate(cache["kv"])
                ),
            }
    return logits, cache, nxt


def slot_prefill(params, prompt_ids, cfg: TransformerConfig, prompt_lens,
                 page_block: int, page_geom, reduce_axis: str | None = None):
    """Prefill entry point for serving-engine JOINS: run the ragged paged
    prefill over a join batch and hand back the page contents for the
    engine to scatter into its long-lived pool.

    ``page_geom`` is the (tables, page_rows, page_blks) triple of a LOCAL
    throwaway geometry covering only the join batch's prompt blocks (the
    tables element is unused by prefill and may be None). Returns
    (last-real-token logits [B, vocab] fp32, per-layer tuple of
    [n_pages, H, block, 2*Dh] page arrays laid out by that geometry —
    the local scratch page already dropped — next positions [B] int32).
    The engine scatters the page arrays at its allocator-assigned ids;
    row-local numerics make the result independent of how the join batch
    was composed (pinned by tests/test_serving_engine.py)."""
    logits, cache, nxt = prefill(
        params, prompt_ids, cfg, reduce_axis=reduce_axis,
        prompt_lens=prompt_lens, page_block=page_block, page_geom=page_geom)
    pages = tuple(kv[:-1] for kv in cache["kv"])  # drop the local scratch
    return logits, pages, nxt


def prefill_suffix(params, suffix_ids, cfg: TransformerConfig, suffix_lens,
                   prefix_lens, prefix_tables, kv_pool, page_block: int,
                   page_geom, reduce_axis: str | None = None):
    """Prefill ONLY the uncached suffix of each row, attending the cached
    prefix KV straight out of the paged pool (the prefix-cache reuse path
    — serving/prefix_cache.py).

    The cached pages hold exactly the post-RoPE K‖V the full prefill
    would have produced for those positions (``prefill`` captures each
    layer's post-rope k/v), so running the suffix tokens at their
    ABSOLUTE positions against the gathered prefix keys reproduces the
    full-prompt forward bit-for-bit: rope tables, causal structure and
    softmax operand sets are identical, and masked pad keys contribute
    exact zeros.

    ``suffix_ids``: [B, SW] LEFT-ALIGNED suffix tokens, row i's real
    tokens in [0, suffix_lens_i); ``prefix_lens``: [B] int32 cached-
    prefix lengths, each a MULTIPLE of ``page_block`` (the cache only
    publishes full blocks) — row i's suffix token j sits at absolute
    position prefix_lens_i + j. ``prefix_tables``: [B, PNB] page ids
    into ``kv_pool`` covering each row's prefix blocks in order, padded
    past prefix_lens_i // block with ANY valid pool index (the mask
    retires them; the engine pads with the scratch page). ``kv_pool``:
    per-layer tuple of [n_pages + 1, H, block, 2*Dh] pool arrays — READ
    only, shared pages are never written here. ``page_geom``:
    (ignored, page_rows, page_blks) local throwaway geometry over the
    SUFFIX blocks only, exactly ``slot_prefill``'s convention.

    Returns (last-real-suffix-token logits [B, vocab] fp32, per-layer
    suffix page arrays laid out by ``page_geom`` — local scratch already
    dropped — next positions prefix_lens + suffix_lens [B] int32). The
    layer loop is UNROLLED (not scanned) so each layer reads its own
    pool leaf without stacking the pool into an [L, ...] copy.

    The ``optimization_barrier`` calls are LOAD-BEARING for the
    bit-exactness contract: the gather+concat attention operands invite
    fusions the full prefill never sees, and on CPU a fusion boundary
    can flip an op to FMA codegen — observed as 1-ulp drift on k after
    rope at some batch shapes, which sampling then amplifies into a
    divergent stream. Pinning materialization at the q/k/v, attention
    and residual boundaries makes every segment compute from
    materialized inputs, which measurably reproduces the full prefill's
    values bit-for-bit (tests/test_prefix_cache.py pins this engine-
    level; padding rows past suffix_lens still hold junk — never
    attended, overwritten by decode one row per step)."""
    b, sw = suffix_ids.shape
    dh, blk = cfg.d_head, page_block
    blocks = params["blocks"]
    h = _local_heads(blocks["attn"], cfg)
    if isinstance(blocks, (tuple, list)):
        per_layer = blocks
    else:
        per_layer = tuple(
            jax.tree_util.tree_map(lambda a, l=l: a[l], blocks)
            for l in range(cfg.num_layers))
    cos, sin = rope_cache(cfg.context_length, cfg.d_head, cfg.rope_theta)

    from cs336_systems_tpu.ops.attention import attention_with_lse
    from cs336_systems_tpu.ops.decode_attention import pack_kv

    slens = jnp.asarray(suffix_lens, jnp.int32)
    plens = jnp.asarray(prefix_lens, jnp.int32)
    tables = jnp.asarray(prefix_tables, jnp.int32)
    pnb = tables.shape[1]
    pn = pnb * blk  # gathered prefix key width

    # absolute positions: queries at prefix_lens + [0, SW); prefix keys
    # at [0, pn) (block-aligned, so gathered block j covers exactly
    # [j*blk, (j+1)*blk)); mask validity per row by the real lengths
    qpos = plens[:, None] + jnp.arange(sw)[None, :]          # [B, SW]
    kpos = jnp.concatenate(
        [jnp.broadcast_to(jnp.arange(pn)[None, :], (b, pn)), qpos], axis=1)
    kvalid = jnp.concatenate(
        [jnp.arange(pn)[None, :] < plens[:, None],
         jnp.arange(sw)[None, :] < slens[:, None]], axis=1)   # [B, pn+SW]
    mask = (kpos[:, None, :] <= qpos[:, :, None]) & kvalid[:, None, :]
    if cfg.attn_window is not None:
        mask &= (qpos[:, :, None] - kpos[:, None, :]) < cfg.attn_window
    mask = mask[:, None]  # [B, 1, SW, pn+SW] — broadcasts over heads

    x = embedding(params["token_embeddings"], suffix_ids, cfg.cdtype)
    ks, vs = [], []
    for bp, pool_l in zip(per_layer, kv_pool):
        with annotate("attn"):
            hsplit = lambda t: t.reshape(b, sw, h, dh).transpose(0, 2, 1, 3)
            hx = rmsnorm(bp["ln1"], x)
            q = hsplit(linear(bp["attn"]["q_proj"], hx, cfg.cdtype))
            k = hsplit(linear(bp["attn"]["k_proj"], hx, cfg.cdtype))
            v = hsplit(linear(bp["attn"]["v_proj"], hx, cfg.cdtype))
            q = apply_rope(q, cos, sin, qpos[:, None, :])
            k = apply_rope(k, cos, sin, qpos[:, None, :])
            q, k, v = jax.lax.optimization_barrier((q, k, v))
            # cached prefix K/V: gather the rows' pages and unpack —
            # [B, PNB, H, blk, W] -> [B, H, pn, W]; post-rope already
            pkv = pool_l[tables].transpose(0, 2, 1, 3, 4).reshape(
                b, h, pn, 2 * dh)
            k_all = jnp.concatenate([pkv[..., :dh], k], axis=2)
            v_all = jnp.concatenate([pkv[..., dh:], v], axis=2)
            attn = attention_with_lse(q, k_all, v_all, mask)[0]
            attn = jax.lax.optimization_barrier(attn)
            attn = attn.transpose(0, 2, 1, 3).reshape(b, sw, h * dh)
            attn_out = linear(bp["attn"]["output_proj"], attn, cfg.cdtype)
            if reduce_axis is not None:
                attn_out = jax.lax.psum(attn_out, reduce_axis)
        x = jax.lax.optimization_barrier(x + attn_out)
        with annotate("ffn"):
            ffn_out = _ffn(bp["ffn"], rmsnorm(bp["ln2"], x), cfg)
        if reduce_axis is not None and cfg.num_experts == 0:
            ffn_out = jax.lax.psum(ffn_out, reduce_axis)
        x = jax.lax.optimization_barrier(x + ffn_out)
        ks.append(k)
        vs.append(v)

    x = rmsnorm(params["ln_final"], x)
    x_last = jnp.take_along_axis(x, (slens - 1)[:, None, None], axis=1)
    logits = linear(params["lm_head"], x_last, cfg.cdtype)[:, 0]
    logits = logits.astype(jnp.float32)

    # lay the SUFFIX K/V out into page_geom's pages — the suffix starts
    # block-aligned, so the per-row packing is prefill's paged branch
    # verbatim over [B, SW]
    _tables, page_rows, page_blks = page_geom
    nbp = -(-sw // blk)
    pad = nbp * blk - sw
    src = page_rows * nbp + jnp.minimum(page_blks, nbp - 1)
    with annotate("kv_update"):
        pages = []
        for l in range(cfg.num_layers):
            packed = pack_kv(ks[l], vs[l])  # [B, H, SW, W]
            if pad:
                packed = jnp.pad(packed, ((0, 0), (0, 0), (0, pad), (0, 0)))
            src_pages = packed.reshape(
                b, h, nbp, blk, 2 * dh).transpose(0, 2, 1, 3, 4)
            pages.append(src_pages.reshape(b * nbp, h, blk, 2 * dh)[src])
    return logits, tuple(pages), plens + slens


def prefill_chunk(params, chunk_ids, cfg: TransformerConfig, chunk_lens,
                  done_lens, page_tables, kv_pool, page_block: int,
                  page_geom, reduce_axis: str | None = None):
    """Prefill the NEXT chunk of each row's prompt against everything
    already landed in the pool (chunked prefill, ISSUE 15).

    A chunk IS a suffix prefill whose "prefix" is the portion of the
    prompt that has already landed — prefix-cache hit pages plus every
    earlier chunk's private pages, in block order. ``prefill_suffix``'s
    offset machinery is exactly this computation (the first chunk of a
    cache-miss prompt is a suffix prefill at offset 0), so this is a
    documented delegation, not a new program: ``chunk_ids`` [B, CW] are
    the next ``chunk_lens`` prompt tokens, ``done_lens`` [B] the
    absolute token counts already landed (each a MULTIPLE of
    ``page_block`` — the engine only dispatches block-aligned chunk
    boundaries; only a prompt's FINAL chunk may be ragged, and then the
    row leaves the chunk path), and ``page_tables`` [B, PNB] the landed
    pages covering ``done_lens`` blocks. Returns ``prefill_suffix``'s
    triple: the chunk's boundary logits (the final chunk's row is the
    join logits ``slot_prefill`` would have produced), the chunk's page
    contents laid out by ``page_geom``, and the advanced positions.

    Bit-exactness is inherited, not re-argued: the gathered landed keys
    equal the full prefill's post-rope K‖V at those positions, masked
    pads contribute exact zeros, and the pinned ``optimization_barrier``
    boundaries make each chunk compute from materialized inputs — so
    chunking changes WHEN prefill compute runs, never its result
    (tests/test_chunked_prefill.py pins the engine-level stream)."""
    return prefill_suffix(
        params, chunk_ids, cfg, chunk_lens, done_lens, page_tables,
        kv_pool, page_block, page_geom, reduce_axis=reduce_axis)


def unstack_blocks(params):
    """Stacked [L, ...]-leaf block params → a tuple of per-layer pytrees.

    Done ONCE outside the decode scan so the per-layer weight slices are
    loop-invariant: left inside the scan body, XLA declines to hoist them
    (traced ~141 slice DMAs/token at b32 — every block leaf re-sliced per
    token, ~131 us/token of pure DMA).

    NEGATIVE RESULT (round 4, do not relearn): fusing the q/k/v weights
    into one [3·H·Dh, d] matmul and the SwiGLU gate/up pair into
    [2·d_ff, d] — stacked HERE, outside the scan, so the concat is
    loop-invariant (unlike the training-side qkv_fused negative) — still
    REGRESSED decode device time 1070 → 1184 us/token (exact, b32,
    traced). The per-head weight slabs of the separate projections are
    prefetch-overlapped by XLA (the trace's slice-done lanes run under
    compute); one big fused weight becomes a synchronous operand read
    (~HBM-roofline 6.4 us inside the conv op) and the launches it saves
    were already hidden."""
    blocks = params["blocks"]
    if isinstance(blocks, (tuple, list)):
        return params
    n = jax.tree_util.tree_leaves(blocks)[0].shape[0]
    out = dict(params)
    out["blocks"] = tuple(
        jax.tree_util.tree_map(lambda a: a[l], blocks) for l in range(n)
    )
    return out


def _sample(logits, key, temperature: float, top_k: int | None,
            top_p: float | None = None, approx_top_k: bool = False,
            row_key_offset=None):
    """Reference sampling semantics (model.py:292-303): temperature scale,
    top-k threshold mask, categorical draw — plus nucleus top-p filtering
    (beyond parity; transformer.top_p_filter).

    ``approx_top_k``: compute the top-k threshold with the TPU-native
    partial reduction (``jax.lax.approx_max_k``) instead of exact top-k —
    the exact form lowers to a full vocab sort (traced: 293 us/token at
    b32, 14% of decode device time; approx measured 14 us on chip, 19x).
    The approximate set can MISS true top-k elements (recall ~0.95), so
    its minimum — the threshold — sits at or BELOW the exact k-th logit:
    the mask then retains the full exact candidate set plus at most a few
    extra tail candidates (a superset; slightly more diversity, never
    less). Off by default (exact reference semantics).

    ``row_key_offset``: when set (traced int32), draw each row from its
    OWN key ``fold_in(key, offset + row)`` instead of one key over the
    whole [B, V] block. One shared key makes row i's Gumbel noise depend
    on the batch SHAPE, so a batch-sharded server could never reproduce
    the single-device draws; row-keyed streams depend only on each row's
    global index — what makes sharded serving (parallel/serve.py)
    bit-identical to the single-device path. A [B] VECTOR offset gives
    each row its global index directly (the serving engine's slot
    batches, where slot order is arbitrary), and ``key`` may then be a
    [B, 2] PER-ROW key batch (each slot carries its own per-request key
    chain) — fold_in is vmapped over both."""
    with annotate("sampling"):
        logits = logits / temperature
        if top_k is not None:
            k = min(top_k, logits.shape[-1])
            if approx_top_k:
                kth = jax.lax.approx_max_k(logits, k)[0][..., -1:]
            else:
                kth = jax.lax.top_k(logits, k)[0][..., -1:]
            logits = jnp.where(logits < kth, -jnp.inf, logits)
        if top_p is not None:
            logits = top_p_filter(logits, top_p)
        if row_key_offset is not None:
            off = jnp.asarray(row_key_offset, jnp.int32)
            if off.ndim == 1:
                rows = off  # per-row global indices (engine slot batch)
            else:
                rows = jnp.arange(logits.shape[0], dtype=jnp.int32) + off
            if key.ndim == 2:  # per-row key chains (engine slot batch)
                keys = jax.vmap(jax.random.fold_in)(key, rows)
            else:
                keys = jax.vmap(lambda r: jax.random.fold_in(key, r))(rows)
            return jax.vmap(
                lambda k_, l: jax.random.categorical(k_, l, axis=-1)
            )(keys, logits)
        return jax.random.categorical(key, logits, axis=-1)


def _check_prompt_lens(prompt_lens, ids_shape) -> jax.Array:
    """Host-side shape AND range validation for per-row prompt lengths:
    out-of-range rows would not error downstream — a 0 makes the prefill
    logit gather wrap to the last pad column, a length beyond the padded
    width decodes from never-written cache rows — both plausible-looking
    garbage, so they must be rejected at the entry point.

    Callers on a hot path should pass a HOST (numpy/list) array: a fresh
    device array costs one blocking device_get here per call (~the
    dispatch floor on remote runtimes); a REUSED device array only pays
    it once (jax caches the fetched host value on the array)."""
    import numpy as np

    lens_np = np.asarray(prompt_lens)
    if not np.issubdtype(lens_np.dtype, np.integer):
        raise ValueError(
            f"prompt_lens must be integers, got dtype {lens_np.dtype} "
            "(silent truncation would shift row boundaries)"
        )
    if lens_np.shape != (ids_shape[0],):
        raise ValueError(
            f"prompt_lens must be [batch]={ids_shape[0]}, got {lens_np.shape}"
        )
    if lens_np.size and (lens_np.min() < 1 or lens_np.max() > ids_shape[1]):
        raise ValueError(
            f"prompt_lens entries must be in [1, {ids_shape[1]}] (the padded "
            f"prompt width), got range [{lens_np.min()}, {lens_np.max()}]"
        )
    return jnp.asarray(lens_np, jnp.int32)


# The attended cache prefix grows in static buckets of this many rows:
# within one bucket segment the decode scan attends a fixed-length slice,
# and successive segments re-specialize the (tiny) step graph at the next
# length. Keeps every shape static inside ONE jit while making per-token
# HBM traffic scale with fill level instead of S_max.
_ATTEND_BUCKET = 256


def _round_up(n: int, m: int) -> int:
    return ((n + m - 1) // m) * m


@functools.partial(
    jax.jit,
    static_argnames=("cfg", "max_new_tokens", "temperature", "top_k", "top_p",
                     "attn_impl", "approx_top_k", "reduce_axis",
                     "page_block", "eos_token_id"),
)
def _generate_scan(params, prompt_ids, key, cfg, max_new_tokens,
                   temperature, top_k, top_p=None, attn_impl="auto",
                   approx_top_k=False, row_key_offset=None,
                   reduce_axis=None, prompt_lens=None,
                   page_block=None, page_geom=None, eos_token_id=None):
    # ``eos_token_id`` (static): carry a per-row finished mask through the
    # scan. A finished row keeps stepping (the scan is static) but its
    # sampled token is pinned to EOS, and — paged mode — its position
    # FREEZES, so the paged kernel's pos//block early-out stops streaming
    # its pages and its writes just re-stamp the EOS column. The return
    # becomes (tokens [B, T], lengths [B]) where lengths is each row's
    # first-EOS step (max_new_tokens if none): the EXACT truncation the
    # host post-hoc scan computed, now a by-product of the scan carry.
    # Pre-EOS tokens are bit-identical to the eos=None run (the key-split
    # chain and every live row's compute are unchanged). None keeps the
    # old single-output contract (serve-family jaxprs unchanged).
    track_eos = eos_token_id is not None
    b = prompt_ids.shape[0]
    plen = prompt_ids.shape[1]
    total = plen + max_new_tokens
    if track_eos:
        fin0 = jnp.zeros((b,), bool)
        len0 = jnp.full((b,), max_new_tokens, jnp.int32)

    if page_block is not None:
        # PAGED cache: the pool is sized by sum(pages_i) (host geometry,
        # page_geom shapes are static), each row attends only its own
        # pages, and decode positions are per-row — so there is no
        # batch-global attend bound to bucket: ONE scan covers the whole
        # generation and per-token KV traffic tracks each row's fill.
        if prompt_lens is None:
            prompt_lens = jnp.full((prompt_ids.shape[0],), plen, jnp.int32)
        tables = jnp.asarray(page_geom[0], jnp.int32)
        logits, cache, pos = prefill(params, prompt_ids, cfg,
                                     reduce_axis=reduce_axis,
                                     prompt_lens=prompt_lens,
                                     page_block=page_block,
                                     page_geom=page_geom)
        params = unstack_blocks(params)

        def body(carry, i):
            if track_eos:
                cache, pos, logits, key, fin, flen = carry
            else:
                cache, pos, logits, key = carry
            key, sub = jax.random.split(key)
            nxt = _sample(logits, sub, temperature, top_k, top_p,
                          approx_top_k, row_key_offset).astype(jnp.int32)
            if track_eos:
                nxt = jnp.where(fin, eos_token_id, nxt)
                just = jnp.logical_and(~fin, nxt == eos_token_id)
                flen = jnp.where(just, i, flen)
                fin = fin | just
            new_logits, cache = decode_step(params, cache, pos, nxt, cfg,
                                            None, attn_impl, reduce_axis,
                                            tables, page_block)
            if track_eos:
                # freeze finished rows' positions: their page stream stops
                # growing (real DMA saving through the kernel's early-out)
                # and their write re-stamps the same column each step
                pos2 = jnp.where(fin, pos, pos + 1)
                return (cache, pos2, new_logits, key, fin, flen), nxt
            return (cache, pos + 1, new_logits, key), nxt

        carry = (cache, jnp.asarray(pos, jnp.int32), logits, key)
        if max_new_tokens == 0:
            tokens = jnp.zeros((b, 0), jnp.int32)
            return (tokens, jnp.zeros((b,), jnp.int32)) if track_eos \
                else tokens
        if track_eos:
            carry = carry + (fin0, len0)
            final, tokens = jax.lax.scan(
                body, carry, jnp.arange(max_new_tokens, dtype=jnp.int32))
            return tokens.T, final[5]  # [B, T], first-EOS steps
        _, tokens = jax.lax.scan(
            body, carry, jnp.arange(max_new_tokens, dtype=jnp.int32))
        return tokens.T  # [B, T]

    # Right-size the cache to this generation (bucket-rounded): decode is
    # cache-bandwidth-bound, so allocating context_length rows and
    # attending over them costs real ms/token when prompt+new << ctx.
    # Ragged batches size by the LONGEST row (plen is the padded width);
    # shorter rows mask the difference away per step.
    alloc = min(_round_up(total, _ATTEND_BUCKET), cfg.context_length)
    logits, cache, pos = prefill(params, prompt_ids, cfg, max_len=alloc,
                                 reduce_axis=reduce_axis,
                                 prompt_lens=prompt_lens)
    params = unstack_blocks(params)  # loop-invariant per-layer slices

    def step(attend_len):
        def body(carry, i):
            if track_eos:
                cache, pos, logits, key, fin, flen = carry
            else:
                cache, pos, logits, key = carry
            key, sub = jax.random.split(key)
            nxt = _sample(logits, sub, temperature, top_k, top_p,
                          approx_top_k, row_key_offset).astype(jnp.int32)
            if track_eos:
                # the contiguous cache shares one scalar write position
                # across the batch, so finished rows keep advancing (no
                # per-row freeze here — that is the paged branch's win);
                # pinning the fed token to EOS keeps their stream inert
                nxt = jnp.where(fin, eos_token_id, nxt)
                just = jnp.logical_and(~fin, nxt == eos_token_id)
                flen = jnp.where(just, i, flen)
                fin = fin | just
            new_logits, cache = decode_step(params, cache, pos, nxt, cfg,
                                            attend_len, attn_impl,
                                            reduce_axis)
            if track_eos:
                return (cache, pos + 1, new_logits, key, fin, flen), nxt
            return (cache, pos + 1, new_logits, key), nxt

        return body

    # Segment the generation so each scan attends a static bucket-rounded
    # prefix: steps i in [i0, i1) write at pos plen+i and read rows
    # [0, plen+i], so a segment may run while plen+i < attend_len.
    carry = (cache, jnp.asarray(pos, jnp.int32), logits, key)
    if track_eos:
        carry = carry + (fin0, len0)
    chunks = []
    i = 0
    while i < max_new_tokens:
        attend_len = min(_round_up(plen + i + 1, _ATTEND_BUCKET), alloc)
        seg = min(max_new_tokens - i, attend_len - plen - i)
        carry, toks = jax.lax.scan(
            step(attend_len), carry,
            jnp.arange(i, i + seg, dtype=jnp.int32))
        chunks.append(toks)
        i += seg
    if not chunks:  # max_new_tokens == 0: empty generation, as before
        tokens = jnp.zeros((b, 0), jnp.int32)
        return (tokens, jnp.zeros((b,), jnp.int32)) if track_eos else tokens
    tokens = chunks[0] if len(chunks) == 1 else jnp.concatenate(chunks)
    if track_eos:
        return tokens.T, carry[5]  # [B, T], first-EOS steps
    return tokens.T  # [B, T]


def generate_kv(
    params,
    cfg: TransformerConfig,
    prompt_ids,
    max_new_tokens: int,
    key,
    temperature: float = 1.0,
    top_k: int | None = None,
    eos_token_id: int | None = None,
    top_p: float | None = None,
    attn_impl: str = "auto",
    approx_top_k: bool = False,
) -> jax.Array:
    """KV-cached sampling — same contract as ``transformer.generate`` (the
    reference semantics) but one jit for the whole generation. 1-D prompt in
    → 1-D tokens out, truncated at EOS on the host.

    ``attn_impl``: cached-attention kernel ("auto" = the fused Pallas
    decode kernel on TPU, masked-softmax XLA elsewhere — see
    ``_decode_block``). ``approx_top_k``: TPU-native approximate top-k
    threshold instead of the full-sort exact form (see ``_sample``).

    Note: prompt + max_new_tokens must fit the context window (the cache is
    the window); the uncached ``generate`` additionally supports sliding-
    window truncation for longer generations.

    MoE: decode routing is DROPLESS by contract (capacity pinned to the
    call's token count — see ``_ffn``), so cached decoding matches the
    uncached ``generate`` exactly whenever the full forward drops nothing;
    a training-capacity forward that DOES drop diverges from serving by
    design (serving never drops activations).
    """
    ids = jnp.asarray(prompt_ids, jnp.int32)
    if ids.ndim != 1:
        raise ValueError(
            f"generate_kv takes a single 1-D prompt, got shape {ids.shape}; "
            "use generate_kv_batched for [batch, prompt_len] prompts"
        )
    ids = ids[None]
    total = ids.shape[1] + max_new_tokens
    if total > cfg.context_length:
        raise ValueError(
            f"prompt ({ids.shape[1]}) + max_new_tokens ({max_new_tokens}) "
            f"exceeds context_length={cfg.context_length}; use generate() "
            "for sliding-window decoding"
        )
    out = _generate_scan(
        params, ids, key, cfg, max_new_tokens, float(temperature), top_k,
        top_p, attn_impl, approx_top_k, eos_token_id=eos_token_id,
    )
    if eos_token_id is None:
        return out[0]
    tokens, lengths = out  # in-scan EOS tracking (see _generate_scan)
    return tokens[0][: int(jax.device_get(lengths)[0])]


def generate_kv_batched(
    params,
    cfg: TransformerConfig,
    prompt_ids,
    max_new_tokens: int,
    key,
    temperature: float = 1.0,
    top_k: int | None = None,
    eos_token_id: int | None = None,
    top_p: float | None = None,
    attn_impl: str = "auto",
    approx_top_k: bool = False,
    row_keyed: bool = False,
    row_key_offset: int = 0,
    prompt_lens=None,
    page_block: int | None = None,
):
    """Batched KV-cached sampling: ``[B, P]`` prompts → one jit dispatch for
    the whole batch's generation. Decoding is matmul-starved at batch 1
    (one [1, d] row against every weight matrix); batching rows is how the
    MXU earns its keep at serving time — same cache/scan machinery, the
    batch rides the existing leading axis.

    ``row_keyed``: draw each row from fold_in(step_key, row_key_offset +
    row) instead of one key over the block (see ``_sample``) — the stream
    the SHARDED server (parallel/serve.py) reproduces bit-for-bit on any
    mesh; this flag is the single-device reference for its equivalence
    tests. ``row_key_offset`` sets the first row's global index, so a
    single-row call reproduces row i of a larger batch.

    ``prompt_lens``: [B] per-row prompt lengths — RAGGED batches. Rows are
    left-aligned in the [B, P] buffer (row i's tokens in columns
    [0, len_i), tail padding ignored); each row decodes from its own
    position with its own rope angles and attend mask (see ``prefill``),
    so a short prompt's generation matches its own single-row call
    token-for-token instead of absorbing the batch max length.

    ``page_block``: PAGED KV cache — the cache becomes a per-layer page
    pool sized sum(ceil((len_i + new)/block)) pages (paged_kv_geometry)
    instead of B contiguous max-length rows, and each row's decode
    attention streams only its own pages. Composes with ``prompt_lens``
    (without it every row pays the padded width, like the unpaged path);
    the XLA paged path samples BIT-identical tokens to the unpaged one.

    Returns ``[B, max_new_tokens]`` when ``eos_token_id`` is None, else a
    list of per-row arrays truncated at each row's first EOS.
    """
    ids = jnp.asarray(prompt_ids, jnp.int32)
    if ids.ndim != 2:
        raise ValueError(f"prompt_ids must be [batch, prompt_len], got {ids.shape}")
    total = ids.shape[1] + max_new_tokens
    if total > cfg.context_length:
        raise ValueError(
            f"prompt ({ids.shape[1]}) + max_new_tokens ({max_new_tokens}) "
            f"exceeds context_length={cfg.context_length}"
        )
    if row_key_offset and not row_keyed:
        raise ValueError(
            "row_key_offset only applies with row_keyed=True (it sets the "
            "first row's global index in the row-keyed stream)"
        )
    if prompt_lens is not None:
        prompt_lens = _check_prompt_lens(prompt_lens, ids.shape)
    page_geom = None
    if page_block is not None:
        import numpy as np

        lens_np = (np.asarray(jax.device_get(prompt_lens))
                   if prompt_lens is not None
                   else np.full((ids.shape[0],), ids.shape[1]))
        geom = paged_kv_geometry(lens_np, max_new_tokens, page_block)
        validate_block_tables(geom.tables, geom.n_pages)
        page_geom = (jnp.asarray(geom.tables), jnp.asarray(geom.page_rows),
                     jnp.asarray(geom.page_blks))
        if prompt_lens is None:
            prompt_lens = jnp.asarray(lens_np, jnp.int32)
    res = _generate_scan(
        params, ids, key, cfg, max_new_tokens, float(temperature), top_k,
        top_p, attn_impl, approx_top_k,
        row_key_offset=jnp.int32(row_key_offset) if row_keyed else None,
        prompt_lens=prompt_lens,
        page_block=page_block, page_geom=page_geom,
        eos_token_id=eos_token_id,
    )
    if eos_token_id is None:
        return res
    # in-scan EOS: the scan already tracked each row's first-EOS step
    # (finished rows stopped paying paged KV streaming) — truncation is a
    # host slice of the fetched buffer, not a token rescan
    tokens, lengths = res
    toks = jax.device_get(tokens)
    return [row[: int(n)] for row, n in zip(toks, jax.device_get(lengths))]
