"""Pure-functional Transformer LM (pytree params + jit-able apply).

Capability parity with the reference ``BasicsTransformerLM``
(cs336-basics/cs336_basics/model.py:153-327): token embedding → N pre-norm
blocks (causal MHA with RoPE, SwiGLU FFN) → final RMSNorm → LM head, plus
temperature/top-k sampling and the named model-size table from the reference
benchmark driver (cs336_systems/benchmark.py:247-259).

TPU-first design (NOT a port of the nn.Module graph):

- Params are a plain pytree; the apply function is pure, so ``jax.jit``,
  ``jax.grad``, ``shard_map`` and ``jax.checkpoint`` compose for free.
- All N blocks are *stacked* along a leading layer axis. With
  ``scan_layers=True`` they are iterated with ``lax.scan`` — one compiled
  block body regardless of depth, compile time flat. With ``scan_layers=
  False`` the loop is unrolled: more HLO, but the backward reads each
  layer's activations in place instead of stashing them into stacked
  buffers via dynamic-update-slice — measurably faster at small depth.
- ``compute_dtype=bfloat16`` gives mixed precision (MXU-native) while
  params/norms/softmax/CE stay fp32.
- The attention inner op is pluggable: ``xla`` (fused naive), ``flash``
  (Pallas TPU kernel), ``flash_ref`` (portable lax.scan tiling).
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Any

import jax
import jax.numpy as jnp

from cs336_systems_tpu.models.layers import (
    apply_rope,
    embedding,
    init_embedding,
    init_linear,
    init_rmsnorm,
    init_swiglu,
    linear,
    rmsnorm,
    rope_cache,
    swiglu,
)
from cs336_systems_tpu.ops.attention import attention_with_lse, causal_mask


@dataclasses.dataclass(frozen=True)
class TransformerConfig:
    """Static model configuration (hashable: safe as a jit static arg)."""

    vocab_size: int
    context_length: int
    d_model: int
    num_layers: int
    num_heads: int
    d_ff: int
    rope_theta: float = 10000.0
    param_dtype: str = "float32"
    compute_dtype: str = "float32"  # "bfloat16" for mixed precision
    attn_impl: str = "xla"  # "xla" | "flash" | "flash_ref" | "flash_xla" | "ring"
    # Mesh axis names the attention operands' batch / head dims are sharded
    # over (activations [B, H, S, Dh]). When set — and a mesh is passed to
    # the apply fns — the flash attention call runs inside a shard_map over
    # those axes: a pallas_call is an opaque custom call that GSPMD cannot
    # partition (it would gather the operands), so under GSPMD-sharded
    # steps (tensor/expert parallel) the kernel must be given its local
    # block explicitly. The "xla" impl needs neither.
    attn_batch_shard: str | None = None
    attn_head_shard: str | None = None
    # Head-fold layout for the flash kernels' [rows, S, Dh] operands:
    # "hb" (default) projects DIRECTLY into [H·B,S,Dh] via head-batched
    # einsums ("bsd,hed->hbse") — the matmul writes the kernel's layout,
    # so the S<->H transpose never exists; "bh" reshapes [B,S,H,Dh] ->
    # transpose -> [B·H,S,Dh], which XLA materializes as operand-layout
    # copies around the custom calls (measured +3.5% headline throughput
    # for "hb", 123.5k -> 128.0k tok/s — BASELINE.md). Row order is
    # irrelevant to the kernel (rows are independent). The GSPMD-sharded
    # attention paths (tp/ep builders) use "bh" — their shard_map region
    # is specced on the [B, H, S, Dh] axes.
    attn_fold: str = "hb"
    # Fuse the RoPE rotation INTO the flash kernels (rope_cos/rope_sin
    # operands; "hb" fold only): the qkv projections' output feeds the
    # Pallas custom call directly, so the rope interleave's S-minor layout
    # preference — the source of the last ~11.4 ms/step of operand-layout
    # copies (BASELINE.md) — never exists in XLA-land. Numerically
    # equivalent (equivalence-tested); gradients unchanged.
    rope_fused: bool = True
    # Project q/k/v with ONE stacked einsum "bsd,xhed->xhbse" instead of
    # three (q/k/v become contiguous slices of its output). MEASURED
    # NEGATIVE on v5e (BASELINE.md round 3): −12% alone, and it erodes the
    # fused-rope win to +5% (the per-step weight stack + the [3,...] fusion
    # output cost more than three direct matmuls). Kept for the record;
    # default off.
    qkv_fused: bool = False
    # causal sliding-window attention: each query attends its last
    # `attn_window` positions (None = full causal). On the Pallas paths the
    # kernel grids are banded — cost scales with window, not context.
    attn_window: int | None = None
    remat: bool = False  # rematerialise each block in backward
    scan_layers: bool = True  # lax.scan over blocks vs unrolled python loop
    sp_axis: str | None = None  # mesh axis of the sequence shard ("ring" only)
    # Mixture-of-Experts FFN (0 = dense SwiGLU; >0 = that many experts in
    # every block, top-k routed — see models/moe.py)
    num_experts: int = 0
    moe_top_k: int = 2
    moe_capacity_factor: float = 1.25
    moe_aux_weight: float = 0.01  # load-balance aux loss weight in lm_loss
    # "dense" one-hot einsum dispatch, "sorted" gather-both-ways index
    # dispatch, or "sorted_scatter" (the round-3 row-scatter form, kept
    # for A/B — see models/moe.py); "sorted" + moe_dp_axis gives
    # full-batch-consistent routing under data parallelism (set by the DP
    # builder).
    moe_dispatch: str = "dense"
    # Token-sharding axes for globally-consistent routing — a mesh axis
    # name, or a TUPLE of names when the batch shards over several axes
    # (the ep all-to-all step shards tokens over (dp, ep)).
    moe_dp_axis: str | tuple | None = None
    # Expert-parallel all-to-all dispatch axis (parallel/ep.py's indexed
    # step): expert leaves shard over this mesh axis inside a shard_map,
    # tokens travel by explicit all-to-all — see moe._moe_ffn_ep_a2a.
    moe_ep_axis: str | None = None
    # Recompute the expert FFN hidden activations in the backward (the
    # [E, C, d_ff] gate/up stash, the MoE layer's largest) — a selective
    # remat far cheaper than cfg.remat's whole-block recompute; it is what
    # fits the larger sorted-dispatch batches on one chip (moe_v5e.txt).
    moe_ffn_remat: bool = False
    # Chunked fused lm-head + cross-entropy (ops/fused_ce.py): the default
    # loss path in train.lm_loss never materializes the [B, S, V] logits —
    # the forward/backward scan over S-chunks keeps the transient at
    # [B, chunk, V]. None = auto chunk (S/4 clamped to [16, 128]);
    # 0 = DISABLED (legacy full-logits cross_entropy — the lint rule's
    # mutation switch and the parity tests' unchunked oracle); >0 = that
    # many rows per chunk (clamped to S).
    ce_chunk_size: int | None = None
    # Vocab-column-parallel CE (tp/tp_sp set these via their builders):
    # the mesh axis lm_head's vocab dim is sharded over, the batch axes
    # the loss/dW reduce over, and — the tp_sp layout — the mesh axis S
    # is sharded over. Requires a mesh at the lm_loss call.
    ce_vocab_axis: str | None = None
    ce_token_axes: tuple = ()  # batch axes, e.g. ("dp",)
    ce_seq_axis: str | None = None

    def __post_init__(self):
        object.__setattr__(self, "ce_token_axes", tuple(self.ce_token_axes))
        if self.ce_chunk_size is not None and self.ce_chunk_size < 0:
            raise ValueError(
                f"ce_chunk_size must be None, 0, or positive; got "
                f"{self.ce_chunk_size}")
        if self.d_model % self.num_heads != 0:
            raise ValueError(
                f"d_model={self.d_model} not divisible by num_heads={self.num_heads}"
            )
        if self.attn_impl not in ("xla", "flash", "flash_ref", "flash_xla", "ring"):
            raise ValueError(f"unknown attn_impl: {self.attn_impl!r}")
        if self.attn_impl == "ring" and not self.sp_axis:
            raise ValueError("attn_impl='ring' requires sp_axis")
        if self.attn_window is not None:
            if self.attn_window < 1:
                raise ValueError(f"attn_window must be >= 1, got {self.attn_window}")
        if self.num_experts > 0 and self.moe_top_k > self.num_experts:
            raise ValueError(
                f"moe_top_k={self.moe_top_k} > num_experts={self.num_experts}"
            )
        if self.attn_fold not in ("bh", "hb"):
            raise ValueError(f"unknown attn_fold: {self.attn_fold!r}")
        if self.attn_fold == "hb" and (
            self.attn_batch_shard or self.attn_head_shard
        ):
            raise ValueError(
                "attn_fold='hb' is a single-device layout optimization; "
                "the sharded attention paths use the 'bh' fold"
            )
        if self.moe_dispatch not in ("dense", "sorted", "sorted_scatter",
                                     "gmm"):
            raise ValueError(f"unknown moe_dispatch: {self.moe_dispatch!r}")
        if self.moe_dp_axis is not None and self.moe_dispatch not in (
            "sorted", "sorted_scatter", "gmm"
        ):
            raise ValueError(
                "moe_dp_axis (DP-consistent routing) requires an indexed "
                "dispatch: 'sorted', 'sorted_scatter', or 'gmm' (the dense "
                "one-hot dispatch has no global-position form)"
            )
        if self.moe_ep_axis is not None and self.moe_dispatch != "sorted":
            raise ValueError(
                "moe_ep_axis (expert parallelism) requires "
                f"moe_dispatch='sorted', got {self.moe_dispatch!r}"
            )
        # (moe_dp_axis is additionally required by the TRAINING a2a path —
        # moe_ffn raises there; expert-sharded SERVING replicates tokens
        # over ep and needs no token axes, models/moe.moe_ffn_ep_local)

    @property
    def d_head(self) -> int:
        return self.d_model // self.num_heads

    @property
    def pdtype(self):
        return jnp.dtype(self.param_dtype)

    @property
    def cdtype(self):
        return jnp.dtype(self.compute_dtype)

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)

    @classmethod
    def from_dict(cls, d: dict) -> "TransformerConfig":
        names = {f.name for f in dataclasses.fields(cls)}
        return cls(**{k: v for k, v in d.items() if k in names})


# attn_impl values that dispatch to ops.flash_attention, and their impl=
# argument — shared by _attention, _mha_hmajor, and the tp/ep builders.
FLASH_IMPLS = {"flash": "pallas", "flash_ref": "reference", "flash_xla": "xla"}


# Named sizes from the reference benchmark table (benchmark.py:247-259):
# (d_model, d_ff, num_layers, num_heads)
MODEL_SIZES: dict[str, tuple[int, int, int, int]] = {
    "small": (768, 3072, 12, 12),
    "medium": (1024, 4096, 24, 16),
    "large": (1280, 5120, 36, 20),
    "xl": (1600, 6400, 48, 25),
    "2.7b": (2560, 10240, 32, 32),
}


def config_for_size(
    name: str,
    vocab_size: int = 10_000,
    context_length: int = 256,
    **overrides: Any,
) -> TransformerConfig:
    d_model, d_ff, num_layers, num_heads = MODEL_SIZES[name]
    kwargs: dict[str, Any] = dict(
        vocab_size=vocab_size,
        context_length=context_length,
        d_model=d_model,
        num_layers=num_layers,
        num_heads=num_heads,
        d_ff=d_ff,
    )
    kwargs.update(overrides)  # explicit overrides win over the named size
    return TransformerConfig(**kwargs)


# ---------------------------------------------------------------------------
# Init


def _init_block(key, cfg: TransformerConfig):
    kq, kk, kv, ko, kffn = jax.random.split(key, 5)
    d = cfg.d_model
    if cfg.num_experts > 0:
        from cs336_systems_tpu.models.moe import init_moe

        ffn = init_moe(kffn, d, cfg.d_ff, cfg.num_experts, cfg.pdtype)
    else:
        ffn = init_swiglu(kffn, d, cfg.d_ff, cfg.pdtype)
    return {
        "ln1": init_rmsnorm(d, cfg.pdtype),
        "attn": {
            "q_proj": init_linear(kq, d, d, cfg.pdtype),
            "k_proj": init_linear(kk, d, d, cfg.pdtype),
            "v_proj": init_linear(kv, d, d, cfg.pdtype),
            "output_proj": init_linear(ko, d, d, cfg.pdtype),
        },
        "ln2": init_rmsnorm(d, cfg.pdtype),
        "ffn": ffn,
    }


def init_transformer_lm(key, cfg: TransformerConfig):
    """Init the full LM params pytree; block params stacked on a layer axis."""
    k_emb, k_blocks, k_head = jax.random.split(key, 3)
    block_keys = jax.random.split(k_blocks, cfg.num_layers)
    blocks = jax.vmap(lambda k: _init_block(k, cfg))(block_keys)
    return {
        "token_embeddings": init_embedding(k_emb, cfg.vocab_size, cfg.d_model, cfg.pdtype),
        "blocks": blocks,
        "ln_final": init_rmsnorm(cfg.d_model, cfg.pdtype),
        "lm_head": init_linear(k_head, cfg.d_model, cfg.vocab_size, cfg.pdtype),
    }


def count_params(params, non_embedding: bool = True) -> int:
    """Total param count; ``non_embedding`` subtracts the LM head (reference
    ``get_num_params``, model.py:220-229)."""
    total = sum(x.size for x in jax.tree_util.tree_leaves(params))
    if non_embedding:
        total -= params["lm_head"]["weight"].size
    return total


# ---------------------------------------------------------------------------
# Apply


def _attention(q, k, v, cfg: TransformerConfig, mesh=None, ring_rope=None):
    """Dispatch the attention inner op. q/k/v: [B, H, S, Dh].

    ``mesh`` (a ``jax.sharding.Mesh``): required only when
    ``cfg.attn_batch_shard`` / ``cfg.attn_head_shard`` declare the operands
    sharded — the flash kernel then runs in a ``shard_map`` over those axes
    with its local [B/dp, H/tp, S, Dh] block (see the config fields).

    ``ring_rope``: (cos, sin, positions) when the ring path fuses RoPE
    in-kernel (cfg.rope_fused) — q/k arrive UNROTATED and each hop rotates
    in VMEM at the hop block's global positions (parallel/ring.py)."""
    if cfg.attn_impl == "xla":
        if cfg.attn_window is not None:
            from cs336_systems_tpu.ops.attention import banded_causal_mask

            mask = banded_causal_mask(q.shape[-2], k.shape[-2], cfg.attn_window)
        else:
            mask = causal_mask(q.shape[-2], k.shape[-2])
        out, _ = attention_with_lse(q, k, v, mask)
        return out
    elif cfg.attn_impl in FLASH_IMPLS:
        from cs336_systems_tpu.ops.flash_attention import flash_attention

        impl = FLASH_IMPLS[cfg.attn_impl]

        def local_attn(q, k, v):
            b, h, s, dh = q.shape
            fold = lambda x: x.reshape(b * h, s, dh)
            out = flash_attention(
                fold(q), fold(k), fold(v), causal=True, impl=impl,
                window=cfg.attn_window,
            )
            return out.reshape(b, h, s, dh)

        if cfg.attn_batch_shard or cfg.attn_head_shard:
            if mesh is None:
                raise ValueError(
                    "cfg declares attention sharding "
                    f"(batch={cfg.attn_batch_shard!r}, "
                    f"head={cfg.attn_head_shard!r}) but no mesh was passed "
                    "to the apply fn"
                )
            from jax.sharding import PartitionSpec as P

            spec = P(cfg.attn_batch_shard, cfg.attn_head_shard)
            return jax.shard_map(
                local_attn, mesh=mesh,
                in_specs=(spec, spec, spec), out_specs=spec,
            )(q, k, v)
        return local_attn(q, k, v)
    elif cfg.attn_impl == "ring":
        from cs336_systems_tpu.parallel.ring import ring_attention

        if cfg.attn_batch_shard or cfg.attn_head_shard:
            # GSPMD composition (dp × tp × sp): like the flash branch, the
            # ring runs in its OWN shard_map island — operands arrive
            # GSPMD-sharded [B/dp, H/tp, S/sp, Dh] with rope already
            # applied outside at global positions (the builder forces
            # rope_fused off for this path; _mha never builds ring_rope
            # when shard axes are declared), and the ring's K/V ppermute
            # hops ride the sp axis inside the island.
            if mesh is None:
                raise ValueError(
                    "cfg declares attention sharding but no mesh was "
                    "passed to the apply fn"
                )
            from jax.sharding import PartitionSpec as P

            spec = P(cfg.attn_batch_shard, cfg.attn_head_shard, cfg.sp_axis)

            def local_ring(q, k, v):
                b, h, s, dh = q.shape
                fold = lambda x: x.reshape(b * h, s, dh)
                out = ring_attention(
                    fold(q), fold(k), fold(v), axis=cfg.sp_axis,
                    causal=True, window=cfg.attn_window,
                )
                return out.reshape(b, h, s, dh)

            return jax.shard_map(
                local_ring, mesh=mesh,
                in_specs=(spec, spec, spec), out_specs=spec,
            )(q, k, v)

        # inside-shard_map form: q/k/v hold the LOCAL sequence shard and
        # positions carry the global offsets. The per-hop inner op is the
        # flash kernel (window → truncated ring).
        b, h, s, dh = q.shape
        fold = lambda x: x.reshape(b * h, s, dh)
        rope_kw = {}
        if ring_rope is not None:
            cos, sin, positions = ring_rope
            rope_kw = dict(rope_cos=cos, rope_sin=sin, positions=positions)
        out = ring_attention(
            fold(q), fold(k), fold(v), axis=cfg.sp_axis, causal=True,
            window=cfg.attn_window, **rope_kw,
        )
        return out.reshape(b, h, s, dh)
    raise ValueError(f"unknown attn_impl: {cfg.attn_impl}")


def _mha_hmajor(p, x, cos, sin, positions, cfg: TransformerConfig):
    """Head-major MHA: projections write the flash kernels' [H·B, S, Dh]
    operand layout straight out of the matmul (cfg.attn_fold="hb").

    The "bh" fold's [B,S,H,Dh] -> [B·H,S,Dh] rearrangement costs measured
    Mosaic operand-layout copies around the Pallas custom calls (~14.5
    ms/step of the 124 ms headline, BASELINE.md); batching the projection
    einsum over the HEAD dim ("bsd,hed->hbse") makes the head dim the
    matmul's leading batch dim, so the [H,B,S,Dh] output IS contiguous in
    the folded layout and the transpose never exists. The kernels don't
    care about row order (rows are independent (batch, head) pairs).
    """
    # apply_rope supports broadcastable [..., seq] positions, but under this
    # fold the leading dim is the FOLDED [H·B] axis — per-batch [B, S]
    # positions would mis-broadcast against it. Only shared-[S] positions
    # are meaningful here; the "bh" path handles richer shapes.
    if positions.ndim != 1:
        raise ValueError(
            "attn_fold='hb' requires shared 1-D positions [seq]; got shape "
            f"{positions.shape} — use attn_fold='bh' for per-batch positions"
        )
    b, s, _ = x.shape
    h, dh = cfg.num_heads, cfg.d_head
    cdt = cfg.cdtype
    from cs336_systems_tpu.ops.flash_attention import flash_attention

    impl = FLASH_IMPLS[cfg.attn_impl]

    def proj(wp):
        w = wp["weight"].astype(cdt).reshape(h, dh, cfg.d_model)
        out = jnp.einsum("bsd,hed->hbse", x.astype(cdt), w)
        return out.reshape(h * b, s, dh)

    with jax.named_scope("qkv_proj"):
        if cfg.qkv_fused:
            # one stacked matmul; q/k/v are contiguous slices of its output
            w_all = jnp.stack([
                p[n]["weight"].astype(cdt).reshape(h, dh, cfg.d_model)
                for n in ("q_proj", "k_proj", "v_proj")
            ])
            qkv = jnp.einsum("bsd,xhed->xhbse", x.astype(cdt), w_all)
            qkv = qkv.reshape(3, h * b, s, dh)
            q, k, v = qkv[0], qkv[1], qkv[2]
        else:
            q, k, v = proj(p["q_proj"]), proj(p["k_proj"]), proj(p["v_proj"])
    if cfg.rope_fused:
        # rotation happens inside the kernels (see ops/flash_attention) —
        # no rope op between the projections and the custom call
        rope_kw = dict(
            rope_cos=jnp.take(cos, positions, axis=0),
            rope_sin=jnp.take(sin, positions, axis=0),
        )
    else:
        with jax.named_scope("rope"):
            q = apply_rope(q, cos, sin, positions)
            k = apply_rope(k, cos, sin, positions)
        rope_kw = {}
    with jax.named_scope("sdpa"):
        o = flash_attention(
            q, k, v, causal=True, impl=impl, window=cfg.attn_window,
            **rope_kw,
        )
    with jax.named_scope("out_proj"):
        wo = p["output_proj"]["weight"].astype(cdt).reshape(cfg.d_model, h, dh)
        return jnp.einsum("hbse,ohe->bso", o.reshape(h, b, s, dh), wo)


def _mha(block_params, x, cos, sin, positions, cfg: TransformerConfig,
         mesh=None):
    """Causal multi-head self-attention with RoPE on Q and K.

    Parity: CausalMultiHeadSelfAttention (model.py:435-524).

    Flash configs default to the head-MAJOR fold (``_mha_hmajor`` — the
    projections write the kernels' [H·B, S, Dh] operand layout directly;
    +3.5% headline, BASELINE.md). This plain [B,H,S,Dh] form remains the
    path for the xla/ring impls and for the GSPMD-sharded attention
    region, whose shard_map specs name the separate B and H axes. (A
    b-major folded einsum ``bsd,hed->bhse`` was measured perf-neutral in
    round 1 — only the h-major output is transpose-free.)
    """
    p = block_params
    if cfg.attn_fold == "hb" and cfg.attn_impl in FLASH_IMPLS:
        return _mha_hmajor(p, x, cos, sin, positions, cfg)
    b, s, _ = x.shape
    h, dh = cfg.num_heads, cfg.d_head
    split = lambda t: t.reshape(b, s, h, dh).transpose(0, 2, 1, 3)  # [B,H,S,Dh]
    with jax.named_scope("qkv_proj"):
        q = split(linear(p["q_proj"], x, cfg.cdtype))
        k = split(linear(p["k_proj"], x, cfg.cdtype))
        v = split(linear(p["v_proj"], x, cfg.cdtype))
    ring_rope = None
    if (cfg.attn_impl == "ring" and cfg.rope_fused and positions.ndim == 1
            and not (cfg.attn_batch_shard or cfg.attn_head_shard)):
        # rotate inside the ring hops' kernels (parallel/ring.py) — no
        # rope op between the projections and the custom calls, matching
        # the single-device fused-rope default. Per-batch positions fall
        # back to the XLA rotation (the per-row table API is shared-[S]);
        # so does the GSPMD dp×tp×sp island (shard axes declared), whose
        # rope applies outside at global positions.
        ring_rope = (cos, sin, positions)
    else:
        with jax.named_scope("rope"):
            q = apply_rope(q, cos, sin, positions)
            k = apply_rope(k, cos, sin, positions)
    with jax.named_scope("sdpa"):
        out = _attention(q, k, v, cfg, mesh, ring_rope)
    out = out.transpose(0, 2, 1, 3).reshape(b, s, h * dh)
    with jax.named_scope("out_proj"):
        return linear(p["output_proj"], out, cfg.cdtype)


def _block(block_params, x, cos, sin, positions, cfg: TransformerConfig,
           mesh=None):
    """Pre-norm block: x + attn(ln1 x); then x + ffn(ln2 x).

    Returns ``(x, aux)`` — ``aux`` is the MoE load-balance loss for this
    block (0.0 for the dense FFN). ``named_scope`` tags every stage in HLO
    metadata and profiler traces — the NVTX-range parity (reference
    transformer_annotated.py:35-98)."""
    with jax.named_scope("attn"):
        x = x + _mha(block_params["attn"], rmsnorm(block_params["ln1"], x), cos, sin, positions, cfg, mesh)
    with jax.named_scope("ffn"):
        h = rmsnorm(block_params["ln2"], x)
        if cfg.num_experts > 0:
            from cs336_systems_tpu.models.moe import moe_ffn

            h, aux = moe_ffn(
                block_params["ffn"], h, cfg.moe_top_k,
                cfg.moe_capacity_factor, cfg.cdtype,
                dispatch=cfg.moe_dispatch, dp_axis=cfg.moe_dp_axis,
                ffn_remat=cfg.moe_ffn_remat, ep_axis=cfg.moe_ep_axis,
            )
        else:
            h = swiglu(block_params["ffn"], h, cfg.cdtype)
            aux = jnp.zeros((), jnp.float32)
        x = x + h
    return x, aux


def transformer_hidden_with_aux(
    params,
    token_ids: jax.Array,
    cfg: TransformerConfig,
    positions: jax.Array | None = None,
    mesh=None,
) -> tuple[jax.Array, jax.Array]:
    """Forward pass up to (and including) the final norm — NO lm head.

    [B, S] int ids → ([B, S, d_model] hidden states, aux scalar). The loss
    entry (``train.lm_loss`` routing through ``ops/fused_ce.py``) consumes
    these pre-head hidden states so the lm-head projection happens fused
    with the cross-entropy, one S-chunk at a time — the ``[B, S, vocab]``
    logits never exist. ``transformer_lm_with_aux`` keeps the materialized
    head for generation and the legacy/oracle loss path.

    ``aux`` is the summed MoE load-balance loss over blocks (0.0 for dense
    configs). Layers run under ``lax.scan`` over the stacked block params
    (``cfg.scan_layers``) or as an unrolled loop; with ``cfg.remat`` each
    block is wrapped in ``jax.checkpoint`` so the backward pass recomputes
    activations instead of storing S×L of them (HBM trade).

    ``mesh``: required when cfg declares attention-operand sharding
    (``attn_batch_shard``/``attn_head_shard`` — see ``_attention``).
    """
    if token_ids.ndim == 1:
        token_ids = token_ids[None, :]
    s = token_ids.shape[-1]
    if positions is None:
        positions = jnp.arange(s)
    cos, sin = rope_cache(cfg.context_length, cfg.d_head, cfg.rope_theta)

    with jax.named_scope("embed"):
        x = embedding(params["token_embeddings"], token_ids, cfg.cdtype)

    def blk_fn(bp, x):
        return _block(bp, x, cos, sin, positions, cfg, mesh)

    aux = jnp.zeros((), jnp.float32)
    if cfg.scan_layers:
        # One compiled block body for any depth; backward stashes activations
        # into stacked [L, ...] buffers via dynamic-update-slice.
        def body(carry, bp):
            return blk_fn(bp, carry)

        if cfg.remat:
            body = jax.checkpoint(body, prevent_cse=False)
        with jax.named_scope("blocks"):
            x, auxes = jax.lax.scan(body, x, params["blocks"])
            aux = jnp.sum(auxes)
    else:
        # Unrolled: more HLO and compile time, but the backward reads each
        # layer's activations where they were produced — no stash copies.
        # ~20% faster per step than scan at small depth (measured on v5e).
        blk = blk_fn
        if cfg.remat:
            # prevent_cse must stay True here: outside lax.scan XLA CSE would
            # merge the forward and recomputed activations, silently undoing
            # the rematerialization.
            blk = jax.checkpoint(blk_fn)
        with jax.named_scope("blocks"):
            for i in range(cfg.num_layers):
                bp = jax.tree_util.tree_map(lambda a: a[i], params["blocks"])
                if cfg.num_experts > 0:
                    # Block XLA from CSE-ing the 12 per-layer fp32→bf16
                    # weight casts of convert(blocks[i]) into ONE
                    # whole-stack convert: with E experts the stacked cast
                    # ([L,E,D,F] bf16) cannot stay live, so XLA remats the
                    # FULL-stack convert at every layer's use site — traced
                    # at 47.9 ms/step at the E8k2 peak (1.36 GB of traffic
                    # × ~23 sites; scripts/trace_moe_step.py). The barrier
                    # keeps each cast per-layer (~0.14 ms of its own
                    # slice's traffic). Dense stacks are 8× smaller, stay
                    # live once-converted, and don't need this.
                    bp = jax.lax.optimization_barrier(bp)
                x, aux_i = blk(bp, x)
                aux = aux + aux_i

    with jax.named_scope("final_norm"):
        x = rmsnorm(params["ln_final"], x)
    return x, aux


def transformer_lm_with_aux(
    params,
    token_ids: jax.Array,
    cfg: TransformerConfig,
    positions: jax.Array | None = None,
    mesh=None,
) -> tuple[jax.Array, jax.Array]:
    """Forward pass: [B, S] int ids → ([B, S, vocab] logits, aux scalar).

    The materialized-logits entry: generation, serving, and the legacy
    (``cfg.ce_chunk_size == 0``) loss path. Training's default loss goes
    through ``transformer_hidden_with_aux`` + the chunked fused CE instead
    (see that docstring).
    """
    x, aux = transformer_hidden_with_aux(params, token_ids, cfg, positions,
                                         mesh)
    with jax.named_scope("lm_head"):
        return linear(params["lm_head"], x, cfg.cdtype), aux


def transformer_lm(
    params,
    token_ids: jax.Array,
    cfg: TransformerConfig,
    positions: jax.Array | None = None,
    mesh=None,
) -> jax.Array:
    """Forward pass: [B, S] int ids → [B, S, vocab] logits (compute dtype).

    See ``transformer_lm_with_aux`` for the (logits, MoE aux loss) variant;
    this drops the aux term (exactly zero for dense configs).
    """
    return transformer_lm_with_aux(params, token_ids, cfg, positions, mesh)[0]


def transformer_hidden(
    params,
    token_ids: jax.Array,
    cfg: TransformerConfig,
    positions: jax.Array | None = None,
    mesh=None,
) -> jax.Array:
    """Forward pass to the post-final-norm hidden states, aux dropped.

    The loss-path twin of ``transformer_lm``: [B, S] int ids →
    [B, S, d_model] — feed to ``ops/fused_ce.fused_linear_cross_entropy``
    with ``params["lm_head"]["weight"]``.
    """
    return transformer_hidden_with_aux(params, token_ids, cfg, positions,
                                       mesh)[0]


# ---------------------------------------------------------------------------
# Sampling (reference BasicsTransformerLM.generate, model.py:255-310)


def _pad_len(n: int, bucket: int = 64) -> int:
    return ((n + bucket - 1) // bucket) * bucket


@functools.partial(jax.jit, static_argnames=("cfg",))
def _forward_logits(params, ids, cfg: TransformerConfig):
    return transformer_lm(params, ids, cfg)


def top_p_filter(logits: jax.Array, top_p: float) -> jax.Array:
    """Nucleus filtering (beyond reference parity — the reference samples
    with temperature/top-k only, model.py:292-303): keep the smallest set
    of tokens whose probability mass reaches ``top_p``, masking the rest
    to −inf. Operates on the last axis; jit-safe (sort-based, static
    shapes). The most-probable token always survives (the nucleus is never
    empty, even for top_p ≤ the max probability)."""
    probs = jax.nn.softmax(logits, axis=-1)
    order = jnp.argsort(probs, axis=-1)[..., ::-1]  # descending
    sorted_probs = jnp.take_along_axis(probs, order, axis=-1)
    csum = jnp.cumsum(sorted_probs, axis=-1)
    # token i (in sorted order) is kept while the mass BEFORE it is < top_p;
    # the argmax is force-kept so the nucleus is never empty (top_p <= 0
    # would otherwise mask everything)
    keep_sorted = ((csum - sorted_probs) < top_p).at[..., 0].set(True)
    inv = jnp.argsort(order, axis=-1)  # sorted position of each vocab id
    keep = jnp.take_along_axis(keep_sorted, inv, axis=-1)
    return jnp.where(keep, logits, -jnp.inf)


def generate(
    params,
    cfg: TransformerConfig,
    prompt_ids,
    max_new_tokens: int,
    key,
    temperature: float = 1.0,
    top_k: int | None = None,
    eos_token_id: int | None = None,
    top_p: float | None = None,
) -> jax.Array:
    """Temperature + top-k (and/or nucleus top-p) sampling loop with EOS
    stop and context truncation.

    Like the reference, a full forward per token (no KV cache); prompts are
    right-padded to 64-token buckets so jit compiles once per bucket, not per
    length (padding after position i never influences logits at i: causal).
    """
    ids = list(jnp.asarray(prompt_ids).reshape(-1).tolist())
    out: list[int] = []
    for _ in range(max_new_tokens):
        window = ids[-cfg.context_length :]
        cur = len(window)
        padded = _pad_len(cur)
        if padded > cfg.context_length:
            padded = cfg.context_length
            window = window[-padded:]
            cur = len(window)
        buf = jnp.zeros((1, padded), jnp.int32).at[0, :cur].set(jnp.asarray(window, jnp.int32))
        logits = _forward_logits(params, buf, cfg)[0, cur - 1].astype(jnp.float32)
        logits = logits / temperature
        if top_k is not None:
            kth = jax.lax.top_k(logits, min(top_k, logits.shape[-1]))[0][-1]
            logits = jnp.where(logits < kth, -jnp.inf, logits)
        if top_p is not None:
            logits = top_p_filter(logits, top_p)
        key, sub = jax.random.split(key)
        nxt = int(jax.random.categorical(sub, logits))
        if eos_token_id is not None and nxt == eos_token_id:
            break
        ids.append(nxt)
        out.append(nxt)
    return jnp.asarray(out, jnp.int32)
