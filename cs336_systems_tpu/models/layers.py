"""Functional building blocks of the Transformer LM.

Every layer is an ``init_*`` function producing a params pytree (dict of
arrays) plus a pure apply function. Capability parity with the reference
layer set (cs336-basics/cs336_basics/model.py):

- Linear: bias-free, trunc-normal init std=sqrt(2/(din+dout)) clipped ±3σ
  (model.py:22-44).
- Embedding: trunc-normal std=1 clipped ±3 (model.py:47-60).
- RMSNorm: eps 1e-5, learned scale, fp32 internal compute (model.py:63-110).
- RoPE: interleaved-pair rotation from a precomputed cos/sin table
  (model.py:113-150).
- SwiGLU: w2(silu(w1 x) * w3 x) (model.py:389-397).

TPU-first notes: weights are stored ``[d_out, d_in]`` and applied with an
einsum that XLA maps straight onto the MXU; params live in ``param_dtype``
(fp32 by default) and are cast to ``compute_dtype`` (bf16 for mixed
precision) at use; RMSNorm always reduces in fp32.
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp


def trunc_normal(key, shape, std: float, dtype=jnp.float32) -> jax.Array:
    """Truncated normal with given std, clipped to ±3σ (matching torch's
    ``trunc_normal_(std=s, a=-3s, b=3s)`` semantics)."""
    return std * jax.random.truncated_normal(key, -3.0, 3.0, shape, jnp.float32).astype(dtype)


# ---------------------------------------------------------------------------
# Linear


def init_linear(key, d_in: int, d_out: int, dtype=jnp.float32):
    std = math.sqrt(2.0 / (d_in + d_out))
    return {"weight": trunc_normal(key, (d_out, d_in), std, dtype)}


def linear(params, x: jax.Array, compute_dtype=None) -> jax.Array:
    w = params["weight"]
    if compute_dtype is not None:
        w = w.astype(compute_dtype)
        x = x.astype(compute_dtype)
    return jnp.einsum("...i,oi->...o", x, w)


# ---------------------------------------------------------------------------
# Embedding


def init_embedding(key, vocab_size: int, d_model: int, dtype=jnp.float32):
    return {"weight": trunc_normal(key, (vocab_size, d_model), 1.0, dtype)}


def embedding(params, token_ids: jax.Array, compute_dtype=None) -> jax.Array:
    w = params["weight"]
    if compute_dtype is not None:
        w = w.astype(compute_dtype)
    return jnp.take(w, token_ids, axis=0)


# ---------------------------------------------------------------------------
# RMSNorm


def init_rmsnorm(d_model: int, dtype=jnp.float32):
    return {"weight": jnp.ones((d_model,), dtype)}


def rmsnorm(params, x: jax.Array, eps: float = 1e-5) -> jax.Array:
    """RMS layer norm; square/mean/rsqrt always in fp32, output in input dtype."""
    in_dtype = x.dtype
    xf = x.astype(jnp.float32)
    rms = jax.lax.rsqrt(jnp.mean(jnp.square(xf), axis=-1, keepdims=True) + eps)
    return (params["weight"].astype(jnp.float32) * (xf * rms)).astype(in_dtype)


# ---------------------------------------------------------------------------
# RoPE (interleaved-pair convention, as in the reference RotaryEmbedding)


def rope_cache(context_length: int, d_head: int, theta: float = 10000.0):
    """Precompute cos/sin tables of shape [context_length, d_head // 2] (fp32)."""
    assert d_head % 2 == 0
    inv_freq = theta ** -(jnp.arange(0, d_head, 2, dtype=jnp.float32) / d_head)
    t = jnp.arange(context_length, dtype=jnp.float32)
    angles = jnp.outer(t, inv_freq)  # [ctx, d/2]
    return jnp.cos(angles), jnp.sin(angles)


def apply_rope(x: jax.Array, cos: jax.Array, sin: jax.Array, positions: jax.Array) -> jax.Array:
    """Rotate interleaved pairs (x[..., 2i], x[..., 2i+1]) by position angles.

    ``x``: [..., seq, d_head]; ``positions``: int [seq] or broadcastable
    [..., seq]. Rotation runs in fp32 and is cast back to x.dtype.
    """
    in_dtype = x.dtype
    xf = x.astype(jnp.float32)
    x1 = xf[..., 0::2]
    x2 = xf[..., 1::2]
    c = jnp.take(cos, positions, axis=0)  # [..., seq, d/2]
    s = jnp.take(sin, positions, axis=0)
    r1 = c * x1 - s * x2
    r2 = s * x1 + c * x2
    out = jnp.stack([r1, r2], axis=-1).reshape(x.shape)
    return out.astype(in_dtype)


# ---------------------------------------------------------------------------
# SwiGLU feed-forward


def init_swiglu(key, d_model: int, d_ff: int, dtype=jnp.float32):
    k1, k2, k3 = jax.random.split(key, 3)
    return {
        "w1": init_linear(k1, d_model, d_ff, dtype),
        "w2": init_linear(k2, d_ff, d_model, dtype),
        "w3": init_linear(k3, d_model, d_ff, dtype),
    }


def swiglu(params, x: jax.Array, compute_dtype=None) -> jax.Array:
    h = linear(params["w1"], x, compute_dtype)
    g = linear(params["w3"], x, compute_dtype)
    return linear(params["w2"], jax.nn.silu(h) * g, compute_dtype)
