from cs336_systems_tpu.optim.adamw import AdamWHparams, adamw_init, adamw_update
from cs336_systems_tpu.optim.schedule import get_cosine_lr

__all__ = ["AdamWHparams", "adamw_init", "adamw_update", "get_cosine_lr"]
