"""From-scratch decoupled AdamW over parameter pytrees.

Semantics parity with the reference hand-written optimizer
(cs336-basics/cs336_basics/optimizer.py:30-86): per-param state {m, v},
shared step count t (the reference stores t per-param but advances all in
lockstep), bias correction folded into the step size
``alpha_t = lr * sqrt(1 - beta2^t) / (1 - beta1^t)``, and decoupled weight
decay ``p -= lr * wd * p`` applied *after* the Adam update.

TPU-first: the update is one pure function over the whole pytree — a single
fused XLA computation per step (no per-parameter Python loop on the hot
path) — and moments/update math run in fp32 even for low-precision params.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp


@dataclasses.dataclass(frozen=True)
class AdamWHparams:
    lr: float = 1e-3
    beta1: float = 0.9
    beta2: float = 0.999
    eps: float = 1e-8
    weight_decay: float = 0.01

    def __post_init__(self):
        if self.lr < 0.0:
            raise ValueError(f"Invalid learning rate: {self.lr}")
        if self.eps < 0.0:
            raise ValueError(f"Invalid epsilon value: {self.eps}")
        if not 0.0 <= self.beta1 < 1.0:
            raise ValueError(f"Invalid beta parameter at index 0: {self.beta1}")
        if not 0.0 <= self.beta2 < 1.0:
            raise ValueError(f"Invalid beta parameter at index 1: {self.beta2}")


def adamw_init(params):
    """Optimizer state pytree: fp32 first/second moments + scalar step count."""
    zeros = lambda p: jnp.zeros(p.shape, jnp.float32)
    return {
        "m": jax.tree_util.tree_map(zeros, params),
        "v": jax.tree_util.tree_map(zeros, params),
        "t": jnp.zeros((), jnp.int32),
    }


def adamw_update(params, grads, state, hp: AdamWHparams, lr=None):
    """One AdamW step. Returns (new_params, new_state).

    ``lr`` (scalar, possibly traced — e.g. from a schedule) overrides
    ``hp.lr`` so schedules don't force recompilation.
    """
    lr = hp.lr if lr is None else lr
    t = state["t"] + 1
    tf = t.astype(jnp.float32)
    b1, b2 = hp.beta1, hp.beta2
    bias = jnp.sqrt(1.0 - b2**tf) / (1.0 - b1**tf)
    alpha_t = lr * bias

    def leaf(p, g, m, v):
        gf = g.astype(jnp.float32)
        m_t = b1 * m + (1.0 - b1) * gf
        v_t = b2 * v + (1.0 - b2) * jnp.square(gf)
        pf = p.astype(jnp.float32)
        pf = pf - alpha_t * m_t / (jnp.sqrt(v_t) + hp.eps)
        pf = pf - lr * hp.weight_decay * pf
        return pf.astype(p.dtype), m_t, v_t

    triples = jax.tree_util.tree_map(leaf, params, grads, state["m"], state["v"])
    pick = lambda i: jax.tree_util.tree_map(
        lambda t3: t3[i], triples, is_leaf=lambda x: isinstance(x, tuple)
    )
    return pick(0), {"m": pick(1), "v": pick(2), "t": t}


def adamw_chunk_update(p, g, m, v, t, hp: AdamWHparams, lr=None):
    """One AdamW step on a flat fp32 chunk: the shared update body of the
    index-sharded optimizers (``parallel.zero`` ZeRO-1, ``parallel.fsdp``
    ZeRO-3). Same arithmetic as ``adamw_update``'s per-leaf body — kept in
    ONE place so the sharded variants cannot drift from the canonical
    update (their bit-exactness vs ``adamw_update`` is test-pinned).

    ``t`` is the PRE-increment step counter; returns (p, m, v, t+1).
    """
    lr = hp.lr if lr is None else lr
    t = t + 1
    tf = t.astype(jnp.float32)
    b1, b2 = hp.beta1, hp.beta2
    alpha_t = lr * jnp.sqrt(1.0 - b2**tf) / (1.0 - b1**tf)
    m = b1 * m + (1.0 - b1) * g
    v = b2 * v + (1.0 - b2) * jnp.square(g)
    p = p - alpha_t * m / (jnp.sqrt(v) + hp.eps)
    p = p - lr * hp.weight_decay * p
    return p, m, v, t
