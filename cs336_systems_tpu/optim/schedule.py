"""Learning-rate schedules.

Parity with the reference warmup-cosine schedule
(cs336-basics/cs336_basics/optimizer.py:9-27), written branch-free with
``jnp.where`` so it can be traced inside a jitted train step (a traced
step count must not drive Python control flow on TPU).
"""

from __future__ import annotations

import jax.numpy as jnp


def get_cosine_lr(
    it,
    max_learning_rate: float,
    min_learning_rate: float,
    warmup_iters: int,
    cosine_cycle_iters: int,
):
    """Linear warmup → cosine decay → floor. Works on ints and traced arrays."""
    it = jnp.asarray(it, jnp.float32)
    warmup = max_learning_rate * it / jnp.maximum(warmup_iters, 1)
    decay_ratio = (it - warmup_iters) / jnp.maximum(cosine_cycle_iters - warmup_iters, 1)
    decay_ratio = jnp.clip(decay_ratio, 0.0, 1.0)
    coeff = 0.5 * (1.0 + jnp.cos(jnp.pi * decay_ratio))
    cosine = min_learning_rate + coeff * (max_learning_rate - min_learning_rate)
    out = jnp.where(it < warmup_iters, warmup, cosine)
    return jnp.where(it > cosine_cycle_iters, min_learning_rate, out)
