"""Trace the batched KV-cache decode scan and print the device-time
breakdown per generated token.

Same measurement recipe as trace_headline_step.py (device-lane durations
only). Attributes the gap between the decode artifact's device_est and the
analytic HBM roofline (results/decode_v5e.txt). The round-3-continuation
optimization arc this script steered: 2064 us/token (XLA masked softmax +
per-token param slices) -> 1518 (fused kernel + unstacked params) -> 1070
(packed in-place kernel) -> 792 with approx sampling, vs roofline 664.

Usage: PYTHONPATH=.:$PYTHONPATH python scripts/trace_decode_step.py [logdir] [--batch N] [--approx-top-k]
"""


from cs336_systems_tpu.utils.platform import honor_cpu_request

honor_cpu_request()

import jax
import jax.numpy as jnp

from cs336_systems_tpu.models.decode import generate_kv_batched
from cs336_systems_tpu.models.transformer import config_for_size, init_transformer_lm
from cs336_systems_tpu.utils.profiling import summarize_trace, trace


def main() -> None:
    import argparse

    ap = argparse.ArgumentParser()
    ap.add_argument("logdir", nargs="?", default="/tmp/decode_trace")
    ap.add_argument("--batch", type=int, default=None)
    ap.add_argument("--approx-top-k", action="store_true")
    args = ap.parse_args()
    logdir = args.logdir
    on_tpu = jax.default_backend() == "tpu"
    batch, prompt, new = (32, 64, 128) if on_tpu else (2, 8, 8)
    if args.batch is not None:
        batch = args.batch
    cfg = config_for_size(
        "small",
        context_length=512,
        compute_dtype="bfloat16" if on_tpu else "float32",
        attn_impl="xla",
        scan_layers=not on_tpu,
    )
    params = init_transformer_lm(jax.random.PRNGKey(0), cfg)
    ids = jax.random.randint(jax.random.PRNGKey(1), (batch, prompt), 0, cfg.vocab_size)

    def run():
        toks = generate_kv_batched(
            params, cfg, ids, new, jax.random.PRNGKey(2),
            temperature=0.8, top_k=50, approx_top_k=args.approx_top_k,
        )
        jax.device_get(toks)

    run()  # compile + warm
    with trace(logdir):
        run()

    rows, total = summarize_trace(logdir, top=30)
    print(f"trace: {logdir}   leaf device time {total / new * 1000:.1f} us/token"
          f"   ({total:.1f} ms total, {new} tokens, batch {batch})")
    print(f"{'op':40s} {'us/token':>9s} {'count':>7s} {'mean_us':>9s}")
    for r in rows:
        print(
            f"{r['op'][:40]:40s} {r['total_ms'] / new * 1000:9.1f} "
            f"{r['count']:7d} {r['mean_us']:9.1f}"
        )


if __name__ == "__main__":
    main()
