"""Trace the batched KV-cache decode scan and print the phase-attributed
device-time breakdown per generated token (a tracekit StepProfile).

Thin wrapper over ``analysis/tracekit.profile_callable`` at the serving
shape (b32, 64-token prompts, 128 new tokens on TPU). The phase rows
separate kv-update (the fused update+attend kernel) from the projections
(fwd-attn), the FFN and sampling — the attribution behind the
2064 → 792 us/token decode arc (results/decode_v5e.txt); the written
StepProfile diffs across runs via ``trace_cli --diff``.

Usage: PYTHONPATH=.:$PYTHONPATH python scripts/trace_decode_step.py \
          [--batch N] [--approx-top-k] [--out decode.stepprofile.json]
"""

import argparse

from cs336_systems_tpu.utils.platform import honor_cpu_request

honor_cpu_request()

import jax

from cs336_systems_tpu.analysis import tracekit
from cs336_systems_tpu.analysis.flops import decode_flops_per_token
from cs336_systems_tpu.models.decode import generate_kv_batched
from cs336_systems_tpu.models.transformer import config_for_size, init_transformer_lm


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch", type=int, default=None)
    ap.add_argument("--approx-top-k", action="store_true")
    ap.add_argument("--out", default="decode.stepprofile.json",
                    help="StepProfile JSON path")
    args = ap.parse_args()
    on_tpu = jax.default_backend() == "tpu"
    batch, prompt, new = (32, 64, 128) if on_tpu else (2, 8, 8)
    if args.batch is not None:
        batch = args.batch
    cfg = config_for_size(
        "small",
        context_length=512,
        compute_dtype="bfloat16" if on_tpu else "float32",
        attn_impl="xla",
        scan_layers=not on_tpu,
    )
    params = init_transformer_lm(jax.random.PRNGKey(0), cfg)
    ids = jax.random.randint(jax.random.PRNGKey(1), (batch, prompt), 0, cfg.vocab_size)

    def gen(params, ids, key):
        return generate_kv_batched(
            params, cfg, ids, new, key,
            temperature=0.8, top_k=50, approx_top_k=args.approx_top_k,
        )

    profile = tracekit.profile_callable(
        gen, (params, ids, jax.random.PRNGKey(2)), iters=1,
        tokens_per_step=batch * new,
        flops_per_token=decode_flops_per_token(
            cfg, attend_len=min(prompt + new, cfg.context_length)),
        family="decode_batched",
    )
    print(tracekit.format_profile(profile))
    us_tok = profile["total_device_ms_per_step"] / new * 1e3
    print(f"  per generated token: {us_tok:.1f} us "
          f"({new} tokens, batch {batch})")
    tracekit.write_profile(profile, args.out)
    print(f"wrote {args.out}")


if __name__ == "__main__":
    main()
