"""On-chip numerics check for the fused-CE Pallas forward chunk kernel
(ops/fused_ce.py impl="pallas") against the XLA scan oracle — the real-
Mosaic half of the Pallas convention (the interpret=True half lives in
tests/test_fused_ce.py). Run on the TPU (NO JAX_PLATFORMS=cpu):

    PYTHONPATH=.:$PYTHONPATH python scripts/check_fused_ce_chip.py
"""
import jax, jax.numpy as jnp, numpy as np
from cs336_systems_tpu.ops.fused_ce import fused_linear_cross_entropy

k1, k2, k3 = jax.random.split(jax.random.PRNGKey(0), 3)
B, S, D, V = 8, 512, 768, 10_000          # the headline loss shape
h = (jax.random.normal(k1, (B, S, D)) * 0.7).astype(jnp.bfloat16)
w = (jax.random.normal(k2, (V, D)) * 0.2).astype(jnp.bfloat16)
t = jax.random.randint(k3, (B, S), 0, V)

def run(impl, vocab=None):
    hh, ww, tt = (h, w, t) if vocab is None else (
        h, w[:vocab], jnp.minimum(t, vocab - 1))
    def f(hh, ww):
        return fused_linear_cross_entropy(
            hh, ww, tt, compute_dtype="bfloat16", impl=impl)
    loss, grads = jax.value_and_grad(f, argnums=(0, 1))(hh, ww)
    return float(loss), grads

loss_p, grads_p = run("pallas")
loss_x, grads_x = run("xla")
# same discipline as the interpret test: loss near-exact (both reduce in
# fp32), grads at bf16 grad tolerance (the lse residual's last-ulp shifts
# feed exp() in the shared recompute backward)
np.testing.assert_allclose(loss_p, loss_x, rtol=1e-5, atol=1e-6)
for g_p, g_x, name in zip(grads_p, grads_x, ("dh", "dW")):
    np.testing.assert_allclose(np.asarray(g_p, np.float32),
                               np.asarray(g_x, np.float32),
                               rtol=1e-3, atol=1e-4, err_msg=name)

# non-lane-multiple vocab: the padded tile masking must hold on real Mosaic
loss_p2, _ = run("pallas", vocab=9_999)
loss_x2, _ = run("xla", vocab=9_999)
np.testing.assert_allclose(loss_p2, loss_x2, rtol=1e-5, atol=1e-6)
print(f"ON-CHIP fused-CE pallas vs xla OK; loss {loss_p:.6f} vs {loss_x:.6f}, "
      f"V=9999 {loss_p2:.6f} vs {loss_x2:.6f}")
