"""One MoE training-throughput cell (the results/moe_v5e.txt methodology,
packaged): build the small-backbone MoE config, run a multi-step in-jit
train loop fenced ONCE (utils.timing.timed_total — single dispatches are
dispatch-floor-bound on this runtime), print ms/step, tokens/sec and the
efficiency columns.

Run ONE cell per process (cross-run buffer retention skews later cells):

  python scripts/bench_moe.py --dispatch sorted --batch 16
  python scripts/bench_moe.py --dispatch sorted_scatter --batch 16  # r3 A/B
  python scripts/bench_moe.py --dispatch dense --batch 8 --remat
"""

import argparse

from cs336_systems_tpu.utils.platform import honor_cpu_request

honor_cpu_request()

import jax
import jax.numpy as jnp

from cs336_systems_tpu.models.transformer import config_for_size
from cs336_systems_tpu.optim.adamw import AdamWHparams
from cs336_systems_tpu.train import init_train_state, make_train_loop
from cs336_systems_tpu.utils.timing import emit_row, timed_total
from bench import V5E_BF16_PEAK_FLOPS, model_flops_per_token

# bench.py's MFU denominator (v5e bf16 chip peak) — shared, not redeclared,
# so the two MFU columns cannot drift.
_PEAK_TFLOPS = V5E_BF16_PEAK_FLOPS / 1e12


def flops_per_token(cfg, remat: bool, ffn_remat: bool) -> float:
    """Executed FLOPs per token: bench.model_flops_per_token (the shared
    MFU-denominator convention, MoE-aware) plus recompute terms so remat
    rows stay comparable — full-block remat re-runs one forward (+2·N +
    one causal attention forward); moe_ffn_remat re-runs only the expert
    gate/up matmuls (2 of the 3, the w2 output is dead code in the
    recompute)."""
    total = model_flops_per_token(cfg)
    d, dff, L = cfg.d_model, cfg.d_ff, cfg.num_layers
    n_ffn = L * max(cfg.moe_top_k, 1) * 3 * d * dff
    if remat:
        n = (total - 6 * cfg.context_length * d * L) / 6  # invert 6·N+attn
        total += 2 * n + 2 * cfg.context_length * d * L
    elif ffn_remat:
        total += 2 * (2 / 3) * n_ffn
    return float(total)


def main() -> None:
    p = argparse.ArgumentParser()
    p.add_argument("--dispatch", default="sorted",
                   choices=["dense", "sorted", "sorted_scatter", "gmm"])
    p.add_argument("--batch", type=int, default=16)
    p.add_argument("--experts", type=int, default=8)
    p.add_argument("--top-k", type=int, default=2)
    p.add_argument("--d-ff", type=int, default=None)
    p.add_argument("--remat", action="store_true")
    p.add_argument("--ffn-remat", action="store_true",
                   help="selective expert-FFN remat (cfg.moe_ffn_remat)")
    p.add_argument("--cf", type=float, default=1.25,
                   help="moe_capacity_factor (ignored by dispatch=gmm)")
    p.add_argument("--ctx", type=int, default=512)
    p.add_argument("--steps", type=int, default=5, help="in-jit loop length")
    p.add_argument("--iters", type=int, default=3)
    p.add_argument("--out", default=None,
                   help="append this cell as a JSON line (one process per "
                        "cell → the JSONL accumulates the sweep)")
    args = p.parse_args()

    on_tpu = jax.default_backend() == "tpu"
    overrides = {}
    if args.d_ff is not None:
        overrides["d_ff"] = args.d_ff
    cfg = config_for_size(
        "small",
        context_length=args.ctx,
        compute_dtype="bfloat16" if on_tpu else "float32",
        attn_impl="flash" if on_tpu else "xla",
        scan_layers=not on_tpu,
        remat=args.remat,
        num_experts=args.experts,
        moe_top_k=args.top_k,
        moe_dispatch=args.dispatch,
        moe_ffn_remat=args.ffn_remat,
        moe_capacity_factor=args.cf,
        **overrides,
    )
    steps = args.steps if on_tpu else 2
    batch = args.batch if on_tpu else 2
    params, opt = init_train_state(jax.random.PRNGKey(0), cfg)
    loop = make_train_loop(cfg, AdamWHparams(lr=3e-4))
    xs = jax.random.randint(
        jax.random.PRNGKey(1), (steps, batch, args.ctx), 0, cfg.vocab_size
    )
    ys = jnp.roll(xs, -1, axis=-1)

    def step(params, opt):
        p2, o2, losses = loop(params, opt, xs, ys)
        return p2, o2, losses

    res, out = timed_total(
        step, params, opt, warmup=1, iters=args.iters,
        carry=lambda out, a: (out[0], out[1]),
    )
    ms_step = res.mean_ms / steps
    tokens = batch * args.ctx
    tok_s = tokens / (ms_step / 1e3)
    # MFU counts MODEL FLOPs only (recompute is not useful work); the
    # executed column includes remat recompute so remat rows stay
    # comparable on achieved hardware FLOP rate.
    gf_model = model_flops_per_token(cfg) / 1e9
    gf_exec = flops_per_token(cfg, args.remat, args.ffn_remat) / 1e9
    mfu = tok_s * gf_model / 1e3 / _PEAK_TFLOPS
    tag = (f"small+E{args.experts}k{args.top_k}"
           + (f"/dff{cfg.d_ff}" if args.d_ff else ""))
    print(
        f"{tag} ctx{args.ctx} b{batch} cf{args.cf:g} "
        f"{'remat' if args.remat else 'no-remat'}"
        f"{'+ffn-remat' if args.ffn_remat else ''} {args.dispatch}: "
        f"{ms_step:.1f} ms/step  {tok_s:,.0f} tok/s  "
        f"{gf_model:.3f} GF/tok  "
        f"exec {tok_s * gf_exec / 1e3:.1f} TFLOP/s  {mfu * 100:.1f}% MFU",
        flush=True,
    )
    if args.out:
        emit_row({
            "tag": tag, "dispatch": args.dispatch, "ctx": args.ctx,
            "batch": batch, "cf": args.cf, "remat": args.remat,
            "ffn_remat": args.ffn_remat, "steps": steps,
            "ms_per_step": round(ms_step, 2), "tokens_per_s": round(tok_s, 1),
            "gflops_per_token": round(gf_model, 3),
            "exec_tflops": round(tok_s * gf_exec / 1e3, 2),
            "mfu": round(mfu, 4),
        }, args.out)


if __name__ == "__main__":
    main()
