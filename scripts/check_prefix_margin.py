#!/usr/bin/env python
"""CI gate: the shared-prefix engine family's analyzed memory must
undercut its unshared twin by EXACTLY the analytic N·P−P page margin.

memkit profiles ``serve_engine_prefix`` (registry geometry: dp8, 2
slots/shard sharing one P=1-page prefix — 3 real pages + scratch per
shard) and an UNSHARED twin of the same step at the same workload where
every slot owns both its blocks privately (4 real pages + scratch).
The twin's kv-cache bytes must exceed the prefix family's
kv-shared + kv-private by (N·P − P) = 1 page per shard — per device,
one page × page-bytes × layers — and the kv split itself must match the
registry's declared fraction (memkit.SERVE_KV_SPLIT). Exact equality,
not a threshold: both profiles come from the same liveness walk over
the same program, so the ONLY difference is the pool geometry; any
drift means the engine step started copying or double-buffering pages.

Run (CPU mesh): scripts/run_tests_and_package.sh invokes this inside
the prefix-cache gate.
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")
os.environ.setdefault("PALLAS_AXON_POOL_IPS", "")

import jax
import jax.numpy as jnp

from cs336_systems_tpu.analysis import memkit
from cs336_systems_tpu.analysis.registry import (
    _abstract_params,
    _tiny_cfg,
    serve_engine_prefix_geometry,
    serve_engine_prefix_state,
)
from cs336_systems_tpu.parallel.mesh import make_mesh
from cs336_systems_tpu.serving.engine import make_engine_step


def _twin_profile():
    """The unshared twin: same engine step, same slot state, but every
    slot's two blocks are PRIVATE pages — 2 slots/shard × 2 pages + the
    scratch page per shard."""
    cfg = _tiny_cfg()
    mesh = make_mesh({"dp": 8})
    slots, _, _, blk = serve_engine_prefix_geometry()
    step = make_engine_step(cfg, blk, mesh=mesh, dp_axis="dp",
                            temperature=0.9, top_k=8, donate=False)
    params = _abstract_params(cfg)
    state = list(serve_engine_prefix_state())
    twin_pages = 2 * (slots // mesh.size)
    state[-1] = jnp.tile(jnp.asarray([[0, 1], [2, 3]], jnp.int32),
                         (slots // 2, 1))
    pool = tuple(jax.ShapeDtypeStruct(
        (mesh.size * (twin_pages + 1), cfg.num_heads, blk,
         2 * cfg.d_head), cfg.cdtype) for _ in range(cfg.num_layers))
    args = (params, pool) + tuple(state)
    arg_cls = memkit._leaf_classes(
        args, memkit.ARG_CLASSES["serve_engine_prefix"])
    return memkit.profile_callable(
        step, args, family="serve_engine_prefix_unshared",
        arg_classes=arg_cls, n_devices=mesh.size)


def main() -> int:
    cfg = _tiny_cfg()
    _, pages, _, blk = serve_engine_prefix_geometry()
    shared_frac, total_frac = memkit.SERVE_KV_SPLIT["serve_engine_prefix"]
    # per-device bytes of ONE page across all layers — the N·P−P margin
    # at N=2 slots/shard, P=1 prefix page
    page_bytes = (cfg.num_heads * blk * 2 * cfg.d_head
                  * jnp.dtype(cfg.cdtype).itemsize * cfg.num_layers)

    shared = memkit.profile_family("serve_engine_prefix")
    twin = _twin_profile()

    comp = shared["composition_bytes"]
    fails = []
    if "kv-cache" in comp:
        fails.append("serve_engine_prefix still reports a raw kv-cache "
                     "class — SERVE_KV_SPLIT did not apply")
    kv_sh = comp.get("kv-shared", 0)
    kv_pr = comp.get("kv-private", 0)
    kv_total = kv_sh + kv_pr
    if kv_sh != kv_total * shared_frac // total_frac:
        fails.append(
            f"kv-shared {kv_sh} != declared {shared_frac}/{total_frac} "
            f"fraction of kv total {kv_total}")
    twin_kv = twin["composition_bytes"].get("kv-cache", 0)
    margin = twin_kv - kv_total
    if margin != page_bytes:
        fails.append(
            f"unshared-twin kv margin {margin} B/device != analytic "
            f"N·P−P = {page_bytes} B/device (1 page × {cfg.num_layers} "
            f"layers); twin kv {twin_kv}, shared kv {kv_total}")
    if shared["peak_bytes"] >= twin["peak_bytes"]:
        fails.append(
            f"shared peak {shared['peak_bytes']} not below twin peak "
            f"{twin['peak_bytes']} — the shared pool saved nothing")

    print(f"prefix-margin: shared kv {kv_sh}+{kv_pr}={kv_total} B/dev, "
          f"twin kv {twin_kv} B/dev, margin {margin} B/dev "
          f"(analytic {page_bytes}), peaks {shared['peak_bytes']} vs "
          f"{twin['peak_bytes']}")
    for f in fails:
        print(f"FAIL: {f}", file=sys.stderr)
    return 1 if fails else 0


if __name__ == "__main__":
    sys.exit(main())
