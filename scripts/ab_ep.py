"""Dense-ep (GSPMD one-hot dispatch, rounds <=4) vs indexed-ep (explicit
all-to-all + local sorted compute, round 5) A/B on the virtual 8-device
CPU mesh — multi-chip TPU hardware is not available, so the recorded
observables are hardware-independent: compiled per-step FLOPs
(XLA cost analysis) and bytes moved, plus the CPU-mesh wall for
completeness. The single-chip analogue of this comparison is measured on
real hardware in results/moe_v5e.txt (dense 33.4k vs sorted 51.0k tok/s
at b16 — the dispatch rewrite the a2a step inherits).

Run:  XLA_FLAGS=--xla_force_host_platform_device_count=8 \
      PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu python scripts/ab_ep.py
"""

import time

from cs336_systems_tpu.utils.platform import honor_cpu_request

honor_cpu_request()

import jax
import jax.numpy as jnp

from cs336_systems_tpu.models.transformer import config_for_size
from cs336_systems_tpu.optim.adamw import AdamWHparams, adamw_init
from cs336_systems_tpu.parallel.ep import make_ep_train_step, shard_params_ep
from cs336_systems_tpu.parallel.mesh import make_mesh, shard_batch
from cs336_systems_tpu.train import init_train_state


def main() -> None:
    # quarter-scale E8k2 backbone: the dense/a2a dispatch FLOP ratio is
    # structural (O(T*E*C*D) vs O(T*k*D) movement), not size-dependent,
    # and the full "small" config does not compile+run in reasonable
    # time on the 8-virtual-device CPU mesh
    from cs336_systems_tpu.models.transformer import TransformerConfig

    cfg = TransformerConfig(
        vocab_size=1024, context_length=128, d_model=128,
        num_layers=2, num_heads=4, d_ff=512, compute_dtype="float32",
        attn_impl="xla", scan_layers=True, num_experts=8, moe_top_k=2,
        moe_capacity_factor=1.25, moe_dispatch="sorted",
    )
    hp = AdamWHparams(lr=3e-4)
    mesh = make_mesh({"dp": 2, "ep": 4})
    batch = 16
    params, _ = init_train_state(jax.random.PRNGKey(0), cfg)
    x = jax.random.randint(jax.random.PRNGKey(1), (batch, 128), 0,
                           cfg.vocab_size)
    y = jnp.roll(x, -1, axis=-1)

    for variant, axes in (("a2a", ("dp", "ep")), ("dense", ("dp",))):
        print("lowering", variant, flush=True)
        import dataclasses

        vcfg = cfg if variant == "a2a" else dataclasses.replace(
            cfg, moe_dispatch="dense")
        p = shard_params_ep(params, mesh, vcfg)
        o = adamw_init(p)
        step = make_ep_train_step(vcfg, hp, mesh, donate=False,
                                  variant=variant)
        xs, ys = shard_batch(mesh, x, y, axis=axes)
        lowered = jax.jit(step).lower(p, o, xs, ys) if not hasattr(
            step, "lower") else step.lower(p, o, xs, ys)
        compiled = lowered.compile()
        ca = compiled.cost_analysis()
        ca = ca[0] if isinstance(ca, (list, tuple)) else ca
        flops = ca.get("flops", float("nan"))
        bytes_ = ca.get("bytes accessed", float("nan"))
        # wall: warmup + 3 fenced steps
        out = compiled(p, o, xs, ys)
        jax.block_until_ready(out)
        t0 = time.perf_counter()
        for _ in range(3):
            out = compiled(p, o, xs, ys)
            jax.block_until_ready(out)
        wall = (time.perf_counter() - t0) / 3
        print(f"{variant:6s} flops/step {flops:.3e}  bytes {bytes_:.3e}  "
              f"cpu-mesh wall {wall * 1e3:8.1f} ms/step")


if __name__ == "__main__":
    main()
