"""Trace the MoE train step (E8k2 sorted peak cell of results/moe_v5e.txt)
and print the phase-attributed device-time breakdown (tracekit).

Thin wrapper over ``analysis/tracekit.profile_callable`` at the MoE bench
shapes. The phase rows give routing its own line (router matmul + softmax
+ the _prefix_count bookkeeping) next to fwd-attn/fwd-ffn/bwd — the
attribution the round-3 artifact could only infer from the dense/sorted
split. The written StepProfile diffs across dispatch schemes or rounds
via ``trace_cli --diff``.

Usage: PYTHONPATH=.:$PYTHONPATH python scripts/trace_moe_step.py \
          [--dispatch sorted|sorted_scatter|dense|gmm] [--batch 16] \
          [--ffn-remat] [--out moe.stepprofile.json]
"""

import argparse

from cs336_systems_tpu.utils.platform import honor_cpu_request

honor_cpu_request()

import jax
import jax.numpy as jnp

from cs336_systems_tpu.analysis import tracekit
from cs336_systems_tpu.analysis.flops import model_flops_per_token
from cs336_systems_tpu.models.transformer import config_for_size
from cs336_systems_tpu.optim.adamw import AdamWHparams
from cs336_systems_tpu.train import init_train_state, make_train_loop


def main() -> None:
    p = argparse.ArgumentParser()
    p.add_argument("--dispatch", default="sorted",
                   choices=["dense", "sorted", "sorted_scatter", "gmm"])
    p.add_argument("--batch", type=int, default=16)
    p.add_argument("--experts", type=int, default=8)
    p.add_argument("--top-k", type=int, default=2)
    p.add_argument("--steps", type=int, default=5)
    p.add_argument("--ffn-remat", action="store_true")
    p.add_argument("--d-ff", type=int, default=None)
    p.add_argument("--cf", type=float, default=1.25)
    p.add_argument("--out", default="moe.stepprofile.json",
                   help="StepProfile JSON path")
    args = p.parse_args()

    on_tpu = jax.default_backend() == "tpu"
    # CPU smoke: the 125M MoE step in float32 is minutes per trace at the
    # bench shapes — shrink to one short-context step (same code paths).
    steps = args.steps if on_tpu else 1
    batch = args.batch if on_tpu else 2
    ctx = 512 if on_tpu else 256
    cfg = config_for_size(
        "small",
        context_length=ctx,
        compute_dtype="bfloat16" if on_tpu else "float32",
        attn_impl="flash" if on_tpu else "xla",
        scan_layers=not on_tpu,
        num_experts=args.experts,
        moe_top_k=args.top_k,
        moe_dispatch=args.dispatch,
        moe_ffn_remat=args.ffn_remat,
        moe_capacity_factor=args.cf,
        **({"d_ff": args.d_ff} if args.d_ff else {}),
    )
    params, opt = init_train_state(jax.random.PRNGKey(0), cfg)
    # donate=False: the traced call repeats on the same buffers
    loop = make_train_loop(cfg, AdamWHparams(lr=3e-4), donate=False)
    xs = jax.random.randint(
        jax.random.PRNGKey(1), (steps, batch, ctx), 0, cfg.vocab_size
    )
    ys = jnp.roll(xs, -1, axis=-1)

    profile = tracekit.profile_callable(
        loop, (params, opt, xs, ys), iters=1,
        tokens_per_step=batch * ctx * steps,  # one call = `steps` steps
        flops_per_token=model_flops_per_token(cfg),
        family=f"moe_{args.dispatch}_E{args.experts}k{args.top_k}_b{batch}",
    )
    print(tracekit.format_profile(profile))
    per_step = profile["total_device_ms_per_step"] / steps
    tok_s = batch * ctx / (per_step / 1e3) if per_step else 0.0
    print(f"  per optimizer step: {per_step:.1f} ms "
          f"({tok_s:,.0f} tok/s device-bound)")
    tracekit.write_profile(profile, args.out)
    print(f"wrote {args.out}")


if __name__ == "__main__":
    main()
