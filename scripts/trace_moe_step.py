"""Trace the MoE train step (E8k2 sorted peak cell of results/moe_v5e.txt)
and print the device-time breakdown per op.

Same measurement recipe as trace_headline_step.py (CLAUDE.md: host
wall-clocks are dispatch-bound on this runtime; trust device-lane totals):
compile+warm a multi-step in-jit loop once, trace a second run, summarize
leaf-op totals. This is the per-op attribution behind the MoE MFU work —
the round-3 artifact *inferred* "XLA scatter/gather, not FLOPs" from the
dense/sorted split; this script measures it directly.

Usage: PYTHONPATH=.:$PYTHONPATH python scripts/trace_moe_step.py \
          [--dispatch sorted|sorted_scatter|dense] [--batch 16] \
          [--ffn-remat] [--logdir DIR]
"""

import argparse

from cs336_systems_tpu.utils.platform import honor_cpu_request

honor_cpu_request()

import jax
import jax.numpy as jnp

from cs336_systems_tpu.models.transformer import config_for_size
from cs336_systems_tpu.optim.adamw import AdamWHparams
from cs336_systems_tpu.train import init_train_state, make_train_loop
from cs336_systems_tpu.utils.profiling import summarize_trace, trace


def main() -> None:
    p = argparse.ArgumentParser()
    p.add_argument("--dispatch", default="sorted",
                   choices=["dense", "sorted", "sorted_scatter", "gmm"])
    p.add_argument("--batch", type=int, default=16)
    p.add_argument("--experts", type=int, default=8)
    p.add_argument("--top-k", type=int, default=2)
    p.add_argument("--steps", type=int, default=5)
    p.add_argument("--ffn-remat", action="store_true")
    p.add_argument("--d-ff", type=int, default=None)
    p.add_argument("--cf", type=float, default=1.25)
    p.add_argument("--logdir", default="/tmp/moe_trace")
    args = p.parse_args()

    on_tpu = jax.default_backend() == "tpu"
    steps = args.steps if on_tpu else 2
    batch = args.batch if on_tpu else 2
    cfg = config_for_size(
        "small",
        context_length=512,
        compute_dtype="bfloat16" if on_tpu else "float32",
        attn_impl="flash" if on_tpu else "xla",
        scan_layers=not on_tpu,
        num_experts=args.experts,
        moe_top_k=args.top_k,
        moe_dispatch=args.dispatch,
        moe_ffn_remat=args.ffn_remat,
        moe_capacity_factor=args.cf,
        **({"d_ff": args.d_ff} if args.d_ff else {}),
    )
    params, opt = init_train_state(jax.random.PRNGKey(0), cfg)
    loop = make_train_loop(cfg, AdamWHparams(lr=3e-4))
    xs = jax.random.randint(
        jax.random.PRNGKey(1), (steps, batch, 512), 0, cfg.vocab_size
    )
    ys = jnp.roll(xs, -1, axis=-1)

    params, opt, losses = loop(params, opt, xs, ys)  # compile + warm
    float(losses[-1])
    with trace(args.logdir):
        params, opt, losses = loop(params, opt, xs, ys)
        float(losses[-1])

    rows, total = summarize_trace(args.logdir)
    tokens = batch * 512
    print(
        f"dispatch={args.dispatch} E{args.experts}k{args.top_k} b{batch}: "
        f"leaf device time {total / steps:.1f} ms/step "
        f"({tokens * steps / (total / 1e3):,.0f} tok/s device-bound)"
    )
    print(f"{'op':40s} {'ms/step':>9s} {'count':>7s} {'mean_us':>9s}")
    for r in rows[:40]:
        print(
            f"{r['op'][:40]:40s} {r['total_ms'] / steps:9.3f} "
            f"{r['count']:7d} {r['mean_us']:9.1f}"
        )


if __name__ == "__main__":
    main()
