import jax, jax.numpy as jnp, numpy as np
from cs336_systems_tpu.models.moe import init_moe, moe_ffn

key = jax.random.PRNGKey(0)
d, f, e = 768, 3072, 8
moe = init_moe(key, d, f, e)
x = jax.random.normal(jax.random.PRNGKey(1), (4, 128, d), jnp.bfloat16)

def run(dispatch):
    def loss(p):
        out, aux = moe_ffn(x=x, params=p, top_k=2, capacity_factor=64.0,
                           dispatch=dispatch, compute_dtype=jnp.bfloat16)
        return jnp.sum(out.astype(jnp.float32) ** 2) * 1e-3 + 0.01 * aux
    out, aux = moe_ffn(x=x, params=moe, top_k=2, capacity_factor=64.0,
                       dispatch=dispatch, compute_dtype=jnp.bfloat16)
    g = jax.grad(loss)(moe)
    return np.asarray(out, np.float32), float(aux), g

o_g, a_g, g_g = run("gmm")       # Pallas kernels, native on TPU
o_s, a_s, g_s = run("sorted")    # XLA path
np.testing.assert_allclose(o_g, o_s, rtol=2e-2, atol=2e-2)  # bf16 dot-order
assert abs(a_g - a_s) < 1e-4
leaves_g = jax.tree_util.tree_leaves(g_g)
leaves_s = jax.tree_util.tree_leaves(g_s)
for lg, ls in zip(leaves_g, leaves_s):
    np.testing.assert_allclose(np.asarray(lg, np.float32), np.asarray(ls, np.float32),
                               rtol=5e-2, atol=5e-2)
rel = max(float(jnp.max(jnp.abs(lg.astype(jnp.float32) - ls.astype(jnp.float32)))) for lg, ls in zip(leaves_g, leaves_s))
print("ON-CHIP gmm vs sorted OK; max abs grad diff", rel)
