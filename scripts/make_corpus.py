"""Build a byte-level training corpus from Python source text on disk.

The environment has no network egress, so the proof-of-learning run
(results/train_small_v5e.txt) trains on real text that ships with the
image: the Python standard library's own source files. Tokens are raw
bytes (ids 0-255), stored uint16 so the corpus drops straight into
``train_cli --corpus`` with the flagship vocab (10k) unchanged — the
model simply never sees ids >= 256.

Usage: python scripts/make_corpus.py [--out /tmp/corpus.npy] [--mb 24]
"""

from __future__ import annotations

import argparse
import pathlib
import sys

import numpy as np


def main() -> None:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--out", default="/tmp/corpus.npy")
    p.add_argument("--mb", type=float, default=24.0,
                   help="approximate corpus size in MB")
    p.add_argument("--root", default=None,
                   help="source tree to read (default: the running "
                        "Python's stdlib directory)")
    args = p.parse_args()

    root = pathlib.Path(args.root or pathlib.Path(sys.modules["os"].__file__).parent)
    budget = int(args.mb * 1e6)
    chunks: list[bytes] = []
    total = 0
    for f in sorted(root.rglob("*.py")):
        try:
            data = f.read_bytes()
        except OSError:
            continue
        chunks.append(data + b"\n\x00")  # NUL as document separator
        total += len(data) + 2
        if total >= budget:
            break
    corpus = np.frombuffer(b"".join(chunks), dtype=np.uint8).astype(np.uint16)
    np.save(args.out, corpus)
    print(f"{args.out}: {corpus.size:,} byte tokens from {len(chunks)} files "
          f"under {root}")


if __name__ == "__main__":
    main()
