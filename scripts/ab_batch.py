"""Same-process batch-size sweep of the headline train step.

Round-2 measured batch 32 as the tokens/sec peak for the headline config.
The round-3 kernels changed the step's composition (fused rope removed
most layout copies; the single-tile forward cut VPU work), so the peak is
re-measured here: each batch gets its own jitted 10-step loop, same
process, best-of-3, tokens/sec compared directly.

Usage: PYTHONPATH=.:$PYTHONPATH python scripts/ab_batch.py [batches...]
"""

from __future__ import annotations

import sys
import time

import jax
import jax.numpy as jnp

from cs336_systems_tpu.models.transformer import config_for_size
from cs336_systems_tpu.optim.adamw import AdamWHparams
from cs336_systems_tpu.train import init_train_state, make_train_loop


def main() -> None:
    assert jax.default_backend() == "tpu", jax.default_backend()
    batches = [int(a) for a in sys.argv[1:]] or [24, 32, 40, 48, 64]
    ctx, timed = 512, 10
    cfg = config_for_size(
        "small", context_length=ctx, compute_dtype="bfloat16",
        attn_impl="flash", scan_layers=False,
    )
    hp = AdamWHparams(lr=3e-4)
    for batch in batches:
        params, opt_state = init_train_state(jax.random.PRNGKey(0), cfg)
        loop = make_train_loop(cfg, hp)
        xs = jax.random.randint(
            jax.random.PRNGKey(1), (timed, batch, ctx), 0, cfg.vocab_size
        )
        ys = jnp.roll(xs, -1, axis=-1)
        try:
            params, opt_state, losses = loop(params, opt_state, xs, ys)
            float(losses[-1])
            dt = float("inf")
            for _ in range(3):
                t0 = time.perf_counter()
                params, opt_state, losses = loop(params, opt_state, xs, ys)
                float(losses[-1])
                dt = min(dt, time.perf_counter() - t0)
            toks = batch * ctx * timed / dt
            print(f"batch {batch:4d}  {dt * 1e3 / timed:7.1f} ms/step  "
                  f"{toks:9.0f} tok/s", flush=True)
        except Exception as e:  # noqa: BLE001 — record over-HBM cells
            print(f"batch {batch:4d}  FAILED: {type(e).__name__}: "
                  f"{str(e)[:160]}", flush=True)
        finally:
            del params, opt_state
    print("done", flush=True)


if __name__ == "__main__":
    main()
