#!/usr/bin/env bash
# Test-and-package harness — parity with the reference's
# test_and_make_submission.sh:1-32 (runs the full pytest suite with a JUnit
# XML report, then zips the tree minus caches/artifacts).
#
# Usage: scripts/run_tests_and_package.sh [out.zip]
set -uo pipefail
cd "$(dirname "$0")/.."

OUT="${1:-cs336_systems_tpu_submission.zip}"

# Hermetic CPU run with the 8-device virtual mesh (same env the test
# conftest selects; the env vars also cover any site TPU plugin). The zip is
# produced even when tests fail (the reference tolerates failures at package
# time), but the failure is NOT masked: the script exits with pytest's code.
XLA_FLAGS="--xla_force_host_platform_device_count=8" \
JAX_PLATFORMS=cpu PALLAS_AXON_POOL_IPS= \
python -m pytest -v tests/ --junitxml=test_results.xml
status=$?

# graft-lint gate: the static performance-contract checks must pass too
# (collective counts per sharding family, donation aliasing, TPU
# anti-pattern lints, Pallas VMEM budgets — see analysis/README.md).
JAX_PLATFORMS=cpu PALLAS_AXON_POOL_IPS= \
python -m cs336_systems_tpu.analysis.lint
lint_status=$?
[ "$status" -eq 0 ] && status=$lint_status

# tracekit gate: one measured StepProfile end to end (trace -> HLO join ->
# phase x class attribution -> MFU) on the hermetic CPU mesh. Catches
# profiler/HLO-name drift that the static lint cannot see.
JAX_PLATFORMS=cpu PALLAS_AXON_POOL_IPS= \
python -m cs336_systems_tpu.analysis.trace_cli --step train_single \
    --iters 1 --out /tmp/trace_smoke.stepprofile.json
trace_status=$?
[ "$status" -eq 0 ] && status=$trace_status

# memkit gate: one analyzed memprofile end to end (lower -> scheduled-HLO
# liveness walk -> phase x class composition -> memory_analysis cross-
# check), then the self-diff must flag nothing (exit 0) — together they
# catch HLO-format drift that would silently break the memory accounting.
JAX_PLATFORMS=cpu PALLAS_AXON_POOL_IPS= \
python -m cs336_systems_tpu.analysis.mem_cli --step train_single \
    --out /tmp/mem_smoke.memprofile.json
mem_status=$?
if [ "$mem_status" -eq 0 ]; then
    JAX_PLATFORMS=cpu PALLAS_AXON_POOL_IPS= \
    python -m cs336_systems_tpu.analysis.mem_cli \
        --diff /tmp/mem_smoke.memprofile.json /tmp/mem_smoke.memprofile.json
    mem_status=$?
fi
[ "$status" -eq 0 ] && status=$mem_status

# schedkit gate (ISSUE 13): the static dependence/critical-path analyzer
# end to end on the three families whose contracts lean on it — train_tp
# (GSPMD collectives + slack floors), train_ep_a2a (shard_map a2a + the
# gradsan-twin grad sync) and serve_engine_prefix (decode-only collective
# contract). Each schedprofile must build (composition sums and the
# census/op_map cross-check are asserted inside profile_hlo) and
# self-diff to exit 0; the fresh train_tp artifact is then diffed against
# the committed baseline in results/schedprofiles/ — the analytic model
# is deterministic, so ANY delta is real drift (cost model, parser, or
# the step's actual HLO) and must be triaged, not absorbed.
sched_status=0
for fam in train_tp train_ep_a2a serve_engine_prefix; do
    JAX_PLATFORMS=cpu PALLAS_AXON_POOL_IPS= \
    python -m cs336_systems_tpu.analysis.sched_cli --step "$fam" \
        --out "/tmp/sched_$fam.schedprofile.json" \
        || { sched_status=$?; echo "schedkit: $fam FAILED" >&2; }
    if [ "$sched_status" -eq 0 ]; then
        JAX_PLATFORMS=cpu PALLAS_AXON_POOL_IPS= \
        python -m cs336_systems_tpu.analysis.sched_cli \
            --diff "/tmp/sched_$fam.schedprofile.json" \
                   "/tmp/sched_$fam.schedprofile.json" \
            || { sched_status=$?
                 echo "schedkit: $fam self-diff FAILED" >&2; }
    fi
done
if [ "$sched_status" -eq 0 ]; then
    JAX_PLATFORMS=cpu PALLAS_AXON_POOL_IPS= \
    python -m cs336_systems_tpu.analysis.sched_cli \
        --diff results/schedprofiles/train_tp.schedprofile.json \
               /tmp/sched_train_tp.schedprofile.json
    sched_status=$?
fi
[ "$status" -eq 0 ] && status=$sched_status

# paged-serving gate: the skewed ragged family through BOTH analysis
# pipelines — a traced StepProfile (phase attribution must see the paged
# kv-update scopes) and an analyzed memprofile under the family's HBM
# budget (the paged pool's whole point is the kv-cache line item).
JAX_PLATFORMS=cpu PALLAS_AXON_POOL_IPS= \
python -m cs336_systems_tpu.analysis.trace_cli --step serve_ragged_paged \
    --iters 1 --out /tmp/paged_smoke.stepprofile.json
paged_status=$?
if [ "$paged_status" -eq 0 ]; then
    JAX_PLATFORMS=cpu PALLAS_AXON_POOL_IPS= \
    python -m cs336_systems_tpu.analysis.mem_cli --step serve_ragged_paged \
        --out /tmp/paged_smoke.memprofile.json
    paged_status=$?
fi
[ "$status" -eq 0 ] && status=$paged_status

# serve-engine gate: the continuous-batching engine's steady-state step
# through both analysis pipelines (the trace must carry all four serve
# phase scopes; the memprofile must attribute the page pool under the
# kv-cache class inside the declared budget), then a tiny poisson smoke
# through the REAL engine loop — all requests must complete and the page
# allocator must end fully free (ServingEngine.check_idle raises on a
# leaked page, which fails the cell and this gate).
JAX_PLATFORMS=cpu PALLAS_AXON_POOL_IPS= \
python -m cs336_systems_tpu.analysis.trace_cli --step serve_engine \
    --iters 1 --out /tmp/engine_smoke.stepprofile.json
engine_status=$?
if [ "$engine_status" -eq 0 ]; then
    JAX_PLATFORMS=cpu PALLAS_AXON_POOL_IPS= \
    python -m cs336_systems_tpu.analysis.mem_cli --step serve_engine \
        --out /tmp/engine_smoke.memprofile.json
    engine_status=$?
fi
if [ "$engine_status" -eq 0 ]; then
    JAX_PLATFORMS=cpu PALLAS_AXON_POOL_IPS= \
    python -m cs336_systems_tpu.benchmarks.serving --test-model \
        --requests 10 --loads 20 --new 6 --profiles uniform zipf spike \
        --out /tmp/engine_smoke.jsonl
    engine_status=$?
fi
[ "$status" -eq 0 ] && status=$engine_status

# prefix-cache gate (ISSUE 9): the shared-prefix engine family through
# both analysis pipelines (the step program must stay byte-identical to
# serve_engine's — lint pins the decode-only collective contract
# verbatim, so prefix reuse adds ZERO collectives), the analytic N·P−P
# memory margin vs the unshared twin (scripts/check_prefix_margin.py,
# exact equality over memkit's kv-shared/kv-private split), then a
# poisson smoke WITH a shared system prompt through the real engine
# loop — requests must complete with a non-trivial prefix_hit_rate and
# the drain must leave every page free (run_cell's check_idle spills
# the cache and runs PagePool.check_all_free; a leak fails the cell).
JAX_PLATFORMS=cpu PALLAS_AXON_POOL_IPS= \
python -m cs336_systems_tpu.analysis.trace_cli --step serve_engine_prefix \
    --iters 1 --out /tmp/prefix_smoke.stepprofile.json
prefix_status=$?
if [ "$prefix_status" -eq 0 ]; then
    JAX_PLATFORMS=cpu PALLAS_AXON_POOL_IPS= \
    python scripts/check_prefix_margin.py
    prefix_status=$?
fi
if [ "$prefix_status" -eq 0 ]; then
    JAX_PLATFORMS=cpu PALLAS_AXON_POOL_IPS= \
    python -m cs336_systems_tpu.benchmarks.serving --test-model \
        --requests 10 --loads 20 --new 6 --shared-prefix 16 \
        --profiles uniform zipf spike --out /tmp/prefix_smoke.jsonl
    prefix_status=$?
fi
if [ "$prefix_status" -eq 0 ]; then
    # the smoke must actually exercise sharing: every cell's hit rate > 0
    python - <<'EOF'
import json, sys
rows = [json.loads(l) for l in open("/tmp/prefix_smoke.jsonl")]
bad = [r["name"] for r in rows if r["prefix_hit_rate"] <= 0
       or r["shared_kv_bytes"] <= 0]
sys.exit(1 if bad or not rows else 0)
EOF
    prefix_status=$?
fi
[ "$status" -eq 0 ] && status=$prefix_status

# servetrace gate (ISSUE 12): the serving flight recorder end to end —
# replay a seeded poisson trace through the shared-prefix engine family,
# fold the flight log into the servetrace/v1 artifact (decomposition,
# host-phase breakdown, conservation), then the self-diff must flag
# nothing. --no-device-join keeps the gate fast (the tracekit join is
# covered by the engine trace gates above).
JAX_PLATFORMS=cpu PALLAS_AXON_POOL_IPS= \
python -m cs336_systems_tpu.analysis.serve_trace_cli --run \
    --step serve_engine_prefix --no-device-join \
    --out /tmp/servetrace_smoke.json
servetrace_status=$?
if [ "$servetrace_status" -eq 0 ]; then
    JAX_PLATFORMS=cpu PALLAS_AXON_POOL_IPS= \
    python -m cs336_systems_tpu.analysis.serve_trace_cli \
        --diff /tmp/servetrace_smoke.json /tmp/servetrace_smoke.json
    servetrace_status=$?
fi
[ "$status" -eq 0 ] && status=$servetrace_status

# gradsan gate: the differential numerics sanitizer on the two composed
# families whose parity regression it root-caused (the a2a grad sync and
# the sp/dp flat sync — parallel/ep.py, parallel/sp.py): the sharded
# step must match the single-device oracle at every stage (exit 0); any
# future reduction defect exits 1 naming the first divergent
# (stage, leaf).
JAX_PLATFORMS=cpu PALLAS_AXON_POOL_IPS= \
python -m cs336_systems_tpu.analysis.gradsan --step train_ep_a2a --json \
    > /tmp/gradsan_ep.json
gradsan_status=$?
if [ "$gradsan_status" -eq 0 ]; then
    JAX_PLATFORMS=cpu PALLAS_AXON_POOL_IPS= \
    python -m cs336_systems_tpu.analysis.gradsan --step train_tp_sp --json \
        > /tmp/gradsan_tp_sp.json
    gradsan_status=$?
fi
[ "$status" -eq 0 ] && status=$gradsan_status

# chunked-CE memory gate: sign assertions on freshly built chunked vs
# chunking-disabled (ce_chunk_size=0) twins — loss-phase high-water must
# drop by at least one full [B,S,V] logits buffer at BOTH the registry
# lint shape and the 32k-vocab loop — plus a 1% drift check of the fresh
# train_single peak against the committed pre-change memprofile
# (results/memprofiles/). This subsumes a raw `mem_cli --diff` against
# that artifact: the dual noise gate cannot assert a sign, and the new
# `loss` phase scope would flag by construction (missing phase == 0).
JAX_PLATFORMS=cpu PALLAS_AXON_POOL_IPS= \
python scripts/check_ce_memory_gate.py
ce_status=$?
# ... and the raw diff against the pre-change artifact must FLAG the loss
# phase (exit 1 — the phase is new + its high-water moved; exit 0 would
# mean the chunked loss path silently stopped changing the profile)
if [ "$ce_status" -eq 0 ] && [ -f /tmp/mem_smoke.memprofile.json ]; then
    JAX_PLATFORMS=cpu PALLAS_AXON_POOL_IPS= \
    python -m cs336_systems_tpu.analysis.mem_cli \
        --diff results/memprofiles/train_single.pre_chunked_ce.memprofile.json \
        /tmp/mem_smoke.memprofile.json
    [ $? -eq 1 ] || ce_status=1
fi
# the gradsan seam must still trip: seeding the broken cross-vocab-shard
# max correction has to exit 1 at the loss stage on a tp family
if [ "$ce_status" -eq 0 ]; then
    JAX_PLATFORMS=cpu PALLAS_AXON_POOL_IPS= \
    python -m cs336_systems_tpu.analysis.gradsan --step train_tp --json \
        --mutate drop-lse-correction > /tmp/gradsan_ce_mutate.json
    [ $? -eq 1 ] || ce_status=1
fi
[ "$status" -eq 0 ] && status=$ce_status

# servesan gate (ISSUE 10): the serving chaos harness — EVERY seeded
# fault class must surface its expected typed serving error (exit 0 per
# fault; a missed/misclassified detection exits 1, a broken trace 2),
# then the clean run must drain with zero findings. Iterates --list so
# a fault class added to serving/chaos.py is gated automatically.
servesan_status=0
for fault in $(JAX_PLATFORMS=cpu PALLAS_AXON_POOL_IPS= \
        python -m cs336_systems_tpu.serving.chaos --list --json \
        | python -c "import json,sys; print(' '.join(json.load(sys.stdin)['faults']))"); do
    JAX_PLATFORMS=cpu PALLAS_AXON_POOL_IPS= \
    python -m cs336_systems_tpu.serving.chaos --fault "$fault" --json \
        > "/tmp/servesan_$fault.json" \
        || { servesan_status=$?; echo "servesan: fault $fault FAILED" >&2; }
done
if [ "$servesan_status" -eq 0 ]; then
    # the full matrix (all faults + the clean false-positive run) on the
    # sharded engine too — detection must not be a single-device accident
    JAX_PLATFORMS=cpu PALLAS_AXON_POOL_IPS= \
    python -m cs336_systems_tpu.serving.chaos --mesh dp8 --json \
        > /tmp/servesan_dp8.json
    servesan_status=$?
fi
[ "$status" -eq 0 ] && status=$servesan_status

# trainsan gate (ISSUE 11): the training-plane chaos harness — every
# seeded checkpoint/blow-up fault must surface its typed
# utils.errors exception AND recover bit-identical to the uninterrupted
# oracle (exit 0 per fault; missed/not-bit-exact 1, broken build 2).
# Iterates --list so a fault class added to analysis/trainsan.py is
# gated automatically; kill-mid-save doubles as the kill→resume smoke
# (it resumes from every kill point and asserts curve equality).
trainsan_status=0
for fault in $(JAX_PLATFORMS=cpu PALLAS_AXON_POOL_IPS= \
        python -m cs336_systems_tpu.analysis.trainsan --list --json \
        | python -c "import json,sys; print(' '.join(json.load(sys.stdin)['faults']))"); do
    JAX_PLATFORMS=cpu PALLAS_AXON_POOL_IPS= \
    python -m cs336_systems_tpu.analysis.trainsan --fault "$fault" --json \
        > "/tmp/trainsan_$fault.json" \
        || { trainsan_status=$?; echo "trainsan: fault $fault FAILED" >&2; }
done
if [ "$trainsan_status" -eq 0 ]; then
    # matrix parity: the full run (all faults + clean) on the sharded
    # families — verdicts must not be a single-device accident (dp
    # replicates, zero1 shards the opt state it checkpoints)
    for mode in dp zero1; do
        JAX_PLATFORMS=cpu PALLAS_AXON_POOL_IPS= \
        python -m cs336_systems_tpu.analysis.trainsan --mode "$mode" \
            --json > "/tmp/trainsan_$mode.json" \
            || { trainsan_status=$?
                 echo "trainsan: mode $mode FAILED" >&2; }
    done
fi
[ "$status" -eq 0 ] && status=$trainsan_status

# fleetsan gate (ISSUE 14): the fleet-router chaos harness — every
# seeded fleet-level fault (replica crash/hang/poison, routing-table
# corruption, duplicate dispatch, stale affinity, shed storm) must
# surface its expected typed serving error with surviving streams
# bit-exact to the single-replica oracle (exit 0 per fault; a missed or
# misclassified detection 1, a broken fleet build 2), then the clean
# fleet must drain with zero findings. Iterates --list so a fault class
# added to serving/fleet_chaos.py is gated automatically.
fleetsan_status=0
for fault in $(JAX_PLATFORMS=cpu PALLAS_AXON_POOL_IPS= \
        python -m cs336_systems_tpu.serving.fleet_chaos --list --json \
        | python -c "import json,sys; print(' '.join(json.load(sys.stdin)['faults']))"); do
    JAX_PLATFORMS=cpu PALLAS_AXON_POOL_IPS= \
    python -m cs336_systems_tpu.serving.fleet_chaos --fault "$fault" --json \
        > "/tmp/fleetsan_$fault.json" \
        || { fleetsan_status=$?; echo "fleetsan: fault $fault FAILED" >&2; }
done
if [ "$fleetsan_status" -eq 0 ]; then
    # matrix parity: the full run (all faults + clean) with dp2-sharded
    # replicas — the router is host-side control plane, so verdicts must
    # be identical when each replica's step program is sharded
    JAX_PLATFORMS=cpu PALLAS_AXON_POOL_IPS= \
    python -m cs336_systems_tpu.serving.fleet_chaos --mesh dp2 --json \
        > /tmp/fleetsan_dp2.json
    fleetsan_status=$?
fi
if [ "$fleetsan_status" -eq 0 ]; then
    # replica-kill-mid-trace recovery smoke through the REAL benchmark
    # driver: kill 1 of 3 replicas mid-trace and require every request
    # to still complete (ample survivor capacity → failovers, not sheds)
    JAX_PLATFORMS=cpu PALLAS_AXON_POOL_IPS= \
    python -m cs336_systems_tpu.benchmarks.serving --test-model \
        --requests 10 --loads 20 --new 6 --replicas 3 --router affinity \
        --kill-replica-at 3 --out /tmp/fleet_kill_smoke.jsonl
    fleetsan_status=$?
fi
if [ "$fleetsan_status" -eq 0 ]; then
    python - <<'EOF'
import json, sys
rows = [json.loads(l) for l in open("/tmp/fleet_kill_smoke.jsonl")]
bad = [r["name"] for r in rows
       if r["shed"] != 0 or r["completed"] != r["requests"]
       or r["failovers"] < 1 or r["quarantines"] != 1]
sys.exit(1 if bad or not rows else 0)
EOF
    fleetsan_status=$?
fi
[ "$status" -eq 0 ] && status=$fleetsan_status

# chunked-prefill gate (ISSUE 15): the interleaved-prefill engine family
# through both analysis pipelines (the decode step program must stay
# byte-identical to serve_engine's — lint pins the decode-only
# collective contract verbatim, so chunking adds ZERO collectives), then
# the deterministic stall gate (scripts/check_chunked_prefill_gate.py:
# chunked streams bit-identical to the monolithic baseline, per-step
# prefill bill <= prefill_budget from the flight records, and
# prefill_stall_p99 STRICTLY down on a work-proportional virtual clock),
# then a spike twin-cell run through the REAL benchmark driver — the
# chunked cell and its identically-seeded unchunked twin must complete
# every request (equal completed-request goodput) with the budget bound
# holding in the engine telemetry. The two chunked servesan faults
# (torn-chunk-state, leaked-chunk-pages) ride the --list loop above.
JAX_PLATFORMS=cpu PALLAS_AXON_POOL_IPS= \
python -m cs336_systems_tpu.analysis.trace_cli --step serve_engine_chunked \
    --iters 1 --out /tmp/chunked_smoke.stepprofile.json
chunked_status=$?
if [ "$chunked_status" -eq 0 ]; then
    JAX_PLATFORMS=cpu PALLAS_AXON_POOL_IPS= \
    python -m cs336_systems_tpu.analysis.mem_cli --step serve_engine_chunked \
        --out /tmp/chunked_smoke.memprofile.json
    chunked_status=$?
fi
if [ "$chunked_status" -eq 0 ]; then
    JAX_PLATFORMS=cpu PALLAS_AXON_POOL_IPS= \
    python scripts/check_chunked_prefill_gate.py
    chunked_status=$?
fi
if [ "$chunked_status" -eq 0 ]; then
    JAX_PLATFORMS=cpu PALLAS_AXON_POOL_IPS= \
    python -m cs336_systems_tpu.benchmarks.serving --test-model \
        --requests 10 --loads 20 --new 6 --profiles spike \
        --prefill-chunk 8 --out /tmp/chunked_smoke.jsonl
    chunked_status=$?
fi
if [ "$chunked_status" -eq 0 ]; then
    python - <<'EOF'
import json, sys
rows = [json.loads(l) for l in open("/tmp/chunked_smoke.jsonl")]
bad = [r["name"] for r in rows
       if r["completed"] != r["requests"]
       or r["unchunked_completed"] != r["requests"]
       or r["prefill_chunks"] < 1
       or r["max_step_prefill_tokens"] > r["prefill_budget"]]
sys.exit(1 if bad or not rows else 0)
EOF
    chunked_status=$?
fi
[ "$status" -eq 0 ] && status=$chunked_status

zip -r "$OUT" . \
    -x "*.git*" -x "*__pycache__*" -x "*.pytest_cache*" \
    -x "*.zip" -x "*.npz" -x "*jax_trace*" -x "*.whl" -x "*.so" \
    >/dev/null
echo "wrote $OUT"
unzip -l "$OUT" | tail -1
[ "$status" -ne 0 ] && echo "WARNING: test suite failed (exit $status)" >&2
exit "$status"
