"""Probe: native int8 MXU dots for the decode-attention kernel.

Round 3 recorded the FIRST int8 negative: an int8 KV cache with in-VMEM
dequant loses to bf16 (50.4 vs 38.3 us/call at S=256; 203 vs 138 at
S=1024) — the full-slab dequant elementwise pass costs more than the
halved DMA saves. Its stated escape hatch: do the score dot NATIVELY in
int8 (q quantized too, per-row scales folded into the scores after the
dot) so only the V half needs dequantizing for the weighted-sum dot.
This probe builds that kernel and measures it.

Kernel variants at the serving shape (rows = B·H, packed W = 2·Dh):
  bf16  — attend-only bf16 kernel (the baseline math of
          ops/decode_attention.py without the column update)
  i8    — int8 K/V slab: scores = dot_general(q_i8, k_i8) -> int32 on the
          MXU, scaled by qs[row]·ks post-dot; V half dequantized in VMEM
          (half the round-3 dequant) for the bf16 weighted-sum dot.

Measured on v5e via a chained in-jit loop (dispatch floor amortized).
Verdict recorded in results/decode_v5e.txt.
"""

import argparse

from cs336_systems_tpu.utils.platform import honor_cpu_request

honor_cpu_request()

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from cs336_systems_tpu.utils.timing import timed_total


def _attend_bf16_kernel(q_ref, kv_ref, o_ref, *, scale):
    g, _, w = q_ref.shape
    d = w // 2
    kv = kv_ref[:]  # [G, S, W]
    k = kv[:, :, :d]
    v = kv[:, :, d:]
    s = jax.lax.dot_general(
        q_ref[:, :, :d], k, (((2,), (2,)), ((0,), (0,))),
        preferred_element_type=jnp.float32,
    ) * scale  # [G, 8, S]
    p = jax.nn.softmax(s, axis=-1)
    o_ref[:] = jax.lax.dot_general(
        p.astype(v.dtype), v, (((2,), (1,)), ((0,), (0,))),
        preferred_element_type=jnp.float32,
    ).astype(o_ref.dtype)


def _attend_i8_kernel(q_ref, qs_ref, kv_ref, ks_ref, o_ref, *, scale):
    g, _, w = q_ref.shape
    d = w // 2
    kv = kv_ref[:]  # [G, S, W] int8
    k = kv[:, :, :d]
    v = kv[:, :, d:]
    # native int8 MXU dot -> int32; per-row scales folded AFTER the dot
    s32 = jax.lax.dot_general(
        q_ref[:, :, :d], k, (((2,), (2,)), ((0,), (0,))),
        preferred_element_type=jnp.int32,
    )  # [G, 8, S]
    qs = qs_ref[:]  # [G, 8]
    ks = ks_ref[:]  # [G, S]
    s = s32.astype(jnp.float32) * (scale * qs[:, :, None]) * ks[:, None, :]
    p = jax.nn.softmax(s, axis=-1)
    # only the V half dequantizes (half the round-3 full-slab pass)
    vdq = v.astype(jnp.bfloat16) * ks[:, :, None].astype(jnp.bfloat16)
    o_ref[:] = jax.lax.dot_general(
        p.astype(jnp.bfloat16), vdq, (((2,), (1,)), ((0,), (0,))),
        preferred_element_type=jnp.float32,
    ).astype(o_ref.dtype)


def _call(kernel, g, w, *operands, interpret):
    rows = operands[-1].shape[0]
    specs = []
    for op in operands:
        if op.ndim == 3:
            specs.append(pl.BlockSpec((g, op.shape[1], op.shape[2]),
                                      lambda r: (r, 0, 0)))
        else:
            specs.append(pl.BlockSpec((g, op.shape[1]), lambda r: (r, 0)))
    return pl.pallas_call(
        kernel,
        grid=(rows // g,),
        in_specs=specs,
        out_specs=pl.BlockSpec((g, 8, w // 2), lambda r: (r, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((rows, 8, w // 2), jnp.bfloat16),
        interpret=interpret,
    )(*operands)


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--rows", type=int, default=384)  # b32 x 12 heads
    args = p.parse_args()
    on_tpu = jax.default_backend() == "tpu"
    interpret = not on_tpu
    d = 64
    w = 2 * d
    scale = 1.0 / d ** 0.5
    key = jax.random.PRNGKey(0)

    for s_len in (256, 1024):
        # group cap keeping the double-buffered bf16 slab under ~8 MB VMEM
        # (the real kernel's _pick_group discipline)
        g = max(1, min(48, 8 * 1024 * 1024 // (s_len * w * 2 * 2)))
        while args.rows % g:
            g //= 2
        q = jax.random.normal(key, (args.rows, 8, w), jnp.bfloat16)
        kv = jax.random.normal(jax.random.PRNGKey(1), (args.rows, s_len, w),
                               jnp.bfloat16)
        # symmetric per-row int8 quantization
        ks = (jnp.max(jnp.abs(kv), axis=(1, 2)) / 127.0).astype(jnp.float32)
        kv_i8 = jnp.clip(
            jnp.round(kv.astype(jnp.float32) / ks[:, None, None]), -127, 127
        ).astype(jnp.int8)
        ksr = jnp.broadcast_to(ks[:, None], (args.rows, s_len)).astype(jnp.float32)
        qs = (jnp.max(jnp.abs(q), axis=(1, 2)) / 127.0).astype(jnp.float32)
        q_i8 = jnp.clip(
            jnp.round(q.astype(jnp.float32) / qs[:, None, None]), -127, 127
        ).astype(jnp.int8)
        qsr = jnp.broadcast_to(qs[:, None], (args.rows, 8)).astype(jnp.float32)

        # MARGINAL per-call timing: the chained outer call carries a fixed
        # ~120 ms cost on this runtime (operand re-placement + dispatch),
        # so a single loop length reports amortization, not the kernel.
        # Timing TWO loop lengths and taking the difference quotient
        # cancels the constant: (t_long - t_short) / (n_long - n_short).
        bf = functools.partial(_attend_bf16_kernel, scale=scale)
        i8 = functools.partial(_attend_i8_kernel, scale=scale)

        # correctness first (vs each other, quantization tolerance)
        o_bf = _call(bf, g, w, q, kv, interpret=interpret)
        o_i8 = _call(i8, g, w, q_i8, qsr, kv_i8, ksr, interpret=interpret)
        err = float(jnp.max(jnp.abs(o_bf.astype(jnp.float32)
                                    - o_i8.astype(jnp.float32))))
        print(f"S={s_len}: max|bf16-i8| = {err:.4f} (int8 quantization noise)")
        if not on_tpu:
            continue

        eps = jnp.bfloat16(1e-2)
        n_short, n_long = 400, 1500

        def marginal(make_run, carry0):
            times = {}
            for n in (n_short, n_long):
                run = make_run(n)
                res, _ = timed_total(run, carry0, warmup=1, iters=2)
                times[n] = res.min_ms
            return (times[n_long] - times[n_short]) / (n_long - n_short) * 1e3

        def bf_run(n):
            @jax.jit
            def run(qv):
                def body(qc, _):
                    o = _call(bf, g, w, qc, kv, interpret=False)
                    return qc + eps * jnp.tile(o, (1, 1, 2)).astype(qc.dtype), None
                out, _ = jax.lax.scan(body, qv, None, length=n)
                return out
            return run

        def i8_run(n):
            # q_i8 must stay int8, so the chain rides the q scales instead
            @jax.jit
            def run(qsr_c):
                def body(c, _):
                    o = _call(i8, g, w, q_i8, c, kv_i8, ksr, interpret=False)
                    return c + 1e-6 * o[:, :, 0].astype(jnp.float32), None
                out, _ = jax.lax.scan(body, qsr_c, None, length=n)
                return out
            return run

        t_bf = marginal(bf_run, q)
        t_i8 = marginal(i8_run, qsr)
        print(f"S={s_len}: bf16 {t_bf:7.1f} us/call   int8-native {t_i8:7.1f} "
              f"us/call   ({t_bf / t_i8:.2f}x)")


if __name__ == "__main__":
    main()
