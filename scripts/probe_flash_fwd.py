"""Decompose the flash forward's remaining roofline gap at the headline
shape (rows = B·H = 192, S = 512, Dh = 64, causal bf16: 0.262 ms/layer
measured round 3 vs 0.13 ms matmul roofline — BASELINE.md attention row).

Ablations, all device-lane timed (trace; host walls are dispatch-bound):

  default        — the shipping config (512-tile clamp -> single-k-tile
                   fast path, G=4 grouping)
  qtile256/128   — smaller q tiles with k_tile still covering S (more
                   grid steps, same single-k-tile math): isolates Mosaic
                   grid-step overhead vs per-tile compute
  noncausal      — same shape without the mask: isolates mask cost
                   (the single-tile path applies the mask inline)
  d128           — double head dim: MXU work doubles, softmax/VPU work
                   per score stays — separates MXU-bound from VPU-bound
                   time (if fwd time scales ~2x, it is MXU/DMA-bound; if
                   much less, the VPU softmax is the floor)
  fp32           — fp32 at the same shape (VPU ops are dtype-agnostic on
                   fp32 lanes; MXU rate halves)

Verdict recorded in BASELINE.md.
"""

from cs336_systems_tpu.utils.platform import honor_cpu_request

honor_cpu_request()

import jax
import jax.numpy as jnp

from cs336_systems_tpu.ops.flash_attention import flash_attention
from cs336_systems_tpu.utils.profiling import summarize_trace, trace


def device_ms(fn, x, iters=200, logdir="/tmp/flash_fwd_probe"):
    @jax.jit
    def loop(q):
        def body(qc, _):
            o = fn(qc)
            return qc + jnp.asarray(1e-2, qc.dtype) * o, None
        out, _ = jax.lax.scan(body, q, None, length=iters)
        return out

    jax.block_until_ready(loop(x))  # compile + warm
    with trace(logdir):
        jax.block_until_ready(loop(x))
    rows, total = summarize_trace(logdir)
    # the kernel is the only custom call in the loop; everything else is
    # the chain add
    kern = sum(r["total_ms"] for r in rows
               if "fusion" not in r["op"] and "add" not in r["op"]
               and r["total_ms"] > 0.01 * total)
    return total / iters, kern / iters


def main():
    rows, s, d = 192, 512, 64
    key = jax.random.PRNGKey(0)

    def mk(dtype=jnp.bfloat16, dd=d, ss=s):
        q = jax.random.normal(key, (rows, ss, dd), dtype)
        k = jax.random.normal(jax.random.PRNGKey(1), (rows, ss, dd), dtype)
        v = jax.random.normal(jax.random.PRNGKey(2), (rows, ss, dd), dtype)
        return q, k, v

    cases = []
    q, k, v = mk()
    cases.append(("default (512-tile fast path)", q,
                  lambda qc: flash_attention(qc, k, v, causal=True)))
    cases.append(("qtile256", q,
                  lambda qc: flash_attention(qc, k, v, causal=True,
                                             q_tile=256, k_tile=512)))
    cases.append(("qtile128", q,
                  lambda qc: flash_attention(qc, k, v, causal=True,
                                             q_tile=128, k_tile=512)))
    cases.append(("noncausal", q,
                  lambda qc: flash_attention(qc, k, v, causal=False)))
    q2, k2, v2 = mk(dd=128)
    cases.append(("d128", q2,
                  lambda qc: flash_attention(qc, k2, v2, causal=True)))
    qf, kf, vf = mk(dtype=jnp.float32)
    cases.append(("fp32", qf,
                  lambda qc: flash_attention(qc, kf, vf, causal=True)))

    for i, (name, x, fn) in enumerate(cases):
        tot, kern = device_ms(fn, x, logdir=f"/tmp/flash_fwd_probe_{i}")
        print(f"{name:32s} total {tot:7.3f} ms/call   kernel {kern:7.3f}")


if __name__ == "__main__":
    main()
