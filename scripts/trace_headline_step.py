"""Trace the headline train step and print the phase-attributed
device-time breakdown (a tracekit StepProfile).

Thin wrapper over ``analysis/tracekit.profile_callable`` at the HEADLINE
shape — the small model, ctx 512, batch 48, the 10-step in-jit loop — the
one config ``analysis/trace_cli`` (tiny lint-registry shapes) does not
cover. The StepProfile JSON it writes diffs against any other run via
``trace_cli --diff`` (the packaged "compare traces, not walls").

Usage: PYTHONPATH=.:$PYTHONPATH python scripts/trace_headline_step.py \
          [--out headline.stepprofile.json]
"""

import argparse

from cs336_systems_tpu.utils.platform import honor_cpu_request

honor_cpu_request()

import jax
import jax.numpy as jnp

from cs336_systems_tpu.analysis import tracekit
from cs336_systems_tpu.analysis.flops import model_flops_per_token
from cs336_systems_tpu.models.transformer import config_for_size
from cs336_systems_tpu.optim.adamw import AdamWHparams
from cs336_systems_tpu.train import init_train_state, make_train_loop


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default="headline.stepprofile.json",
                    help="StepProfile JSON path")
    args = ap.parse_args()

    on_tpu = jax.default_backend() == "tpu"
    steps = 10 if on_tpu else 2
    batch = 48 if on_tpu else 2  # keep in lockstep with bench.py (the headline peak)
    cfg = config_for_size(
        "small",
        context_length=512,
        compute_dtype="bfloat16" if on_tpu else "float32",
        attn_impl="flash" if on_tpu else "xla",
        scan_layers=not on_tpu,
    )
    params, opt = init_train_state(jax.random.PRNGKey(0), cfg)
    # donate=False: the traced call repeats on the same buffers
    loop = make_train_loop(cfg, AdamWHparams(lr=3e-4), donate=False)
    xs = jax.random.randint(
        jax.random.PRNGKey(1), (steps, batch, 512), 0, cfg.vocab_size
    )
    ys = jnp.roll(xs, -1, axis=-1)

    profile = tracekit.profile_callable(
        loop, (params, opt, xs, ys), iters=1,
        tokens_per_step=batch * 512 * steps,  # one call = `steps` steps
        flops_per_token=model_flops_per_token(cfg),
        family="headline_loop",
    )
    print(tracekit.format_profile(profile))
    per_step = profile["total_device_ms_per_step"] / steps
    print(f"  per optimizer step: {per_step:.1f} ms ({steps}-step loop)")
    tracekit.write_profile(profile, args.out)
    print(f"wrote {args.out}")


if __name__ == "__main__":
    main()
