"""Trace the headline train step on the current backend and print the
device-time breakdown.

This packages the measurement recipe CLAUDE.md mandates for this runtime
(host wall-clocks are dispatch-bound; trust device-lane durations): run the
10-step in-jit loop once for compile, trace a second run, and summarize the
leaf-op totals via ``utils.profiling.summarize_trace``.

Usage: PYTHONPATH=. python scripts/trace_headline_step.py [logdir]
"""

import sys

from cs336_systems_tpu.utils.platform import honor_cpu_request

honor_cpu_request()

import jax
import jax.numpy as jnp

from cs336_systems_tpu.models.transformer import config_for_size
from cs336_systems_tpu.optim.adamw import AdamWHparams
from cs336_systems_tpu.train import init_train_state, make_train_loop
from cs336_systems_tpu.utils.profiling import summarize_trace, trace


def main() -> None:
    logdir = sys.argv[1] if len(sys.argv) > 1 else "/tmp/headline_trace"
    on_tpu = jax.default_backend() == "tpu"
    steps = 10 if on_tpu else 2
    batch = 48 if on_tpu else 2  # keep in lockstep with bench.py (the headline peak)
    cfg = config_for_size(
        "small",
        context_length=512,
        compute_dtype="bfloat16" if on_tpu else "float32",
        attn_impl="flash" if on_tpu else "xla",
        scan_layers=not on_tpu,
    )
    params, opt = init_train_state(jax.random.PRNGKey(0), cfg)
    loop = make_train_loop(cfg, AdamWHparams(lr=3e-4))
    xs = jax.random.randint(
        jax.random.PRNGKey(1), (steps, batch, 512), 0, cfg.vocab_size
    )
    ys = jnp.roll(xs, -1, axis=-1)

    params, opt, losses = loop(params, opt, xs, ys)  # compile + warm
    float(losses[-1])
    with trace(logdir):
        params, opt, losses = loop(params, opt, xs, ys)
        float(losses[-1])

    rows, total = summarize_trace(logdir)
    print(f"trace: {logdir}   leaf device time {total / steps:.1f} ms/step")
    print(f"{'op':32s} {'ms/step':>9s} {'count':>7s} {'mean_us':>9s}")
    for r in rows:
        print(
            f"{r['op'][:32]:32s} {r['total_ms'] / steps:9.3f} "
            f"{r['count']:7d} {r['mean_us']:9.1f}"
        )


if __name__ == "__main__":
    main()
