"""Probe: can a Pallas grouped-expert matmul (megablocks-style `gmm`) beat
the XLA padded batched expert matmul that the sorted MoE dispatch runs?

Context (round-4 trace, scripts/trace_moe_step.py at the E8k2 b32 peak):
the expert matmuls run at ~98% of their EXECUTED-FLOP roofline, but they
execute over E·C capacity slots — cf×(T·k) rows, 25% padding at the
default capacity factor 1.25. A grouped kernel over tightly packed rows
(padded per group only to the row tile bm) would cut the padding to
~E·bm/2 rows (~3-6%), IF Mosaic's grid-step overhead does not eat the
saving (the dots per grid step are 2-12 us against ~2 us/step overhead —
the same regime where the flash kernels needed 1024-tiles).

This probe measures the FORWARD only, device-lane timed via an in-jit
chained loop: y = x @ w[g(row)] with [M=32768(+pad), K=768, N=3072] bf16,
E=8 — one expert FFN matmul of the b32 cell — against (a) the padded
[E, C=5120, K] @ [E, K, N] batched dot (what runs today) and (b) the
tight cf=1.0 [E, 4096, K] batched dot (the XLA lower bound if capacity
were exact).

Verdict recorded in results/moe_v5e.txt; the kernel is promoted to
ops/ only if it wins.

`--bwd` (round 6) probes the w13 BACKWARD kernels in isolation at the
E8k2 b40 geometry (M=43008 packed rows): the fused one-pass dx and dw
kernels (`ops/grouped_matmul._dx13_call`/`_dw13_call`, SiLU grads
in-register from the stored h/g residuals) against the retained five-pass
unfused chain — the attribution behind BASELINE.md's "exec 80.3 vs 92.9
TF/s, the dx/dw bwd kernels" open item, reproducible before/after. Same
timing discipline as the forward probe: in-jit chained loops, one fence.
"""

import argparse
import functools

from cs336_systems_tpu.utils.platform import honor_cpu_request

honor_cpu_request()

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from cs336_systems_tpu.utils.timing import timed_total


def _gmm_fwd_kernel(te_ref, x_ref, w_ref, y_ref):
    del te_ref  # consumed by the index maps
    y_ref[:] = jnp.dot(
        x_ref[:], w_ref[:], preferred_element_type=jnp.float32
    ).astype(y_ref.dtype)


@functools.partial(jax.jit, static_argnames=("bm", "bn", "interpret"))
def gmm_fwd(x, w, tile_expert, bm: int = 512, bn: int = 1024,
            interpret: bool = False):
    """y[rows of tile i] = x[tile i] @ w[tile_expert[i]].

    x: [M, K] rows grouped by expert, each group padded to a multiple of
    bm so every row tile belongs to ONE expert; w: [E, K, N];
    tile_expert: [M//bm] int32 (non-decreasing), a scalar-prefetch
    operand read by the weight BlockSpec index map.
    """
    m, k = x.shape
    e, k2, n = w.shape
    assert k2 == k and m % bm == 0 and n % bn == 0
    wf = w.reshape(e * k, n)
    return pl.pallas_call(
        _gmm_fwd_kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(m // bm, n // bn),
            in_specs=[
                pl.BlockSpec((bm, k), lambda i, j, te: (i, 0)),
                pl.BlockSpec((k, bn), lambda i, j, te: (te[i], j)),
            ],
            out_specs=pl.BlockSpec((bm, bn), lambda i, j, te: (i, j)),
        ),
        out_shape=jax.ShapeDtypeStruct((m, n), x.dtype),
        interpret=interpret,
    )(tile_expert, x, wf)


def check_correctness():
    """Interpret-mode oracle check (CPU or TPU)."""
    key = jax.random.PRNGKey(0)
    e, k, n, bm = 4, 256, 512, 128
    counts = [128, 384, 128, 256]  # multiples of bm for the probe
    m = sum(counts)
    x = jax.random.normal(key, (m, k), jnp.float32)
    w = jax.random.normal(jax.random.PRNGKey(1), (e, k, n), jnp.float32)
    te = np.repeat(np.arange(e), [c // bm for c in counts]).astype(np.int32)
    y = gmm_fwd(x, w, jnp.asarray(te), bm=bm, bn=n, interpret=True)
    row = 0
    for g, c in enumerate(counts):
        want = x[row:row + c] @ w[g]
        np.testing.assert_allclose(
            np.asarray(y[row:row + c]), np.asarray(want), rtol=2e-5, atol=2e-5
        )
        row += c
    print("gmm_fwd interpret-mode oracle OK")


def bench(bm: int, bn: int, iters: int = 600):
    # 600 in-jit execs × 2 fenced outer calls: the ~230 ms dispatch+fence
    # floor (CLAUDE.md) amortizes to ~0.2 ms/call against ~1 ms calls.
    e, k, n = 8, 768, 3072
    tk = 32768  # T·k at the b32 cell
    c_pad = 5120  # cf=1.25 capacity slots per expert
    c_tight = 4096  # cf=1.0
    m = tk + e * bm  # tight packing, per-group pad to bm (worst case)
    key = jax.random.PRNGKey(0)
    x = jax.random.normal(key, (m, k), jnp.bfloat16)
    w = jax.random.normal(jax.random.PRNGKey(1), (e, k, n), jnp.bfloat16)
    xe_pad = jax.random.normal(jax.random.PRNGKey(2), (e, c_pad, k), jnp.bfloat16)
    xe_tight = xe_pad[:, :c_tight]
    te = jnp.asarray(
        np.repeat(np.arange(e), m // bm // e).astype(np.int32)
    )

    eps = jnp.bfloat16(1e-2)

    @jax.jit
    def loop_gmm(x):
        def body(xc, _):
            y = gmm_fwd(x=xc, w=w, tile_expert=te, bm=bm, bn=bn)
            # chain the dependency or the loop body is hoisted (CLAUDE.md)
            return xc + eps * y[:, :k], None
        out, _ = jax.lax.scan(body, x, None, length=iters)
        return out

    @jax.jit
    def loop_xla(xe):
        def body(xc, _):
            y = jax.lax.dot_general(
                xc, w, (((2,), (1,)), ((0,), (0,))),
                preferred_element_type=jnp.float32,
            ).astype(xc.dtype)
            return xc + eps * y[:, :, :k], None
        out, _ = jax.lax.scan(body, xe, None, length=iters)
        return out

    flops_tk = 2 * tk * k * n  # useful FLOPs (the claims)
    for name, fn, arg, rows in [
        (f"gmm bm{bm} bn{bn} (rows {m})", loop_gmm, x, m),
        (f"xla padded cf1.25 (rows {e * c_pad})", loop_xla, xe_pad, e * c_pad),
        (f"xla tight cf1.0 (rows {e * c_tight})", loop_xla, xe_tight,
         e * c_tight),
    ]:
        res, _ = timed_total(fn, arg, warmup=1, iters=2)
        ms = res.min_ms / iters
        from bench import V5E_BF16_PEAK_FLOPS

        eff = flops_tk / (ms / 1e3) / V5E_BF16_PEAK_FLOPS
        print(f"{name:36s} {ms:8.3f} ms/call  "
              f"{2 * rows * k * n / (ms / 1e3) / 1e12:6.1f} TF/s executed  "
              f"{eff * 100:5.1f}% useful-FLOP MFU")


def _bwd_case(e, k, n, bm, tiles_per_e, dtype=jnp.bfloat16):
    """Packed backward operands at a uniform claims-per-expert layout."""
    from cs336_systems_tpu.ops import grouped_matmul as gm

    m = e * tiles_per_e * bm
    keys = jax.random.split(jax.random.PRNGKey(3), 6)
    x = jax.random.normal(keys[0], (m, k), dtype)
    w1 = jax.random.normal(keys[1], (e, n, k), dtype)
    w3 = jax.random.normal(keys[2], (e, n, k), dtype)
    h = jax.random.normal(keys[3], (m, n), dtype)
    g = jax.random.normal(keys[4], (m, n), dtype)
    dp = jax.random.normal(keys[5], (m, n), dtype)
    te = jnp.asarray(np.repeat(np.arange(e), tiles_per_e).astype(np.int32))
    first = jnp.asarray(
        (np.arange(e * tiles_per_e) % tiles_per_e == 0).astype(np.int32))
    visited = jnp.ones((e,), jnp.int32)
    res = (x, w1, w3, h, g, te, first, visited)
    return gm, m, res, dp


def check_bwd_correctness():
    """Interpret-mode: the fused dx/dw kernels match the unfused chain
    (the ops-level oracle tests carry the einsum comparison)."""
    gm, _, res, dp = _bwd_case(4, 32, 64, 8, 3, jnp.float32)
    fused = gm._gmm13_bwd(8, True, res, dp)[:3]
    unfused = gm._gmm13_bwd_unfused(8, True, res, dp)[:3]
    for a, b, name in zip(fused, unfused, ("dx", "dw1", "dw3")):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-6, err_msg=name)
    print("fused-bwd interpret-mode parity vs unfused chain OK")


def bench_bwd(iters: int = 100):
    """dx/dw backward kernels in isolation at the E8k2 b40 shapes
    (M = 8 experts × 21 tiles × bm=256 = 43008 packed rows ≈ the 40960
    routed claims + tile padding). Each row is an in-jit chained loop
    fenced once; executed TF/s uses each pass's own 4·M·N·K FLOPs."""
    from bench import V5E_BF16_PEAK_FLOPS

    gm, m, res, dp = _bwd_case(e=8, k=768, n=3072, bm=256, tiles_per_e=21)
    x, w1, w3, h, g, te, first, visited = res
    bm, k, n = 256, 768, 3072
    plan = gm._fused_bwd_plan(bm, n, k, w1.dtype.itemsize)
    assert plan is not None, "headline shapes must take the fused path"
    dx_tiles, dw_tiles = plan
    print(f"fused plan: dx (bm, bk) = {dx_tiles}, "
          f"dw (bm, bn, bk) = {dw_tiles}")
    eps = jnp.bfloat16(1e-3)

    def chained(step_fn):
        @jax.jit
        def loop(dpc):
            def body(dpc, _):
                return step_fn(dpc), None
            out, _ = jax.lax.scan(body, dpc, None, length=iters)
            return out
        return loop

    def fused_dx(dpc):
        dx = gm._dx13_call(dpc, h, g, w1, w3, te, bm, dx_tiles, False)
        return dpc + eps * dx[:, :1]  # chain or the body hoists

    def fused_dw(dpc):
        dw1, dw3 = gm._dw13_call(dpc, h, g, x, w1, te, first, visited,
                                 bm, dw_tiles, False)
        return dpc + eps * (dw1[0, 0, 0] + dw3[0, 0, 0]).astype(dpc.dtype)

    def fused_total(dpc):
        dx, dw1, dw3 = gm._gmm13_bwd(bm, False, res, dpc)[:3]
        return (dpc + eps * dx[:, :1]
                + eps * (dw1[0, 0, 0] + dw3[0, 0, 0]).astype(dpc.dtype))

    def unfused_total(dpc):
        dx, dw1, dw3 = gm._gmm13_bwd_unfused(bm, False, res, dpc)[:3]
        return (dpc + eps * dx[:, :1]
                + eps * (dw1[0, 0, 0] + dw3[0, 0, 0]).astype(dpc.dtype))

    pass_flops = 4 * m * n * k  # two [M,N]x[N,K]-class dots per pass
    for name, fn, flops in [
        ("fused dx (one pass)", fused_dx, pass_flops),
        ("fused dw (one pass)", fused_dw, pass_flops),
        ("fused bwd total", fused_total, 2 * pass_flops),
        ("unfused 5-pass bwd total", unfused_total, 2 * pass_flops),
    ]:
        result, _ = timed_total(chained(fn), dp, warmup=1, iters=2)
        ms = result.min_ms / iters
        tf = flops / (ms / 1e3) / 1e12
        print(f"{name:28s} {ms:8.3f} ms/call  {tf:6.1f} TF/s executed  "
              f"{tf * 1e12 / V5E_BF16_PEAK_FLOPS * 100:5.1f}% MFU")


if __name__ == "__main__":
    p = argparse.ArgumentParser()
    p.add_argument("--bm", type=int, default=512)
    p.add_argument("--bn", type=int, default=1024)
    p.add_argument("--check", action="store_true")
    p.add_argument("--bwd", action="store_true",
                   help="probe the fused w13 backward kernels instead")
    args = p.parse_args()
    on_tpu = jax.default_backend() == "tpu"
    if args.bwd:
        if args.check or not on_tpu:
            check_bwd_correctness()
        if on_tpu:
            bench_bwd()
    else:
        if args.check or not on_tpu:
            check_correctness()
        if on_tpu:
            bench(args.bm, args.bn)
