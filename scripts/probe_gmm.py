"""Probe: can a Pallas grouped-expert matmul (megablocks-style `gmm`) beat
the XLA padded batched expert matmul that the sorted MoE dispatch runs?

Context (round-4 trace, scripts/trace_moe_step.py at the E8k2 b32 peak):
the expert matmuls run at ~98% of their EXECUTED-FLOP roofline, but they
execute over E·C capacity slots — cf×(T·k) rows, 25% padding at the
default capacity factor 1.25. A grouped kernel over tightly packed rows
(padded per group only to the row tile bm) would cut the padding to
~E·bm/2 rows (~3-6%), IF Mosaic's grid-step overhead does not eat the
saving (the dots per grid step are 2-12 us against ~2 us/step overhead —
the same regime where the flash kernels needed 1024-tiles).

This probe measures the FORWARD only, device-lane timed via an in-jit
chained loop: y = x @ w[g(row)] with [M=32768(+pad), K=768, N=3072] bf16,
E=8 — one expert FFN matmul of the b32 cell — against (a) the padded
[E, C=5120, K] @ [E, K, N] batched dot (what runs today) and (b) the
tight cf=1.0 [E, 4096, K] batched dot (the XLA lower bound if capacity
were exact).

Verdict recorded in results/moe_v5e.txt; the kernel is promoted to
ops/ only if it wins.
"""

import argparse
import functools

from cs336_systems_tpu.utils.platform import honor_cpu_request

honor_cpu_request()

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from cs336_systems_tpu.utils.timing import timed_total


def _gmm_fwd_kernel(te_ref, x_ref, w_ref, y_ref):
    del te_ref  # consumed by the index maps
    y_ref[:] = jnp.dot(
        x_ref[:], w_ref[:], preferred_element_type=jnp.float32
    ).astype(y_ref.dtype)


@functools.partial(jax.jit, static_argnames=("bm", "bn", "interpret"))
def gmm_fwd(x, w, tile_expert, bm: int = 512, bn: int = 1024,
            interpret: bool = False):
    """y[rows of tile i] = x[tile i] @ w[tile_expert[i]].

    x: [M, K] rows grouped by expert, each group padded to a multiple of
    bm so every row tile belongs to ONE expert; w: [E, K, N];
    tile_expert: [M//bm] int32 (non-decreasing), a scalar-prefetch
    operand read by the weight BlockSpec index map.
    """
    m, k = x.shape
    e, k2, n = w.shape
    assert k2 == k and m % bm == 0 and n % bn == 0
    wf = w.reshape(e * k, n)
    return pl.pallas_call(
        _gmm_fwd_kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(m // bm, n // bn),
            in_specs=[
                pl.BlockSpec((bm, k), lambda i, j, te: (i, 0)),
                pl.BlockSpec((k, bn), lambda i, j, te: (te[i], j)),
            ],
            out_specs=pl.BlockSpec((bm, bn), lambda i, j, te: (i, j)),
        ),
        out_shape=jax.ShapeDtypeStruct((m, n), x.dtype),
        interpret=interpret,
    )(tile_expert, x, wf)


def check_correctness():
    """Interpret-mode oracle check (CPU or TPU)."""
    key = jax.random.PRNGKey(0)
    e, k, n, bm = 4, 256, 512, 128
    counts = [128, 384, 128, 256]  # multiples of bm for the probe
    m = sum(counts)
    x = jax.random.normal(key, (m, k), jnp.float32)
    w = jax.random.normal(jax.random.PRNGKey(1), (e, k, n), jnp.float32)
    te = np.repeat(np.arange(e), [c // bm for c in counts]).astype(np.int32)
    y = gmm_fwd(x, w, jnp.asarray(te), bm=bm, bn=n, interpret=True)
    row = 0
    for g, c in enumerate(counts):
        want = x[row:row + c] @ w[g]
        np.testing.assert_allclose(
            np.asarray(y[row:row + c]), np.asarray(want), rtol=2e-5, atol=2e-5
        )
        row += c
    print("gmm_fwd interpret-mode oracle OK")


def bench(bm: int, bn: int, iters: int = 600):
    # 600 in-jit execs × 2 fenced outer calls: the ~230 ms dispatch+fence
    # floor (CLAUDE.md) amortizes to ~0.2 ms/call against ~1 ms calls.
    e, k, n = 8, 768, 3072
    tk = 32768  # T·k at the b32 cell
    c_pad = 5120  # cf=1.25 capacity slots per expert
    c_tight = 4096  # cf=1.0
    m = tk + e * bm  # tight packing, per-group pad to bm (worst case)
    key = jax.random.PRNGKey(0)
    x = jax.random.normal(key, (m, k), jnp.bfloat16)
    w = jax.random.normal(jax.random.PRNGKey(1), (e, k, n), jnp.bfloat16)
    xe_pad = jax.random.normal(jax.random.PRNGKey(2), (e, c_pad, k), jnp.bfloat16)
    xe_tight = xe_pad[:, :c_tight]
    te = jnp.asarray(
        np.repeat(np.arange(e), m // bm // e).astype(np.int32)
    )

    eps = jnp.bfloat16(1e-2)

    @jax.jit
    def loop_gmm(x):
        def body(xc, _):
            y = gmm_fwd(x=xc, w=w, tile_expert=te, bm=bm, bn=bn)
            # chain the dependency or the loop body is hoisted (CLAUDE.md)
            return xc + eps * y[:, :k], None
        out, _ = jax.lax.scan(body, x, None, length=iters)
        return out

    @jax.jit
    def loop_xla(xe):
        def body(xc, _):
            y = jax.lax.dot_general(
                xc, w, (((2,), (1,)), ((0,), (0,))),
                preferred_element_type=jnp.float32,
            ).astype(xc.dtype)
            return xc + eps * y[:, :, :k], None
        out, _ = jax.lax.scan(body, xe, None, length=iters)
        return out

    flops_tk = 2 * tk * k * n  # useful FLOPs (the claims)
    for name, fn, arg, rows in [
        (f"gmm bm{bm} bn{bn} (rows {m})", loop_gmm, x, m),
        (f"xla padded cf1.25 (rows {e * c_pad})", loop_xla, xe_pad, e * c_pad),
        (f"xla tight cf1.0 (rows {e * c_tight})", loop_xla, xe_tight,
         e * c_tight),
    ]:
        res, _ = timed_total(fn, arg, warmup=1, iters=2)
        ms = res.min_ms / iters
        from bench import V5E_BF16_PEAK_FLOPS

        eff = flops_tk / (ms / 1e3) / V5E_BF16_PEAK_FLOPS
        print(f"{name:36s} {ms:8.3f} ms/call  "
              f"{2 * rows * k * n / (ms / 1e3) / 1e12:6.1f} TF/s executed  "
              f"{eff * 100:5.1f}% useful-FLOP MFU")


if __name__ == "__main__":
    p = argparse.ArgumentParser()
    p.add_argument("--bm", type=int, default=512)
    p.add_argument("--bn", type=int, default=1024)
    p.add_argument("--check", action="store_true")
    args = p.parse_args()
    if args.check or jax.default_backend() != "tpu":
        check_correctness()
    if jax.default_backend() == "tpu":
        bench(args.bm, args.bn)
