"""CI gate for the chunked fused CE memory claim (ops/fused_ce.py).

Signs, not thresholds: the default mem_cli --diff noise gate (10% / 1 MiB,
BOTH must trip) is deliberately deaf to deltas this small at the tiny
hermetic shapes, so this script asserts the DIRECTION of the change on
freshly built chunked vs chunking-disabled (``ce_chunk_size=0``) twins —
the same mutation switch the no-materialized-logits lint rule is tested
against:

1. train_single (the registry lint shape): the loss-phase high-water must
   be strictly reduced, by at least one full ``[B, S, V]`` logits buffer
   (the disabled twin materializes it in fwd AND keeps the fwd logits as
   the CE residual across the bwd).
2. train_vocab32k (the 32k-vocab headline loop, the shape the fused CE
   exists for): loss-phase high-water strictly reduced, again by at least
   the full ``[B, S, V]`` margin (~131 MB fp32 at the CPU smoke shape —
   far beyond scheduling noise). The GLOBAL peak is informational only at
   this shape: b2's peak sits in the transformer-bwd stash region either
   way, and the chunked path's known cost — the fp32 ``[V, D]`` dW
   accumulator carried through the bwd chunk scan — lands there, while
   its [B,S,V]-sized savings land in the loss phase. At the real b48
   shapes the logits dwarf the accumulator 30:1.
3. The fresh train_single profile must still agree with the committed
   pre-change artifact (results/memprofiles/) on total peak to 1% — the
   chunked loss path must not move the tiny-shape peak, which sits in
   fwd-attn, not the loss.

Runs on the hermetic CPU mesh; exits 1 naming the first violated sign.
Launch: JAX_PLATFORMS=cpu PALLAS_AXON_POOL_IPS= python scripts/check_ce_memory_gate.py
"""

import json
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

PRE_ARTIFACT = os.path.join(
    os.path.dirname(__file__), "..", "results", "memprofiles",
    "train_single.pre_chunked_ce.memprofile.json")


def _profile_train_single(ce_chunk_size):
    from cs336_systems_tpu.analysis import memkit, registry
    from cs336_systems_tpu.train import make_train_step

    kw = {} if ce_chunk_size is None else {"ce_chunk_size": ce_chunk_size}
    cfg = registry._tiny_cfg(**kw)
    params, opt = registry._abstract_state(cfg)
    x, y = registry._batch(cfg)
    # donate=False matches the tracekit bundle the committed artifact was
    # profiled from (mem_cli --step train_single)
    step = make_train_step(cfg, registry._hp(), donate=False)
    classes = memkit._leaf_classes((params, opt, x, y),
                                   memkit._train_arg_classes())
    name = f"train_single[ce_chunk_size={ce_chunk_size}]"
    return memkit.profile_callable(step, (params, opt, x, y), family=name,
                                   arg_classes=classes), cfg


def _profile_vocab32k(ce_chunk_size):
    import jax

    from cs336_systems_tpu.analysis import memkit
    from cs336_systems_tpu.models.transformer import config_for_size
    from cs336_systems_tpu.optim.adamw import AdamWHparams
    from cs336_systems_tpu.train import init_train_state, make_train_loop

    # the CPU smoke shape of memkit._bench_vocab32k, with the chunk switch
    kw = {} if ce_chunk_size is None else {"ce_chunk_size": ce_chunk_size}
    cfg = config_for_size("small", vocab_size=32_000, context_length=512,
                          compute_dtype="float32", attn_impl="xla",
                          scan_layers=True, **kw)
    params, opt = jax.eval_shape(
        lambda k: init_train_state(k, cfg), jax.random.PRNGKey(0))
    loop = make_train_loop(cfg, AdamWHparams(lr=3e-4), donate=False)
    xs = jax.ShapeDtypeStruct((2, 2, 512), "int32")
    classes = memkit._leaf_classes((params, opt, xs, xs),
                                   memkit._train_arg_classes())
    name = f"train_vocab32k[ce_chunk_size={ce_chunk_size}]"
    return memkit.profile_callable(loop, (params, opt, xs, xs), family=name,
                                   arg_classes=classes), cfg


def _mb(n):
    return f"{n / 2**20:.2f}MiB"


def main() -> int:
    failures = []

    def check(ok, msg):
        print(("  ok    " if ok else "  FAIL  ") + msg)
        if not ok:
            failures.append(msg)

    print("== train_single: loss-phase high-water sign ==")
    on, cfg = _profile_train_single(None)
    off, _ = _profile_train_single(0)
    b, s, v = 8, cfg.context_length, cfg.vocab_size
    logits_bytes = b * s * v * 4  # fp32 at the lint shape
    hw_on = on["phase_peak_bytes"].get("loss", 0)
    hw_off = off["phase_peak_bytes"].get("loss", 0)
    print(f"  loss high-water: chunked {_mb(hw_on)}  "
          f"full-logits {_mb(hw_off)}  ([B,S,V] = {_mb(logits_bytes)})")
    check(hw_on < hw_off,
          "chunked loss-phase high-water < full-logits twin")
    check(hw_off - hw_on >= logits_bytes,
          "reduction >= one full [B,S,V] logits buffer")

    print("== train_vocab32k: loss-phase high-water sign ==")
    on32, cfg32 = _profile_vocab32k(None)
    off32, _ = _profile_vocab32k(0)
    logits32 = 2 * cfg32.context_length * cfg32.vocab_size * 4
    hw32_on = on32["phase_peak_bytes"].get("loss", 0)
    hw32_off = off32["phase_peak_bytes"].get("loss", 0)
    print(f"  loss high-water: chunked {_mb(hw32_on)}  "
          f"full-logits {_mb(hw32_off)}  ([B,S,V] = {_mb(logits32)})")
    print(f"  global peak (informational — see module docstring): "
          f"chunked {_mb(on32['peak_bytes'])}  "
          f"full-logits {_mb(off32['peak_bytes'])}")
    check(hw32_on < hw32_off,
          "chunked 32k-vocab loss-phase high-water < full-logits twin")
    check(hw32_off - hw32_on >= logits32,
          "32k-vocab reduction >= one full [B,S,V] logits buffer")

    print("== train_single vs committed pre-change artifact ==")
    with open(PRE_ARTIFACT) as f:
        pre = json.load(f)
    drift = abs(on["peak_bytes"] - pre["peak_bytes"]) / pre["peak_bytes"]
    print(f"  peak: fresh {_mb(on['peak_bytes'])}  "
          f"committed {_mb(pre['peak_bytes'])}  drift {drift:.4%}")
    check(drift <= 0.01,
          "total peak within 1% of the committed baseline (the tiny-shape "
          "peak sits in fwd-attn; the loss path must not move it)")

    if failures:
        print(f"ce-memory-gate: {len(failures)} sign violation(s)")
        return 1
    print("ce-memory-gate: all signs hold")
    return 0


if __name__ == "__main__":
    sys.exit(main())
