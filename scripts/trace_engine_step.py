"""Trace the serving engine's steady-state step at SATURATED slots and
print the phase-attributed device-time breakdown (a tracekit StepProfile).

Thin wrapper over ``analysis/tracekit.profile_callable`` in the
trace_decode_step.py mold, but over the ENGINE's jit step program with a
real engine's state as the operands: a ServingEngine is driven until
every slot is occupied (submit ``slots`` requests, step through their
prefills), then a ``donate=False`` twin of ``make_engine_step`` is
traced on that live state — logits carry, per-slot PRNG chains, paged
pool, block tables — so the profile is the per-step device cost the
continuous-batching loop actually pays at full occupancy, not the cold
fixed-batch decode shape. The host side of the same step (schedule/
admit, table rewrites, readback) comes from the flight recorder and is
printed alongside; ``serve_trace_cli --run`` is the full-trace version.

The written StepProfile diffs across runs via ``trace_cli --diff`` and
joins into the servetrace artifact as ``device_ms_per_step``.

Usage: PYTHONPATH=.:$PYTHONPATH python scripts/trace_engine_step.py \
          [--slots N] [--out engine.stepprofile.json]
"""

import argparse
import time

from cs336_systems_tpu.utils.platform import honor_cpu_request

honor_cpu_request()

import jax
import numpy as np

from cs336_systems_tpu.analysis import tracekit
from cs336_systems_tpu.analysis.flops import decode_flops_per_token
from cs336_systems_tpu.models.transformer import (
    TransformerConfig,
    config_for_size,
    init_transformer_lm,
)
from cs336_systems_tpu.serving import Request, ServingEngine
from cs336_systems_tpu.serving.engine import make_engine_step


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--slots", type=int, default=None)
    ap.add_argument("--out", default="engine.stepprofile.json",
                    help="StepProfile JSON path")
    args = ap.parse_args()
    on_tpu = jax.default_backend() == "tpu"
    if on_tpu:
        cfg = config_for_size("small", context_length=512,
                              compute_dtype="bfloat16", attn_impl="xla",
                              scan_layers=False)
        slots, prompt, new = 32, 64, 128
    else:
        cfg = TransformerConfig(vocab_size=64, context_length=64,
                                d_model=64, d_ff=128, num_layers=2,
                                num_heads=4)
        slots, prompt, new = 8, 8, 16
    if args.slots is not None:
        slots = args.slots
    blk = 8 if not on_tpu else 16
    max_blocks = -(-(prompt + new) // blk)
    params = init_transformer_lm(jax.random.PRNGKey(0), cfg)

    # Saturate a real engine: slots requests, all arrived at t=0, long
    # enough streams that nobody finishes while we trace. After the
    # prefill step every slot is running.
    t0 = time.monotonic()
    engine = ServingEngine(
        params, cfg, key=jax.random.PRNGKey(0), slots=slots,
        n_pages=slots * max_blocks, max_blocks=max_blocks,
        page_block=blk, temperature=0.9, top_k=8,
        clock=lambda: time.monotonic() - t0)
    rng = np.random.default_rng(0)
    for i in range(slots):
        engine.submit(Request(rid=i,
                              prompt=rng.integers(0, cfg.vocab_size,
                                                  size=prompt),
                              max_new_tokens=new))
    for _ in range(3):  # prefill-join + settle into steady state
        engine.step(0.0)
    assert len(engine.running) == slots, "engine did not saturate"

    # donate=False twin of the engine's own step program: tracekit
    # re-executes the same bundle, so the live state must survive
    step = make_engine_step(cfg, blk, temperature=0.9, top_k=8,
                            donate=False)
    bundle = (params, engine._pool,
              np.asarray(engine.logits), np.asarray(engine.keys),
              np.asarray(engine.pos), np.asarray(engine.active),
              np.asarray(engine.row_off), np.asarray(engine.tables))
    profile = tracekit.profile_callable(
        step, bundle, iters=3 if on_tpu else 1,
        tokens_per_step=slots,
        flops_per_token=decode_flops_per_token(
            cfg, attend_lens=np.asarray(engine.pos, np.int64) + 1),
        family="serve_engine_saturated",
    )
    print(tracekit.format_profile(profile))
    us_tok = profile["total_device_ms_per_step"] / slots * 1e3
    print(f"  per slot-token: {us_tok:.1f} us ({slots} saturated slots)")
    host = [s for s in engine.flight.steps if s["phases"]]
    if host:
        n = len(host)
        tot = {p: sum(s["phases"][p] for s in host) / n * 1e3
               for p in host[0]["phases"]}
        breakdown = "  ".join(f"{p}={v:.3f}" for p, v in tot.items())
        print(f"  host ms/step (flight recorder, {n} steps): {breakdown}")
    tracekit.write_profile(profile, args.out)
    print(f"wrote {args.out}")


if __name__ == "__main__":
    main()
