#!/usr/bin/env python
"""CI gate: chunked prefill must kill the p99 prefill stall — and change
NOTHING else (ISSUE 15).

Drives the SAME seeded spike workload (8 short prompts + one long
straggler, staggered max_new so the shorts are mid-decode when the
straggler joins) through a chunked engine and the monolithic-join
baseline on a DETERMINISTIC work-proportional virtual clock: the
engine's ``_PREFILL_CLOCK_HOOK`` seam charges 1 ms per prefill token
between each prefill span's two clock reads, so the flight-recorder
stall decomposition (analysis/servetrace.py) compares the two designs
on trace structure alone — no wall jitter, bitwise-reproducible
verdict. Asserts:

- streams BIT-IDENTICAL chunked vs unchunked (every rid, every token),
  both traces complete every request — equal completed-request goodput
  by construction;
- the baseline pays at least one prefill span over the budget (the
  straggler's monolithic join — the contrast being gated exists);
- the chunked trace's per-step prefill bill never exceeds
  ``prefill_budget``, asserted from the flight records (every span is a
  chunk drain, every span's tokens <= budget) AND the engine's
  ``max_step_prefill_tokens`` telemetry;
- chunked ``prefill_stall_p99_ms`` STRICTLY below unchunked — the
  shorts still running at the straggler's admission each wait through
  at most their remaining decode steps' worth of 8-token chunks
  instead of the full 128-token prefill;
- the chunked servetrace artifact carries the per-chunk records and
  its fold-time conservation check (sum of chunk tokens == admitted
  suffix tokens per rid) passes;
- pool conservation (``check_idle``) on both engines.

Run (CPU): scripts/run_tests_and_package.sh invokes this as the
chunked-prefill gate. Exit 0 ok / 1 any assertion failed.
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("PALLAS_AXON_POOL_IPS", "")

import numpy as np

import jax

from cs336_systems_tpu.analysis import servetrace
from cs336_systems_tpu.models.transformer import (
    TransformerConfig,
    init_transformer_lm,
)
from cs336_systems_tpu.serving import Request, ServingEngine
from cs336_systems_tpu.serving import engine as engine_mod

CHUNK = BUDGET = 8
SHORT, LONG, N_SHORT = 16, 128, 8
TOK_S = 1e-3  # virtual seconds charged per prefill token (1 ms/token)


def _cfg() -> TransformerConfig:
    # the test model widened to a 256-token context so the straggler's
    # prompt is 16 chunks long — enough steps for the shorts to finish
    # progressively while its prefill drains
    return TransformerConfig(vocab_size=64, context_length=256,
                             d_model=64, d_ff=128, num_layers=2,
                             num_heads=4)


def _requests(rng: np.random.Generator) -> list[Request]:
    lens = [SHORT] * N_SHORT + [LONG]
    return [
        Request(rid=i, prompt=rng.integers(0, 64, size=ln),
                max_new_tokens=4 + i, arrival=0.0)
        for i, ln in enumerate(lens)
    ]


class _WorkClock:
    """Virtual trace clock: advances ONLY when the prefill hook charges
    it, so every span duration is exactly its token count in ms."""

    def __init__(self):
        self.t = 0.0

    def __call__(self) -> float:
        return self.t

    def charge(self, tokens: int) -> None:
        self.t += tokens * TOK_S


def _run(params, cfg, chunked: bool):
    clk = _WorkClock()
    eng = ServingEngine(
        params, cfg, key=jax.random.PRNGKey(0), slots=N_SHORT,
        n_pages=64, max_blocks=-(-(LONG + 4 + N_SHORT) // 8),
        page_block=8, temperature=0.9, top_k=8, clock=clk,
        prefill_chunk=CHUNK if chunked else None,
        prefill_budget=BUDGET if chunked else None)
    engine_mod._PREFILL_CLOCK_HOOK = clk.charge
    try:
        for r in _requests(np.random.default_rng(7)):
            eng.submit(r)
        results = eng.run()
    finally:
        engine_mod._PREFILL_CLOCK_HOOK = None
    eng.check_idle()  # pool conservation: the no-leak gate
    return eng, results


def main() -> int:
    cfg = _cfg()
    params = init_transformer_lm(jax.random.PRNGKey(1), cfg)
    base_eng, base = _run(params, cfg, chunked=False)
    chk_eng, chk = _run(params, cfg, chunked=True)

    fails = []
    n = N_SHORT + 1
    if sorted(base) != list(range(n)) or sorted(chk) != list(range(n)):
        fails.append(f"not every request completed: baseline "
                     f"{sorted(base)}, chunked {sorted(chk)}")
    for rid in base:
        if not np.array_equal(base[rid], chk.get(rid)):
            fails.append(f"rid {rid}: chunked stream diverges from the "
                         f"monolithic baseline — not bit-identical")
            break

    base_art, chk_art = servetrace.fold(base_eng), servetrace.fold(chk_eng)
    b99 = base_art["components_ms"]["prefill_stall"]["p99"]
    c99 = chk_art["components_ms"]["prefill_stall"]["p99"]
    if not any(p["tokens"] > BUDGET for p in base_eng.flight.prefills):
        fails.append("baseline never exceeded the budget in one span — "
                     "the workload lost its straggler contrast")
    over = [p["tokens"] for p in chk_eng.flight.prefills
            if p["tokens"] > BUDGET]
    if over:
        fails.append(f"chunked spans over budget {BUDGET}: {over}")
    if any("chunks" not in p for p in chk_eng.flight.prefills):
        fails.append("chunked engine emitted a prefill span without "
                     "per-chunk records")
    if chk_eng.max_step_prefill_tokens > BUDGET:
        fails.append(f"max_step_prefill_tokens "
                     f"{chk_eng.max_step_prefill_tokens} > budget {BUDGET}")
    cons = chk_art["conservation"].get("prefill_chunks")
    if not (cons and cons.get("ok")):
        fails.append(f"chunk-token conservation missing or failed in the "
                     f"servetrace artifact: {cons}")
    if not c99 < b99:
        fails.append(f"chunked prefill_stall p99 {c99:.3f} ms not "
                     f"strictly below unchunked {b99:.3f} ms")

    print(f"chunked-prefill gate: stall p99 {b99:.1f} -> {c99:.1f} ms "
          f"(virtual 1 ms/token), {chk_eng.prefill_chunks} chunks, "
          f"max step bill {chk_eng.max_step_prefill_tokens}/{BUDGET} "
          f"tok, streams bit-identical over {len(base)} requests")
    for f in fails:
        print(f"FAIL: {f}", file=sys.stderr)
    return 1 if fails else 0


if __name__ == "__main__":
    sys.exit(main())
