"""Same-process A/B of the round-3 attention-path optimizations on the chip.

Measures the headline train step (bench.py config) under the four
combinations of {rope_fused, qkv_fused} — same process, same data, each
best-of-3 — plus an on-chip numerics check of the fused-rope kernels
(fwd + grads vs the rotate-outside formulation) and a compile probe of the
fused single-pass backward at its S=1024 bf16 VMEM boundary with the rope
operands added.

BASELINE.md rule: isolated-kernel harness deltas do not transfer — only
the end-to-end step decides. This script IS the end-to-end step.

Usage: PYTHONPATH=. python scripts/ab_rope_fused.py
"""

from __future__ import annotations

import dataclasses
import time

import jax
import jax.numpy as jnp

from cs336_systems_tpu.models.transformer import config_for_size
from cs336_systems_tpu.optim.adamw import AdamWHparams
from cs336_systems_tpu.train import init_train_state, make_train_loop


def measure(cfg, xs, ys, reps: int = 3) -> tuple[float, float]:
    params, opt_state = init_train_state(jax.random.PRNGKey(0), cfg)
    loop = make_train_loop(cfg, AdamWHparams(lr=3e-4))
    params, opt_state, losses = loop(params, opt_state, xs, ys)
    final_loss = float(losses[-1])  # fence + sanity value
    dt = float("inf")
    for _ in range(reps):
        t0 = time.perf_counter()
        params, opt_state, losses = loop(params, opt_state, xs, ys)
        float(losses[-1])
        dt = min(dt, time.perf_counter() - t0)
    return dt, final_loss


def main() -> None:
    assert jax.default_backend() == "tpu", jax.default_backend()

    # --- on-chip numerics: fused rope vs rotate-outside, headline shape ---
    from cs336_systems_tpu.models.layers import apply_rope, rope_cache
    from cs336_systems_tpu.ops.flash_attention import flash_attention

    B, S, D = 384, 512, 64
    ks = jax.random.split(jax.random.PRNGKey(1), 4)
    q, k, v = (jax.random.normal(kk, (B, S, D), jnp.bfloat16) for kk in ks[:3])
    cos, sin = rope_cache(S, D)
    pos = jnp.arange(S)

    def loss_fused(q, k, v):
        return jnp.sum(
            flash_attention(q, k, v, causal=True, impl="pallas",
                            rope_cos=cos, rope_sin=sin).astype(jnp.float32) ** 2
        )

    def loss_outside(q, k, v):
        qr = apply_rope(q, cos, sin, pos)
        kr = apply_rope(k, cos, sin, pos)
        return jnp.sum(
            flash_attention(qr, kr, v, causal=True,
                            impl="pallas").astype(jnp.float32) ** 2
        )

    gf = jax.jit(jax.grad(loss_fused, argnums=(0, 1, 2)))(q, k, v)
    go = jax.jit(jax.grad(loss_outside, argnums=(0, 1, 2)))(q, k, v)
    for a, b, name in zip(gf, go, "qkv"):
        err = float(jnp.max(jnp.abs(a.astype(jnp.float32) - b.astype(jnp.float32))))
        ref = float(jnp.max(jnp.abs(b.astype(jnp.float32))))
        print(f"on-chip d{name} max abs err {err:.4f} (ref magnitude {ref:.1f})",
              flush=True)

    # --- compile probe: fused single-pass bwd boundary S=1024 bf16 + rope ---
    try:
        q2, k2, v2 = (jax.random.normal(kk, (8, 1024, 64), jnp.bfloat16)
                      for kk in ks[:3])
        c2, s2 = rope_cache(1024, 64)
        g2 = jax.jit(jax.grad(
            lambda q, k, v: jnp.sum(
                flash_attention(q, k, v, causal=True, impl="pallas",
                                rope_cos=c2, rope_sin=s2).astype(jnp.float32) ** 2
            )
        ))(q2, k2, v2)
        jax.block_until_ready(g2)
        print("S=1024 bf16 fused bwd + rope: compiles and runs", flush=True)
    except Exception as e:  # noqa: BLE001 — report the Mosaic failure verbatim
        print(f"S=1024 bf16 fused bwd + rope FAILED: {type(e).__name__}: "
              f"{str(e)[:300]}", flush=True)

    # --- end-to-end A/B ---
    ctx, batch, timed = 512, 32, 10
    base = config_for_size(
        "small", context_length=ctx, compute_dtype="bfloat16",
        attn_impl="flash", scan_layers=False,
        rope_fused=False, qkv_fused=False,
    )
    xs = jax.random.randint(jax.random.PRNGKey(2), (timed, batch, ctx), 0,
                            base.vocab_size)
    ys = jnp.roll(xs, -1, axis=-1)

    results = {}
    for rf, qf in [(False, False), (True, False), (False, True), (True, True)]:
        cfg = dataclasses.replace(base, rope_fused=rf, qkv_fused=qf)
        dt, loss = measure(cfg, xs, ys)
        toks = batch * ctx * timed / dt
        results[(rf, qf)] = toks
        print(f"rope_fused={rf!s:5} qkv_fused={qf!s:5}  "
              f"{dt * 1e3 / timed:7.1f} ms/step  {toks:9.0f} tok/s  "
              f"loss {loss:.4f}", flush=True)

    base_t = results[(False, False)]
    for kcfg, t in results.items():
        print(f"{kcfg}: {t / base_t:+.1%} vs baseline", flush=True)


if __name__ == "__main__":
    main()
