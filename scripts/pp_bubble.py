"""Measure the GPipe pipeline bubble: step time vs microbatch count.

Analytically the bubble fraction is (W-1)/(M+W-1) for W stages and M
microbatches. The GPipe timing model is

    t_pp(M) = kappa * (M + W - 1) / M

(per-microbatch work ∝ 1/M; the schedule runs M + W - 1 microbatch
slots). This script measures t_pp at several M, fits the single constant
kappa by least squares, and reports the MEASURED bubble fraction
(t - kappa)/t per M against the analytic value — agreement within a few
percent means the schedule really pays exactly the GPipe bubble and
nothing else grows with M.

Methodology caveat (8-virtual-device CPU mesh — same status as
results/allreduce_cpu8.txt): virtual devices timeshare one host's cores,
so comparisons against the UNPIPELINED step are invalid here ("idle"
pipeline stages donate their cores to busy ones); the t(M) scaling shape
is the valid observable, and it is hardware-independent — the same fit on
a real pp mesh measures the same schedule property over ICI.

Usage: PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu \
  XLA_FLAGS=--xla_force_host_platform_device_count=8 \
  PYTHONPATH=. python scripts/pp_bubble.py [> results/pp_cpu8.txt]
"""

from cs336_systems_tpu.utils.platform import honor_cpu_request

honor_cpu_request()

import jax
import jax.numpy as jnp

from cs336_systems_tpu.models.transformer import TransformerConfig, init_transformer_lm
from cs336_systems_tpu.optim.adamw import AdamWHparams, adamw_init
from cs336_systems_tpu.parallel.mesh import make_mesh
from cs336_systems_tpu.parallel.pp import make_pp_train_step, shard_params_pp
from cs336_systems_tpu.utils.timing import timed_total

CFG = TransformerConfig(
    vocab_size=512, context_length=128, d_model=128,
    num_layers=8, num_heads=4, d_ff=256,
)
BATCH = 32
W = 4


def main() -> None:
    hp = AdamWHparams(lr=1e-3)
    x = jax.random.randint(jax.random.PRNGKey(1), (BATCH, CFG.context_length),
                           0, CFG.vocab_size)
    y = jnp.roll(x, -1, axis=-1)

    params = init_transformer_lm(jax.random.PRNGKey(0), CFG)
    print(f"# W={W} stages, {CFG.num_layers} layers, batch {BATCH}, "
          f"ctx {CFG.context_length}, 8-virtual-CPU mesh")

    mesh = make_mesh({"pp": W})
    p_pp = shard_params_pp(params, mesh, CFG)
    ms = (W, 2 * W, 4 * W)
    times = []
    for m in ms:
        step = make_pp_train_step(CFG, hp, mesh, num_microbatches=m,
                                  donate=False)
        o_pp = adamw_init(p_pp)
        t_pp, _ = timed_total(step, p_pp, o_pp, x, y, warmup=2, iters=8)
        times.append(t_pp.mean_ms)

    # least-squares kappa for t(M) = kappa * (M+W-1)/M
    factors = [(m + W - 1) / m for m in ms]
    kappa = sum(t * f for t, f in zip(times, factors)) / sum(
        f * f for f in factors
    )
    print(f"GPipe-model fit: t(M) = {kappa:.0f} ms * (M+{W - 1})/M")
    print(f"{'M':>4} {'t_pp_ms':>9} {'model_ms':>9} {'fit_err%':>9} "
          f"{'measured_bubble%':>17} {'analytic_bubble%':>17}")
    for m, t in zip(ms, times):
        model = kappa * (m + W - 1) / m
        measured = (t - kappa) / t
        analytic = (W - 1) / (m + W - 1)
        print(f"{m:4d} {t:9.1f} {model:9.1f} "
              f"{(t - model) / model * 100:9.1f} "
              f"{measured * 100:17.1f} {analytic * 100:17.1f}")


if __name__ == "__main__":
    main()
