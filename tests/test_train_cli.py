"""Training CLI smoke tests: every parallel mode runs on the virtual mesh,
losses agree across modes (same update semantics), checkpoint/resume works.
"""

import jax
import numpy as np
import pytest

from cs336_systems_tpu.train_cli import main

TINY = [
    "--size", "small", "--layers", "2", "--d-model", "64", "--d-ff", "128",
    "--heads", "4", "--ctx", "32", "--vocab", "64", "--batch", "8",
    "--warmup", "1", "--synthetic", "--log-every", "2",
]


def _step_losses(out):
    """Loss column of every training step line (eval lines excluded)."""
    return [l.split("loss")[1].split()[0] for l in out.splitlines()
            if l.startswith("step") and "eval" not in l]


def _snapshot_at_step(cli, monkeypatch, src_dir, dst_dir, step):
    """Monkeypatch save_checkpoint to copy the checkpoint dir the moment
    the given step's checkpoint is written — the mid-run snapshot move the
    resume-exact tests share (both runs keep the same --steps so the
    cosine schedule matches)."""
    import shutil

    real_save = cli.save_checkpoint

    def snapshotting_save(path, *a, **kw):
        real_save(path, *a, **kw)
        if kw.get("step") == step:
            shutil.copytree(src_dir, dst_dir, dirs_exist_ok=True)

    monkeypatch.setattr(cli, "save_checkpoint", snapshotting_save)
    return real_save


def _last_loss(out: str) -> float:
    """Last TRAINING loss — eval lines ('step N  eval_loss X') excluded."""
    lines = [
        l for l in out.splitlines()
        if l.startswith("step") and "eval_loss" not in l
    ]
    assert lines, out
    return float(lines[-1].split("loss")[1].split()[0])


_MODE_NONE_LOSS: dict[str, float] = {}


def _reference_loss(capsys) -> float:
    """Loss of --parallel none, computed once per session — the other modes
    are compared to it rather than to a hard-coded constant (which any
    jax/XLA RNG change would break even with all modes still agreeing)."""
    if "loss" not in _MODE_NONE_LOSS:
        main(TINY + ["--steps", "4", "--parallel", "none"])
        _MODE_NONE_LOSS["loss"] = _last_loss(capsys.readouterr().out)
    return _MODE_NONE_LOSS["loss"]


@pytest.mark.parametrize("mode,extra", [
    ("bucketed", []),
    ("zero1", []),
    ("fsdp", []),
    ("tp", ["--mesh", "dp=2,tp=4"]),
    ("sp", []),
    ("pp", ["--mesh", "dp=2,pp=2", "--microbatches", "2"]),
    ("tp_sp", ["--mesh", "dp=2,tp=2,sp=2"]),
])
def test_cli_parallel_modes_agree(mode, extra, capsys):
    ref = _reference_loss(capsys)
    main(TINY + ["--steps", "4", "--parallel", mode] + extra)
    loss = _last_loss(capsys.readouterr().out)
    # same seed, same data, same update semantics in every mode
    np.testing.assert_allclose(loss, ref, atol=2e-3)
    # and the run is actually training (not NaN/degenerate)
    assert 0 < ref < 10


def test_cli_window_flag_trains(capsys, monkeypatch):
    """--window plumbs cfg.attn_window through the CLI (asserted on the
    constructed config, not only on the loss — two float losses coinciding
    at print precision would make a loss-only check flaky) and the windowed
    run trains to a finite loss."""
    import cs336_systems_tpu.train_cli as cli

    seen = {}
    real = cli.config_for_size

    def spy(size, **kw):
        cfg = real(size, **kw)
        seen["attn_window"] = cfg.attn_window
        return cfg

    monkeypatch.setattr(cli, "config_for_size", spy)
    main(TINY + ["--steps", "4", "--window", "8"])
    win = _last_loss(capsys.readouterr().out)
    assert seen["attn_window"] == 8
    assert 0 < win < 10


def test_cli_moe_dispatch_flags(capsys, monkeypatch):
    """--moe-dispatch/--moe-ffn-remat/--moe-cf plumb through to the config
    (asserted on the constructed cfg) and the run trains; the flags are
    rejected without --experts."""
    import cs336_systems_tpu.train_cli as cli

    seen = {}
    real = cli.config_for_size

    def spy(size, **kw):
        cfg = real(size, **kw)
        seen.update(dispatch=cfg.moe_dispatch, remat=cfg.moe_ffn_remat,
                    cf=cfg.moe_capacity_factor)
        return cfg

    monkeypatch.setattr(cli, "config_for_size", spy)
    main(TINY + ["--steps", "2", "--experts", "4", "--moe-dispatch", "gmm",
                 "--moe-ffn-remat", "--moe-cf", "1.0"])
    out = capsys.readouterr().out
    assert seen == {"dispatch": "gmm", "remat": True, "cf": 1.0}
    assert any(l.startswith("step") for l in out.splitlines())
    with pytest.raises(SystemExit, match="--moe-"):
        main(TINY + ["--steps", "1", "--moe-dispatch", "gmm"])


def test_cli_ep_mode_trains(capsys):
    """--parallel ep trains an MoE model (different loss surface than the
    dense modes — aux load-balance term — so: finite and decreasing)."""
    main(TINY + ["--steps", "12", "--parallel", "ep", "--experts", "16",
                 "--log-every", "1"])
    out = capsys.readouterr().out
    losses = [
        float(l.split("loss")[1].split()[0])
        for l in out.splitlines()
        if l.startswith("step") and "eval" not in l
    ]
    assert len(losses) >= 4 and np.isfinite(losses).all()
    # training, not diverging (single steps can tick up: aux term)
    assert min(losses[-3:]) < losses[0]


def test_cli_ep_requires_experts():
    with pytest.raises(SystemExit, match="experts"):
        main(TINY + ["--steps", "1", "--parallel", "ep"])


@pytest.mark.parametrize("mode", ["zero1", "fsdp"])
def test_cli_sharded_checkpoint_resume(mode, tmp_path, capsys):
    """Sharded modes checkpoint their [world, chunk] optimizer rows and
    resume exactly (the bitwise oracle is tests/test_sharded_checkpoint.py;
    this pins the CLI wiring end to end)."""
    ck = str(tmp_path / "ck")
    main(TINY + ["--steps", "4", "--parallel", mode, "--checkpoint-dir", ck,
                 "--checkpoint-every", "2"])
    first = capsys.readouterr().out
    assert "checkpointed step 4" in first

    main(TINY + ["--steps", "8", "--parallel", mode, "--checkpoint-dir", ck,
                 "--checkpoint-every", "2", "--resume"])
    out = capsys.readouterr().out
    assert "resumed" in out and "step      8" in out
    assert "step      2" not in out  # no re-run of consumed steps


def test_cli_checkpoint_resume(tmp_path, capsys):
    ck = str(tmp_path / "ck")
    main(TINY + ["--steps", "4", "--checkpoint-dir", ck,
                 "--checkpoint-every", "2"])
    first = capsys.readouterr().out
    assert "checkpointed step 4" in first

    main(TINY + ["--steps", "8", "--checkpoint-dir", ck,
                 "--checkpoint-every", "2", "--resume"])
    out = capsys.readouterr().out
    assert "resumed" in out and "step      8" in out
    # resumed run must not re-log steps <= 4
    assert "step      2" not in out


def test_cli_moe_checkpoint_resume_exact(tmp_path, capsys, monkeypatch):
    """MoE checkpoint resume reproduces the uninterrupted run's losses
    EXACTLY (the step-keyed data stream + full opt-state restore cover the
    router/expert/aux machinery the dense resume test never exercises).

    The mid-run checkpoint is snapshotted the moment it is written (the
    same move as the on-chip dense proof, train_small_v5e.txt) — both
    runs use --steps 8, so the cosine schedule is identical; a shorter
    head run would sit on a different LR curve and diverge before any
    resume happened."""
    import cs336_systems_tpu.train_cli as cli

    moe = ["--experts", "4", "--moe-dispatch", "sorted"]
    ck = str(tmp_path / "ck")
    ck_mid = str(tmp_path / "ck_mid")
    real_save = _snapshot_at_step(cli, monkeypatch, ck, ck_mid, step=4)
    main(TINY + moe + ["--steps", "8", "--log-every", "1",
                       "--checkpoint-dir", ck, "--checkpoint-every", "4"])
    unbroken = _step_losses(capsys.readouterr().out)
    monkeypatch.setattr(cli, "save_checkpoint", real_save)

    main(TINY + moe + ["--steps", "8", "--log-every", "1",
                       "--checkpoint-dir", ck_mid, "--checkpoint-every", "100",
                       "--resume"])
    tail = _step_losses(capsys.readouterr().out)
    assert tail == unbroken[4:]  # string-exact, digit for digit


def test_cli_requires_corpus():
    with pytest.raises(SystemExit, match="corpus"):
        main(["--steps", "1"])


def test_sampled_train_loop_learns_and_reproduces():
    """In-jit corpus sampling: loss falls on the successor corpus; the same
    key yields the same loss trajectory."""
    import jax.numpy as jnp

    from cs336_systems_tpu.models.transformer import TransformerConfig
    from cs336_systems_tpu.optim.adamw import AdamWHparams
    from cs336_systems_tpu.train import init_train_state, make_sampled_train_loop

    cfg = TransformerConfig(
        vocab_size=32, context_length=32, d_model=32, num_layers=2,
        num_heads=2, d_ff=64,
    )
    corpus = jnp.asarray(np.tile(np.arange(32, dtype=np.int32), 200))
    loop = make_sampled_train_loop(
        cfg, AdamWHparams(lr=3e-3), steps_per_call=20, donate=False
    )

    params, opt = init_train_state(jax.random.PRNGKey(0), cfg)
    key = jax.random.PRNGKey(1)
    p1, o1, losses1, key1 = loop(params, opt, corpus, key, 8)
    p2, o2, losses2, _ = loop(p1, o1, corpus, key1, 8)
    assert float(losses2[-1]) < float(losses1[0]) - 1.0

    # reproducibility: identical inputs -> identical trajectory
    _, _, losses1b, _ = loop(params, opt, corpus, jax.random.PRNGKey(1), 8)
    np.testing.assert_allclose(
        np.asarray(losses1), np.asarray(losses1b), rtol=1e-6
    )


def test_cli_loop_chunking_exact_steps_and_ckpt_cadence(tmp_path, capsys):
    """--loop-steps must not overshoot --steps (single-step tail), and
    checkpoints fire whenever a multiple of checkpoint-every is crossed,
    plus a final save."""
    ck = str(tmp_path / "ck")
    main(TINY + ["--steps", "11", "--loop-steps", "4", "--checkpoint-dir", ck,
                 "--checkpoint-every", "3"])
    out = capsys.readouterr().out
    assert "step     11" in out and "step     12" not in out
    # chunks end at 4, 8, 9, 10, 11; multiples of 3 crossed at 4 (3), 8 (6),
    # 9 (9); final save at 11
    for s in ("checkpointed step 4", "checkpointed step 8",
              "checkpointed step 9", "checkpointed step 11"):
        assert s in out, out


def test_cli_resume_params_only_checkpoint_errors(tmp_path):
    """Resuming from a checkpoint without optimizer state must fail with a
    clear message, not a TypeError inside the update."""
    from cs336_systems_tpu.models.transformer import TransformerConfig, init_transformer_lm
    from cs336_systems_tpu.utils.checkpoint import save_checkpoint

    cfg = TransformerConfig(vocab_size=64, context_length=32, d_model=64,
                            num_layers=2, num_heads=4, d_ff=128)
    params = init_transformer_lm(jax.random.PRNGKey(0), cfg)
    ck = str(tmp_path / "ck")
    save_checkpoint(ck, params, config=cfg, step=4)  # no opt_state
    with pytest.raises(SystemExit, match="opt_state"):
        main(TINY + ["--steps", "8", "--checkpoint-dir", ck, "--resume"])


def test_cli_eval_split(capsys):
    """--eval-every reports held-out loss on a reserved corpus split."""
    main(TINY + ["--steps", "4", "--eval-every", "2"])
    out = capsys.readouterr().out
    evals = [l for l in out.splitlines() if "eval_loss" in l]
    assert len(evals) >= 2, out
    assert all(float(l.split("eval_loss")[1]) < 10 for l in evals)


def test_cli_tp_sp_mode_trains(capsys):
    """--parallel tp_sp (the 3-axis dp x tp x sp composition) trains with
    finite decreasing-ish loss through the CLI wiring."""
    main(TINY + ["--steps", "6", "--parallel", "tp_sp",
                 "--mesh", "dp=2,tp=2,sp=2"])
    out = capsys.readouterr().out
    losses = [
        float(l.split("loss")[1].split()[0])
        for l in out.splitlines()
        if l.startswith("step") and "eval" not in l
    ]
    assert len(losses) >= 2 and np.isfinite(losses).all()


def test_cli_tp_sp_checkpoint_resume_exact(tmp_path, capsys, monkeypatch):
    """The 3-axis tp_sp mode checkpoints and resumes EXACTLY: losses of
    the resumed tail equal the uninterrupted run digit for digit (params
    and opt state re-placed onto the tp layout; step-keyed data stream;
    the shared mid-run snapshot pattern keeps the cosine schedule equal)."""
    import cs336_systems_tpu.train_cli as cli

    mode = ["--parallel", "tp_sp", "--mesh", "dp=2,tp=2,sp=2"]
    ck = str(tmp_path / "ck")
    ck_mid = str(tmp_path / "ck_mid")
    real_save = _snapshot_at_step(cli, monkeypatch, ck, ck_mid, step=4)
    main(TINY + mode + ["--steps", "6", "--log-every", "1",
                        "--checkpoint-dir", ck, "--checkpoint-every", "2"])
    unbroken = _step_losses(capsys.readouterr().out)
    monkeypatch.setattr(cli, "save_checkpoint", real_save)

    main(TINY + mode + ["--steps", "6", "--log-every", "1",
                        "--checkpoint-dir", ck_mid,
                        "--checkpoint-every", "100", "--resume"])
    out = capsys.readouterr().out
    assert "resumed" in out
    assert _step_losses(out) == unbroken[4:]  # string-exact


def test_cli_resume_falls_back_from_corrupt_newest(tmp_path, capsys):
    """End-to-end recovery through the CLI (ISSUE 11): byte-flip the
    newest checkpoint's params.npz — --resume must print the typed
    WARNING (DigestMismatch is retriable), walk back to the newest
    intact version, and the resumed tail must be string-exact against
    the uninterrupted run (step-keyed data stream: falling back from
    step 4 to step 2 replays 3..6 identically)."""
    from cs336_systems_tpu.utils import checkpoint as ckpt

    ck = str(tmp_path / "ck")
    main(TINY + ["--steps", "6", "--log-every", "1",
                 "--checkpoint-dir", ck, "--checkpoint-every", "2"])
    unbroken = _step_losses(capsys.readouterr().out)

    # damage the newest version (step 6 is newest; nuke it so the
    # walk-back target is step 4 — keeps the tail comparison non-empty
    # after restoring a middle checkpoint)
    import os

    versions = ckpt._version_dirs(ck)
    newest = os.path.join(ck, versions[-1][1])
    with open(os.path.join(newest, "params.npz"), "r+b") as f:
        f.seek(100)
        byte = f.read(1)[0]
        f.seek(100)
        f.write(bytes([byte ^ 0xFF]))

    main(TINY + ["--steps", "6", "--log-every", "1",
                 "--checkpoint-dir", ck, "--checkpoint-every", "100",
                 "--resume"])
    out = capsys.readouterr().out
    assert "WARNING: DigestMismatch" in out
    assert "falling back" in out
    assert "resumed" in out
    # fell back from the corrupt step-6 to intact step-4, replayed 5..6
    assert _step_losses(out) == unbroken[4:]
