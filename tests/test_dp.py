"""DP equivalence tests.

Mirrors the reference DDP suites (tests/test_ddp.py,
tests/test_ddp_individual_parameters.py): per-rank differently-initialised
models must equal rank-0 after broadcast; N steps of SGD on disjoint batch
shards must track a single-process model trained on the full batch;
edge cases are a frozen (requires_grad=False) parameter and tied weights;
bucket sizes are tuned to force 1 / several / many buckets on the toy model.
World size is the reference's 2 (subset of the 8-device CPU mesh).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from cs336_systems_tpu.parallel.collectives import broadcast_from_rank0
from cs336_systems_tpu.parallel.dp import (
    VARIANTS,
    assign_buckets,
    make_dp_grad_fn,
    sync_grads,
)
from cs336_systems_tpu.parallel.mesh import make_mesh, shard_batch

from common import (
    mse_loss,
    tied_model_apply,
    tied_model_init,
    toy_model_apply,
    toy_model_init,
    trees_allclose,
)

WORLD = 2
LR = 0.1
STEPS = 5


@pytest.fixture(scope="module")
def mesh():
    return make_mesh({"dp": WORLD}, devices=jax.devices()[:WORLD])


@pytest.fixture(scope="module")
def fixture_data():
    rng = np.random.default_rng(4)
    x = rng.standard_normal((20, 10)).astype(np.float32)
    y = rng.standard_normal((20, 5)).astype(np.float32)
    return jnp.asarray(x), jnp.asarray(y)


def sgd(params, grads, trainable):
    return jax.tree_util.tree_map(
        lambda p, g, t: p - LR * g if t else p, params, grads, trainable
    )


def _run_single(apply_fn, params, trainable, x, y):
    loss_fn = lambda p, xx, yy: mse_loss(apply_fn, p, xx, yy)
    for _ in range(STEPS):
        grads = jax.grad(loss_fn)(params, x, y)
        params = sgd(params, grads, trainable)
    return params


def _run_dp(apply_fn, params, trainable, x, y, mesh, variant, bucket_mb=1000.0):
    loss_fn = lambda p, xx, yy: mse_loss(apply_fn, p, xx, yy)
    grad_fn = make_dp_grad_fn(
        loss_fn, mesh, variant=variant, bucket_size_mb=bucket_mb, trainable=trainable
    )
    xs, ys = shard_batch(mesh, x, y)
    for _ in range(STEPS):
        _, grads = grad_fn(params, xs, ys)
        params = sgd(params, grads, trainable)
    return params


def test_broadcast_from_rank0(mesh):
    """Differently-seeded per-rank params must all equal rank-0 after wrap
    (reference test_ddp.py:86-97 + validate_ddp_net_equivalence)."""
    stacks = jax.tree_util.tree_map(
        lambda *xs: jnp.stack(xs),
        *[toy_model_init(jax.random.PRNGKey(100 + r))[0] for r in range(WORLD)],
    )
    bcast = broadcast_from_rank0(stacks, mesh)
    rank0, _ = toy_model_init(jax.random.PRNGKey(100))
    assert trees_allclose(bcast, rank0)
    # and NOT equal to rank 1's independent init
    rank1, _ = toy_model_init(jax.random.PRNGKey(101))
    assert not trees_allclose(bcast, rank1)


@pytest.mark.parametrize("variant", VARIANTS)
def test_dp_matches_single_process(mesh, fixture_data, variant):
    """DP-trained params == single-process full-batch params after 5 steps
    (reference test_ddp.py:105-180)."""
    x, y = fixture_data
    params, trainable = toy_model_init(jax.random.PRNGKey(0))
    single = _run_single(toy_model_apply, params, trainable, x, y)
    dp = _run_dp(toy_model_apply, params, trainable, x, y, mesh, variant)
    assert trees_allclose(single, dp, rtol=1e-5, atol=1e-6)


@pytest.mark.parametrize("bucket_mb", [0.0001, 0.0016, 0.01])
def test_dp_bucketed_bucket_sizes(mesh, fixture_data, bucket_mb):
    """Bucket sizes forcing many/2/1 buckets on the toy model all agree
    (reference bucket-size sweep, test_ddp.py docstring 33-41)."""
    x, y = fixture_data
    params, trainable = toy_model_init(jax.random.PRNGKey(1))
    single = _run_single(toy_model_apply, params, trainable, x, y)
    dp = _run_dp(toy_model_apply, params, trainable, x, y, mesh, "bucketed", bucket_mb)
    assert trees_allclose(single, dp, rtol=1e-5, atol=1e-6)


@pytest.mark.parametrize("variant", VARIANTS)
def test_dp_tied_weights(mesh, fixture_data, variant):
    """One array used by two layers gets a single summed gradient and stays
    consistent (reference ToyModelWithTiedWeights, common.py:51-68)."""
    x, y = fixture_data
    params, trainable = tied_model_init(jax.random.PRNGKey(2))
    single = _run_single(tied_model_apply, params, trainable, x, y)
    dp = _run_dp(tied_model_apply, params, trainable, x, y, mesh, variant)
    assert trees_allclose(single, dp, rtol=1e-5, atol=1e-6)


def test_frozen_params_untouched(mesh, fixture_data):
    """Frozen leaves must neither be synced nor updated."""
    x, y = fixture_data
    params, trainable = toy_model_init(jax.random.PRNGKey(3))
    dp = _run_dp(toy_model_apply, params, trainable, x, y, mesh, "bucketed")
    np.testing.assert_array_equal(
        np.asarray(dp["fc2"]["bias"]), np.asarray(params["fc2"]["bias"])
    )
    np.testing.assert_array_equal(
        np.asarray(dp["no_grad_fixed_param"]),
        np.asarray(params["no_grad_fixed_param"]),
    )
    # trainable leaves did move
    assert not np.allclose(np.asarray(dp["fc1"]["weight"]), np.asarray(params["fc1"]["weight"]))


def test_assign_buckets_reverse_greedy():
    leaves = [np.zeros(n, np.float32) for n in (100, 200, 300, 400)]
    # 1 KB budget: reverse order walk = sizes 1600,1200,800,400 bytes
    buckets = assign_buckets(leaves, 1600 / (1024 * 1024))
    # reverse walk: 1600B fills a bucket; 1200B opens one (adding 800 would
    # overflow); 800B+400B pack together
    assert buckets == [[3], [2], [1, 0]]
    # huge budget: single bucket, reverse order preserved
    assert assign_buckets(leaves, 1000) == [[3, 2, 1, 0]]


def test_sync_grads_bad_variant(mesh):
    with pytest.raises(ValueError):
        sync_grads({"w": jnp.ones(3)}, variant="overlapped2")


def test_dp_lm_train_step(mesh):
    """The full LM DP step runs on the mesh and matches single-device
    training (both sides see the same global batch)."""
    from cs336_systems_tpu.models.transformer import TransformerConfig
    from cs336_systems_tpu.optim.adamw import AdamWHparams
    from cs336_systems_tpu.parallel.dp import make_dp_train_step
    from cs336_systems_tpu.train import init_train_state, make_train_step

    cfg = TransformerConfig(
        vocab_size=32, context_length=16, d_model=32,
        num_layers=2, num_heads=2, d_ff=64,
    )
    hp = AdamWHparams(lr=1e-3)
    params, opt = init_train_state(jax.random.PRNGKey(0), cfg)
    x = jax.random.randint(jax.random.PRNGKey(1), (4, 16), 0, 32)
    y = jax.random.randint(jax.random.PRNGKey(2), (4, 16), 0, 32)

    single_step = make_train_step(cfg, hp, clip_norm=1.0, donate=False)
    p1, o1, l1 = single_step(params, opt, x, y)

    dp_step = make_dp_train_step(cfg, hp, mesh, variant="bucketed", clip_norm=1.0, donate=False)
    xs, ys = shard_batch(mesh, x, y)
    p2, o2, l2 = dp_step(params, opt, xs, ys)

    np.testing.assert_allclose(float(l1), float(l2), rtol=1e-6)
    assert trees_allclose(p1, p2, rtol=1e-5, atol=1e-6)


def test_sync_grads_preserves_dtype(mesh):
    """Mixed-dtype grads must come back in their own dtype for every variant
    (no silent bf16→fp32 promotion in the flat/bucketed concat)."""
    grads = {
        "a": jnp.ones((4, 4), jnp.bfloat16),
        "b": jnp.ones((4,), jnp.float32),
    }
    for variant in VARIANTS:
        def local(g, variant=variant):
            g = jax.tree_util.tree_map(lambda t: jax.lax.pcast(t, "dp", to="varying"), g)
            return sync_grads(g, "dp", variant, 0.001)
        fn = jax.jit(jax.shard_map(local, mesh=mesh, in_specs=(P(),), out_specs=P()))
        out = fn(grads)
        assert out["a"].dtype == jnp.bfloat16, variant
        assert out["b"].dtype == jnp.float32, variant
