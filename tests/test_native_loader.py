"""Native (C++) data loader tests.

Contract under test (data/native_loader.py + native/dataloader.cpp):
correct crop semantics (y is x shifted by one in the corpus), determinism
in (seed, step), seed independence, prefetch-equals-sample sequence, and
dtype handling. Skips if no C++ toolchain is available.
"""

import numpy as np
import pytest

from cs336_systems_tpu.data.native_loader import (
    NativeTokenLoader,
    native_available,
    native_load_error,
)

pytestmark = pytest.mark.skipif(
    not native_available(), reason=f"native loader: {native_load_error()}"
)


@pytest.fixture(scope="module")
def corpus_file(tmp_path_factory):
    path = tmp_path_factory.mktemp("corpus") / "tokens.bin"
    tokens = np.arange(50_000, dtype=np.uint16) % 1000
    tokens.tofile(path)
    return path, tokens


def test_open_and_len(corpus_file):
    path, tokens = corpus_file
    with NativeTokenLoader(path) as dl:
        assert len(dl) == tokens.size
        assert dl.token(0) == int(tokens[0])
        assert dl.token(1234) == int(tokens[1234])


def test_crop_semantics_and_ranges(corpus_file):
    path, tokens = corpus_file
    with NativeTokenLoader(path) as dl:
        x, y = dl.sample(batch=16, ctx=64, seed=7, step=0)
        assert x.shape == y.shape == (16, 64) and x.dtype == np.int32
        # every row must be a contiguous corpus crop with y = next tokens
        for b in range(16):
            # recover the start from the corpus pattern (i % 1000 with a
            # strictly increasing underlying index makes rows unique by
            # locating the crop via exact match)
            matches = np.flatnonzero(
                np.all(np.lib.stride_tricks.sliding_window_view(
                    tokens, 64) == x[b].astype(np.uint16), axis=1)
            )
            assert matches.size >= 1
            s = int(matches[0])
            np.testing.assert_array_equal(
                y[b], tokens[s + 1 : s + 65].astype(np.int32)
            )


def test_determinism_and_seed_independence(corpus_file):
    path, _ = corpus_file
    with NativeTokenLoader(path) as dl:
        a = dl.sample(8, 32, seed=1, step=5)
        b = dl.sample(8, 32, seed=1, step=5)
        np.testing.assert_array_equal(a[0], b[0])
        np.testing.assert_array_equal(a[1], b[1])
        c = dl.sample(8, 32, seed=1, step=6)
        d = dl.sample(8, 32, seed=2, step=5)
        assert not np.array_equal(a[0], c[0])
        assert not np.array_equal(a[0], d[0])


def test_prefetch_matches_sample_sequence(corpus_file):
    path, _ = corpus_file
    with NativeTokenLoader(path) as dl:
        want = [dl.sample(4, 16, seed=3, step=s) for s in range(6)]
        it = dl.batches(4, 16, seed=3, slots=3)
        got = [next(it) for _ in range(6)]
        it.close()
        for (wx, wy), (gx, gy) in zip(want, got):
            np.testing.assert_array_equal(wx, gx)
            np.testing.assert_array_equal(wy, gy)
        # prefetch can be restarted after close
        it2 = dl.batches(4, 16, seed=3, slots=2)
        gx2, _ = next(it2)
        it2.close()
        np.testing.assert_array_equal(gx2, want[0][0])


def test_int32_corpus(tmp_path):
    path = tmp_path / "tok32.bin"
    tokens = (np.arange(10_000, dtype=np.int32) * 7) % 50_021
    tokens.tofile(path)
    with NativeTokenLoader(path, dtype="int32") as dl:
        assert len(dl) == tokens.size
        x, y = dl.sample(4, 128, seed=0, step=0)
        assert int(x.max()) < 50_021 and int(x.min()) >= 0


def test_stream_batches_both_paths(corpus_file):
    """The high-level iterator works over the native and NumPy backends and
    yields self-consistent (x, y) crops."""
    from cs336_systems_tpu.data.loader import stream_batches

    path, _ = corpus_file
    for use_native in (True, False):
        it = stream_batches(path, 4, 32, seed=5, use_native=use_native)
        x, y = next(it)
        it.close()
        assert x.shape == (4, 32)
        np.testing.assert_array_equal(np.asarray(y)[:, :-1], np.asarray(x)[:, 1:])


def test_too_short_corpus_errors(tmp_path):
    path = tmp_path / "tiny.bin"
    np.arange(8, dtype=np.uint16).tofile(path)
    with NativeTokenLoader(path) as dl:
        with pytest.raises(ValueError, match="dl_sample failed"):
            dl.sample(2, 64, seed=0, step=0)
