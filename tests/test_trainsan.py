"""trainsan oracle tests: the checkpoint/blow-up chaos harness must
(a) report the recovery-armed training loop clean when no fault is
injected (and bit-identical to recovery disabled), and (b) for each
seeded fault prove the typed detector fires AND the recovered curve is
bit-exact against the uninterrupted oracle.

Same discipline as tests/test_gradsan.py / the servesan CI gate: the
harness is itself a test subject — a fault class that stops being
detected is a MISSED verdict here before it is a gap on chip. The fast
single-mode cells run in tier 1; the sharded matrix parity cell
(identical verdicts on zero1's 8-way mesh) is tier-2 ``slow`` — CI's
package gate runs the full dp/zero1 matrix anyway
(scripts/run_tests_and_package.sh).
"""

import json

import pytest

from cs336_systems_tpu.analysis import trainsan
from cs336_systems_tpu.analysis.trainsan import Harness, fault_names


@pytest.fixture(scope="module")
def harness():
    """One single-mode cell shared across tests: the oracle run (and its
    checkpoint store) is cached per Harness, so sharing it keeps the
    module at one uninterrupted 8-step run plus per-fault resumes."""
    with Harness("single", seed=0) as h:
        h.oracle()
        yield h


def test_list_cli_names_every_fault(capsys):
    assert trainsan.main(["--list", "--json"]) == 0
    rep = json.loads(capsys.readouterr().out)
    assert rep["faults"] == fault_names()
    assert set(rep["modes"]) == {"single", "dp", "zero1"}
    # the contracted 8 fault classes, stable order
    assert rep["faults"] == [
        "kill-mid-save", "corrupt-leaf-bytes", "truncated-npz",
        "stale-latest", "manifest-digest-drift", "missing-opt-state",
        "config-mismatch", "nan-grad-at-step-k",
    ]


def test_unknown_fault_is_a_build_error(capsys):
    assert trainsan.main(["--fault", "no-such-fault", "--json"]) == 2
    rep = json.loads(capsys.readouterr().out)
    assert "error" in rep and "no-such-fault" in rep["error"]


def test_unknown_mode_is_rejected():
    with pytest.raises(SystemExit):
        trainsan.main(["--mode", "pp"])  # argparse choices


def test_clean_run_zero_findings(harness):
    row = harness.run_clean()
    assert row["ok"], row
    assert row["detail"]["recovery_on_equals_off"]
    # the oracle never tripped the recovery policy
    last = harness.oracle()["last"]
    assert last["skipped_steps"] == 0 and last["rollbacks"] == 0
    assert last["nonfinite_onset_step"] is None


def test_corrupt_leaf_bytes_verdict(harness):
    row = harness.run_fault("corrupt-leaf-bytes")
    assert row["ok"], row
    assert row["detected"] and row["recovered"]
    assert row["error"]["type"] == "DigestMismatch"
    assert row["error"]["retriable"] is True
    # walk-back landed on the newest undamaged version (step 6)
    assert row["detail"]["fallback_step"] == (
        trainsan.STEPS - trainsan.CKPT_EVERY)


def test_stale_latest_verdict(harness):
    row = harness.run_fault("stale-latest")
    assert row["ok"], row
    assert row["error"]["type"] == "TornCheckpoint"
    assert row["error"]["retriable"] is True


def test_nan_grad_blowup_verdict(harness):
    row = harness.run_fault("nan-grad-at-step-k")
    assert row["ok"], row
    final = row["detail"]["final"]
    assert final["skipped_steps"] == len(trainsan.NAN_STEPS)
    assert final["rollbacks"] == 1
    assert final["nonfinite_onset_step"] == trainsan.NAN_STEPS[0]


def test_config_mismatch_verdict(harness):
    row = harness.run_fault("config-mismatch")
    assert row["ok"], row
    assert row["error"]["type"] == "ConfigMismatch"
    assert row["error"]["retriable"] is False
    assert row["detail"]["cli_systemexit"]


@pytest.mark.slow
def test_zero1_matrix_parity():
    """The verdict matrix must not depend on the sharding family: the
    full zero1 cell (8-way mesh, sharded opt state on disk) returns the
    same all-ok verdicts as single mode. dp is covered by the CI gate."""
    with Harness("zero1", seed=0) as h:
        rows = h.run_all()
    assert all(r["ok"] for r in rows), [
        (r["fault"], r["detected"], r["recovered"])
        for r in rows if not r["ok"]]
    assert {r["fault"] for r in rows} == set(fault_names()) | {"clean"}
