"""Benchmark/profiling harness tests.

The reference "tests" its benchmarks by running them (SURVEY §4.4); here we
run each driver on a tiny grid and assert on the shape/sanity of results —
plus real assertions on the timing and profiling utilities.
"""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from cs336_systems_tpu.utils.timing import timed, timed_total, results_table


def test_timed_measures_and_carries():
    @jax.jit
    def f(x):
        return x * 2.0

    x = jnp.ones((8, 8))
    res, out = timed(f, x, warmup=1, iters=4)
    assert res.iters == 4 and len(res.times_ms) == 4
    assert res.mean_ms > 0 and res.min_ms <= res.mean_ms <= res.max_ms
    np.testing.assert_allclose(np.asarray(out), 2.0)

    # carry threads outputs into the next call's args
    res2, out2 = timed(
        f, x, warmup=0, iters=3, carry=lambda out, args: (out,)
    )
    np.testing.assert_allclose(np.asarray(out2), 8.0)  # 1 * 2^3


def test_timed_total_amortised():
    @jax.jit
    def f(x):
        return x + 1.0

    res, out = timed_total(f, jnp.zeros(()), warmup=1, iters=5)
    assert res.mean_ms > 0


def test_results_table_roundtrip(tmp_path):
    rows = [{"a": 1, "b": "x"}, {"a": 2, "b": "y"}]
    latex = tmp_path / "t.tex"
    df = results_table(rows, str(latex))
    assert latex.exists() and "tabular" in latex.read_text()
    assert len(df) == 2


def test_lm_benchmark_tiny_grid(monkeypatch):
    from cs336_systems_tpu.benchmarks import lm
    from cs336_systems_tpu.models import transformer

    monkeypatch.setitem(transformer.MODEL_SIZES, "tiny", (32, 64, 2, 2))
    df = lm.run_lm_benchmark(
        sizes=("tiny",), context_length=16, batch_size=2,
        dtypes=("float32",), warmup=1, iters=2,
    )
    row = df.iloc[0].to_dict()
    assert row["size"] == "tiny"
    assert float(row["tokens_per_sec"]) > 0
    for col in ("forward_ms", "fwd_bwd_ms", "full_step_ms", "optimizer_ms"):
        assert "±" in row[col]


def test_lm_benchmark_oom_null_row(monkeypatch):
    """A failing cell must yield a null row, not abort the sweep."""
    from cs336_systems_tpu.benchmarks import lm

    def boom(*a, **k):
        raise RuntimeError("RESOURCE_EXHAUSTED (simulated)")

    monkeypatch.setattr(lm, "benchmark_lm_size", boom)
    df = lm.run_lm_benchmark(sizes=("small",), dtypes=("float32",))
    assert df.iloc[0]["error"].startswith("RuntimeError: RESOURCE_EXHAUSTED")


def test_attention_benchmark_tiny_grid():
    from cs336_systems_tpu.benchmarks.attention import run_attention_benchmark

    df = run_attention_benchmark(
        impls=("naive", "flash_ref"), seq_lens=(64,), head_dims=(16,),
        batch=2, warmup=1, iters=2,
    )
    assert len(df) == 2
    assert (df["forward_ms"] > 0).all()
    # no fwd vs fwd+bwd ordering assert: wall-clock on a loaded CI box is
    # too noisy for tiny shapes, and backward_ms is already floored at 0
    assert (df["fwd_bwd_ms"] > 0).all()


def test_memory_benchmark_tiny(monkeypatch, tmp_path):
    from cs336_systems_tpu.benchmarks import memory as mem
    from cs336_systems_tpu.models import transformer

    monkeypatch.setitem(transformer.MODEL_SIZES, "tiny", (32, 64, 2, 2))
    df = mem.run_memory_benchmark(
        size="tiny", context_lengths=(16,), dtypes=("float32",),
        batch_size=2, snapshot_dir=str(tmp_path), isolate=False,
    )
    assert len(df) == 2  # forward + fullstep
    files = os.listdir(tmp_path)
    assert any(f.startswith("memory_ctx16_forward") for f in files)
    assert any(f.startswith("memory_ctx16_fullstep") for f in files)


def test_memory_snapshot_and_stats(tmp_path):
    from cs336_systems_tpu.utils.profiling import (
        live_buffer_bytes,
        memory_snapshot,
        peak_bytes,
    )

    x = jnp.ones((128, 128))
    jax.block_until_ready(x)
    path = tmp_path / "snap.pb.gz"
    memory_snapshot(str(path))
    assert path.exists() and path.stat().st_size > 0
    assert live_buffer_bytes() >= x.nbytes
    assert peak_bytes() >= 0  # CPU backend may not expose allocator stats


def test_trace_writes_profile(tmp_path):
    from cs336_systems_tpu.utils.profiling import annotate, trace

    @jax.jit
    def f(x):
        with annotate("stage"):
            return x @ x

    logdir = tmp_path / "trace"
    with trace(str(logdir)):
        jax.block_until_ready(f(jnp.ones((64, 64))))
    found = [
        os.path.join(r, f)
        for r, _, fs in os.walk(logdir)
        for f in fs
        if f.endswith((".xplane.pb", ".trace.json.gz"))
    ]
    assert found, f"no trace artifacts under {logdir}"


def test_ddp_benchmark_cli_smoke(capsys):
    """The DDP benchmark driver runs end-to-end on the CPU mesh and prints
    every requested variant row plus the differential comm split."""
    from cs336_systems_tpu.benchmarks.ddp import main

    main([
        "--variants", "naive", "bucketed", "--sharded", "--fsdp",
        "--batch", "8", "--ctx", "32",
        "--steps", "1", "--warmup", "1", "--layers", "2", "--dp", "4",
        "--d-model", "64", "--d-ff", "128", "--heads", "4", "--vocab", "128",
        "--bucket-sweep", "0.05",
    ])
    out = capsys.readouterr().out
    for token in ("naive", "bucketed", "nosync", "zero1", "fsdp", "step_ms",
                  "comm_pct", "n_collectives"):
        assert token in out, f"missing {token!r} in DDP benchmark output"
    # the sweep row's collective count reflects the forced tiny bucket
    # (many buckets), not the single-bucket default — parse the
    # n_collectives column by header position (a trailing-float regex
    # could be satisfied by any other .0-valued column)
    lines = [ln for ln in out.splitlines() if ln.strip()]
    header = next(ln for ln in lines if "n_collectives" in ln)
    cols = header.split()
    ci = cols.index("n_collectives")
    counts = []
    for ln in lines[lines.index(header) + 1:]:
        toks = ln.split()
        if len(toks) != len(cols):
            continue
        try:
            v = float(toks[ci])
        except ValueError:
            continue
        if v == v:  # drop NaN cells (rows where bucketing doesn't apply)
            counts.append(int(v))
    assert any(c > 1 for c in counts), out


def test_named_scopes_in_hlo():
    """The model's named_scope annotations must land in HLO metadata —
    that is the NVTX-parity contract (reference transformer_annotated.py)."""
    from cs336_systems_tpu.models.transformer import (
        TransformerConfig,
        init_transformer_lm,
        transformer_lm,
    )

    cfg = TransformerConfig(
        vocab_size=32, context_length=8, d_model=16,
        num_layers=1, num_heads=2, d_ff=32,
    )
    params = init_transformer_lm(jax.random.PRNGKey(0), cfg)
    ids = jnp.zeros((1, 8), jnp.int32)
    lowered = jax.jit(lambda p, i: transformer_lm(p, i, cfg)).lower(params, ids)
    try:
        # scopes live in location metadata
        hlo = lowered.as_text(debug_info=True)
    except TypeError:  # jax < 0.5: as_text has no debug_info kwarg
        hlo = lowered.compiler_ir().operation.get_asm(enable_debug_info=True)
    for scope in ("attn", "ffn", "embed", "lm_head", "sdpa"):
        assert scope in hlo, f"named_scope {scope!r} missing from HLO"


def test_decode_benchmark_cli_smoke(capsys, monkeypatch):
    """The decode benchmark driver runs end-to-end (tiny shapes) and prints
    all three path rows."""
    from cs336_systems_tpu.benchmarks.decode import main
    from cs336_systems_tpu.models import transformer

    monkeypatch.setitem(transformer.MODEL_SIZES, "tiny", (32, 64, 2, 2))
    main(["--size", "tiny", "--prompt", "8", "--new", "4", "--reps", "1"])
    out = capsys.readouterr().out
    for token in ("kv_cache", "prefill_only", "uncached_loop", "ms_per_token"):
        assert token in out, f"missing {token!r} in decode benchmark output"

    # MoE serving path: cfg construction, all-expert roofline, row tag
    main(["--size", "tiny", "--prompt", "8", "--new", "4", "--reps", "1",
          "--no-uncached", "--batches", "2", "--experts", "2",
          "--moe-top-k", "1"])
    out = capsys.readouterr().out
    assert "kv_cache_b2_moe2k1" in out


def test_summarize_trace(tmp_path):
    """The trace summarizer reads back real profiler output and reports
    leaf-op totals (CPU-backend lanes accepted when no device lanes exist)."""
    from cs336_systems_tpu.utils.profiling import summarize_trace, trace

    @jax.jit
    def f(x):
        return (x @ x).sum()

    logdir = tmp_path / "t"
    with trace(str(logdir)):
        jax.block_until_ready(f(jnp.ones((256, 256))))
    rows, total = summarize_trace(str(logdir))
    assert rows and all(
        {"op", "total_ms", "count", "mean_us"} <= set(r) for r in rows
    )
    assert total >= sum(r["total_ms"] for r in rows) - 1e-6
    # host python stack-frame lanes must not pollute the op rows
    assert not any(r["op"].startswith("$") for r in rows), rows[:5]


def test_device_time_per_call():
    """The trace-based per-call timer (the benchmark suites' 'device'
    timing mode) returns a positive per-call millisecond figure and scales
    its denominator by iters (same trace volume / more iters → smaller
    per-call value or equal; exact ratios are backend-noise-bound, so only
    sanity bounds are pinned)."""
    from cs336_systems_tpu.utils.profiling import device_time_per_call

    @jax.jit
    def f(x):
        return jnp.tanh(x @ x).sum()

    x = jnp.ones((256, 256))
    ms = device_time_per_call(f, x, iters=4, warmup=1)
    assert 0.0 < ms < 10_000.0
