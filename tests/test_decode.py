"""KV-cache incremental decoding tests.

Oracles: the batched full forward (``transformer_lm``) for per-step logits,
and the uncached ``generate`` loop for end-to-end sampling — the cache is an
algebraic rearrangement, so both must agree.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from cs336_systems_tpu.models.decode import (
    decode_step,
    generate_kv,
    init_kv_cache,
    prefill,
)
from cs336_systems_tpu.models.transformer import (
    TransformerConfig,
    generate,
    init_transformer_lm,
    transformer_lm,
)

CFG = TransformerConfig(
    vocab_size=64, context_length=48, d_model=32,
    num_layers=2, num_heads=4, d_ff=64,
)


@pytest.fixture(scope="module")
def params():
    return init_transformer_lm(jax.random.PRNGKey(0), CFG)


def test_incremental_logits_match_full_forward(params):
    """Teacher-forced: decoding token-by-token must reproduce the full
    forward's logits at every position."""
    ids = jax.random.randint(jax.random.PRNGKey(1), (2, 12), 0, CFG.vocab_size)
    full = transformer_lm(params, ids, CFG)  # [2, 12, V]

    cache = init_kv_cache(CFG, 2)
    for i in range(12):
        logits, cache = decode_step(params, cache, i, ids[:, i], CFG)
        np.testing.assert_allclose(
            np.asarray(logits), np.asarray(full[:, i], np.float32),
            rtol=1e-4, atol=1e-5, err_msg=f"position {i}",
        )


def test_prefill_matches_stepwise(params):
    ids = jax.random.randint(jax.random.PRNGKey(2), (2, 9), 0, CFG.vocab_size)
    logits_p, cache_p, pos = prefill(params, ids, CFG)
    assert pos == 9

    cache = init_kv_cache(CFG, 2)
    for i in range(9):
        logits, cache = decode_step(params, cache, i, ids[:, i], CFG)
    np.testing.assert_allclose(
        np.asarray(logits_p), np.asarray(logits), rtol=1e-5, atol=1e-6
    )
    np.testing.assert_allclose(
        np.asarray(cache_p["kv"]), np.asarray(cache["kv"]),
        rtol=1e-5, atol=1e-6,
    )


def test_generate_kv_matches_uncached_generate(params):
    """Same key, same sampling semantics → identical token sequences (near-
    greedy temperature keeps categorical draws away from fp tie flips)."""
    prompt = [1, 2, 3]
    kw = dict(max_new_tokens=10, temperature=0.05, top_k=8)
    key = jax.random.PRNGKey(7)
    want = generate(params, CFG, prompt, key=key, **kw)
    got = generate_kv(params, CFG, prompt, key=key, **kw)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_generate_kv_windowed_matches_uncached():
    """Sliding-window attention (cfg.attn_window) must survive the KV-cache
    rearrangement: prefill uses the banded mask and each decode step drops
    keys older than the window, matching the uncached generate exactly.
    Window of 4 over a 6-token prompt + 10 new tokens guarantees every step
    past the fourth actually excludes history (the regression this pins:
    decode used to attend the full cache)."""
    win_cfg = dataclasses.replace(CFG, attn_window=4)
    win_params = init_transformer_lm(jax.random.PRNGKey(4), win_cfg)
    prompt = [1, 2, 3, 4, 5, 6]
    kw = dict(max_new_tokens=10, temperature=1e-3, top_k=None)
    key = jax.random.PRNGKey(9)
    want = generate(win_params, win_cfg, prompt, key=key, **kw)
    got = generate_kv(win_params, win_cfg, prompt, key=key, **kw)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))

    # and the window genuinely changes the distribution vs full causal
    full = generate_kv(win_params, dataclasses.replace(win_cfg, attn_window=None),
                       prompt, key=key, **kw)
    assert not np.array_equal(np.asarray(got), np.asarray(full))


def test_top_p_filter_nucleus_membership():
    """Known distribution: probs [0.5, 0.3, 0.15, 0.05]. top_p=0.6 keeps
    the smallest prefix reaching 0.6 -> {0, 1}; top_p=0.4 keeps {0}; a
    tiny top_p still keeps the argmax (nucleus never empty). Batched rows
    filter independently."""
    from cs336_systems_tpu.models.transformer import top_p_filter

    probs = jnp.asarray([0.5, 0.3, 0.15, 0.05])
    logits = jnp.log(probs)

    kept = np.isfinite(np.asarray(top_p_filter(logits, 0.6)))
    np.testing.assert_array_equal(kept, [True, True, False, False])
    kept = np.isfinite(np.asarray(top_p_filter(logits, 0.4)))
    np.testing.assert_array_equal(kept, [True, False, False, False])
    kept = np.isfinite(np.asarray(top_p_filter(logits, 1e-9)))
    np.testing.assert_array_equal(kept, [True, False, False, False])
    kept = np.isfinite(np.asarray(top_p_filter(logits, 1.0)))
    np.testing.assert_array_equal(kept, [True, True, True, True])

    batched = jnp.stack([logits, logits[::-1]])
    kept = np.isfinite(np.asarray(top_p_filter(batched, 0.6)))
    np.testing.assert_array_equal(kept[0], [True, True, False, False])
    np.testing.assert_array_equal(kept[1], [False, False, True, True])


def test_top_p_generate_kv_matches_uncached(params):
    """Nucleus sampling through the KV-cache path == the uncached generate,
    in a regime where top_p DECIDES the outcome: high temperature flattens
    the distribution, and a tiny top_p forces the argmax — so a silently
    dropped top_p in either path would sample near-uniformly and diverge
    (and from the greedy reference)."""
    prompt = [1, 2, 3]
    kw = dict(max_new_tokens=8, temperature=2.0, top_p=1e-6)
    key = jax.random.PRNGKey(13)
    want = generate(params, CFG, prompt, key=key, **kw)
    got = generate_kv(params, CFG, prompt, key=key, **kw)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))

    # the tiny-nucleus run must equal greedy decoding (argmax), which a
    # missing filter at temperature 2.0 would not produce
    greedy = generate_kv(params, CFG, prompt, key=key, max_new_tokens=8,
                         temperature=1e-3, top_k=None)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(greedy))


def test_generate_kv_eos_truncation(params):
    prompt = [1, 2, 3]
    key = jax.random.PRNGKey(3)
    full = generate_kv(params, CFG, prompt, 12, key, temperature=0.05, top_k=8)
    eos = int(full[4])
    trunc = generate_kv(params, CFG, prompt, 12, key, temperature=0.05,
                        top_k=8, eos_token_id=eos)
    assert len(trunc) <= len(full)
    assert eos not in np.asarray(trunc)


def test_generate_kv_rejects_overflow(params):
    with pytest.raises(ValueError, match="exceeds context_length"):
        generate_kv(params, CFG, list(range(40)), 20, jax.random.PRNGKey(0))


def test_generate_kv_moe_matches_uncached():
    """KV-cached decoding of an MoE model reproduces the uncached generate
    (greedy, generous expert capacity so no tokens drop on either path)."""
    from cs336_systems_tpu.models.transformer import init_transformer_lm

    moe_cfg = dataclasses.replace(
        CFG, num_experts=4, moe_top_k=2, moe_capacity_factor=8.0
    )
    moe_params = init_transformer_lm(jax.random.PRNGKey(5), moe_cfg)
    kw = dict(max_new_tokens=8, temperature=1e-3, top_k=None)
    key = jax.random.PRNGKey(7)
    want = generate(moe_params, moe_cfg, [1, 2, 3], key=key, **kw)
    got = generate_kv(moe_params, moe_cfg, [1, 2, 3], key=key, **kw)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_moe_decode_dropless_under_skew():
    """The MoE serving contract (models/decode._ffn): decode routing is
    DROPLESS — capacity pinned to the call's token count — so a router
    skewed enough to overflow the per-call training capacity still drops
    nothing at decode, deterministically, and the cached chain equals a
    dropless full forward token for token."""
    from cs336_systems_tpu.models.moe import moe_capacity, route_topk_indexed
    from cs336_systems_tpu.models.transformer import init_transformer_lm

    moe_cfg = dataclasses.replace(
        CFG, num_experts=4, moe_top_k=2, moe_capacity_factor=1.0
    )
    moe_params = init_transformer_lm(jax.random.PRNGKey(5), moe_cfg)
    # Skew the router hard toward expert 0: bias its logit row up by a
    # large constant so (nearly) every token's top-1 lands on expert 0.
    w = np.array(moe_params["blocks"]["ffn"]["router"]["weight"])
    w[:, 0, :] += 8.0
    moe_params["blocks"]["ffn"]["router"]["weight"] = jnp.asarray(w)

    # The overflow premise must hold: at the OLD per-call capacity a
    # single decode call (B=1 token... use the prefill call, T=B·P) would
    # drop. Verify with the actual router on the prompt tokens.
    prompt = [1, 2, 3, 0, 2, 1]
    from cs336_systems_tpu.models.layers import embedding, linear, rmsnorm

    x = embedding(moe_params["token_embeddings"], jnp.asarray([prompt]))
    h = rmsnorm(
        jax.tree_util.tree_map(lambda a: a[0], moe_params["blocks"])["ln1"], x
    )
    t = len(prompt)
    gates = jax.nn.softmax(
        linear(
            jax.tree_util.tree_map(lambda a: a[0], moe_params["blocks"])
            ["ffn"]["router"], h.reshape(t, -1).astype(jnp.float32),
            jnp.float32,
        ),
        axis=-1,
    )
    old_cap = moe_capacity(t, 4, 2, 1.0)
    _, pos, _, _ = route_topk_indexed(gates, 2, old_cap)
    assert bool(jnp.any(pos >= old_cap)), "skew failed to overflow old capacity"

    # Dropless contract: cached decode == dropless full-forward generate.
    kw = dict(max_new_tokens=8, temperature=1e-3, top_k=None)
    key = jax.random.PRNGKey(7)
    dropless_cfg = dataclasses.replace(
        moe_cfg, moe_capacity_factor=float(moe_cfg.num_experts)
    )  # C = k·T ≥ T: the full forward provably drops nothing either
    want = generate(moe_params, dropless_cfg, prompt, key=key, **kw)
    got = generate_kv(moe_params, moe_cfg, prompt, key=key, **kw)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))
    # and it is deterministic call to call
    again = generate_kv(moe_params, moe_cfg, prompt, key=key, **kw)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(again))


def test_generate_kv_batched_matches_single_row(params):
    """Greedy-ish batched decoding must reproduce the single-sequence path
    row by row (identical prompts, shared key, near-argmax temperature)."""
    from cs336_systems_tpu.models.decode import generate_kv_batched

    prompt = [1, 2, 3, 4]
    key = jax.random.PRNGKey(11)
    kw = dict(max_new_tokens=8, temperature=1e-3, top_k=None)
    single = generate_kv(params, CFG, prompt, key=key, **kw)
    batched = generate_kv_batched(
        params, CFG, jnp.tile(jnp.asarray([prompt], jnp.int32), (3, 1)),
        key=key, **kw,
    )
    assert batched.shape == (3, 8)
    for row in np.asarray(batched):
        np.testing.assert_array_equal(row, np.asarray(single))


def test_generate_kv_batched_eos_and_validation(params):
    from cs336_systems_tpu.models.decode import generate_kv_batched

    key = jax.random.PRNGKey(3)
    full = generate_kv_batched(
        params, CFG, jnp.asarray([[1, 2, 3]], jnp.int32), 12, key,
        temperature=0.05, top_k=8,
    )
    eos = int(full[0][4])
    rows = generate_kv_batched(
        params, CFG, jnp.asarray([[1, 2, 3]], jnp.int32), 12, key,
        temperature=0.05, top_k=8, eos_token_id=eos,
    )
    assert isinstance(rows, list) and len(rows) == 1
    assert eos not in rows[0]

    with pytest.raises(ValueError, match="batch, prompt_len"):
        generate_kv_batched(params, CFG, jnp.asarray([1, 2, 3]), 4, key)
    with pytest.raises(ValueError, match="exceeds context_length"):
        generate_kv_batched(
            params, CFG, jnp.zeros((2, 40), jnp.int32), 20, key
        )


def test_generate_kv_zero_new_tokens():
    """max_new_tokens=0 returns an empty generation (regression: the
    bucket-segmented scan concatenated an empty chunk list)."""
    from cs336_systems_tpu.models.decode import generate_kv
    from cs336_systems_tpu.models.transformer import (
        TransformerConfig,
        init_transformer_lm,
    )

    cfg = TransformerConfig(vocab_size=32, context_length=64, d_model=64,
                            num_layers=2, num_heads=4, d_ff=128)
    params = init_transformer_lm(jax.random.PRNGKey(0), cfg)
    toks = generate_kv(params, cfg, [1, 2, 3], 0, jax.random.PRNGKey(1))
    assert toks.shape == (0,)


def test_generate_kv_crosses_attend_bucket_boundary():
    """The bucket-grown attended prefix (decode._ATTEND_BUCKET segments)
    must be numerically invisible: a generation whose fill crosses a
    segment boundary must match the uncached loop token for token. Uses a
    context larger than one bucket so the scan really re-specializes
    mid-generation (prompt 200 + 100 new crosses the 256-row boundary)."""
    import dataclasses

    from cs336_systems_tpu.models import decode as decode_mod

    cfg = dataclasses.replace(CFG, context_length=512)
    params = init_transformer_lm(jax.random.PRNGKey(3), cfg)
    prompt = list(range(1, 201))
    # sanity: the segment plan really splits at the 256-row bucket
    plen, new = 200, 100
    bounds = []
    i = 0
    while i < new:
        attend = min(
            decode_mod._round_up(plen + i + 1, decode_mod._ATTEND_BUCKET),
            decode_mod._round_up(plen + new, decode_mod._ATTEND_BUCKET),
        )
        seg = min(new - i, attend - plen - i)
        bounds.append((attend, seg))
        i += seg
    assert len(bounds) == 2 and bounds[0][0] == 256 and bounds[1][0] == 512

    kw = dict(max_new_tokens=new, temperature=0.05, top_k=8)
    key = jax.random.PRNGKey(11)
    want = generate(params, cfg, prompt, key=key, **kw)
    got = generate_kv(params, cfg, prompt, key=key, **kw)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_fused_update_kernel_matches_xla_path():
    """The fused update+attend kernel (ops/decode_attention.py, interpret
    mode on CPU) must match the portable DUS + masked-softmax path: same
    attention output AND the same updated cache, at every fill-position
    class — tile-aligned and not (pos % 8), first, last, windowed, and the
    non-128-pack head dim (d_head=80 is the 2.7b config)."""
    from cs336_systems_tpu.models.decode import _attend_update_xla
    from cs336_systems_tpu.ops.decode_attention import (
        decode_attention_update,
        pack_kv,
    )

    key = jax.random.PRNGKey(5)
    for b, h, s, d, pos, window in [
        (2, 4, 64, 32, 0, None),
        (2, 4, 64, 32, 17, None),
        (2, 4, 64, 32, 63, None),
        (2, 4, 64, 32, 24, None),
        (3, 2, 128, 64, 100, 16),
        (1, 2, 64, 80, 40, None),
    ]:
        kq, kk, kv, kn1, kn2, key = jax.random.split(key, 6)
        q = jax.random.normal(kq, (b, h, 1, d))
        kvc = pack_kv(jax.random.normal(kk, (b, h, s, d)),
                      jax.random.normal(kv, (b, h, s, d)))
        k_new = jax.random.normal(kn1, (b, h, 1, d))
        v_new = jax.random.normal(kn2, (b, h, 1, d))
        want_o, want_kv = _attend_update_xla(
            q, kvc, k_new, v_new, jnp.int32(pos), window
        )
        got_o, got_kv = decode_attention_update(
            q, k_new, v_new, kvc, jnp.int32(pos), window=window
        )
        msg = f"b={b} h={h} s={s} d={d} pos={pos} window={window}"
        np.testing.assert_allclose(
            np.asarray(got_o), np.asarray(want_o), rtol=1e-5, atol=1e-5,
            err_msg=msg,
        )
        np.testing.assert_array_equal(
            np.asarray(got_kv), np.asarray(want_kv), err_msg=msg
        )


def test_fused_update_kernel_attend_len_prefix():
    """attend_len bounds the streamed prefix without changing the result
    (all attended rows < attend_len) and the write-back still lands in the
    full-size cache."""
    from cs336_systems_tpu.models.decode import _attend_update_xla
    from cs336_systems_tpu.ops.decode_attention import (
        decode_attention_update,
        pack_kv,
    )

    b, h, s, d, pos, attend = 2, 2, 128, 32, 50, 64
    key = jax.random.PRNGKey(9)
    kq, kk, kv, kn1, kn2 = jax.random.split(key, 5)
    q = jax.random.normal(kq, (b, h, 1, d))
    kvc = pack_kv(jax.random.normal(kk, (b, h, s, d)),
                  jax.random.normal(kv, (b, h, s, d)))
    k_new = jax.random.normal(kn1, (b, h, 1, d))
    v_new = jax.random.normal(kn2, (b, h, 1, d))
    want_o, want_kv = _attend_update_xla(
        q, kvc, k_new, v_new, jnp.int32(pos), None, attend
    )
    got_o, got_kv = decode_attention_update(
        q, k_new, v_new, kvc, jnp.int32(pos), attend_len=attend
    )
    np.testing.assert_allclose(np.asarray(got_o), np.asarray(want_o),
                               rtol=1e-5, atol=1e-5)
    assert got_kv.shape == kvc.shape
    np.testing.assert_array_equal(np.asarray(got_kv), np.asarray(want_kv))


def test_generate_kv_pallas_attention_matches_xla(params):
    """End-to-end generation through the Pallas decode kernel must sample
    the same tokens as the XLA masked-softmax path (same PRNG stream; the
    kernels agree to fp32 rounding, and low temperature keeps the draw
    deterministic)."""
    prompt = [5, 9, 2, 7, 1, 4]
    kw = dict(max_new_tokens=12, temperature=0.05, top_k=8)
    key = jax.random.PRNGKey(13)
    want = generate_kv(params, CFG, prompt, key=key, attn_impl="xla", **kw)
    got = generate_kv(params, CFG, prompt, key=key, attn_impl="pallas", **kw)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_unstacked_blocks_match_stacked(params):
    """decode_step over pre-unstacked per-layer block params (the scan-
    invariant layout) is the same computation as over stacked leaves."""
    from cs336_systems_tpu.models.decode import unstack_blocks

    ids = jax.random.randint(jax.random.PRNGKey(21), (2, 8), 0, CFG.vocab_size)
    logits_s, cache_s, pos = prefill(params, ids, CFG)
    unstacked = unstack_blocks(params)
    assert isinstance(unstacked["blocks"], tuple)
    assert unstack_blocks(unstacked) is unstacked  # idempotent, no re-wrap

    nxt = jnp.array([3, 4], jnp.int32)
    want, _ = decode_step(params, cache_s, pos, nxt, CFG)
    got, _ = decode_step(unstacked, cache_s, pos, nxt, CFG)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_approx_top_k_matches_exact_on_cpu(params):
    """approx_top_k draws from a SUPERSET of the exact top-k candidate set
    (approx_max_k's recall misses can only LOWER the threshold; measured
    on chip: 10/32 rows equal, the rest below). On CPU the lowering falls
    back to exact sort, so the paths must agree token for token — the
    equality here pins the plumbing; the superset property is the
    documented on-chip contract."""
    prompt = [1, 2, 3, 4]
    kw = dict(max_new_tokens=10, temperature=0.05, top_k=8)
    key = jax.random.PRNGKey(17)
    want = generate_kv(params, CFG, prompt, key=key, **kw)
    got = generate_kv(params, CFG, prompt, key=key, approx_top_k=True, **kw)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_cached_attention_impl_validation_and_vmem_fallback():
    """Unknown impl strings raise (the arg is NOT TransformerConfig.
    attn_impl); 'auto' falls back to masked-softmax when the attended
    prefix exceeds the kernel's VMEM slab plan, and the kernel itself
    refuses such prefixes rather than OOMing Mosaic."""
    from cs336_systems_tpu.models.decode import _resolve_impl
    from cs336_systems_tpu.ops import decode_attention as da

    with pytest.raises(ValueError, match="serving-kernel"):
        _resolve_impl("flash", 256, 64, 2)

    assert da.supported(4096, 64, 2)
    assert not da.supported(32768, 64, 2)
    big = jnp.zeros((1, 1, 32768, 128), jnp.bfloat16)
    one = jnp.zeros((1, 1, 1, 64), jnp.bfloat16)
    with pytest.raises(ValueError, match="VMEM slab plan"):
        da.decode_attention_update(one, one, one, big, jnp.int32(5))
    # auto on the same prefix routes to xla without error
    assert _resolve_impl("auto", 32768, 64, 2) == "xla"


def test_resolve_impl_requires_aligned_prefix():
    """'auto' must route non-8-aligned attended prefixes to xla (the
    kernel's write-back tile needs 8-row alignment) rather than letting
    the kernel raise mid-trace."""
    from cs336_systems_tpu.models.decode import _resolve_impl

    assert _resolve_impl("auto", 1020, 64, 2) == "xla"


def test_ragged_fused_kernel_matches_xla_path():
    """Per-row write positions (ragged serving) through the fused kernel:
    each batch row writes its own column and masks its own prefix —
    values AND updated cache equal the portable per-row where/masked-
    softmax path, with positions spread across different 8-row tiles,
    tile boundaries, row 0, and a windowed case."""
    from cs336_systems_tpu.models.decode import _attend_update_xla
    from cs336_systems_tpu.ops.decode_attention import (
        decode_attention_update,
        pack_kv,
    )

    key = jax.random.PRNGKey(13)
    for b, h, s, d, pos, window in [
        (4, 4, 64, 32, [0, 17, 63, 24], None),
        (3, 2, 128, 64, [100, 5, 56], 16),
        (2, 3, 64, 32, [8, 39], None),  # odd head count: group divides h
    ]:
        kq, kk, kv, kn1, kn2, key = jax.random.split(key, 6)
        q = jax.random.normal(kq, (b, h, 1, d))
        kvc = pack_kv(jax.random.normal(kk, (b, h, s, d)),
                      jax.random.normal(kv, (b, h, s, d)))
        k_new = jax.random.normal(kn1, (b, h, 1, d))
        v_new = jax.random.normal(kn2, (b, h, 1, d))
        posv = jnp.asarray(pos, jnp.int32)
        want_o, want_kv = _attend_update_xla(q, kvc, k_new, v_new, posv,
                                             window)
        got_o, got_kv = decode_attention_update(
            q, k_new, v_new, kvc, posv, window=window
        )
        msg = f"b={b} h={h} s={s} d={d} pos={pos} window={window}"
        np.testing.assert_allclose(
            np.asarray(got_o), np.asarray(want_o), rtol=1e-5, atol=1e-5,
            err_msg=msg,
        )
        np.testing.assert_array_equal(
            np.asarray(got_kv), np.asarray(want_kv), err_msg=msg
        )


def test_ragged_generate_matches_per_row_single_calls(params):
    """THE ragged-serving contract: a batch with an 8x prompt-length
    spread generates, row for row, exactly what each row's own single-row
    call generates (row-keyed streams + per-row positions make the batch
    layout invisible) — through BOTH cached-attention impls. Pad content
    beyond each row's length must be ignorable."""
    from cs336_systems_tpu.models.decode import generate_kv_batched

    rng = np.random.default_rng(2)
    lens = [2, 16, 4, 8]  # 8x spread
    pmax = max(lens)
    prompts = np.full((len(lens), pmax), 1, np.int32)
    rows = [rng.integers(0, CFG.vocab_size, n).astype(np.int32) for n in lens]
    for i, r in enumerate(rows):
        prompts[i, : len(r)] = r
    key = jax.random.PRNGKey(21)
    kw = dict(temperature=0.9, top_k=8, row_keyed=True)

    for impl in ("xla", "pallas"):
        got = np.asarray(generate_kv_batched(
            params, CFG, prompts, 10, key, prompt_lens=np.asarray(lens),
            attn_impl=impl, **kw,
        ))
        for i, r in enumerate(rows):
            want = np.asarray(generate_kv_batched(
                params, CFG, r[None], 10, key, row_key_offset=i,
                attn_impl=impl, **kw,
            ))[0]
            np.testing.assert_array_equal(got[i], want,
                                          err_msg=f"impl={impl} row {i}")

    # junk pad tokens cannot leak into any row's generation
    prompts2 = prompts.copy()
    for i, n in enumerate(lens):
        prompts2[i, n:] = rng.integers(0, CFG.vocab_size, pmax - n)
    got2 = np.asarray(generate_kv_batched(
        params, CFG, prompts2, 10, key, prompt_lens=np.asarray(lens), **kw,
    ))
    base = np.asarray(generate_kv_batched(
        params, CFG, prompts, 10, key, prompt_lens=np.asarray(lens), **kw,
    ))
    np.testing.assert_array_equal(got2, base)


def test_ragged_generate_windowed_and_moe():
    """Ragged decoding composes with sliding-window attention and with
    MoE (dropless serving routing): each still matches its per-row
    single-row calls."""
    from cs336_systems_tpu.models.decode import generate_kv_batched

    rng = np.random.default_rng(3)
    lens = [3, 12]
    prompts = np.full((2, 12), 1, np.int32)
    rows = [rng.integers(0, CFG.vocab_size, n).astype(np.int32) for n in lens]
    for i, r in enumerate(rows):
        prompts[i, : len(r)] = r
    key = jax.random.PRNGKey(22)
    kw = dict(temperature=0.9, top_k=8, row_keyed=True)

    for cfg in (
        dataclasses.replace(CFG, attn_window=8),
        dataclasses.replace(CFG, num_experts=4, moe_top_k=2),
    ):
        p = init_transformer_lm(jax.random.PRNGKey(7), cfg)
        got = np.asarray(generate_kv_batched(
            p, cfg, prompts, 8, key, prompt_lens=np.asarray(lens), **kw,
        ))
        for i, r in enumerate(rows):
            want = np.asarray(generate_kv_batched(
                p, cfg, r[None], 8, key, row_key_offset=i, **kw,
            ))[0]
            np.testing.assert_array_equal(
                got[i], want,
                err_msg=f"{'window' if cfg.attn_window else 'moe'} row {i}")


def test_ragged_eos_and_validation(params):
    """Per-row EOS truncation applies to ragged batches, and a wrong-shape
    prompt_lens is rejected."""
    from cs336_systems_tpu.models.decode import generate_kv_batched

    prompts = np.asarray([[1, 2, 3, 1], [4, 5, 1, 1]], np.int32)
    lens = np.asarray([4, 2])
    key = jax.random.PRNGKey(23)
    full = generate_kv_batched(params, CFG, prompts, 10, key,
                               temperature=0.05, top_k=8, row_keyed=True,
                               prompt_lens=lens)
    eos = int(np.asarray(full)[1][3])
    rows = generate_kv_batched(params, CFG, prompts, 10, key,
                               temperature=0.05, top_k=8, row_keyed=True,
                               prompt_lens=lens, eos_token_id=eos)
    assert isinstance(rows, list) and len(rows) == 2
    assert all(eos not in np.asarray(r) for r in rows)
    assert len(rows[1]) <= 3

    with pytest.raises(ValueError, match="prompt_lens"):
        generate_kv_batched(params, CFG, prompts, 4, key,
                            prompt_lens=np.asarray([4, 2, 2]))


def test_ragged_lens_range_rejected(params):
    """Out-of-range prompt_lens would produce plausible-looking garbage
    (wrapped logit gather at 0; never-written cache reads beyond the
    padded width) — both entry points must reject them."""
    from cs336_systems_tpu.models.decode import generate_kv_batched

    prompts = np.ones((2, 6), np.int32)
    key = jax.random.PRNGKey(0)
    for bad in ([0, 4], [3, 7]):
        with pytest.raises(ValueError, match="prompt_lens entries"):
            generate_kv_batched(params, CFG, prompts, 4, key,
                                prompt_lens=np.asarray(bad))
    with pytest.raises(ValueError, match="integers"):
        generate_kv_batched(params, CFG, prompts, 4, key,
                            prompt_lens=np.asarray([2.7, 3.9]))
    with pytest.raises(ValueError, match="row_key_offset"):
        generate_kv_batched(params, CFG, prompts, 4, key, row_key_offset=3)
