"""Multi-host readiness: ``init_distributed`` + ``make_mesh`` over
process-spanning devices, with the unmodified DP train step.

The reference fakes multi-node with single-host ``mp.spawn`` + Gloo
(tests/common.py:71-88, naive_ddp.py:35-51). The analogue here is two REAL
OS processes rendezvousing through ``jax.distributed`` (the same mechanism
a TPU pod uses over DCN; on CPU the collectives ride Gloo), each owning 2
virtual devices of a 4-device global mesh. The invariant: the same
``make_mesh``/train-step code, unchanged, produces the same training
result at every process topology — (2 procs × 2 devs) must equal the
(1 proc × 4 devs) run that the rest of the suite uses.
"""

import os
import re
import socket
import subprocess
import sys

import numpy as np
import pytest


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _worker_env(n_local: int) -> dict:
    env = dict(os.environ)
    env.update(
        JAX_PLATFORMS="cpu",
        XLA_FLAGS=f"--xla_force_host_platform_device_count={n_local}",
        PALLAS_AXON_POOL_IPS="",
        PYTHONPATH=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    )
    return env


_RESULT = re.compile(
    r"RESULT pid=(\d+) world=(\d+) loss=([\d.]+) checksum=([\d.]+)"
)


def _launch(pid: int, nproc: int, port: int, n_local: int):
    return subprocess.Popen(
        [
            sys.executable,
            os.path.join(os.path.dirname(os.path.abspath(__file__)), "mh_worker.py"),
            str(pid), str(nproc), f"127.0.0.1:{port}",
        ],
        env=_worker_env(n_local),
        stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT,
        text=True,
    )


_PROBE_SRC = """
import jax, jax.numpy as jnp
import sys
jax.distributed.initialize("127.0.0.1:{port}", 2, int(sys.argv[1]))
out = jax.pmap(lambda x: jax.lax.psum(x, "i"), axis_name="i")(
    jnp.ones((jax.local_device_count(),)))
print("PROBE_OK", float(out.sum()))
"""


def _cross_process_collectives_supported() -> bool:
    """Probe (once per session) whether this jaxlib's CPU backend runs
    cross-process collectives: two 1-device processes rendezvous and
    psum. The current jaxlib aborts with 'Multiprocess computations
    aren't implemented on the CPU backend' — an environmental limit, not
    a code defect — and a hard-coded xfail would silently keep skipping
    after a jaxlib upgrade fixes it; this probe flips the test live the
    moment the capability appears."""
    if _PROBE_RESULT:
        return _PROBE_RESULT[0]
    port = _free_port()
    procs = [
        subprocess.Popen(
            [sys.executable, "-c", _PROBE_SRC.format(port=port), str(pid)],
            env=_worker_env(1), stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT, text=True)
        for pid in range(2)
    ]
    ok = True
    for p in procs:
        try:
            out = p.communicate(timeout=120)[0]
        except subprocess.TimeoutExpired:
            p.kill()
            p.communicate()
            ok = False
            continue
        ok = ok and p.returncode == 0 and "PROBE_OK" in out
    _PROBE_RESULT.append(ok)
    return ok


_PROBE_RESULT: list = []


def test_two_process_dp_matches_single_process():
    if not _cross_process_collectives_supported():
        pytest.skip(
            "this jaxlib's CPU backend rejects cross-process collectives "
            "(probe: 2-process jax.distributed psum failed) — "
            "environmental, not a code defect; see ROADMAP.md")
    port = _free_port()
    # 2 processes x 2 local devices -> a 4-device global dp mesh
    procs = [_launch(pid, 2, port, n_local=2) for pid in range(2)]
    outs = [p.communicate(timeout=280)[0] for p in procs]
    for p, out in zip(procs, outs):
        assert p.returncode == 0, out

    results = [_RESULT.search(out) for out in outs]
    assert all(results), outs
    worlds = {int(m.group(2)) for m in results}
    losses = {m.group(3) for m in results}
    sums = {m.group(4) for m in results}
    assert worlds == {4}
    # replicated training state: every process reports identical numbers
    assert len(losses) == 1 and len(sums) == 1, outs

    # the same worker on ONE process with 4 local devices: same mesh shape,
    # same data stream -> the training result must match across topologies
    single = _launch(0, 1, _free_port(), n_local=4)
    out_single = single.communicate(timeout=280)[0]
    assert single.returncode == 0, out_single
    m = _RESULT.search(out_single)
    assert m and int(m.group(2)) == 4, out_single
    np.testing.assert_allclose(
        float(m.group(3)), float(next(iter(losses))), rtol=1e-6
    )
    np.testing.assert_allclose(
        float(m.group(4)), float(next(iter(sums))), rtol=1e-6
    )
