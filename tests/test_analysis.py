"""graft-lint tests: the linter must (a) pass the real repo clean and
(b) FLAG each seeded violation with its rule-specific diagnostic.

The mutation tests re-introduce, one at a time, the exact regressions the
rules encode — donation switched off, raw ``lax.cumsum`` routing, a
barrier-stripped unrolled MoE stack, a VMEM budget edit that shifts the
Pallas group picker — and assert the matching rule fires. This is the
same oracle discipline as the parallelism tests: the checker is tested
against known-bad programs, not assumed correct.
"""

import jax
import jax.numpy as jnp
import pytest

from cs336_systems_tpu.analysis import contracts, jaxpr_scan, registry, vmem
from cs336_systems_tpu.analysis.lint import lint_step, run
from cs336_systems_tpu.ops import flash_attention as fa


def _rules(violations):
    return {v.rule for v in violations}


# --- the real repo is clean -------------------------------------------------


def test_full_lint_clean():
    """Every registered step + the VMEM facts pass on the current tree.
    This is the gate scripts/run_tests_and_package.sh runs."""
    results, violations, errors = run()
    assert not errors, [v.message for v in errors]
    assert not violations, [v.message for v in violations]
    assert len(results) == len(registry.STEPS) + 1  # + vmem


# --- collective contracts ---------------------------------------------------


def test_collective_contract_flags_extra_psum():
    from cs336_systems_tpu.parallel.mesh import make_mesh

    mesh = make_mesh({"dp": 8})

    def fn(x):
        return jax.lax.pmean(x, "dp")

    sm = jax.shard_map(fn, mesh=mesh, in_specs=(jax.sharding.PartitionSpec("dp"),),
                       out_specs=jax.sharding.PartitionSpec("dp"))
    jaxpr = jax.make_jaxpr(sm)(jax.ShapeDtypeStruct((8, 4), jnp.float32))
    # pmean traces to psum; a zero-collective contract must flag it
    vs = contracts.check_collectives("t", jaxpr, {})
    assert _rules(vs) == {"collective-contract"}
    assert "psum: 1 issued, contract says 0" in vs[0].message
    # and the correct count passes
    assert contracts.check_collectives("t", jaxpr, {"psum": 1}) == []


def test_collective_counts_are_static_sites():
    """A collective inside a lax.scan body counts ONCE (the granularity
    every declared contract uses)."""
    from cs336_systems_tpu.parallel.mesh import make_mesh

    mesh = make_mesh({"dp": 8})

    def fn(x):
        def body(c, _):
            return jax.lax.psum(c, "dp"), None

        out, _ = jax.lax.scan(body, x, None, length=5)
        return out

    sm = jax.shard_map(fn, mesh=mesh,
                       in_specs=(jax.sharding.PartitionSpec(),),
                       out_specs=jax.sharding.PartitionSpec())
    jaxpr = jax.make_jaxpr(sm)(jax.ShapeDtypeStruct((4,), jnp.float32))
    assert jaxpr_scan.count_collectives(jaxpr)["psum"] == 1


# --- donation ---------------------------------------------------------------


def test_donation_mutation_flagged():
    """make_train_step(donate=False) must trip the donation rule that the
    donated build passes."""
    from cs336_systems_tpu.train import make_train_step

    cfg = registry._tiny_cfg()
    state = registry._abstract_state(cfg)
    x, y = registry._batch(cfg)
    n = registry._n_leaves(state)

    good = jaxpr_scan.lowered_text(
        make_train_step(cfg, registry._hp(), donate=True), *state, x, y)
    assert contracts.check_donation("t", good, n) == []

    bad = jaxpr_scan.lowered_text(
        make_train_step(cfg, registry._hp(), donate=False), *state, x, y)
    vs = contracts.check_donation("t", bad, n)
    assert _rules(vs) == {"donation"}
    assert "donate_argnums is not taking effect" in vs[0].message


# --- routing cumsum ---------------------------------------------------------


def test_raw_cumsum_routing_flagged():
    """The 2.1 ms hazard: lax.cumsum over a [16384, 8] routing tensor.
    models/moe._prefix_count exists so this never appears in a step."""

    def bad_routing(mask):
        return jnp.cumsum(mask, axis=0)  # positions via prefix-count: BAD

    jaxpr = jax.make_jaxpr(bad_routing)(
        jax.ShapeDtypeStruct((16384, 8), jnp.int32))
    vs = contracts.check_no_big_cumsum("t", jaxpr)
    assert _rules(vs) == {"routing-cumsum"}
    assert "16384" in vs[0].message and "_prefix_count" in vs[0].message


def test_small_cumsum_not_flagged():
    """The [E+1] expert-offset cumsum inside tile_maps is harmless and
    must stay allowed."""
    jaxpr = jax.make_jaxpr(lambda m: jnp.cumsum(m))(
        jax.ShapeDtypeStruct((9,), jnp.int32))
    assert contracts.check_no_big_cumsum("t", jaxpr) == []


def test_registered_moe_steps_use_prefix_count():
    """The real sorted MoE step carries NO long cumsum — the whole point
    of _prefix_count."""
    traced = registry.STEPS[2].build()  # train_moe_sorted
    assert contracts.check_no_big_cumsum("moe", traced.jaxpr) == []


# --- MoE barriers -----------------------------------------------------------


def test_barrier_stripped_moe_flagged(monkeypatch):
    """Stripping the per-layer optimization_barrier (the 47.9 ms/step
    regression) must trip the moe-barrier rule on the SAME build that
    passes un-stripped."""
    monkeypatch.setattr(jax.lax, "optimization_barrier", lambda x: x)
    traced = registry.STEPS[2].build()  # train_moe_sorted
    vs = lint_step("train_moe_sorted", traced)
    assert _rules(vs) == {"moe-barrier"}
    assert "47.9 ms/step" in vs[0].message


# --- phase scopes (tracekit instrumentation) --------------------------------


def test_phase_scope_rule_direct():
    """The rule fires on a scope-less program and passes a scoped one —
    including the ``transpose(`` marker AD stamps on backward ops."""

    def plain(x):
        return jnp.sum(x * 2)

    jaxpr = jax.make_jaxpr(plain)(jax.ShapeDtypeStruct((4,), jnp.float32))
    vs = contracts.check_phase_scopes("t", jaxpr, ("attn",))
    assert _rules(vs) == {"phase-scope"}
    assert "'attn'" in vs[0].message and "other" in vs[0].message

    def scoped(x):
        with jax.named_scope("attn"):
            return jnp.sum(x * 2)

    jaxpr = jax.make_jaxpr(scoped)(jax.ShapeDtypeStruct((4,), jnp.float32))
    assert contracts.check_phase_scopes("t", jaxpr, ("attn",)) == []
    # AD's transpose(jvp(...)) stack satisfies the bwd marker w/o annotation
    jaxpr = jax.make_jaxpr(jax.grad(scoped))(
        jax.ShapeDtypeStruct((4,), jnp.float32))
    assert contracts.check_phase_scopes("t", jaxpr,
                                        ("attn", "transpose(")) == []


def test_phase_scope_mutation_flagged(monkeypatch):
    """Stripping train.make_update_fn's annotate("optimizer") scope — the
    exact rot the rule exists for — must trip phase-scope on the same
    train_single build that passes annotated."""
    import contextlib

    from cs336_systems_tpu import train as train_mod

    monkeypatch.setattr(train_mod, "annotate",
                        lambda name: contextlib.nullcontext())
    spec = next(s for s in registry.STEPS if s.name == "train_single")
    vs = lint_step("train_single", spec.build())
    assert "phase-scope" in _rules(vs)
    assert "optimizer" in " ".join(v.message for v in vs)


# --- fp32 big dots ----------------------------------------------------------


def test_fp32_big_dot_flagged():
    def bad(a, b):
        return a @ b

    jaxpr = jax.make_jaxpr(bad)(
        jax.ShapeDtypeStruct((512, 512), jnp.float32),
        jax.ShapeDtypeStruct((512, 512), jnp.float32))
    vs = contracts.check_no_big_fp32_dots("t", jaxpr)
    assert _rules(vs) == {"fp32-big-dot"}
    assert "preferred_element_type" in vs[0].message


def test_bf16_big_dot_and_small_fp32_dot_pass():
    jaxpr = jax.make_jaxpr(lambda a, b: a @ b)(
        jax.ShapeDtypeStruct((512, 512), jnp.bfloat16),
        jax.ShapeDtypeStruct((512, 512), jnp.bfloat16))
    assert contracts.check_no_big_fp32_dots("t", jaxpr) == []
    # the fp32 router matmul shape ([T, D] x [D, E], E tiny) stays legal
    jaxpr = jax.make_jaxpr(lambda a, b: a @ b)(
        jax.ShapeDtypeStruct((16384, 512), jnp.float32),
        jax.ShapeDtypeStruct((512, 8), jnp.float32))
    assert contracts.check_no_big_fp32_dots("t", jaxpr) == []


# --- gmm fused backward -----------------------------------------------------


def _trace_w13_bwd(bwd_fn):
    """Trace a w13-backward implementation at the registry's headline-like
    geometry (the shapes where the fused plan subdivides the row tile)."""
    from cs336_systems_tpu.ops import grouped_matmul as gm

    bm, e, n, k = 256, 8, 3072, 768
    m = e * bm
    bf16 = jnp.bfloat16
    x = jax.ShapeDtypeStruct((m, k), bf16)
    w = jax.ShapeDtypeStruct((e, n, k), bf16)
    rows = jax.ShapeDtypeStruct((m, n), bf16)
    ti = jax.ShapeDtypeStruct((m // bm,), jnp.int32)
    ve = jax.ShapeDtypeStruct((e,), jnp.int32)

    def fn(x, w1, w3, h, g, te, first, visited, dp):
        res = (x, w1, w3, h, g, te, first, visited)
        return bwd_fn(bm, True, res, dp)[:3]

    return jax.make_jaxpr(fn)(x, w, w, rows, rows, ti, ti, ve, rows)


def test_gmm_fused_bwd_contract_clean():
    """The shipped fused backward is <= 2 pallas_calls with the SiLU grads
    in-register — the registered gmm_fused_bwd step must lint clean."""
    from cs336_systems_tpu.ops import grouped_matmul as gm

    jaxpr = _trace_w13_bwd(gm._gmm13_bwd)
    assert contracts.check_gmm_fused_bwd("t", jaxpr) == []
    assert jaxpr_scan.count_prim(jaxpr, "pallas_call") == 2


def test_gmm_unfused_bwd_flagged():
    """The pre-round-6 five-pass chain (the retained fallback) is the
    known-bad program: 4 pallas_calls AND a host-program logistic — BOTH
    diagnostics must fire."""
    from cs336_systems_tpu.ops import grouped_matmul as gm

    jaxpr = _trace_w13_bwd(gm._gmm13_bwd_unfused)
    vs = contracts.check_gmm_fused_bwd("t", jaxpr)
    assert _rules(vs) == {"gmm-fused-bwd"}
    msgs = " ".join(v.message for v in vs)
    assert "pallas_calls" in msgs and "logistic" in msgs
    assert len(vs) == 2


def test_gmm_fused_bwd_budget_edit_falls_back_and_is_flagged(monkeypatch):
    """Starving the fused-bwd budget makes the planner fall back to the
    unfused chain (correctness preserved) — and the contract catches the
    silent perf regression, plus the pinned-picker vmem check."""
    from cs336_systems_tpu.ops import grouped_matmul as gm

    monkeypatch.setattr(gm, "GMM_BWD_VMEM_BUDGET", 64 * 1024)
    assert gm._fused_bwd_plan(256, 3072, 768, 2) is None
    jaxpr = _trace_w13_bwd(gm._gmm13_bwd)
    assert "gmm-fused-bwd" in _rules(contracts.check_gmm_fused_bwd("t", jaxpr))
    assert {"gmm-fused-dx-picked-fits", "gmm-fused-dw-picked-fits",
            "gmm-fused-bwd-plans-everywhere"} <= {
                v.where for v in vmem.run_vmem_checks()}


def test_gmm_fused_dx_full_bm_rejected():
    """The estimator must reject the full-bm=256 dx row tile the VMEM
    arithmetic rules out (the reason _subdivide_tiles exists)."""
    from cs336_systems_tpu.ops import grouped_matmul as gm

    assert gm.gmm_fused_dx_vmem_bytes(256, 256, 3072, 2) > vmem.SCOPED_VMEM_LIMIT
    bm_b, _ = gm._pick_dx_tiles(256, 3072, 768, 2)
    assert bm_b < 256


# --- VMEM budget facts ------------------------------------------------------


def test_vmem_facts_hold():
    assert vmem.run_vmem_checks() == []


def test_vmem_budget_edit_flagged(monkeypatch):
    """Raising the fwd budget would shift the group picker's shipped
    decisions (every BASELINE.md number was measured at them) — the
    pinned-picker check must catch the drift."""
    monkeypatch.setattr(fa, "FWD_VMEM_BUDGET", 32 * 1024 * 1024)
    vs = vmem.run_vmem_checks()
    assert "flash-fwd-picker-pinned" in {v.where for v in vs}


def test_vmem_over_budget_tile_detected():
    """The estimators must reject the configs the chip rejected."""
    assert fa.fwd_vmem_bytes(2048, 2048, 64, 2, g=1,
                             has_rope=True) > vmem.SCOPED_VMEM_LIMIT
    assert fa.tiled_bwd_vmem_bytes(1024, 1024, 64, 2, g=1,
                                   has_rope=True) > vmem.SCOPED_VMEM_LIMIT
    assert fa.fused_bwd_vmem_bytes(1024, 64, 4) > vmem.SCOPED_VMEM_LIMIT


def test_mosaic_crash_matrix_enforced():
    """fp32 × narrow head × G=4 is the on-chip compiler crash; the picker
    may never choose it."""
    assert fa.fwd_group_cap(4, 16) == 2
    assert fa._pick_group(8, 128, 128, 16, 4) <= 2


# --- HBM budget rule (memkit-backed) ----------------------------------------


def test_hbm_budget_declared_families_are_registered():
    """Every budgeted family must be a real registry step — a typo'd key
    would silently never be checked by lint_step."""
    from cs336_systems_tpu.analysis import memkit

    assert set(registry.HBM_BUDGET_BYTES) <= set(memkit.family_names())
    assert all(b > 0 for b in registry.HBM_BUDGET_BYTES.values())


def test_hbm_budget_rule_clean_then_mutated():
    """Same mutation discipline as the other rules: the shipped budget
    passes on the current tree, and an (artificially) starved budget for
    the SAME family fires with the peak/ratio diagnostic."""
    assert contracts.check_hbm_budget("train_single",
                                      registry.HBM_BUDGET_BYTES["train_single"]) == []
    vs = contracts.check_hbm_budget("train_single", 1 << 20)
    assert _rules(vs) == {"hbm-budget"}
    assert "exceeds" in vs[0].message and "peak" in vs[0].message


def test_hbm_budget_rule_survives_analysis_failure():
    """A family memkit can't lower must surface as a violation, not an
    exception that kills the whole lint run."""
    vs = contracts.check_hbm_budget("not_a_registered_family", 1 << 30)
    assert _rules(vs) == {"hbm-budget"}
    assert "failed to analyze" in vs[0].message


# --- grad-reduction ---------------------------------------------------------
#
# Mutation discipline for the rule that pins the a2a/sp parity root cause
# (gradients inside shard_map are LOCAL under this jax's forced
# check_rep=False — parallel/sp.py, parallel/ep.py): each known-bad
# gradient-reduction shape must fire, the correct shape must pass.


def _grad_sync_jaxpr(body, mesh_axes=None):
    from jax.sharding import PartitionSpec as P

    from cs336_systems_tpu.parallel.mesh import make_mesh

    mesh = make_mesh(mesh_axes or {"dp": 8})
    sm = jax.shard_map(body, mesh=mesh, in_specs=(P("dp"),), out_specs=P())
    return jax.make_jaxpr(sm)(jax.ShapeDtypeStruct((8, 4), jnp.float32))


_GR_CONTRACT = {"axes": ("dp",), "count": 1}


def test_grad_reduction_clean_and_missing():
    from cs336_systems_tpu.utils.profiling import annotate

    def good(g):
        with annotate("grad_sync"):
            return jax.lax.pmean(g, "dp")  # psum + div: mean-normalized

    assert contracts.check_grad_reduction(
        "t", _grad_sync_jaxpr(good), _GR_CONTRACT) == []

    def missing(g):
        return g  # the historical defect: each device keeps its local grad

    vs = contracts.check_grad_reduction(
        "t", _grad_sync_jaxpr(missing), _GR_CONTRACT)
    assert _rules(vs) == {"grad-reduction"}
    assert "missing their reduction" in vs[0].message
    assert "LOCAL" in vs[0].message


def test_grad_reduction_double_psum_flagged():
    from cs336_systems_tpu.utils.profiling import annotate

    def double(g):
        with annotate("grad_sync"):
            return jax.lax.psum(jax.lax.pmean(g, "dp"), "dp")

    vs = contracts.check_grad_reduction(
        "t", _grad_sync_jaxpr(double), _GR_CONTRACT)
    assert "grad-reduction" in _rules(vs)
    assert any("MORE than once" in v.message for v in vs)


def test_grad_reduction_sum_without_mean_flagged():
    from cs336_systems_tpu.utils.profiling import annotate

    def summed(g):
        with annotate("grad_sync"):
            return jax.lax.psum(g, "dp")  # right count, W x scale

    vs = contracts.check_grad_reduction(
        "t", _grad_sync_jaxpr(summed), _GR_CONTRACT)
    assert _rules(vs) == {"grad-reduction"}
    assert "no div/mul consumer" in vs[0].message


def test_grad_reduction_wrong_axis_flagged():
    from cs336_systems_tpu.utils.profiling import annotate

    def wrong_axis(g):
        with annotate("grad_sync"):
            return jax.lax.pmean(g, ("dp", "tp"))

    jaxpr = _grad_sync_jaxpr(wrong_axis, {"dp": 4, "tp": 2})
    vs = contracts.check_grad_reduction("t", jaxpr, _GR_CONTRACT)
    assert "grad-reduction" in _rules(vs)
    assert any("non-data axis" in v.message for v in vs)


def test_grad_reduction_dropped_sync_in_real_dp_step_flagged(monkeypatch):
    """End-to-end: strip dp.sync_grads from the registered dp family (the
    exact sp/ep-a2a defect shape) and BOTH the grad-reduction rule and the
    collective contract must fire on the same build that passes intact."""
    from cs336_systems_tpu.parallel import dp

    monkeypatch.setattr(dp, "sync_grads", lambda grads, *a, **k: grads)
    spec = next(s for s in registry.STEPS if s.name == "train_dp_bucketed")
    vs = lint_step("train_dp_bucketed", spec.build())
    assert "grad-reduction" in _rules(vs)
    assert any("gradsan" in v.message for v in vs
               if v.rule == "grad-reduction")


def test_explicit_sync_families_declare_grad_reduction():
    """Every explicit-sync training family's contract carries the
    grad_reduction key (GSPMD families are exempt — XLA owns their
    reduction), so the rule cannot silently rot out of the registry."""
    from cs336_systems_tpu.parallel import dp, ep, sp
    from cs336_systems_tpu.parallel.mesh import make_mesh

    params = {"w": jnp.zeros((4, 4))}
    assert "grad_reduction" in dp.lint_contract(params)
    assert "grad_reduction" in ep.lint_contract(registry._moe_cfg())
    mesh = make_mesh({"dp": 2, "sp": 4})
    assert "grad_reduction" in sp.lint_contract(
        params, registry._tiny_cfg(), mesh)


# --- no-materialized-logits -------------------------------------------------


def test_no_materialized_logits_mutation_flagged():
    """Disable chunking (``ce_chunk_size=0`` — the legacy full-logits CE)
    on the exact train_single build shape and the rule must fire: the
    [B, S, V] logits live in the lm_head/loss scopes in fwd AND bwd. The
    default chunked build of the same shape passes (test_full_lint_clean
    covers every registered family)."""
    from cs336_systems_tpu.train import make_train_step

    cfg = registry._tiny_cfg(ce_chunk_size=0)
    state = registry._abstract_state(cfg)
    x, y = registry._batch(cfg)
    jaxpr = jax.make_jaxpr(make_train_step(cfg, registry._hp()))(*state, x, y)
    vs = contracts.check_no_materialized_logits(
        "train_single[ce=0]", jaxpr, registry._logits_bound(cfg))
    assert _rules(vs) == {"no-materialized-logits"}
    assert "ce_chunk_size=0" in vs[0].message


def test_no_materialized_logits_scope_gated():
    """The rule keys on the lm_head/loss named_scopes, so the tiny-config
    shape collision (d_ff == vocab_size == 64 in the registry configs)
    cannot flag FFN activations; neither does an ``lm_loss`` scope leak
    a bare ``loss`` word-boundary match."""
    bound = {"vocab": 64, "max_rows": 16}

    def ffn_like(x, w):
        with jax.named_scope("ffn"):
            a = x @ w  # [8, 64, 64]: vocab-shaped but NOT loss-scoped
        with jax.named_scope("lm_loss"):
            b = a + 1.0  # underscore = word char: \bloss\b must not match
        return b

    x = jax.ShapeDtypeStruct((8, 64, 32), jnp.float32)
    w = jax.ShapeDtypeStruct((32, 64), jnp.float32)
    jaxpr = jax.make_jaxpr(ffn_like)(x, w)
    assert contracts.check_no_materialized_logits("t", jaxpr, bound) == []

    def loss_like(x, w):
        with jax.named_scope("loss"):
            return x @ w

    jaxpr = jax.make_jaxpr(loss_like)(x, w)
    vs = contracts.check_no_materialized_logits("t", jaxpr, bound)
    assert _rules(vs) == {"no-materialized-logits"}


def test_no_materialized_logits_chunk_transients_pass():
    """The fused path's per-chunk [B, chunk, V] transients sit exactly AT
    the bound (max_rows = auto_chunk(S)), so the rule's strict inequality
    admits them — directly on the fused-CE VJP jaxpr."""
    from cs336_systems_tpu.ops.fused_ce import (
        auto_chunk, fused_linear_cross_entropy)

    b, s, d, v = 2, 64, 16, 64

    def loss_fn(h, w, t):
        return fused_linear_cross_entropy(h, w, t)

    h = jax.ShapeDtypeStruct((b, s, d), jnp.float32)
    w = jax.ShapeDtypeStruct((v, d), jnp.float32)
    t = jax.ShapeDtypeStruct((b, s), jnp.int32)
    jaxpr = jax.make_jaxpr(jax.grad(loss_fn, argnums=(0, 1)))(h, w, t)
    bound = {"vocab": v, "max_rows": auto_chunk(s)}
    assert contracts.check_no_materialized_logits("t", jaxpr, bound) == []


def test_all_training_families_declare_logits_bound():
    """Every registered training family must carry the contract key, so
    the rule cannot silently rot out of the registry."""
    for spec in registry.STEPS:
        if not spec.name.startswith("train"):
            continue
        traced = spec.build()
        assert "logits_bound" in traced.contract, spec.name
        assert traced.contract["logits_bound"]["max_rows"] >= 1


# --- exit codes -------------------------------------------------------------


def test_lint_build_error_exits_2(monkeypatch, capsys):
    """A registered step that fails to build must drive exit status 2 (a
    broken registration is a finding, distinct from contract violations'
    exit 1) — the run_tests_and_package.sh gate relies on this."""
    import json as json_mod

    from cs336_systems_tpu.analysis import lint as lint_mod

    def boom():
        raise RuntimeError("kaboom")

    monkeypatch.setattr(registry, "STEPS",
                        (registry.StepSpec("boom_step", boom),))
    rc = lint_mod.main(["--only", "boom", "--json"])
    assert rc == 2
    rep = json_mod.loads(capsys.readouterr().out)
    assert not rep["clean"]
    assert rep["violations"][0]["rule"] == "build-error"
    assert "kaboom" in rep["violations"][0]["message"]
