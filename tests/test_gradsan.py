"""gradsan oracle tests: the sanitizer must (a) report the current tree
clean on its families and (b) LOCALIZE each seeded defect at the exact
(stage, leaf) where it enters the pipeline, with every upstream stage
still clean — the property that makes the tool a bisector rather than a
pass/fail bit.

Same mutation discipline as tests/test_analysis.py: each --mutate seam
re-injects a known defect class — the dropped grad sync that WAS the
a2a/sp parity regression (diverges at ``grads`` while ``loss`` matches),
a double reduction (also ``grads``), and a sharded-side optimizer skew
(every gradient stage clean, first divergence at ``adamw_delta``).
"""

import json

import pytest

from cs336_systems_tpu.analysis import gradsan
from cs336_systems_tpu.analysis.gradsan_cli import main as cli_main

GRAD_STAGE_NAMES = list(gradsan.GRAD_STAGES)


def _stage(rep, name):
    return next(s for s in rep["stages"] if s["stage"] == name)


def test_clean_self_diff_exits_0(capsys):
    rc = cli_main(["--step", "train_single", "--json"])
    assert rc == 0
    rep = json.loads(capsys.readouterr().out)
    assert rep["clean"] and rep["first_divergence"] is None
    # self-diff of an identical program is bit-equal, not merely close
    assert all(s["max_ulp"] == 0 for s in rep["stages"])


def test_dropped_grad_sync_localizes_at_grads():
    """The historical defect: local per-device gradients. The forward
    loss matches (its pmean is separate), so the first divergence must
    land exactly at the ``grads`` stage with a concrete leaf name."""
    rep = gradsan.run_family("train_dp_bucketed", mutate="drop-grad-sync")
    assert not rep["clean"]
    first = rep["first_divergence"]
    assert first["stage"] == "grads"
    assert first["leaf"]  # a real param-tree path, not a scalar
    assert first["n_bad"] > 0
    assert _stage(rep, "loss")["clean"]


def test_double_psum_localizes_at_grads():
    rep = gradsan.run_family("train_dp_naive", mutate="double-psum")
    assert not rep["clean"]
    assert rep["first_divergence"]["stage"] == "grads"
    assert _stage(rep, "loss")["clean"]


def test_drop_lse_correction_localizes_at_loss():
    """Break the chunked CE's cross-vocab-shard max correction
    (ops/fused_ce._shard_max_correction -> identity): each tp shard mixes
    shard-local max offsets into the psum'd sum-exp, so the sharded loss
    itself is wrong — the FIRST stage diverges, unlike the grad-sync
    defects whose forward loss matches."""
    rep = gradsan.run_family("train_tp", mutate="drop-lse-correction")
    assert not rep["clean"]
    assert rep["first_divergence"]["stage"] == "loss"


def test_drop_lse_correction_only_hits_vocab_sharded_families():
    """The seam lives in the sharded CE island; a family whose config
    never sets ``ce_vocab_axis`` (the dp explicit-sync step runs the
    single-shard fused CE) must stay bit-clean under the mutation —
    the same discipline that keeps drop-grad-sync from implicating
    GSPMD families."""
    rep = gradsan.run_family("train_dp_bucketed",
                             mutate="drop-lse-correction")
    assert rep["clean"]


def test_wrong_stage_skew_localizes_at_adamw_delta():
    """A defect past the gradient pipeline must NOT implicate it: every
    grad-level stage (and the grad-only moments) stays clean and the
    first divergence is the AdamW delta."""
    rep = gradsan.run_family("train_single", mutate="optimizer-lr")
    assert not rep["clean"]
    assert rep["first_divergence"]["stage"] == "adamw_delta"
    for name in GRAD_STAGE_NAMES:
        assert _stage(rep, name)["clean"], name
    # m/v depend on grads only, not lr: still bit-clean
    assert _stage(rep, "new_m")["clean"]
    assert _stage(rep, "new_v")["clean"]


def test_cli_exit_1_names_first_divergence(capsys):
    rc = cli_main(["--step", "train_dp_bucketed", "--json",
                   "--mutate", "drop-grad-sync"])
    assert rc == 1
    rep = json.loads(capsys.readouterr().out)
    assert rep["first_divergence"]["stage"] == "grads"
    assert rep["first_divergence"]["leaf"]
    assert rep["mutation"] == "drop-grad-sync"


def test_cli_unknown_family_exits_2(capsys):
    rc = cli_main(["--step", "not_a_family", "--json"])
    assert rc == 2
    rep = json.loads(capsys.readouterr().out)
    assert "error" in rep


def test_cli_list_matches_module(capsys):
    rc = cli_main(["--list", "--json"])
    assert rc == 0
    rep = json.loads(capsys.readouterr().out)
    assert tuple(rep["families"]) == gradsan.family_names()
    assert tuple(rep["mutations"]) == gradsan.MUTATIONS
    # every gradsan family is a registered lint step of the same name
    from cs336_systems_tpu.analysis import registry

    step_names = {s.name for s in registry.STEPS}
    assert set(rep["families"]) <= step_names


@pytest.mark.slow
def test_sp_family_clean_post_fix():
    """The family whose regression the tool root-caused: sharded sp step
    vs single-device oracle, clean at both tolerance classes. (The ep-a2a
    twin runs in the package gate — scripts/run_tests_and_package.sh.)"""
    rep = gradsan.run_family("train_sp")
    assert rep["clean"], rep["first_divergence"]
