"""Continuous-batching engine tests: the page-pool allocator's
conservation invariants, the scratch-page table contract, in-scan EOS
tracking, and the headline property — the engine's per-request streams
are BIT-IDENTICAL to the row-keyed oracle
(``generate_kv_batched(..., row_keyed=True, page_block=...)``) no matter
when requests arrive, in what order they join, how few slots exist, or
how the slots shard over a dp/tp mesh. Same oracle discipline as
tests/test_serve.py: continuous batching is a SCHEDULE, not an
approximation.
"""

import numpy as np
import pytest

import jax

from cs336_systems_tpu.models.decode import (
    generate_kv_batched,
    validate_block_tables,
)
from cs336_systems_tpu.models.transformer import (
    TransformerConfig,
    init_transformer_lm,
)
from cs336_systems_tpu.parallel.mesh import make_mesh
from cs336_systems_tpu.serving import (
    PagePool,
    RefcountViolation,
    Request,
    Scheduler,
    ServingEngine,
)

CFG = TransformerConfig(
    vocab_size=64, context_length=64, d_model=64,
    num_layers=2, num_heads=4, d_ff=128,
)
BLK = 8
NEW = 10
LENS = [12, 3, 7, 1, 12, 5, 9, 2]  # test_paged_decode's skew profile


@pytest.fixture(scope="module")
def params():
    return init_transformer_lm(jax.random.PRNGKey(1), CFG)


@pytest.fixture(scope="module")
def prompts():
    rng = np.random.default_rng(7)
    return [rng.integers(0, CFG.vocab_size, size=n).astype(np.int32)
            for n in LENS]


def _oracle(params, prompts, eos=None):
    """All rows in ONE row-keyed paged batch — the stream the engine must
    reproduce per request regardless of its serving schedule."""
    pmax = max(p.size for p in prompts)
    padded = np.zeros((len(prompts), pmax), np.int32)
    for i, p in enumerate(prompts):
        padded[i, :p.size] = p
    return generate_kv_batched(
        params, CFG, padded, NEW, jax.random.PRNGKey(0), temperature=0.9,
        top_k=8, row_keyed=True, prompt_lens=[p.size for p in prompts],
        page_block=BLK, eos_token_id=eos)


def _engine(params, **kw):
    base = dict(key=jax.random.PRNGKey(0), slots=8, n_pages=32,
                max_blocks=4, page_block=BLK, temperature=0.9, top_k=8)
    base.update(kw)
    return ServingEngine(params, CFG, **base)


# --- page-pool allocator ----------------------------------------------


class TestPagePool:
    def test_alloc_free_conserves(self):
        pool = PagePool(8)
        a = pool.alloc(3, "a")
        b = pool.alloc(4, "b")
        assert len(set(a) | set(b)) == 7 and pool.available == 1
        pool.check_conserved()
        assert pool.free("a") == 3
        pool.check_conserved()
        pool.free("b")
        pool.check_all_free()

    def test_scratch_never_allocated(self):
        pool = PagePool(4)
        pages = pool.alloc(4, "all")
        assert pool.scratch_page == 4 and 4 not in pages
        assert sorted(pages) == [0, 1, 2, 3]

    def test_exhaustion_is_all_or_nothing(self):
        pool = PagePool(4)
        pool.alloc(3, "a")
        with pytest.raises(MemoryError):
            pool.alloc(2, "b")
        assert pool.available == 1  # the failed alloc took nothing
        pool.check_conserved()

    def test_double_alloc_and_double_free_raise(self):
        pool = PagePool(4)
        pool.alloc(1, "a")
        with pytest.raises(ValueError):
            pool.alloc(1, "a")
        pool.free("a")
        # ISSUE 10: ownership misuse is the typed RefcountViolation
        # (still a ValueError via compat subclassing)
        with pytest.raises(RefcountViolation):
            pool.free("a")

    def test_leak_detection(self):
        pool = PagePool(4)
        pool.alloc(2, "a")
        pool._owned["a"].pop()  # corrupt: drop a page on the floor
        with pytest.raises(AssertionError, match="leaked"):
            pool.check_conserved()


# --- the scratch-page table contract (satellite: validate_block_tables) -


def test_validate_block_tables_rejects_scratch_id():
    good = np.array([[0, 1], [2, 2]], np.int32)
    validate_block_tables(good, n_pages=4)
    bad = good.copy()
    bad[1, 1] = 4  # the reserved scratch page id
    with pytest.raises(ValueError, match="scratch"):
        validate_block_tables(bad, n_pages=4)
    with pytest.raises(ValueError):
        validate_block_tables(np.array([[5]], np.int32), n_pages=4)
    with pytest.raises(ValueError):
        validate_block_tables(np.array([[-1]], np.int32), n_pages=4)


def test_generate_kv_batched_validates_corrupt_geometry(params, prompts):
    """The consumer-side check: a geometry whose table smuggles the
    scratch id must be rejected before any kernel sees it."""
    import dataclasses

    from cs336_systems_tpu.models import decode as D

    orig = D.paged_kv_geometry

    def corrupt(*a, **kw):
        g = orig(*a, **kw)
        tables = np.array(g.tables)
        tables[0, 0] = g.n_pages  # scratch id into a live table
        return dataclasses.replace(g, tables=tables)

    D.paged_kv_geometry = corrupt
    try:
        with pytest.raises(ValueError, match="scratch"):
            _oracle(params, prompts)
    finally:
        D.paged_kv_geometry = orig


# --- FIFO scheduler ----------------------------------------------------


def test_scheduler_fifo_by_arrival_then_submission():
    s = Scheduler()
    s.submit(Request(rid=1, prompt=[1], max_new_tokens=1, arrival=2.0))
    s.submit(Request(rid=2, prompt=[1], max_new_tokens=1, arrival=1.0))
    s.submit(Request(rid=3, prompt=[1], max_new_tokens=1, arrival=1.0))
    assert s.head(0.5) is None          # nothing has arrived yet
    assert s.head(1.0).rid == 2         # earliest arrival wins
    assert s.pop().rid == 2
    assert s.head(1.0).rid == 3         # ties break by submission order
    assert s.pop().rid == 3
    assert s.next_arrival() == 2.0


# --- in-scan EOS tracking (satellite: generate_kv_batched) -------------


def test_in_scan_eos_matches_host_truncation(params, prompts):
    """The in-scan finished-mask must reproduce exactly what the old
    host-side post-hoc truncation computed: cut at the first EOS,
    excluding the EOS token itself."""
    full = np.asarray(_oracle(params, prompts))
    eos = int(full[0][3])  # appears mid-stream in row 0
    got = _oracle(params, prompts, eos=eos)
    for row in range(len(prompts)):
        hits = np.where(full[row] == eos)[0]
        want = full[row][: hits[0]] if hits.size else full[row]
        np.testing.assert_array_equal(np.asarray(got[row]), want)


# --- engine vs oracle: the bit-exactness contract ----------------------


def test_engine_matches_oracle_all_at_once(params, prompts):
    want = np.asarray(_oracle(params, prompts))
    eng = _engine(params)
    for r, p in enumerate(prompts):
        eng.submit(Request(rid=r, prompt=p, max_new_tokens=NEW))
    res = eng.run()
    eng.check_idle()  # every page back in the free list
    for r in range(len(prompts)):
        np.testing.assert_array_equal(res[r], want[r])


@pytest.mark.parametrize("order", [
    [5, 2, 7, 0, 3, 6, 1, 4],
    [7, 6, 5, 4, 3, 2, 1, 0],
], ids=["shuffled", "reversed"])
def test_engine_matches_oracle_across_join_orders(params, prompts, order):
    """Half the slots, staggered arrivals in permuted orders: requests
    queue, join mid-flight into slots vacated by earlier evictions — and
    every stream still equals the oracle's row (the per-slot key chain +
    global-row fold_in make the stream a function of the request alone)."""
    want = np.asarray(_oracle(params, prompts))
    eng = _engine(params, slots=4, n_pages=16)
    for i, r in enumerate(order):
        eng.submit(Request(rid=r, prompt=prompts[r], max_new_tokens=NEW,
                           arrival=float(i) * 0.25))
    tick = iter(np.arange(0.0, 1e4, 0.5))
    res = eng.run(time_fn=lambda: next(tick))
    eng.check_idle()
    for r in range(len(prompts)):
        np.testing.assert_array_equal(res[r], want[r])


def test_engine_eos_eviction_matches_oracle(params, prompts):
    """A slot sampling EOS finishes without emitting it and its pages
    free immediately — streams equal the oracle's truncated rows."""
    full = np.asarray(_oracle(params, prompts))
    eos = int(full[0][3])
    want = _oracle(params, prompts, eos=eos)
    eng = _engine(params, eos_token_id=eos)
    for r, p in enumerate(prompts):
        eng.submit(Request(rid=r, prompt=p, max_new_tokens=NEW))
    res = eng.run()
    eng.check_idle()
    for r in range(len(prompts)):
        np.testing.assert_array_equal(res[r], np.asarray(want[r]))


@pytest.mark.parametrize("mesh_axes,dp,tp", [
    ({"dp": 8}, "dp", None),
    ({"dp": 2, "tp": 4}, "dp", "tp"),
], ids=["dp8", "dp2xtp4"])
def test_engine_matches_oracle_on_mesh(params, prompts, mesh_axes, dp, tp):
    """Sharded slots (shard-local pools and allocators), staggered
    shuffled arrivals: still bit-identical to the single-device oracle."""
    want = np.asarray(_oracle(params, prompts))
    eng = _engine(params, slots=8, n_pages=8,
                  mesh=make_mesh(mesh_axes), dp_axis=dp, tp_axis=tp)
    for i, r in enumerate([4, 1, 6, 0, 7, 2, 5, 3]):
        eng.submit(Request(rid=r, prompt=prompts[r], max_new_tokens=NEW,
                           arrival=float(i) * 0.25))
    tick = iter(np.arange(0.0, 1e4, 0.5))
    res = eng.run(time_fn=lambda: next(tick))
    eng.check_idle()
    for r in range(len(prompts)):
        np.testing.assert_array_equal(res[r], want[r])


def test_engine_strict_fifo_blocks_head(params, prompts):
    """A head request too big for the CURRENT free pages blocks admission
    — nothing behind it bypasses — until an eviction frees capacity;
    every request still completes with its oracle stream."""
    want = np.asarray(_oracle(params, prompts))
    # 3 pages: one 12-token request (2 pages incl. growth) + one 1-token
    # request fill the pool; everything else must wait for evictions
    eng = _engine(params, slots=2, n_pages=3, max_blocks=3)
    for r in range(len(prompts)):
        eng.submit(Request(rid=r, prompt=prompts[r], max_new_tokens=NEW))
    res = eng.run()
    eng.check_idle()
    for r in range(len(prompts)):
        np.testing.assert_array_equal(res[r], want[r])


def test_engine_rejects_impossible_requests(params):
    eng = _engine(params, n_pages=2, max_blocks=2)
    with pytest.raises(ValueError, match="pages"):
        eng.submit(Request(rid=0, prompt=np.zeros(17, np.int32),
                           max_new_tokens=8))  # 4 pages > pool's 2
    with pytest.raises(ValueError, match="context_length"):
        eng.submit(Request(rid=1, prompt=np.zeros(8, np.int32),
                           max_new_tokens=CFG.context_length))
