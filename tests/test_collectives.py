"""Mesh construction + collective microbenchmark smoke tests
(reference distributed_communication_single.py capability)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from cs336_systems_tpu.parallel.collectives import (
    benchmark_allreduce,
    format_allreduce_table,
)
from cs336_systems_tpu.parallel.mesh import (
    batch_sharding,
    make_mesh,
    replicated,
    shard_batch,
)


def test_make_mesh_default_and_named():
    mesh = make_mesh()
    assert mesh.shape["dp"] == len(jax.devices())
    mesh2 = make_mesh({"dp": 2, "tp": 4})
    assert mesh2.shape == {"dp": 2, "tp": 4}
    mesh3 = make_mesh(4)
    assert mesh3.shape["dp"] == 4


def test_make_mesh_too_many_devices():
    with pytest.raises(ValueError):
        make_mesh({"dp": 1024})


def test_init_distributed_single_process_noop(monkeypatch):
    """Without cluster env vars or explicit args, init_distributed must not
    try to rendezvous — it returns the current process count."""
    from cs336_systems_tpu.parallel import mesh as mesh_mod

    for v in mesh_mod._CLUSTER_ENV_VARS:
        monkeypatch.delenv(v, raising=False)
    assert mesh_mod.init_distributed() == jax.process_count() == 1


def test_shard_batch_layout():
    mesh = make_mesh({"dp": 4}, devices=jax.devices()[:4])
    x = np.arange(8 * 3, dtype=np.float32).reshape(8, 3)
    xs = shard_batch(mesh, x)
    assert xs.sharding == batch_sharding(mesh)
    np.testing.assert_array_equal(np.asarray(xs), x)
    assert replicated(mesh).is_fully_replicated


def test_benchmark_allreduce_smoke():
    mesh = make_mesh({"dp": 2}, devices=jax.devices()[:2])
    res = benchmark_allreduce(mesh, payload_mbs=(0.25,), warmup=1, iters=2)
    assert len(res) == 1
    assert res[0].world_size == 2
    assert res[0].mean_ms > 0
    table = format_allreduce_table(res)
    assert "bus_GB/s" in table and "0.2" in table


def test_psum_correctness_over_mesh():
    """The psum the benchmark times actually sums across devices."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    mesh = make_mesh({"dp": 4}, devices=jax.devices()[:4])
    x = jax.device_put(
        jnp.arange(8, dtype=jnp.float32), NamedSharding(mesh, P("dp"))
    )
    out = jax.jit(
        jax.shard_map(
            lambda v: jax.lax.psum(v, "dp"), mesh=mesh,
            in_specs=(P("dp"),), out_specs=P("dp"),
        )
    )(x)
    # each device's 2-element shard is replaced by the sum over devices
    expect = np.tile(np.array([0.0 + 2 + 4 + 6, 1.0 + 3 + 5 + 7]), 4)
    np.testing.assert_array_equal(np.asarray(out), expect)
