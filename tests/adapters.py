"""Adapter seam: the public API contract, mirrored from the reference.

The reference's only "public API" is tests/adapters.py — staff tests call
student code exclusively through these ``get_*`` functions
(/root/reference/tests/adapters.py:10-140). This file keeps the same seam
shape with TPU-native return values, so the reference test *intent* maps
one-to-one:

| reference adapter                                | returns (torch)        | here returns (jax)                    |
|--------------------------------------------------|------------------------|---------------------------------------|
| get_flashattention_autograd_function_pytorch     | autograd.Function      | differentiable fn (portable tiling)   |
| get_flashattention_autograd_function_triton      | autograd.Function      | differentiable fn (Pallas TPU kernel) |
| get_ddp_individual_parameters (+ on_after_backward) | DDP wrapper module  | per-leaf-collective DP grad fn        |
| get_ddp_bucketed (+ hooks)                       | DDP_Bucketed module    | bucketed DP grad fn                   |
| get_sharded_optimizer                            | ZeRO-1 optimizer       | ZeRO-1 sharded AdamW step             |

An ``torch.autograd.Function`` and a ``jax.custom_vjp``-wrapped function are
the same contract (forward + custom backward); DDP wrapper classes map to
gradient-synchronising step functions because JAX models are pytrees, not
modules — the hook-driven ``on_after_backward`` lifecycle collapses into
the jitted step itself (XLA schedules the overlap; SURVEY §3.4).
"""

from __future__ import annotations

import functools
from typing import Callable

from cs336_systems_tpu.ops import flash_attention as _fa


def get_flashattention_autograd_function_pytorch() -> Callable:
    """Portable tiled online-softmax attention (reference
    FlashAttentionTorch, flash_attention.py:8-83): differentiable
    ``fn(q, k, v, causal=False) -> O`` with the recompute backward."""
    return functools.partial(_fa.flash_attention, impl="reference")


def get_flashattention_autograd_function_triton() -> Callable:
    """Native-kernel attention (reference FlashAttentionTriton,
    flash_attention.py:85-266): the Pallas (Mosaic) TPU kernel, interpreter
    mode off-TPU. Saves exactly (Q, K, V, O, L) with L the logsumexp —
    the residual contract the reference forward test asserts."""
    return functools.partial(_fa.flash_attention, impl="pallas")


def get_flashattention_with_lse(impl: str = "pallas") -> Callable:
    """(O, L) variant used by the forward-LSE contract test."""
    return functools.partial(_fa.flash_attention_with_lse, impl=impl)


def get_ddp_individual_parameters(loss_fn, mesh, trainable=None) -> Callable:
    """Per-parameter-collective DP (reference DDP wrapper,
    ddp_bucketed_overlapped_sharded.py:217-248): returns
    ``(params, *batch) -> (loss, synced_grads)`` with one independent
    all-reduce per gradient leaf — XLA's scheduler overlaps them with the
    remaining backward, which is what the reference's per-param async
    hooks + reverse-order waits implement by hand."""
    from cs336_systems_tpu.parallel.dp import make_dp_grad_fn

    return make_dp_grad_fn(loss_fn, mesh, variant="naive", trainable=trainable)


def ddp_individual_parameters_on_after_backward(ddp_model, optimizer) -> None:
    """No-op by design: gradient synchronisation is *inside* the jitted
    step (there is no separate post-backward phase to hook). Kept so the
    reference test-lifecycle shape still maps."""


def get_ddp_bucketed(loss_fn, mesh, bucket_size_mb: float, trainable=None) -> Callable:
    """Bucketed DP (reference DDP_Bucketed,
    ddp_bucketed_overlapped_sharded.py:251-318): reverse-order ≤size_mb
    buckets, one concatenated all-reduce per bucket."""
    from cs336_systems_tpu.parallel.dp import make_dp_grad_fn

    return make_dp_grad_fn(
        loss_fn, mesh, variant="bucketed",
        bucket_size_mb=bucket_size_mb, trainable=trainable,
    )


def ddp_bucketed_on_after_backward(ddp_model, optimizer) -> None:
    """No-op by design (see ddp_individual_parameters_on_after_backward)."""


def ddp_bucketed_on_train_batch_start(ddp_model, optimizer) -> None:
    """No-op by design: bucket counters/handles do not exist — the jitted
    step has no cross-step communication state to reset."""


def get_sharded_optimizer(params, mesh, hp=None, loss_fn=None, **kwargs):
    """ZeRO-1 sharded AdamW (reference ShardedStateOptimizer,
    ddp_bucketed_overlapped_sharded.py:322-362): returns
    ``(zstate, step_fn)`` where the state is index-sharded over the mesh's
    dp axis and ``step_fn(params, zstate, *batch)`` does
    reduce-scatter → owner-computes AdamW → all-gather."""
    from cs336_systems_tpu.optim.adamw import AdamWHparams
    from cs336_systems_tpu.parallel.zero import make_zero1_step_for, zero1_init

    hp = hp or AdamWHparams(**kwargs)
    zstate = zero1_init(params, mesh)
    if loss_fn is None:
        raise ValueError("loss_fn required: the ZeRO-1 step fuses grad+update")
    return zstate, make_zero1_step_for(loss_fn, hp, mesh)
