"""Chunked fused lm-head + CE (ops/fused_ce.py) parity tests.

Oracle: the unchunked composition the repo already trusts —
``models.layers.linear`` + ``ops.nn.cross_entropy`` (itself custom-VJP'd
and reference-tested). Chunking is row-parallel along S: every per-row
quantity (lse, picked logit, softmax row) is identical chunked vs
unchunked, so value AND gradients must agree at grad-level tolerance
across chunk sizes {1, non-divisor, S/4, S}, dtypes {fp32, bf16}, and
the Pallas-kernel forward (interpret=True — CI has no TPU; the on-chip
run is queued in results/). The vocab-sharded variant (tp / tp_sp
layouts) is oracle-tested on the 8-virtual-device CPU mesh (conftest),
same discipline as tests/test_tp_sp.py.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from cs336_systems_tpu.models.layers import linear
from cs336_systems_tpu.ops.fused_ce import (
    auto_chunk,
    fused_linear_cross_entropy,
    fused_linear_cross_entropy_sharded,
)
from cs336_systems_tpu.ops.nn import cross_entropy
from cs336_systems_tpu.parallel.mesh import make_mesh

B, S, D, V = 4, 64, 32, 96


def _data(dtype=jnp.float32, key=0):
    k1, k2, k3 = jax.random.split(jax.random.PRNGKey(key), 3)
    h = (jax.random.normal(k1, (B, S, D)) * 0.7).astype(dtype)
    w = (jax.random.normal(k2, (V, D)) * 0.2).astype(dtype)
    t = jax.random.randint(k3, (B, S), 0, V)
    return h, w, t


def _oracle_loss(h, w, t, cdtype):
    return cross_entropy(linear({"weight": w}, h, cdtype), t)


def _tol(dtype):
    # fp32: chunking only reassociates the scalar loss sum and the fp32 dW
    # accumulation — near-exact. bf16: dh is produced by the same bf16
    # matmul both ways; dW differs by the fused path's fp32 accumulator
    # (BETTER than the oracle's, bounded by bf16 resolution on the cast).
    if dtype == jnp.float32:
        return dict(rtol=1e-5, atol=1e-6)
    return dict(rtol=2e-2, atol=2e-3)


@pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16])
@pytest.mark.parametrize("chunk", [1, 50, None, S])
def test_fused_ce_matches_unchunked_oracle(chunk, dtype):
    """Loss and (dh, dW) match the full-logits oracle at grad tolerance —
    chunk=1 (degenerate row-at-a-time), 50 (non-divisor of S=64: padded
    tail chunk masked), None (auto_chunk = S/4), S (single chunk)."""
    cdtype = "bfloat16" if dtype == jnp.bfloat16 else "float32"
    h, w, t = _data(dtype)

    def fused(h, w):
        return fused_linear_cross_entropy(
            h, w, t, chunk_size=chunk, compute_dtype=cdtype)

    def ref(h, w):
        return _oracle_loss(h, w, t, cdtype)

    loss, grads = jax.value_and_grad(fused, argnums=(0, 1))(h, w)
    loss_r, grads_r = jax.value_and_grad(ref, argnums=(0, 1))(h, w)
    tol = _tol(dtype)
    np.testing.assert_allclose(np.asarray(loss, np.float32),
                               np.asarray(loss_r, np.float32), **tol)
    for g, g_r, name in zip(grads, grads_r, ("dh", "dW")):
        assert g.dtype == g_r.dtype, name
        np.testing.assert_allclose(np.asarray(g, np.float32),
                                   np.asarray(g_r, np.float32),
                                   err_msg=name, **tol)


def test_auto_chunk_bounds():
    assert auto_chunk(64) == 16          # S/4 at the registry shape
    assert auto_chunk(512) == 128        # S/4 == cap
    assert auto_chunk(65536) == 128      # long-context cap
    assert auto_chunk(16) == 16          # floor clamps to S
    assert auto_chunk(3) == 3            # never exceeds S
    with pytest.raises(ValueError):
        fused_linear_cross_entropy(
            jnp.zeros((1, 4, 8)), jnp.zeros((16, 8)),
            jnp.zeros((1, 4), jnp.int32), chunk_size=0)


@pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16])
def test_pallas_interpret_matches_xla(dtype):
    """The Pallas forward chunk kernel (interpret=True on CPU) against the
    XLA scan oracle: same loss, same grads (the backward is the shared
    XLA recompute — what differs per impl is the lse/picked residual the
    kernel produces)."""
    cdtype = "bfloat16" if dtype == jnp.bfloat16 else "float32"
    h, w, t = _data(dtype)

    def run(impl):
        def f(h, w):
            return fused_linear_cross_entropy(
                h, w, t, compute_dtype=cdtype, impl=impl)

        return jax.value_and_grad(f, argnums=(0, 1))(h, w)

    loss_x, grads_x = run("xla")
    loss_p, grads_p = run("pallas_interpret")
    # both reduce in fp32; the online (streamed-max) vs two-pass softmax
    # reassociation is the only difference. At bf16 the lse residual's
    # last-ulp shifts feed exp() in the shared recompute backward, so
    # near-zero dW entries move by O(1e-5) — grad-level atol, not exact.
    gtol = (dict(rtol=1e-5, atol=1e-5) if dtype == jnp.float32
            else dict(rtol=1e-3, atol=1e-4))
    np.testing.assert_allclose(np.asarray(loss_p, np.float32),
                               np.asarray(loss_x, np.float32),
                               rtol=1e-5, atol=1e-6)
    for g_p, g_x, name in zip(grads_p, grads_x, ("dh", "dW")):
        np.testing.assert_allclose(np.asarray(g_p, np.float32),
                                   np.asarray(g_x, np.float32),
                                   err_msg=name, **gtol)


def test_pallas_interpret_vocab_not_tile_multiple():
    """V=100 is not a lane-tile multiple: the kernel's padded vocab tile
    must be masked out of max/sum-exp/picked (the -inf / isfinite guards)."""
    k1, k2, k3 = jax.random.split(jax.random.PRNGKey(3), 3)
    h = jax.random.normal(k1, (2, 16, 8))
    w = jax.random.normal(k2, (100, 8)) * 0.3
    t = jax.random.randint(k3, (2, 16), 0, 100)
    loss_x = fused_linear_cross_entropy(h, w, t, impl="xla")
    loss_p = fused_linear_cross_entropy(h, w, t, impl="pallas_interpret")
    np.testing.assert_allclose(np.asarray(loss_p), np.asarray(loss_x),
                               rtol=1e-6, atol=1e-7)


# --- vocab-sharded variant (tp / tp_sp layouts) -----------------------------


@pytest.mark.parametrize("chunk", [None, 50])
def test_sharded_tp_matches_unsharded(chunk):
    """Vocab-column-parallel CE on the tp mesh against the single-device
    fused path: the pmax/psum decomposition of the logsumexp is exact up
    to fp reassociation."""
    mesh = make_mesh({"tp": 4})
    h, w, t = _data()

    def sharded(h, w):
        return fused_linear_cross_entropy_sharded(
            h, w, t, mesh=mesh, vocab_axis="tp", chunk_size=chunk)

    def ref(h, w):
        return fused_linear_cross_entropy(h, w, t, chunk_size=chunk)

    loss, grads = jax.value_and_grad(jax.jit(sharded), argnums=(0, 1))(h, w)
    loss_r, grads_r = jax.value_and_grad(ref, argnums=(0, 1))(h, w)
    np.testing.assert_allclose(np.asarray(loss), np.asarray(loss_r),
                               rtol=1e-5, atol=1e-6)
    for g, g_r, name in zip(grads, grads_r, ("dh", "dW")):
        np.testing.assert_allclose(np.asarray(g), np.asarray(g_r),
                                   rtol=1e-5, atol=1e-6, err_msg=name)


def test_sharded_tp_sp_matches_unsharded():
    """The 3-axis layout (batch over dp, S over sp, vocab over tp): the
    chunk scan runs over the LOCAL sequence and the loss/dW psums close
    over ALL token axes — must still match the single-device fused path."""
    mesh = make_mesh({"dp": 2, "tp": 2, "sp": 2})
    h, w, t = _data()

    def sharded(h, w):
        return fused_linear_cross_entropy_sharded(
            h, w, t, mesh=mesh, vocab_axis="tp", batch_axes=("dp",),
            seq_axis="sp")

    def ref(h, w):
        return fused_linear_cross_entropy(h, w, t)

    loss, grads = jax.value_and_grad(jax.jit(sharded), argnums=(0, 1))(h, w)
    loss_r, grads_r = jax.value_and_grad(ref, argnums=(0, 1))(h, w)
    np.testing.assert_allclose(np.asarray(loss), np.asarray(loss_r),
                               rtol=1e-5, atol=1e-6)
    for g, g_r, name in zip(grads, grads_r, ("dh", "dW")):
        np.testing.assert_allclose(np.asarray(g), np.asarray(g_r),
                                   rtol=1e-5, atol=1e-6, err_msg=name)


def test_train_step_chunked_matches_full_logits():
    """End-to-end oracle at the train-step level: one step with the
    default chunked loss path vs one with ``ce_chunk_size=0`` (the legacy
    full-logits CE) — loss near-exact, post-AdamW params at the
    eps-amplification tolerance (tests/test_pp.py derivation)."""
    from cs336_systems_tpu.models.transformer import (
        TransformerConfig, init_transformer_lm)
    from cs336_systems_tpu.optim.adamw import AdamWHparams, adamw_init
    from cs336_systems_tpu.train import make_train_step

    def one_step(ce_chunk_size):
        cfg = TransformerConfig(
            vocab_size=64, context_length=32, d_model=32, num_layers=2,
            num_heads=4, d_ff=64, ce_chunk_size=ce_chunk_size)
        params = init_transformer_lm(jax.random.PRNGKey(0), cfg)
        opt = adamw_init(params)
        k = jax.random.PRNGKey(7)
        x = jax.random.randint(k, (4, cfg.context_length), 0, cfg.vocab_size)
        y = jnp.roll(x, -1, axis=-1)
        step = make_train_step(cfg, AdamWHparams(lr=1e-3), donate=False)
        new_params, _, loss = step(params, opt, x, y)
        return loss, new_params

    loss_c, params_c = one_step(None)
    loss_f, params_f = one_step(0)
    np.testing.assert_allclose(np.asarray(loss_c), np.asarray(loss_f),
                               rtol=1e-6, atol=1e-7)
    for (pa, a), (pb, b) in zip(
            jax.tree_util.tree_leaves_with_path(params_c),
            jax.tree_util.tree_leaves_with_path(params_f)):
        assert pa == pb
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-3, atol=5e-4, err_msg=str(pa))
