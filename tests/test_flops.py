"""flops.py oracle tests: the shared MFU denominator pinned against
hand-computed numbers (ISSUE 13 satellite). Every consumer — bench.py's
headline line, the MoE bench's MFU column, tracekit's achieved-TF/s and
schedkit's MXU cost model — divides by these conventions, so a silent
change here skews every artifact that gets compared across rounds. The
oracles below are worked BY HAND in the comments from the docstring's
stated convention; if one fails, either the convention changed (update
the docstring AND these numbers together) or a refactor broke the
arithmetic.
"""

from types import SimpleNamespace

import pytest

from cs336_systems_tpu.analysis.flops import (
    V5E_BF16_PEAK_FLOPS,
    decode_flops_per_token,
    model_flops_per_token,
)


def _cfg(**kw):
    base = dict(vocab_size=10_000, context_length=512, d_model=1024,
                num_layers=24, d_ff=4096, num_experts=0, moe_top_k=0)
    base.update(kw)
    return SimpleNamespace(**base)


def test_v5e_peak_is_nameplate():
    assert V5E_BF16_PEAK_FLOPS == 197e12


def test_dense_train_flops_hand_computed():
    # Headline-ish dense config, worked by hand:
    #   d=1024, dff=4096, L=24, V=10000, S=512
    #   per-layer param matmuls: 4*d*d (qkvo) + 3*d*dff (SwiGLU)
    #     = 4*1024*1024 + 3*1024*4096 = 4_194_304 + 12_582_912
    #     = 16_777_216
    #   N_matmul = 24 * 16_777_216 + d*V = 402_653_184 + 10_240_000
    #     = 412_893_184
    #   attn (causal) = 12*S*d*L*0.5 = 12*512*1024*24/2 = 75_497_472
    #   total = 6*N_matmul + attn = 2_477_359_104 + 75_497_472
    #     = 2_552_856_576
    assert model_flops_per_token(_cfg()) == 2_552_856_576


def test_full_attention_doubles_the_causal_term():
    causal = model_flops_per_token(_cfg(), causal=True)
    full = model_flops_per_token(_cfg(), causal=False)
    # full attention scores 12*S*d*L = 150_994_944 per token; causal
    # counts only the lower triangle, so the delta is the other half
    assert full - causal == 75_497_472


def test_moe_train_flops_counts_topk_experts_and_router():
    # E=8 experts, top_k=2: a token's FFN work doubles and the router
    # matmul d*E joins the per-layer params.
    #   per-layer: 4*d*d + 2*3*d*dff + d*8
    #     = 4_194_304 + 25_165_824 + 8_192 = 29_368_320
    #   N_matmul = 24*29_368_320 + 10_240_000 = 715_079_680
    #   total = 6*N_matmul + 75_497_472 = 4_290_478_080 + 75_497_472
    #     = 4_365_975_552
    cfg = _cfg(num_experts=8, moe_top_k=2)
    assert model_flops_per_token(cfg) == 4_365_975_552


def test_moe_top_k_zero_still_counts_one_expert():
    # max(top_k, 1): a degenerate top_k=0 config must not zero the FFN
    cfg = _cfg(num_experts=8, moe_top_k=0)
    dense_plus_router = model_flops_per_token(_cfg()) + 6 * 24 * 1024 * 8
    assert model_flops_per_token(cfg) == dense_plus_router


def test_decode_flops_hand_computed():
    # Forward only (2*N_matmul) + cached attention 4*attend*d*L.
    #   N_matmul = 412_893_184 (dense config above)
    #   attend_len=256: 4*256*1024*24 = 25_165_824
    #   total = 825_786_368 + 25_165_824 = 850_952_192
    assert decode_flops_per_token(_cfg(), attend_len=256) == 850_952_192


def test_decode_defaults_to_full_context_window():
    cfg = _cfg()
    assert decode_flops_per_token(cfg) == decode_flops_per_token(
        cfg, attend_len=cfg.context_length)


def test_ragged_decode_uses_mean_of_lens_not_max():
    # Per-token share of a ragged batch is the MEAN attended length:
    # lens [128, 256, 384, 512] -> mean 320, NOT max 512.
    cfg = _cfg()
    ragged = decode_flops_per_token(cfg, attend_lens=[128, 256, 384, 512])
    assert ragged == decode_flops_per_token(cfg, attend_len=320)
    assert ragged < decode_flops_per_token(cfg, attend_len=512)


def test_ragged_and_scalar_are_mutually_exclusive():
    with pytest.raises(ValueError, match="not both"):
        decode_flops_per_token(_cfg(), attend_len=256,
                               attend_lens=[1, 2, 3])
