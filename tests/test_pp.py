"""Pipeline-parallel (GPipe over the ``pp`` mesh axis) tests.

Oracle: the single-device train step — pipelining is a schedule, not an
approximation, so one dp×pp step must match one full-batch step tightly.
Runs on the 8-virtual-device CPU mesh (conftest).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from common import trees_allclose
from cs336_systems_tpu.models.transformer import (
    TransformerConfig,
    init_transformer_lm,
)
from cs336_systems_tpu.optim.adamw import AdamWHparams, adamw_init
from cs336_systems_tpu.parallel.mesh import make_mesh
from cs336_systems_tpu.parallel.pp import (
    make_pp_train_step,
    shard_params_pp,
    validate_pp,
)
from cs336_systems_tpu.train import make_train_step

CFG = TransformerConfig(
    vocab_size=64, context_length=32, d_model=32,
    num_layers=4, num_heads=4, d_ff=64,
)


def _data(key, batch=8):
    x = jax.random.randint(key, (batch, CFG.context_length), 0, CFG.vocab_size)
    return x, jnp.roll(x, -1, axis=-1)


def _ref_step_result(x, y, clip_norm=1.0):
    params = init_transformer_lm(jax.random.PRNGKey(0), CFG)
    opt = adamw_init(params)
    step = make_train_step(CFG, AdamWHparams(lr=1e-3), clip_norm=clip_norm,
                           donate=False)
    return step(params, opt, x, y)


# Post-AdamW tolerance: with t=1 the update is alpha_t * g/(|g|+eps), so
# ulp-level fp-reassociation differences in near-zero gradients flip the
# quotient by up to ~alpha_t = lr*sqrt(1-b2)/(1-b1) ≈ 3.2e-4 at lr=1e-3.
# Gradients themselves are checked near-exactly in test_pp_grads_*.
ADAMW_ATOL = 5e-4


@pytest.mark.parametrize("num_microbatches", [2, 4])
def test_pp_grads_match_single_device(num_microbatches):
    """The GPipe schedule is exact: gradients match the unpipelined model to
    fp reassociation."""
    from cs336_systems_tpu.parallel.pp import make_pp_grad_fn
    from cs336_systems_tpu.train import lm_loss

    mesh = make_mesh({"pp": 4})
    x, y = _data(jax.random.PRNGKey(1))
    params = init_transformer_lm(jax.random.PRNGKey(0), CFG)
    l_ref, g_ref = jax.value_and_grad(lm_loss)(params, x, y, CFG)

    grad_fn = make_pp_grad_fn(CFG, mesh, num_microbatches)
    l_pp, g_pp = grad_fn(shard_params_pp(params, mesh, CFG), x, y)
    np.testing.assert_allclose(float(l_pp), float(l_ref), rtol=1e-6)
    assert trees_allclose(g_pp, g_ref, rtol=1e-5, atol=1e-6)


@pytest.mark.parametrize("num_microbatches", [2, 4])
def test_pp_step_matches_single_device(num_microbatches):
    mesh = make_mesh({"pp": 4})
    x, y = _data(jax.random.PRNGKey(1))
    p_ref, o_ref, l_ref = _ref_step_result(x, y)

    params = shard_params_pp(init_transformer_lm(jax.random.PRNGKey(0), CFG),
                             mesh, CFG)
    opt = adamw_init(params)
    step = make_pp_train_step(CFG, AdamWHparams(lr=1e-3), mesh,
                              num_microbatches=num_microbatches, donate=False)
    p_pp, o_pp, l_pp = step(params, opt, x, y)

    np.testing.assert_allclose(float(l_pp), float(l_ref), rtol=1e-5)
    assert trees_allclose(p_pp, p_ref, rtol=1e-3, atol=ADAMW_ATOL)


def test_pp_composes_with_dp():
    """dp=2 × pp=4: batch sharded over dp, layers over pp."""
    mesh = make_mesh({"dp": 2, "pp": 4})
    x, y = _data(jax.random.PRNGKey(2))
    p_ref, o_ref, l_ref = _ref_step_result(x, y)

    params = shard_params_pp(init_transformer_lm(jax.random.PRNGKey(0), CFG),
                             mesh, CFG)
    opt = adamw_init(params)
    step = make_pp_train_step(CFG, AdamWHparams(lr=1e-3), mesh,
                              num_microbatches=2, donate=False)
    from jax.sharding import NamedSharding, PartitionSpec as P

    sh = NamedSharding(mesh, P("dp"))
    p_pp, o_pp, l_pp = step(params, opt, jax.device_put(x, sh),
                            jax.device_put(y, sh))
    np.testing.assert_allclose(float(l_pp), float(l_ref), rtol=1e-5)
    assert trees_allclose(p_pp, p_ref, rtol=1e-3, atol=ADAMW_ATOL)


def test_pp_single_stage_degenerates_to_plain_step():
    mesh = make_mesh({"pp": 1})
    x, y = _data(jax.random.PRNGKey(3), batch=4)
    p_ref, o_ref, l_ref = _ref_step_result(x, y)
    params = init_transformer_lm(jax.random.PRNGKey(0), CFG)
    opt = adamw_init(params)
    step = make_pp_train_step(CFG, AdamWHparams(lr=1e-3), mesh,
                              num_microbatches=2, dp_axis=None, donate=False)
    p_pp, _, l_pp = step(params, opt, x, y)
    np.testing.assert_allclose(float(l_pp), float(l_ref), rtol=1e-5)
    assert trees_allclose(p_pp, p_ref, rtol=1e-3, atol=ADAMW_ATOL)


def test_pp_validation():
    mesh = make_mesh({"pp": 8})
    with pytest.raises(ValueError, match="not divisible"):
        validate_pp(CFG, mesh)  # 4 layers, pp=8

    mesh4 = make_mesh({"pp": 4})
    step = make_pp_train_step(CFG, AdamWHparams(lr=1e-3), mesh4,
                              num_microbatches=3, dp_axis=None, donate=False)
    params = shard_params_pp(init_transformer_lm(jax.random.PRNGKey(0), CFG),
                             mesh4, CFG)
    opt = adamw_init(params)
    x, y = _data(jax.random.PRNGKey(4))  # batch 8 not divisible by m=3
    with pytest.raises(ValueError, match="not divisible"):
        step(params, opt, x, y)
