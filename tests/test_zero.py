"""ZeRO-1 exactness tests.

Mirrors the reference tests/test_sharded_optimizer.py: identical replicas
(same seed), no gradient noise between ranks, 10 optimizer steps; final
params must match a non-sharded optimizer at tight tolerance (80-84). Plus
the greedy byte-balanced assignment policy and the state-memory claim.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from cs336_systems_tpu.optim.adamw import AdamWHparams, adamw_init, adamw_update
from cs336_systems_tpu.parallel.mesh import make_mesh, shard_batch
from cs336_systems_tpu.parallel.zero import (
    greedy_param_assignment,
    make_zero1_step_for,
    make_zero1_train_step,
    zero1_init,
    zero1_state_bytes,
)

from common import mse_loss, toy_model_apply, toy_model_init, trees_allclose

WORLD = 2
STEPS = 10


@pytest.fixture(scope="module")
def mesh():
    return make_mesh({"dp": WORLD}, devices=jax.devices()[:WORLD])


def test_zero1_matches_unsharded_adamw(mesh):
    """10 AdamW steps sharded vs unsharded must agree tightly."""
    params, _ = toy_model_init(jax.random.PRNGKey(0))
    hp = AdamWHparams(lr=1e-3, weight_decay=0.01)
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.standard_normal((8, 10)).astype(np.float32))
    y = jnp.asarray(rng.standard_normal((8, 5)).astype(np.float32))

    loss_fn = lambda p, xx, yy: mse_loss(toy_model_apply, p, xx, yy)

    # unsharded
    p_ref, opt = params, adamw_init(params)
    for _ in range(STEPS):
        grads = jax.grad(loss_fn)(p_ref, x, y)
        p_ref, opt = adamw_update(p_ref, grads, opt, hp)

    # ZeRO-1: every rank sees the SAME full batch (reference setup: identical
    # replicas, no DP gradient averaging differences — grads identical, and
    # psum_scatter/world == the same gradient)
    step = make_zero1_step_for(loss_fn, hp, mesh)
    xs = jnp.concatenate([x, x])  # each of the 2 ranks gets the full batch
    ys = jnp.concatenate([y, y])
    xs, ys = shard_batch(mesh, xs, ys)
    p_z, z = params, zero1_init(params, mesh)
    for _ in range(STEPS):
        p_z, z, loss = step(p_z, z, xs, ys)

    assert trees_allclose(p_ref, p_z, rtol=1e-6, atol=1e-7)
    assert int(z["t"]) == STEPS


def test_zero1_lm_step_runs_and_learns(mesh):
    from cs336_systems_tpu.models.transformer import TransformerConfig
    from cs336_systems_tpu.train import init_train_state

    cfg = TransformerConfig(
        vocab_size=32, context_length=16, d_model=32,
        num_layers=2, num_heads=2, d_ff=64,
    )
    hp = AdamWHparams(lr=3e-3)
    params, _ = init_train_state(jax.random.PRNGKey(0), cfg)
    zstate = zero1_init(params, mesh)
    step = make_zero1_train_step(cfg, hp, mesh, clip_norm=1.0, donate=False)

    data = np.tile(np.arange(16, dtype=np.int32), 100)
    rng = np.random.default_rng(0)
    first = last = None
    for i in range(30):
        starts = rng.integers(0, len(data) - 17, size=4)
        idx = starts[:, None] + np.arange(17)[None, :]
        w = data[idx]
        xs, ys = shard_batch(mesh, jnp.asarray(w[:, :-1]), jnp.asarray(w[:, 1:]))
        params, zstate, loss = step(params, zstate, xs, ys)
        if first is None:
            first = float(loss)
        last = float(loss)
    assert last < first * 0.5, (first, last)


def test_zero1_matches_dp_adamw_end_to_end(mesh):
    """DP + ZeRO-1 == DP + unsharded AdamW on sharded batches."""
    from cs336_systems_tpu.parallel.dp import make_dp_grad_fn

    params, _ = toy_model_init(jax.random.PRNGKey(5))
    hp = AdamWHparams(lr=1e-3)
    rng = np.random.default_rng(1)
    x = jnp.asarray(rng.standard_normal((8, 10)).astype(np.float32))
    y = jnp.asarray(rng.standard_normal((8, 5)).astype(np.float32))
    loss_fn = lambda p, xx, yy: mse_loss(toy_model_apply, p, xx, yy)

    # DP with unsharded AdamW
    grad_fn = make_dp_grad_fn(loss_fn, mesh, variant="flat")
    xs, ys = shard_batch(mesh, x, y)
    p_ref, opt = params, adamw_init(params)
    for _ in range(STEPS):
        _, grads = grad_fn(p_ref, xs, ys)
        p_ref, opt = adamw_update(p_ref, grads, opt, hp)

    # DP with ZeRO-1 (reduce-scatter averages over ranks internally)
    step = make_zero1_step_for(loss_fn, hp, mesh)
    p_z, z = params, zero1_init(params, mesh)
    for _ in range(STEPS):
        p_z, z, _ = step(p_z, z, xs, ys)

    assert trees_allclose(p_ref, p_z, rtol=1e-5, atol=1e-7)


def test_greedy_assignment_balanced():
    """Byte-balanced greedy assignment (reference argmin policy)."""
    params = {
        "a": jnp.zeros((100,)), "b": jnp.zeros((100,)),
        "c": jnp.zeros((50,)), "d": jnp.zeros((50,)), "e": jnp.zeros((100,)),
    }
    owners = greedy_param_assignment(params, 2)
    leaves = jax.tree_util.tree_leaves(params)
    per_rank = [0, 0]
    for o, leaf in zip(owners, leaves):
        per_rank[o] += leaf.size
    assert abs(per_rank[0] - per_rank[1]) <= 100
    assert sorted(set(owners)) == [0, 1]


def test_zero1_state_memory_scales_down():
    params, _ = toy_model_init(jax.random.PRNGKey(0))
    full = zero1_state_bytes(params, 1)
    half = zero1_state_bytes(params, 2)
    assert half <= full / 2 + 8  # ceil padding slack
