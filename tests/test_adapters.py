"""The adapter seam must expose working implementations — this re-runs the
reference's core test intents through tests/adapters.py exclusively."""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import adapters
from common import mse_loss, toy_model_apply, toy_model_init, trees_allclose
from cs336_systems_tpu.parallel.mesh import make_mesh, shard_batch


@pytest.fixture(scope="module")
def mesh():
    return make_mesh({"dp": 2})


def _oracle_attention(q, k, v, causal):
    s = jnp.einsum("bqd,bkd->bqk", q, k) / jnp.sqrt(q.shape[-1])
    if causal:
        mask = jnp.tril(jnp.ones((q.shape[1], k.shape[1]), bool))
        s = jnp.where(mask, s, -1e6)
    return jnp.einsum("bqk,bkd->bqd", jax.nn.softmax(s, axis=-1), v)


@pytest.mark.parametrize(
    "getter",
    [
        adapters.get_flashattention_autograd_function_pytorch,
        adapters.get_flashattention_autograd_function_triton,
    ],
)
@pytest.mark.parametrize("causal", [False, True])
def test_flashattention_adapters(getter, causal):
    """Reference test_attention.py shapes: batch 4, Nq=Nk=128, D=64,
    tolerance 1e-2; forward and backward vs the plain-attention oracle."""
    fa = getter()
    ks = jax.random.split(jax.random.PRNGKey(0), 3)
    q, k, v = (jax.random.normal(kk, (4, 128, 64)) for kk in ks)

    out = fa(q, k, v, causal=causal)
    ref = _oracle_attention(q, k, v, causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=1e-2, atol=1e-2)

    g = jax.grad(lambda q, k, v: jnp.sum(fa(q, k, v, causal=causal) ** 2), (0, 1, 2))(q, k, v)
    gr = jax.grad(lambda q, k, v: jnp.sum(_oracle_attention(q, k, v, causal) ** 2), (0, 1, 2))(q, k, v)
    for a, b in zip(g, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-2, atol=1e-2)


def test_flashattention_lse_contract():
    """Forward must expose L = logsumexp of shape (batch, n_queries) —
    the reference's saved-residual contract (test_attention.py:48-51)."""
    fa = adapters.get_flashattention_with_lse("reference")
    ks = jax.random.split(jax.random.PRNGKey(1), 3)
    q, k, v = (jax.random.normal(kk, (4, 128, 64)) for kk in ks)
    out, lse = fa(q, k, v)
    assert lse.shape == (4, 128)
    s = jnp.einsum("bqd,bkd->bqk", q, k) / jnp.sqrt(64.0)
    ref_lse = jax.scipy.special.logsumexp(s, axis=-1)
    np.testing.assert_allclose(np.asarray(lse), np.asarray(ref_lse), rtol=1e-4, atol=1e-4)


@pytest.mark.parametrize("flavor", ["individual", "bucketed"])
def test_ddp_adapters_match_single_process(mesh, flavor):
    """Reference test_ddp* invariant: DP grads == full-batch grads,
    including frozen-parameter handling."""
    params, trainable = toy_model_init(jax.random.PRNGKey(0))
    loss_fn = functools.partial(mse_loss, toy_model_apply)

    if flavor == "individual":
        fn = adapters.get_ddp_individual_parameters(loss_fn, mesh, trainable=trainable)
        adapters.ddp_individual_parameters_on_after_backward(None, None)
    else:
        fn = adapters.get_ddp_bucketed(loss_fn, mesh, 0.001, trainable=trainable)
        adapters.ddp_bucketed_on_train_batch_start(None, None)
        adapters.ddp_bucketed_on_after_backward(None, None)

    x = jax.random.normal(jax.random.PRNGKey(1), (8, 10))
    y = jax.random.normal(jax.random.PRNGKey(2), (8, 5))
    xs, ys = shard_batch(mesh, x, y)
    loss, grads = fn(params, xs, ys)

    ref_loss, ref_grads = jax.value_and_grad(loss_fn)(params, x, y)
    # per-shard mean of losses == full-batch loss for MSE with equal shards
    np.testing.assert_allclose(float(loss), float(ref_loss), rtol=1e-5)
    masked = jax.tree_util.tree_map(
        lambda g, t: g if t else jnp.zeros_like(g), ref_grads, trainable
    )
    assert trees_allclose(grads, masked, rtol=1e-4, atol=1e-5)


def test_sharded_optimizer_adapter(mesh):
    """Reference test_sharded_optimizer intent: ZeRO-1 must track the
    unsharded optimizer tightly over several steps."""
    from cs336_systems_tpu.optim.adamw import AdamWHparams, adamw_init, adamw_update

    params, _ = toy_model_init(jax.random.PRNGKey(3))
    loss_fn = functools.partial(mse_loss, toy_model_apply)
    hp = AdamWHparams(lr=1e-2)
    zstate, step = adapters.get_sharded_optimizer(params, mesh, hp=hp, loss_fn=loss_fn)

    ref_params, ref_opt = params, adamw_init(params)
    x = jax.random.normal(jax.random.PRNGKey(4), (8, 10))
    y = jax.random.normal(jax.random.PRNGKey(5), (8, 5))
    xs, ys = shard_batch(mesh, x, y)
    for _ in range(10):
        params, zstate, _ = step(params, zstate, xs, ys)
        _, g = jax.value_and_grad(loss_fn)(ref_params, x, y)
        ref_params, ref_opt = adamw_update(ref_params, g, ref_opt, hp)
    assert trees_allclose(params, ref_params, rtol=1e-5, atol=1e-6)
