"""FSDP (ZeRO-3) exactness and memory tests.

Same oracle discipline as test_zero.py / the reference's
test_sharded_optimizer.py: the fully-sharded step must track an unsharded
AdamW run at tight tolerance, because the index-sharded update is
elementwise and therefore bit-faithful by construction. Plus: DP-style
batch sharding equivalence against the single-device full-batch step, the
persistent-memory claim, and the gather/eval round-trip.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from cs336_systems_tpu.optim.adamw import AdamWHparams, adamw_init, adamw_update
from cs336_systems_tpu.parallel.fsdp import (
    fsdp_gather_params,
    fsdp_init,
    fsdp_state_bytes,
    make_fsdp_step_for,
    make_fsdp_train_step,
)
from cs336_systems_tpu.parallel.mesh import make_mesh, shard_batch

from common import mse_loss, toy_model_apply, toy_model_init, trees_allclose

WORLD = 2
STEPS = 10


@pytest.fixture(scope="module")
def mesh():
    return make_mesh({"dp": WORLD}, devices=jax.devices()[:WORLD])


def test_fsdp_matches_unsharded_adamw(mesh):
    """Identical replicas, identical batches: 10 fully-sharded AdamW steps
    must agree tightly with the unsharded optimizer."""
    params, _ = toy_model_init(jax.random.PRNGKey(0))
    hp = AdamWHparams(lr=1e-3, weight_decay=0.01)
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.standard_normal((8, 10)).astype(np.float32))
    y = jnp.asarray(rng.standard_normal((8, 5)).astype(np.float32))

    loss_fn = lambda p, xx, yy: mse_loss(toy_model_apply, p, xx, yy)

    p_ref, opt = params, adamw_init(params)
    for _ in range(STEPS):
        grads = jax.grad(loss_fn)(p_ref, x, y)
        p_ref, opt = adamw_update(p_ref, grads, opt, hp)

    step = make_fsdp_step_for(loss_fn, hp, mesh, params_like=params)
    state = fsdp_init(params, mesh)
    xs, ys = shard_batch(mesh, jnp.concatenate([x, x]), jnp.concatenate([y, y]))
    for _ in range(STEPS):
        state, loss = step(state, xs, ys)

    p_fsdp = fsdp_gather_params(state, params)
    assert trees_allclose(p_fsdp, p_ref, rtol=1e-5, atol=1e-6)


def test_fsdp_dp_equivalence_vs_single_device(mesh):
    """Sharded batches: FSDP over a DP=2 mesh must track the single-device
    full-batch step (mean-loss gradients average across shards)."""
    params, _ = toy_model_init(jax.random.PRNGKey(1))
    hp = AdamWHparams(lr=1e-3, weight_decay=0.01)
    rng = np.random.default_rng(1)
    x = jnp.asarray(rng.standard_normal((16, 10)).astype(np.float32))
    y = jnp.asarray(rng.standard_normal((16, 5)).astype(np.float32))

    loss_fn = lambda p, xx, yy: mse_loss(toy_model_apply, p, xx, yy)

    p_ref, opt = params, adamw_init(params)
    for _ in range(5):
        grads = jax.grad(loss_fn)(p_ref, x, y)
        p_ref, opt = adamw_update(p_ref, grads, opt, hp)

    step = make_fsdp_step_for(loss_fn, hp, mesh, params_like=params)
    state = fsdp_init(params, mesh)
    xs, ys = shard_batch(mesh, x, y)
    for _ in range(5):
        state, loss = step(state, xs, ys)

    p_fsdp = fsdp_gather_params(state, params)
    assert trees_allclose(p_fsdp, p_ref, rtol=1e-5, atol=1e-6)


def test_fsdp_state_is_sharded_and_small(mesh):
    params, _ = toy_model_init(jax.random.PRNGKey(0))
    state = fsdp_init(params, mesh)
    n = sum(l.size for l in jax.tree_util.tree_leaves(params))
    chunk = -(-n // WORLD)
    assert state["p"].shape == (WORLD, chunk)
    # each device holds exactly one row of each buffer
    for buf in (state["p"], state["m"], state["v"]):
        assert len(buf.sharding.device_set) == WORLD
        for shard in buf.addressable_shards:
            assert shard.data.shape == (1, chunk)
    assert fsdp_state_bytes(params, WORLD) == 3 * 4 * chunk


def test_fsdp_lm_train_step_runs_and_learns(mesh):
    """End-to-end LM smoke on the mesh: loss decreases over a few steps."""
    from cs336_systems_tpu.models.transformer import TransformerConfig
    from cs336_systems_tpu.train import init_train_state

    cfg = TransformerConfig(
        vocab_size=64, context_length=32, d_model=32, num_layers=2,
        num_heads=2, d_ff=64,
    )
    params, _ = init_train_state(jax.random.PRNGKey(0), cfg)
    step = make_fsdp_train_step(
        cfg, AdamWHparams(lr=1e-2), mesh, params_like=params
    )
    state = fsdp_init(params, mesh)
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.integers(0, 64, (8, 32)), jnp.int32)
    y = jnp.roll(x, -1, axis=-1)
    xs, ys = shard_batch(mesh, x, y)
    losses = []
    for _ in range(20):
        state, loss = step(state, xs, ys)
        losses.append(float(loss))
    assert losses[-1] < losses[0] - 0.5, losses
