"""Shared test fixtures: toy models as pytrees.

Mirrors the reference fixtures (tests/common.py:24-68): ``ToyModel`` with a
non-trainable bias and a frozen parameter, and ``ToyModelWithTiedWeights``
where one weight is used by two layers.

JAX translation of the edge cases:
- *Frozen params* are expressed as a boolean ``trainable`` mask pytree; DP
  sync and optimizers must leave masked-out leaves untouched.
- *Tied weights* are one array referenced twice in the apply function —
  ``jax.grad`` then delivers a single summed gradient for the shared leaf,
  which the DP/ZeRO paths must keep consistent across replicas.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def toy_model_init(key):
    k1, k2, k3, k4 = jax.random.split(key, 4)
    params = {
        "fc1": {"weight": jax.random.normal(k1, (10, 10)) * 0.3},
        "fc2": {
            "weight": jax.random.normal(k2, (50, 10)) * 0.3,
            "bias": jax.random.normal(k4, (50,)) * 0.1,  # frozen
        },
        "fc3": {"weight": jax.random.normal(k3, (5, 50)) * 0.3},
        "no_grad_fixed_param": jnp.array([2.0, 2.0]),  # frozen
    }
    trainable = {
        "fc1": {"weight": True},
        "fc2": {"weight": True, "bias": False},
        "fc3": {"weight": True},
        "no_grad_fixed_param": False,
    }
    return params, trainable


def toy_model_apply(params, x):
    x = jax.nn.relu(x @ params["fc1"]["weight"].T)
    x = jax.nn.relu(x @ params["fc2"]["weight"].T + params["fc2"]["bias"])
    return x @ params["fc3"]["weight"].T


def tied_model_init(key):
    ks = jax.random.split(key, 4)
    params = {
        "fc1": {"weight": jax.random.normal(ks[0], (10, 10)) * 0.3},
        "fc2": {"weight": jax.random.normal(ks[1], (50, 10)) * 0.3},  # also used as fc4
        "fc3": {"weight": jax.random.normal(ks[2], (10, 50)) * 0.3},
        "fc5": {"weight": jax.random.normal(ks[3], (5, 50)) * 0.3},
    }
    trainable = jax.tree_util.tree_map(lambda _: True, params)
    return params, trainable


def tied_model_apply(params, x):
    w_tied = params["fc2"]["weight"]
    x = jax.nn.relu(x @ params["fc1"]["weight"].T)
    x = jax.nn.relu(x @ w_tied.T)
    x = jax.nn.relu(x @ params["fc3"]["weight"].T)
    x = jax.nn.relu(x @ w_tied.T)  # tied reuse (fc4.weight = fc2.weight)
    return x @ params["fc5"]["weight"].T


def mse_loss(apply_fn, params, x, y):
    pred = apply_fn(params, x)
    return jnp.mean(jnp.square(pred - y))


def trees_allclose(a, b, rtol=1e-5, atol=1e-6) -> bool:
    leaves_a = jax.tree_util.tree_leaves(a)
    leaves_b = jax.tree_util.tree_leaves(b)
    if len(leaves_a) != len(leaves_b):
        return False
    return all(
        jnp.allclose(x, y, rtol=rtol, atol=atol) for x, y in zip(leaves_a, leaves_b)
    )
