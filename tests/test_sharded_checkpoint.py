"""Sharded checkpoint/resume exactness (ZeRO-1 and FSDP).

Oracle pattern (tests/test_zero.py / reference test_sharded_optimizer.py):
a run interrupted at step k — state saved through utils.checkpoint, loaded,
re-placed on the mesh — must continue to the same final state as an
uninterrupted run on identical batches. Also covers ELASTIC resume: the
index-sharded [world, chunk] layout is world-size-invariant as a flat
vector, so a checkpoint taken on dp=8 restores onto dp=4 by re-chunking
(parallel.zero.rechunk_rows) and continues to the same math.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from common import trees_allclose
from cs336_systems_tpu.models.transformer import TransformerConfig, init_transformer_lm
from cs336_systems_tpu.optim.adamw import AdamWHparams
from cs336_systems_tpu.parallel.fsdp import (
    fsdp_gather_params,
    fsdp_init,
    fsdp_restore,
    make_fsdp_train_step,
)
from cs336_systems_tpu.parallel.mesh import make_mesh, shard_batch
from cs336_systems_tpu.parallel.zero import (
    make_zero1_train_step,
    zero1_init,
    zero1_restore,
)
from cs336_systems_tpu.utils.checkpoint import load_checkpoint, save_checkpoint

CFG = TransformerConfig(
    vocab_size=64, context_length=32, d_model=64,
    num_layers=2, num_heads=4, d_ff=128,
)
HP = AdamWHparams(lr=1e-3)


def _batches(n, batch=8):
    out = []
    for i in range(n):
        x = jax.random.randint(
            jax.random.PRNGKey(100 + i), (batch, CFG.context_length), 0,
            CFG.vocab_size,
        )
        out.append((x, jnp.roll(x, -1, axis=-1)))
    return out


def _roundtrip(tmp_path, params, opt):
    """Host round-trip through the on-disk format (np arrays back)."""
    save_checkpoint(str(tmp_path), params, config=CFG, opt_state=opt, step=3)
    return load_checkpoint(str(tmp_path))


def test_zero1_checkpoint_resume_exact(tmp_path):
    mesh = make_mesh({"dp": 8})
    step = make_zero1_train_step(CFG, HP, mesh, donate=False)
    batches = [tuple(shard_batch(mesh, x, y)) for x, y in _batches(6)]

    params = init_transformer_lm(jax.random.PRNGKey(0), CFG)
    z = zero1_init(params, mesh)
    p_ref, z_ref = params, z
    for x, y in batches:
        p_ref, z_ref, _ = step(p_ref, z_ref, x, y)

    # interrupted run: 3 steps, save, load+restore, 3 more
    p, z = params, zero1_init(params, mesh)
    for x, y in batches[:3]:
        p, z, _ = step(p, z, x, y)
    ck = _roundtrip(tmp_path, p, z)
    assert ck["step"] == 3
    p2 = ck["params"]
    z2 = zero1_restore(ck["opt_state"], p2, mesh)
    for x, y in batches[3:]:
        p2, z2, _ = step(p2, z2, x, y)

    assert trees_allclose(p2, p_ref, rtol=0, atol=0)  # bitwise
    np.testing.assert_array_equal(np.asarray(z2["m"]), np.asarray(z_ref["m"]))
    np.testing.assert_array_equal(np.asarray(z2["t"]), np.asarray(z_ref["t"]))


def test_zero1_elastic_resume_different_world(tmp_path):
    """dp=8 checkpoint resumed on a dp=4 mesh: identical update math (the
    chunked AdamW is elementwise), only collective reduction order differs
    — the ZeRO equivalence tolerance applies (tests/test_zero.py)."""
    mesh8 = make_mesh({"dp": 8})
    mesh4 = make_mesh({"dp": 4}, devices=jax.devices()[:4])
    step8 = make_zero1_train_step(CFG, HP, mesh8, donate=False)
    step4 = make_zero1_train_step(CFG, HP, mesh4, donate=False)
    raw = _batches(6)

    params = init_transformer_lm(jax.random.PRNGKey(0), CFG)
    p_ref, z_ref = params, zero1_init(params, mesh8)
    for x, y in raw:
        xs, ys = shard_batch(mesh8, x, y)
        p_ref, z_ref, _ = step8(p_ref, z_ref, xs, ys)

    p, z = params, zero1_init(params, mesh8)
    for x, y in raw[:3]:
        xs, ys = shard_batch(mesh8, x, y)
        p, z, _ = step8(p, z, xs, ys)
    ck = _roundtrip(tmp_path, p, z)
    p2 = ck["params"]
    z2 = zero1_restore(ck["opt_state"], p2, mesh4)  # re-chunked 8 -> 4
    assert z2["m"].shape[0] == 4
    for x, y in raw[3:]:
        xs, ys = shard_batch(mesh4, x, y)
        p2, z2, _ = step4(p2, z2, xs, ys)

    # compare on host: the two trees live on different-size device meshes
    assert trees_allclose(
        jax.device_get(p2), jax.device_get(p_ref), rtol=1e-6, atol=1e-7
    )


def test_fsdp_checkpoint_resume_exact(tmp_path):
    mesh = make_mesh({"dp": 8})
    params_like = jax.eval_shape(
        lambda k: init_transformer_lm(k, CFG), jax.random.PRNGKey(0)
    )
    step = make_fsdp_train_step(CFG, HP, mesh, params_like=params_like,
                                donate=False)
    batches = [tuple(shard_batch(mesh, x, y)) for x, y in _batches(6)]

    params = init_transformer_lm(jax.random.PRNGKey(0), CFG)
    s_ref = fsdp_init(params, mesh)
    for x, y in batches:
        s_ref, _ = step(s_ref, x, y)

    s = fsdp_init(params, mesh)
    for x, y in batches[:3]:
        s, _ = step(s, x, y)
    ck = _roundtrip(tmp_path, fsdp_gather_params(s, params_like), s)
    s2 = fsdp_restore(ck["opt_state"], params_like, mesh)
    for x, y in batches[3:]:
        s2, _ = step(s2, x, y)

    for k in ("p", "m", "v", "t"):
        np.testing.assert_array_equal(
            np.asarray(s2[k]), np.asarray(s_ref[k]), err_msg=k
        )


def test_rechunk_rows_rejects_wrong_model():
    from cs336_systems_tpu.parallel.zero import rechunk_rows

    with pytest.raises(ValueError, match="does not match"):
        rechunk_rows(np.zeros((8, 4)), 100, 4)  # 32 elements, needs 100
    with pytest.raises(ValueError, match="does not match"):
        # a LARGER model's state must not be silently truncated: 64
        # elements for n=32 exceeds the <1-element-per-row padding bound
        rechunk_rows(np.zeros((8, 8)), 32, 4)
    # legitimate padding passes: n=30 over 8 rows pads 2 (< 8)
    out = rechunk_rows(np.arange(32, dtype=np.float32).reshape(8, 4), 30, 4)
    assert out.shape == (4, 8)
    np.testing.assert_array_equal(out.reshape(-1)[:30], np.arange(30))


# -- atomic versioned store + typed verification (ISSUE 11) -------------

import json
import os
import shutil

from cs336_systems_tpu.utils.checkpoint import (
    _FAULT_HOOK,  # noqa: F401 — imported to assert the seam exists
    find_latest_intact,
    verify_checkpoint,
)
from cs336_systems_tpu.utils import checkpoint as ckpt_mod
from cs336_systems_tpu.utils.errors import (
    ConfigMismatch,
    DigestMismatch,
    NoIntactCheckpoint,
    TornCheckpoint,
)

_P1 = {"w": np.arange(16, dtype=np.float32).reshape(4, 4)}
_P2 = {"w": np.arange(16, dtype=np.float32).reshape(4, 4) + 1}
_OPT = {"m": np.zeros((4, 4), np.float32), "t": np.int32(3)}


def _newest(root):
    name = sorted(e for e in os.listdir(root) if e.startswith("step-"))[-1]
    return os.path.join(root, name)


def test_save_publishes_versioned_dir_with_manifest(tmp_path):
    root = str(tmp_path)
    final = save_checkpoint(root, _P1, config=CFG, opt_state=_OPT, step=3)
    assert os.path.basename(final) == "step-00000003"
    man = verify_checkpoint(final)
    assert man["step"] == 3
    assert set(man["files"]) == {
        "model_config.json", "params.npz", "opt_state.npz", "step.json"}
    with open(os.path.join(root, "LATEST")) as f:
        assert f.read().strip() == "step-00000003"
    assert not [e for e in os.listdir(root) if e.startswith(".tmp-")]


def test_stale_sibling_regression(tmp_path):
    """The pre-ISSUE-11 store wrote files into ONE live dir: a later
    params-only save left the previous opt_state.npz/step.json behind,
    so --resume silently paired new params with old optimizer state.
    Versioned saves make the pairing impossible by construction."""
    root = str(tmp_path)
    save_checkpoint(root, _P1, config=CFG, opt_state=_OPT, step=1)
    save_checkpoint(root, _P2, config=CFG, step=2)  # params-only
    ck = load_checkpoint(root)
    np.testing.assert_array_equal(ck["params"]["w"], _P2["w"])
    assert ck["step"] == 2
    assert ck["opt_state"] is None  # NOT step 1's stale optimizer rows


def test_kill_between_any_two_writes_leaves_intact_store(tmp_path):
    """Interrupt the step-6 save at EVERY durability boundary: the store
    must always resolve to a verifiable checkpoint (step 3 before
    publish, step 6 after), and the torn temp must raise typed."""
    points = ["file:model_config.json", "file:params.npz",
              "file:opt_state.npz", "file:step.json", "file:manifest.json",
              "published", "latest"]
    for point in points:
        root = str(tmp_path / point.replace(":", "-"))
        save_checkpoint(root, _P1, config=CFG, opt_state=_OPT, step=3)

        def hook(event, _point=point):
            if event == _point:
                raise RuntimeError(f"injected kill at {_point}")

        ckpt_mod._FAULT_HOOK = hook
        try:
            with pytest.raises(RuntimeError, match="injected kill"):
                save_checkpoint(
                    root, _P2, config=CFG, opt_state=_OPT, step=6)
        finally:
            ckpt_mod._FAULT_HOOK = None
        want = 3 if point.startswith("file:") else 6
        path, step = find_latest_intact(root)
        assert step == want, point
        ck = load_checkpoint(path)
        assert ck["step"] == want, point
        torn = [e for e in os.listdir(root) if e.startswith(".tmp-")]
        if point.startswith("file:"):
            assert torn, point
            with pytest.raises(TornCheckpoint):
                load_checkpoint(os.path.join(root, torn[0]))
        # root-level load never sees the torn temp; in the publish→pointer
        # kill window it follows the stale-but-VALID LATEST (step 3) while
        # find_latest_intact already sees the published step 6
        want_root = 3 if point == "published" else want
        assert load_checkpoint(root)["step"] == want_root, point


def test_truncated_and_byteflip_raise_typed_and_fall_back(tmp_path):
    root = str(tmp_path)
    save_checkpoint(root, _P1, config=CFG, opt_state=_OPT, step=3)
    save_checkpoint(root, _P2, config=CFG, opt_state=_OPT, step=6)

    # truncate the newest params.npz mid-file -> TornCheckpoint
    target = os.path.join(_newest(root), "params.npz")
    keep = os.path.getsize(target)
    with open(target, "r+b") as f:
        f.truncate(keep // 2)
    with pytest.raises(TornCheckpoint, match="truncated"):
        load_checkpoint(root)
    path, step = find_latest_intact(root)
    assert step == 3
    np.testing.assert_array_equal(
        load_checkpoint(path)["params"]["w"], _P1["w"])

    # same-size byte flip -> DigestMismatch (content, not structure)
    save_checkpoint(root, _P2, config=CFG, opt_state=_OPT, step=6)
    with open(target, "r+b") as f:
        data = f.read()
        f.seek(len(data) // 2)
        f.write(bytes([data[len(data) // 2] ^ 0xFF]))
    with pytest.raises(DigestMismatch, match="digest mismatch"):
        load_checkpoint(root)
    assert find_latest_intact(root)[1] == 3


def test_zero1_fallback_restores_on_mesh_after_corruption(tmp_path):
    """The dp/zero1 side of the satellite: damage the newest version of
    a real zero1 run's store and prove the typed error + walk-back +
    [world, chunk] re-placement all compose."""
    mesh = make_mesh({"dp": 8})
    step = make_zero1_train_step(CFG, HP, mesh, donate=False)
    batches = [tuple(shard_batch(mesh, x, y)) for x, y in _batches(4)]
    params = init_transformer_lm(jax.random.PRNGKey(0), CFG)
    p, z = params, zero1_init(params, mesh)
    root = str(tmp_path)
    for i, (x, y) in enumerate(batches):
        p, z, _ = step(p, z, x, y)
        save_checkpoint(root, p, config=CFG, opt_state=z, step=i + 1)
    # corrupt the newest (step 4): resume must fall back to step 3
    target = os.path.join(_newest(root), "opt_state.npz")
    with open(target, "r+b") as f:
        data = f.read()
        f.seek(len(data) // 2)
        f.write(bytes([data[len(data) // 2] ^ 0xFF]))
    with pytest.raises(DigestMismatch):
        load_checkpoint(root)
    path, fb = find_latest_intact(root)
    assert fb == 3
    ck = load_checkpoint(path)
    z2 = zero1_restore(ck["opt_state"], ck["params"], mesh)
    assert z2["m"].shape[0] == 8  # re-placed [world, chunk] rows
    p2, z2, _ = step(ck["params"], z2, *batches[3])
    assert trees_allclose(p2, p, rtol=0, atol=0)  # replay == original


def test_config_mismatch_is_typed_and_not_retriable(tmp_path):
    root = str(tmp_path)
    save_checkpoint(root, _P1, config=CFG, step=1)
    import dataclasses

    other = dataclasses.replace(CFG, d_model=128)
    with pytest.raises(ConfigMismatch, match="different model config") as ei:
        load_checkpoint(root, expect_config=other)
    assert ei.value.retriable is False
    # the matching config still loads
    assert load_checkpoint(root, expect_config=CFG)["step"] == 1


def test_retention_ring_prunes_oldest(tmp_path):
    root = str(tmp_path)
    for i in range(1, 6):
        save_checkpoint(root, _P1, config=CFG, step=i, keep=2)
    steps = sorted(int(e.split("-")[1]) for e in os.listdir(root)
                   if e.startswith("step-"))
    assert steps == [4, 5]
    assert load_checkpoint(root)["step"] == 5


def test_old_format_dir_still_loads(tmp_path):
    """Compat shim: a pre-ISSUE-11 flat checkpoint dir (params.npz at
    top level, no manifest) loads unverified, and counts as the
    walk-back floor."""
    root = str(tmp_path)
    np.savez(os.path.join(root, "params.npz"),
             **{"w": _P1["w"]})
    np.savez(os.path.join(root, "opt_state.npz"),
             **{"m": _OPT["m"]})
    with open(os.path.join(root, "step.json"), "w") as f:
        json.dump({"step": 7}, f)
    ck = load_checkpoint(root)
    np.testing.assert_array_equal(ck["params"]["w"], _P1["w"])
    assert ck["step"] == 7
    assert find_latest_intact(root)[1] == 7


def test_empty_store_raises_no_intact(tmp_path):
    root = str(tmp_path / "empty")
    os.makedirs(root)
    with pytest.raises(NoIntactCheckpoint):
        load_checkpoint(root)
    with pytest.raises(NoIntactCheckpoint):
        find_latest_intact(root)


def test_stale_latest_pointer_raises_then_falls_back(tmp_path):
    root = str(tmp_path)
    save_checkpoint(root, _P1, config=CFG, step=1)
    save_checkpoint(root, _P2, config=CFG, step=2)
    shutil.rmtree(_newest(root))  # LATEST now dangles at step-2
    with pytest.raises(TornCheckpoint, match="LATEST points at missing"):
        load_checkpoint(root)
    path, step = find_latest_intact(root)
    assert step == 1
    np.testing.assert_array_equal(
        load_checkpoint(path)["params"]["w"], _P1["w"])
