"""Sharded checkpoint/resume exactness (ZeRO-1 and FSDP).

Oracle pattern (tests/test_zero.py / reference test_sharded_optimizer.py):
a run interrupted at step k — state saved through utils.checkpoint, loaded,
re-placed on the mesh — must continue to the same final state as an
uninterrupted run on identical batches. Also covers ELASTIC resume: the
index-sharded [world, chunk] layout is world-size-invariant as a flat
vector, so a checkpoint taken on dp=8 restores onto dp=4 by re-chunking
(parallel.zero.rechunk_rows) and continues to the same math.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from common import trees_allclose
from cs336_systems_tpu.models.transformer import TransformerConfig, init_transformer_lm
from cs336_systems_tpu.optim.adamw import AdamWHparams
from cs336_systems_tpu.parallel.fsdp import (
    fsdp_gather_params,
    fsdp_init,
    fsdp_restore,
    make_fsdp_train_step,
)
from cs336_systems_tpu.parallel.mesh import make_mesh, shard_batch
from cs336_systems_tpu.parallel.zero import (
    make_zero1_train_step,
    zero1_init,
    zero1_restore,
)
from cs336_systems_tpu.utils.checkpoint import load_checkpoint, save_checkpoint

CFG = TransformerConfig(
    vocab_size=64, context_length=32, d_model=64,
    num_layers=2, num_heads=4, d_ff=128,
)
HP = AdamWHparams(lr=1e-3)


def _batches(n, batch=8):
    out = []
    for i in range(n):
        x = jax.random.randint(
            jax.random.PRNGKey(100 + i), (batch, CFG.context_length), 0,
            CFG.vocab_size,
        )
        out.append((x, jnp.roll(x, -1, axis=-1)))
    return out


def _roundtrip(tmp_path, params, opt):
    """Host round-trip through the on-disk format (np arrays back)."""
    save_checkpoint(str(tmp_path), params, config=CFG, opt_state=opt, step=3)
    return load_checkpoint(str(tmp_path))


def test_zero1_checkpoint_resume_exact(tmp_path):
    mesh = make_mesh({"dp": 8})
    step = make_zero1_train_step(CFG, HP, mesh, donate=False)
    batches = [tuple(shard_batch(mesh, x, y)) for x, y in _batches(6)]

    params = init_transformer_lm(jax.random.PRNGKey(0), CFG)
    z = zero1_init(params, mesh)
    p_ref, z_ref = params, z
    for x, y in batches:
        p_ref, z_ref, _ = step(p_ref, z_ref, x, y)

    # interrupted run: 3 steps, save, load+restore, 3 more
    p, z = params, zero1_init(params, mesh)
    for x, y in batches[:3]:
        p, z, _ = step(p, z, x, y)
    ck = _roundtrip(tmp_path, p, z)
    assert ck["step"] == 3
    p2 = ck["params"]
    z2 = zero1_restore(ck["opt_state"], p2, mesh)
    for x, y in batches[3:]:
        p2, z2, _ = step(p2, z2, x, y)

    assert trees_allclose(p2, p_ref, rtol=0, atol=0)  # bitwise
    np.testing.assert_array_equal(np.asarray(z2["m"]), np.asarray(z_ref["m"]))
    np.testing.assert_array_equal(np.asarray(z2["t"]), np.asarray(z_ref["t"]))


def test_zero1_elastic_resume_different_world(tmp_path):
    """dp=8 checkpoint resumed on a dp=4 mesh: identical update math (the
    chunked AdamW is elementwise), only collective reduction order differs
    — the ZeRO equivalence tolerance applies (tests/test_zero.py)."""
    mesh8 = make_mesh({"dp": 8})
    mesh4 = make_mesh({"dp": 4}, devices=jax.devices()[:4])
    step8 = make_zero1_train_step(CFG, HP, mesh8, donate=False)
    step4 = make_zero1_train_step(CFG, HP, mesh4, donate=False)
    raw = _batches(6)

    params = init_transformer_lm(jax.random.PRNGKey(0), CFG)
    p_ref, z_ref = params, zero1_init(params, mesh8)
    for x, y in raw:
        xs, ys = shard_batch(mesh8, x, y)
        p_ref, z_ref, _ = step8(p_ref, z_ref, xs, ys)

    p, z = params, zero1_init(params, mesh8)
    for x, y in raw[:3]:
        xs, ys = shard_batch(mesh8, x, y)
        p, z, _ = step8(p, z, xs, ys)
    ck = _roundtrip(tmp_path, p, z)
    p2 = ck["params"]
    z2 = zero1_restore(ck["opt_state"], p2, mesh4)  # re-chunked 8 -> 4
    assert z2["m"].shape[0] == 4
    for x, y in raw[3:]:
        xs, ys = shard_batch(mesh4, x, y)
        p2, z2, _ = step4(p2, z2, xs, ys)

    # compare on host: the two trees live on different-size device meshes
    assert trees_allclose(
        jax.device_get(p2), jax.device_get(p_ref), rtol=1e-6, atol=1e-7
    )


def test_fsdp_checkpoint_resume_exact(tmp_path):
    mesh = make_mesh({"dp": 8})
    params_like = jax.eval_shape(
        lambda k: init_transformer_lm(k, CFG), jax.random.PRNGKey(0)
    )
    step = make_fsdp_train_step(CFG, HP, mesh, params_like=params_like,
                                donate=False)
    batches = [tuple(shard_batch(mesh, x, y)) for x, y in _batches(6)]

    params = init_transformer_lm(jax.random.PRNGKey(0), CFG)
    s_ref = fsdp_init(params, mesh)
    for x, y in batches:
        s_ref, _ = step(s_ref, x, y)

    s = fsdp_init(params, mesh)
    for x, y in batches[:3]:
        s, _ = step(s, x, y)
    ck = _roundtrip(tmp_path, fsdp_gather_params(s, params_like), s)
    s2 = fsdp_restore(ck["opt_state"], params_like, mesh)
    for x, y in batches[3:]:
        s2, _ = step(s2, x, y)

    for k in ("p", "m", "v", "t"):
        np.testing.assert_array_equal(
            np.asarray(s2[k]), np.asarray(s_ref[k]), err_msg=k
        )


def test_rechunk_rows_rejects_wrong_model():
    from cs336_systems_tpu.parallel.zero import rechunk_rows

    with pytest.raises(ValueError, match="does not match"):
        rechunk_rows(np.zeros((8, 4)), 100, 4)  # 32 elements, needs 100
    with pytest.raises(ValueError, match="does not match"):
        # a LARGER model's state must not be silently truncated: 64
        # elements for n=32 exceeds the <1-element-per-row padding bound
        rechunk_rows(np.zeros((8, 8)), 32, 4)
    # legitimate padding passes: n=30 over 8 rows pads 2 (< 8)
    out = rechunk_rows(np.arange(32, dtype=np.float32).reshape(8, 4), 30, 4)
    assert out.shape == (4, 8)
    np.testing.assert_array_equal(out.reshape(-1)[:30], np.arange(30))
