"""Prefix-cache tests (ISSUE 9): refcounted shared KV pages +
copy-on-write in the serving engine.

Three layers, matching the subsystem's stack:

- ``serving/pool.py`` shared-page regime: property-style checks that
  double-acquire / early-free / refcount-vs-table drift / spill-while-
  referenced all fail loud, and that ``check_conserved`` counts each
  shared page ONCE against the partition.
- ``serving/prefix_cache.py`` trie: chain-hash prefix property and
  fingerprint domain separation, the lookup cap that keeps >= 1 suffix
  token unless boundary logits are cached, publish-skip of already-
  cached blocks, and LRU spill order (a parent is never evicted before
  its children).
- The ENGINE contract: with the cache on, streams are bit-identical to
  the unshared engine AND the row-keyed oracle across join orders and
  on dp8 / dp2×tp4 meshes; N=8 requests sharing a P=4·page_block prefix
  allocate exactly P/page_block shared pages once (not N×) and prefill
  only the uncached tails; mid-block divergence takes the COW path; and
  pool pressure forces LRU spill without deadlocking admission — with
  ``check_conserved``/``check_all_free`` passing after every drain.
"""

import numpy as np
import pytest

import jax

from cs336_systems_tpu.models.decode import (
    generate_kv_batched,
    validate_block_tables,
)
from cs336_systems_tpu.models.transformer import (
    TransformerConfig,
    init_transformer_lm,
)
from cs336_systems_tpu.parallel.mesh import make_mesh
from cs336_systems_tpu.serving import (
    PagePool,
    PrefixCache,
    RefcountViolation,
    Request,
    ServingEngine,
)

CFG = TransformerConfig(
    vocab_size=64, context_length=64, d_model=64,
    num_layers=2, num_heads=4, d_ff=128,
)
BLK = 8
NEW = 6
PREFIX_BLOCKS = 4                      # the acceptance shape: P = 4·BLK
TAIL_LENS = [3, 5, 7, 2, 6, 4, 1, 7]   # all < BLK: only the prefix is
#                                        ever published as shared pages


@pytest.fixture(scope="module")
def params():
    return init_transformer_lm(jax.random.PRNGKey(1), CFG)


@pytest.fixture(scope="module")
def prompts():
    """8 prompts sharing a P=4·BLK-token prefix with distinct sub-block
    tails — the millions-of-users acceptance shape."""
    rng = np.random.default_rng(7)
    prefix = rng.integers(0, CFG.vocab_size, PREFIX_BLOCKS * BLK)
    return [np.concatenate([prefix, rng.integers(0, CFG.vocab_size, n)])
            .astype(np.int32) for n in TAIL_LENS]


def _oracle(params, prompts):
    """All rows in ONE row-keyed paged batch — the stream every engine
    (shared or not) must reproduce per request."""
    pmax = max(p.size for p in prompts)
    padded = np.zeros((len(prompts), pmax), np.int32)
    for i, p in enumerate(prompts):
        padded[i, :p.size] = p
    return generate_kv_batched(
        params, CFG, padded, NEW, jax.random.PRNGKey(0), temperature=0.9,
        top_k=8, row_keyed=True, prompt_lens=[p.size for p in prompts],
        page_block=BLK)


def _engine(params, **kw):
    base = dict(key=jax.random.PRNGKey(0), slots=8, n_pages=64,
                max_blocks=6, page_block=BLK, temperature=0.9, top_k=8)
    base.update(kw)
    return ServingEngine(params, CFG, **base)


def _run(eng, reqs):
    for r in reqs:
        eng.submit(r)
    tick = iter(np.arange(0.0, 1e5, 0.5))
    res = eng.run(time_fn=lambda: next(tick))
    eng.check_conserved()
    eng.check_idle()
    return res


# --- PagePool shared-page regime ---------------------------------------


class TestSharedPool:
    def test_shared_lifecycle_and_refcounts(self):
        pool = PagePool(4)
        pages = pool.alloc_shared(2, "tag")
        assert all(pool.refcount(p) == 0 for p in pages)
        pool.check_conserved()
        pool.acquire(pages, "r1")
        pool.acquire(pages, "r2")
        assert all(pool.refcount(p) == 2 for p in pages)
        pool.check_conserved(block_tables=[pages, pages])
        assert pool.release("r1") == 2
        assert all(pool.refcount(p) == 1 for p in pages)
        pool.release("r2")
        assert pool.drop_shared("tag") == 2
        pool.check_all_free()

    def test_double_acquire_raises(self):
        pool = PagePool(2)
        pages = pool.alloc_shared(1, "t")
        pool.acquire(pages, "r")
        with pytest.raises(ValueError, match="double acquire"):
            pool.acquire(pages, "r")
        pool.release("r")
        pool.drop_shared("t")
        pool.check_all_free()

    def test_early_and_double_release_raise(self):
        pool = PagePool(2)
        pages = pool.alloc_shared(1, "t")
        with pytest.raises(RefcountViolation, match="release"):
            pool.release("ghost")
        pool.acquire(pages, "r")
        pool.release("r")
        with pytest.raises(RefcountViolation, match="release"):
            pool.release("r")

    def test_acquire_of_unshared_page_raises(self):
        pool = PagePool(4)
        priv = pool.alloc(1, "a")
        with pytest.raises(ValueError, match="not a shared page"):
            pool.acquire(priv, "r")          # private page
        with pytest.raises(ValueError, match="not a shared page"):
            pool.acquire([pool._free[-1]], "r")  # free page

    def test_spill_while_referenced_raises(self):
        pool = PagePool(2)
        pages = pool.alloc_shared(1, "t")
        pool.acquire(pages, "r")
        with pytest.raises(ValueError, match="refcount"):
            pool.drop_shared("t")
        pool.release("r")
        pool.drop_shared("t")

    def test_promote_records_publisher_reference(self):
        pool = PagePool(4)
        priv = pool.alloc(3, "owner")
        pool.promote("owner", priv[:2], "t")
        assert pool.owned_by("owner") == priv[2:]
        assert pool.acquired_by("owner") == priv[:2]
        assert all(pool.refcount(p) == 1 for p in priv[:2])
        # the owner's block table holds promoted + remaining-private
        pool.check_conserved(block_tables=[priv])
        with pytest.raises(ValueError, match="cannot promote"):
            pool.promote("owner", [priv[0]], "t2")  # no longer private
        pool.free("owner")
        pool.release("owner")
        pool.drop_shared("t")
        pool.check_all_free()

    def test_refcount_vs_table_drift_detected(self):
        pool = PagePool(4)
        pages = pool.alloc_shared(1, "t")
        pool.acquire(pages, "r")
        # ISSUE 10: refcount drift is the typed RefcountViolation
        with pytest.raises(RefcountViolation, match="block tables"):
            pool.check_conserved(block_tables=[[3]])  # table lost the page

    def test_shared_counted_once_and_drain_gate(self):
        pool = PagePool(4)
        pages = pool.alloc_shared(2, "t")
        pool.acquire(pages, "r1")
        pool.acquire(pages, "r2")
        pool.check_conserved()               # 2 pages, counted once
        assert pool.available == 2
        pool.release("r1")
        pool.release("r2")
        with pytest.raises(AssertionError, match="spill the prefix cache"):
            pool.check_all_free()            # cached-but-unreferenced
        pool.drop_shared("t")
        pool.check_all_free()


# --- PrefixCache trie --------------------------------------------------


def _publish(cache, pool, prompt, owner, logits=None):
    """Simulate a completed prefill: private pages for every FULL block,
    then publish them."""
    n = len(prompt) // cache.block
    pages = pool.alloc(max(n, 1), owner)
    cache.publish(prompt, owner, dict(enumerate(pages[:n])), logits=logits)
    return pages


class TestPrefixTrie:
    def test_chain_hash_prefix_property_and_fingerprint(self):
        a = PrefixCache(PagePool(4), BLK, b"fp-a")
        b = PrefixCache(PagePool(4), BLK, b"fp-b")
        p1 = np.arange(3 * BLK + 2)
        p2 = np.concatenate([p1[:2 * BLK], 63 - p1[2 * BLK:]])
        h1, h2 = a.chain_hashes(p1), a.chain_hashes(p2)
        assert len(h1) == 3 and len(h2) == 3       # full blocks only
        assert h1[:2] == h2[:2] and h1[2] != h2[2]  # shared-prefix spine
        assert a.chain_hashes(p1) != b.chain_hashes(p1)  # model-keyed

    def test_lookup_caps_full_aligned_hit_without_logits(self):
        pool = PagePool(8)
        cache = PrefixCache(pool, BLK, b"fp")
        prompt = np.arange(2 * BLK, dtype=np.int32)
        _publish(cache, pool, prompt, "r0")
        hit, pages, logits = cache.lookup(prompt)
        assert (hit, len(pages), logits) == (1, 1, None)  # >= 1 token left
        hit, pages, _ = cache.lookup(np.concatenate([prompt, [5]]))
        assert hit == 2 and len(pages) == 2        # unaligned: full hit

    def test_boundary_logits_enable_full_hit(self):
        pool = PagePool(8)
        cache = PrefixCache(pool, BLK, b"fp")
        prompt = np.arange(2 * BLK, dtype=np.int32)
        row = np.full(CFG.vocab_size, 0.5, np.float32)
        _publish(cache, pool, prompt, "r0", logits=row)
        hit, pages, logits = cache.lookup(prompt)
        assert hit == 2 and len(pages) == 2
        np.testing.assert_array_equal(logits, row)

    def test_publish_skips_cached_blocks(self):
        pool = PagePool(8)
        cache = PrefixCache(pool, BLK, b"fp")
        prompt = np.arange(2 * BLK, dtype=np.int32)
        _publish(cache, pool, prompt, "r0")
        assert len(cache) == 2
        # r1 prefilled the same prompt before r0's publish landed: its
        # duplicate pages stay private, nothing new enters the trie
        pages = pool.alloc(2, "r1")
        assert cache.publish(prompt, "r1", dict(enumerate(pages))) == 0
        assert len(cache) == 2 and pool.owned_by("r1") == pages

    def test_spill_lru_order_keeps_trie_well_formed(self):
        pool = PagePool(16)
        cache = PrefixCache(pool, BLK, b"fp")
        old = np.arange(3 * BLK, dtype=np.int32)
        new = 63 - old
        _publish(cache, pool, old, "r0")
        _publish(cache, pool, new, "r1")
        for r in ("r0", "r1"):
            pool.release(r)                  # publishers evicted
        assert cache.spillable_pages() == 6
        assert cache.spill(2) == 2
        # LRU: the OLD chain spilled first, deepest node first — every
        # remaining node's parent is still present (well-formed trie)
        hashes = {n.h for n in cache._nodes.values()}
        for n in cache._nodes.values():
            assert n.parent is None or n.parent in hashes
        hit, _, _ = cache.lookup(np.concatenate([new, [1]]))
        assert hit == 3                      # the recent chain survived
        assert cache.drop_unreferenced() == 4
        pool.check_all_free()


# --- copy-on-write validation (models/decode) --------------------------


def test_validate_block_tables_rejects_shared_write():
    tables = np.array([[0, 1], [0, 2]], np.int32)
    ro = {0}
    # write block pos // BLK = 1 for both rows: private pages 1/2 — ok
    validate_block_tables(tables, n_pages=4, read_only=ro,
                          write_pos=np.array([10, 12]), block=BLK,
                          active=np.array([1, 1]))
    # row 1 rewound into the shared block: COW violation
    with pytest.raises(ValueError, match="read-only"):
        validate_block_tables(tables, n_pages=4, read_only=ro,
                              write_pos=np.array([10, 4]), block=BLK,
                              active=np.array([1, 1]))
    # the same position on an INACTIVE row writes only scratch — ok
    validate_block_tables(tables, n_pages=4, read_only=ro,
                          write_pos=np.array([10, 4]), block=BLK,
                          active=np.array([1, 0]))


# --- engine: accounting + bit-exactness --------------------------------


def test_shared_prefix_page_accounting(params, prompts):
    """THE acceptance criterion: N=8 requests sharing P=4·BLK tokens →
    exactly P/BLK shared pages allocated ONCE, prefill only on uncached
    tails, every later request's hit recorded on the request."""
    eng = _engine(params, prefix_cache=True)
    reqs = [Request(rid=i, prompt=p, max_new_tokens=NEW)
            for i, p in enumerate(prompts)]
    for r in reqs:
        eng.submit(r)
    tick = iter(np.arange(0.0, 1e5, 0.5))
    eng.run(time_fn=lambda: next(tick))
    eng.check_conserved()
    P = PREFIX_BLOCKS * BLK
    total_prompt = sum(p.size for p in prompts)
    # sub-block tails: the trie holds exactly the P/BLK prefix pages
    assert sum(len(c) for c in eng.prefix_caches) == PREFIX_BLOCKS
    assert eng.shared_kv_bytes_peak == PREFIX_BLOCKS * eng._page_bytes
    # one publisher prefilled the prefix; the other 7 hit all 4 blocks
    assert eng.prefix_hit_tokens == (len(prompts) - 1) * P
    assert eng.prefill_tokens == total_prompt - (len(prompts) - 1) * P
    assert eng.prefix_prompt_tokens == total_prompt
    hits = sorted(r.prefix_hit_tokens for r in reqs)
    assert hits == [0] + [P] * (len(prompts) - 1)
    eng.check_idle()                         # drops the cache, all free


@pytest.mark.parametrize("order", [
    list(range(8)),
    [5, 2, 7, 0, 3, 6, 1, 4],
    [7, 6, 5, 4, 3, 2, 1, 0],
], ids=["fifo", "shuffled", "reversed"])
def test_streams_bit_identical_across_join_orders(params, prompts, order):
    """Shared-prefix engine == unshared engine == row-keyed oracle, for
    every join order (staggered arrivals, half the slots so requests
    queue and join mid-flight into shared pages)."""
    want = np.asarray(_oracle(params, prompts))
    base = _run(_engine(params, prefix_cache=False),
                [Request(rid=r, prompt=prompts[r], max_new_tokens=NEW)
                 for r in range(len(prompts))])
    eng = _engine(params, slots=4, n_pages=32, prefix_cache=True)
    res = _run(eng, [Request(rid=r, prompt=prompts[r], max_new_tokens=NEW,
                             arrival=float(i) * 0.25)
                     for i, r in enumerate(order)])
    for r in range(len(prompts)):
        np.testing.assert_array_equal(res[r], want[r])
        np.testing.assert_array_equal(res[r], base[r])
    assert eng.prefix_hit_tokens > 0         # sharing actually happened


@pytest.mark.parametrize("mesh_axes,dp,tp", [
    ({"dp": 8}, "dp", None),
    ({"dp": 2, "tp": 4}, "dp", "tp"),
], ids=["dp8", "dp2xtp4"])
def test_streams_bit_identical_on_mesh(params, prompts, mesh_axes, dp, tp):
    """Shard-local prefix caches over shard-local pools: staggered
    shuffled arrivals on dp8 and dp2×tp4 still stream the oracle rows.
    TWO waves of the same prompts (wave 2 with ``row`` mapped back to
    the oracle rows): on dp8's one-slot shards sharing only happens
    ACROSS waves, so this also pins that a shard's cache survives its
    publisher's eviction and that hits land on every shard."""
    want = np.asarray(_oracle(params, prompts))
    eng = _engine(params, n_pages=8, mesh=make_mesh(mesh_axes),
                  dp_axis=dp, tp_axis=tp, prefix_cache=True)
    n = len(prompts)
    reqs = [Request(rid=w * n + r, prompt=prompts[r], max_new_tokens=NEW,
                    row=r, arrival=float(w * n + i) * 0.25)
            for w in range(2)
            for i, r in enumerate([4, 1, 6, 0, 7, 2, 5, 3])]
    res = _run(eng, reqs)
    for w in range(2):
        for r in range(n):
            np.testing.assert_array_equal(res[w * n + r], want[r])
    assert eng.prefix_hit_tokens > 0


def test_cow_midblock_divergence(params, prompts):
    """A prompt that diverges INSIDE a published block shares only the
    blocks before the divergence; the divergent partial block is private
    (COW) and the stream still matches the unshared engine."""
    base_prompt = prompts[0]                 # prefix + 3-token tail
    mid = np.concatenate([base_prompt[:PREFIX_BLOCKS * BLK - 4],
                          (63 - base_prompt[PREFIX_BLOCKS * BLK - 4:
                                            PREFIX_BLOCKS * BLK + 2])])
    pair = [base_prompt, mid.astype(np.int32)]
    want = _run(_engine(params, prefix_cache=False),
                [Request(rid=i, prompt=p, max_new_tokens=NEW)
                 for i, p in enumerate(pair)])
    eng = _engine(params, prefix_cache=True)
    reqs = [Request(rid=i, prompt=p, max_new_tokens=NEW,
                    arrival=float(i)) for i, p in enumerate(pair)]
    res = _run(eng, reqs)
    for i in range(2):
        np.testing.assert_array_equal(res[i], want[i])
    # diverged 4 tokens into block 3: only blocks 0..2 hit
    assert reqs[1].prefix_hit_tokens == (PREFIX_BLOCKS - 1) * BLK


def test_boundary_logits_join_with_zero_prefill(params, prompts):
    """Identical prompt ending exactly at a block boundary: the second
    request replays the publisher's boundary logits and joins with ZERO
    prefill — and still streams the unshared engine's tokens."""
    prompt = prompts[0][:PREFIX_BLOCKS * BLK]  # block-aligned
    pair = [Request(rid=i, prompt=prompt, max_new_tokens=NEW,
                    arrival=float(i)) for i in range(2)]
    want = _run(_engine(params, prefix_cache=False),
                [Request(rid=i, prompt=prompt, max_new_tokens=NEW)
                 for i in range(2)])
    eng = _engine(params, prefix_cache=True)
    res = _run(eng, pair)
    for i in range(2):
        np.testing.assert_array_equal(res[i], want[i])
    assert eng.prefill_tokens == prompt.size   # paid once, not twice
    assert pair[1].prefix_hit_tokens == prompt.size


def test_lru_spill_under_pool_pressure(params):
    """Two prefix families through a pool too small to cache both:
    admission spills the LRU prefix instead of deadlocking, streams stay
    bit-identical to the unshared engine, and the drain leaves every
    page free."""
    rng = np.random.default_rng(11)
    fam_a = rng.integers(0, CFG.vocab_size, 2 * BLK)
    fam_b = rng.integers(0, CFG.vocab_size, 2 * BLK)
    reqs = []
    for i, fam in enumerate([fam_a, fam_a, fam_b, fam_b, fam_a, fam_b]):
        tail = rng.integers(0, CFG.vocab_size, 3)
        reqs.append(np.concatenate([fam, tail]).astype(np.int32))
    make = lambda: [Request(rid=i, prompt=p, max_new_tokens=NEW,
                            arrival=float(i)) for i, p in enumerate(reqs)]
    want = _run(_engine(params, prefix_cache=False, slots=1, n_pages=4,
                        max_blocks=4), make())
    # 4 pages/request (2 prefix + tail + growth), 5-page pool: caching a
    # 2-page prefix leaves 3 free — the next foreign-prefix request MUST
    # spill the cached family to fit
    eng = _engine(params, prefix_cache=True, slots=1, n_pages=5,
                  max_blocks=4)
    res = _run(eng, make())
    for i in range(len(reqs)):
        np.testing.assert_array_equal(res[i], want[i])
    assert sum(c.spilled_pages_total for c in eng.prefix_caches) > 0
