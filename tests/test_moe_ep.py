"""MoE layer and expert-parallelism tests.

Oracles: the dense SwiGLU (a 1-expert MoE must reduce to it exactly) and
the unsharded MoE step (ep sharding is a layout, not an approximation).
Runs on the 8-virtual-device CPU mesh (conftest).
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from common import trees_allclose
from cs336_systems_tpu.models.layers import init_swiglu, swiglu
from cs336_systems_tpu.models.moe import (
    init_moe,
    moe_capacity,
    moe_ffn,
    route_topk,
)
from cs336_systems_tpu.models.transformer import (
    TransformerConfig,
    init_transformer_lm,
    transformer_lm_with_aux,
)
from cs336_systems_tpu.optim.adamw import AdamWHparams, adamw_init
from cs336_systems_tpu.parallel.ep import (
    make_ep_train_step,
    shard_params_ep,
    validate_ep,
)
from cs336_systems_tpu.parallel.mesh import make_mesh
from cs336_systems_tpu.train import init_train_state, make_train_step

MOE_CFG = TransformerConfig(
    vocab_size=64, context_length=32, d_model=32,
    num_layers=2, num_heads=4, d_ff=64,
    num_experts=8, moe_top_k=2,
)


def test_single_expert_matches_dense_swiglu():
    """E=1, k=1, ample capacity: MoE(x) == SwiGLU(x) exactly (router gives
    the one expert weight 1.0)."""
    key = jax.random.PRNGKey(0)
    d, f = 16, 32
    dense = init_swiglu(key, d, f)
    moe = init_moe(jax.random.PRNGKey(1), d, f, 1)
    # stack dense weights into the 1-expert slot
    moe["experts"] = jax.tree_util.tree_map(lambda a: a[None], dense)

    x = jax.random.normal(jax.random.PRNGKey(2), (3, 5, d))
    out, aux = moe_ffn(moe, x, top_k=1, capacity_factor=2.0)
    want = swiglu(dense, x)
    np.testing.assert_allclose(np.asarray(out), np.asarray(want), rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(float(aux), 1.0, rtol=1e-5)  # E=1: aux == 1


def test_route_topk_respects_capacity_and_weights():
    t, e, k = 12, 4, 2
    gates = jax.nn.softmax(jax.random.normal(jax.random.PRNGKey(0), (t, e)), axis=-1)
    c = moe_capacity(t, e, k, 1.0)
    dispatch, combine, aux = route_topk(gates, k, c)
    # each (expert, slot) holds at most one token
    assert float(jnp.max(jnp.sum(dispatch, axis=0))) <= 1.0 + 1e-6
    # a token's combine weights sum to 1 when none of its experts overflowed
    per_token = jnp.sum(combine, axis=(1, 2))
    assert float(jnp.max(per_token)) <= 1.0 + 1e-6
    # dispatched slots never exceed capacity
    assert dispatch.shape == (t, e, c)
    assert np.isfinite(float(aux))


def test_route_topk_drops_overflow():
    """All tokens prefer expert 0 with capacity 2: exactly 2 dispatched."""
    t, e = 6, 2
    gates = jnp.tile(jnp.asarray([[0.9, 0.1]]), (t, 1))
    dispatch, combine, _ = route_topk(gates, 1, 2)
    assert float(jnp.sum(dispatch[:, 0])) == 2.0
    assert float(jnp.sum(dispatch[:, 1])) == 0.0


@pytest.mark.parametrize("cf", [8.0, 0.5])  # ample capacity / forced drops
def test_sorted_dispatch_matches_dense(cf):
    """The scatter/gather dispatch must reproduce the one-hot dispatch —
    identical routing decisions (same GShard fill order), same outputs —
    both when nothing drops and when capacity forces drops."""
    key = jax.random.PRNGKey(3)
    d, f, e = 16, 32, 4
    moe = init_moe(key, d, f, e)
    x = jax.random.normal(jax.random.PRNGKey(4), (2, 24, d))

    dense_out, dense_aux = moe_ffn(x=x, params=moe, top_k=2,
                                   capacity_factor=cf, dispatch="dense")
    sort_out, sort_aux = moe_ffn(x=x, params=moe, top_k=2,
                                 capacity_factor=cf, dispatch="sorted")
    np.testing.assert_allclose(
        np.asarray(sort_out), np.asarray(dense_out), rtol=1e-5, atol=1e-6
    )
    np.testing.assert_allclose(float(sort_aux), float(dense_aux), rtol=1e-6)
    if cf < 1.0:  # the with-drop case must actually drop
        t = x.shape[0] * x.shape[1]
        c = moe_capacity(t, e, 2, cf)
        from cs336_systems_tpu.models.moe import route_topk_indexed

        gates = jax.nn.softmax(
            jnp.einsum("td,ed->te",
                       x.reshape(-1, d).astype(jnp.float32),
                       moe["router"]["weight"].astype(jnp.float32)),
            axis=-1,
        )
        _, pos, _, _ = route_topk_indexed(gates, 2, c)
        assert bool(jnp.any(pos >= c))


def test_sorted_dispatch_grads_match_dense():
    key = jax.random.PRNGKey(5)
    d, f, e = 16, 32, 4
    moe = init_moe(key, d, f, e)
    x = jax.random.normal(jax.random.PRNGKey(6), (24, d))

    def loss(params, dispatch):
        out, aux = moe_ffn(x=x, params=params, top_k=2,
                           capacity_factor=1.0, dispatch=dispatch)
        return jnp.sum(out.astype(jnp.float32) ** 2) + 0.01 * aux

    g_dense = jax.grad(lambda p: loss(p, "dense"))(moe)
    g_sort = jax.grad(lambda p: loss(p, "sorted"))(moe)
    assert trees_allclose(g_sort, g_dense, rtol=1e-4, atol=1e-6)


@pytest.mark.parametrize("cf", [8.0, 0.75])  # no-drop AND with-drop
def test_dp_moe_step_matches_full_batch(cf):
    """DP + MoE == single-device full-batch step, including when capacity
    drops tokens: the DP builder switches to globally-consistent sorted
    routing (moe_dp_axis), so drop decisions follow the global fill order."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from cs336_systems_tpu.parallel.dp import make_dp_train_step

    cfg = dataclasses.replace(
        MOE_CFG, moe_capacity_factor=cf, moe_dispatch="sorted"
    )
    mesh = make_mesh({"dp": 4})
    hp = AdamWHparams(lr=1e-3)
    x = jax.random.randint(jax.random.PRNGKey(7), (8, 32), 0, cfg.vocab_size)
    y = jnp.roll(x, -1, axis=-1)

    params, opt = init_train_state(jax.random.PRNGKey(0), cfg)
    ref_step = make_train_step(cfg, hp, donate=False)
    p_ref, _, l_ref = ref_step(params, opt, x, y)

    dp_step = make_dp_train_step(cfg, hp, mesh, donate=False)
    sh = NamedSharding(mesh, P("dp"))
    p_dp, _, l_dp = dp_step(
        params, opt, jax.device_put(x, sh), jax.device_put(y, sh)
    )

    np.testing.assert_allclose(float(l_dp), float(l_ref), rtol=1e-5)
    assert trees_allclose(p_dp, p_ref, rtol=1e-4, atol=1e-5)
    if cf < 1.0:  # prove the with-drop case drops globally
        from cs336_systems_tpu.models.moe import moe_capacity as mc

        assert mc(8 * 32, cfg.num_experts, cfg.moe_top_k, cf) < (
            8 * 32 * cfg.moe_top_k / cfg.num_experts * 2
        )


def test_moe_lm_trains_and_aux_finite():
    params, opt = init_train_state(jax.random.PRNGKey(0), MOE_CFG)
    step = make_train_step(MOE_CFG, AdamWHparams(lr=1e-3), donate=False)
    x = jax.random.randint(jax.random.PRNGKey(1), (4, 32), 0, MOE_CFG.vocab_size)
    y = jnp.roll(x, -1, axis=-1)
    losses = []
    for _ in range(5):
        params, opt, loss = step(params, opt, x, y)
        losses.append(float(loss))
    assert all(np.isfinite(losses))
    assert losses[-1] < losses[0]  # it learns
    logits, aux = transformer_lm_with_aux(params, x, MOE_CFG)
    assert logits.shape == (4, 32, MOE_CFG.vocab_size)
    assert np.isfinite(float(aux)) and float(aux) > 0.0


def test_moe_all_experts_get_gradients():
    params, _ = init_train_state(jax.random.PRNGKey(0), MOE_CFG)
    from cs336_systems_tpu.train import lm_loss

    x = jax.random.randint(jax.random.PRNGKey(1), (4, 32), 0, MOE_CFG.vocab_size)
    y = jnp.roll(x, -1, axis=-1)
    g = jax.grad(lm_loss)(params, x, y, MOE_CFG)
    gw1 = g["blocks"]["ffn"]["experts"]["w1"]["weight"]  # [L, E, f, d]
    per_expert = jnp.sum(jnp.abs(gw1), axis=(0, 2, 3))
    # with top-2 of 8 experts over 128 tokens, every expert sees traffic
    assert float(jnp.min(per_expert)) > 0.0
    # router is differentiable
    assert float(jnp.max(jnp.abs(g["blocks"]["ffn"]["router"]["weight"]))) > 0.0


def test_ep_step_matches_unsharded():
    mesh = make_mesh({"dp": 2, "ep": 4})
    hp = AdamWHparams(lr=1e-3)
    x = jax.random.randint(jax.random.PRNGKey(1), (8, 32), 0, MOE_CFG.vocab_size)
    y = jnp.roll(x, -1, axis=-1)

    params, opt = init_train_state(jax.random.PRNGKey(0), MOE_CFG)
    ref = make_train_step(MOE_CFG, hp, donate=False)
    p_ref, o_ref, l_ref = ref(params, opt, x, y)

    p_ep = shard_params_ep(params, mesh, MOE_CFG)
    o_ep = adamw_init(p_ep)
    step = make_ep_train_step(MOE_CFG, hp, mesh, donate=False)
    p_ep, o_ep, l_ep = step(p_ep, o_ep, x, y)

    np.testing.assert_allclose(float(l_ep), float(l_ref), rtol=1e-5)
    assert trees_allclose(p_ep, p_ref, rtol=1e-4, atol=1e-5)


def test_ep_validation():
    mesh = make_mesh({"ep": 8})
    dense = dataclasses.replace(MOE_CFG, num_experts=0)
    with pytest.raises(ValueError, match="needs a MoE config"):
        validate_ep(dense, mesh)
    odd = dataclasses.replace(MOE_CFG, num_experts=6)
    with pytest.raises(ValueError, match="not divisible"):
        validate_ep(odd, mesh)
    with pytest.raises(ValueError, match="moe_top_k"):
        dataclasses.replace(MOE_CFG, moe_top_k=9)


def test_dp_moe_trains_with_aux():
    """DP accepts MoE (per-shard routing, documented); loss finite, all
    experts receive gradient traffic via the synced pytree."""
    from cs336_systems_tpu.parallel.dp import make_dp_train_step

    mesh = make_mesh({"dp": 4})
    params, opt = init_train_state(jax.random.PRNGKey(0), MOE_CFG)
    step = make_dp_train_step(MOE_CFG, AdamWHparams(lr=1e-3), mesh, donate=False)
    x = jax.random.randint(jax.random.PRNGKey(1), (8, 32), 0, MOE_CFG.vocab_size)
    y = jnp.roll(x, -1, axis=-1)
    from jax.sharding import NamedSharding, PartitionSpec as P

    sh = NamedSharding(mesh, P("dp"))
    p2, o2, loss = step(params, opt, jax.device_put(x, sh), jax.device_put(y, sh))
    assert np.isfinite(float(loss))
    delta = jax.tree_util.tree_map(lambda a, b: jnp.max(jnp.abs(a - b)), params, p2)
    assert float(delta["blocks"]["ffn"]["router"]["weight"]) > 0.0


def test_sp_rejects_moe():
    from cs336_systems_tpu.parallel.sp import make_sp_train_step

    mesh = make_mesh({"sp": 4})
    with pytest.raises(ValueError, match="MoE blocks under sequence"):
        make_sp_train_step(MOE_CFG, AdamWHparams(lr=1e-3), mesh)


def test_pp_rejects_moe():
    from cs336_systems_tpu.parallel.pp import validate_pp

    mesh = make_mesh({"pp": 2})
    with pytest.raises(ValueError, match="MoE blocks under pipeline"):
        validate_pp(MOE_CFG, mesh)
