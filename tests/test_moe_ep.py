"""MoE layer and expert-parallelism tests.

Oracles: the dense SwiGLU (a 1-expert MoE must reduce to it exactly) and
the unsharded MoE step (ep sharding is a layout, not an approximation).
Runs on the 8-virtual-device CPU mesh (conftest).
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from common import trees_allclose
from cs336_systems_tpu.models.layers import init_swiglu, swiglu
from cs336_systems_tpu.models.moe import (
    init_moe,
    moe_capacity,
    moe_ffn,
    route_topk,
)
from cs336_systems_tpu.models.transformer import (
    TransformerConfig,
    init_transformer_lm,
    transformer_lm_with_aux,
)
from cs336_systems_tpu.optim.adamw import AdamWHparams, adamw_init
from cs336_systems_tpu.parallel.ep import (
    make_ep_train_step,
    shard_params_ep,
    validate_ep,
)
from cs336_systems_tpu.parallel.mesh import make_mesh
from cs336_systems_tpu.train import init_train_state, make_train_step

MOE_CFG = TransformerConfig(
    vocab_size=64, context_length=32, d_model=32,
    num_layers=2, num_heads=4, d_ff=64,
    num_experts=8, moe_top_k=2,
)


def test_single_expert_matches_dense_swiglu():
    """E=1, k=1, ample capacity: MoE(x) == SwiGLU(x) exactly (router gives
    the one expert weight 1.0)."""
    key = jax.random.PRNGKey(0)
    d, f = 16, 32
    dense = init_swiglu(key, d, f)
    moe = init_moe(jax.random.PRNGKey(1), d, f, 1)
    # stack dense weights into the 1-expert slot
    moe["experts"] = jax.tree_util.tree_map(lambda a: a[None], dense)

    x = jax.random.normal(jax.random.PRNGKey(2), (3, 5, d))
    out, aux = moe_ffn(moe, x, top_k=1, capacity_factor=2.0)
    want = swiglu(dense, x)
    np.testing.assert_allclose(np.asarray(out), np.asarray(want), rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(float(aux), 1.0, rtol=1e-5)  # E=1: aux == 1


def test_route_topk_respects_capacity_and_weights():
    t, e, k = 12, 4, 2
    gates = jax.nn.softmax(jax.random.normal(jax.random.PRNGKey(0), (t, e)), axis=-1)
    c = moe_capacity(t, e, k, 1.0)
    dispatch, combine, aux = route_topk(gates, k, c)
    # each (expert, slot) holds at most one token
    assert float(jnp.max(jnp.sum(dispatch, axis=0))) <= 1.0 + 1e-6
    # a token's combine weights sum to 1 when none of its experts overflowed
    per_token = jnp.sum(combine, axis=(1, 2))
    assert float(jnp.max(per_token)) <= 1.0 + 1e-6
    # dispatched slots never exceed capacity
    assert dispatch.shape == (t, e, c)
    assert np.isfinite(float(aux))


def test_route_topk_drops_overflow():
    """All tokens prefer expert 0 with capacity 2: exactly 2 dispatched."""
    t, e = 6, 2
    gates = jnp.tile(jnp.asarray([[0.9, 0.1]]), (t, 1))
    dispatch, combine, _ = route_topk(gates, 1, 2)
    assert float(jnp.sum(dispatch[:, 0])) == 2.0
    assert float(jnp.sum(dispatch[:, 1])) == 0.0


@pytest.mark.parametrize("cf", [8.0, 0.5])  # ample capacity / forced drops
def test_sorted_dispatch_matches_dense(cf):
    """The scatter/gather dispatch must reproduce the one-hot dispatch —
    identical routing decisions (same GShard fill order), same outputs —
    both when nothing drops and when capacity forces drops."""
    key = jax.random.PRNGKey(3)
    d, f, e = 16, 32, 4
    moe = init_moe(key, d, f, e)
    x = jax.random.normal(jax.random.PRNGKey(4), (2, 24, d))

    dense_out, dense_aux = moe_ffn(x=x, params=moe, top_k=2,
                                   capacity_factor=cf, dispatch="dense")
    sort_out, sort_aux = moe_ffn(x=x, params=moe, top_k=2,
                                 capacity_factor=cf, dispatch="sorted")
    np.testing.assert_allclose(
        np.asarray(sort_out), np.asarray(dense_out), rtol=1e-5, atol=1e-6
    )
    np.testing.assert_allclose(float(sort_aux), float(dense_aux), rtol=1e-6)
    if cf < 1.0:  # the with-drop case must actually drop
        t = x.shape[0] * x.shape[1]
        c = moe_capacity(t, e, 2, cf)
        from cs336_systems_tpu.models.moe import route_topk_indexed

        gates = jax.nn.softmax(
            jnp.einsum("td,ed->te",
                       x.reshape(-1, d).astype(jnp.float32),
                       moe["router"]["weight"].astype(jnp.float32)),
            axis=-1,
        )
        _, pos, _, _ = route_topk_indexed(gates, 2, c)
        assert bool(jnp.any(pos >= c))


def test_sorted_dispatch_grads_match_dense():
    key = jax.random.PRNGKey(5)
    d, f, e = 16, 32, 4
    moe = init_moe(key, d, f, e)
    x = jax.random.normal(jax.random.PRNGKey(6), (24, d))

    def loss(params, dispatch):
        out, aux = moe_ffn(x=x, params=params, top_k=2,
                           capacity_factor=1.0, dispatch=dispatch)
        return jnp.sum(out.astype(jnp.float32) ** 2) + 0.01 * aux

    g_dense = jax.grad(lambda p: loss(p, "dense"))(moe)
    g_sort = jax.grad(lambda p: loss(p, "sorted"))(moe)
    assert trees_allclose(g_sort, g_dense, rtol=1e-4, atol=1e-6)


@pytest.mark.parametrize("dispatch", ["dense", "sorted", "sorted_scatter"])
def test_ffn_remat_grads_match(dispatch):
    """moe_ffn_remat (jax.checkpoint around the vmapped expert SwiGLU) is a
    memory trade, not a numerics change: values and grads must match the
    non-remat path exactly, on every dispatch scheme."""
    key = jax.random.PRNGKey(11)
    d, f, e = 16, 32, 4
    moe = init_moe(key, d, f, e)
    x = jax.random.normal(jax.random.PRNGKey(12), (24, d))

    def run(ffn_remat):
        def loss(params):
            out, aux = moe_ffn(x=x, params=params, top_k=2,
                               capacity_factor=1.25, dispatch=dispatch,
                               ffn_remat=ffn_remat)
            return jnp.sum(out.astype(jnp.float32) ** 2) + 0.01 * aux

        out, _ = moe_ffn(x=x, params=moe, top_k=2, capacity_factor=1.25,
                         dispatch=dispatch, ffn_remat=ffn_remat)
        return out, jax.grad(loss)(moe)

    out_a, g_a = run(False)
    out_b, g_b = run(True)
    np.testing.assert_array_equal(np.asarray(out_a), np.asarray(out_b))
    assert trees_allclose(g_b, g_a, rtol=1e-6, atol=1e-8)


@pytest.mark.parametrize("cf", [8.0, 0.5])  # ample capacity / forced drops
def test_sorted_scatter_matches_sorted(cf):
    """The round-3 row-scatter movement (dispatch='sorted_scatter') and the
    round-4 gather-both-ways movement are the SAME function — identical
    routing, bit-equal dataflow up to summation order — values and grads."""
    key = jax.random.PRNGKey(7)
    d, f, e = 16, 32, 4
    moe = init_moe(key, d, f, e)
    x = jax.random.normal(jax.random.PRNGKey(8), (2, 24, d))

    def run(dispatch):
        def loss(params):
            out, aux = moe_ffn(x=x, params=params, top_k=2,
                               capacity_factor=cf, dispatch=dispatch)
            return jnp.sum(out.astype(jnp.float32) ** 2) + 0.01 * aux

        (out, _aux) = moe_ffn(x=x, params=moe, top_k=2, capacity_factor=cf,
                              dispatch=dispatch)
        return out, jax.grad(loss)(moe)

    out_s, g_s = run("sorted")
    out_l, g_l = run("sorted_scatter")
    np.testing.assert_allclose(np.asarray(out_s), np.asarray(out_l),
                               rtol=1e-6, atol=1e-7)
    assert trees_allclose(g_s, g_l, rtol=1e-5, atol=1e-7)


def test_gmm_kernel_matches_per_expert_matmul():
    """grouped_matmul (interpret mode) == per-group x @ w[g], including an
    EMPTY middle expert, uneven group sizes, and pad rows inside a tile —
    both values and grads (the dw kernel's accumulate-over-tiles and the
    visited-mask zeroing of untouched experts)."""
    from cs336_systems_tpu.ops.grouped_matmul import grouped_matmul, tile_maps

    bm, e, k, n = 8, 4, 16, 32
    counts = jnp.array([10, 0, 17, 5], jnp.int32)  # expert 1 empty, pads
    m_pad = int(jnp.sum(counts)) + e * bm
    te, first, visited, starts = tile_maps(counts, bm, m_pad // bm)
    x = np.zeros((m_pad, k), np.float32)
    rows = {}
    rng = np.random.default_rng(0)
    for g in range(e):
        s, c = int(starts[g]), int(counts[g])
        rows[g] = rng.normal(size=(c, k)).astype(np.float32)
        x[s:s + c] = rows[g]
    # native layers.linear [out, in] layout: y = x @ w[g].T
    w = rng.normal(size=(e, n, k)).astype(np.float32)

    y = grouped_matmul(jnp.asarray(x), jnp.asarray(w), te, first, visited,
                       bm, True)
    for g in range(e):
        s, c = int(starts[g]), int(counts[g])
        np.testing.assert_allclose(np.asarray(y[s:s + c]), rows[g] @ w[g].T,
                                   rtol=1e-5, atol=1e-5)

    def loss(x, w):
        y = grouped_matmul(x, w, te, first, visited, bm, True)
        # only real rows count, like the combine map does
        mask = np.zeros((m_pad, 1), np.float32)
        for g in range(e):
            mask[int(starts[g]):int(starts[g]) + int(counts[g])] = 1.0
        return jnp.sum((y * mask) ** 2)

    gx, gw = jax.grad(loss, argnums=(0, 1))(jnp.asarray(x), jnp.asarray(w))

    def loss_ref(x, w):
        tot = 0.0
        for g in range(e):
            s, c = int(starts[g]), int(counts[g])
            tot = tot + jnp.sum((x[s:s + c] @ w[g].T) ** 2)
        return tot

    gx_ref, gw_ref = jax.grad(loss_ref, argnums=(0, 1))(
        jnp.asarray(x), jnp.asarray(w)
    )
    np.testing.assert_allclose(np.asarray(gx), np.asarray(gx_ref),
                               rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(np.asarray(gw), np.asarray(gw_ref),
                               rtol=1e-4, atol=1e-4)


def test_gmm_dispatch_matches_sorted_dropless():
    """dispatch='gmm' (dropless) == dispatch='sorted' at generous capacity
    (nothing drops there either) — values, aux, and grads."""
    key = jax.random.PRNGKey(21)
    d, f, e = 16, 32, 4
    moe = init_moe(key, d, f, e)
    x = jax.random.normal(jax.random.PRNGKey(22), (2, 24, d))

    def run(dispatch, cf):
        def loss(params):
            out, aux = moe_ffn(x=x, params=params, top_k=2,
                               capacity_factor=cf, dispatch=dispatch)
            return jnp.sum(out.astype(jnp.float32) ** 2) + 0.01 * aux

        out, aux = moe_ffn(x=x, params=moe, top_k=2, capacity_factor=cf,
                           dispatch=dispatch)
        return out, aux, jax.grad(loss)(moe)

    out_g, aux_g, g_g = run("gmm", 123.0)
    out_s, aux_s, g_s = run("sorted", 123.0)
    np.testing.assert_allclose(np.asarray(out_g), np.asarray(out_s),
                               rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(float(aux_g), float(aux_s), rtol=1e-6)
    assert trees_allclose(g_g, g_s, rtol=1e-4, atol=1e-6)


def test_gmm_lm_trains():
    """A small MoE LM with dispatch='gmm' trains end to end (finite,
    decreasing loss) — the model-level smoke for the Pallas path
    (interpret mode on CPU)."""
    cfg = dataclasses.replace(MOE_CFG, moe_dispatch="gmm",
                              moe_ffn_remat=True)
    params, opt = init_train_state(jax.random.PRNGKey(31), cfg)
    step = make_train_step(cfg, AdamWHparams(lr=3e-3))
    x = jax.random.randint(jax.random.PRNGKey(32), (4, 32), 0, 64)
    y = jnp.roll(x, -1, axis=-1)
    losses = []
    for _ in range(6):
        params, opt, loss = step(params, opt, x, y)
        losses.append(float(loss))
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0]


def test_prefix_count_matches_cumsum():
    """_prefix_count (blocked tril-matmul prefix sum, the MXU replacement
    for lax.cumsum's reduce-window lowering) is exact over one-hot counts,
    including non-multiple-of-block lengths and multi-block inputs."""
    from cs336_systems_tpu.models.moe import _prefix_count

    for t, e, seed in [(5, 3, 0), (128, 4, 1), (300, 8, 2), (1024, 2, 3)]:
        idx = jax.random.randint(jax.random.PRNGKey(seed), (t,), 0, e)
        onehot = jax.nn.one_hot(idx, e, dtype=jnp.int32)
        got = _prefix_count(onehot)
        want = jnp.cumsum(onehot, axis=0)
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))
        # fp32 input path (the dense router uses fp32 one-hots)
        got_f = _prefix_count(onehot.astype(jnp.float32))
        np.testing.assert_array_equal(np.asarray(got_f),
                                      np.asarray(want.astype(jnp.float32)))


@pytest.mark.parametrize("dispatch,cf", [
    ("sorted", 8.0), ("sorted", 0.75),  # no-drop AND with-drop
    ("gmm", 1.0),  # dropless: per-shard compute must equal full batch as-is
])
def test_dp_moe_step_matches_full_batch(dispatch, cf):
    """DP + MoE == single-device full-batch step, including when capacity
    drops tokens: the DP builder keeps the configured dispatch and sets
    moe_dp_axis — sorted routes in the global fill order (drop decisions
    follow the full batch), gmm is dropless so only its aux loss needs the
    global form."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from cs336_systems_tpu.parallel.dp import make_dp_train_step

    cfg = dataclasses.replace(
        MOE_CFG, moe_capacity_factor=cf, moe_dispatch=dispatch
    )
    mesh = make_mesh({"dp": 4})
    hp = AdamWHparams(lr=1e-3)
    x = jax.random.randint(jax.random.PRNGKey(7), (8, 32), 0, cfg.vocab_size)
    y = jnp.roll(x, -1, axis=-1)

    params, opt = init_train_state(jax.random.PRNGKey(0), cfg)
    ref_step = make_train_step(cfg, hp, donate=False)
    p_ref, _, l_ref = ref_step(params, opt, x, y)

    dp_step = make_dp_train_step(cfg, hp, mesh, donate=False)
    sh = NamedSharding(mesh, P("dp"))
    p_dp, _, l_dp = dp_step(
        params, opt, jax.device_put(x, sh), jax.device_put(y, sh)
    )

    np.testing.assert_allclose(float(l_dp), float(l_ref), rtol=1e-5)
    assert trees_allclose(p_dp, p_ref, rtol=1e-4, atol=1e-5)
    if cf < 1.0:  # prove the with-drop case drops globally
        from cs336_systems_tpu.models.moe import moe_capacity as mc

        assert mc(8 * 32, cfg.num_experts, cfg.moe_top_k, cf) < (
            8 * 32 * cfg.moe_top_k / cfg.num_experts * 2
        )


def test_moe_lm_trains_and_aux_finite():
    params, opt = init_train_state(jax.random.PRNGKey(0), MOE_CFG)
    step = make_train_step(MOE_CFG, AdamWHparams(lr=1e-3), donate=False)
    x = jax.random.randint(jax.random.PRNGKey(1), (4, 32), 0, MOE_CFG.vocab_size)
    y = jnp.roll(x, -1, axis=-1)
    losses = []
    for _ in range(5):
        params, opt, loss = step(params, opt, x, y)
        losses.append(float(loss))
    assert all(np.isfinite(losses))
    assert losses[-1] < losses[0]  # it learns
    logits, aux = transformer_lm_with_aux(params, x, MOE_CFG)
    assert logits.shape == (4, 32, MOE_CFG.vocab_size)
    assert np.isfinite(float(aux)) and float(aux) > 0.0


def test_moe_all_experts_get_gradients():
    params, _ = init_train_state(jax.random.PRNGKey(0), MOE_CFG)
    from cs336_systems_tpu.train import lm_loss

    x = jax.random.randint(jax.random.PRNGKey(1), (4, 32), 0, MOE_CFG.vocab_size)
    y = jnp.roll(x, -1, axis=-1)
    g = jax.grad(lm_loss)(params, x, y, MOE_CFG)
    gw1 = g["blocks"]["ffn"]["experts"]["w1"]["weight"]  # [L, E, f, d]
    per_expert = jnp.sum(jnp.abs(gw1), axis=(0, 2, 3))
    # with top-2 of 8 experts over 128 tokens, every expert sees traffic
    assert float(jnp.min(per_expert)) > 0.0
    # router is differentiable
    assert float(jnp.max(jnp.abs(g["blocks"]["ffn"]["router"]["weight"]))) > 0.0


# These oracles were the "a2a/sp post-AdamW parity regression" pins
# (~40% first-step sign flips bounded by 2*lr). Root cause, found with
# analysis/gradsan: in-body value_and_grad under this jax's forced
# check_rep=False shard_map yields LOCAL gradients (no auto-psum for
# replicated operands; the a2a transpose sums only the ep direction of
# the expert leaves) — the step must own the reduction, which
# ep._sync_ep_grads now issues before the norm/clip. The gradient-level
# a2a unit tests above always passed because they differentiate OUTSIDE
# the shard_map.
@pytest.mark.parametrize("mesh_axes,dp", [
    ({"dp": 2, "ep": 4}, "dp"),
    ({"ep": 8}, None),
])
def test_ep_a2a_step_matches_unsharded(mesh_axes, dp):
    """THE ep oracle (round-5 indexed path): the all-to-all expert-parallel
    step — tokens sharded over (dp ×) ep, expert weights/moments sharded
    over ep, routed rows moved by explicit all-to-alls, local sorted
    compute — must reproduce the single-device full-batch SORTED step:
    same loss, same updated params. moe_capacity_factor=1.0 so routing
    pressure is real; the global-fill-order contract decides which claims
    drop identically to the full batch."""
    from cs336_systems_tpu.parallel.mesh import shard_batch

    cfg = dataclasses.replace(MOE_CFG, moe_dispatch="sorted",
                              moe_capacity_factor=1.0)
    mesh = make_mesh(mesh_axes)
    hp = AdamWHparams(lr=1e-3)
    x = jax.random.randint(jax.random.PRNGKey(1), (8, 32), 0, cfg.vocab_size)
    y = jnp.roll(x, -1, axis=-1)

    params, opt = init_train_state(jax.random.PRNGKey(0), cfg)
    ref = make_train_step(cfg, hp, donate=False)
    p_ref, o_ref, l_ref = ref(params, opt, x, y)

    p_ep = shard_params_ep(params, mesh, cfg)
    o_ep = adamw_init(p_ep)
    step = make_ep_train_step(cfg, hp, mesh, donate=False, dp_axis=dp)
    axes = (dp, "ep") if dp else ("ep",)
    xs, ys = shard_batch(mesh, x, y, axis=axes)
    p_ep, o_ep, l_ep = step(p_ep, o_ep, xs, ys)

    np.testing.assert_allclose(float(l_ep), float(l_ref), rtol=1e-5)
    assert trees_allclose(p_ep, p_ref, rtol=1e-4, atol=1e-5)


def test_ep_a2a_matches_under_forced_drops():
    """Skew the router so one expert overflows its capacity by a wide
    margin: the a2a step's drop decisions (global fill order across the
    dp × ep token sharding) must still match the full-batch sorted model —
    layer outputs AND router gradients (the kept-mask weight contract)."""
    from cs336_systems_tpu.parallel.mesh import shard_batch

    cfg = dataclasses.replace(MOE_CFG, moe_dispatch="sorted",
                              moe_capacity_factor=0.6)
    params, opt = init_train_state(jax.random.PRNGKey(2), cfg)
    # bias the first layer's router hard toward expert 0
    rw = params["blocks"]["ffn"]["router"]["weight"]
    params["blocks"]["ffn"]["router"]["weight"] = rw.at[0, 0].add(3.0)

    hp = AdamWHparams(lr=1e-3)
    x = jax.random.randint(jax.random.PRNGKey(3), (8, 32), 0, cfg.vocab_size)
    y = jnp.roll(x, -1, axis=-1)
    ref = make_train_step(cfg, hp, donate=False)
    p_ref, _, l_ref = ref(params, opt, x, y)

    # drops are guaranteed by pigeonhole at cf=0.6: total capacity is
    # E*ceil(k*T/E*0.6) = 8*ceil(64*0.6) = 312 < 512 = T*k total claims,
    # so some claims drop REGARDLESS of router weights; the skew just
    # concentrates them on one expert.
    from cs336_systems_tpu.models.moe import moe_capacity

    assert cfg.num_experts * moe_capacity(
        256, cfg.num_experts, cfg.moe_top_k, 0.6
    ) < 256 * cfg.moe_top_k

    mesh = make_mesh({"dp": 2, "ep": 4})
    p_ep = shard_params_ep(params, mesh, cfg)
    o_ep = adamw_init(p_ep)
    step = make_ep_train_step(cfg, hp, mesh, donate=False)
    xs, ys = shard_batch(mesh, x, y, axis=("dp", "ep"))
    p_ep, _, l_ep = step(p_ep, o_ep, xs, ys)

    np.testing.assert_allclose(float(l_ep), float(l_ref), rtol=1e-5)
    assert trees_allclose(p_ep, p_ref, rtol=1e-4, atol=1e-5)


def test_ep_dense_variant_still_matches():
    """The GSPMD-dense variant (rounds <=4) is kept for A/B and must stay
    correct: same oracle as the a2a test, dense dispatch."""
    mesh = make_mesh({"dp": 2, "ep": 4})
    hp = AdamWHparams(lr=1e-3)
    x = jax.random.randint(jax.random.PRNGKey(1), (8, 32), 0, MOE_CFG.vocab_size)
    y = jnp.roll(x, -1, axis=-1)

    params, opt = init_train_state(jax.random.PRNGKey(0), MOE_CFG)
    ref = make_train_step(MOE_CFG, hp, donate=False)
    p_ref, o_ref, l_ref = ref(params, opt, x, y)

    p_ep = shard_params_ep(params, mesh, MOE_CFG)
    o_ep = adamw_init(p_ep)
    step = make_ep_train_step(MOE_CFG, hp, mesh, donate=False,
                              variant="dense")
    p_ep, o_ep, l_ep = step(p_ep, o_ep, x, y)

    np.testing.assert_allclose(float(l_ep), float(l_ref), rtol=1e-5)
    assert trees_allclose(p_ep, p_ref, rtol=1e-4, atol=1e-5)


def test_ep_validation():
    mesh = make_mesh({"ep": 8})
    dense = dataclasses.replace(MOE_CFG, num_experts=0)
    with pytest.raises(ValueError, match="needs a MoE config"):
        validate_ep(dense, mesh)
    odd = dataclasses.replace(MOE_CFG, num_experts=6)
    with pytest.raises(ValueError, match="not divisible"):
        validate_ep(odd, mesh)
    with pytest.raises(ValueError, match="moe_top_k"):
        dataclasses.replace(MOE_CFG, moe_top_k=9)


def test_dp_moe_trains_with_aux():
    """DP accepts MoE (per-shard routing, documented); loss finite, all
    experts receive gradient traffic via the synced pytree."""
    from cs336_systems_tpu.parallel.dp import make_dp_train_step

    mesh = make_mesh({"dp": 4})
    params, opt = init_train_state(jax.random.PRNGKey(0), MOE_CFG)
    step = make_dp_train_step(MOE_CFG, AdamWHparams(lr=1e-3), mesh, donate=False)
    x = jax.random.randint(jax.random.PRNGKey(1), (8, 32), 0, MOE_CFG.vocab_size)
    y = jnp.roll(x, -1, axis=-1)
    from jax.sharding import NamedSharding, PartitionSpec as P

    sh = NamedSharding(mesh, P("dp"))
    p2, o2, loss = step(params, opt, jax.device_put(x, sh), jax.device_put(y, sh))
    assert np.isfinite(float(loss))
    delta = jax.tree_util.tree_map(lambda a, b: jnp.max(jnp.abs(a - b)), params, p2)
    assert float(delta["blocks"]["ffn"]["router"]["weight"]) > 0.0


def test_sp_rejects_moe():
    from cs336_systems_tpu.parallel.sp import make_sp_train_step

    mesh = make_mesh({"sp": 4})
    with pytest.raises(ValueError, match="MoE blocks under sequence"):
        make_sp_train_step(MOE_CFG, AdamWHparams(lr=1e-3), mesh)


def test_pp_rejects_moe():
    from cs336_systems_tpu.parallel.pp import validate_pp

    mesh = make_mesh({"pp": 2})
    with pytest.raises(ValueError, match="MoE blocks under pipeline"):
        validate_pp(MOE_CFG, mesh)


def test_gmm_w13_fused_matches_unfused_chain():
    """grouped_matmul_w13 (one fused gate/up+silu·mul kernel, interpret
    mode) == the unfused chain (two grouped_matmuls + XLA silu·mul) —
    values AND all three gradients, including an empty expert, uneven
    group sizes, and pad rows inside a tile."""
    from cs336_systems_tpu.ops.grouped_matmul import (
        grouped_matmul,
        grouped_matmul_w13,
        tile_maps,
    )

    d, f, e, bm = 16, 32, 4, 8
    counts = jnp.asarray([10, 0, 5, 3], jnp.int32)
    m_pad = (int(jnp.sum((counts + bm - 1) // bm * bm)) // bm + 2) * bm
    te, first, visited, starts = tile_maps(counts, bm, m_pad // bm)
    used = int(starts[-1])
    kx, k1, k3 = jax.random.split(jax.random.PRNGKey(7), 3)
    x = jnp.zeros((m_pad, d))
    for g, c in enumerate(np.asarray(counts)):
        s = int(starts[g])
        x = x.at[s:s + int(c)].set(
            jax.random.normal(jax.random.fold_in(kx, g), (int(c), d)))
    w1 = jax.random.normal(k1, (e, f, d)) * 0.3
    w3 = jax.random.normal(k3, (e, f, d)) * 0.3

    def fused(args):
        x, w1, w3 = args
        return grouped_matmul_w13(x, w1, w3, te, first, visited, bm)

    def unfused(args):
        x, w1, w3 = args
        h = grouped_matmul(x, w1, te, first, visited, bm)
        g = grouped_matmul(x, w3, te, first, visited, bm)
        return (jax.nn.silu(h) * g).astype(x.dtype)

    pf = fused((x, w1, w3))
    pu = unfused((x, w1, w3))
    np.testing.assert_allclose(np.asarray(pf[:used]), np.asarray(pu[:used]),
                               rtol=1e-5, atol=1e-5)

    loss = lambda f_: lambda a: jnp.sum(jnp.sin(f_(a)[:used] * 3.0))
    gf = jax.grad(loss(fused))((x, w1, w3))
    gu = jax.grad(loss(unfused))((x, w1, w3))
    for a, b, name in zip(gf, gu, ("dx", "dw1", "dw3")):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-4, err_msg=name)


def _w13_bwd_case(key, d, f, e, bm, counts, spare_tiles=2):
    """Packed operands + vjp residuals + a random cotangent for backward
    parity tests: NON-divisible counts (pad rows inside tiles), an empty
    expert, and spare tail tiles past the last group."""
    from cs336_systems_tpu.ops import grouped_matmul as gm

    n_tiles = int(jnp.sum((counts + bm - 1) // bm)) + spare_tiles
    m_pad = n_tiles * bm
    te, first, visited, starts = gm.tile_maps(counts, bm, n_tiles)
    kx, k1, k3, kd = jax.random.split(key, 4)
    x = jnp.zeros((m_pad, d))
    for g, c in enumerate(np.asarray(counts)):
        s = int(starts[g])
        x = x.at[s:s + int(c)].set(
            jax.random.normal(jax.random.fold_in(kx, g), (int(c), d)))
    w1 = jax.random.normal(k1, (e, f, d)) * 0.3
    w3 = jax.random.normal(k3, (e, f, d)) * 0.3
    _, res = gm._gmm13_fwd(x, w1, w3, te, first, visited, bm, True)
    dp = jax.random.normal(kd, (m_pad, f))
    return res, dp, (te, visited)


def test_gmm13_fused_bwd_three_way_parity():
    """The round-6 fused backward (TWO Pallas kernels, SiLU grads
    in-register) == the retained five-pass unfused chain == the einsum
    oracle — dx, dw1, dw3, with non-divisible counts, pad rows inside
    tiles, and an EMPTY expert whose dw must stay exactly zero."""
    from cs336_systems_tpu.ops import grouped_matmul as gm

    d, f, e, bm = 16, 32, 4, 8
    counts = jnp.asarray([9, 0, 13, 3], jnp.int32)  # none divides bm
    res, dp, (te, visited) = _w13_bwd_case(
        jax.random.PRNGKey(11), d, f, e, bm, counts)
    x, w1, w3 = res[0], res[1], res[2]

    assert gm._fused_bwd_plan(bm, f, d, x.dtype.itemsize) is not None
    fused = gm._gmm13_bwd(bm, True, res, dp)[:3]
    unfused = gm._gmm13_bwd_unfused(bm, True, res, dp)[:3]

    # kernel chain vs kernel chain: same staging, near-identical in f32
    for a, b, name in zip(fused, unfused, ("dx", "dw1", "dw3")):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-6, err_msg=name)

    # einsum oracle (pad rows have x = 0, so their SiLU grads vanish and
    # the full-array comparison is exact-by-contract)
    m_pad = x.shape[0]
    onehot = gm._row_onehot(te, bm, m_pad, e, jnp.float32)

    def ref(x, w1, w3):
        h = jnp.einsum("me,mk,enk->mn", onehot, x, w1)
        g = jnp.einsum("me,mk,enk->mn", onehot, x, w3)
        return jax.nn.silu(h) * g

    _, vjp = jax.vjp(ref, x, w1, w3)
    oracle = vjp(dp)
    for a, b, name in zip(fused, oracle, ("dx", "dw1", "dw3")):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-4, err_msg=name)

    # expert 1 owns zero tiles: its dw slabs are EXACTLY zero (the
    # visited mask, not just small numbers)
    assert int(visited[1]) == 0
    assert np.all(np.asarray(fused[1][1]) == 0)
    assert np.all(np.asarray(fused[2][1]) == 0)


def test_gmm13_fused_bwd_row_subdivision(monkeypatch):
    """Starving the bwd VMEM budget makes the pickers subdivide the
    packing's row tile (the headline-shape regime, where full-N operand
    blocks at bm=256 blow scoped VMEM) — sub-tiles inherit the parent's
    expert and the grads still match the unfused chain."""
    from cs336_systems_tpu.ops import grouped_matmul as gm

    d, f, e, bm = 16, 32, 4, 16
    counts = jnp.asarray([9, 0, 13, 3], jnp.int32)
    res, dp, _ = _w13_bwd_case(
        jax.random.PRNGKey(13), d, f, e, bm, counts)

    monkeypatch.setattr(gm, "GMM_BWD_VMEM_BUDGET", 25_000)
    bm_dx, _ = gm._pick_dx_tiles(bm, f, d, 4)
    bm_dw, _, _ = gm._pick_dw_tiles(bm, f, d, 4)
    assert bm_dx < bm and bm_dw < bm  # the subdivision actually engages

    fused = gm._gmm13_bwd(bm, True, res, dp)[:3]
    unfused = gm._gmm13_bwd_unfused(bm, True, res, dp)[:3]
    for a, b, name in zip(fused, unfused, ("dx", "dw1", "dw3")):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-6, err_msg=name)


def test_gmm13_fused_bwd_unfused_fallback(monkeypatch):
    """A budget no block set can satisfy must fall back to the unfused
    chain (plan None) — correctness preserved, never an exception."""
    from cs336_systems_tpu.ops import grouped_matmul as gm

    d, f, e, bm = 16, 32, 4, 8
    counts = jnp.asarray([9, 0, 13, 3], jnp.int32)
    res, dp, (te, visited) = _w13_bwd_case(
        jax.random.PRNGKey(17), d, f, e, bm, counts)

    monkeypatch.setattr(gm, "GMM_BWD_VMEM_BUDGET", 64)
    assert gm._fused_bwd_plan(bm, f, d, 4) is None
    out = gm._gmm13_bwd(bm, True, res, dp)[:3]
    ref = gm._gmm13_bwd_unfused(bm, True, res, dp)[:3]
    for a, b in zip(out, ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b))


def test_ep_a2a_uneven_split_direction():
    """{dp:4, ep:2} — more dp than ep (the transpose of the main oracle
    mesh): two local experts per shard, fill order over 8 token shards."""
    from cs336_systems_tpu.parallel.mesh import shard_batch

    cfg = dataclasses.replace(MOE_CFG, moe_dispatch="sorted")
    mesh = make_mesh({"dp": 4, "ep": 2})
    hp = AdamWHparams(lr=1e-3)
    x = jax.random.randint(jax.random.PRNGKey(5), (8, 32), 0, cfg.vocab_size)
    y = jnp.roll(x, -1, axis=-1)
    params, opt = init_train_state(jax.random.PRNGKey(0), cfg)
    ref = make_train_step(cfg, hp, donate=False)
    p_ref, _, l_ref = ref(params, opt, x, y)

    p_ep = shard_params_ep(params, mesh, cfg)
    o_ep = adamw_init(p_ep)
    step = make_ep_train_step(cfg, hp, mesh, donate=False)
    xs, ys = shard_batch(mesh, x, y, axis=("dp", "ep"))
    p_ep, _, l_ep = step(p_ep, o_ep, xs, ys)
    np.testing.assert_allclose(float(l_ep), float(l_ref), rtol=1e-5)
    assert trees_allclose(p_ep, p_ref, rtol=1e-4, atol=1e-5)
