"""Model-library tests (L1): layers, LM forward, sampling, checkpointing."""

import math

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from cs336_systems_tpu.models.layers import (
    apply_rope,
    init_linear,
    init_rmsnorm,
    linear,
    rmsnorm,
    rope_cache,
)
from cs336_systems_tpu.models.transformer import (
    MODEL_SIZES,
    TransformerConfig,
    config_for_size,
    count_params,
    generate,
    init_transformer_lm,
    transformer_lm,
)
from cs336_systems_tpu.utils.checkpoint import load_checkpoint, save_checkpoint


def tiny_cfg(**kw):
    base = dict(
        vocab_size=128,
        context_length=64,
        d_model=32,
        num_layers=2,
        num_heads=4,
        d_ff=64,
    )
    base.update(kw)
    return TransformerConfig(**base)


def test_linear_init_stats():
    key = jax.random.PRNGKey(0)
    p = init_linear(key, 512, 512)
    std = math.sqrt(2 / (512 + 512))
    w = np.asarray(p["weight"])
    assert abs(w.std() - std) / std < 0.1
    assert np.abs(w).max() <= 3 * std + 1e-6
    assert w.shape == (512, 512)


def test_rmsnorm_fp32_internals_and_shape():
    p = init_rmsnorm(16)
    x = jax.random.normal(jax.random.PRNGKey(1), (4, 16)).astype(jnp.bfloat16) * 100
    out = rmsnorm(p, x)
    assert out.dtype == jnp.bfloat16
    # unit RMS after norm (weight=1)
    rms = np.sqrt(np.mean(np.square(np.asarray(out, np.float32)), -1))
    np.testing.assert_allclose(rms, 1.0, rtol=0.05)


def test_rope_preserves_norm_and_zero_position_identity():
    cos, sin = rope_cache(32, 8)
    x = jax.random.normal(jax.random.PRNGKey(2), (1, 1, 5, 8))
    pos = jnp.arange(5)
    out = apply_rope(x, cos, sin, pos)
    np.testing.assert_allclose(
        np.linalg.norm(np.asarray(out), axis=-1),
        np.linalg.norm(np.asarray(x), axis=-1),
        rtol=1e-5,
    )
    # position 0 has angle 0 → identity
    np.testing.assert_allclose(np.asarray(out)[..., 0, :], np.asarray(x)[..., 0, :], rtol=1e-6)


def test_rope_relative_position_property():
    # Attention score q_i . k_j after RoPE must depend only on (i - j).
    cos, sin = rope_cache(64, 16)
    q = jax.random.normal(jax.random.PRNGKey(3), (16,))
    k = jax.random.normal(jax.random.PRNGKey(4), (16,))

    def score(i, j):
        qr = apply_rope(q[None, None], cos, sin, jnp.array([i]))[0, 0]
        kr = apply_rope(k[None, None], cos, sin, jnp.array([j]))[0, 0]
        return float(qr @ kr)

    assert abs(score(5, 3) - score(10, 8)) < 1e-4
    assert abs(score(20, 0) - score(40, 20)) < 1e-4


def test_lm_forward_shape_and_dtype():
    cfg = tiny_cfg()
    params = init_transformer_lm(jax.random.PRNGKey(0), cfg)
    x = jax.random.randint(jax.random.PRNGKey(1), (2, 16), 0, cfg.vocab_size)
    logits = transformer_lm(params, x, cfg)
    assert logits.shape == (2, 16, cfg.vocab_size)
    assert logits.dtype == jnp.float32


def test_lm_causality():
    """Changing a future token must not change logits at earlier positions."""
    cfg = tiny_cfg()
    params = init_transformer_lm(jax.random.PRNGKey(0), cfg)
    x = jax.random.randint(jax.random.PRNGKey(1), (1, 16), 0, cfg.vocab_size)
    logits_a = transformer_lm(params, x, cfg)
    x2 = x.at[0, 10].set((x[0, 10] + 1) % cfg.vocab_size)
    logits_b = transformer_lm(params, x2, cfg)
    np.testing.assert_allclose(
        np.asarray(logits_a[0, :10]), np.asarray(logits_b[0, :10]), rtol=1e-4, atol=1e-5
    )
    assert not np.allclose(np.asarray(logits_a[0, 10]), np.asarray(logits_b[0, 10]))


def test_lm_bf16_compute_close_to_fp32():
    cfg = tiny_cfg()
    cfg_bf16 = tiny_cfg(compute_dtype="bfloat16")
    params = init_transformer_lm(jax.random.PRNGKey(0), cfg)
    x = jax.random.randint(jax.random.PRNGKey(1), (2, 16), 0, cfg.vocab_size)
    lf = transformer_lm(params, x, cfg)
    lb = transformer_lm(params, x, cfg_bf16)
    assert lb.dtype == jnp.bfloat16
    # bf16 has ~3 decimal digits; logits should agree loosely
    assert np.mean(np.abs(np.asarray(lf) - np.asarray(lb, np.float32))) < 0.15


def test_remat_matches_no_remat():
    cfg = tiny_cfg()
    cfg_remat = tiny_cfg(remat=True)
    params = init_transformer_lm(jax.random.PRNGKey(0), cfg)
    x = jax.random.randint(jax.random.PRNGKey(1), (1, 8), 0, cfg.vocab_size)

    def loss(p, c):
        return jnp.mean(transformer_lm(p, x, c) ** 2)

    g1 = jax.grad(loss)(params, cfg)
    g2 = jax.grad(loss)(params, cfg_remat)
    for a, b in zip(jax.tree_util.tree_leaves(g1), jax.tree_util.tree_leaves(g2)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-5)


def test_unrolled_layers_match_scan():
    """scan_layers=False (the TPU benchmark config) must be numerically
    identical to the lax.scan path, in forward and gradient, with and
    without remat."""
    cfg = tiny_cfg()
    params = init_transformer_lm(jax.random.PRNGKey(0), cfg)
    x = jax.random.randint(jax.random.PRNGKey(1), (2, 8), 0, cfg.vocab_size)

    def loss(p, c):
        return jnp.mean(transformer_lm(p, x, c) ** 2)

    l_scan = loss(params, cfg)
    g_scan = jax.grad(loss)(params, cfg)
    for unrolled in (tiny_cfg(scan_layers=False), tiny_cfg(scan_layers=False, remat=True)):
        np.testing.assert_allclose(
            np.asarray(loss(params, unrolled)), np.asarray(l_scan), rtol=1e-5
        )
        g = jax.grad(loss)(params, unrolled)
        for a, b in zip(jax.tree_util.tree_leaves(g_scan), jax.tree_util.tree_leaves(g)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-5)


def test_flash_xla_attn_impl_in_model():
    """attn_impl='flash_xla' (benchmark headline config) must match the
    plain xla attention path."""
    cfg = tiny_cfg()
    cfg_fx = tiny_cfg(attn_impl="flash_xla")
    params = init_transformer_lm(jax.random.PRNGKey(0), cfg)
    x = jax.random.randint(jax.random.PRNGKey(1), (2, 8), 0, cfg.vocab_size)

    def loss(p, c):
        return jnp.mean(transformer_lm(p, x, c) ** 2)

    np.testing.assert_allclose(
        np.asarray(loss(params, cfg_fx)), np.asarray(loss(params, cfg)), rtol=1e-5
    )
    g1 = jax.grad(loss)(params, cfg)
    g2 = jax.grad(loss)(params, cfg_fx)
    for a, b in zip(jax.tree_util.tree_leaves(g1), jax.tree_util.tree_leaves(g2)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-5)


def test_count_params_analytic():
    cfg = tiny_cfg()
    params = init_transformer_lm(jax.random.PRNGKey(0), cfg)
    d, f, v, L = cfg.d_model, cfg.d_ff, cfg.vocab_size, cfg.num_layers
    per_block = 4 * d * d + 3 * d * f + 2 * d
    expected_total = v * d + L * per_block + d + d * v
    assert count_params(params, non_embedding=False) == expected_total
    assert count_params(params, non_embedding=True) == expected_total - d * v


def test_model_size_table():
    assert set(MODEL_SIZES) == {"small", "medium", "large", "xl", "2.7b"}
    cfg = config_for_size("small")
    assert (cfg.d_model, cfg.d_ff, cfg.num_layers, cfg.num_heads) == (768, 3072, 12, 12)


def test_generate_shapes_eos_and_topk():
    cfg = tiny_cfg()
    params = init_transformer_lm(jax.random.PRNGKey(0), cfg)
    prompt = jnp.array([1, 2, 3])
    out = generate(params, cfg, prompt, 5, jax.random.PRNGKey(7), temperature=0.8, top_k=10)
    assert out.shape[0] <= 5
    assert out.dtype == jnp.int32
    assert np.all(np.asarray(out) >= 0) and np.all(np.asarray(out) < cfg.vocab_size)


def test_generate_eos_stops_early():
    """EOS must terminate sampling and must not be appended to the output.

    top_k=1 makes sampling deterministic (argmax); running once without an
    eos_token_id gives the greedy continuation, then designating its first
    token as EOS must produce an empty output.
    """
    cfg = tiny_cfg()
    params = init_transformer_lm(jax.random.PRNGKey(0), cfg)
    prompt = jnp.array([1, 2, 3])
    free = generate(params, cfg, prompt, 4, jax.random.PRNGKey(3), top_k=1)
    assert free.shape[0] == 4
    first = int(free[0])
    stopped = generate(
        params, cfg, prompt, 4, jax.random.PRNGKey(3), top_k=1, eos_token_id=first
    )
    assert stopped.shape[0] == 0
    # an EOS id that never wins argmax must not stop generation
    other = (first + 1) % cfg.vocab_size
    if other not in [int(t) for t in free]:
        full = generate(
            params, cfg, prompt, 4, jax.random.PRNGKey(3), top_k=1, eos_token_id=other
        )
        assert [int(t) for t in full] == [int(t) for t in free]


def test_config_validation():
    with pytest.raises(ValueError):
        TransformerConfig(
            vocab_size=32, context_length=16, d_model=65,
            num_layers=1, num_heads=4, d_ff=64,
        )
    with pytest.raises(ValueError):
        tiny_cfg(attn_impl="nope")


def test_checkpoint_roundtrip(tmp_path):
    cfg = tiny_cfg()
    params = init_transformer_lm(jax.random.PRNGKey(0), cfg)
    from cs336_systems_tpu.optim.adamw import adamw_init

    opt_state = adamw_init(params)
    save_checkpoint(str(tmp_path), params, config=cfg, opt_state=opt_state, step=42)
    ck = load_checkpoint(str(tmp_path))
    cfg2 = TransformerConfig.from_dict(ck["config"])
    assert cfg2 == cfg
    assert ck["step"] == 42
    for a, b in zip(
        jax.tree_util.tree_leaves(params), jax.tree_util.tree_leaves(ck["params"])
    ):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    x = jax.random.randint(jax.random.PRNGKey(1), (1, 8), 0, cfg.vocab_size)
    np.testing.assert_allclose(
        np.asarray(transformer_lm(params, x, cfg)),
        np.asarray(transformer_lm(ck["params"], x, cfg2)),
        rtol=1e-6,
    )


def test_hmajor_fold_matches_default():
    """attn_fold='hb' (head-major projections writing the kernel layout
    directly) must be numerically equivalent to the default fold — fwd and
    grads — including with a sliding window."""
    import dataclasses

    from cs336_systems_tpu.models.transformer import (
        TransformerConfig,
        init_transformer_lm,
        transformer_lm,
    )

    for window in (None, 16):
        # baseline must be the EXPLICIT "bh" fold — "hb" is the config
        # default, so comparing against the default would compare a
        # computation to itself
        cfg = TransformerConfig(
            vocab_size=64, context_length=64, d_model=64, num_layers=2,
            num_heads=4, d_ff=128, attn_impl="flash_ref", attn_window=window,
            attn_fold="bh",
        )
        cfg_hb = dataclasses.replace(cfg, attn_fold="hb")
        params = init_transformer_lm(jax.random.PRNGKey(0), cfg)
        x = jax.random.randint(jax.random.PRNGKey(1), (2, 64), 0, 64)

        out = transformer_lm(params, x, cfg)
        out_hb = transformer_lm(params, x, cfg_hb)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(out_hb), rtol=1e-4, atol=1e-5
        )

        def loss(p, c):
            return jnp.sum(transformer_lm(p, x, c).astype(jnp.float32) ** 2)

        g = jax.grad(lambda p: loss(p, cfg))(params)
        g_hb = jax.grad(lambda p: loss(p, cfg_hb))(params)
        from common import trees_allclose

        assert trees_allclose(g_hb, g, rtol=1e-3, atol=1e-4), f"window={window}"

    import pytest as _pytest

    with _pytest.raises(ValueError, match="single-device"):
        TransformerConfig(
            vocab_size=64, context_length=64, d_model=64, num_layers=1,
            num_heads=4, d_ff=128, attn_impl="flash", attn_fold="hb",
            attn_head_shard="tp",
        )


def test_lm_attn_window_locality():
    """With attn_window=W, a token's logits must be invariant to input
    changes more than W positions back (and sensitive within the window)."""
    import dataclasses

    from cs336_systems_tpu.models.transformer import (
        TransformerConfig,
        init_transformer_lm,
        transformer_lm,
    )

    cfg = TransformerConfig(
        vocab_size=32, context_length=64, d_model=32, num_layers=1,
        num_heads=2, d_ff=64, attn_impl="flash_ref", attn_window=8,
    )
    params = init_transformer_lm(jax.random.PRNGKey(0), cfg)
    ids = jnp.asarray(np.random.default_rng(0).integers(0, 32, (1, 64)), jnp.int32)
    base = transformer_lm(params, ids, cfg)

    # single-layer window=8: position 40 sees inputs 33..40 only
    far = ids.at[0, 10].set((int(ids[0, 10]) + 1) % 32)
    near = ids.at[0, 38].set((int(ids[0, 38]) + 1) % 32)
    out_far = transformer_lm(params, far, cfg)
    out_near = transformer_lm(params, near, cfg)
    np.testing.assert_allclose(
        np.asarray(out_far[0, 40]), np.asarray(base[0, 40]), atol=1e-5
    )
    assert float(jnp.max(jnp.abs(out_near[0, 40] - base[0, 40]))) > 1e-4

    # config validation
    with pytest.raises(ValueError, match="attn_window"):
        dataclasses.replace(cfg, attn_window=0)
    # window + ring is a supported combination (truncated ring — see
    # parallel/ring.py; equivalence pinned in test_tp_sp.py)
    TransformerConfig(
        vocab_size=32, context_length=64, d_model=32, num_layers=1,
        num_heads=2, d_ff=64, attn_impl="ring", sp_axis="sp",
        attn_window=8,
    )
