"""Test configuration.

Mirrors the reference test strategy (SURVEY §4): distributed tests run
without TPU hardware by forcing the CPU backend with 8 virtual XLA devices
(the analogue of the reference's world_size=2 Gloo process groups), so the
suite is exercised hermetically on CPU CI. Pallas kernels run in interpreter
mode on CPU and compiled on real TPU.

Env vars must be set before jax initialises, hence the top-of-file block.
"""

import os
import sys

# Force CPU regardless of ambient JAX_PLATFORMS (e.g. a tunneled TPU):
# the suite must run hermetically on CI. Set CS336_TPU_TESTS=1 to run the
# TPU-gated kernel tests on real hardware instead.
if not os.environ.get("CS336_TPU_TESTS"):
    os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: E402

if not os.environ.get("CS336_TPU_TESTS"):
    # A site-level plugin (e.g. a tunneled TPU PJRT backend) may have
    # imported jax before this conftest and pinned jax_platforms from the
    # ambient env; the live-config update below wins over both.
    jax.config.update("jax_platforms", "cpu")

jax.config.update("jax_enable_x64", False)

import pytest  # noqa: E402


def pytest_addoption(parser):
    parser.addoption(
        "--snapshot-exact",
        action="store_true",
        help="Require exact snapshot matches (parity with reference conftest).",
    )


@pytest.fixture
def on_tpu() -> bool:
    return jax.default_backend() == "tpu"
