"""Fleet router (ISSUE 14): prefix-affinity dispatch, the health state
machine, mid-stream failover, and the fleetsan fault matrix.

The load-bearing properties, in the order they compose:

1. TRANSPARENCY — a 1-replica router with affinity off drives the
   engine through the exact same submit/step sequence as calling it
   directly: per-step event lists and final results byte-identical (the
   router is pure host-side control plane; the jit step program is
   pinned separately by the serve_engine lint families).
2. AFFINITY — same-prefix sessions land on the replica that already
   holds the KV (the trie is shard-local, so the fleet hit rate is a
   routing property).
3. FAILOVER BIT-EXACTNESS — a stream is a pure function of (params,
   base key, row, prompt), so a request replayed on a survivor after a
   mid-stream kill produces the identical tokens, verified against the
   row-keyed oracle ``generate_kv_batched(row_keyed=True)`` — the same
   oracle discipline as tests/test_serving_engine.py — and the
   at-most-once emit cursor delivers each token to the client exactly
   once across the replay.
4. DEGRADATION — zero survivors sheds every request with the retriable
   typed error; ``run()`` terminates, never hangs.
5. The fleetsan matrix (serving/fleet_chaos.py): every seeded
   fleet-level fault surfaces its expected typed error with bit-exact
   survivors, on single-device and dp2-per-replica meshes alike.
"""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

import jax

from cs336_systems_tpu.analysis import servetrace
from cs336_systems_tpu.models.decode import generate_kv_batched
from cs336_systems_tpu.models.transformer import (
    TransformerConfig,
    init_transformer_lm,
)
from cs336_systems_tpu.serving import (
    FleetInvariantViolation,
    FleetRouter,
    ReplicaUnavailable,
    Request,
    ServingEngine,
    fleet_chaos,
)

CFG = TransformerConfig(
    vocab_size=64, context_length=64, d_model=64,
    num_layers=2, num_heads=4, d_ff=128,
)
BLK = 8
NEW = 8
N_REQ = 6


@pytest.fixture(scope="module")
def params():
    return init_transformer_lm(jax.random.PRNGKey(1), CFG)


@pytest.fixture(scope="module")
def prompts():
    """One shared full-block session prefix + distinct 4-token tails —
    the affinity-routable shape (every prompt shares its first chain
    hash)."""
    rng = np.random.default_rng(7)
    prefix = rng.integers(0, CFG.vocab_size, size=BLK)
    return [np.concatenate([prefix, rng.integers(0, CFG.vocab_size,
                                                 size=4)]).astype(np.int32)
            for _ in range(N_REQ)]


@pytest.fixture(scope="module")
def oracle(params, prompts):
    padded = np.zeros((len(prompts), BLK + 4), np.int32)
    for i, p in enumerate(prompts):
        padded[i, :p.size] = p
    return np.asarray(generate_kv_batched(
        params, CFG, padded, NEW, jax.random.PRNGKey(0), temperature=0.9,
        top_k=8, row_keyed=True, prompt_lens=[p.size for p in prompts],
        page_block=BLK))


def _engine(params, **kw):
    base = dict(key=jax.random.PRNGKey(0), slots=4, n_pages=16,
                max_blocks=4, page_block=BLK, temperature=0.9, top_k=8)
    base.update(kw)
    return ServingEngine(params, CFG, **base)


def _requests(prompts):
    return [Request(i, np.array(p), max_new_tokens=NEW, arrival=0.0)
            for i, p in enumerate(prompts)]


def _tick():
    it = iter(np.arange(0.0, 1e4, 0.5))
    return lambda: float(next(it))


# --- 1-replica transparency --------------------------------------------


def test_single_replica_byte_identical_to_direct_engine(params, prompts):
    """Same virtual clock, same requests: the per-step event sequences
    and final results of router(1 replica, affinity off) and the bare
    engine must be identical — the router adds decisions only when there
    is more than one replica to decide between."""
    direct = _engine(params)
    routed = FleetRouter([_engine(params)], policy="least-loaded")
    for r in _requests(prompts):
        direct.submit(r)
    for r in _requests(prompts):
        routed.submit(r)
    t = 0.0
    for _ in range(64):
        ev_d = direct.step(t)
        ev_r = routed.step(t)
        assert ev_d == ev_r, f"step events diverged at t={t}"
        t += 1.0
        if not direct.running and not len(direct.scheduler):
            break
    assert set(direct.results) == set(routed.results)
    for rid in direct.results:
        assert np.array_equal(np.asarray(direct.results[rid]),
                              np.asarray(routed.results[rid]))
    assert routed.failovers == 0 and routed.quarantines == 0
    direct.check_idle()
    routed.check_idle()
    routed.self_check()


# --- prefix-affinity dispatch ------------------------------------------


def test_affinity_pins_sessions_and_balances_cold(params):
    """Two sessions over three replicas: every session-A request lands
    on the replica that admitted session A's first request (warm KV),
    session B on a different one (least-loaded at first sight), and the
    third replica serves nothing."""
    rng = np.random.default_rng(11)
    pref_a = rng.integers(0, CFG.vocab_size, size=BLK)
    pref_b = rng.integers(0, CFG.vocab_size, size=BLK)
    reqs = []
    for i in range(8):
        pref = pref_a if i % 2 == 0 else pref_b
        prompt = np.concatenate(
            [pref, rng.integers(0, CFG.vocab_size, size=4)]).astype(np.int32)
        # staggered arrivals so each session's first prefill PUBLISHES
        # before the next member admits (simultaneous admits are all
        # cold by construction); pinning is submit-order based, so the
        # homes are deterministic either way
        reqs.append(Request(i, prompt, max_new_tokens=4,
                            arrival=float(i) * 5.0))
    router = FleetRouter([_engine(params) for _ in range(3)],
                         policy="affinity")
    for r in reqs:
        router.submit(r)
    router.run(time_fn=_tick())
    router.check_idle()
    assert set(router.results) == {r.rid for r in reqs}
    homes = {rid: next(k for k, eng in enumerate(router.engines)
                       if rid in eng.results)
             for rid in router.results}
    a_home = {homes[rid] for rid in (0, 2, 4, 6)}
    b_home = {homes[rid] for rid in (1, 3, 5, 7)}
    assert len(a_home) == 1 and len(b_home) == 1, \
        f"a session scattered across replicas: {homes}"
    assert a_home != b_home, "cold sessions must balance, not pile up"
    # the pinned replicas paid each prefix's prefill once — later
    # session members hit the shard-local trie
    for home in (a_home | b_home):
        eng = router.engines[home]
        assert eng.prefix_hit_tokens > 0


@pytest.mark.slow
def test_router_policies_all_complete(params, prompts, oracle):
    """random and least-loaded scatter the session (no affinity), but
    every stream is still bit-exact — placement never changes tokens."""
    for policy in ("random", "least-loaded"):
        router = FleetRouter([_engine(params) for _ in range(3)],
                             policy=policy, seed=3)
        for r in _requests(prompts):
            router.submit(r)
        router.run(time_fn=_tick())
        router.check_idle()
        assert set(router.results) == set(range(N_REQ))
        for rid, toks in router.results.items():
            n = len(np.asarray(toks))
            assert np.array_equal(np.asarray(toks), oracle[rid, :n])


# --- mid-stream failover -----------------------------------------------


def test_kill_mid_stream_failover_bit_exact(params, prompts, oracle):
    """Kill the replica holding every in-flight stream after 3 steps:
    the requests replay from the prompt on survivors and the final
    streams equal the row-keyed oracle bitwise; the emit cursor delivers
    each token to the client exactly once (no duplicate, no tear)."""
    delivered: dict[int, list[int]] = {}
    router = FleetRouter([_engine(params) for _ in range(3)],
                         policy="affinity",
                         on_token=lambda rid, tok:
                         delivered.setdefault(rid, []).append(tok))
    reqs = _requests(prompts)
    for r in reqs:
        router.submit(r)
    t = 0.0
    for _ in range(3):
        router.step(t)
        t += 1.0
    victim = router._where[0]  # the shared session's pinned replica
    assert any(len(eng.running) for eng in router.engines), \
        "trace drained before the kill — nothing in flight"
    router.kill(victim)
    assert router.replicas[victim].state == "quarantined"
    assert router.failovers >= 1
    while router._open:
        router.step(t)
        t += 1.0
        router.self_check()
    router.check_idle()
    assert set(router.results) == {r.rid for r in reqs}, \
        f"lost requests: failed={list(router.failed)}"
    for rid, toks in router.results.items():
        arr = np.asarray(toks)
        assert np.array_equal(arr, oracle[rid, :len(arr)]), \
            f"rid {rid}: failed-over stream diverged from the oracle"
        # the client saw each token exactly once, in order
        assert delivered[rid] == list(arr), \
            f"rid {rid}: client stream duplicated or torn"
    # the caller's original Request objects carry the full stream too
    # (the benchmark reads these)
    for r in reqs:
        assert r.tokens == list(np.asarray(router.results[r.rid]))
        assert len(r.emit_times) == len(r.tokens)


def test_torn_stream_detected(params, prompts):
    """A replayed token that diverges from the already-delivered prefix
    is a torn stream — FleetInvariantViolation, never silent."""
    router = FleetRouter([_engine(params) for _ in range(2)])
    for r in _requests(prompts[:2]):
        router.submit(r)
    t = 0.0
    while not router._delivered.get(0):
        router.step(t)
        t += 1.0
    good = router._delivered[0][0]
    with pytest.raises(FleetInvariantViolation, match="torn stream"):
        router._seen[(0, 1)] = 0  # a fresh replay stream on replica 1
        router._on_token(1, 0, good + 1)


def test_watchdog_quarantines_hung_replica(params, prompts):
    """A replica with running slots that stops producing events trips
    the dispatch watchdog after ``watchdog_steps`` and its streams
    complete on the survivor."""
    router = FleetRouter([_engine(params) for _ in range(2)],
                         policy="affinity", watchdog_steps=3)
    for r in _requests(prompts):
        router.submit(r)
    t = 0.0
    for _ in range(2):
        router.step(t)
        t += 1.0
    victim = router._where[0]
    assert router.engines[victim].running
    router.replicas[victim].engine.step = lambda now=None: []
    while router._open:
        router.step(t)
        t += 1.0
    assert router.replicas[victim].state == "quarantined"
    assert any(isinstance(e, ReplicaUnavailable)
               and "watchdog" in str(e) for e in router.faults)
    assert set(router.results) == set(range(N_REQ))


def test_shed_storm_degrades_never_hangs(params, prompts):
    """Zero survivors: every request fails with the retriable typed
    error and run() returns — proportional degradation, not a cliff."""

    def _boom(now=None):
        raise RuntimeError("outage")

    router = FleetRouter([_engine(params) for _ in range(2)])
    for r in _requests(prompts):
        router.submit(r)
    for rep in router.replicas:
        rep.engine.step = _boom
    router.run(time_fn=_tick())  # must terminate
    assert not router._open
    assert all(rep.state == "quarantined" for rep in router.replicas)
    assert set(router.failed) == set(range(N_REQ))
    for err in router.failed.values():
        assert isinstance(err, ReplicaUnavailable) and err.retriable
    # and a fleet that is already fully down rejects at submit time
    with pytest.raises(ReplicaUnavailable, match="no healthy replica"):
        router.submit(Request(99, np.array(prompts[0]), 2, arrival=0.0))


def test_duplicate_dispatch_caught_structurally(params, prompts):
    """The same rid live on two replicas emits IDENTICAL tokens (same
    key chain) — token-level checks cannot see it, the liveness sweep
    must."""
    router = FleetRouter([_engine(params) for _ in range(2)])
    for r in _requests(prompts[:3]):
        router.submit(r)
    t = 0.0
    for _ in range(2):
        router.step(t)
        t += 1.0
    rid = next(iter(router._where))
    other = 1 - router._where[rid]
    router.engines[other].submit(
        Request(rid, np.array(prompts[rid]), 2, arrival=0.0))
    with pytest.raises(FleetInvariantViolation,
                       match="live on two replicas"):
        router.self_check()


def test_router_validates_fleet_construction(params):
    """Mismatched base keys would silently break failover bit-exactness
    — rejected at construction, not discovered at the first kill."""
    with pytest.raises(ValueError, match="base key"):
        FleetRouter([_engine(params),
                     _engine(params, key=jax.random.PRNGKey(9))])
    with pytest.raises(ValueError, match="at least one"):
        FleetRouter([])
    with pytest.raises(ValueError, match="unknown policy"):
        FleetRouter([_engine(params)], policy="round-robin")


# --- servetrace fleet fold ---------------------------------------------


def test_fold_fleet_additive_fields(params, prompts):
    """fold() on a router emits the servetrace/v1 schema plus the
    additive fleet section; per-request conservation holds across a
    mid-trace kill, and the single-engine fold stays byte-compatible
    (no fleet keys) so committed artifacts diff unchanged."""
    router = FleetRouter([_engine(params) for _ in range(2)],
                         policy="affinity")
    for r in _requests(prompts):
        router.submit(r)
    t = 0.0
    for _ in range(3):
        router.step(t)
        t += 1.0
    router.kill(router._where[0])
    while router._open:
        router.step(t)
        t += 1.0
    art = servetrace.fold(router, family="serve_engine_prefix")
    assert art["schema"] == servetrace.SCHEMA
    assert art["fleet"]["replicas"] == 2
    assert art["fleet"]["quarantines"] == 1
    assert art["requests"]["failovers"] == router.failovers >= 1
    assert art["requests"]["completed"] == N_REQ
    assert art["conservation"]["ok"], art["conservation"]
    states = art["fleet"]["states"]
    assert states.count("quarantined") == 1
    assert len(art["fleet"]["per_replica"]) == 2
    # old single-engine artifacts: no fleet keys anywhere
    solo = _engine(params)
    for r in _requests(prompts):
        solo.submit(r)
    solo.run(time_fn=_tick())
    art1 = servetrace.fold(solo, family="serve_engine_prefix")
    assert "fleet" not in art1
    assert "failovers" not in art1["requests"]
    # fleet artifacts pass through the same CI diff gate
    d = servetrace.diff_servetraces(art, art)
    assert d["n_flagged"] == 0


# --- the fleetsan matrix -----------------------------------------------


@pytest.mark.slow
def test_fleetsan_single_fault_and_clean_smoke():
    """Fleetsan verdict smoke: one absorbed fault (replica-crash →
    quarantine + failover, bit-exact survivors) plus the clean
    false-positive gate. Tier 2 with the full matrix — the harness
    builds its own fleet/oracle shapes, and tier 1 already drives the
    same failure paths through the router API directly (kill/watchdog/
    torn-stream/duplicate tests above); the per-fault CI gate runs
    every fault in scripts/run_tests_and_package.sh."""
    row = fleet_chaos.run_fault("replica-crash", "none")
    assert row["ok"], row
    assert row["error"]["type"] == "ReplicaUnavailable"
    clean = fleet_chaos.run_clean("none")
    assert clean["ok"], clean


@pytest.mark.slow
@pytest.mark.parametrize("mesh", ["none", "dp2"])
def test_fleetsan_matrix_detects_every_fault(mesh):
    """Every seeded fleet-level fault must surface its EXPECTED typed
    error with bit-exact surviving streams, and the un-injected fleet
    must drain with zero findings — identically on single-device and
    dp2-per-replica meshes (the router is host-side control plane)."""
    rows = [fleet_chaos.run_fault(name, mesh)
            for name in fleet_chaos.fault_names()]
    rows.append(fleet_chaos.run_clean(mesh))
    bad = [(r["fault"], r.get("error")) for r in rows if not r["ok"]]
    assert not bad, f"fleetsan verdicts failed on {mesh}: {bad}"
    assert len(rows) == len(fleet_chaos.fault_names()) + 1 >= 8


@pytest.mark.slow
def test_fleetsan_cli_contract():
    """--list enumerates ≥7 fault classes fast (no fleet build), a
    single-fault run reports ok with exit 0, and an unknown fault is the
    exit-2 build error, not a miss."""
    env = dict(os.environ, PALLAS_AXON_POOL_IPS="")
    base = [sys.executable, "-m", "cs336_systems_tpu.serving.fleet_chaos"]

    ls = subprocess.run(base + ["--list", "--json"], env=env,
                        capture_output=True, text=True)
    assert ls.returncode == 0
    assert len(json.loads(ls.stdout)["faults"]) >= 7

    one = subprocess.run(base + ["--fault", "shed-storm", "--json"],
                         env=env, capture_output=True, text=True)
    assert one.returncode == 0, one.stdout + one.stderr
    row = json.loads(one.stdout)["rows"][0]
    assert row["ok"] and row["error"]["type"] == "ReplicaUnavailable"
    assert row["error"]["retriable"] is True

    bad = subprocess.run(base + ["--fault", "no-such-fault", "--json"],
                         env=env, capture_output=True, text=True)
    assert bad.returncode == 2
