"""Paged KV-cache decode tests (per-row attended-prefix serving).

The paging contract, each piece oracle-tested:

- geometry: row i owns ceil((len_i + new) / block) consecutive pages;
  table entries past a row's last page clamp to that page (valid
  prefetch targets, never attended, never the write-scratch page).
- kernel: the paged Pallas kernel (interpret mode — CI has no TPU) must
  match ``_attend_update_xla_paged``, the portable scatter/gather
  oracle, which in turn is BIT-IDENTICAL to the unpaged XLA path — so
  paged generation, at any skew, draws exactly the tokens the unpaged
  path draws.  Paging is a layout, not an approximation: the same
  discipline as the sharding tests.
- memory: the whole point — memkit's analyzed kv-cache bytes for the
  skewed registry family must undercut the unpaged twin by at least the
  analytic pool margin (sum of touched pages vs B·max rows).
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from cs336_systems_tpu.models.decode import (
    _attend_update_xla_paged,
    generate_kv_batched,
    init_paged_kv_cache,
    paged_kv_geometry,
)
from cs336_systems_tpu.models.transformer import (
    TransformerConfig,
    init_transformer_lm,
)
from cs336_systems_tpu.ops import decode_attention as da
from cs336_systems_tpu.parallel.mesh import make_mesh
from cs336_systems_tpu.parallel.serve import make_sharded_generate

CFG = TransformerConfig(
    vocab_size=64, context_length=64, d_model=64,
    num_layers=2, num_heads=4, d_ff=128,
)

# the skewed profile every generation test reuses: spread 12x, two rows
# at the max so the bucket boundary is shared, one length-1 row
SKEW_LENS = np.asarray([12, 3, 7, 1, 12, 5, 9, 2])


# --- geometry ---------------------------------------------------------------


def test_paged_geometry_hand_computed():
    # lens [3, 12, 6] + new 4, block 8 -> pages ceil([7,16,10]/8) = [1,2,2]
    g = paged_kv_geometry([3, 12, 6], 4, block=8)
    assert (g.block, g.n_pages, g.max_blocks) == (8, 5, 2)
    # row 0 has ONE page: its second table entry clamps to its own page 0
    np.testing.assert_array_equal(g.tables, [[0, 0], [1, 2], [3, 4]])
    np.testing.assert_array_equal(g.page_rows, [0, 1, 1, 2, 2])
    np.testing.assert_array_equal(g.page_blks, [0, 0, 1, 0, 1])


def test_paged_geometry_validation():
    with pytest.raises(ValueError, match="multiple of 8"):
        paged_kv_geometry([4, 4], 2, block=12)
    with pytest.raises(ValueError, match="multiple of 8"):
        paged_kv_geometry([4, 4], 2, block=0)
    with pytest.raises(ValueError, match="non-empty"):
        paged_kv_geometry([], 2, block=8)
    with pytest.raises(ValueError, match="non-empty"):
        paged_kv_geometry([[3, 4]], 2, block=8)


def test_paged_pool_shape_and_scratch_page():
    g = paged_kv_geometry([3, 12, 6], 4, block=8)
    cache = init_paged_kv_cache(CFG, g.n_pages, g.block)
    assert len(cache["kv"]) == CFG.num_layers
    # +1 page: the kernel's reserved write scratch, never in a table
    assert cache["kv"][0].shape == (g.n_pages + 1, CFG.num_heads, g.block,
                                    2 * CFG.d_head)
    assert g.tables.max() < g.n_pages


def test_paged_attended_kv_bytes_tracks_sum_not_max():
    # one 1000-token straggler among 8-token rows: the paged DMA bytes
    # follow sum(ceil(len_i/block)), the unpaged kernel's follow B*max
    lens = [8, 1000, 8, 8]
    w, it = 256, 2
    paged = da.paged_attended_kv_bytes(lens, 128, w, it)
    unpaged = len(lens) * 1024 * w * it  # B * bucketed-max rows
    assert paged == (1 + 8 + 1 + 1) * 128 * w * it
    assert paged < 0.4 * unpaged


# --- kernel vs the XLA paged oracle (interpret mode) ------------------------


@pytest.mark.parametrize("pos,window", [
    ([3, 8, 17, 25], None),   # mid-page, page start (pos%block==0), deep
    ([0, 15, 31, 39], None),  # first token ever + last-row-of-page cases
    ([3, 8, 17, 25], 8),      # sliding window crossing page boundaries
])
@pytest.mark.parametrize("dtype", ["float32", "bfloat16"])
def test_paged_kernel_matches_oracle(pos, window, dtype):
    # fp32 d_head=16 exercises the narrow-head group cap (g<=2); bf16
    # takes the full group ladder (g=4 at these shapes)
    b, h, d, block = 4, 4, 16, 8
    g = paged_kv_geometry([3, 8, 17, 25], 8, block=block)
    key = jax.random.PRNGKey(0)
    ks = jax.random.split(key, 4)
    q = jax.random.normal(ks[0], (b, h, 1, d), dtype)
    k_new = jax.random.normal(ks[1], (b, h, 1, d), dtype)
    v_new = jax.random.normal(ks[2], (b, h, 1, d), dtype)
    pool = jax.random.normal(
        ks[3], (g.n_pages + 1, h, block, 2 * d), dtype)
    tables = jnp.asarray(g.tables, jnp.int32)
    posv = jnp.asarray(pos, jnp.int32)

    o_ref, pool_ref = _attend_update_xla_paged(
        q, pool, k_new, v_new, posv, tables, block, window=window)
    o_got, pool_got = da.paged_decode_attention_update(
        q, k_new, v_new, pool, tables, posv, window=window)

    tol = dict(rtol=2e-2, atol=2e-2) if dtype == "bfloat16" else \
        dict(rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(
        np.asarray(o_got, np.float32), np.asarray(o_ref, np.float32), **tol)
    # the pool write is a plain store: REAL pages bit-exact (the scratch
    # page absorbs interpret-mode's every-step output flushes — excluded)
    np.testing.assert_array_equal(
        np.asarray(pool_got[:g.n_pages]), np.asarray(pool_ref[:g.n_pages]))


def test_paged_kernel_validation():
    b, h, d, block = 2, 4, 16, 8
    g = paged_kv_geometry([3, 4], 4, block=block)
    q = jnp.zeros((b, h, 1, d))
    pool = jnp.zeros((g.n_pages + 1, h, block, 2 * d))
    tables = jnp.asarray(g.tables, jnp.int32)
    pos = jnp.asarray([3, 4], jnp.int32)
    with pytest.raises(ValueError, match="head axis"):
        da.paged_decode_attention_update(
            q, q, q, pool[:, :2], tables, pos)
    with pytest.raises(ValueError, match="table rows"):
        da.paged_decode_attention_update(
            q, q, q, pool, tables[:1], pos)


def test_paged_supported_gate():
    assert da.paged_supported(128, 128, 2)
    assert da.paged_supported(128, 128, 4)
    assert not da.paged_supported(12, 128, 2)   # not 8-row-aligned


# --- paged generation == unpaged generation (bit-exact) ---------------------


@pytest.fixture(scope="module")
def params():
    return init_transformer_lm(jax.random.PRNGKey(0), CFG)


def _prompts(batch=8, plen=12, seed=0):
    prompts = jax.random.randint(
        jax.random.PRNGKey(seed + 1), (batch, plen), 0, CFG.vocab_size)
    key = jax.random.PRNGKey(seed + 2)
    return prompts, key


@pytest.mark.parametrize("lens", [None, SKEW_LENS],
                         ids=["uniform", "skewed"])
def test_paged_generate_matches_unpaged(params, lens):
    """Same prompts, keys and sampling; only the cache layout differs.
    Every attended column holds the same value in both layouts and the
    clamped/junk page columns are masked to exact softmax zeros, so the
    token streams are IDENTICAL — at uniform lengths and at 12x skew."""
    prompts, key = _prompts()
    want = np.asarray(generate_kv_batched(
        params, CFG, prompts, 10, key, temperature=0.9, top_k=8,
        row_keyed=True, prompt_lens=lens))
    got = np.asarray(generate_kv_batched(
        params, CFG, prompts, 10, key, temperature=0.9, top_k=8,
        row_keyed=True, prompt_lens=lens, page_block=8))
    np.testing.assert_array_equal(got, want)


def test_paged_generate_pallas_matches_xla(params):
    """The paged kernel (interpret mode) inside full generation: forced
    attn_impl='pallas' must draw the same tokens as the XLA paged path —
    which the test above pins to the unpaged path."""
    prompts, key = _prompts()
    kw = dict(temperature=0.9, top_k=8, row_keyed=True,
              prompt_lens=SKEW_LENS, page_block=8)
    want = np.asarray(generate_kv_batched(
        params, CFG, prompts, 10, key, attn_impl="xla", **kw))
    got = np.asarray(generate_kv_batched(
        params, CFG, prompts, 10, key, attn_impl="pallas", **kw))
    np.testing.assert_array_equal(got, want)


def test_paged_generate_windowed(params):
    """Sliding-window attention composes with paging: the window mask is
    applied over the gathered per-row prefix exactly as over the
    contiguous cache."""
    cfg = dataclasses.replace(CFG, attn_window=8)
    wparams = init_transformer_lm(jax.random.PRNGKey(3), cfg)
    prompts, key = _prompts()
    want = np.asarray(generate_kv_batched(
        wparams, cfg, prompts, 10, key, temperature=0.9, top_k=8,
        row_keyed=True, prompt_lens=SKEW_LENS))
    got = np.asarray(generate_kv_batched(
        wparams, cfg, prompts, 10, key, temperature=0.9, top_k=8,
        row_keyed=True, prompt_lens=SKEW_LENS, page_block=8))
    np.testing.assert_array_equal(got, want)


# --- sharded paged serving --------------------------------------------------


@pytest.mark.parametrize("mesh_axes,dp,tp", [
    ({"dp": 8}, "dp", None),
    ({"dp": 2, "tp": 4}, "dp", "tp"),
])
def test_sharded_paged_matches_single_device(params, mesh_axes, dp, tp):
    """Paged serving through the dp/tp server: per-shard page pools
    (shard-local ids, SPMD max-sized), tokens bit-equal to the
    single-device UNPAGED row-keyed path — paging plus sharding is still
    just a layout."""
    prompts, key = _prompts()
    want = np.asarray(generate_kv_batched(
        params, CFG, prompts, 10, key, temperature=0.9, top_k=8,
        row_keyed=True, prompt_lens=SKEW_LENS))
    mesh = make_mesh(mesh_axes)
    gen = make_sharded_generate(
        CFG, mesh, max_new_tokens=10, dp_axis=dp, tp_axis=tp,
        temperature=0.9, top_k=8, page_block=8)
    got = np.asarray(gen(params, prompts, key, prompt_lens=SKEW_LENS))
    np.testing.assert_array_equal(got, want)
    # the paged server also takes uniform batches (lens synthesized)
    got_u = np.asarray(gen(params, prompts, key))
    want_u = np.asarray(generate_kv_batched(
        params, CFG, prompts, 10, key, temperature=0.9, top_k=8,
        row_keyed=True))
    np.testing.assert_array_equal(got_u, want_u)


def test_sharded_paged_moe_expert_sharded():
    """Paged serving composed with expert sharding (dp x ep): the page
    pool shards with its batch rows over dp and replicates over ep, the
    MoE combine psum is untouched — bit-identical at top_k=2."""
    cfg = dataclasses.replace(CFG, num_experts=8, moe_top_k=2)
    mparams = init_transformer_lm(jax.random.PRNGKey(5), cfg)
    prompts, key = _prompts()
    lens = np.asarray([3, 6, 2, 5, 12, 4, 1, 6])
    want = np.asarray(generate_kv_batched(
        mparams, cfg, prompts, 8, key, temperature=0.9, top_k=8,
        row_keyed=True, prompt_lens=lens))
    mesh = make_mesh({"dp": 2, "ep": 4})
    gen = make_sharded_generate(cfg, mesh, max_new_tokens=8, dp_axis="dp",
                                ep_axis="ep", temperature=0.9, top_k=8,
                                page_block=8)
    got = np.asarray(gen(mparams, prompts, key, prompt_lens=lens))
    np.testing.assert_array_equal(got, want)


def test_sharded_paged_block_validation():
    gen = make_sharded_generate(CFG, make_mesh({"dp": 4}),
                                max_new_tokens=4, page_block=12)
    p = init_transformer_lm(jax.random.PRNGKey(0), CFG)
    prompts, key = _prompts(batch=4)
    with pytest.raises(ValueError, match="multiple of 8"):
        gen(p, prompts, key)


# --- the memory claim: pool bytes vs B*max ----------------------------------


def test_memkit_paged_pool_beats_unpaged_cache():
    """The headline assertion: memkit's analyzed kv-cache bytes for the
    skewed serve_ragged_paged family must undercut an UNPAGED server on
    the identical workload by at least the analytic pool margin.

    Registry shape (analysis/registry.serve_ragged_lens): 8 rows over
    dp=8, lens [6,2,...,2], max_new 4, 8-row pages -> each shard's pool
    is max-local 2 pages + 1 scratch = 24 rows, vs the unpaged path's
    64-row bucket-rounded alloc. Margin per shard per layer:
    40 rows x H4 x W16 x 4B = 10240, x L2 = 20480 bytes."""
    from cs336_systems_tpu.analysis import memkit, registry

    paged = memkit.profile_family("serve_ragged_paged")
    paged_kv = paged["composition_bytes"].get("kv-cache", 0)
    assert paged_kv > 0  # the pool is seen and classified

    # unpaged twin: same mesh, lens, sampling — only page_block dropped
    cfg = registry._tiny_cfg()
    gen = make_sharded_generate(
        cfg, make_mesh({"dp": 8}), max_new_tokens=4, dp_axis="dp",
        temperature=0.9, top_k=8)
    lens = registry.serve_ragged_lens(True)
    fn = lambda p, i, k: gen(p, i, k, prompt_lens=lens)
    params = registry._abstract_params(cfg)
    ids = jax.ShapeDtypeStruct((8, 6), jnp.int32)
    key = jax.ShapeDtypeStruct((2,), jnp.uint32)
    unpaged = memkit.profile_callable(
        fn, (params, ids, key), family="serve_ragged_unpaged_twin",
        arg_classes=memkit._serve_arg_classes(), n_devices=8)
    unpaged_kv = unpaged["composition_bytes"].get("kv-cache", 0)

    w = 2 * cfg.d_head
    itemsize = jnp.dtype(cfg.cdtype).itemsize
    margin = 40 * cfg.num_heads * w * itemsize * cfg.num_layers
    assert unpaged_kv - paged_kv >= margin, (
        f"paged kv-cache {paged_kv} vs unpaged {unpaged_kv}: margin "
        f"{unpaged_kv - paged_kv} < analytic pool margin {margin}")


# --- analysis wiring --------------------------------------------------------


def test_ragged_decode_flops_mean_of_lens():
    from cs336_systems_tpu.analysis.flops import decode_flops_per_token
    from cs336_systems_tpu.analysis.registry import (
        _tiny_cfg,
        serve_ragged_lens,
    )

    cfg = _tiny_cfg()
    lens = serve_ragged_lens(True) + 4  # prompt + max_new, as tracekit does
    got = decode_flops_per_token(cfg, attend_lens=lens)
    # per-token share of the batch's attention work is the MEAN length
    assert got == decode_flops_per_token(cfg,
                                         attend_len=float(np.mean(lens)))
    # a skewed batch must NOT be billed at its max
    assert got < decode_flops_per_token(cfg, attend_len=int(lens.max()))
    with pytest.raises(ValueError, match="not both"):
        decode_flops_per_token(cfg, attend_len=8, attend_lens=lens)


def test_tracekit_paged_family_flops_crosscheck():
    """tracekit's serve_ragged_paged MFU denominator must be the
    per-row-lens FLOPs model — registry lens in, mean-of-lens out."""
    from cs336_systems_tpu.analysis import tracekit
    from cs336_systems_tpu.analysis.flops import decode_flops_per_token
    from cs336_systems_tpu.analysis.registry import (
        _tiny_cfg,
        serve_ragged_lens,
    )

    runner = tracekit.FAMILIES["serve_ragged_paged"]()
    want = decode_flops_per_token(
        _tiny_cfg(), attend_lens=serve_ragged_lens(True) + 4)
    assert runner.flops_per_token == want
