"""Worker for the multi-host readiness test (tests/test_multihost.py).

Run as ``python mh_worker.py <process_id> <num_processes> <coordinator>``
with JAX_PLATFORMS=cpu and --xla_force_host_platform_device_count set in
XLA_FLAGS by the launcher. Exercises the REAL multi-host code path the
reference fakes with mp.spawn+Gloo (tests/common.py:71-88): our
``init_distributed`` rendezvous, one ``make_mesh`` over the global device
view, and the unmodified DP train step — then prints the final loss and a
parameter checksum for the parent to compare across processes and against
the single-process run.
"""

import sys


def main() -> None:
    pid, nproc, coord = int(sys.argv[1]), int(sys.argv[2]), sys.argv[3]

    from cs336_systems_tpu.parallel.mesh import init_distributed, make_mesh

    assert init_distributed(coord, num_processes=nproc, process_id=pid) == nproc

    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec as P

    from cs336_systems_tpu.models.transformer import (
        TransformerConfig,
        init_transformer_lm,
    )
    from cs336_systems_tpu.optim.adamw import AdamWHparams, adamw_init
    from cs336_systems_tpu.parallel.dp import make_dp_train_step

    cfg = TransformerConfig(
        vocab_size=64, context_length=16, d_model=32,
        num_layers=2, num_heads=4, d_ff=64,
    )
    mesh = make_mesh()  # all global devices on dp — unchanged user code
    world = mesh.shape["dp"]

    # identical seeds on every process -> identical host values; lift onto
    # the global mesh via per-process local shards
    def globalize(host, spec):
        sh = NamedSharding(mesh, spec)
        return jax.make_array_from_callback(
            host.shape, sh, lambda idx: np.asarray(host)[idx]
        )

    params = init_transformer_lm(jax.random.PRNGKey(0), cfg)
    opt = adamw_init(params)
    params = jax.tree_util.tree_map(lambda a: globalize(np.asarray(a), P()), params)
    opt = jax.tree_util.tree_map(lambda a: globalize(np.asarray(a), P()), opt)

    rng = np.random.default_rng(1)
    step = make_dp_train_step(cfg, AdamWHparams(lr=1e-3), mesh, donate=False)
    loss = None
    for _ in range(2):
        x = rng.integers(0, cfg.vocab_size, (world, cfg.context_length),
                         dtype=np.int32)
        y = np.roll(x, -1, axis=-1)
        params, opt, loss = step(
            params, opt, globalize(x, P("dp")), globalize(y, P("dp"))
        )

    checksum = float(
        sum(
            jnp.sum(jnp.abs(leaf.addressable_data(0).astype(jnp.float64)))
            for leaf in jax.tree_util.tree_leaves(params)
        )
    )
    print(f"RESULT pid={pid} world={world} loss={float(loss):.8f} "
          f"checksum={checksum:.6f}", flush=True)


if __name__ == "__main__":
    main()
