"""Tensor-parallel and sequence-parallel (ring attention) tests.

All run on the 8-virtual-device CPU mesh (conftest). The oracles are
single-device computations: TP/SP must be numerically equivalent layouts,
not approximations.
"""

import dataclasses
import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from common import trees_allclose
from cs336_systems_tpu.models.transformer import (
    TransformerConfig,
    init_transformer_lm,
    transformer_lm,
)
from cs336_systems_tpu.optim.adamw import AdamWHparams, adamw_init
from cs336_systems_tpu.parallel.mesh import make_mesh
from cs336_systems_tpu.parallel.ring import ring_attention_with_lse
from cs336_systems_tpu.parallel.sp import make_sp_train_step, shard_batch_sp
from cs336_systems_tpu.parallel.tp import (
    make_tp_train_step,
    param_specs,
    shard_params,
    tp_param_bytes_per_device,
)
from cs336_systems_tpu.train import make_train_step


CFG = TransformerConfig(
    vocab_size=64, context_length=32, d_model=32,
    num_layers=2, num_heads=4, d_ff=64,
)


def _data(key, batch=4, ctx=32):
    x = jax.random.randint(key, (batch, ctx), 0, CFG.vocab_size)
    return x, jnp.roll(x, -1, axis=-1)


# ---------------------------------------------------------------------------
# Ring attention


@pytest.mark.parametrize("causal", [False, True])
def test_ring_attention_matches_dense(causal):
    """Exactness: ring over sp=4 == dense attention on the full sequence."""
    mesh = make_mesh({"sp": 4})
    b, s, d = 2, 64, 16
    ks = jax.random.split(jax.random.PRNGKey(0), 3)
    q, k, v = (jax.random.normal(kk, (b, s, d)) for kk in ks)

    def local(q, k, v):
        return ring_attention_with_lse(q, k, v, axis="sp", causal=causal)

    out, lse = jax.jit(
        jax.shard_map(
            local, mesh=mesh,
            in_specs=(P(None, "sp"), P(None, "sp"), P(None, "sp")),
            out_specs=(P(None, "sp"), P(None, "sp")),
        )
    )(q, k, v)

    scores = jnp.einsum("bqd,bkd->bqk", q, k) / jnp.sqrt(d)
    if causal:
        scores = jnp.where(jnp.tril(jnp.ones((s, s), bool)), scores, -1e30)
    ref = jnp.einsum("bqk,bkd->bqd", jax.nn.softmax(scores, -1), v)
    ref_lse = jax.scipy.special.logsumexp(scores, axis=-1)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5)
    np.testing.assert_allclose(np.asarray(lse), np.asarray(ref_lse), rtol=2e-5, atol=2e-5)


def test_ring_attention_grads_match_dense():
    mesh = make_mesh({"sp": 4})
    b, s, d = 2, 32, 8
    ks = jax.random.split(jax.random.PRNGKey(1), 3)
    q, k, v = (jax.random.normal(kk, (b, s, d)) for kk in ks)

    def ring_loss(q, k, v):
        def local(q, k, v):
            out, _ = ring_attention_with_lse(q, k, v, axis="sp", causal=True)
            return jax.lax.psum(jnp.sum(jnp.square(out.astype(jnp.float32))), "sp")

        return jax.shard_map(
            local, mesh=mesh,
            in_specs=(P(None, "sp"),) * 3, out_specs=P(),
        )(q, k, v)

    def dense_loss(q, k, v):
        scores = jnp.einsum("bqd,bkd->bqk", q, k) / jnp.sqrt(d)
        scores = jnp.where(jnp.tril(jnp.ones((s, s), bool)), scores, -1e30)
        out = jnp.einsum("bqk,bkd->bqd", jax.nn.softmax(scores, -1), v)
        return jnp.sum(jnp.square(out))

    g_ring = jax.jit(jax.grad(ring_loss, (0, 1, 2)))(q, k, v)
    g_dense = jax.grad(dense_loss, (0, 1, 2))(q, k, v)
    for a, b_ in zip(g_ring, g_dense):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_), rtol=1e-4, atol=1e-5)


@pytest.mark.parametrize("window", [8, 20, 64])
def test_ring_attention_windowed_matches_dense(window):
    """Sliding window under the ring: must equal dense banded attention.
    window=8 < S_local=16 truncates the ring to 2 hops; 20 needs 3; 64
    covers the full sequence (4 hops, same as unwindowed)."""
    from cs336_systems_tpu.ops.attention import attention_with_lse, banded_causal_mask

    mesh = make_mesh({"sp": 4})
    b, s, d = 2, 64, 16
    ks = jax.random.split(jax.random.PRNGKey(5), 3)
    q, k, v = (jax.random.normal(kk, (b, s, d)) for kk in ks)

    def ring_fn(q, k, v):
        def local(q, k, v):
            return ring_attention_with_lse(
                q, k, v, axis="sp", causal=True, window=window
            )

        return jax.shard_map(
            local, mesh=mesh,
            in_specs=(P(None, "sp"),) * 3,
            out_specs=(P(None, "sp"), P(None, "sp")),
        )(q, k, v)

    out, lse = jax.jit(ring_fn)(q, k, v)
    ref, ref_lse = attention_with_lse(q, k, v, banded_causal_mask(s, s, window))
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5)
    np.testing.assert_allclose(np.asarray(lse), np.asarray(ref_lse), rtol=2e-5, atol=2e-5)

    # the window truncates COMMUNICATION, not just masking: hops beyond
    # ceil((window-1)/S_local) never ppermute at all
    jaxpr = str(jax.make_jaxpr(ring_fn)(q, k, v))
    expected_hops = min(4, -(-(window - 1) // 16) + 1)
    assert jaxpr.count("ppermute") == 2 * (expected_hops - 1), (
        f"window={window}: expected {expected_hops - 1} K/V rotation(s)"
    )

    # gradients flow exactly through the truncated ring + flash merge
    def ring_loss(q, k, v):
        def local(q, k, v):
            o, _ = ring_attention_with_lse(
                q, k, v, axis="sp", causal=True, window=window
            )
            return jax.lax.psum(jnp.sum(o.astype(jnp.float32) ** 2), "sp")

        return jax.shard_map(
            local, mesh=mesh, in_specs=(P(None, "sp"),) * 3, out_specs=P(),
        )(q, k, v)

    def dense_loss(q, k, v):
        o, _ = attention_with_lse(q, k, v, banded_causal_mask(s, s, window))
        return jnp.sum(o ** 2)

    g_ring = jax.jit(jax.grad(ring_loss, (0, 1, 2)))(q, k, v)
    g_dense = jax.grad(dense_loss, (0, 1, 2))(q, k, v)
    for a, b_ in zip(g_ring, g_dense):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_), rtol=1e-4, atol=1e-5)


# ---------------------------------------------------------------------------
# SP train step


# These oracles were the "a2a/sp post-AdamW parity regression" pins
# (~41% first-step sign flips bounded by 2*lr). Root cause, found with
# analysis/gradsan: under this jax's forced check_rep=False shard_map
# (_compat.py), in-body value_and_grad yields LOCAL per-device gradients
# — the step must own the (dp × sp) pmean, which make_sp_train_step now
# issues via dp.sync_grads before clip/AdamW. The gradient-level ring
# tests above always passed because they take jax.grad OUTSIDE shard_map.
def test_sp_train_step_matches_single_device():
    """One dp×sp step == one single-device step on the same global batch."""
    mesh = make_mesh({"dp": 2, "sp": 4})
    params, opt = init_transformer_lm(jax.random.PRNGKey(0), CFG), None
    from cs336_systems_tpu.optim.adamw import adamw_init

    opt = adamw_init(params)
    hp = AdamWHparams(lr=1e-3)
    x, y = _data(jax.random.PRNGKey(1))

    ref_step = make_train_step(CFG, hp, clip_norm=1.0, donate=False)
    p_ref, o_ref, l_ref = ref_step(params, opt, x, y)

    sp_step = make_sp_train_step(CFG, hp, mesh, clip_norm=1.0, donate=False)
    xs, ys = shard_batch_sp(mesh, x, y)
    p_sp, o_sp, l_sp = sp_step(params, opt, xs, ys)

    np.testing.assert_allclose(float(l_sp), float(l_ref), rtol=1e-5)
    assert trees_allclose(p_sp, p_ref, rtol=1e-4, atol=1e-5)


def test_sp_rejects_sequence_beyond_context_length():
    """Global sequence sp*S_local > context_length must raise at trace time
    (silent RoPE out-of-bounds garbage otherwise)."""
    mesh = make_mesh({"sp": 4})
    params = init_transformer_lm(jax.random.PRNGKey(0), CFG)
    opt = adamw_init(params)
    step = make_sp_train_step(CFG, AdamWHparams(lr=1e-3), mesh, donate=False)
    # global S = 64 > context_length = 32
    x = jax.random.randint(jax.random.PRNGKey(3), (2, 64), 0, CFG.vocab_size)
    y = jnp.roll(x, -1, axis=-1)
    xs, ys = shard_batch_sp(mesh, x, y)
    with pytest.raises(ValueError, match="exceeds context_length"):
        step(params, opt, xs, ys)


def test_sp_only_mesh_no_dp_axis():
    mesh = make_mesh({"sp": 4})
    params = init_transformer_lm(jax.random.PRNGKey(0), CFG)
    from cs336_systems_tpu.optim.adamw import adamw_init

    opt = adamw_init(params)
    step = make_sp_train_step(CFG, AdamWHparams(lr=1e-3), mesh, donate=False)
    x, y = _data(jax.random.PRNGKey(2), batch=2)
    xs, ys = shard_batch_sp(mesh, x, y)
    _, _, loss = step(params, opt, xs, ys)
    assert np.isfinite(float(loss))


# ---------------------------------------------------------------------------
# TP train step


def test_tp_param_sharding_layout():
    mesh = make_mesh({"tp": 4})
    params = init_transformer_lm(jax.random.PRNGKey(0), CFG)
    sharded = shard_params(params, mesh, CFG)
    qw = sharded["blocks"]["attn"]["q_proj"]["weight"]
    # column-parallel: d_out (axis 1 of [L, d_out, d_in]) split 4 ways
    assert qw.sharding.spec == P(None, "tp", None)
    shard_shapes = {tuple(s.data.shape) for s in qw.addressable_shards}
    assert shard_shapes == {(CFG.num_layers, CFG.d_model // 4, CFG.d_model)}
    # accounting helper agrees with an actual leaf walk
    assert tp_param_bytes_per_device(params, mesh, CFG) < sum(
        l.size * l.dtype.itemsize for l in jax.tree_util.tree_leaves(params)
    )


def test_tp_forward_matches_single_device():
    mesh = make_mesh({"tp": 4})
    params = init_transformer_lm(jax.random.PRNGKey(0), CFG)
    x, _ = _data(jax.random.PRNGKey(3))
    ref = transformer_lm(params, x, CFG)

    sharded = shard_params(params, mesh, CFG)
    out = jax.jit(lambda p, i: transformer_lm(p, i, CFG))(sharded, x)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("axes", [{"tp": 4}, {"dp": 2, "tp": 4}])
def test_tp_train_step_matches_single_device(axes):
    mesh = make_mesh(axes)
    params = init_transformer_lm(jax.random.PRNGKey(0), CFG)
    from cs336_systems_tpu.optim.adamw import adamw_init

    opt = adamw_init(params)
    hp = AdamWHparams(lr=1e-3)
    x, y = _data(jax.random.PRNGKey(4))

    ref_step = make_train_step(CFG, hp, clip_norm=1.0, donate=False)
    p_ref, o_ref, l_ref = ref_step(params, opt, x, y)

    tp_step = make_tp_train_step(CFG, hp, mesh, clip_norm=1.0, donate=False)
    p_tp, o_tp, l_tp = tp_step(params, opt, x, y)

    np.testing.assert_allclose(float(l_tp), float(l_ref), rtol=1e-5)
    assert trees_allclose(p_tp, p_ref, rtol=1e-4, atol=1e-5)


@pytest.mark.parametrize("impl", ["flash", "flash_ref"])
def test_tp_train_step_with_flash_kernel(impl):
    """The flagship composition: the flash attention kernel under the
    GSPMD-sharded TP step (heads over tp, batch over dp). The builder pins
    the operand sharding and runs the kernel in a shard_map — equivalence
    vs the single-device flash step proves the kernel survives the mesh."""
    mesh = make_mesh({"dp": 2, "tp": 4})
    cfg = dataclasses.replace(CFG, attn_impl=impl)
    params = init_transformer_lm(jax.random.PRNGKey(0), cfg)
    opt = adamw_init(params)
    hp = AdamWHparams(lr=1e-3)
    x, y = _data(jax.random.PRNGKey(4))

    ref_step = make_train_step(cfg, hp, clip_norm=1.0, donate=False)
    p_ref, _, l_ref = ref_step(params, opt, x, y)

    tp_step = make_tp_train_step(cfg, hp, mesh, clip_norm=1.0, donate=False)
    p_tp, _, l_tp = tp_step(params, opt, x, y)

    np.testing.assert_allclose(float(l_tp), float(l_ref), rtol=1e-5)
    assert trees_allclose(p_tp, p_ref, rtol=1e-4, atol=1e-5)


def test_tp_train_step_flash_windowed():
    """Flash + sliding window + TP in one step (banded kernel under the
    mesh)."""
    mesh = make_mesh({"tp": 4})
    cfg = dataclasses.replace(CFG, attn_impl="flash", attn_window=16)
    params = init_transformer_lm(jax.random.PRNGKey(0), cfg)
    opt = adamw_init(params)
    hp = AdamWHparams(lr=1e-3)
    x, y = _data(jax.random.PRNGKey(6))

    ref_step = make_train_step(cfg, hp, clip_norm=1.0, donate=False)
    p_ref, _, l_ref = ref_step(params, opt, x, y)
    tp_step = make_tp_train_step(cfg, hp, mesh, clip_norm=1.0, donate=False)
    p_tp, _, l_tp = tp_step(params, opt, x, y)
    np.testing.assert_allclose(float(l_tp), float(l_ref), rtol=1e-5)
    assert trees_allclose(p_tp, p_ref, rtol=1e-4, atol=1e-5)


def test_flash_shard_declared_without_mesh_raises():
    # attn_fold must be "bh" when shard axes are declared (the default
    # "hb" fold is single-device and is rejected at config construction)
    cfg = dataclasses.replace(
        CFG, attn_impl="flash", attn_head_shard="tp", attn_fold="bh"
    )
    params = init_transformer_lm(jax.random.PRNGKey(0), cfg)
    x, _ = _data(jax.random.PRNGKey(3))
    with pytest.raises(ValueError, match="no mesh"):
        transformer_lm(params, x, cfg)


def test_sp_train_step_windowed_matches_single_device():
    """attn_window through the SP/ring step vs the single-device windowed
    step (window smaller than one sequence shard → truncated ring)."""
    mesh = make_mesh({"sp": 4})
    cfg = dataclasses.replace(CFG, attn_window=8)
    params = init_transformer_lm(jax.random.PRNGKey(0), cfg)
    opt = adamw_init(params)
    hp = AdamWHparams(lr=1e-3)
    x, y = _data(jax.random.PRNGKey(7), batch=2)

    ref_step = make_train_step(cfg, hp, clip_norm=1.0, donate=False)
    p_ref, _, l_ref = ref_step(params, opt, x, y)

    sp_step = make_sp_train_step(cfg, hp, mesh, clip_norm=1.0, donate=False)
    xs, ys = shard_batch_sp(mesh, x, y)
    p_sp, _, l_sp = sp_step(params, opt, xs, ys)

    np.testing.assert_allclose(float(l_sp), float(l_ref), rtol=1e-5)
    assert trees_allclose(p_sp, p_ref, rtol=1e-4, atol=1e-5)


def test_tp_requires_divisible_degrees():
    """GSPMD would compute correctly with ragged sharding, but the step
    builder rejects head/ff/vocab-misaligned TP degrees up front."""
    from cs336_systems_tpu.parallel.tp import validate_tp

    mesh = make_mesh({"tp": 4})
    bad_cfg = dataclasses.replace(CFG, num_heads=2, d_model=32)
    with pytest.raises(ValueError, match="num_heads"):
        make_tp_train_step(bad_cfg, AdamWHparams(), mesh)
    validate_tp(CFG, mesh)  # aligned config passes


@pytest.mark.parametrize("causal", [True, False])
def test_ring_fused_rope_matches_prerotated(causal):
    """Fused rope over the ring (unrotated q/k + global tables + shard
    positions) must equal the pre-rotated ring (rope applied in XLA before
    sharding): forward, lse, and the gradients mapped back through the
    rotation. The non-causal case exercises the wrapped-hop table modulo
    (every hop contributes there)."""
    from cs336_systems_tpu.models.layers import apply_rope, rope_cache

    mesh = make_mesh({"sp": 4})
    b, s, d = 2, 64, 16
    ks = jax.random.split(jax.random.PRNGKey(5), 3)
    q, k, v = (jax.random.normal(kk, (b, s, d)) for kk in ks)
    cos, sin = rope_cache(s, d)

    def fused(q, k, v):
        def local(q, k, v):
            s_local = q.shape[1]
            positions = jax.lax.axis_index("sp") * s_local + jnp.arange(s_local)
            return ring_attention_with_lse(
                q, k, v, axis="sp", causal=causal,
                rope_cos=cos, rope_sin=sin, positions=positions,
            )
        return jax.shard_map(
            local, mesh=mesh,
            in_specs=(P(None, "sp"),) * 3,
            out_specs=(P(None, "sp"), P(None, "sp")),
        )(q, k, v)

    def prerotated(q, k, v):
        positions = jnp.arange(s)
        qr = apply_rope(q, cos, sin, positions)
        kr = apply_rope(k, cos, sin, positions)

        def local(q, k, v):
            return ring_attention_with_lse(q, k, v, axis="sp", causal=causal)
        return jax.shard_map(
            local, mesh=mesh,
            in_specs=(P(None, "sp"),) * 3,
            out_specs=(P(None, "sp"), P(None, "sp")),
        )(qr, kr, v)

    o_got, lse_got = jax.jit(fused)(q, k, v)
    o_want, lse_want = jax.jit(prerotated)(q, k, v)
    np.testing.assert_allclose(np.asarray(o_got), np.asarray(o_want),
                               rtol=2e-4, atol=2e-4)
    np.testing.assert_allclose(np.asarray(lse_got), np.asarray(lse_want),
                               rtol=2e-5, atol=2e-5)

    loss = lambda f: lambda q, k, v: jnp.sum(
        jnp.tanh(f(q, k, v)[0].astype(jnp.float32)))
    g_got = jax.jit(jax.grad(loss(fused), (0, 1, 2)))(q, k, v)
    # dq/dk of the fused path are w.r.t. UNROTATED inputs; map the
    # pre-rotated path's grads back through the (orthogonal) rotation by
    # differentiating the composition explicitly.
    g_want = jax.jit(jax.grad(
        lambda q, k, v: loss(prerotated)(q, k, v), (0, 1, 2)))(q, k, v)
    for a, w, name in zip(g_got, g_want, "qkv"):
        np.testing.assert_allclose(np.asarray(a), np.asarray(w),
                                   rtol=5e-4, atol=5e-4,
                                   err_msg=f"d{name} (causal={causal})")


@pytest.mark.parametrize("axes,dp", [
    ({"dp": 2, "tp": 2, "sp": 2}, "dp"),
    ({"tp": 4, "sp": 2}, None),
])
def test_tp_sp_3axis_train_step_matches_single_device(axes, dp):
    """THE 3-axis composition oracle (round 5): dp × tp × sp in one
    GSPMD-jitted step — Megatron-sharded params, batch over dp, sequence
    over sp with the ring attention running as a shard_map island under
    the jit — must reproduce the single-device step: same loss, same
    updated params (the ring is exact attention; tp/sp are layouts)."""
    from cs336_systems_tpu.parallel.tp_sp import make_tp_sp_train_step

    mesh = make_mesh(axes)
    params = init_transformer_lm(jax.random.PRNGKey(0), CFG)
    opt = adamw_init(params)
    hp = AdamWHparams(lr=1e-3)
    x, y = _data(jax.random.PRNGKey(4))

    ref_step = make_train_step(CFG, hp, clip_norm=1.0, donate=False)
    p_ref, o_ref, l_ref = ref_step(params, opt, x, y)

    step = make_tp_sp_train_step(CFG, hp, mesh, clip_norm=1.0,
                                 donate=False, dp_axis=dp)
    p3, o3, l3 = step(shard_params(params, mesh, CFG),
                      adamw_init(shard_params(params, mesh, CFG)), x, y)
    np.testing.assert_allclose(float(l3), float(l_ref), rtol=1e-5)
    assert trees_allclose(p3, p_ref, rtol=1e-4, atol=1e-5)


def test_tp_sp_windowed_matches_single_device():
    """Sliding-window attention through the 3-axis step: the banded ring
    (hops beyond the window skipped) under tp sharding."""
    from cs336_systems_tpu.parallel.tp_sp import make_tp_sp_train_step

    cfg = dataclasses.replace(CFG, attn_window=8)
    mesh = make_mesh({"dp": 2, "tp": 2, "sp": 2})
    params = init_transformer_lm(jax.random.PRNGKey(1), cfg)
    opt = adamw_init(params)
    hp = AdamWHparams(lr=1e-3)
    x, y = _data(jax.random.PRNGKey(5))

    ref_step = make_train_step(cfg, hp, clip_norm=1.0, donate=False)
    p_ref, _, l_ref = ref_step(params, opt, x, y)
    step = make_tp_sp_train_step(cfg, hp, mesh, clip_norm=1.0,
                                 donate=False)
    p3, _, l3 = step(shard_params(params, mesh, cfg),
                     adamw_init(shard_params(params, mesh, cfg)), x, y)
    np.testing.assert_allclose(float(l3), float(l_ref), rtol=1e-5)
    assert trees_allclose(p3, p_ref, rtol=1e-4, atol=1e-5)


def test_tp_sp_validation():
    from cs336_systems_tpu.parallel.tp_sp import validate_tp_sp

    with pytest.raises(ValueError, match="no 'sp' axis"):
        validate_tp_sp(CFG, make_mesh({"tp": 4}))
    with pytest.raises(ValueError, match="MoE"):
        validate_tp_sp(dataclasses.replace(CFG, num_experts=4),
                       make_mesh({"tp": 4, "sp": 2}))
