"""Doc-drift tripwire (ISSUE 13 satellite): the family/target counts the
docs CLAIM must match what the CLIs actually register. CLAUDE.md and
analysis/README.md both say "--list is the source of truth" — this test
makes that sentence enforceable: every numeric count printed next to a
--list mention is parsed out of the doc text and asserted against the
live registry, so adding a family without touching the docs (or
vice-versa) fails here instead of rotting silently.
"""

import re
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
CLAUDE_MD = (REPO / "CLAUDE.md").read_text()
README = (REPO / "cs336_systems_tpu" / "analysis" / "README.md").read_text()


def _trace_families():
    from cs336_systems_tpu.analysis import tracekit

    return list(tracekit.FAMILIES)


def _mem_targets():
    from cs336_systems_tpu.analysis import memkit

    return memkit.family_names()


def test_claude_md_tracekit_family_count():
    # "# family (17: train single/..." in the tracekit block
    m = re.search(r"family \((\d+): train single", CLAUDE_MD)
    assert m, "CLAUDE.md tracekit block lost its family-count claim"
    assert int(m.group(1)) == len(_trace_families())


def test_claude_md_memkit_target_count():
    # "...bench shapes (21 targets; --list is the source of" (memkit)
    m = re.search(r"\((\d+) targets; --list is the source", CLAUDE_MD)
    assert m, "CLAUDE.md memkit block lost its target-count claim"
    assert int(m.group(1)) == len(_mem_targets())


def test_claude_md_schedkit_target_count():
    # "# schedkit: ... for the same 21\n# targets"
    m = re.search(r"schedkit: static dependence/critical-path analysis "
                  r"for the same (\d+)\n# targets", CLAUDE_MD)
    assert m, "CLAUDE.md schedkit block lost its target-count claim"
    from cs336_systems_tpu.analysis import schedkit

    assert int(m.group(1)) == len(schedkit.family_names())


def test_readme_list_count_claims():
    # every "--list      # N families/targets" comment in analysis/README
    claims = re.findall(
        r"analysis\.(\w+) --list\s+# (\d+) (?:families|targets)", README)
    assert {c[0] for c in claims} >= {"trace_cli", "mem_cli", "sched_cli"}
    live = {
        "trace_cli": len(_trace_families()),
        "mem_cli": len(_mem_targets()),
        "sched_cli": len(_mem_targets()),  # schedkit mirrors memkit
    }
    for cli, n in claims:
        if cli in live:
            assert int(n) == live[cli], (cli, n)


def test_fleetsan_fault_count_claims():
    # ISSUE 14 satellite: the "(N seeded fault classes" claim in the
    # CLAUDE.md fleetsan block and the analysis/README detection matrix
    # must match what fleet_chaos actually registers — a fault class
    # added without touching the docs (or vice-versa) fails here
    from cs336_systems_tpu.serving import fleet_chaos

    live = len(fleet_chaos.fault_names())
    m = re.search(r"injects (\d+) seeded fleet-level fault", CLAUDE_MD)
    assert m, "CLAUDE.md fleetsan block lost its fault-count claim"
    assert int(m.group(1)) == live
    m = re.search(r"fleetsan.*?(\d+) fault classes", README, re.S)
    assert m, "analysis/README.md fleetsan section lost its fault count"
    assert int(m.group(1)) == live


def test_servesan_fault_count_claims():
    # ISSUE 15 satellite: the "(N seeded fault classes" claim in the
    # CLAUDE.md servesan block and the analysis/README detection matrix
    # must match what serving/chaos.py actually registers — the chunked
    # faults (torn-chunk-state, leaked-chunk-pages) landed here once
    from cs336_systems_tpu.serving import chaos

    live = len(chaos.fault_names())
    m = re.search(r"injects (\d+) seeded fault classes", CLAUDE_MD)
    assert m, "CLAUDE.md servesan block lost its fault-count claim"
    assert int(m.group(1)) == live
    m = re.search(r"servesan.*?(\d+) fault classes", README, re.S)
    assert m, "analysis/README.md servesan section lost its fault count"
    assert int(m.group(1)) == live


def test_lint_registry_matches_serve_and_train_families():
    # the lint registry = the 17 traced families + the kernel-level
    # gmm_fused_bwd step (README: "minus the kernel-level gmm_fused_bwd")
    from cs336_systems_tpu.analysis import registry

    lint_names = {s.name for s in registry.STEPS}
    assert lint_names == set(_trace_families()) | {"gmm_fused_bwd"}


def test_sched_census_allowlist_names_registered_steps():
    # a renamed/removed family must not leave a dangling allowlist entry
    # (the lint rule would silently never run for it)
    from cs336_systems_tpu.analysis import registry

    lint_names = {s.name for s in registry.STEPS}
    assert registry.SCHED_CENSUS_FAMILIES <= lint_names


def test_slack_floor_families_are_census_families():
    # every family whose contract declares slack floors must be in the
    # allowlist doc story (tp/tp_sp/ep) and actually declare floors
    from cs336_systems_tpu.analysis import registry
    from cs336_systems_tpu.parallel import ep, tp, tp_sp

    for name, contract in (
            ("tp", tp.lint_contract()),
            ("tp_sp", tp_sp.lint_contract(registry._tiny_cfg())),
            ("ep", ep.lint_contract(registry._moe_cfg()))):
        floors = contract.get("collective_slack_floor_ms")
        assert floors and all(v > 0 for v in floors.values()), name
