"""Chaos-hardened serving (ISSUE 10): the typed failure surface, the
request lifecycle (cancellation, deadline shedding, poisoned-slot
containment), and the servesan fault matrix.

The load-bearing property everywhere: robustness actions are HOST-SIDE
schedule edits, so every surviving stream stays bit-identical to the
row-keyed oracle (``generate_kv_batched(row_keyed=True, page_block=)``)
no matter what was cancelled, shed or poisoned around it, in what order
requests joined, or how the slots shard over dp8 / dp2×tp4 — the same
oracle discipline as tests/test_serving_engine.py. The fault matrix is
the gradsan discipline (PR 6): every detector must have SEEN its fault.
"""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

import jax

from cs336_systems_tpu.models.decode import generate_kv_batched
from cs336_systems_tpu.models.transformer import (
    TransformerConfig,
    init_transformer_lm,
)
from cs336_systems_tpu.serving import (
    AdmissionImpossible,
    CorruptBlockTable,
    DeadlineExceeded,
    DeadlinePolicy,
    FifoPolicy,
    FleetInvariantViolation,
    InvariantViolation,
    PoolExhausted,
    RefcountViolation,
    ReplicaUnavailable,
    Request,
    ServingEngine,
    ServingError,
    SlotPoisoned,
    chaos,
)

CFG = TransformerConfig(
    vocab_size=64, context_length=64, d_model=64,
    num_layers=2, num_heads=4, d_ff=128,
)
BLK = 8
NEW = 10
LENS = [12, 3, 7, 1, 12, 5, 9, 2]  # test_serving_engine's skew profile


@pytest.fixture(scope="module")
def params():
    return init_transformer_lm(jax.random.PRNGKey(1), CFG)


@pytest.fixture(scope="module")
def prompts():
    rng = np.random.default_rng(7)
    return [rng.integers(0, CFG.vocab_size, size=n).astype(np.int32)
            for n in LENS]


@pytest.fixture(scope="module")
def oracle(params, prompts):
    pmax = max(p.size for p in prompts)
    padded = np.zeros((len(prompts), pmax), np.int32)
    for i, p in enumerate(prompts):
        padded[i, :p.size] = p
    return np.asarray(generate_kv_batched(
        params, CFG, padded, NEW, jax.random.PRNGKey(0), temperature=0.9,
        top_k=8, row_keyed=True, prompt_lens=[p.size for p in prompts],
        page_block=BLK))


def _engine(params, **kw):
    base = dict(key=jax.random.PRNGKey(0), slots=4, n_pages=16,
                max_blocks=4, page_block=BLK, temperature=0.9, top_k=8)
    base.update(kw)
    return ServingEngine(params, CFG, **base)


def _ticker():
    it = iter(np.arange(0.0, 1e4, 0.5))
    return lambda: next(it)


# --- the typed failure surface -----------------------------------------


class TestErrorTaxonomy:
    def test_retriable_flags(self):
        # transient capacity/latency/numerics faults invite a retry;
        # ownership/table/invariant corruption never does
        assert PoolExhausted.retriable
        assert DeadlineExceeded.retriable
        assert SlotPoisoned.retriable
        assert not RefcountViolation.retriable
        assert not CorruptBlockTable.retriable
        assert not AdmissionImpossible.retriable
        assert not InvariantViolation.retriable
        # fleet layer (ISSUE 14): a replica failure invites re-dispatch;
        # router-state corruption never does
        assert ReplicaUnavailable.retriable
        assert not FleetInvariantViolation.retriable

    def test_compat_bases(self):
        # pre-ISSUE-10 callers caught MemoryError / ValueError /
        # AssertionError from these seams; the typed errors keep those
        # contracts via dual inheritance
        assert issubclass(PoolExhausted, MemoryError)
        assert issubclass(RefcountViolation, ValueError)
        assert issubclass(CorruptBlockTable, ValueError)
        assert issubclass(AdmissionImpossible, ValueError)
        assert issubclass(InvariantViolation, AssertionError)
        # FleetInvariantViolation subclasses InvariantViolation so
        # existing invariant handlers (and AssertionError sites) keep
        # working one level up
        assert issubclass(FleetInvariantViolation, InvariantViolation)
        assert issubclass(FleetInvariantViolation, AssertionError)
        for cls in (PoolExhausted, DeadlineExceeded, SlotPoisoned,
                    RefcountViolation, CorruptBlockTable,
                    AdmissionImpossible, InvariantViolation,
                    ReplicaUnavailable, FleetInvariantViolation):
            assert issubclass(cls, ServingError)

    def test_shard_attribution(self):
        e = RefcountViolation("page 3 double free", shard=2)
        assert e.shard == 2 and e.detail == "page 3 double free"
        assert str(e) == "shard 2: page 3 double free"
        assert RefcountViolation("x").shard is None
        assert str(InvariantViolation("pool not conserved")) == \
            "pool not conserved"

    def test_replica_attribution(self):
        # ReplicaUnavailable carries the replica index in the typed
        # surface AND the message; None = the whole fleet is down
        e = ReplicaUnavailable("crashed mid-step", replica=2)
        assert e.replica == 2 and e.retriable
        assert str(e) == "replica 2: crashed mid-step"
        down = ReplicaUnavailable("no healthy replica")
        assert down.replica is None
        assert str(down) == "no healthy replica"


# --- exhaustive submit-time rejection ----------------------------------


def test_submit_rejects_every_impossible_request(params):
    """Every never-admittable request dies AT SUBMIT with the
    non-retriable AdmissionImpossible — it must not occupy queue space
    waiting for evictions that cannot help it."""
    eng = _engine(params, n_pages=2, max_blocks=2)
    with pytest.raises(AdmissionImpossible, match="context_length"):
        eng.submit(Request(rid=0, prompt=np.zeros(8, np.int32),
                           max_new_tokens=CFG.context_length))
    with pytest.raises(AdmissionImpossible, match="pages"):
        eng.submit(Request(rid=1, prompt=np.zeros(17, np.int32),
                           max_new_tokens=8))  # 4 pages > pool's 2
    # 3 blocks > 2-wide tables, but a 3-page pool could hold it: the
    # block-table width is its own independent impossibility
    eng3 = _engine(params, n_pages=3, max_blocks=2)
    with pytest.raises(AdmissionImpossible, match="blocks"):
        eng3.submit(Request(rid=2, prompt=np.zeros(17, np.int32),
                            max_new_tokens=7))
    eng.submit(Request(rid=3, prompt=np.zeros(4, np.int32),
                       max_new_tokens=4))
    with pytest.raises(AdmissionImpossible, match="duplicate"):
        eng.submit(Request(rid=3, prompt=np.zeros(4, np.int32),
                           max_new_tokens=4))
    assert not AdmissionImpossible.retriable
    assert isinstance(AdmissionImpossible("x"), ValueError)  # compat


def test_engine_rejects_degenerate_geometry(params):
    with pytest.raises(ValueError, match="slots"):
        _engine(params, slots=0)
    with pytest.raises(ValueError, match="page"):
        _engine(params, n_pages=0)
    with pytest.raises(ValueError, match="page"):
        _engine(params, max_blocks=0)


# --- cancellation ------------------------------------------------------


def test_cancel_running_and_queued_vs_oracle(params, prompts, oracle):
    """Cancel one RUNNING and one QUEUED request mid-trace: both land in
    ``cancelled`` (partial stream = oracle prefix; queued = empty), and
    every surviving stream is bit-identical to an oracle that never saw
    the cancellations — tokens are row-local."""
    eng = _engine(params)  # 4 slots: rids 0-3 run, 4-7 queue
    for r, p in enumerate(prompts):
        eng.submit(Request(rid=r, prompt=p, max_new_tokens=NEW))
    eng.step(0.0)
    eng.step(0.5)
    assert eng.cancel(1, now=1.0)   # running, 2 tokens streamed
    assert eng.cancel(6, now=1.0)   # still queued, never ran
    res = eng.run(time_fn=_ticker())
    eng.check_idle()

    assert set(res) == {0, 2, 3, 4, 5, 7}
    assert set(eng.cancelled) == {1, 6} and not eng.failed
    np.testing.assert_array_equal(eng.cancelled[1], oracle[1][:2])
    assert eng.cancelled[6].size == 0
    for r in res:
        np.testing.assert_array_equal(res[r], oracle[r])


def test_cancel_is_idempotent(params, prompts):
    eng = _engine(params)
    eng.submit(Request(rid=0, prompt=prompts[0], max_new_tokens=2))
    assert not eng.cancel(99)        # unknown rid
    eng.run()
    assert not eng.cancel(0)         # already finished
    assert 0 in eng.results and not eng.cancelled


# --- deadline-aware admission ------------------------------------------

# arrivals r*0.1; rids 0/1/6/7 get a reachable 12-unit budget, the
# middle rids 2-5 a 2-unit budget that 10 decode steps at 0.5/step can
# never meet — the doomed middle FIFO wastes two waves serving
_DEADLINE = {0: 12.0, 1: 12.0, 2: 2.0, 3: 2.0, 4: 2.0, 5: 2.0,
             6: 12.0, 7: 12.0}


def _deadline_requests(prompts):
    return [Request(rid=r, prompt=p, max_new_tokens=NEW, arrival=r * 0.1,
                    deadline=r * 0.1 + _DEADLINE[r])
            for r, p in enumerate(prompts)]


def _run_deadline(params, prompts, policy, order=None):
    eng = _engine(params, slots=2, n_pages=8, policy=policy)
    reqs = _deadline_requests(prompts)
    for i in (order if order is not None else range(len(reqs))):
        eng.submit(reqs[i])
    res = eng.run(time_fn=_ticker())
    eng.check_idle()
    return eng, reqs, res


def _deadline_goodput(reqs, res):
    return sum(len(r.tokens) for r in reqs
               if r.rid in res and r.finish_time <= r.deadline)


def test_deadline_policy_beats_fifo_goodput(params, prompts):
    """The acceptance criterion: under overload the deadline policy's
    goodput (tokens from requests that finished BY their deadline) is
    STRICTLY higher than strict FIFO's on the same virtual-clock trace,
    and every shed request got the retriable typed DeadlineExceeded."""
    fifo_eng, fifo_reqs, fifo_res = _run_deadline(
        params, prompts, FifoPolicy())
    assert set(fifo_res) == set(range(8)) and not fifo_eng.failed

    dl_eng, dl_reqs, dl_res = _run_deadline(
        params, prompts, DeadlinePolicy(token_time=0.5))
    assert set(dl_eng.failed) == {2, 3, 4, 5}
    for err in dl_eng.failed.values():
        assert isinstance(err, DeadlineExceeded) and err.retriable

    assert _deadline_goodput(dl_reqs, dl_res) > \
        _deadline_goodput(fifo_reqs, fifo_res)
    # fewer steps too: the doomed middle never occupied a slot
    assert dl_eng.steps < fifo_eng.steps


def test_deadline_shed_deterministic_across_join_orders(
        params, prompts, oracle):
    """Shedding is a function of the ARRIVAL clock, not submission
    order: permuted submit orders (distinct arrivals) shed the same
    rids at the same step count, and every surviving stream equals its
    oracle row."""
    outcomes = []
    for order in ([5, 2, 7, 0, 3, 6, 1, 4], [7, 6, 5, 4, 3, 2, 1, 0],
                  None):
        eng, _reqs, res = _run_deadline(
            params, prompts, DeadlinePolicy(token_time=0.5), order=order)
        outcomes.append((set(eng.failed), set(res), eng.steps))
        for r in res:
            np.testing.assert_array_equal(res[r], oracle[r])
    assert outcomes[0] == outcomes[1] == outcomes[2]
    assert outcomes[0][0] == {2, 3, 4, 5}


# --- poisoned-slot containment -----------------------------------------


def test_poisoned_slot_contained_vs_oracle(params, prompts, oracle):
    """NaN-poison one slot's carried logits mid-stream: that request is
    evicted with the retriable SlotPoisoned (tokens streamed before the
    poison stay valid — they came from finite logits), the trace drains,
    and every OTHER stream is bit-identical to the oracle."""
    eng = _engine(params)
    for r, p in enumerate(prompts):
        eng.submit(Request(rid=r, prompt=p, max_new_tokens=NEW))
    eng.step(0.0)
    eng.step(0.5)
    slot = next(s for s, rq in eng.running.items() if rq.rid == 2)
    eng.logits[slot, :5] = np.nan
    res = eng.run(time_fn=_ticker())
    eng.check_idle()

    assert set(res) == set(range(8)) - {2}
    err = eng.failed[2]
    assert isinstance(err, SlotPoisoned) and err.retriable
    assert err.shard == slot // eng.slots_per
    assert "non-finite" in str(err)
    for r in res:
        np.testing.assert_array_equal(res[r], oracle[r])


# --- the servesan fault matrix -----------------------------------------


@pytest.mark.parametrize("mesh", ["dp8", "dp2xtp4"])
def test_chaos_matrix_detects_every_fault(mesh):
    """Every seeded fault class must surface its EXPECTED typed error
    (from the self_check sweep or the engine's own operation), and the
    un-injected trace must drain with zero findings — on sharded slot
    batches, not just single-device."""
    rows = [chaos.run_fault(name, mesh) for name in chaos.fault_names()]
    rows.append(chaos.run_clean(mesh))
    bad = [(r["fault"], r.get("error")) for r in rows if not r["ok"]]
    assert not bad, f"chaos verdicts failed on {mesh}: {bad}"
    assert len(rows) == len(chaos.fault_names()) + 1 >= 9


def test_chaos_clean_run_zero_findings_single_device():
    row = chaos.run_clean("none")
    assert row["ok"] and not row["detected"]
    assert row["all_requests_completed"]


def test_chaos_cli_contract():
    """The CLI is the CI gate: --list enumerates ≥8 fault classes fast
    (no engine build), a single-fault run reports ok with exit 0, and an
    unknown fault is the exit-2 build error, not a miss."""
    env = dict(os.environ, PALLAS_AXON_POOL_IPS="")
    base = [sys.executable, "-m", "cs336_systems_tpu.serving.chaos"]

    ls = subprocess.run(base + ["--list", "--json"], env=env,
                        capture_output=True, text=True)
    assert ls.returncode == 0
    assert len(json.loads(ls.stdout)["faults"]) >= 8

    one = subprocess.run(base + ["--fault", "nan-logits", "--json"],
                         env=env, capture_output=True, text=True)
    assert one.returncode == 0, one.stdout + one.stderr
    row = json.loads(one.stdout)["rows"][0]
    assert row["ok"] and row["error"]["type"] == "SlotPoisoned"
    assert row["error"]["retriable"] is True

    bad = subprocess.run(base + ["--fault", "no-such-fault", "--json"],
                         env=env, capture_output=True, text=True)
    assert bad.returncode == 2
