"""Mixed-precision tests.

The reference's precision scripts are demos (precision.py,
mixed_precision_testing.py — print-only); here their observations are
pinned as assertions, per SURVEY §7.7 ("the fp16 accumulation demo becomes
a dtype-accumulation unit test").
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from cs336_systems_tpu.ops.precision import (
    FP32,
    MIXED_BF16,
    PURE_BF16,
    Policy,
    accumulate,
    accumulation_error,
    introspect_dtypes,
)


class TestAccumulation:
    """Reference precision.py:1-23 — 1000 × 0.01 four ways."""

    def test_fp32_accumulation_accurate(self):
        err = abs(float(accumulate(1000, 0.01, jnp.float32)) - 10.0)
        assert err < 1e-3

    def test_fp16_accumulation_drifts(self):
        # fp16 cannot represent 0.01 exactly and loses increments as the
        # accumulator grows; the error is orders of magnitude above fp32's.
        err = abs(float(accumulate(1000, 0.01, jnp.float16)) - 10.0)
        assert err > 0.01

    def test_bf16_accumulation_much_worse(self):
        # bf16 has 8 mantissa bits: accumulation error is large — this is
        # exactly why moments/accumulators stay fp32 in mixed policies.
        err = abs(float(accumulate(1000, 0.01, jnp.bfloat16)) - 10.0)
        assert err > 0.1

    def test_fp32_acc_of_low_precision_addends_small_bias(self):
        # fp32 accumulator fixes the drift even with low-precision addends:
        # only the constant representation error of 0.01 remains.
        err16 = abs(float(accumulate(1000, 0.01, jnp.float32, jnp.float16)) - 10.0)
        err_pure16 = abs(float(accumulate(1000, 0.01, jnp.float16)) - 10.0)
        assert err16 < err_pure16

    def test_error_table_ordering(self):
        errs = accumulation_error()
        assert errs["fp32"] < errs["fp16_acc"] < errs["bf16_acc"]
        assert errs["fp32_acc_fp16_add"] < errs["fp16_acc"]


class TestPolicyIntrospection:
    """Reference mixed_precision_testing.py:33-51 — where dtypes land."""

    def test_mixed_bf16_placement(self):
        d = introspect_dtypes(MIXED_BF16)
        assert d["params"] == jnp.float32  # master weights fp32
        assert d["fc1_output"] == jnp.bfloat16  # matmul runs in bf16
        assert d["norm_output"] == jnp.bfloat16  # fp32 inside, recast out
        assert d["logits"] == jnp.bfloat16
        assert d["loss"] == jnp.float32  # loss upcast
        assert d["grads"] == jnp.float32  # grads w.r.t. fp32 params

    def test_fp32_placement(self):
        d = introspect_dtypes(FP32)
        assert all(jnp.dtype(v) == jnp.float32 for v in d.values())

    def test_pure_bf16_placement(self):
        d = introspect_dtypes(PURE_BF16)
        assert d["params"] == jnp.bfloat16
        assert d["grads"] == jnp.bfloat16

    def test_policy_casting_helpers(self):
        p = Policy(param_dtype="bfloat16", compute_dtype="bfloat16")
        tree = {"w": jnp.ones((2, 2), jnp.float32)}
        assert p.cast_params(tree)["w"].dtype == jnp.bfloat16
        a, b = p.cast_compute(jnp.ones(2), jnp.zeros(2))
        assert a.dtype == b.dtype == jnp.bfloat16


class TestModelUnderPolicy:
    """The policy contract holds through the real Transformer LM."""

    @pytest.mark.parametrize("compute_dtype", ["float32", "bfloat16"])
    def test_lm_forward_dtype_and_finite(self, compute_dtype):
        from cs336_systems_tpu.models.transformer import (
            TransformerConfig,
            init_transformer_lm,
            transformer_lm,
        )

        cfg = TransformerConfig(
            vocab_size=64, context_length=16, d_model=32,
            num_layers=2, num_heads=2, d_ff=64,
            compute_dtype=compute_dtype,
        )
        params = init_transformer_lm(jax.random.PRNGKey(0), cfg)
        # params stay fp32 regardless of compute dtype
        assert params["lm_head"]["weight"].dtype == jnp.float32
        ids = jax.random.randint(jax.random.PRNGKey(1), (2, 16), 0, 64)
        logits = transformer_lm(params, ids, cfg)
        assert logits.dtype == jnp.dtype(compute_dtype)
        assert bool(jnp.all(jnp.isfinite(logits.astype(jnp.float32))))

    def test_bf16_loss_close_to_fp32(self):
        """bf16 compute must track the fp32 loss closely at init — the
        autocast-equivalence sanity the reference eyeballs by printing."""
        from cs336_systems_tpu.models.transformer import (
            TransformerConfig,
            init_transformer_lm,
        )
        from cs336_systems_tpu.train import lm_loss

        mk = lambda cd: TransformerConfig(
            vocab_size=64, context_length=16, d_model=32,
            num_layers=2, num_heads=2, d_ff=64, compute_dtype=cd,
        )
        params = init_transformer_lm(jax.random.PRNGKey(0), mk("float32"))
        ids = jax.random.randint(jax.random.PRNGKey(1), (2, 16), 0, 64)
        tgt = jnp.roll(ids, -1, axis=-1)
        l32 = float(lm_loss(params, ids, tgt, mk("float32")))
        l16 = float(lm_loss(params, ids, tgt, mk("bfloat16")))
        assert abs(l32 - l16) / abs(l32) < 0.05
