"""Mixed-precision tests.

The reference's precision scripts are demos (precision.py,
mixed_precision_testing.py — print-only); here their observations are
pinned as assertions, per SURVEY §7.7 ("the fp16 accumulation demo becomes
a dtype-accumulation unit test").
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from cs336_systems_tpu.ops.precision import (
    FP32,
    MIXED_BF16,
    PURE_BF16,
    Policy,
    accumulate,
    accumulation_error,
    introspect_dtypes,
)


class TestAccumulation:
    """Reference precision.py:1-23 — 1000 × 0.01 four ways."""

    def test_fp32_accumulation_accurate(self):
        err = abs(float(accumulate(1000, 0.01, jnp.float32)) - 10.0)
        assert err < 1e-3

    def test_fp16_accumulation_drifts(self):
        # fp16 cannot represent 0.01 exactly and loses increments as the
        # accumulator grows; the error is orders of magnitude above fp32's.
        err = abs(float(accumulate(1000, 0.01, jnp.float16)) - 10.0)
        assert err > 0.01

    def test_bf16_accumulation_much_worse(self):
        # bf16 has 8 mantissa bits: accumulation error is large — this is
        # exactly why moments/accumulators stay fp32 in mixed policies.
        err = abs(float(accumulate(1000, 0.01, jnp.bfloat16)) - 10.0)
        assert err > 0.1

    def test_fp32_acc_of_low_precision_addends_small_bias(self):
        # fp32 accumulator fixes the drift even with low-precision addends:
        # only the constant representation error of 0.01 remains.
        err16 = abs(float(accumulate(1000, 0.01, jnp.float32, jnp.float16)) - 10.0)
        err_pure16 = abs(float(accumulate(1000, 0.01, jnp.float16)) - 10.0)
        assert err16 < err_pure16

    def test_error_table_ordering(self):
        errs = accumulation_error()
        assert errs["fp32"] < errs["fp16_acc"] < errs["bf16_acc"]
        assert errs["fp32_acc_fp16_add"] < errs["fp16_acc"]


class TestPolicyIntrospection:
    """Reference mixed_precision_testing.py:33-51 — where dtypes land."""

    def test_mixed_bf16_placement(self):
        d = introspect_dtypes(MIXED_BF16)
        assert d["params"] == jnp.float32  # master weights fp32
        assert d["fc1_output"] == jnp.bfloat16  # matmul runs in bf16
        assert d["norm_output"] == jnp.bfloat16  # fp32 inside, recast out
        assert d["logits"] == jnp.bfloat16
        assert d["loss"] == jnp.float32  # loss upcast
        assert d["grads"] == jnp.float32  # grads w.r.t. fp32 params

    def test_fp32_placement(self):
        d = introspect_dtypes(FP32)
        assert all(jnp.dtype(v) == jnp.float32 for v in d.values())

    def test_pure_bf16_placement(self):
        d = introspect_dtypes(PURE_BF16)
        assert d["params"] == jnp.bfloat16
        assert d["grads"] == jnp.bfloat16

    def test_policy_casting_helpers(self):
        p = Policy(param_dtype="bfloat16", compute_dtype="bfloat16")
        tree = {"w": jnp.ones((2, 2), jnp.float32)}
        assert p.cast_params(tree)["w"].dtype == jnp.bfloat16
        a, b = p.cast_compute(jnp.ones(2), jnp.zeros(2))
        assert a.dtype == b.dtype == jnp.bfloat16


class TestModelUnderPolicy:
    """The policy contract holds through the real Transformer LM."""

    @pytest.mark.parametrize("compute_dtype", ["float32", "bfloat16"])
    def test_lm_forward_dtype_and_finite(self, compute_dtype):
        from cs336_systems_tpu.models.transformer import (
            TransformerConfig,
            init_transformer_lm,
            transformer_lm,
        )

        cfg = TransformerConfig(
            vocab_size=64, context_length=16, d_model=32,
            num_layers=2, num_heads=2, d_ff=64,
            compute_dtype=compute_dtype,
        )
        params = init_transformer_lm(jax.random.PRNGKey(0), cfg)
        # params stay fp32 regardless of compute dtype
        assert params["lm_head"]["weight"].dtype == jnp.float32
        ids = jax.random.randint(jax.random.PRNGKey(1), (2, 16), 0, 64)
        logits = transformer_lm(params, ids, cfg)
        assert logits.dtype == jnp.dtype(compute_dtype)
        assert bool(jnp.all(jnp.isfinite(logits.astype(jnp.float32))))

    def test_bf16_loss_close_to_fp32(self):
        """bf16 compute must track the fp32 loss closely at init — the
        autocast-equivalence sanity the reference eyeballs by printing."""
        from cs336_systems_tpu.models.transformer import (
            TransformerConfig,
            init_transformer_lm,
        )
        from cs336_systems_tpu.train import lm_loss

        mk = lambda cd: TransformerConfig(
            vocab_size=64, context_length=16, d_model=32,
            num_layers=2, num_heads=2, d_ff=64, compute_dtype=cd,
        )
        params = init_transformer_lm(jax.random.PRNGKey(0), mk("float32"))
        ids = jax.random.randint(jax.random.PRNGKey(1), (2, 16), 0, 64)
        tgt = jnp.roll(ids, -1, axis=-1)
        l32 = float(lm_loss(params, ids, tgt, mk("float32")))
        l16 = float(lm_loss(params, ids, tgt, mk("bfloat16")))
        assert abs(l32 - l16) / abs(l32) < 0.05


# ---------------------------------------------------------------------------
# Dynamic loss scaling


def test_loss_scaler_state_machine():
    """Backoff on overflow, growth after the interval, clipping at bounds."""
    import jax.numpy as jnp

    from cs336_systems_tpu.ops.precision import (
        LossScalerConfig,
        loss_scaler_init,
        loss_scaler_update,
    )

    cfg = LossScalerConfig(init_scale=1024.0, growth_interval=3)
    s = loss_scaler_init(cfg)
    assert float(s["scale"]) == 1024.0

    s = loss_scaler_update(s, jnp.asarray(False), cfg)  # overflow -> halve
    assert float(s["scale"]) == 512.0 and int(s["good_steps"]) == 0

    for _ in range(2):
        s = loss_scaler_update(s, jnp.asarray(True), cfg)
        assert float(s["scale"]) == 512.0
    s = loss_scaler_update(s, jnp.asarray(True), cfg)  # 3rd good -> double
    assert float(s["scale"]) == 1024.0 and int(s["good_steps"]) == 0

    tiny = loss_scaler_init(LossScalerConfig(init_scale=1.0, min_scale=1.0))
    tiny = loss_scaler_update(
        tiny, jnp.asarray(False), LossScalerConfig(min_scale=1.0)
    )
    assert float(tiny["scale"]) == 1.0  # clipped at min


def test_scaled_grads_recover_fp16_underflow():
    """A gradient below fp16's subnormal floor underflows to zero without
    scaling and is recovered (vs fp32 oracle) with the scaler."""
    import jax
    import jax.numpy as jnp

    from cs336_systems_tpu.ops.precision import (
        LossScalerConfig,
        loss_scaler_init,
        scaled_value_and_grad,
    )

    w = jnp.asarray(1.0, jnp.float32)
    tiny = 1e-8  # below fp16's subnormal floor (~6e-8): flushes to zero

    def loss_fn(w, x):
        # fp16 compute region (as under the MIXED_FP16 policy), then an
        # fp32 epilogue that makes the backward cotangent entering the
        # fp16 region `tiny` — underflow in the COTANGENT chain is what
        # loss scaling exists to fix.
        prod = (w.astype(jnp.float16) * x.astype(jnp.float16)).astype(
            jnp.float32
        )
        return prod * tiny

    x = jnp.asarray(1.0, jnp.float32)
    # unscaled: the fp32->fp16 cotangent cast flushes tiny to 0
    _, g_plain = jax.value_and_grad(loss_fn)(w, x)
    assert float(g_plain) == 0.0

    state = loss_scaler_init(LossScalerConfig(init_scale=2.0**20))
    loss, g_scaled, finite = jax.jit(
        lambda w, x: scaled_value_and_grad(loss_fn, state)(w, x)
    )(w, x)
    assert bool(finite)
    np.testing.assert_allclose(float(g_scaled), tiny, rtol=1e-3)


def test_scaled_update_skips_nonfinite_step():
    """An overflowing step must leave params/opt state untouched and back
    the scale off; a finite step must apply the update."""
    import jax
    import jax.numpy as jnp

    from cs336_systems_tpu.optim.adamw import AdamWHparams, adamw_init
    from cs336_systems_tpu.ops.precision import (
        LossScalerConfig,
        loss_scaler_init,
        make_scaled_update_fn,
    )

    def loss_fn(params, x):
        return jnp.sum(params["w"] * x)

    params = {"w": jnp.ones((4,), jnp.float32)}
    opt = adamw_init(params)
    cfg = LossScalerConfig(init_scale=4.0)
    scaler = loss_scaler_init(cfg)
    step = jax.jit(make_scaled_update_fn(loss_fn, AdamWHparams(lr=0.1), cfg))

    # overflow: x = inf makes the gradient non-finite
    p2, o2, s2, loss, finite = step(
        params, opt, scaler, jnp.asarray([jnp.inf, 1.0, 1.0, 1.0])
    )
    assert not bool(finite)
    np.testing.assert_array_equal(np.asarray(p2["w"]), np.asarray(params["w"]))
    assert int(o2["t"]) == 0  # skipped step does not advance the counter
    assert float(s2["scale"]) == 2.0  # backed off

    # finite: update applies, counter advances
    p3, o3, s3, loss, finite = step(p2, o2, s2, jnp.ones((4,)))
    assert bool(finite)
    assert int(o3["t"]) == 1
    assert not np.allclose(np.asarray(p3["w"]), np.asarray(p2["w"]))
