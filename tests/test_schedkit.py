"""schedkit tests: critical-path/slack reconstruction against synthetic
scheduled-HLO fixtures with HAND-COMPUTED answers, the container
conventions (while = condition + one body iteration), the two lint rules
(collective-zero-slack / collective-count-consistency) fired by SEEDED
mutations and quiet on clean inputs, and CPU end-to-end runs on real
registered families (composition sums to the critical-path total, the
DAG census agrees with tracekit's independent parse, self-diff is
exactly zero, the declared slack floors hold on the current tree).

Same oracle discipline as test_memkit.py / test_tracekit.py: every
modeling rule is pinned by a fixture whose correct answer is derived by
hand in a comment before the pipeline ever touches a compiled module.
"""

import json

import pytest

from cs336_systems_tpu.analysis import contracts, schedkit
from cs336_systems_tpu.analysis.schedkit import (
    HBM_BYTES_PER_S,
    ICI_BYTES_PER_S,
    ICI_LATENCY_MS,
    MXU_PEAK_FLOPS,
    analyze_hlo_schedule,
    diff_schedprofiles,
    profile_hlo,
)

TOL = 1e-6  # artifact values are round(x, 6) — half-ulp of that


def _ms(nbytes: float) -> float:
    return nbytes / HBM_BYTES_PER_S * 1e3


# --- fixture A: diamond of elementwise ops ---------------------------------
# f32[262144] = 1 MiB. Every add/multiply reads two distinct 1 MiB
# operands and writes 1 MiB -> cost = 3 MiB at HBM rate each. a and b depend only
# on the parameters (free), c on both:
#   critical path = a->c (or b->c) = 2 * cost
#   serialized    = 3 * cost
#   efficiency    = 2/3
# All ops are scope-less ("other" phase) vpu-elementwise.

_HLO_DIAMOND = """\
HloModule jit_d, is_scheduled=true, entry_computation_layout={(f32[262144]{0}, f32[262144]{0})->f32[262144]{0}}

ENTRY %main.6 (p0.1: f32[262144], p1.2: f32[262144]) -> f32[262144] {
  %p0.1 = f32[262144]{0} parameter(0)
  %p1.2 = f32[262144]{0} parameter(1)
  %a.3 = f32[262144]{0} add(f32[262144]{0} %p0.1, f32[262144]{0} %p1.2)
  %b.4 = f32[262144]{0} multiply(f32[262144]{0} %p0.1, f32[262144]{0} %p1.2)
  ROOT %c.5 = f32[262144]{0} add(f32[262144]{0} %a.3, f32[262144]{0} %b.4)
}
"""


def test_diamond_critical_path_and_efficiency():
    p = profile_hlo(_HLO_DIAMOND, family="fixture", n_devices=1)
    cost = _ms(3 << 20)
    assert p["critical_path_ms"] == pytest.approx(2 * cost, abs=TOL)
    assert p["serialized_ms"] == pytest.approx(3 * cost, abs=TOL)
    assert p["schedule_efficiency"] == pytest.approx(2 / 3, abs=1e-4)
    assert p["collectives"] == {}
    assert p["predicted_exposed_ms"] == 0.0


def test_diamond_composition_sums_to_critical_path():
    p = profile_hlo(_HLO_DIAMOND, family="fixture", n_devices=1)
    total = sum(v for cls in p["critical_path_phase_class_ms"].values()
                for v in cls.values())
    assert total == pytest.approx(p["critical_path_ms"], abs=1e-5)
    assert p["critical_path_class_ms"] == pytest.approx(
        {"vpu-elementwise": 2 * _ms(3 << 20)}, rel=1e-3)
    assert list(p["critical_path_phase_ms"]) == ["other"]


# --- fixture B: collective slack -------------------------------------------
# The dot and the all-reduce are dependence-independent: the dot is MXU
# compute the scheduler could legally run inside the all-reduce's window.
#   dot:  bf16 [128,256] x [256,128] -> 2*(128*128)*256 = 8_388_608 FLOPs
#         at the full bf16 peak
#   ar:   1 MiB over an 8-device ring: latency + 2*(8-1)/8 * bytes/rate
#   slack(ar) = cost(dot); exposed(ar) = cost(ar) - cost(dot)

_HLO_COLL = """\
HloModule jit_c, is_scheduled=true

%red.add (x.1: f32[], y.1: f32[]) -> f32[] {
  %x.1 = f32[] parameter(0)
  %y.1 = f32[] parameter(1)
  ROOT %r.1 = f32[] add(f32[] %x.1, f32[] %y.1)
}

ENTRY %main.7 (p0.1: bf16[128,256], p1.2: bf16[256,128], p2.3: f32[262144]) -> (bf16[128,128], f32[262144]) {
  %p0.1 = bf16[128,256]{1,0} parameter(0)
  %p1.2 = bf16[256,128]{1,0} parameter(1)
  %p2.3 = f32[262144]{0} parameter(2)
  %dot.4 = bf16[128,128]{1,0} dot(bf16[128,256]{1,0} %p0.1, bf16[256,128]{1,0} %p1.2), lhs_contracting_dims={1}, rhs_contracting_dims={0}
  %ar.5 = f32[262144]{0} all-reduce(f32[262144]{0} %p2.3), replica_groups={{0,1,2,3,4,5,6,7}}, to_apply=%red.add
  ROOT %t.6 = (bf16[128,128]{1,0}, f32[262144]{0}) tuple(bf16[128,128]{1,0} %dot.4, f32[262144]{0} %ar.5)
}
"""

# The seeded mutation the zero-slack rule exists for: the SAME module
# with one extra dependence edge — the dot now waits on the all-reduce
# (a control-predecessor, exactly how an accidental serialization prints
# in scheduled HLO) — so the collective's slack pool collapses to zero.

_HLO_COLL_SERIALIZED = """\
HloModule jit_c, is_scheduled=true

%red.add (x.1: f32[], y.1: f32[]) -> f32[] {
  %x.1 = f32[] parameter(0)
  %y.1 = f32[] parameter(1)
  ROOT %r.1 = f32[] add(f32[] %x.1, f32[] %y.1)
}

ENTRY %main.7 (p0.1: bf16[128,256], p1.2: bf16[256,128], p2.3: f32[262144]) -> (bf16[128,128], f32[262144]) {
  %p0.1 = bf16[128,256]{1,0} parameter(0)
  %p1.2 = bf16[256,128]{1,0} parameter(1)
  %p2.3 = f32[262144]{0} parameter(2)
  %ar.5 = f32[262144]{0} all-reduce(f32[262144]{0} %p2.3), replica_groups={{0,1,2,3,4,5,6,7}}, to_apply=%red.add
  %dot.4 = bf16[128,128]{1,0} dot(bf16[128,256]{1,0} %p0.1, bf16[256,128]{1,0} %p1.2), lhs_contracting_dims={1}, rhs_contracting_dims={0}, control-predecessors={%ar.5}
  ROOT %t.6 = (bf16[128,128]{1,0}, f32[262144]{0}) tuple(bf16[128,128]{1,0} %dot.4, f32[262144]{0} %ar.5)
}
"""

_DOT_MS = 2 * 128 * 128 * 256 / MXU_PEAK_FLOPS * 1e3
_AR_MS = ICI_LATENCY_MS + 2 * (8 - 1) / 8 * (1 << 20) / ICI_BYTES_PER_S * 1e3


def test_collective_cost_slack_and_exposure():
    p = profile_hlo(_HLO_COLL, family="fixture", n_devices=8)
    assert p["collectives"] == {"all-reduce": 1}
    assert p["op_map_census"] == {"all-reduce": 1}
    (row,) = p["collective_rows"]
    assert row["kind"] == "all-reduce" and row["bytes"] == 1 << 20
    assert row["cost_ms"] == pytest.approx(_AR_MS, abs=1e-6)
    assert row["slack_ms"] == pytest.approx(_DOT_MS, abs=1e-6)
    assert row["exposed_ms"] == pytest.approx(_AR_MS - _DOT_MS, abs=1e-6)
    assert p["predicted_exposed_ms"] == pytest.approx(
        _AR_MS - _DOT_MS, abs=1e-6)


def test_seeded_dependency_collapses_slack():
    p = profile_hlo(_HLO_COLL_SERIALIZED, family="fixture", n_devices=8)
    (row,) = p["collective_rows"]
    assert row["slack_ms"] == 0.0
    assert row["exposed_ms"] == pytest.approx(_AR_MS, abs=1e-6)


def test_group_size_parsing():
    # {{0,1,2,3},{4,5,6,7}} -> n=4 even on an 8-device family
    hlo = _HLO_COLL.replace("replica_groups={{0,1,2,3,4,5,6,7}}",
                            "replica_groups={{0,1,2,3},{4,5,6,7}}")
    p = profile_hlo(hlo, family="fixture", n_devices=8)
    want = ICI_LATENCY_MS + 2 * (4 - 1) / 4 * (1 << 20) / ICI_BYTES_PER_S * 1e3
    assert p["collective_rows"][0]["cost_ms"] == pytest.approx(
        want, abs=1e-6)


# --- fixture C: while = condition + ONE body iteration ---------------------
# Body crit path = the single add (3 MiB at HBM rate); gte/tuple are
# free aliases, the condition is a free constant. The while op's cost —
# and therefore the entry critical path AND the merged phase x class
# composition — must equal exactly one body iteration.

_HLO_WHILE = """\
HloModule jit_w, is_scheduled=true

%body.b (bp.1: (f32[262144], f32[262144])) -> (f32[262144], f32[262144]) {
  %bp.1 = (f32[262144]{0}, f32[262144]{0}) parameter(0)
  %g0.1 = f32[262144]{0} get-tuple-element((f32[262144]{0}, f32[262144]{0}) %bp.1), index=0
  %g1.1 = f32[262144]{0} get-tuple-element((f32[262144]{0}, f32[262144]{0}) %bp.1), index=1
  %w0.1 = f32[262144]{0} add(f32[262144]{0} %g0.1, f32[262144]{0} %g1.1)
  ROOT %wt.1 = (f32[262144]{0}, f32[262144]{0}) tuple(f32[262144]{0} %w0.1, f32[262144]{0} %g1.1)
}

%cond.c (cp.1: (f32[262144], f32[262144])) -> pred[] {
  %cp.1 = (f32[262144]{0}, f32[262144]{0}) parameter(0)
  ROOT %lt.1 = pred[] constant(false)
}

ENTRY %main.w (p0.1: f32[262144], p1.2: f32[262144]) -> (f32[262144], f32[262144]) {
  %p0.1 = f32[262144]{0} parameter(0)
  %p1.2 = f32[262144]{0} parameter(1)
  %in.3 = (f32[262144]{0}, f32[262144]{0}) tuple(f32[262144]{0} %p0.1, f32[262144]{0} %p1.2)
  ROOT %wh.4 = (f32[262144]{0}, f32[262144]{0}) while((f32[262144]{0}, f32[262144]{0}) %in.3), condition=%cond.c, body=%body.b
}
"""


def test_while_costs_one_body_iteration():
    p = profile_hlo(_HLO_WHILE, family="fixture", n_devices=1)
    body = _ms(3 << 20)
    assert p["critical_path_ms"] == pytest.approx(body, abs=TOL)
    assert p["serialized_ms"] == pytest.approx(body, abs=TOL)
    total = sum(v for cls in p["critical_path_phase_class_ms"].values()
                for v in cls.values())
    assert total == pytest.approx(p["critical_path_ms"], abs=1e-5)


def test_analyzer_exposes_per_computation_results():
    a = analyze_hlo_schedule(_HLO_WHILE, n_devices=1)
    assert a.analyze("body.b").crit_ms == pytest.approx(_ms(3 << 20),
                                                        abs=TOL)
    assert a.analyze("cond.c").crit_ms == 0.0


# --- the lint rules on fixture-derived profiles ----------------------------


def _coll_profile(hlo=_HLO_COLL):
    return profile_hlo(hlo, family="train_tp", n_devices=8)


def test_zero_slack_rule_quiet_on_clean():
    floors = {"all-reduce": _DOT_MS / 4}
    assert contracts.check_collective_slack(
        "train_tp", floors, profile=_coll_profile()) == []


def test_zero_slack_rule_fires_on_seeded_dependency():
    floors = {"all-reduce": _DOT_MS / 4}
    vs = contracts.check_collective_slack(
        "train_tp", floors, profile=_coll_profile(_HLO_COLL_SERIALIZED))
    assert [v.rule for v in vs] == ["collective-zero-slack"]
    assert "serialize" in vs[0].message


def test_zero_slack_rule_flags_contract_drift():
    # a floor for a kind the module no longer contains is itself a finding
    vs = contracts.check_collective_slack(
        "train_tp", {"all-gather": 1e-6}, profile=_coll_profile())
    assert [v.rule for v in vs] == ["collective-zero-slack"]
    assert "drifted" in vs[0].message


def test_count_consistency_quiet_on_clean():
    assert contracts.check_collective_count_consistency(
        "train_tp", {"psum": 1}, profile=_coll_profile()) == []


def test_count_consistency_fires_on_dropped_psum():
    # contract says 2 psum call sites, the compiled module carries 1 —
    # the seeded "a collective silently disappeared" defect
    vs = contracts.check_collective_count_consistency(
        "train_tp", {"psum": 2}, profile=_coll_profile())
    assert [v.rule for v in vs] == ["collective-count-consistency"]
    assert "all-reduce" in vs[0].message


def test_count_consistency_gspmd_is_superset():
    p = _coll_profile()
    # gspmd: census may exceed the declared sites but never undershoot
    assert contracts.check_collective_count_consistency(
        "train_tp", {}, gspmd=True, profile=p) == []
    vs = contracts.check_collective_count_consistency(
        "train_tp", {"psum": 2}, gspmd=True, profile=p)
    assert [v.rule for v in vs] == ["collective-count-consistency"]
    assert "at least" in vs[0].message


def test_count_consistency_fires_on_parser_drift():
    p = _coll_profile()
    p["op_map_census"] = {"all-reduce": 3}
    vs = contracts.check_collective_count_consistency(
        "train_tp", {"psum": 1}, profile=p)
    assert any("drifted apart" in v.message for v in vs)


def test_rules_report_analysis_failure_as_finding():
    vs = contracts.check_collective_slack("not_a_family", {"all-reduce": 1})
    assert [v.rule for v in vs] == ["collective-zero-slack"]
    assert "failed to analyze" in vs[0].message
    vs = contracts.check_collective_count_consistency("not_a_family", {})
    assert [v.rule for v in vs] == ["collective-count-consistency"]


# --- diffing ---------------------------------------------------------------


def test_self_diff_is_exactly_zero():
    p = _coll_profile()
    d = diff_schedprofiles(p, json.loads(json.dumps(p)))
    assert d["n_flagged"] == 0
    assert all(r["delta_ms"] == 0.0 for r in d["rows"])


def test_diff_flags_slack_regression():
    a = _coll_profile()
    b = _coll_profile(_HLO_COLL_SERIALIZED)
    # the exposure delta is small relative to the latency-dominated
    # all-reduce cost (~0.4%), so gate it at a tight analytic threshold;
    # the slack row itself collapses to zero and flags at any threshold
    d = diff_schedprofiles(a, b, threshold_pct=0.1)
    flagged = {(r["kind"], r["key"]) for r in d["rows"] if r["flagged"]}
    assert ("slack", "all-reduce") in flagged
    assert ("total", "predicted_exposed_ms") in flagged


def test_diff_rejects_family_mismatch():
    a = _coll_profile()
    b = dict(_coll_profile(), family="train_ep_a2a")
    with pytest.raises(ValueError, match="different families"):
        diff_schedprofiles(a, b)


# --- CPU end-to-end on real registered families ----------------------------


@pytest.fixture(scope="module")
def train_tp_profile():
    return schedkit.profile_family_cached("train_tp")


@pytest.fixture(scope="module")
def train_ep_profile():
    return schedkit.profile_family_cached("train_ep_a2a")


@pytest.mark.parametrize("fam", ["train_tp_profile", "train_ep_profile"])
def test_family_composition_sums_and_census_crosscheck(fam, request):
    p = request.getfixturevalue(fam)
    assert p["schema"] == "schedprofile/v1"
    total = sum(v for cls in p["critical_path_phase_class_ms"].values()
                for v in cls.values())
    assert total == pytest.approx(p["critical_path_ms"], abs=1e-4)
    assert 0.0 < p["schedule_efficiency"] <= 1.0
    # the anti-drift tripwire: schedkit's DAG census and tracekit's
    # instruction-map census of the SAME module must agree
    assert p["collectives"] == p["op_map_census"]
    assert p["collectives"], "sharded family must carry collectives"


def test_train_tp_slack_pools_hold_declared_floors(train_tp_profile):
    from cs336_systems_tpu.parallel import tp

    floors = tp.lint_contract()["collective_slack_floor_ms"]
    pools = {}
    for r in train_tp_profile["collective_rows"]:
        pools[r["kind"]] = pools.get(r["kind"], 0.0) + r["slack_ms"]
    for kind, floor in floors.items():
        assert pools.get(kind, 0.0) >= floor, (kind, pools)


def test_train_ep_slack_pools_hold_declared_floors(train_ep_profile):
    from cs336_systems_tpu.analysis import registry
    from cs336_systems_tpu.parallel import ep

    floors = ep.lint_contract(registry._moe_cfg())[
        "collective_slack_floor_ms"]
    pools = {}
    for r in train_ep_profile["collective_rows"]:
        pools[r["kind"]] = pools.get(r["kind"], 0.0) + r["slack_ms"]
    for kind, floor in floors.items():
        assert pools.get(kind, 0.0) >= floor, (kind, pools)


def test_unknown_family_raises():
    with pytest.raises(KeyError, match="unknown step family"):
        schedkit.profile_family("not_a_family")


@pytest.mark.slow
def test_every_registered_family_profiles():
    """schedprofile/v1 builds for ALL registered targets (the 17 step
    families + the bench shapes) and every composition sums to its
    critical-path total."""
    for fam in schedkit.family_names():
        p = schedkit.profile_family(fam)
        assert p["schema"] == "schedprofile/v1", fam
        total = sum(v for cls in p["critical_path_phase_class_ms"].values()
                    for v in cls.values())
        assert total == pytest.approx(p["critical_path_ms"],
                                      abs=1e-4), fam


# --- sched_cli -------------------------------------------------------------


def test_sched_cli_list_matches_memkit(capsys):
    from cs336_systems_tpu.analysis import memkit, sched_cli

    assert sched_cli.main(["--list"]) == 0
    out = capsys.readouterr().out.split()
    assert out == memkit.family_names()


def test_sched_cli_diff_roundtrip(tmp_path, capsys):
    from cs336_systems_tpu.analysis import sched_cli

    a = tmp_path / "a.json"
    b = tmp_path / "b.json"
    schedkit.write_profile(_coll_profile(), str(a))
    schedkit.write_profile(_coll_profile(_HLO_COLL_SERIALIZED), str(b))
    assert sched_cli.main(["--diff", str(a), str(a)]) == 0
    capsys.readouterr()
    assert sched_cli.main(["--diff", str(a), str(b)]) == 1
    out = capsys.readouterr().out
    assert "FLAGGED" in out


def test_sched_cli_step_writes_artifact(tmp_path, capsys):
    from cs336_systems_tpu.analysis import sched_cli

    out = tmp_path / "p.json"
    assert sched_cli.main(["--step", "serve_dp", "--out", str(out)]) == 0
    p = json.loads(out.read_text())
    assert p["schema"] == "schedprofile/v1"
    assert p["family"] == "serve_dp" and p["n_devices"] == 8
    text = capsys.readouterr().out
    assert "critical path" in text and "efficiency" in text


def test_sched_cli_unknown_family_exits_1(capsys):
    from cs336_systems_tpu.analysis import sched_cli

    assert sched_cli.main(["--step", "nope"]) == 1
    assert "unknown step family" in capsys.readouterr().err


def test_format_profile_renders(train_tp_profile):
    text = schedkit.format_profile(train_tp_profile)
    assert "critical path" in text
    assert "slack table" in text


# --- cross-validation against tracekit (the measured half) -----------------


def test_predicted_exposure_ordering_matches_tracekit(
        train_tp_profile, train_ep_profile):
    """The static and measured halves of the overlap story must agree on
    ORDERING for the pinned families: schedkit predicts train_tp's
    collectives are harder to hide than train_ep_a2a's (the chunked-CE
    psums sit in scan bodies with little independent compute; the a2a
    dispatch runs against the expert FFN work), and tracekit's measured
    hidden/exposed split must rank them the same way. Exposed FRACTIONS
    (exposed / total collective time) are compared, not walls — CPU-mesh
    wall times jitter run to run; the fractions are steadier but still
    carry ±0.05 of single-host scheduling noise (measured spread: tp
    0.48–0.57, ep 0.48–0.53), so the measured half asserts NO CONFIDENT
    CONTRADICTION (margin 0.10) rather than strict ordering — a real
    overlap regression (a fully-hidden tp or fully-exposed ep) moves the
    fraction by far more than the margin."""
    from cs336_systems_tpu.analysis import tracekit

    pred = {}
    for fam, prof in (("train_tp", train_tp_profile),
                      ("train_ep_a2a", train_ep_profile)):
        assert prof["predicted_exposed_ms"] <= prof["collective_cost_ms"]
        pred[fam] = prof["predicted_exposed_ms"] / prof["collective_cost_ms"]
    assert pred["train_tp"] > pred["train_ep_a2a"]

    meas = {}
    for fam in ("train_tp", "train_ep_a2a"):
        t = tracekit.profile_step(fam, iters=1)
        total = sum(v for c, v in t["class_ms"].items()
                    if c.startswith("collective-"))
        assert t["collective_hidden_ms"] + t["collective_exposed_ms"] == \
            pytest.approx(total, abs=1e-2)
        meas[fam] = t["collective_exposed_ms"] / total
    assert meas["train_tp"] > meas["train_ep_a2a"] - 0.10, (meas, pred)
