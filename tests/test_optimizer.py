"""AdamW + LR schedule + nn-utils tests against independent numpy oracles."""

import math

import jax
import jax.numpy as jnp
import numpy as np

from cs336_systems_tpu.ops.nn import clip_gradients, cross_entropy, global_grad_norm, log_softmax, softmax
from cs336_systems_tpu.optim.adamw import AdamWHparams, adamw_init, adamw_update
from cs336_systems_tpu.optim.schedule import get_cosine_lr


def numpy_adamw_reference(p, grads_seq, lr, b1, b2, eps, wd):
    """Straight transcription of the reference update semantics
    (optimizer.py:50-86) in numpy, used as the oracle."""
    m = np.zeros_like(p)
    v = np.zeros_like(p)
    for t, g in enumerate(grads_seq, start=1):
        m = b1 * m + (1 - b1) * g
        v = b2 * v + (1 - b2) * g * g
        alpha_t = lr * math.sqrt(1 - b2**t) / (1 - b1**t)
        p = p - alpha_t * m / (np.sqrt(v) + eps)
        p = p - lr * wd * p
    return p


def test_adamw_matches_reference_semantics():
    rng = np.random.default_rng(0)
    p0 = rng.normal(size=(7, 5)).astype(np.float32)
    grads = [rng.normal(size=(7, 5)).astype(np.float32) for _ in range(10)]
    hp = AdamWHparams(lr=1e-2, beta1=0.9, beta2=0.999, eps=1e-8, weight_decay=0.01)

    params = {"w": jnp.asarray(p0)}
    state = adamw_init(params)
    for g in grads:
        params, state = adamw_update(params, {"w": jnp.asarray(g)}, state, hp)

    expected = numpy_adamw_reference(p0, grads, 1e-2, 0.9, 0.999, 1e-8, 0.01)
    np.testing.assert_allclose(np.asarray(params["w"]), expected, rtol=1e-5, atol=1e-6)
    assert int(state["t"]) == 10


def test_adamw_under_jit_and_traced_lr():
    hp = AdamWHparams()
    params = {"a": jnp.ones((3,)), "b": {"c": jnp.full((2, 2), 2.0)}}
    state = adamw_init(params)
    grads = jax.tree_util.tree_map(jnp.ones_like, params)

    @jax.jit
    def step(p, s, lr):
        return adamw_update(p, grads, s, hp, lr=lr)

    p1, s1 = step(params, state, jnp.float32(0.1))
    assert int(s1["t"]) == 1
    assert not np.allclose(np.asarray(p1["a"]), np.asarray(params["a"]))


def test_cosine_lr_schedule():
    mx, mn, warm, total = 1.0, 0.1, 10, 100
    # warmup is linear
    assert math.isclose(float(get_cosine_lr(0, mx, mn, warm, total)), 0.0)
    assert math.isclose(float(get_cosine_lr(5, mx, mn, warm, total)), 0.5, rel_tol=1e-6)
    # peak at end of warmup
    assert math.isclose(float(get_cosine_lr(10, mx, mn, warm, total)), mx, rel_tol=1e-6)
    # midpoint of cosine: average of max and min
    assert math.isclose(float(get_cosine_lr(55, mx, mn, warm, total)), (mx + mn) / 2, rel_tol=1e-5)
    # floor after the cycle
    assert math.isclose(float(get_cosine_lr(150, mx, mn, warm, total)), mn, rel_tol=1e-6)
    # traceable
    vals = jax.vmap(lambda i: get_cosine_lr(i, mx, mn, warm, total))(jnp.arange(200))
    assert vals.shape == (200,)


def test_softmax_and_log_softmax():
    x = jnp.array([[1e4, 1e4 + 1.0, 0.0]])  # overflow-prone without max-subtract
    s = np.asarray(softmax(x))
    assert np.all(np.isfinite(s))
    np.testing.assert_allclose(s.sum(-1), 1.0, rtol=1e-6)
    ls = np.asarray(log_softmax(x))
    np.testing.assert_allclose(np.exp(ls), s, rtol=1e-5, atol=1e-7)


def test_cross_entropy_matches_manual():
    rng = np.random.default_rng(1)
    logits = rng.normal(size=(4, 9, 11)).astype(np.float32)
    targets = rng.integers(0, 11, size=(4, 9))
    # manual
    x = logits - logits.max(-1, keepdims=True)
    logp = x - np.log(np.exp(x).sum(-1, keepdims=True))
    expected = -np.take_along_axis(logp, targets[..., None], -1).mean()
    got = float(cross_entropy(jnp.asarray(logits), jnp.asarray(targets)))
    assert math.isclose(got, float(expected), rel_tol=1e-5)


def test_cross_entropy_custom_vjp_matches_autodiff():
    """cross_entropy's fused backward (softmax − onehot scaled by the
    cotangent) must equal autodiff through log_softmax, in fp32 and bf16,
    including non-unit cotangents."""

    def ce_ref(logits, targets):
        nls = -log_softmax(logits.astype(jnp.float32), axis=-1)
        return jnp.mean(
            jnp.take_along_axis(nls, targets[..., None].astype(jnp.int32), -1)
        )

    targets = jax.random.randint(jax.random.PRNGKey(1), (4, 16), 0, 101)
    for dtype in (jnp.float32, jnp.bfloat16):
        logits = jax.random.normal(jax.random.PRNGKey(0), (4, 16, 101), dtype) * 3
        np.testing.assert_allclose(
            float(cross_entropy(logits, targets)), float(ce_ref(logits, targets)),
            rtol=1e-6,
        )
        for scale in (1.0, 3.5):
            g1 = jax.grad(lambda x: scale * cross_entropy(x, targets))(logits)
            g2 = jax.grad(lambda x: scale * ce_ref(x, targets))(logits)
            np.testing.assert_allclose(
                np.asarray(g1, np.float32), np.asarray(g2, np.float32),
                rtol=1e-2, atol=1e-6,
            )


def test_gradient_clipping():
    grads = {"a": jnp.full((10,), 3.0), "b": jnp.full((10,), 4.0)}
    norm = float(global_grad_norm(grads))
    assert math.isclose(norm, math.sqrt(10 * 9 + 10 * 16), rel_tol=1e-6)
    clipped = clip_gradients(grads, max_norm=1.0)
    new_norm = float(global_grad_norm(clipped))
    assert math.isclose(new_norm, 1.0, rel_tol=1e-4)
    # below threshold: untouched
    small = {"a": jnp.full((4,), 0.01)}
    same = clip_gradients(small, max_norm=1.0)
    np.testing.assert_allclose(np.asarray(same["a"]), np.asarray(small["a"]), rtol=1e-7)


def test_data_loader():
    from cs336_systems_tpu.data.loader import get_batch

    dataset = np.arange(1000, dtype=np.uint16)
    x, y = get_batch(dataset, batch_size=8, context_length=32, rng=0)
    assert x.shape == (8, 32) and y.shape == (8, 32)
    # y is x shifted by one
    np.testing.assert_array_equal(np.asarray(x)[:, 1:], np.asarray(y)[:, :-1])
    np.testing.assert_array_equal(np.asarray(y)[:, 0], np.asarray(x)[:, 0] + 1)


def test_train_step_reduces_loss():
    from cs336_systems_tpu.train import init_train_state, make_train_step
    from cs336_systems_tpu.models.transformer import TransformerConfig

    cfg = TransformerConfig(
        vocab_size=64, context_length=32, d_model=32, num_layers=2, num_heads=4, d_ff=64
    )
    params, opt_state = init_train_state(jax.random.PRNGKey(0), cfg)
    step = make_train_step(cfg, AdamWHparams(lr=3e-3))
    x = jax.random.randint(jax.random.PRNGKey(1), (4, 32), 0, cfg.vocab_size)
    y = jnp.roll(x, -1, axis=-1)
    losses = []
    for _ in range(8):
        params, opt_state, loss = step(params, opt_state, x, y)
        losses.append(float(loss))
    assert losses[-1] < losses[0]


# ---------------------------------------------------------------------------
# Gradient accumulation


def test_accum_grads_match_full_batch():
    """Microbatch-accumulated gradients equal the full-batch gradient for a
    mean-reduced loss (equal microbatch sizes)."""
    from cs336_systems_tpu.train import make_accum_value_and_grad

    from common import mse_loss, toy_model_apply, toy_model_init

    params, _ = toy_model_init(jax.random.PRNGKey(0))
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.standard_normal((16, 10)).astype(np.float32))
    y = jnp.asarray(rng.standard_normal((16, 5)).astype(np.float32))

    loss_fn = lambda p, xx, yy: mse_loss(toy_model_apply, p, xx, yy)
    full_loss, full_grads = jax.value_and_grad(loss_fn)(params, x, y)

    acc = make_accum_value_and_grad(loss_fn, 4)
    a_loss, a_grads = jax.jit(acc)(
        params, x.reshape(4, 4, 10), y.reshape(4, 4, 5)
    )
    np.testing.assert_allclose(float(a_loss), float(full_loss), rtol=1e-6)
    for a, b in zip(
        jax.tree_util.tree_leaves(a_grads), jax.tree_util.tree_leaves(full_grads)
    ):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-7)


def test_accum_train_step_matches_full_batch_step():
    """make_train_step(accum_steps=4) tracks the full-batch step over
    several updates on the LM."""
    from cs336_systems_tpu.models.transformer import TransformerConfig
    from cs336_systems_tpu.train import init_train_state, make_train_step

    cfg = TransformerConfig(
        vocab_size=32, context_length=16, d_model=32, num_layers=2,
        num_heads=2, d_ff=64,
    )
    hp = AdamWHparams(lr=1e-3)
    params, opt = init_train_state(jax.random.PRNGKey(0), cfg)
    pa, oa = jax.tree_util.tree_map(lambda x: x, (params, opt))

    full = make_train_step(cfg, hp, donate=False)
    accum = make_train_step(cfg, hp, donate=False, accum_steps=4)

    rng = np.random.default_rng(0)
    for _ in range(3):
        x = jnp.asarray(rng.integers(0, 32, (8, 16)), jnp.int32)
        y = jnp.roll(x, -1, axis=-1)
        params, opt, l_full = full(params, opt, x, y)
        pa, oa, l_acc = accum(pa, oa, x.reshape(4, 2, 16), y.reshape(4, 2, 16))
        np.testing.assert_allclose(float(l_acc), float(l_full), rtol=1e-5)
    for a, b in zip(
        jax.tree_util.tree_leaves(pa), jax.tree_util.tree_leaves(params)
    ):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-6)
