"""Sharded serving tests: batch-dp and head-tp decode over the virtual
CPU mesh must reproduce the single-device row-keyed generation
BIT-IDENTICALLY (sharding is a layout, not an approximation — the same
oracle discipline as the training parallelism tests).
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from cs336_systems_tpu.models.decode import generate_kv_batched
from cs336_systems_tpu.models.transformer import (
    TransformerConfig,
    init_transformer_lm,
)
from cs336_systems_tpu.parallel.mesh import make_mesh
from cs336_systems_tpu.parallel.serve import make_sharded_generate

CFG = TransformerConfig(
    vocab_size=64, context_length=64, d_model=64,
    num_layers=2, num_heads=4, d_ff=128,
)


def _setup(cfg=CFG, batch=8, plen=6, seed=0):
    params = init_transformer_lm(jax.random.PRNGKey(seed), cfg)
    prompts = jax.random.randint(
        jax.random.PRNGKey(seed + 1), (batch, plen), 0, cfg.vocab_size
    )
    key = jax.random.PRNGKey(seed + 2)
    return params, prompts, key


def _reference(params, prompts, key, cfg=CFG, new=10, **kw):
    return np.asarray(generate_kv_batched(
        params, cfg, prompts, new, key, temperature=0.9, top_k=8,
        row_keyed=True, **kw,
    ))


@pytest.mark.parametrize("mesh_axes,dp,tp", [
    ({"dp": 8}, "dp", None),
    ({"dp": 2, "tp": 4}, "dp", "tp"),
    ({"tp": 4}, None, "tp"),
])
def test_sharded_generate_matches_single_device(mesh_axes, dp, tp):
    params, prompts, key = _setup()
    want = _reference(params, prompts, key)

    mesh = make_mesh(mesh_axes)
    gen = make_sharded_generate(
        CFG, mesh, max_new_tokens=10, dp_axis=dp, tp_axis=tp,
        temperature=0.9, top_k=8,
    )
    got = np.asarray(gen(params, prompts, key))
    np.testing.assert_array_equal(got, want)


def test_row_keyed_rows_independent_of_batch_layout():
    """The row-keyed stream depends only on a row's global index: the same
    row generated inside a bigger batch draws the same tokens."""
    params, prompts, key = _setup(batch=8)
    full = _reference(params, prompts, key)
    # rows 0..3 alone, same offset 0
    head = np.asarray(generate_kv_batched(
        params, CFG, prompts[:4], 10, key, temperature=0.9, top_k=8,
        row_keyed=True,
    ))
    np.testing.assert_array_equal(head, full[:4])


def test_sharded_generate_moe_dp():
    """MoE serving shards over dp (expert weights replicated). Serving
    routing is DROPLESS by contract (capacity pinned to each call's token
    count — models/decode._ffn), so shard-local routing equals the
    full-batch routing for every row at ANY capacity_factor."""
    cfg = dataclasses.replace(CFG, num_experts=4, moe_top_k=2)
    params, prompts, key = _setup(cfg)
    want = np.asarray(generate_kv_batched(
        params, cfg, prompts, 8, key, temperature=0.9, top_k=8,
        row_keyed=True,
    ))
    mesh = make_mesh({"dp": 4})
    gen = make_sharded_generate(cfg, mesh, max_new_tokens=8,
                                temperature=0.9, top_k=8)
    got = np.asarray(gen(params, prompts, key))
    np.testing.assert_array_equal(got, want)


def test_sharded_generate_windowed():
    """Sliding-window attention (cfg.attn_window) rides through the
    sharded generation unchanged — windowed prefill mask + windowed decode
    reads per shard, bit-equal to the single-device windowed path."""
    cfg = dataclasses.replace(CFG, attn_window=8)
    params, prompts, key = _setup(cfg)
    want = np.asarray(generate_kv_batched(
        params, cfg, prompts, 10, key, temperature=0.9, top_k=8,
        row_keyed=True,
    ))
    mesh = make_mesh({"dp": 2, "tp": 4})
    gen = make_sharded_generate(cfg, mesh, max_new_tokens=10, dp_axis="dp",
                                tp_axis="tp", temperature=0.9, top_k=8)
    got = np.asarray(gen(params, prompts, key))
    np.testing.assert_array_equal(got, want)


def test_serve_validation():
    mesh = make_mesh({"dp": 4})
    gen = make_sharded_generate(CFG, mesh, max_new_tokens=8)
    params, prompts, key = _setup(batch=6)
    with pytest.raises(ValueError, match="divisible"):
        gen(params, prompts, key)
    # tp+MoE is SUPPORTED since round 5 (attention over tp, experts
    # replicated or over ep — test_sharded_generate_moe_tp_ep_composed);
    # what must still raise is a head count the tp degree cannot divide:
    with pytest.raises(ValueError, match="num_heads"):
        make_sharded_generate(
            dataclasses.replace(CFG, num_heads=2, d_model=32,
                                num_experts=4),
            make_mesh({"dp": 2, "tp": 4}),
            max_new_tokens=8, tp_axis="tp",  # heads 2 % tp 4 != 0
        )


@pytest.mark.parametrize("mesh_axes,dp,tp", [
    ({"dp": 8}, "dp", None),
    ({"dp": 2, "tp": 4}, "dp", "tp"),
    ({"tp": 4}, None, "tp"),
])
def test_sharded_ragged_matches_single_device(mesh_axes, dp, tp):
    """Ragged batches (per-row prompt lengths, 4x spread) through the
    sharded server: lengths shard with their rows over dp, replicate over
    tp, and the tokens equal the single-device ragged row-keyed path —
    which itself equals each row's own single-row call
    (tests/test_decode.py::test_ragged_generate_matches_per_row_single_calls)."""
    params, prompts, key = _setup(plen=12)
    rng = np.random.default_rng(4)
    lens = np.asarray([3, 12, 6, 9, 12, 4, 8, 5])
    want = np.asarray(generate_kv_batched(
        params, CFG, prompts, 10, key, temperature=0.9, top_k=8,
        row_keyed=True, prompt_lens=lens,
    ))

    mesh = make_mesh(mesh_axes)
    gen = make_sharded_generate(
        CFG, mesh, max_new_tokens=10, dp_axis=dp, tp_axis=tp,
        temperature=0.9, top_k=8,
    )
    got = np.asarray(gen(params, prompts, key, prompt_lens=lens))
    np.testing.assert_array_equal(got, want)
    # uniform path still works from the same server (separate cached entry)
    got_u = np.asarray(gen(params, prompts, key))
    want_u = _reference(params, prompts, key)
    np.testing.assert_array_equal(got_u, want_u)


def test_sharded_ragged_lens_validation():
    mesh = make_mesh({"dp": 4})
    gen = make_sharded_generate(CFG, mesh, max_new_tokens=4)
    params, prompts, key = _setup()
    with pytest.raises(ValueError, match="prompt_lens"):
        gen(params, prompts, key, prompt_lens=np.asarray([3, 4]))


@pytest.mark.parametrize("mesh_axes,dp", [
    ({"ep": 4}, None),
    ({"dp": 2, "ep": 4}, "dp"),
])
def test_sharded_generate_moe_expert_sharded(mesh_axes, dp):
    """EXPERT-SHARDED MoE serving (round 5): expert weights shard over
    ep (1/W of the expert bytes per device — the path for expert weights
    beyond one chip's HBM), tokens replicate over ep, one psum per MoE
    layer. At top_k=2 every claim is computed on exactly one shard and
    the combine psum is one commutative fp32 addition, so the tokens are
    BIT-IDENTICAL to the single-device dropless path."""
    cfg = dataclasses.replace(CFG, num_experts=8, moe_top_k=2)
    params, prompts, key = _setup(cfg)
    want = np.asarray(generate_kv_batched(
        params, cfg, prompts, 8, key, temperature=0.9, top_k=8,
        row_keyed=True,
    ))
    mesh = make_mesh(mesh_axes)
    gen = make_sharded_generate(cfg, mesh, max_new_tokens=8, dp_axis=dp,
                                ep_axis="ep", temperature=0.9, top_k=8)
    got = np.asarray(gen(params, prompts, key))
    np.testing.assert_array_equal(got, want)
    # ragged composes with expert sharding too
    lens = np.asarray([3, 6, 2, 5, 6, 4, 1, 6])
    want_r = np.asarray(generate_kv_batched(
        params, cfg, prompts, 8, key, temperature=0.9, top_k=8,
        row_keyed=True, prompt_lens=lens,
    ))
    got_r = np.asarray(gen(params, prompts, key, prompt_lens=lens))
    np.testing.assert_array_equal(got_r, want_r)


def test_ep_serving_validation():
    mesh = make_mesh({"dp": 2, "ep": 4})
    with pytest.raises(ValueError, match="num_experts=0"):
        make_sharded_generate(CFG, mesh, max_new_tokens=4, ep_axis="ep")
    moe = dataclasses.replace(CFG, num_experts=6, moe_top_k=2)
    with pytest.raises(ValueError, match="not divisible"):
        make_sharded_generate(moe, mesh, max_new_tokens=4, ep_axis="ep")
    moe8 = dataclasses.replace(CFG, num_experts=8, moe_top_k=2)
    with pytest.raises(ValueError, match="distinct"):
        make_sharded_generate(moe8, mesh, max_new_tokens=4, dp_axis="ep",
                              ep_axis="ep")


@pytest.mark.parametrize("mesh_axes,dp,tp", [
    ({"tp": 2, "ep": 4}, None, "tp"),
    ({"dp": 2, "tp": 2, "ep": 2}, "dp", "tp"),
    ({"tp": 4}, None, "tp"),  # tp-alone MoE: attention sharded, experts replicated
])
def test_sharded_generate_moe_tp_ep_composed(mesh_axes, dp, tp):
    """MoE serving composed with head sharding (round 5): attention
    projections + KV caches shard over tp, expert weights over ep (or
    replicate), batch over dp — the former tp+MoE exclusion is gone. The
    ffn tp-psum is skipped for MoE (the expert output is tp-replicated;
    models/decode._decode_block), which this test would catch as a
    tp-degree multiplication if wrong. Token equality vs the
    single-device row-keyed path at the tested configs (tp psums can
    perturb logit low bits; same empirical contract as dense tp)."""
    cfg = dataclasses.replace(CFG, num_experts=8, moe_top_k=2)
    params, prompts, key = _setup(cfg)
    want = np.asarray(generate_kv_batched(
        params, cfg, prompts, 8, key, temperature=0.9, top_k=8,
        row_keyed=True,
    ))
    mesh = make_mesh(mesh_axes)
    tp_kw = {"tp_axis": tp} if tp else {}
    ep_kw = {"ep_axis": "ep"} if "ep" in mesh_axes else {}
    gen = make_sharded_generate(cfg, mesh, max_new_tokens=8, dp_axis=dp,
                                temperature=0.9, top_k=8, **tp_kw, **ep_kw)
    got = np.asarray(gen(params, prompts, key))
    np.testing.assert_array_equal(got, want)


def test_sharded_generate_moe_ep_topk3_tolerance():
    """top_k=3 expert-sharded serving: the combine psum's shard-order
    summation can differ from slot order in low bits (the k<=2 bit-exact
    argument no longer applies — documented tolerance), but the logits
    path must still be numerically equivalent: compare PREFILL logits at
    tolerance rather than cascaded sampled tokens."""
    from cs336_systems_tpu.models.decode import prefill
    from cs336_systems_tpu.parallel.serve import serve_param_specs
    from cs336_systems_tpu.parallel.mesh import shard_tree

    cfg = dataclasses.replace(CFG, num_experts=8, moe_top_k=3,
                              moe_dispatch="sorted")
    params, prompts, _ = _setup(cfg)
    want = np.asarray(jax.jit(
        lambda p, ids: prefill(p, ids, cfg, max_len=64)[0]
    )(params, prompts))

    mesh = make_mesh({"ep": 4})
    ecfg = dataclasses.replace(cfg, moe_ep_axis="ep")
    specs = serve_param_specs(cfg, None, "ep")
    sharded = shard_tree(params, mesh, specs)
    from jax import shard_map
    from jax.sharding import PartitionSpec as P

    got = np.asarray(jax.jit(shard_map(
        lambda p, ids: prefill(p, ids, ecfg, max_len=64)[0],
        mesh=mesh, in_specs=(specs, P()), out_specs=P(),
        check_vma=False,
    ))(sharded, prompts))
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)
