"""Chunked prefill tests (ISSUE 15): splitting a join's prefill into
page-aligned chunks drained into the decode loop is a SCHEDULE, not an
approximation — every stream stays bit-identical to the row-keyed
oracle (and therefore to the unchunked engine) for every arrival order,
mesh, chunk size and prefix-cache setting; the jit decode step program
is byte-identical chunking on or off; the per-step prefill bill never
exceeds ``prefill_budget`` (asserted from the flight records); and a
mid-prefill cancel releases every page the cursor held. Same oracle
discipline as tests/test_serving_engine.py, whose fixtures this module
mirrors.
"""

import numpy as np
import pytest

import jax

from cs336_systems_tpu.analysis import servetrace
from cs336_systems_tpu.models.decode import generate_kv_batched
from cs336_systems_tpu.models.transformer import (
    TransformerConfig,
    init_transformer_lm,
)
from cs336_systems_tpu.parallel.mesh import make_mesh
from cs336_systems_tpu.serving import InvariantViolation, Request, ServingEngine

CFG = TransformerConfig(
    vocab_size=64, context_length=64, d_model=64,
    num_layers=2, num_heads=4, d_ff=128,
)
BLK = 8
NEW = 10
LENS = [12, 3, 7, 1, 12, 5, 9, 2]  # test_paged_decode's skew profile

ORDERS = {
    "fifo": list(range(8)),
    "shuffled": [5, 2, 7, 0, 3, 6, 1, 4],
    "reversed": [7, 6, 5, 4, 3, 2, 1, 0],
}


@pytest.fixture(scope="module")
def params():
    return init_transformer_lm(jax.random.PRNGKey(1), CFG)


@pytest.fixture(scope="module")
def prompts():
    rng = np.random.default_rng(7)
    return [rng.integers(0, CFG.vocab_size, size=n).astype(np.int32)
            for n in LENS]


def _oracle(params, prompts):
    pmax = max(p.size for p in prompts)
    padded = np.zeros((len(prompts), pmax), np.int32)
    for i, p in enumerate(prompts):
        padded[i, :p.size] = p
    return np.asarray(generate_kv_batched(
        params, CFG, padded, NEW, jax.random.PRNGKey(0), temperature=0.9,
        top_k=8, row_keyed=True, prompt_lens=[p.size for p in prompts],
        page_block=BLK))


@pytest.fixture(scope="module")
def want(params, prompts):
    return _oracle(params, prompts)


def _engine(params, **kw):
    base = dict(key=jax.random.PRNGKey(0), slots=8, n_pages=32,
                max_blocks=4, page_block=BLK, temperature=0.9, top_k=8,
                prefill_chunk=BLK)
    base.update(kw)
    return ServingEngine(params, CFG, **base)


def _run(eng, prompts, order, staggered=True):
    for i, r in enumerate(order):
        eng.submit(Request(rid=r, prompt=prompts[r], max_new_tokens=NEW,
                           arrival=float(i) * 0.25 if staggered else 0.0))
    tick = iter(np.arange(0.0, 1e4, 0.5))
    res = eng.run(time_fn=lambda: next(tick))
    eng.check_idle()  # every page (incl. released cursors') back free
    return res


# --- the headline property: chunking never changes a stream -----------


@pytest.mark.parametrize("order", sorted(ORDERS), ids=sorted(ORDERS))
@pytest.mark.parametrize("cache", [True, False], ids=["cache", "nocache"])
def test_chunked_matches_oracle_across_orders(params, prompts, want,
                                              order, cache):
    """Half the slots so requests queue and chunk drains interleave with
    joins and evictions — streams equal the oracle row for row for every
    arrival order, prefix cache on or off."""
    eng = _engine(params, slots=4, n_pages=16, prefix_cache=cache)
    res = _run(eng, prompts, ORDERS[order])
    assert eng.prefill_chunks > 0  # the chunked path actually ran
    for r in range(len(prompts)):
        np.testing.assert_array_equal(res[r], want[r])


def test_chunked_equals_unchunked_streams(params, prompts):
    """The direct A/B: same arrivals through a chunked and an unchunked
    engine — identical result dict, token for token."""
    a = _run(_engine(params, prefill_chunk=None), prompts,
             ORDERS["shuffled"])
    b = _run(_engine(params, prefill_chunk=BLK, prefill_budget=2 * BLK),
             prompts, ORDERS["shuffled"])
    assert sorted(a) == sorted(b)
    for r in a:
        np.testing.assert_array_equal(a[r], b[r])


@pytest.mark.parametrize("mesh_axes,dp,tp", [
    ({"dp": 8}, "dp", None),
    ({"dp": 2, "tp": 4}, "dp", "tp"),
], ids=["dp8", "dp2xtp4"])
def test_chunked_matches_oracle_on_mesh(params, prompts, want,
                                        mesh_axes, dp, tp):
    """Sharded slots: chunk drains batch per shard through the same
    bucketed programs as suffix joins — still bit-identical."""
    eng = _engine(params, slots=8, n_pages=8,
                  mesh=make_mesh(mesh_axes), dp_axis=dp, tp_axis=tp)
    res = _run(eng, prompts, [4, 1, 6, 0, 7, 2, 5, 3])
    for r in range(len(prompts)):
        np.testing.assert_array_equal(res[r], want[r])


def test_chunked_with_shared_prefix_hits(params):
    """Prefix-cache composition: only the UNCACHED suffix is chunked.
    Staggered requests sharing a full prefix block — the first publishes
    on completion, later ones acquire the hit pages and chunk only their
    tails; streams still equal the oracle over the full prompts."""
    rng = np.random.default_rng(11)
    prefix = rng.integers(0, CFG.vocab_size, size=BLK).astype(np.int32)
    tails = [rng.integers(0, CFG.vocab_size, size=n).astype(np.int32)
             for n in (9, 4, 12, 2)]
    shared = [np.concatenate([prefix, t]) for t in tails]
    want = _oracle(params, shared)
    eng = _engine(params, slots=2, n_pages=16)
    res = _run(eng, shared, list(range(len(shared))))
    assert eng.prefix_hit_tokens > 0  # later requests really hit
    for r in range(len(shared)):
        np.testing.assert_array_equal(res[r], want[r])
    # fold-time chunk-token conservation over the hit-adjusted suffixes
    cons = servetrace.fold(eng)["conservation"]["prefill_chunks"]
    assert cons["ok"] and cons["rids_checked"] == len(shared)


# --- the zero-new-collectives contract, program-identity form ---------


def test_step_program_byte_identical_chunking_on_off(params):
    """Chunking is host-side admission state: the jit decode step the
    two engines compile must LOWER to the same text, byte for byte."""
    import jax.numpy as jnp

    a, b = (_engine(params, prefill_chunk=c) for c in (None, BLK))
    args = (params, a._pool, jnp.asarray(a.logits), jnp.asarray(a.keys),
            jnp.asarray(a.pos), jnp.asarray(a.active),
            jnp.asarray(a.row_off), jnp.asarray(a.tables))
    assert (a._step_fn.lower(*args).as_text()
            == b._step_fn.lower(*args).as_text())


# --- the budget bound, from the flight records ------------------------


def test_prefill_budget_bound(params, prompts):
    """No step drains more than prefill_budget tokens: every flight
    prefill span is a chunk drain at or under the budget, and the
    engine's max_step_prefill_tokens telemetry agrees."""
    eng = _engine(params, slots=4, n_pages=16, prefill_chunk=BLK,
                  prefill_budget=BLK)
    _run(eng, prompts, ORDERS["fifo"])
    spans = eng.flight.prefills
    assert spans and all("chunks" in p for p in spans)
    assert max(p["tokens"] for p in spans) <= BLK
    assert eng.max_step_prefill_tokens <= BLK
    # per-rid conservation straight off the records: chunk tokens sum to
    # each request's full prompt (no prefix cache hits in this run's
    # distinct prompts)
    got = {}
    for p in spans:
        for c in p["chunks"]:
            got[c["rid"]] = got.get(c["rid"], 0) + c["tokens"]
    assert got == {r: int(p.size) for r, p in enumerate(prompts)}


# --- mid-prefill release + self_check --------------------------------


def test_cancel_mid_prefill_releases_pages(params, prompts):
    """Cancel between chunks: the cursor's pages free, the partial
    stream is empty, and the pool conserves (check_idle passes)."""
    eng = _engine(params, prefill_chunk=BLK, prefill_budget=BLK)
    eng.submit(Request(rid=0, prompt=prompts[0], max_new_tokens=NEW))
    eng.step(now=0.0)  # admits the cursor, drains chunk 0 of the 12-token
    assert 0 in {st.req.rid for st in eng.prefilling.values()}
    assert eng.cancel(0)
    assert not eng.prefilling and 0 in eng.cancelled
    assert eng.cancelled[0].size == 0  # no tokens ever emitted
    eng.check_idle()


def test_self_check_catches_torn_cursor(params, prompts):
    """A cursor whose ``done`` leaves the page-aligned window is the
    torn-chunk-state fault servesan injects — self_check must name it."""
    eng = _engine(params, prefill_chunk=BLK)
    eng.submit(Request(rid=0, prompt=prompts[0], max_new_tokens=NEW))
    eng.step(now=0.0)
    st = next(iter(eng.prefilling.values()))
    st.done += 3
    with pytest.raises(InvariantViolation, match="torn chunk cursor"):
        eng.self_check()


def test_chunk_config_validation(params):
    with pytest.raises(ValueError, match="multiple of"):
        _engine(params, prefill_chunk=BLK + 1)
    with pytest.raises(ValueError, match="must be >="):
        _engine(params, prefill_chunk=2 * BLK, prefill_budget=BLK)
    with pytest.raises(ValueError, match="requires prefill_chunk"):
        _engine(params, prefill_chunk=None, prefill_budget=BLK)
