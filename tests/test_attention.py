"""FlashAttention correctness vs the plain-attention oracle.

Mirrors the reference tests/test_attention.py: oracle computes attention and
logsumexp in plain ops (11-26); shapes batch 4, n=128, d=64 (29-40);
tolerance rtol=atol=1e-2 (56-57); causal × {fwd, bwd} parametrization; the
"forward must produce the [batch, n_queries] logsumexp residual" contract
(48-51). Both the portable lax.scan impl and the Pallas kernel (interpreter
mode on CPU) are tested; additional cases cover rectangular shapes, padding
(non-tile-multiple lengths), bf16, and long-sequence tiling.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from cs336_systems_tpu.ops.attention import attention_with_lse, causal_mask
from cs336_systems_tpu.ops.flash_attention import (
    flash_attention,
    flash_attention_with_lse,
)

IMPLS = ["reference", "pallas", "xla"]


def _make_qkv(key, batch, n_q, n_k, d, dtype=jnp.float32):
    kq, kk, kv = jax.random.split(key, 3)
    q = jax.random.normal(kq, (batch, n_q, d), dtype)
    k = jax.random.normal(kk, (batch, n_k, d), dtype)
    v = jax.random.normal(kv, (batch, n_k, d), dtype)
    return q, k, v


def _oracle(q, k, v, causal):
    mask = causal_mask(q.shape[-2], k.shape[-2]) if causal else None
    return attention_with_lse(q, k, v, mask)


@pytest.mark.parametrize("impl", IMPLS)
@pytest.mark.parametrize("causal", [False, True])
def test_flash_forward_matches_oracle(impl, causal):
    q, k, v = _make_qkv(jax.random.PRNGKey(0), 4, 128, 128, 64)
    o_ref, lse_ref = _oracle(q, k, v, causal)
    o, lse = flash_attention_with_lse(q, k, v, causal=causal, impl=impl)
    assert lse.shape == (4, 128)  # the [batch, n_queries] LSE contract
    assert lse.dtype == jnp.float32
    np.testing.assert_allclose(np.asarray(o), np.asarray(o_ref), rtol=1e-2, atol=1e-2)
    np.testing.assert_allclose(np.asarray(lse), np.asarray(lse_ref), rtol=1e-2, atol=1e-2)


@pytest.mark.parametrize("impl", IMPLS)
@pytest.mark.parametrize("causal", [False, True])
def test_flash_backward_matches_oracle(impl, causal):
    q, k, v = _make_qkv(jax.random.PRNGKey(1), 4, 128, 128, 64)

    def loss_flash(q, k, v):
        return jnp.sum(flash_attention(q, k, v, causal=causal, impl=impl) ** 2)

    def loss_oracle(q, k, v):
        return jnp.sum(_oracle(q, k, v, causal)[0] ** 2)

    g = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_oracle, argnums=(0, 1, 2))(q, k, v)
    for got, want, name in zip(g, g_ref, "qkv"):
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), rtol=1e-2, atol=1e-2,
            err_msg=f"d{name} mismatch",
        )


@pytest.mark.parametrize("impl", IMPLS)
def test_flash_rectangular_and_padding(impl):
    """n_q != n_k and lengths that are not tile multiples (exercises padding)."""
    q, k, v = _make_qkv(jax.random.PRNGKey(2), 2, 96, 160, 32)
    o_ref, lse_ref = _oracle(q, k, v, False)
    o, lse = flash_attention_with_lse(q, k, v, causal=False, impl=impl, q_tile=64, k_tile=64)
    np.testing.assert_allclose(np.asarray(o), np.asarray(o_ref), rtol=1e-2, atol=1e-2)
    np.testing.assert_allclose(np.asarray(lse), np.asarray(lse_ref), rtol=1e-2, atol=1e-2)


@pytest.mark.parametrize("impl", IMPLS)
def test_flash_multi_tile_causal(impl):
    """Sequence spanning several tiles, causal: block-edge masking correctness."""
    q, k, v = _make_qkv(jax.random.PRNGKey(3), 1, 512, 512, 16)
    o_ref, lse_ref = _oracle(q, k, v, True)
    o, lse = flash_attention_with_lse(q, k, v, causal=True, impl=impl, q_tile=128, k_tile=128)
    np.testing.assert_allclose(np.asarray(o), np.asarray(o_ref), rtol=1e-2, atol=1e-2)
    np.testing.assert_allclose(np.asarray(lse), np.asarray(lse_ref), rtol=1e-2, atol=1e-2)


@pytest.mark.parametrize("causal", [False, True])
def test_pallas_bwd_kernel_matches_recompute(causal):
    """The fused Pallas backward (whole-sequence VMEM tile) must equal the
    XLA recompute backward; on CPU this runs the kernel in interpret mode."""
    from cs336_systems_tpu.ops.flash_attention import (
        _flash_bwd_pallas,
        _flash_bwd_recompute,
    )

    q, k, v = _make_qkv(jax.random.PRNGKey(6), 3, 256, 256, 64)
    o_ref, lse = _oracle(q, k, v, causal)
    do = jax.random.normal(jax.random.PRNGKey(7), o_ref.shape, o_ref.dtype)
    dlse = jnp.zeros(lse.shape, jnp.float32)
    want = _flash_bwd_recompute(q, k, v, o_ref, lse, do, dlse, causal)
    got = _flash_bwd_pallas(q, k, v, o_ref, lse, do, dlse, causal,
                            interpret=True)
    for g, w, name in zip(got, want, "qkv"):
        np.testing.assert_allclose(
            np.asarray(g), np.asarray(w), rtol=2e-3, atol=2e-3,
            err_msg=f"d{name} (causal={causal})",
        )


@pytest.mark.parametrize("causal", [False, True])
def test_pallas_tiled_bwd_matches_recompute(causal):
    """The two-pass tiled backward (long-sequence path: O(S) memory, dK/dV
    pass then dQ pass) must equal the XLA recompute backward across tile
    boundaries; interpret mode on CPU."""
    from cs336_systems_tpu.ops.flash_attention import (
        _flash_bwd_pallas_tiled,
        _flash_bwd_recompute,
    )

    q, k, v = _make_qkv(jax.random.PRNGKey(8), 2, 512, 512, 64)
    o_ref, lse = _oracle(q, k, v, causal)
    do = jax.random.normal(jax.random.PRNGKey(9), o_ref.shape, o_ref.dtype)
    dlse = jnp.zeros(lse.shape, jnp.float32)
    want = _flash_bwd_recompute(q, k, v, o_ref, lse, do, dlse, causal)
    got = _flash_bwd_pallas_tiled(
        q, k, v, o_ref, lse, do, dlse, causal, q_tile=128, k_tile=128,
        interpret=True
    )
    for g, w, name in zip(got, want, "qkv"):
        np.testing.assert_allclose(
            np.asarray(g), np.asarray(w), rtol=2e-3, atol=2e-3,
            err_msg=f"d{name} (causal={causal})",
        )


@pytest.mark.parametrize("impl", IMPLS)
def test_flash_bf16(impl):
    q, k, v = _make_qkv(jax.random.PRNGKey(4), 2, 128, 128, 64, jnp.bfloat16)
    o_ref, _ = _oracle(q, k, v, True)
    o = flash_attention(q, k, v, causal=True, impl=impl)
    assert o.dtype == jnp.bfloat16
    np.testing.assert_allclose(
        np.asarray(o, np.float32), np.asarray(o_ref, np.float32), rtol=5e-2, atol=5e-2
    )


@pytest.mark.parametrize("impl", IMPLS)
def test_flash_2d_inputs(impl):
    """2-D inputs get a singleton batch (reference host side unsqueeze)."""
    q, k, v = _make_qkv(jax.random.PRNGKey(5), 1, 64, 64, 16)
    o3 = flash_attention(q, k, v, causal=True, impl=impl, q_tile=64, k_tile=64)
    o2 = flash_attention(q[0], k[0], v[0], causal=True, impl=impl, q_tile=64, k_tile=64)
    assert o2.shape == (64, 16)
    np.testing.assert_allclose(np.asarray(o2), np.asarray(o3[0]), rtol=1e-6, atol=1e-6)


def test_flash_in_transformer_forward():
    """attn_impl='flash_ref' end-to-end through the LM matches the xla path."""
    from cs336_systems_tpu.models.transformer import (
        TransformerConfig,
        init_transformer_lm,
        transformer_lm,
    )

    kw = dict(vocab_size=64, context_length=64, d_model=64, num_layers=2,
              num_heads=4, d_ff=128)
    cfg_x = TransformerConfig(**kw, attn_impl="xla")
    cfg_f = TransformerConfig(**kw, attn_impl="flash_ref")
    params = init_transformer_lm(jax.random.PRNGKey(0), cfg_x)
    x = jax.random.randint(jax.random.PRNGKey(1), (2, 64), 0, 64)
    lx = transformer_lm(params, x, cfg_x)
    lf = transformer_lm(params, x, cfg_f)
    np.testing.assert_allclose(np.asarray(lx), np.asarray(lf), rtol=1e-4, atol=1e-4)


@pytest.mark.parametrize("impl", IMPLS)
def test_flash_with_lse_4d_and_grad(impl):
    """with_lse accepts [..., S, D] and differentiates through the same
    recompute backward as flash_attention."""
    q = jax.random.normal(jax.random.PRNGKey(6), (2, 3, 64, 16))
    o, lse = flash_attention_with_lse(q, q, q, causal=True, impl=impl, q_tile=64, k_tile=64)
    assert o.shape == q.shape and lse.shape == (2, 3, 64)

    g = jax.grad(
        lambda q: jnp.sum(
            flash_attention_with_lse(q, q, q, causal=True, impl=impl, q_tile=64, k_tile=64)[0] ** 2
        )
    )(q)
    g_ref = jax.grad(
        lambda q: jnp.sum(
            flash_attention(q, q, q, causal=True, impl=impl, q_tile=64, k_tile=64) ** 2
        )
    )(q)
    np.testing.assert_allclose(np.asarray(g), np.asarray(g_ref), rtol=1e-5, atol=1e-6)


@pytest.mark.parametrize("impl", IMPLS)
def test_lse_cotangent_flows_through_backward(impl):
    """Gradients of a function that CONSUMES the logsumexp (ring attention's
    online-softmax merge does) must match autodiff through the oracle — the
    lse cotangent folds into the backward's delta term."""
    q, k, v = _make_qkv(jax.random.PRNGKey(10), 2, 128, 128, 32)

    def loss_flash(q, k, v):
        o, lse = flash_attention_with_lse(q, k, v, causal=True, impl=impl)
        return jnp.sum(o ** 2) + jnp.sum(jnp.sin(lse))

    def loss_oracle(q, k, v):
        o, lse = _oracle(q, k, v, True)
        return jnp.sum(o ** 2) + jnp.sum(jnp.sin(lse))

    g = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_oracle, argnums=(0, 1, 2))(q, k, v)
    for got, want, name in zip(g, g_ref, "qkv"):
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), rtol=1e-2, atol=1e-2,
            err_msg=f"d{name} mismatch ({impl})",
        )


@pytest.mark.parametrize("impl", IMPLS)
@pytest.mark.parametrize("offset", [64, 128, 100])
def test_q_pos_offset_matches_shifted_oracle(impl, offset):
    """q_pos_offset shifts the queries' global positions right of the keys
    (a ring hop attending an earlier K/V shard): fwd and bwd must equal the
    oracle under the shifted causal mask. offset=100 is deliberately not
    tile-aligned (markers the mask-only path); 64/128 hit tile-aligned
    mappings."""
    b, s, d = 2, 128, 32
    q, k, v = _make_qkv(jax.random.PRNGKey(11), b, s, s, d)
    qi = offset + jnp.arange(s)[:, None]
    kj = jnp.arange(s)[None, :]
    mask = qi >= kj

    def loss_flash(q, k, v):
        o, lse = flash_attention_with_lse(
            q, k, v, causal=True, impl=impl, q_tile=64, k_tile=64,
            q_pos_offset=offset,
        )
        return jnp.sum(o ** 2) + jnp.sum(lse), (o, lse)

    def loss_oracle(q, k, v):
        o, lse = attention_with_lse(q, k, v, mask)
        return jnp.sum(o ** 2) + jnp.sum(lse), (o, lse)

    (l, (o, lse)), g = jax.value_and_grad(
        loss_flash, argnums=(0, 1, 2), has_aux=True)(q, k, v)
    (l_ref, (o_ref, lse_ref)), g_ref = jax.value_and_grad(
        loss_oracle, argnums=(0, 1, 2), has_aux=True)(q, k, v)
    np.testing.assert_allclose(np.asarray(o), np.asarray(o_ref), rtol=1e-2, atol=1e-2)
    np.testing.assert_allclose(np.asarray(lse), np.asarray(lse_ref), rtol=1e-2, atol=1e-2)
    for got, want, name in zip(g, g_ref, "qkv"):
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), rtol=1e-2, atol=1e-2,
            err_msg=f"d{name} mismatch ({impl}, offset={offset})",
        )


@pytest.mark.parametrize("impl", ["reference", "pallas"])
def test_q_pos_offset_with_window(impl):
    """Offset + sliding window: the banded grids follow the shifted
    diagonal (tile-aligned offset) and masking stays exact. offset=64 keeps
    every query row inside the window of some key (an all-masked row is
    well-defined for the flash kernels — zero output — but the dense
    oracle's -1e30 fill degenerates to uniform softmax there, so rows must
    stay populated for an oracle comparison)."""
    b, s, d, window, offset = 2, 256, 16, 100, 64
    q, k, v = _make_qkv(jax.random.PRNGKey(12), b, s, s, d)
    qi = offset + jnp.arange(s)[:, None]
    kj = jnp.arange(s)[None, :]
    mask = (qi >= kj) & (qi - kj < window)

    got = jax.grad(
        lambda q, k, v: jnp.sum(flash_attention(
            q, k, v, causal=True, impl=impl, window=window,
            q_tile=64, k_tile=64, q_pos_offset=offset) ** 2),
        argnums=(0, 1, 2))(q, k, v)
    want = jax.grad(
        lambda q, k, v: jnp.sum(attention_with_lse(q, k, v, mask)[0] ** 2),
        argnums=(0, 1, 2))(q, k, v)
    for g, w, nm in zip(got, want, "qkv"):
        np.testing.assert_allclose(
            np.asarray(g), np.asarray(w), rtol=2e-2, atol=2e-2,
            err_msg=f"d{nm} mismatch ({impl})",
        )

    # fully out-of-window hop: offset so large every key is stale. The
    # contract for all-masked rows is lse ≈ -inf (so an online-softmax
    # merge weights the block by exp(lse - anything) = 0); the o rows are
    # unspecified (the banded grid skips them to zero, mask-only paths
    # compute a degenerate mean that the zero weight discards).
    _, far_lse = flash_attention_with_lse(
        q, k, v, causal=True, impl=impl, window=64,
        q_tile=64, k_tile=64, q_pos_offset=4096,
    )
    assert np.all(np.asarray(far_lse) < -1e29)


# ---------------------------------------------------------------------------
# Sliding-window (banded) attention


def _window_oracle(q, k, v, window):
    """Plain softmax attention under the causal sliding-window mask."""
    from cs336_systems_tpu.ops.attention import attention_with_lse, banded_causal_mask

    return attention_with_lse(
        q, k, v, banded_causal_mask(q.shape[-2], k.shape[-2], window)
    )[0]


@pytest.mark.parametrize("impl", ["reference", "pallas", "xla"])
@pytest.mark.parametrize("window", [1, 100, 256, 10_000])
def test_windowed_forward_matches_oracle(impl, window):
    from cs336_systems_tpu.ops.flash_attention import flash_attention

    b, s, d = 3, 256, 32
    ks = jax.random.split(jax.random.PRNGKey(0), 3)
    q, k, v = (jax.random.normal(kk, (b, s, d), jnp.float32) for kk in ks)
    got = flash_attention(q, k, v, causal=True, impl=impl, window=window,
                          q_tile=128, k_tile=128)
    want = _window_oracle(q, k, v, window)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-2, atol=1e-2)


@pytest.mark.parametrize("impl", ["reference", "pallas"])
def test_windowed_backward_matches_oracle(impl):
    """Gradients through the windowed kernels vs autograd through the
    masked-oracle — exercises the banded tiled backward in interpret mode
    (s=512 > fused-bwd fp32 bound with 128-tiles => tiled path)."""
    from cs336_systems_tpu.ops.flash_attention import flash_attention

    b, s, d, window = 2, 512, 32, 100
    ks = jax.random.split(jax.random.PRNGKey(1), 4)
    q, k, v, do = (jax.random.normal(kk, (b, s, d), jnp.float32) * 0.3
                   for kk in ks)

    def loss(fn):
        return lambda q, k, v: (fn(q, k, v) * do).sum()

    got = jax.grad(
        loss(lambda q, k, v: flash_attention(
            q, k, v, causal=True, impl=impl, window=window,
            q_tile=128, k_tile=128)),
        argnums=(0, 1, 2),
    )(q, k, v)
    want = jax.grad(
        loss(lambda q, k, v: _window_oracle(q, k, v, window)),
        argnums=(0, 1, 2),
    )(q, k, v)
    for g, w, nm in zip(got, want, "qkv"):
        np.testing.assert_allclose(
            np.asarray(g), np.asarray(w), rtol=2e-2, atol=2e-2,
            err_msg=f"d{nm} mismatch ({impl})",
        )


def test_window_equals_causal_when_covering():
    """window >= S must reproduce plain causal attention exactly."""
    from cs336_systems_tpu.ops.flash_attention import flash_attention

    b, s, d = 2, 128, 16
    ks = jax.random.split(jax.random.PRNGKey(2), 3)
    q, k, v = (jax.random.normal(kk, (b, s, d), jnp.float32) for kk in ks)
    plain = flash_attention(q, k, v, causal=True, impl="reference")
    wide = flash_attention(q, k, v, causal=True, impl="reference", window=s)
    np.testing.assert_allclose(np.asarray(wide), np.asarray(plain), rtol=1e-6)


def test_window_requires_causal():
    from cs336_systems_tpu.ops.flash_attention import flash_attention

    q = jnp.ones((1, 8, 8))
    with pytest.raises(ValueError, match="causal"):
        flash_attention(q, q, q, causal=False, window=4)


# ---------------------------------------------------------------------------
# Fused RoPE (rotation inside the kernels — rope_cos/rope_sin operands)


def _rope_oracle_attn(q, k, v, cos, sin, causal, window=None):
    """Rotate-outside oracle: apply_rope in XLA, then plain attention."""
    from cs336_systems_tpu.models.layers import apply_rope
    from cs336_systems_tpu.ops.attention import banded_causal_mask

    pos = jnp.arange(q.shape[-2])
    qr = apply_rope(q, cos, sin, pos)
    kr = apply_rope(k, cos, sin, pos)
    if window is not None:
        mask = banded_causal_mask(q.shape[-2], k.shape[-2], window)
    elif causal:
        mask = causal_mask(q.shape[-2], k.shape[-2])
    else:
        mask = None
    return attention_with_lse(qr, kr, v, mask)


@pytest.mark.parametrize("impl", IMPLS)
@pytest.mark.parametrize("causal", [False, True])
def test_fused_rope_forward_matches_rotate_outside(impl, causal):
    from cs336_systems_tpu.models.layers import rope_cache

    q, k, v = _make_qkv(jax.random.PRNGKey(20), 3, 256, 256, 64)
    cos, sin = rope_cache(256, 64)
    o_ref, lse_ref = _rope_oracle_attn(q, k, v, cos, sin, causal)
    o, lse = flash_attention_with_lse(
        q, k, v, causal=causal, impl=impl, q_tile=128, k_tile=128,
        rope_cos=cos, rope_sin=sin,
    )
    np.testing.assert_allclose(np.asarray(o), np.asarray(o_ref), rtol=1e-2, atol=1e-2)
    np.testing.assert_allclose(np.asarray(lse), np.asarray(lse_ref), rtol=1e-2, atol=1e-2)


@pytest.mark.parametrize("impl", IMPLS)
def test_fused_rope_grads_are_wrt_unrotated_inputs(impl):
    """Gradients through the fused-rope call must equal gradients through
    the rotate-outside formulation — i.e. the kernel's inverse rotation of
    the cotangents is the exact VJP of the in-kernel rotation."""
    from cs336_systems_tpu.models.layers import rope_cache

    q, k, v = _make_qkv(jax.random.PRNGKey(21), 2, 128, 128, 64)
    cos, sin = rope_cache(128, 64)

    def loss_fused(q, k, v):
        return jnp.sum(
            flash_attention(q, k, v, causal=True, impl=impl,
                            rope_cos=cos, rope_sin=sin) ** 2
        )

    def loss_oracle(q, k, v):
        return jnp.sum(_rope_oracle_attn(q, k, v, cos, sin, True)[0] ** 2)

    g = jax.grad(loss_fused, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_oracle, argnums=(0, 1, 2))(q, k, v)
    for got, want, name in zip(g, g_ref, "qkv"):
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), rtol=1e-2, atol=1e-2,
            err_msg=f"d{name} mismatch",
        )


@pytest.mark.parametrize("kernel", ["fused", "tiled"])
@pytest.mark.parametrize("causal", [False, True])
def test_fused_rope_pallas_bwd_matches_recompute(kernel, causal):
    """Both Pallas backwards (whole-seq fused and two-pass tiled) with rope
    operands must equal the XLA recompute backward with the same rope
    tables; interpret mode on CPU."""
    from cs336_systems_tpu.models.layers import rope_cache
    from cs336_systems_tpu.ops.flash_attention import (
        _expand_rope_tables,
        _flash_bwd_pallas,
        _flash_bwd_pallas_tiled,
        _flash_bwd_recompute,
        _flash_fwd_reference,
    )

    q, k, v = _make_qkv(jax.random.PRNGKey(22), 2, 256, 256, 64)
    cos, sin = rope_cache(256, 64)
    # internal 4-tuple convention (_folded_call): q tables then k tables
    rope = _expand_rope_tables(cos, sin) * 2
    o, lse = _flash_fwd_reference(q, k, v, causal, 128, 128, rope=rope)
    do = jax.random.normal(jax.random.PRNGKey(23), o.shape, o.dtype)
    want = _flash_bwd_recompute(q, k, v, o, lse, do, None, causal, rope=rope)
    if kernel == "fused":
        got = _flash_bwd_pallas(q, k, v, o, lse, do, None, causal,
                                interpret=True, rope=rope)
    else:
        got = _flash_bwd_pallas_tiled(q, k, v, o, lse, do, None, causal,
                                      q_tile=128, k_tile=128,
                                      interpret=True, rope=rope)
    for g, w, name in zip(got, want, "qkv"):
        np.testing.assert_allclose(
            np.asarray(g), np.asarray(w), rtol=2e-3, atol=2e-3,
            err_msg=f"d{name} (causal={causal})",
        )


def test_fused_rope_windowed_banded(impl="pallas"):
    """Fused rope composes with the banded sliding-window grids (clamped
    table fetches must be masked out exactly like the K/V fetches)."""
    from cs336_systems_tpu.models.layers import rope_cache

    q, k, v = _make_qkv(jax.random.PRNGKey(24), 2, 512, 512, 64)
    cos, sin = rope_cache(512, 64)
    window = 100
    o_ref, lse_ref = _rope_oracle_attn(q, k, v, cos, sin, True, window=window)
    o, lse = flash_attention_with_lse(
        q, k, v, causal=True, impl=impl, q_tile=64, k_tile=64,
        window=window, rope_cos=cos, rope_sin=sin,
    )
    np.testing.assert_allclose(np.asarray(o), np.asarray(o_ref), rtol=1e-2, atol=1e-2)
    np.testing.assert_allclose(np.asarray(lse), np.asarray(lse_ref), rtol=1e-2, atol=1e-2)


def test_fused_rope_model_equivalence():
    """rope_fused / qkv_fused are pure layout optimizations: the LM forward
    must be bitwise-close to the unfused config with identical params."""
    import dataclasses

    from cs336_systems_tpu.models.transformer import (
        config_for_size,
        init_transformer_lm,
        transformer_lm,
    )

    cfg0 = config_for_size(
        "small", context_length=128, num_layers=2, attn_impl="flash",
        rope_fused=False, qkv_fused=False, scan_layers=False,
    )
    params = init_transformer_lm(jax.random.PRNGKey(0), cfg0)
    ids = jax.random.randint(jax.random.PRNGKey(1), (2, 128), 0, cfg0.vocab_size)
    base = transformer_lm(params, ids, cfg0)
    for rf, qf in [(True, False), (False, True), (True, True)]:
        cfg = dataclasses.replace(cfg0, rope_fused=rf, qkv_fused=qf)
        out = transformer_lm(params, ids, cfg)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(base), rtol=1e-4, atol=1e-4,
            err_msg=f"rope_fused={rf} qkv_fused={qf}",
        )


def test_single_tile_all_masked_rows_emit_lse_marker():
    """The single-k-tile fast path must still write O/lse when masking
    leaves rows (or the whole tile) without valid keys — the huge-negative
    lse is the documented discard marker (regression: an early version
    skipped the body under `needed` and left the outputs unwritten)."""
    q, k, v = _make_qkv(jax.random.PRNGKey(30), 2, 128, 128, 64)
    o, lse = flash_attention_with_lse(
        q, k, v, causal=True, impl="pallas", q_tile=128, k_tile=128,
        window=16, q_pos_offset=1024,  # every query far past every key
    )
    assert bool(jnp.all(lse < -1e20))
    assert bool(jnp.all(jnp.isfinite(o)))


def test_pick_group_caps_fp32_narrow_head():
    """Pin the on-chip-bisected Mosaic compiler boundary: fp32 with
    d_head < 32 crashes the TPU compiler at forward group G=4 (g<=2,
    bf16 g=4, and fp32 d>=32 g=4 all compile) — _pick_group must cap
    that case. CI cannot reproduce the crash (it is a TPU-compiler
    subprocess failure), so the picker's clamp is the tested contract."""
    from cs336_systems_tpu.ops.flash_attention import _pick_group

    # small tiles so the VMEM budget is not the binding constraint
    assert _pick_group(8, 128, 128, 16, 4) <= 2   # fp32, d=16: capped
    assert _pick_group(8, 128, 128, 16, 2) == 4   # bf16, d=16: uncapped
    assert _pick_group(8, 128, 128, 64, 4) == 4   # fp32, d=64: uncapped


@pytest.mark.parametrize("impl", ["reference", "pallas"])
def test_fused_rope_distinct_k_tables_at_ring_offset(impl):
    """A ring hop attends a K block sitting q_pos_offset positions behind
    the local queries: fused rope must rotate q rows at their global
    positions and k rows at the BLOCK's positions (distinct tables). Oracle:
    rotate in XLA (models.layers.apply_rope) then flash without rope. Both
    the forward pair and the (unrotated-input) gradients must match."""
    from cs336_systems_tpu.models.layers import apply_rope, rope_cache
    from cs336_systems_tpu.ops.flash_attention import flash_attention_with_lse

    s, d, q_off = 128, 64, 128
    q, k, v = _make_qkv(jax.random.PRNGKey(31), 3, s, s, d)
    cos, sin = rope_cache(512, d)
    q_pos = jnp.arange(q_off, q_off + s)
    k_pos = jnp.arange(s)

    def fused(q, k, v):
        return flash_attention_with_lse(
            q, k, v, causal=True, impl=impl, q_tile=128, k_tile=128,
            q_pos_offset=q_off,
            rope_cos=jnp.take(cos, q_pos, 0), rope_sin=jnp.take(sin, q_pos, 0),
            rope_cos_k=jnp.take(cos, k_pos, 0), rope_sin_k=jnp.take(sin, k_pos, 0),
        )

    def oracle(q, k, v):
        qr = apply_rope(q, cos, sin, q_pos)
        kr = apply_rope(k, cos, sin, k_pos)
        return flash_attention_with_lse(
            qr, kr, v, causal=True, impl="reference", q_tile=128, k_tile=128,
            q_pos_offset=q_off,
        )

    o_got, lse_got = fused(q, k, v)
    o_want, lse_want = oracle(q, k, v)
    np.testing.assert_allclose(np.asarray(o_got), np.asarray(o_want),
                               rtol=2e-3, atol=2e-3)
    np.testing.assert_allclose(np.asarray(lse_got), np.asarray(lse_want),
                               rtol=1e-4, atol=1e-4)

    loss = lambda f: lambda q, k, v: jnp.sum(jnp.tanh(f(q, k, v)[0]))
    g_got = jax.grad(loss(fused), argnums=(0, 1, 2))(q, k, v)
    g_want = jax.grad(loss(oracle), argnums=(0, 1, 2))(q, k, v)
    for g, w, name in zip(g_got, g_want, "qkv"):
        np.testing.assert_allclose(np.asarray(g), np.asarray(w),
                                   rtol=5e-3, atol=5e-3,
                                   err_msg=f"d{name} ({impl})")


def test_fused_rope_offset_without_k_tables_raises():
    from cs336_systems_tpu.models.layers import rope_cache
    from cs336_systems_tpu.ops.flash_attention import flash_attention

    q, k, v = _make_qkv(jax.random.PRNGKey(32), 2, 64, 64, 32)
    cos, sin = rope_cache(256, 32)
    with pytest.raises(ValueError, match="explicit k "):
        flash_attention(q, k, v, causal=True, q_pos_offset=64,
                        rope_cos=cos, rope_sin=sin)


def test_fused_rope_short_explicit_tables_raise():
    """Explicit k-table path must reject tables shorter than the row
    counts — the Pallas launch would silently ZERO-pad them (rotating
    tail rows by cos=0/sin=0)."""
    from cs336_systems_tpu.models.layers import rope_cache
    from cs336_systems_tpu.ops.flash_attention import flash_attention

    q, k, v = _make_qkv(jax.random.PRNGKey(33), 2, 128, 128, 32)
    cos, sin = rope_cache(256, 32)
    with pytest.raises(ValueError, match="too short"):
        flash_attention(q, k, v, causal=True, q_pos_offset=128,
                        rope_cos=cos[:100], rope_sin=sin[:100],
                        rope_cos_k=cos[:128], rope_sin_k=sin[:128])


def test_pick_tile_prefers_divisors():
    """The 1024 default must not drop 512-divisible lengths (S=1536,
    2560, ...) out of the tiled backward: _pick_tile prefers the largest
    power-of-two tile that DIVIDES the length (>=128) and only falls back
    to the padding clamp when none exists."""
    from cs336_systems_tpu.ops.flash_attention import _pick_tile

    assert _pick_tile(1536, 1024) == 512
    assert _pick_tile(2560, 1024) == 512
    assert _pick_tile(65536, 1024) == 1024
    assert _pick_tile(2048, 1024) == 1024
    assert _pick_tile(512, 1024) == 512   # headline shape: unchanged
    assert _pick_tile(96, 1024) == 64     # padding fallback unchanged
    assert _pick_tile(64, 512) == 64
