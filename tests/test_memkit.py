"""memkit tests: buffer-liveness reconstruction against synthetic HLO
fixtures with HAND-COMPUTED peaks, the aliasing rules that carry the
model's accuracy (tuple-element-precise while carries, in-place
dynamic-update-slice fusions, input_output_alias donation), buffer
classification, the diff gate, OOM forensics, and CPU end-to-end smokes
of ``mem_cli`` (exit codes included).

Same oracle discipline as test_tracekit.py: every modeling rule is
pinned by a fixture whose correct answer is computed by hand in a
comment, then the full pipeline runs end to end on the hermetic CPU mesh
and must land within the acceptance band of XLA's own
``memory_analysis()`` totals.
"""

import json

import pytest

from cs336_systems_tpu.analysis import memkit
from cs336_systems_tpu.analysis.memkit import (
    BufferInfo,
    analyze_hlo,
    check_budget,
    classify_buffer,
    diff_memprofiles,
    explain_oom,
    parse_io_aliases,
    parse_oom_demand,
    profile_hlo,
    shape_bytes,
)


# --- shape parsing ----------------------------------------------------------


@pytest.mark.parametrize("type_str,expected", [
    ("f32[256]{0}", 1024),
    ("bf16[8,128]{1,0}", 2048),
    ("s32[]", 4),
    ("pred[]", 1),
    ("(f32[1024]{0}, f32[16]{0}, s32[])", 4096 + 64 + 4),
    ("token[]", 0),  # unknown leaf types count zero, not crash
])
def test_shape_bytes(type_str, expected):
    assert shape_bytes(type_str) == expected


# --- fixture A: linear chain ------------------------------------------------
# Hand-computed walk (1 KiB per f32[256] buffer):
#   up-front: params p0+p1 = 2048, root output sub.3 reserved = 1024
#   add.1 (1 KiB, dies before the output is defined) PARKS in the output
#   slot — XLA places short-lived temps inside not-yet-defined output
#   allocations — so the peak is NOT 3072+1024 at add.1;
#   mul.2 (1 KiB) cannot park (slot busy until add.1's last use) -> +1024
#   peak = 2048 + 1024 + 1024 = 4096, at mul.2

_HLO_CHAIN = """\
HloModule jit_f, is_scheduled=true, entry_computation_layout={(f32[256]{0}, f32[256]{0})->f32[256]{0}}

ENTRY %main (p0: f32[256], p1: f32[256]) -> f32[256] {
  %p0 = f32[256]{0} parameter(0)
  %p1 = f32[256]{0} parameter(1)
  %add.1 = f32[256]{0} add(%p0, %p1), metadata={op_name="jit(f)/fwd/ffn/up_proj"}
  %mul.2 = f32[256]{0} multiply(%add.1, %add.1), metadata={op_name="jit(f)/fwd/ffn/gate"}
  ROOT %sub.3 = f32[256]{0} subtract(%mul.2, %p1), metadata={op_name="jit(f)/transpose(jvp(f))/ffn/down"}
}
"""


def test_chain_peak_with_output_slot_parking():
    a = analyze_hlo(_HLO_CHAIN)
    assert a.peak_bytes == 4096
    assert a.peak_at[0] == "mul.2"


def test_chain_phase_highwater():
    a = analyze_hlo(_HLO_CHAIN)
    assert a.phase_peak_bytes["fwd-ffn"] == 4096
    assert a.phase_peak_bytes["bwd"] == 4096  # transpose( scope at sub.3
    # before any temp exists only params+reserved outputs are live
    assert a.phase_peak_bytes["other"] == 3072


def test_chain_profile_composition_and_classes():
    p = profile_hlo(_HLO_CHAIN, family="chain",
                    arg_classes=["params", "optimizer-state"])
    assert p["schema"] == "memprofile/v1"
    assert p["peak_bytes"] == 4096
    # at the peak: p0 (params), p1 (optimizer-state via param index),
    # the reserved output, and mul.2 — defined fwd-ffn, freed by the
    # backward consumer => an activation stash
    assert p["composition_bytes"] == {
        "params": 1024, "optimizer-state": 1024,
        "output": 1024, "activation-stash": 1024,
    }
    assert p["peak_at"]["phase"] == "fwd-ffn"


# --- fixture B: while carry, tuple-element precision ------------------------
# carry = (f32[1024] from %dbl, f32[16] from %p1, s32[]); after the while
# only element 1 is read (%gte.small). Element-precise aliasing frees
# %dbl's 4096 B at the while, so the f32[4096] temp %big (16384 B) peaks
# WITHOUT %dbl live:
#   up-front: params 4096+64, const 4, output reserve 64 -> 4228
#   at %big: 4228 + 16384 = 20612  <- the peak
#   at %while: 4228 + 4096 (dbl) + body transient 72 = 8396
# A whole-carry alias union (the bug this pins) would keep %dbl live
# through %out and report 24708.

_HLO_WHILE = """\
HloModule jit_g, is_scheduled=true, entry_computation_layout={(f32[1024]{0}, f32[16]{0})->f32[16]{0}}

%cond (c: (f32[1024], f32[16], s32[])) -> pred[] {
  %c = (f32[1024]{0}, f32[16]{0}, s32[]) parameter(0)
  %gte.c = s32[] get-tuple-element(%c), index=2
  %k = s32[] constant(3)
  ROOT %lt = pred[] compare(%gte.c, %k), direction=LT
}

%body (b: (f32[1024], f32[16], s32[])) -> (f32[1024], f32[16], s32[]) {
  %b = (f32[1024]{0}, f32[16]{0}, s32[]) parameter(0)
  %gte.0 = f32[1024]{0} get-tuple-element(%b), index=0
  %gte.1 = f32[16]{0} get-tuple-element(%b), index=1
  %gte.2 = s32[] get-tuple-element(%b), index=2
  %neg.b = f32[16]{0} negate(%gte.1)
  %one = s32[] constant(1)
  %inc = s32[] add(%gte.2, %one)
  ROOT %tup = (f32[1024]{0}, f32[16]{0}, s32[]) tuple(%gte.0, %neg.b, %inc)
}

ENTRY %main (p0: f32[1024], p1: f32[16]) -> f32[16] {
  %p0 = f32[1024]{0} parameter(0)
  %p1 = f32[16]{0} parameter(1)
  %zero = s32[] constant(0)
  %dbl = f32[1024]{0} multiply(%p0, %p0)
  %init = (f32[1024]{0}, f32[16]{0}, s32[]) tuple(%dbl, %p1, %zero)
  %w = (f32[1024]{0}, f32[16]{0}, s32[]) while(%init), condition=%cond, body=%body
  %gte.small = f32[16]{0} get-tuple-element(%w), index=1
  %big = f32[4096]{0} exponential(%p0)
  ROOT %out = f32[16]{0} add(%gte.small, %p1)
}
"""


def test_while_carry_element_precise_liveness():
    a = analyze_hlo(_HLO_WHILE)
    assert a.peak_bytes == 20612
    assert a.peak_at[0] == "big"


# --- fixture C: fusion with dynamic-update-slice root is in-place -----------
# The lowering of every scan stash / KV-cache write. %upd must alias
# %buf's buffer (the DUS target), not allocate 4 KiB of its own:
#   params 4096+64, const 4, output reserve (%done) 4096, %buf 4096
#   peak = 12356; a fresh allocation for the fusion would say 16452.

_HLO_DUS = """\
HloModule jit_h, is_scheduled=true, entry_computation_layout={(f32[64,16]{1,0}, f32[1,16]{1,0})->f32[64,16]{1,0}}

%fused_dus (fp0: f32[64,16], fp1: f32[1,16], fp2: s32[], fp3: s32[]) -> f32[64,16] {
  %fp0 = f32[64,16]{1,0} parameter(0)
  %fp1 = f32[1,16]{1,0} parameter(1)
  %fp2 = s32[] parameter(2)
  %fp3 = s32[] parameter(3)
  ROOT %dus.f = f32[64,16]{1,0} dynamic-update-slice(%fp0, %fp1, %fp2, %fp3)
}

ENTRY %main (p0: f32[64,16], p1: f32[1,16]) -> f32[64,16] {
  %p0 = f32[64,16]{1,0} parameter(0)
  %p1 = f32[1,16]{1,0} parameter(1)
  %i = s32[] constant(0)
  %buf = f32[64,16]{1,0} copy(%p0), metadata={op_name="jit(h)/fwd/attn/kv_update/stash"}
  %upd = f32[64,16]{1,0} fusion(%buf, %p1, %i, %i), kind=kLoop, calls=%fused_dus
  ROOT %done = f32[64,16]{1,0} copy(%upd)
}
"""


def test_dus_fusion_updates_in_place():
    a = analyze_hlo(_HLO_DUS)
    assert a.peak_bytes == 12356


# --- fixture D: donation (input_output_alias) -------------------------------
# Outputs {0} and {1} write into the donated parameter buffers; only
# output {2} gets its own allocation: peak = 3*1024 params + 1024 = 4096
# (an alias-blind walk reserves all three outputs and says 6144).

_HLO_DONATED = """\
HloModule jit_d, is_scheduled=true, input_output_alias={ {0}: (0, {}, may-alias), {1}: (1, {}, may-alias) }, entry_computation_layout={(f32[256]{0}, f32[256]{0}, f32[256]{0})->(f32[256]{0}, f32[256]{0}, f32[256]{0})}

ENTRY %main (p0: f32[256], p1: f32[256], p2: f32[256]) -> (f32[256], f32[256], f32[256]) {
  %p0 = f32[256]{0} parameter(0)
  %p1 = f32[256]{0} parameter(1)
  %p2 = f32[256]{0} parameter(2)
  %new0 = f32[256]{0} add(%p0, %p2)
  %new1 = f32[256]{0} multiply(%p1, %p2)
  %new2 = f32[256]{0} subtract(%p2, %p0)
  ROOT %tup = (f32[256]{0}, f32[256]{0}, f32[256]{0}) tuple(%new0, %new1, %new2)
}
"""


def test_io_alias_parse_handles_nested_braces():
    # the map nests {} inside {} — a naive regex sees only the first pair
    assert parse_io_aliases(_HLO_DONATED) == {0: 0, 1: 1}
    assert parse_io_aliases(_HLO_CHAIN) == {}


def test_donated_outputs_reuse_param_buffers():
    assert analyze_hlo(_HLO_DONATED).peak_bytes == 4096


# --- classification ---------------------------------------------------------


def _buf(**kw):
    d = dict(name="x", bytes=64, opcode="fusion", scope="",
             def_phase="other", free_phase="other", param_idx=None)
    d.update(kw)
    return BufferInfo(**d)


@pytest.mark.parametrize("info,classes,expected", [
    (_buf(opcode="parameter", param_idx=0), ["params", "batch"], "params"),
    (_buf(opcode="parameter", param_idx=1), ["params", "batch"], "batch"),
    (_buf(opcode="parameter", param_idx=9), ["params"], "params"),
    (_buf(opcode="constant"), [], "constant"),
    (_buf(opcode="all-reduce"), [], "collective"),
    (_buf(opcode="all-gather-start"), [], "collective"),
    (_buf(scope="jit(g)/decode/kv_update/dus"), [], "kv-cache"),
    (_buf(def_phase="fwd-ffn", free_phase="bwd"), [], "activation-stash"),
    (_buf(def_phase="fwd-ffn", free_phase="bwd",
          scope="jit(s)/ffn/gmm_w13/pallas_call"), [], "gmm-residual"),
    (_buf(def_phase="fwd-attn", free_phase="fwd-attn"), [], "temp"),
    (_buf(def_phase="bwd", free_phase="bwd"), [], "temp"),
])
def test_classify_buffer(info, classes, expected):
    assert classify_buffer(info, classes) == expected


# --- diff gate --------------------------------------------------------------


def _profile(peak=10 << 20, fam="train_single", **over):
    p = {
        "schema": memkit.SCHEMA, "family": fam, "peak_bytes": peak,
        "phase_peak_bytes": {"fwd-attn": peak, "bwd": peak // 2},
        "composition_bytes": {"params": peak // 4, "temp": peak // 2},
    }
    p.update(over)
    return p


def test_diff_identical_flags_nothing():
    d = diff_memprofiles(_profile(), _profile())
    assert d["n_flagged"] == 0


def test_diff_flags_real_regression():
    b = _profile(peak=20 << 20)
    d = diff_memprofiles(_profile(), b)
    flagged = [r for r in d["rows"] if r["flagged"]]
    assert any(r["key"] == "peak_bytes" for r in flagged)


def test_diff_dual_gate_absolute_floor():
    # +50% on a small phase: over the pct gate, under the 1 MiB floor
    a = _profile()
    b = _profile()
    b["phase_peak_bytes"] = dict(a["phase_peak_bytes"], routing=512 << 10)
    a["phase_peak_bytes"] = dict(a["phase_peak_bytes"], routing=256 << 10)
    assert diff_memprofiles(a, b)["n_flagged"] == 0
    # same relative jump above the floor IS flagged
    b["phase_peak_bytes"]["routing"] = 8 << 20
    a["phase_peak_bytes"]["routing"] = 4 << 20
    assert diff_memprofiles(a, b)["n_flagged"] == 1


def test_diff_family_mismatch_raises():
    with pytest.raises(ValueError, match="different families"):
        diff_memprofiles(_profile(), _profile(fam="serve_dp"))


# --- budgets ----------------------------------------------------------------


def test_check_budget():
    assert check_budget(_profile(peak=10 << 20), 48 << 20) == []
    assert len(check_budget(_profile(peak=10 << 20), 1 << 20)) == 1


def test_registry_budgets_name_real_families():
    from cs336_systems_tpu.analysis import registry

    assert set(registry.HBM_BUDGET_BYTES) <= set(memkit.family_names())


# --- OOM forensics ----------------------------------------------------------


def test_parse_oom_demand_total_usage_shape():
    msg = ("RESOURCE_EXHAUSTED: Ran out of memory in memory space hbm. "
           "Total hbm usage >= 17.48G:\n  reserved 1.00G\n"
           "program 16.48G\nlimit: 15.70G")
    peak, limit = parse_oom_demand(msg)
    assert peak == int(17.48 * 2**30)
    assert limit == int(15.70 * 2**30)


def test_parse_oom_demand_used_of_shape():
    peak, limit = parse_oom_demand("Used 14.2G of 15.7G hbm")
    assert peak == int(14.2 * 2**30)
    assert limit == int(15.7 * 2**30)


def test_parse_oom_demand_not_an_oom():
    assert parse_oom_demand("Segmentation fault") == (None, None)


def test_parse_oom_demand_reexported_for_benchmarks():
    # benchmarks/memory moved its parser here; the old private name must
    # keep resolving for pre-memkit callers
    from cs336_systems_tpu.benchmarks.memory import _parse_oom_demand

    assert _parse_oom_demand is parse_oom_demand


def test_explain_oom_joins_profile():
    e = explain_oom("Total hbm usage >= 2.0G\nlimit: 1.0G",
                    _profile(peak=1 << 30))
    assert e["over_limit_bytes"] == 1 << 30
    assert e["demand_over_analyzed"] == 2.0
    assert "2.0x" in memkit.format_explain(e)


# --- CPU end-to-end ---------------------------------------------------------


@pytest.fixture(scope="module")
def train_single_profile():
    return memkit.profile_family("train_single")


def test_profile_family_matches_xla_crosscheck(train_single_profile):
    p = train_single_profile
    assert p["schema"] == "memprofile/v1"
    assert p["family"] == "train_single"
    total = p["xla"]["total_bytes"]
    assert total > 0
    # the acceptance band: analyzed peak within 10% of the XLA totals
    assert 0.9 <= p["peak_bytes"] / total <= 1.1
    # params must be classified: the at-peak live set carries the model
    assert p["composition_bytes"].get("params", 0) > 0
    assert p["composition_bytes"].get("optimizer-state", 0) > 0
    assert sum(p["composition_bytes"].values()) == p["peak_bytes"]


def test_profile_family_serve_smoke():
    p = memkit.profile_family("serve_dp")
    total = p["xla"]["total_bytes"]
    assert 0.9 <= p["peak_bytes"] / total <= 1.1
    assert p["n_devices"] == 8


def test_format_profile_renders(train_single_profile):
    text = memkit.format_profile(train_single_profile)
    assert "analyzed peak" in text and "composition at peak" in text


def test_unknown_family_raises():
    with pytest.raises(KeyError, match="unknown step family"):
        memkit.profile_family("not_a_family")


# --- mem_cli ----------------------------------------------------------------


def test_mem_cli_list_exits_zero(capsys):
    from cs336_systems_tpu.analysis import mem_cli

    assert mem_cli.main(["--list"]) == 0
    out = capsys.readouterr().out
    assert "train_single" in out and "bench_headline" in out


def test_mem_cli_step_json_and_diff_roundtrip(tmp_path, capsys,
                                              train_single_profile):
    from cs336_systems_tpu.analysis import mem_cli

    a = tmp_path / "a.json"
    memkit.write_profile(train_single_profile, str(a))

    # self-compare exits 0 (the dual gate flags nothing on identity)
    assert mem_cli.main(["--diff", str(a), str(a)]) == 0
    capsys.readouterr()

    # injected regression >= threshold exits 1
    worse = json.loads(a.read_text())
    worse["peak_bytes"] = int(worse["peak_bytes"] * 1.5) + (4 << 20)
    worse["phase_peak_bytes"] = {
        k: int(v * 1.5) + (4 << 20)
        for k, v in worse["phase_peak_bytes"].items()
    }
    b = tmp_path / "b.json"
    b.write_text(json.dumps(worse))
    assert mem_cli.main(["--diff", str(a), str(b)]) == 1
    assert "FLAGGED" in capsys.readouterr().out


def test_mem_cli_step_writes_profile(tmp_path, capsys):
    from cs336_systems_tpu.analysis import mem_cli

    out = tmp_path / "serve.memprofile.json"
    assert mem_cli.main(["--step", "serve_tp", "--json",
                         "--out", str(out)]) == 0
    printed = json.loads(capsys.readouterr().out)
    on_disk = json.loads(out.read_text())
    assert printed["schema"] == on_disk["schema"] == "memprofile/v1"
    assert printed["family"] == "serve_tp"


def test_mem_cli_explain_oom(tmp_path, capsys):
    from cs336_systems_tpu.analysis import mem_cli

    log = tmp_path / "oom.log"
    log.write_text("RESOURCE_EXHAUSTED: Ran out of memory in memory "
                   "space hbm. Total hbm usage >= 17.48G\nlimit: 15.70G")
    assert mem_cli.main(["--explain-oom", str(log)]) == 0
    out = capsys.readouterr().out
    assert "17.48GiB" in out and "15.70GiB" in out
