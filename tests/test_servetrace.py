"""Flight-recorder + servetrace tests (ISSUE 12).

Oracle discipline mirrors tests/test_serving_engine.py: the recorder's
event log is checked against the per-request lifecycle it must describe
(submit <= admit <= first-token <= finish, one eviction each), the
latency decomposition is checked for EXACT conservation (components sum
to e2e — host_overhead is the residual and must never go negative), and
the headline invariant — the recorder is pure observation — is pinned by
running the same trace with the recorder on and off on dp8 AND dp2x:tp4
and demanding bit-identical streams. The spike test reproduces the
attribution the artifact exists for: a cold straggler prefill mid-trace
must land in the RUNNING requests' prefill_stall, and dominate p99.
"""

import json
import math
import time

import numpy as np
import pytest

import jax

from cs336_systems_tpu.analysis import servetrace
from cs336_systems_tpu.models.transformer import (
    TransformerConfig,
    init_transformer_lm,
)
from cs336_systems_tpu.parallel.mesh import make_mesh
from cs336_systems_tpu.serving import Request, ServingEngine

CFG = TransformerConfig(
    vocab_size=64, context_length=64, d_model=64,
    num_layers=2, num_heads=4, d_ff=128,
)
BLK = 8
NEW = 8
LENS = [12, 3, 7, 1, 12, 5, 9, 2]


@pytest.fixture(scope="module")
def params():
    return init_transformer_lm(jax.random.PRNGKey(1), CFG)


@pytest.fixture(scope="module")
def prompts():
    rng = np.random.default_rng(7)
    return [rng.integers(0, CFG.vocab_size, size=n).astype(np.int32)
            for n in LENS]


class Tick:
    """Stateful virtual clock: every read advances by ``dt`` — a fully
    deterministic timeline in which every recorded span is a positive
    multiple of dt."""

    def __init__(self, dt: float = 1e-3):
        self.t, self.dt = 0.0, dt

    def __call__(self) -> float:
        self.t += self.dt
        return self.t


def _engine(params, **kw):
    base = dict(key=jax.random.PRNGKey(0), slots=8, n_pages=32,
                max_blocks=4, page_block=BLK, temperature=0.9, top_k=8)
    base.update(kw)
    return ServingEngine(params, CFG, **base)


def _drive(params, **kw):
    """One full trace on the virtual tick clock; returns the engine."""
    eng = _engine(params, clock=Tick(), **kw)
    rng = np.random.default_rng(7)
    for i, n in enumerate(LENS):
        eng.submit(Request(rid=i,
                           prompt=rng.integers(0, CFG.vocab_size, size=n),
                           max_new_tokens=NEW))
    eng.run()
    eng.check_idle()
    return eng


# --- lifecycle well-formedness ----------------------------------------


def test_lifecycle_well_formed(params):
    eng = _drive(params)
    fr = eng.flight
    by_rid = {}
    for e in fr.events:
        by_rid.setdefault(e["rid"], {})[e["kind"]] = e
    assert set(by_rid) == set(range(len(LENS)))
    for rid, ev in by_rid.items():
        for kind in ("submit", "admit", "running", "first_token",
                     "finish"):
            assert kind in ev, f"rid {rid} missing {kind}"
        assert (ev["submit"]["t"] <= ev["admit"]["t"]
                <= ev["running"]["t"] <= ev["first_token"]["t"]
                <= ev["finish"]["t"])
        assert ev["finish"]["tokens"] == len(eng.results[rid])
        assert ev["admit"]["hit_tokens"] + ev["admit"]["suffix_tokens"] \
            >= LENS[rid]
    # every request evicted exactly once, at its finish step
    evicts = [r for s in fr.steps for r in s["evicts"]]
    assert sorted(evicts) == sorted(by_rid)
    # step records are monotone and phase-complete
    for s in fr.steps:
        assert s["t0"] <= s["t1"]
        assert set(s["phases"]) == set(
            ("schedule_admit", "prefix_lookup", "prefill_dispatch",
             "table_rewrite", "step_dispatch", "readback_sample"))


def test_phase_tiling_exact_and_counters(params):
    """Consecutive clock reads tile the step wall: the six phases sum to
    t1 - t0 exactly (the residual IS schedule_admit), and the per-step
    counters carry the scheduler/pool state."""
    eng = _drive(params)
    for s in eng.flight.steps:
        assert sum(s["phases"].values()) == pytest.approx(
            s["t1"] - s["t0"], abs=1e-12)
        # counters sample POST-evict state: the drain step reads 0
        assert s["counters"]["running"] >= 0
        assert s["counters"]["free_pages"] >= 0
    assert any(s["counters"]["running"] > 0 for s in eng.flight.steps)
    assert eng.flight.nonfinite_spans == 0


# --- conservation ------------------------------------------------------


def test_emit_conservation(params):
    eng = _drive(params)
    art = servetrace.fold(eng)
    cons = art["conservation"]
    assert cons["ok"]
    assert cons["emitted_tokens"] == sum(
        len(t) for t in eng.results.values())
    assert cons["live_tokens"] == 0
    assert art["requests"]["submitted"] == len(LENS)
    assert art["requests"]["completed"] == len(LENS)
    assert art["requests"]["decomposed"] == len(LENS)
    assert art["requests"]["nonfinite_skipped"] == 0


# --- decomposition exactness ------------------------------------------


def test_decomposition_sums_to_e2e(params):
    eng = _drive(params)
    per_req, skipped = servetrace.decompose(eng)
    assert skipped == 0 and set(per_req) == set(range(len(LENS)))
    by_rid = {}
    for e in eng.flight.events:
        by_rid.setdefault(e["rid"], {})[e["kind"]] = e
    for rid, r in per_req.items():
        parts = (r["queue_wait"] + r["prefill_stall"] + r["decode"]
                 + r["host_overhead"])
        assert parts == pytest.approx(r["e2e"], abs=1e-9), rid
        assert r["e2e"] == pytest.approx(
            by_rid[rid]["finish"]["t"] - by_rid[rid]["submit"]["t"],
            abs=1e-12)
        for c in r:
            assert r[c] is None or r[c] >= 0.0, (rid, c)


def test_nonfinite_timeline_skipped_not_poisoned(params):
    """No clock at all -> every timestamp is the math.inf fallback; the
    fold must SKIP those requests, not emit inf/nan percentiles."""
    eng = _engine(params)  # clock=None
    rng = np.random.default_rng(7)
    for i, n in enumerate(LENS[:3]):
        eng.submit(Request(rid=i,
                           prompt=rng.integers(0, CFG.vocab_size, size=n),
                           max_new_tokens=NEW))
    eng.run()
    per_req, skipped = servetrace.decompose(eng)
    assert per_req == {} and skipped == 3
    art = servetrace.fold(eng)
    assert art["requests"]["nonfinite_skipped"] == 3
    assert art["components_ms"]["e2e"] is None
    assert art["conservation"]["ok"]
    blob = json.dumps(art)  # artifact must stay JSON-clean
    assert "Infinity" not in blob and "NaN" not in blob


# --- recorder is pure observation: bit-identical streams ---------------


@pytest.mark.parametrize("mesh_axes,dp,tp", [
    ({"dp": 8}, "dp", None),
    ({"dp": 2, "tp": 4}, "dp", "tp"),
], ids=["dp8", "dp2xtp4"])
def test_streams_bit_identical_recorder_on_off(params, prompts,
                                               mesh_axes, dp, tp):
    out = {}
    for flight in (True, False):
        eng = _engine(params, n_pages=8, mesh=make_mesh(mesh_axes),
                      dp_axis=dp, tp_axis=tp, flight=flight,
                      clock=Tick())
        for i, r in enumerate([4, 1, 6, 0, 7, 2, 5, 3]):
            eng.submit(Request(rid=r, prompt=prompts[r],
                               max_new_tokens=NEW,
                               arrival=float(i) * 0.25))
        tick = iter(np.arange(0.0, 1e4, 0.5))
        out[flight] = eng.run(time_fn=lambda: next(tick))
        eng.check_idle()
        assert bool(eng.flight.events) == flight
    assert set(out[True]) == set(out[False])
    for rid in out[True]:
        np.testing.assert_array_equal(out[True][rid], out[False][rid])


# --- deterministic virtual-clock timeline ------------------------------


def test_virtual_clock_timeline_deterministic(params):
    a, b = _drive(params), _drive(params)
    assert a.flight.events == b.flight.events
    assert a.flight.steps == b.flight.steps
    assert a.flight.prefills == b.flight.prefills
    assert servetrace.fold(a) == servetrace.fold(b)


# --- spike: the straggler's cold prefill lands in prefill_stall --------


def test_spike_prefill_stall_dominates_p99(params):
    """The attribution the artifact exists for: 7 short requests decode
    on a WARM engine; a straggler with a cold prefill bucket joins
    mid-flight, and its (compile-heavy, wall-clock) prefill stalls every
    running stream. prefill_stall must dominate the p99 decomposition —
    strictly above each other component's p99 and the majority of e2e's.
    """
    t0 = time.monotonic()
    eng = _engine(params, prefix_cache=False,
                  clock=lambda: time.monotonic() - t0)
    rng = np.random.default_rng(3)
    shorts = [rng.integers(0, CFG.vocab_size, size=4).astype(np.int32)
              for _ in range(7)]
    # prewarm: compile the shorts' join bucket + the decode step, drain
    for i, p in enumerate(shorts):
        eng.submit(Request(rid=100 + i, prompt=p, max_new_tokens=NEW))
    eng.run()
    eng.check_idle()
    eng.flight.reset()

    # the measured trace: same short shapes (warm), then the straggler
    # joins mid-flight with a prompt-length bucket never compiled
    for i, p in enumerate(shorts):
        eng.submit(Request(rid=i, prompt=p, max_new_tokens=NEW,
                           arrival=eng.clock()))
    eng.step()  # shorts join (warm prefill) and start decoding
    assert len(eng.running) == 7
    eng.submit(Request(
        rid=7, prompt=rng.integers(0, CFG.vocab_size, size=16),
        max_new_tokens=NEW, arrival=eng.clock()))
    eng.run()
    eng.check_idle()

    art = servetrace.fold(eng)
    comps = art["components_ms"]
    stall = comps["prefill_stall"]["p99"]
    assert stall > comps["queue_wait"]["p99"]
    assert stall > comps["decode"]["p99"]
    assert stall > comps["host_overhead"]["p99"]
    assert stall >= 0.5 * comps["e2e"]["p99"]


# --- CLI exit codes ----------------------------------------------------


def test_cli_run_selfdiff_report_exit_codes(params, tmp_path):
    from cs336_systems_tpu.analysis import serve_trace_cli

    out = str(tmp_path / "st.json")
    assert serve_trace_cli.main(
        ["--run", "--step", "serve_engine", "--no-device-join",
         "--requests", "6", "--out", out]) == 0
    assert serve_trace_cli.main(["--diff", out, out]) == 0  # self-diff
    assert serve_trace_cli.main(["--report", out]) == 0
    assert serve_trace_cli.main(["--list"]) == 0

    # a real regression (>2 ms and >50%) must exit 1
    with open(out) as f:
        art = json.load(f)
    worse = json.loads(json.dumps(art))
    c = worse["components_ms"]["e2e"]
    c["p99"] = c["p99"] * 10 + 100.0
    bad = str(tmp_path / "bad.json")
    with open(bad, "w") as f:
        json.dump(worse, f)
    assert serve_trace_cli.main(["--diff", out, bad]) == 1

    # unknown family and family-mismatched diff are build errors: 2
    assert serve_trace_cli.main(["--run", "--step", "nope"]) == 2
    other = json.loads(json.dumps(art))
    other["family"] = "some_other_family"
    mism = str(tmp_path / "mism.json")
    with open(mism, "w") as f:
        json.dump(other, f)
    assert serve_trace_cli.main(["--diff", out, mism]) == 2
