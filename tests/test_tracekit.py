"""tracekit tests: taxonomy classification, phase attribution, the
multi-device trace-total fix, diff thresholds, and a CPU smoke of
``trace_cli --step`` for one train and one serve family.

Same oracle discipline as test_analysis.py: the classifier and the diff
gate are tested against hand-built known inputs (synthetic HLO text and
synthetic ``.trace.json.gz`` fixtures), not assumed correct; the smoke
tests then check the full pipeline end to end on the hermetic CPU mesh.
"""

import gzip
import json
import os

import pytest

from cs336_systems_tpu.analysis import tracekit
from cs336_systems_tpu.analysis.tracekit import (
    HloOp,
    attribute,
    classify_op,
    diff_profiles,
    parse_hlo_ops,
    phase_of,
    read_trace_events,
)


# --- HLO parsing ------------------------------------------------------------


_HLO_FIXTURE = """\
HloModule jit_step, entry_computation_layout={...}

%fused_computation (p: f32[8,8]) -> f32[8,8] {
  %p = f32[8,8]{1,0} parameter(0)
  ROOT %mul.9 = f32[8,8]{1,0} multiply(%p, %p), metadata={op_name="jit(step)/fwd/ffn/silu"}
}

ENTRY %main (a: f32[8,8], b: f32[8,8]) -> f32[8,8] {
  %a = f32[8,8]{1,0} parameter(0)
  %b = f32[8,8]{1,0} parameter(1)
  %dot.1 = f32[8,8]{1,0} dot(%a, %b), metadata={op_name="jit(step)/fwd/attn/qkv_proj/dot_general" source_file="m.py"}
  %dot.2 = f32[8,8]{1,0} dot(%a, %b), metadata={op_name="jit(step)/transpose(jvp(step))/attn/dot_general"}
  %copy.3 = f32[8,8]{1,0} copy(%dot.1), metadata={op_name="jit(step)/fwd/attn/rope"}
  %fusion.4 = f32[8,8]{1,0} fusion(%copy.3), kind=kLoop, calls=%fused_computation, metadata={op_name="jit(step)/fwd/ffn/silu"}
  %all-reduce-start.5 = f32[8,8]{1,0} all-reduce-start(%fusion.4), metadata={op_name="jit(step)/optimizer/psum"}
  %all-reduce-done.6 = f32[8,8]{1,0} all-reduce-done(%all-reduce-start.5)
  %custom-call.7 = f32[8,8]{1,0} custom-call(%a), custom_call_target="tpu_custom_call", metadata={op_name="jit(step)/fwd/attn/sdpa/pallas_call"}
  %while.8 = f32[8,8]{1,0} while(%dot.1), condition=%cond, body=%body
  ROOT %add.9 = f32[8,8]{1,0} add(%dot.2, %fusion.4), metadata={op_name="jit(step)/blk/ffn/residual"}
}
"""


def test_parse_hlo_ops_all_computations():
    ops = parse_hlo_ops(_HLO_FIXTURE)
    assert ops["dot.1"].opcode == "dot"
    assert ops["dot.1"].scope == "jit(step)/fwd/attn/qkv_proj/dot_general"
    assert ops["custom-call.7"].call_target == "tpu_custom_call"
    assert ops["while.8"].opcode == "while"
    # non-ENTRY computations are parsed too (their ops trace as events)
    assert ops["mul.9"].opcode == "multiply"
    # metadata-free ops still parse, with an empty scope
    assert ops["all-reduce-done.6"].scope == ""


# --- taxonomy ---------------------------------------------------------------


@pytest.mark.parametrize("op,expected", [
    (HloOp("dot", ""), "mxu-matmul"),
    (HloOp("convolution", ""), "mxu-matmul"),
    (HloOp("fusion", "fwd/ffn"), "vpu-elementwise"),
    (HloOp("add", ""), "vpu-elementwise"),
    (HloOp("copy", ""), "copy-transpose"),
    (HloOp("dynamic-update-slice", ""), "copy-transpose"),
    (HloOp("all-reduce", ""), "collective-all-reduce"),
    (HloOp("all-reduce-start", ""), "collective-all-reduce"),
    (HloOp("all-reduce-done", ""), "dma"),
    (HloOp("all-to-all", ""), "collective-all-to-all"),
    (HloOp("copy-start", ""), "dma"),
    (HloOp("custom-call", "", "tpu_custom_call"), "pallas-kernel"),
    (HloOp("custom-call", "", "MosaicKernel"), "pallas-kernel"),
    (HloOp("custom-call", "fwd/attn/pallas_call", ""), "pallas-kernel"),
    (HloOp("custom-call", "", "xla_ffi_something"), "host"),
    (HloOp("parameter", ""), "host"),
    (HloOp("get-tuple-element", ""), "host"),
])
def test_classify_op(op, expected):
    assert classify_op(op) == expected


# --- phase attribution ------------------------------------------------------


@pytest.mark.parametrize("scope,expected", [
    ("", "other"),
    ("jit(step)/fwd/attn/qkv_proj/dot_general", "fwd-attn"),
    ("jit(step)/blk0/attn/rope", "fwd-attn"),
    ("jit(step)/blk0/ffn/silu", "fwd-ffn"),
    ("jit(step)/lm_head/dot_general", "fwd-ffn"),
    # AD's transpose( marker beats the forward scope it wraps
    ("jit(step)/transpose(jvp(step))/attn/dot_general", "bwd"),
    ("jit(step)/optimizer/adamw/mul", "optimizer"),
    # inner scopes win where they nest
    ("generate/blk0/attn/kv_update/dynamic-update-slice", "kv-update"),
    ("generate/blk0/ffn/routing/softmax", "routing"),
    ("generate/sampling/top_k", "sampling"),
    ("jit(step)/some/unrelated/scope", "other"),
])
def test_phase_of(scope, expected):
    assert phase_of(scope) == expected


# --- attribution over synthetic events --------------------------------------


def _ev(name, dur, pid=1, tid=1):
    return {"ph": "X", "pid": pid, "tid": tid, "ts": 0, "dur": dur,
            "name": name}


def test_attribute_joins_skips_and_divides():
    op_map = parse_hlo_ops(_HLO_FIXTURE)
    events = [
        _ev("dot.1", 100), _ev("dot.1", 100),        # 2 execs, fwd-attn mxu
        _ev("dot.2", 300), _ev("dot.2", 300),        # bwd mxu
        _ev("fusion.4", 50), _ev("fusion.4", 50),    # fwd-ffn vpu
        _ev("while.8", 9999), _ev("while.8", 9999),  # container: skipped
        _ev("a", 500),                               # parameter/host: skipped
        _ev("not_an_instruction", 700),              # no HLO join: skipped
    ]
    phase_class, rows = attribute(events, op_map, divisor=2.0)
    assert phase_class["fwd-attn"]["mxu-matmul"] == 200
    assert phase_class["bwd"]["mxu-matmul"] == 600
    assert phase_class["fwd-ffn"]["vpu-elementwise"] == 100
    assert "other" not in phase_class  # the container/host time never lands
    by_op = {r["op"]: r for r in rows}
    assert by_op["dot.1"]["total_ms"] == pytest.approx(0.1)
    assert by_op["dot.1"]["count"] == 1  # 2 events / divisor 2
    assert by_op["dot.2"]["phase"] == "bwd"
    assert "while.8" not in by_op and "a" not in by_op
    assert rows[0]["op"] == "dot.2"  # sorted by time


# --- trace reading: noise lanes ---------------------------------------------


def _write_trace(tmp_path, events):
    path = os.path.join(tmp_path, "host.trace.json.gz")
    with gzip.open(path, "wt") as f:
        json.dump({"traceEvents": events}, f)
    return tmp_path


def test_read_trace_events_drops_noise_lanes(tmp_path):
    events = [
        {"ph": "M", "pid": 1, "tid": 1, "name": "thread_name",
         "args": {"name": "XLA Ops"}},
        {"ph": "M", "pid": 1, "tid": 2, "name": "thread_name",
         "args": {"name": "Framework Name Scope"}},
        _ev("dot.1", 100, tid=1),
        _ev("fwd/attn", 100, tid=2),  # name-scope mirror lane: dropped
    ]
    got = read_trace_events(_write_trace(str(tmp_path), events))
    assert [e["name"] for e in got] == ["dot.1"]


# --- satellite: summarize_trace multi-device fix ----------------------------


def test_summarize_trace_divides_by_device_lanes(tmp_path):
    """Two device processes each logging the same op once: the historical
    behavior summed both lanes (2x the per-device time); the fixed version
    reports the per-device mean and exposes the divisor."""
    from cs336_systems_tpu.utils.profiling import summarize_trace

    events = []
    for pid in (1, 2):
        events += [
            {"ph": "M", "pid": pid, "name": "process_name",
             "args": {"name": f"/device:TPU:{pid - 1}"}},
            {"ph": "M", "pid": pid, "tid": 1, "name": "thread_name",
             "args": {"name": "XLA Ops"}},
            _ev("dot.1", 500, pid=pid),
            _ev("dot.1", 500, pid=pid),
        ]
    res = summarize_trace(_write_trace(str(tmp_path), events))
    rows, total = res  # the historical 2-tuple unpack must keep working
    assert res.n_devices == 2
    assert total == pytest.approx(1.0)      # 2000 us / 2 lanes, not 2.0
    assert rows[0]["total_ms"] == pytest.approx(1.0)
    assert rows[0]["count"] == 2            # per-device executions
    assert rows[0]["mean_us"] == pytest.approx(500.0)


def test_summarize_trace_single_lane_unchanged(tmp_path):
    from cs336_systems_tpu.utils.profiling import summarize_trace

    events = [
        {"ph": "M", "pid": 1, "name": "process_name",
         "args": {"name": "/device:TPU:0"}},
        {"ph": "M", "pid": 1, "tid": 1, "name": "thread_name",
         "args": {"name": "XLA Ops"}},
        _ev("dot.1", 500),
    ]
    rows, total = summarize_trace(_write_trace(str(tmp_path), events))
    assert total == pytest.approx(0.5)
    assert rows[0]["count"] == 1


def test_summarize_trace_explicit_n_devices(tmp_path):
    """CPU-backend traces put all virtual devices in one process; the
    caller passes mesh.size and the division still happens."""
    from cs336_systems_tpu.utils.profiling import summarize_trace

    events = [
        {"ph": "M", "pid": 1, "tid": 1, "name": "thread_name",
         "args": {"name": "XLA Ops"}},
        _ev("dot.1", 400),
        _ev("dot.1", 400),
    ]
    res = summarize_trace(_write_trace(str(tmp_path), events), n_devices=2)
    assert res.n_devices == 2
    assert res.total_ms == pytest.approx(0.4)


# --- collective overlap (ISSUE 12 satellite) --------------------------------


def _oev(name, ts, dur, pid=1):
    return {"ph": "X", "pid": pid, "tid": 1, "ts": ts, "dur": dur,
            "name": name}


_OVERLAP_OPS = {
    "all-reduce.1": HloOp("all-reduce", "jit(step)/blk/ffn/psum"),
    "all-gather.2": HloOp("all-gather", "jit(step)/fwd/attn/ag"),
    "dot.1": HloOp("dot", "jit(step)/fwd/ffn/dot_general"),
    "fusion.2": HloOp("fusion", "jit(step)/fwd/ffn/silu"),
    "copy-start.3": HloOp("copy-start", ""),       # dma: must not hide
    "while.9": HloOp("while", ""),                 # container: skipped
}


def test_collective_overlap_oracle():
    """Hand-built timeline: a [0,100] collective against compute at
    [0,40] and [60,80] on the SAME lane -> hidden 60 us, exposed 40 us.
    A second collective on a lane whose only compute lives on ANOTHER
    pid must come out fully exposed — cross-lane compute never hides."""
    events = [
        _oev("all-reduce.1", 0, 100),
        _oev("dot.1", 0, 40),
        _oev("fusion.2", 60, 20),
        _oev("copy-start.3", 0, 100),       # concurrent DMA: ignored
        _oev("while.9", 0, 100),            # container: ignored
        _oev("all-gather.2", 0, 50, pid=2),
        _oev("dot.1", 0, 50, pid=3),        # other lane: cannot hide pid 2
    ]
    ov = tracekit.collective_overlap(events, _OVERLAP_OPS, divisor=1.0)
    assert ov["fwd-ffn"] == {"hidden_ms": 0.06, "exposed_ms": 0.04,
                             "overlap_ratio": 0.6}
    assert ov["fwd-attn"] == {"hidden_ms": 0.0, "exposed_ms": 0.05,
                              "overlap_ratio": 0.0}


def test_collective_overlap_merges_stacked_compute():
    """Two overlapping compute events must union, not double-cover: a
    [0,50] collective against compute [0,30] and [20,60] hides 50 us
    (the full span), never 80."""
    events = [
        _oev("all-reduce.1", 0, 50),
        _oev("dot.1", 0, 30),
        _oev("fusion.2", 20, 40),
    ]
    ov = tracekit.collective_overlap(events, _OVERLAP_OPS, divisor=1.0)
    assert ov["fwd-ffn"] == {"hidden_ms": 0.05, "exposed_ms": 0.0,
                             "overlap_ratio": 1.0}


def test_collective_overlap_empty_without_collectives():
    assert tracekit.collective_overlap(
        [_oev("dot.1", 0, 100)], _OVERLAP_OPS) == {}


def test_diff_covers_overlap_fields_and_absent_is_zero():
    """Old profiles (written before the overlap fields existed) diff as
    0.0; a real exposed-time regression is a flagged overlap row."""
    a = _profile(10.0, {"fwd-ffn": 10.0}, {"mxu-matmul": 10.0})
    b = dict(_profile(10.0, {"fwd-ffn": 10.0}, {"mxu-matmul": 10.0}),
             collective_hidden_ms=1.0, collective_exposed_ms=5.0)
    d = diff_profiles(a, b)
    rows = {r["key"]: r for r in d["rows"] if r["kind"] == "overlap"}
    assert rows["collective-hidden"]["a_ms"] == 0.0
    assert rows["collective-exposed"]["flagged"]
    # identical profiles (both without the fields) flag nothing
    assert diff_profiles(a, dict(a))["n_flagged"] == 0


@pytest.mark.parametrize("family", ["train_tp", "train_ep_a2a"])
def test_profile_step_overlap_fields(family):
    """Real collective-bearing families carry the overlap split, and it
    conserves: hidden + exposed == total collective class time (same
    events, same divisor — only the partition is new)."""
    p = tracekit.profile_step(family, iters=1)
    coll_total = sum(v for c, v in p["class_ms"].items()
                     if c.startswith("collective-"))
    hid, exp = p["collective_hidden_ms"], p["collective_exposed_ms"]
    assert hid >= 0.0 and exp >= 0.0
    assert hid + exp == pytest.approx(coll_total, abs=1e-2)
    assert 0.0 <= p["collective_overlap_ratio"] <= 1.0
    assert set(p["overlap_by_phase"]) <= set(p["phase_ms"]) | {"other"}


# --- diffing ----------------------------------------------------------------


def _profile(total, phases, classes, family="fam"):
    return {
        "schema": tracekit.SCHEMA, "family": family,
        "total_device_ms_per_step": total,
        "phase_ms": phases, "class_ms": classes,
    }


def test_diff_identical_flags_nothing():
    a = _profile(10.0, {"fwd-attn": 6.0, "bwd": 4.0}, {"mxu-matmul": 10.0})
    d = diff_profiles(a, dict(a))
    assert d["n_flagged"] == 0
    assert d["total_delta_ms"] == 0.0


def test_diff_flags_real_regression_only():
    a = _profile(10.0, {"fwd-attn": 6.0, "bwd": 4.0}, {"mxu-matmul": 10.0})
    b = _profile(13.0, {"fwd-attn": 9.0, "bwd": 4.0}, {"mxu-matmul": 13.0})
    d = diff_profiles(a, b, threshold_pct=10.0, abs_floor_ms=0.05)
    flagged = {(r["kind"], r["key"]) for r in d["rows"] if r["flagged"]}
    assert flagged == {("phase", "fwd-attn"), ("class", "mxu-matmul")}


def test_diff_abs_floor_gates_noise():
    """An 80% swing on a 50 us phase is lane jitter, not a regression —
    the absolute floor must keep it quiet."""
    a = _profile(0.05, {"sampling": 0.05}, {"vpu-elementwise": 0.05})
    b = _profile(0.09, {"sampling": 0.09}, {"vpu-elementwise": 0.09})
    assert diff_profiles(a, b)["n_flagged"] == 0


def test_diff_new_phase_flagged():
    a = _profile(1.0, {"fwd-attn": 1.0}, {"mxu-matmul": 1.0})
    b = _profile(2.0, {"fwd-attn": 1.0, "routing": 1.0},
                 {"mxu-matmul": 1.0, "vpu-elementwise": 1.0})
    d = diff_profiles(a, b)
    new = [r for r in d["rows"] if r["key"] == "routing"][0]
    assert new["flagged"] and new["delta_pct"] is None


def test_diff_family_mismatch_raises():
    a = _profile(1.0, {}, {}, family="train_single")
    b = _profile(1.0, {}, {}, family="serve_dp")
    with pytest.raises(ValueError, match="different families"):
        diff_profiles(a, b)


# --- end-to-end CPU smoke ---------------------------------------------------


@pytest.mark.parametrize("family", ["train_single", "serve_dp"])
def test_trace_cli_step_smoke(family, tmp_path):
    """The acceptance path: trace_cli --step writes a StepProfile with a
    non-empty phase x class breakdown and an MFU estimate, exit 0."""
    from cs336_systems_tpu.analysis import trace_cli

    out = str(tmp_path / f"{family}.json")
    assert trace_cli.main(["--step", family, "--iters", "1",
                           "--out", out]) == 0
    with open(out) as f:
        p = json.load(f)
    assert p["schema"] == tracekit.SCHEMA
    assert p["family"] == family
    assert p["total_device_ms_per_step"] > 0
    assert p["phase_class_ms"] and any(
        c for c in p["phase_class_ms"].values())
    assert p["mfu"] > 0 and p["achieved_tflops"] > 0
    assert p["ops"], "top op rows must be populated"
    if family == "train_single":
        # the canonical step must attribute real time to its core phases
        for ph in ("fwd-attn", "bwd", "optimizer"):
            assert p["phase_ms"].get(ph, 0) > 0, ph
    else:
        for ph in ("kv-update", "sampling"):
            assert p["phase_ms"].get(ph, 0) > 0, ph


def test_trace_cli_diff_identical_exits_zero(tmp_path):
    from cs336_systems_tpu.analysis import trace_cli

    p = _profile(10.0, {"fwd-attn": 6.0}, {"mxu-matmul": 6.0})
    a, b = str(tmp_path / "a.json"), str(tmp_path / "b.json")
    for path in (a, b):
        with open(path, "w") as f:
            json.dump(p, f)
    assert trace_cli.main(["--diff", a, b]) == 0

    worse = _profile(20.0, {"fwd-attn": 12.0}, {"mxu-matmul": 12.0})
    with open(b, "w") as f:
        json.dump(worse, f)
    assert trace_cli.main(["--diff", a, b]) == 1  # CI-gateable
